"""Replayable fleet simulator — drill the serving fleet (and its
autopilot) on a VIRTUAL clock, deterministically, at scales tier-1 can
afford.

The real multi-replica frontend is driven exactly as production drives
it — real `ServingFrontend`, real `ReplicaSupervisor` pump loops, real
`Engine`s over `testing.chaos.toy_decoder` — but every clock the
serving tier reads is this module's `VirtualClock`, advanced a fixed
``dt_s`` per supervision round. That closes every nondeterminism hole
at once:

- **Time** is simulated: latency/TTFT percentiles, hedge budgets, and
  mode-transition timestamps are functions of queueing structure, not
  of how loaded the CI box is.
- **Arrivals** are a `Trace`: either synthetic (``bursty`` /
  ``diurnal`` / ``adversarial_overload`` generators, seed-keyed) or
  recorded (`Trace.load` of a banked JSONL). Request ids are the trace
  indices, so derived sampling seeds — and therefore every token — are
  functions of (trace, seed) alone.
- **Faults** are seed-keyed `testing.chaos` schedules firing at exact
  (replica, step) coordinates.

Same (trace, seed) ⇒ bit-identical episode: `SimReport.fingerprint`
hashes the full transition history, every autopilot actuation, and
every request's outcome INCLUDING its token stream — the determinism
drills pin two runs' fingerprints equal.

What this does and does NOT prove (docs/autopilot.md): it proves
control-loop LOGIC — detection, hysteresis, actuation ordering,
recovery, SLO arithmetic — against real serving code paths. It does
not prove wall-clock numbers: virtual seconds cost nothing, so a
simulated "p99 = 0.4s" says nothing about silicon latency, and
replica restarts are free of XLA recompile time. Hardware claims stay
with the banked-bench queue.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "VirtualClock", "SimRequest", "Trace", "synthetic_trace",
    "FleetSimConfig", "FleetSim", "SimReport", "run_fleet",
]

TRACE_SCHEMA = "apex1-fleettrace-v1"
# APPEND-only: `TRACE_KINDS.index(kind)` keys each generator's rng
# stream, so reordering would silently regenerate every banked trace
TRACE_KINDS = ("steady", "bursty", "diurnal", "adversarial_overload",
               "adversarial_long_prompt")


class VirtualClock:
    """The one clock of a simulated episode. Callable (drop-in for
    ``time.monotonic``), advanced only by the simulator."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += float(dt)
        return self.t


@dataclasses.dataclass(frozen=True)
class SimRequest:
    """One arrival: WHEN it lands and its admission contract. Prompt
    tokens are derived, not stored — request index x trace seed keys a
    deterministic draw, so a trace file stays a few bytes per
    request."""

    t: float
    qos: str
    tenant: str
    prompt_len: int
    max_new_tokens: int


@dataclasses.dataclass
class Trace:
    """An arrival trace: replayable input to `FleetSim`. ``seed`` keys
    BOTH the generator that built it and the per-request prompt-token
    draws at replay."""

    kind: str
    seed: int
    horizon_s: float
    requests: List[SimRequest]

    def fingerprint(self) -> str:
        doc = {"schema": TRACE_SCHEMA, "kind": self.kind,
               "seed": self.seed, "horizon_s": self.horizon_s,
               "requests": [dataclasses.astuple(r)
                            for r in self.requests]}
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]

    def save(self, path: str) -> str:
        """Bank as JSONL (header + one line per arrival) — the
        'recorded trace' format `load` replays."""
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"schema": TRACE_SCHEMA, "kind": self.kind,
                 "seed": self.seed, "horizon_s": self.horizon_s,
                 "n": len(self.requests)}) + "\n")
            for r in self.requests:
                f.write(json.dumps(dataclasses.astuple(r)) + "\n")
        return path

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, encoding="utf-8") as f:
            head = json.loads(f.readline())
            if head.get("schema") != TRACE_SCHEMA:
                raise ValueError(
                    f"{path}: not a {TRACE_SCHEMA} trace "
                    f"(schema={head.get('schema')!r})")
            reqs = [SimRequest(float(t), str(q), str(tn), int(pl),
                               int(mn))
                    for t, q, tn, pl, mn in map(json.loads, f)]
        return cls(kind=str(head["kind"]), seed=int(head["seed"]),
                   horizon_s=float(head["horizon_s"]), requests=reqs)


def synthetic_trace(kind: str, *, seed: int, horizon_s: float = 8.0,
                    base_rate: float = 25.0,
                    class_mix: Optional[Dict[str, float]] = None,
                    tenants: tuple = ("acme", "zeta"),
                    prompt_lens: tuple = (3, 9),
                    new_tokens: tuple = (4, 10),
                    burst_mult: float = 5.0,
                    burst_len_s: float = 0.6,
                    n_bursts: int = 3,
                    diurnal_period_s: float = 4.0,
                    overload_mult: float = 3.0,
                    overload_span: tuple = (0.3, 0.8),
                    long_prompt_lens: tuple = (18, 30)) -> Trace:
    """Seed-keyed arrival generator (inhomogeneous Poisson via
    thinning). Kinds:

    - ``steady``: flat ``base_rate`` req/s.
    - ``bursty``: flat base + ``n_bursts`` seed-placed windows at
      ``burst_mult`` x base — the anti-flap fixture (each burst is
      shorter than any honest sustain threshold).
    - ``diurnal``: sinusoidal rate between ~0.3x and 1x base.
    - ``adversarial_overload``: base rate outside
      ``overload_span`` (fractions of the horizon), ``overload_mult``
      x base inside — sustained past any burst filter, the headline
      drill's input.
    - ``adversarial_long_prompt``: FLAT base rate — the adversarial
      axis is the prompt-length mix, not the rate: non-guaranteed
      classes draw from ``long_prompt_lens`` while guaranteed keeps
      ``prompt_lens``, so long prefills head-of-line-block short
      interactive traffic at EQUAL offered load (the disaggregation
      drill's input; pair with ``prefill_round_cost``).
    """
    if kind not in TRACE_KINDS:
        raise ValueError(f"unknown trace kind {kind!r}; "
                         f"one of {TRACE_KINDS}")
    mix = dict(class_mix or {"guaranteed": 0.5, "best_effort": 0.25,
                             "sheddable": 0.25})
    classes = sorted(mix)
    probs = np.asarray([mix[c] for c in classes], float)
    probs = probs / probs.sum()
    rng = np.random.default_rng(
        [int(seed), TRACE_KINDS.index(kind), 0xF1EE7])
    if kind == "bursty":
        starts = np.sort(rng.uniform(
            0.0, max(horizon_s - burst_len_s, 0.0), int(n_bursts)))
    t_on, t_off = (overload_span[0] * horizon_s,
                   overload_span[1] * horizon_s)

    def rate(t: float) -> float:
        if kind == "steady":
            return base_rate
        if kind == "bursty":
            hot = any(s <= t < s + burst_len_s for s in starts)
            return base_rate * (burst_mult if hot else 1.0)
        if kind == "diurnal":
            phase = math.sin(2.0 * math.pi * t / diurnal_period_s)
            return base_rate * (0.65 + 0.35 * phase)
        if kind == "adversarial_long_prompt":
            return base_rate
        return base_rate * (overload_mult if t_on <= t < t_off else 1.0)

    rmax = base_rate * max(burst_mult, overload_mult, 1.0)
    reqs: List[SimRequest] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / rmax))
        if t >= horizon_s:
            break
        if rng.uniform() >= rate(t) / rmax:
            continue                    # thinned
        qos = classes[int(rng.choice(len(classes), p=probs))]
        plens = (long_prompt_lens
                 if (kind == "adversarial_long_prompt"
                     and qos != "guaranteed") else prompt_lens)
        reqs.append(SimRequest(
            t=round(t, 6),
            qos=qos,
            tenant=str(tenants[int(rng.integers(len(tenants)))]),
            prompt_len=int(rng.integers(plens[0], plens[1] + 1)),
            max_new_tokens=int(rng.integers(new_tokens[0],
                                            new_tokens[1] + 1))))
    return Trace(kind=kind, seed=int(seed), horizon_s=float(horizon_s),
                 requests=reqs)


@dataclasses.dataclass
class FleetSimConfig:
    """Simulator knobs (the serving knobs ride the `FrontendConfig`
    the caller passes). ``dt_s`` is the virtual cost of ONE
    supervision round — i.e. one decode step per replica — so a
    replica's service rate is ``slots / (max_new_tokens * dt_s)``
    req/s; provisioning arithmetic in the drills builds on that."""

    dt_s: float = 0.02
    control_interval_s: float = 0.1   # autopilot tick cadence (virtual)
    slots_per_replica: int = 4
    max_len: int = 48
    prefill_chunk: int = 4
    temperature: float = 0.8          # nonzero: determinism claims
    #                                   cover real sampling, not greedy
    vocab: int = 61                   # toy_decoder's default
    num_draft: int = 0                # >0: replica engines run the
    #  speculative verify loop. Tokens are UNCHANGED by construction
    #  (exact-match counter-seed verify), so per-request token digests
    #  replay bit-identically; rounds/latency shift (multi-token steps)
    #  — same (trace, seed, config) stays bit-identical, and accept
    #  rates flow into `summary`/`to_json` for the autopilot to read.
    cache_dtype: Optional[object] = None  # e.g. jnp.int8 — the KV
    #  capacity tier under sim (exact for toy_decoder: values < 128)
    drain_grace_s: float = 30.0       # virtual time allowed past the
    #                                   horizon before declaring wedged
    max_rounds: int = 500_000         # hard stop (wedged episode)
    # ---- two-tier (disaggregated) fleet model; all defaults keep the
    # unified path — and every banked fingerprint — byte-identical
    disagg: bool = False              # split frontend_config.n_replicas
    #  into a prefill pool + a decode pool behind a `DisaggFrontend`
    #  (EQUAL total replicas vs the unified fleet — the A/B is fair)
    prefill_replicas: int = 1         # pool split: prefill tier size;
    #                                   decode gets the remainder (>= 1)
    handoff_latency_s: float = 0.0    # virtual seconds a finished
    #  prefill's KV page spends in flight before arrival verification
    #  + decode admission (the ICI/DCN transfer knob)
    prefill_round_cost: bool = False  # charge prefill its CHUNK count
    #  in supervision rounds (a replica prefilling an 8-chunk prompt
    #  stalls its decode slots 8 rounds) — the head-of-line cost that
    #  makes unified vs disaggregated an honest A/B; off by default
    #  (pre-existing traces replay with free prefills, as banked)


@dataclasses.dataclass
class SimReport:
    """One episode's outcome — everything the drills assert on."""

    trace_kind: str
    trace_seed: int
    trace_fingerprint: str
    n_arrivals: int
    n_submitted: int
    rejected: Dict[str, int]          # per class, at the front door
    outcomes: List[dict]              # per request: idx/qos/tenant/
    #                                   status/latency/ttft/token digest
    transitions: List[dict]           # full banked transition history
    actions: List[dict]               # autopilot episode log ([] if off)
    summary: dict                     # frontend.summary() at the end
    virtual_s: float
    rounds: int

    def per_class(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for o in self.outcomes:
            d = out.setdefault(o["qos"], {"n": 0, "done": 0, "full": 0,
                                          "latencies": [], "ttfts": []})
            d["n"] += 1
            if o["status"] == "done":
                d["done"] += 1
                if o["full"]:
                    d["full"] += 1
                    if o["latency"] is not None:
                        d["latencies"].append(o["latency"])
                    if o["ttft"] is not None:
                        d["ttfts"].append(o["ttft"])
        for cls, n in self.rejected.items():
            out.setdefault(cls, {"n": 0, "done": 0, "full": 0,
                                 "latencies": [],
                                 "ttfts": []})["n"] += n
        return out

    def latency_p99_s(self, qos: str) -> Optional[float]:
        """Whole-episode p99 completion latency of the class's
        full-service DONE requests (virtual seconds)."""
        lats = self.per_class().get(qos, {}).get("latencies", [])
        return float(np.percentile(lats, 99)) if lats else None

    def slo_attainment(self, qos: str, latency_s: float) -> float:
        """Fraction of the class's OFFERED load (accepted + rejected)
        that finished 'done', AT FULL SERVICE, within ``latency_s``.
        A rejected or shed request is a miss — admission control must
        not launder SLO misses into non-measurements — and so is a
        degrade-capped truncation: answering 4 of the 10 requested
        tokens fast is not meeting the SLO, it is a cheap way to fake
        one (the static-panic sweep point exists to prove the
        distinction matters)."""
        d = self.per_class().get(qos)
        if not d or d["n"] == 0:
            return 1.0
        ok = sum(1 for x in d["latencies"] if x <= latency_s)
        return ok / d["n"]

    def ttft_attainment(self, qos: str, ttft_s: float) -> float:
        """Fraction of the class's OFFERED load whose first token
        landed within ``ttft_s`` AND whose request finished done at
        full service — the same no-laundering discipline as
        `slo_attainment` (a fast first token on a request that was
        then evicted is not an attained TTFT), and the disaggregation
        drill's headline metric."""
        d = self.per_class().get(qos)
        if not d or d["n"] == 0:
            return 1.0
        ok = sum(1 for x in d["ttfts"] if x <= ttft_s)
        return ok / d["n"]

    def goodput_tok_s(self) -> float:
        """Generated tokens of DONE requests per virtual second."""
        tok = sum(o["n_tokens"] for o in self.outcomes
                  if o["status"] == "done")
        return tok / max(self.virtual_s, 1e-9)

    def fingerprint(self) -> str:
        """The bit-determinism surface: sha256 over the transition
        history, the autopilot episode, and every request outcome
        (status + token digest). Same (trace, seed) ⇒ same value."""
        doc = {"trace": self.trace_fingerprint,
               "transitions": self.transitions,
               "actions": self.actions,
               "outcomes": self.outcomes,
               "rejected": self.rejected,
               "rounds": self.rounds}
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()).hexdigest()

    def to_json(self) -> dict:
        per = {cls: {"offered": d["n"], "done": d["done"],
                     "full": d["full"]}
               for cls, d in sorted(self.per_class().items())}
        out = {"trace": self.trace_kind, "seed": self.trace_seed,
               "trace_fingerprint": self.trace_fingerprint,
               "n_arrivals": self.n_arrivals,
               "n_submitted": self.n_submitted,
               "rejected": self.rejected, "per_class": per,
               "goodput_tok_per_virtual_s":
                   round(self.goodput_tok_s(), 2),
               "n_actions": len(self.actions),
               "n_transitions": len(self.transitions),
               "virtual_s": round(self.virtual_s, 3),
               "rounds": self.rounds,
               "fingerprint": self.fingerprint()}
        # goodput-multiplier rates (ISSUE 15), when the episode banked
        # them — ride the report, NOT the fingerprint (pre-existing
        # traces must fingerprint bit-stably)
        for k in ("prefix_hit_rate", "accept_rate"):
            if k in self.summary:
                out[k] = round(self.summary[k], 4)
        # disaggregated-episode visibility (ISSUE 16) — same rule:
        # rides the report, never the fingerprint
        cnt = self.summary.get("counters", {})
        if "handoff_failures" in cnt:
            out["handoffs"] = sum(1 for t in self.transitions
                                  if t.get("event") == "handoff")
            out["handoff_failures"] = cnt["handoff_failures"]
            out["handoff_reroutes"] = cnt.get("handoff_reroutes", 0)
            out["pool_shifts"] = sum(1 for t in self.transitions
                                     if t.get("event") == "pool_shift")
        return out


_METERED_CLS = None


def _metered_engine_cls():
    """Engine subclass charging prefill its chunk count in supervision
    rounds (``FleetSimConfig.prefill_round_cost``): a step that admits
    ``k`` total prefill chunks stalls the replica for ``k - 1`` further
    rounds (every resident decode stream waits — the head-of-line cost
    disaggregation removes from the decode tier, whose radix-hit
    admissions prefill at most one remainder chunk). Built lazily so
    the module imports without the serving stack."""
    global _METERED_CLS
    if _METERED_CLS is not None:
        return _METERED_CLS
    from apex1_tpu.serving import Engine

    class _MeteredEngine(Engine):
        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._stall_rounds = 0
            self._chunks_this_step = 0

        def _run_chunks(self, slot, tokens, idx0, install_lane, seed):
            C = self.cfg.prefill_chunk
            self._chunks_this_step += math.ceil(int(tokens.size) / C)
            return super()._run_chunks(slot, tokens, idx0,
                                       install_lane, seed)

        def step(self):
            if self._stall_rounds > 0:
                # still paying an earlier admission's prefill: no
                # admissions, no decode — the round is burned
                self._stall_rounds -= 1
                self.metrics.step_sample(0, self.cfg.max_slots,
                                         self.scheduler.depth)
                return 0
            self._chunks_this_step = 0
            out = super().step()
            if self._chunks_this_step > 1:
                self._stall_rounds = self._chunks_this_step - 1
            return out

    _METERED_CLS = _MeteredEngine
    return _MeteredEngine


class FleetSim:
    """One simulated episode over a `Trace`.

    ``frontend_config`` is the real `serving.FrontendConfig` under
    test (static baseline or autopilot-driven); ``autopilot`` an
    `autopilot.AutopilotConfig` to attach a controller (None = static
    fleet); ``chaos`` a `testing.chaos.ServingFault`. The toy-decoder
    engines, the virtual clock, and the shared metrics window are
    owned here.
    """

    def __init__(self, trace: Trace, frontend_config, *,
                 sim: Optional[FleetSimConfig] = None,
                 autopilot=None, chaos=None):
        from apex1_tpu.serving import (Engine, EngineConfig,
                                       ServingFrontend)
        from apex1_tpu.testing.chaos import toy_decoder

        self.trace = trace
        self.cfg = sim or FleetSimConfig()
        self.clock = VirtualClock()
        apply_fn, make_cache, params = toy_decoder(self.cfg.vocab)
        ecfg = EngineConfig(
            max_slots=self.cfg.slots_per_replica,
            max_len=self.cfg.max_len,
            prefill_chunk=self.cfg.prefill_chunk,
            vocab_size=self.cfg.vocab,
            temperature=self.cfg.temperature,
            num_draft=self.cfg.num_draft,
            cache_dtype=self.cfg.cache_dtype,
            seed=frontend_config.seed)

        EngineCls = (_metered_engine_cls()
                     if self.cfg.prefill_round_cost else Engine)

        def make_engine(cache_dtype=None):
            # a degraded-mode restart's explicit dtype overrides the
            # sim's steady-state tier (the Engine kwarg-beats-config
            # rule)
            return EngineCls(apply_fn, make_cache, params, ecfg,
                             cache_dtype=cache_dtype)

        # no explicit metrics=: the frontend's own default wiring
        # (window from the config, our virtual clock) IS the
        # production wiring the simulator claims to drive
        if self.cfg.disagg:
            from apex1_tpu.serving.disagg import (DisaggConfig,
                                                  DisaggFrontend)
            n_pre = max(1, int(self.cfg.prefill_replicas))
            n_dec = max(1, int(frontend_config.n_replicas) - n_pre)
            # split, never add: prefill + decode == the unified fleet's
            # replica count, so a unified-vs-disagg A/B compares
            # ROUTING, not provisioning
            dcfg = DisaggConfig(
                prefill=dataclasses.replace(frontend_config,
                                            n_replicas=n_pre),
                decode=dataclasses.replace(frontend_config,
                                           n_replicas=n_dec),
                prefill_chunk=self.cfg.prefill_chunk,
                handoff_latency_s=self.cfg.handoff_latency_s,
                seed=frontend_config.seed,
                metrics_window=frontend_config.metrics_window)
            self.front = DisaggFrontend(make_engine, dcfg,
                                        fault=chaos, clock=self.clock)
        else:
            self.front = ServingFrontend(make_engine, frontend_config,
                                         fault=chaos, clock=self.clock)
        self.pilot = None
        if autopilot is not None:
            from apex1_tpu.autopilot import Autopilot
            self.pilot = Autopilot(self.front, autopilot,
                                   clock=self.clock)

    def _prompt(self, idx: int, n: int) -> np.ndarray:
        # prompt tokens are a pure function of (trace seed, request
        # index): replaying the same trace re-derives identical prompts
        rng = np.random.default_rng(
            [int(self.trace.seed), 0x70C5, int(idx)])
        return rng.integers(0, self.cfg.vocab, (n,)).astype(np.int32)

    def run(self) -> SimReport:
        from apex1_tpu.serving import Backpressure

        trace, cfg, front = self.trace, self.cfg, self.front
        reqs = trace.requests
        rejected: Dict[str, int] = {}
        submitted: Dict[int, int] = {}   # rid (== trace idx) -> idx
        i = 0
        rounds = 0
        next_ctl = 0.0
        deadline = trace.horizon_s + cfg.drain_grace_s
        while i < len(reqs) or front.total_inflight > 0:
            now = self.clock()
            if now > deadline or rounds >= cfg.max_rounds:
                raise TimeoutError(
                    f"fleetsim wedged: {front.total_inflight} in "
                    f"flight at virtual t={now:.2f}s "
                    f"(deadline {deadline:.2f}s, round {rounds}; "
                    f"replicas {front.replica_states()})")
            while i < len(reqs) and reqs[i].t <= now:
                r = reqs[i]
                try:
                    front.submit(self._prompt(i, r.prompt_len),
                                 max_new_tokens=r.max_new_tokens,
                                 qos=r.qos, tenant=r.tenant,
                                 req_id=i)  # trace idx = stable id ⇒
                    #  derived seeds (and tokens) replay bit-identical
                    submitted[i] = i
                except Backpressure:
                    rejected[r.qos] = rejected.get(r.qos, 0) + 1
                i += 1
            front.pump(1)
            if self.pilot is not None and now + 1e-12 >= next_ctl:
                self.pilot.tick()
                next_ctl += cfg.control_interval_s
            self.clock.advance(cfg.dt_s)
            rounds += 1
        return self._report(submitted, rejected, rounds)

    def _report(self, submitted: Dict[int, int],
                rejected: Dict[str, int], rounds: int) -> SimReport:
        front, trace = self.front, self.trace
        outcomes = []
        for rid in sorted(submitted):
            res = front.poll(rid)
            rec = front.metrics.records.get(rid)
            req = trace.requests[rid]
            toks = res.tokens if res is not None else np.zeros(0)
            n_tokens = int(np.asarray(toks).size)
            outcomes.append({
                "idx": rid, "qos": req.qos, "tenant": req.tenant,
                "status": res.status if res else "lost",
                # full service = every REQUESTED token delivered (a
                # degrade-capped truncation is not a fulfilled request)
                "full": bool(res is not None and res.status == "done"
                             and n_tokens >= req.max_new_tokens),
                "latency": (None if rec is None or rec.latency is None
                            else round(rec.latency, 6)),
                "ttft": (None if rec is None or rec.ttft is None
                         else round(rec.ttft, 6)),
                "n_tokens": n_tokens,
                "tokens_sha1": hashlib.sha1(
                    np.ascontiguousarray(
                        np.asarray(toks, np.int32)).tobytes()
                ).hexdigest()[:12]})
        return SimReport(
            trace_kind=trace.kind, trace_seed=trace.seed,
            trace_fingerprint=trace.fingerprint(),
            n_arrivals=len(trace.requests),
            n_submitted=len(submitted),
            rejected=dict(sorted(rejected.items())),
            outcomes=outcomes,
            transitions=list(front.metrics.transitions),
            actions=(list(self.pilot.actions) if self.pilot else []),
            summary=front.summary(),
            virtual_s=self.clock(), rounds=rounds)


def run_fleet(trace: Trace, frontend_config, *,
              sim: Optional[FleetSimConfig] = None, autopilot=None,
              chaos=None) -> SimReport:
    """Build + run one episode (the one-call form the drills and
    benches use)."""
    return FleetSim(trace, frontend_config, sim=sim,
                    autopilot=autopilot, chaos=chaos).run()


def kill_k_of_n(seed: int, *, n_replicas: int, k: int, lo: int,
                hi: int):
    """Seed-keyed PERMANENT shrink of a serving fleet: k distinct
    replicas each get a repeating `chaos.ReplicaKill` at a derived
    step, so every restart crashes again until the supervisor's budget
    is spent and the frontend fails the replica's work over — the
    fleet serves on the n−k survivors. The serving mirror of the
    training side's `chaos.shrink_schedule` (ISSUE 14's kill-k-of-n
    drill): same seed ⇒ same victims and steps, so "k of n replicas
    die and every request still completes" is an assertable property.
    """
    from apex1_tpu.resilience.retry import _mix32
    from apex1_tpu.testing.chaos import ChaosSchedule, ReplicaKill

    if not 0 < k < n_replicas:
        raise ValueError(
            f"need 0 < k < n_replicas, got k={k} of {n_replicas}")
    if hi <= lo:
        raise ValueError(f"need hi > lo, got [{lo}, {hi})")
    start = _mix32(seed ^ 0x51A7E) % n_replicas
    kills = []
    for j in range(k):
        victim = (start + j) % n_replicas       # k DISTINCT victims
        step = lo + _mix32(seed ^ 0xB10C ^ (j * 0x9E3779B9)) % (hi - lo)
        kills.append(ReplicaKill(victim, step, repeat=True))
    return ChaosSchedule(kills)
