"""HLO-text probes that pin the communication/compute OVERLAP property.

The overlap layer (`parallel.ring_attention`'s double-buffered carry,
`parallel.halo.exchange_overlap`, the decomposed collective matmuls in
`transformer.tensor_parallel.mappings`) claims that each loop step's
ppermute is issued so the step's compute has no data dependence on it —
letting XLA hide the ICI transfer behind the MXU work. A docstring
claim rots; this module makes it a PINNED property of the optimized
executable text, checked two ways depending on what the backend emits:

- **async mode** (TPU, incl. the tunnel-free AOT topology client that
  `tools/aot_check.py` uses): XLA converts collectives to
  ``collective-permute-start``/``-done`` pairs and the printed
  instruction order of a compiled executable is the post-scheduling
  order. A loop body passes when some start is scheduled BEFORE the
  body's first compute op and its matching done AFTER the last one —
  i.e. the transfer brackets the dots. The serialized rotate→attend
  loop fails: its done must precede the dots that consume it.
- **dependence mode** (CPU virtual mesh — the tier-1 harness — where
  XLA keeps synchronous ``collective-permute``): instruction order
  proves nothing, but the DATA DEPENDENCE that forces serialization is
  visible: a body passes when no compute op is a (transitive, in-body)
  consumer of any collective-permute's result. The serialized loop
  fails because its dots consume this step's permute.

"Compute ops" are dots/convolutions, fusions whose fused computation
contains one, and Pallas kernels (``tpu_custom_call`` custom-calls).

Entry points: `optimized_hlo` (compile and return executable text),
`check_collective_overlap` (returns a report), and
`assert_collective_overlap` (raises on failure — the test/gate form).
``python -m apex1_tpu.testing.hlo_probe`` runs the CPU self-check that
`tools/check_all.sh` wires in: the overlapped ring (fwd AND bwd) must
PASS and the retained `ring_attention_serial` loop must FAIL.

STANDING-RISK NOTE (the gate topology, VERDICT r5 Weak #7): on the CPU
harness the Pallas ring/ulysses path only ever EXECUTES in interpret
mode under ``check_vma=False`` — tier-1 therefore proves ring
*numerics* on the XLA-composite path, while the Mosaic lowering of the
shipped TPU configuration is guarded ONLY by the AOT compile gate
(``tools/aot_check.py`` collectives section, which also runs the async
form of this probe). Keep that gate in ``check_all.sh``; it is the real
guard for the TPU ring path, not the pytest suite. See
docs/parallel.md "Communication overlap layer".
"""

from __future__ import annotations

import dataclasses
import re

_COMPUTE_OPCODES = ("dot", "convolution")


@dataclasses.dataclass
class Instruction:
    name: str
    opcode: str
    operands: list
    line: str


@dataclasses.dataclass
class BodyReport:
    """Verdict for one while-loop body."""

    body: str
    mode: str            # "async" | "dependence"
    ok: bool
    n_permutes: int
    n_compute: int
    detail: str


@dataclasses.dataclass
class ProbeReport:
    """Aggregate verdict: every applicable loop body must pass."""

    mode: str
    ok: bool
    bodies: list
    detail: str


def optimized_hlo(fn, *args):
    """Optimized-executable HLO text of ``jit(fn)`` on ``args`` (arrays
    or ShapeDtypeStructs)."""
    import jax

    return jax.jit(fn).lower(*args).compile().as_text()


def _skip_balanced(s, i):
    """Index just past the balanced-paren group starting at ``s[i]``."""
    depth = 0
    while i < len(s):
        if s[i] == "(":
            depth += 1
        elif s[i] == ")":
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    return i


def _parse_instruction(line):
    ls = line.strip()
    if " = " not in ls:
        return None
    lhs, rhs = ls.split(" = ", 1)
    name = lhs.replace("ROOT", "").strip().lstrip("%")
    # skip the result type: a balanced (..) tuple type or one
    # space-free token, then the opcode runs up to the operand paren
    rhs = rhs.strip()
    if rhs.startswith("("):
        rhs = rhs[_skip_balanced(rhs, 0):].strip()
    else:
        parts = rhs.split(" ", 1)
        rhs = parts[1].strip() if len(parts) > 1 else ""
    m = re.match(r"([a-zA-Z][\w\-]*)\(", rhs)
    if not m:
        return None
    opcode = m.group(1)
    operands = re.findall(r"%([\w.\-]+)", rhs)
    return Instruction(name=name, opcode=opcode, operands=operands,
                       line=ls)


def parse_computations(hlo_text):
    """{computation name: [Instruction, ...]} for an HLO module dump."""
    comps = {}
    cur = None
    for line in hlo_text.splitlines():
        ls = line.strip()
        if ls.endswith("{") and "->" in ls and "=" not in ls.split("(")[0]:
            name = ls.split("(")[0].replace("ENTRY", "").strip()
            cur = name.lstrip("%")
            comps[cur] = []
            continue
        if ls == "}":
            cur = None
            continue
        if cur is not None:
            instr = _parse_instruction(line)
            if instr is not None:
                comps[cur].append(instr)
    return comps


def _while_bodies(comps):
    """Names of computations used as while-loop bodies."""
    bodies = set()
    for instrs in comps.values():
        for ins in instrs:
            if ins.opcode == "while":
                m = re.search(r"body=%?([\w.\-]+)", ins.line)
                if m:
                    bodies.add(m.group(1))
    return bodies


def _direct_compute(ins):
    if ins.opcode in _COMPUTE_OPCODES:
        return True
    return ins.opcode == "custom-call" and "tpu_custom_call" in ins.line


def _called_computations(ins, comps):
    """Computation names an instruction references (fusion ``calls=``,
    conditional branches, nested while bodies, reducers, …): every
    %-reference that names a computation rather than an instruction."""
    return [ref for ref in ins.operands if ref in comps]


def _computation_has_compute(name, comps, cache):
    if name in cache:
        return cache[name]
    cache[name] = False  # cycle guard
    result = False
    for ins in comps.get(name, []):
        if _direct_compute(ins):
            result = True
            break
        if any(_computation_has_compute(c, comps, cache)
               for c in _called_computations(ins, comps)):
            result = True
            break
    cache[name] = result
    return result


def _is_compute(ins, comps, cache):
    """Directly a dot/convolution/Pallas call, or an op (fusion,
    conditional, nested call…) whose called computations contain one —
    the ring's attend sits under the causal ``lax.cond``, so the
    conditional IS the compute op at loop-body level."""
    if _direct_compute(ins):
        return True
    return any(_computation_has_compute(c, comps, cache)
               for c in _called_computations(ins, comps))


def _check_body_async(body, instrs, compute_idx):
    """Scheduled-order check: some start strictly before the first
    compute op whose matching done lands after the last one."""
    starts = {ins.name: i for i, ins in enumerate(instrs)
              if ins.opcode == "collective-permute-start"}
    first, last = min(compute_idx), max(compute_idx)
    n_pairs = 0
    for i, ins in enumerate(instrs):
        if ins.opcode != "collective-permute-done":
            continue
        for op in ins.operands:
            if op in starts:
                n_pairs += 1
                if starts[op] < first and i > last:
                    return BodyReport(
                        body=body, mode="async", ok=True,
                        n_permutes=len(starts), n_compute=len(compute_idx),
                        detail=f"start@{starts[op]} < compute[{first}.."
                               f"{last}] < done@{i}")
    return BodyReport(
        body=body, mode="async", ok=False, n_permutes=len(starts),
        n_compute=len(compute_idx),
        detail=f"no start/done pair brackets the compute ops "
               f"[{first}..{last}] ({n_pairs} pairs inspected) — the "
               f"transfers are serialized against the dots")


def _check_body_dependence(body, instrs, compute_idx, comps):
    """Data-dependence check: no compute op may (transitively, within
    the body) consume a collective-permute result."""
    permute_idx = [i for i, ins in enumerate(instrs)
                   if ins.opcode in ("collective-permute",
                                     "collective-permute-start")]
    by_name = {ins.name: i for i, ins in enumerate(instrs)}
    consumers = {i: set() for i in range(len(instrs))}
    for i, ins in enumerate(instrs):
        for op in ins.operands:
            j = by_name.get(op)
            if j is not None:
                consumers[j].add(i)
    compute = set(compute_idx)
    for p in permute_idx:
        seen, stack = set(), [p]
        while stack:
            cur = stack.pop()
            for nxt in consumers[cur]:
                if nxt in seen:
                    continue
                seen.add(nxt)
                if nxt in compute:
                    return BodyReport(
                        body=body, mode="dependence", ok=False,
                        n_permutes=len(permute_idx),
                        n_compute=len(compute_idx),
                        detail=f"compute op '{instrs[nxt].name}' consumes "
                               f"'{instrs[p].name}' — the dots wait on "
                               f"this step's transfer")
                stack.append(nxt)
    return BodyReport(
        body=body, mode="dependence", ok=True,
        n_permutes=len(permute_idx), n_compute=len(compute_idx),
        detail="no compute op depends on an in-body collective-permute")


def count_collectives(hlo_text, prefixes=("all-reduce",)):
    """Count instructions whose opcode starts with any of ``prefixes``
    across every computation (async pairs count once via their -start).
    The structural pin for fusions that REDUCE the collective count
    rather than overlap it — e.g. the fused vocab-parallel linear_xent
    merge (2 all-reduces: one pmax + one packed psum) against its
    decomposed 4-collective ladder (the falsifiable negative control:
    the decomposed program must count higher)."""
    comps = parse_computations(hlo_text)
    n = 0
    for instrs in comps.values():
        for ins in instrs:
            if any(ins.opcode.startswith(p) for p in prefixes):
                if ins.opcode.endswith("-done"):
                    continue  # its -start was already counted
                n += 1
    return n


def check_collective_overlap(hlo_text):
    """Probe every while-loop body that carries both collective-permutes
    and compute ops. Returns a `ProbeReport`; ``ok`` iff at least one
    such body exists and ALL of them exhibit the overlap property."""
    comps = parse_computations(hlo_text)
    mode = ("async" if "collective-permute-start" in hlo_text
            else "dependence")
    reports = []
    cache = {}
    for body in sorted(_while_bodies(comps)):
        instrs = comps.get(body, [])
        has_permute = any(ins.opcode.startswith("collective-permute")
                          for ins in instrs)
        compute_idx = [i for i, ins in enumerate(instrs)
                       if _is_compute(ins, comps, cache)]
        if not has_permute or not compute_idx:
            continue
        if mode == "async":
            reports.append(_check_body_async(body, instrs, compute_idx))
        else:
            reports.append(_check_body_dependence(body, instrs,
                                                  compute_idx, comps))
    if not reports:
        return ProbeReport(
            mode=mode, ok=False, bodies=[],
            detail="no while-loop body with both collective-permutes and "
                   "compute ops found — nothing to probe (wrong program, "
                   "or the loop was fully unrolled)")
    ok = all(r.ok for r in reports)
    detail = "; ".join(f"{r.body}: {'OK' if r.ok else 'FAIL'} "
                       f"({r.n_permutes} permutes, {r.n_compute} compute) "
                       f"{r.detail}" for r in reports)
    return ProbeReport(mode=mode, ok=ok, bodies=reports, detail=detail)


def assert_collective_overlap(hlo_text, *, expect_mode=None):
    """Raise ``AssertionError`` unless every applicable loop body in
    ``hlo_text`` overlaps its transfers with compute. ``expect_mode``
    optionally pins which probe mode must apply ("async" on TPU
    executables — the start-before-dots/done-after property the
    acceptance gate names; "dependence" on CPU)."""
    rep = check_collective_overlap(hlo_text)
    if expect_mode is not None and rep.mode != expect_mode:
        raise AssertionError(
            f"hlo_probe ran in {rep.mode!r} mode, expected "
            f"{expect_mode!r} (wrong backend for this gate?)")
    if not rep.ok:
        raise AssertionError(f"collective overlap probe FAILED "
                             f"[{rep.mode}]: {rep.detail}")
    return rep


def _self_check():
    """CPU-mesh gate (check_all.sh): compile the overlapped ring fwd AND
    bwd on the 8-device virtual mesh and require the probe to PASS;
    compile the retained serialized ring and require it to FAIL (the
    probe must be falsifiable, not vacuous)."""
    from apex1_tpu.testing import force_virtual_cpu_devices

    force_virtual_cpu_devices(8)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.parallel.ring_attention import (ring_attention,
                                                   ring_attention_serial)

    mesh = make_mesh(cp=4, dp=1, devices=jax.devices()[:4])
    rng = np.random.default_rng(0)
    B, H, S, D = 1, 2, 128, 32
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
               for _ in range(3))
    spec = P(None, None, "cp", None)

    def smap(fn):
        return jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec)

    ring = smap(lambda q, k, v: ring_attention(q, k, v, "cp", causal=True))
    rep = assert_collective_overlap(optimized_hlo(ring, q, k, v),
                                    expect_mode="dependence")
    print(f"  OK   ring fwd overlapped      [{rep.mode}] "
          f"{len(rep.bodies)} loop body(ies)")

    def ring_loss(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    rep = assert_collective_overlap(
        optimized_hlo(jax.grad(ring_loss, argnums=(0, 1, 2)), q, k, v),
        expect_mode="dependence")
    print(f"  OK   ring fwd+bwd overlapped  [{rep.mode}] "
          f"{len(rep.bodies)} loop body(ies)")

    serial = smap(lambda q, k, v: ring_attention_serial(q, k, v, "cp",
                                                        causal=True))
    srep = check_collective_overlap(optimized_hlo(serial, q, k, v))
    if srep.ok or not srep.bodies:
        raise AssertionError(
            "negative control failed: the serialized ring must FAIL the "
            f"overlap probe, got ok={srep.ok} bodies={len(srep.bodies)}")
    print("  OK   serialized ring FAILS the probe (negative control)")

    # fused comm-kernels (ops.fused_collective): the SP-boundary fused
    # matmuls must pass the same dependence probe (their ring hops are
    # carry-only), and the serialized rotate-then-dot form must FAIL —
    # the PR 9 additions to this gate
    from jax.sharding import PartitionSpec as P2
    from apex1_tpu.ops.fused_collective import (
        fused_all_gather_matmul, fused_all_gather_matmul_serial,
        fused_matmul_reduce_scatter)

    tp_mesh = make_mesh(tp=4, dp=1, devices=jax.devices()[:4])
    S_l, hid, ffn = 32, 16, 24
    x = jnp.asarray(rng.normal(size=(S_l * 4, hid)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(hid, ffn)), jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(ffn, hid)), jnp.float32)

    def fused_mlp(x, w1, w2):
        h = fused_all_gather_matmul(x, w1, "tp", 0)
        return fused_matmul_reduce_scatter(
            h.astype(jnp.float32), w2, "tp", 0)

    fsm = jax.shard_map(fused_mlp, mesh=tp_mesh,
                        in_specs=(P2("tp"), P2(None, "tp"),
                                  P2("tp", None)),
                        out_specs=P2("tp"), check_vma=False)
    rep = assert_collective_overlap(optimized_hlo(fsm, x, w1, w2),
                                    expect_mode="dependence")
    print(f"  OK   fused SP matmuls overlapped [{rep.mode}] "
          f"{len(rep.bodies)} loop body(ies)")

    ssm = jax.shard_map(
        lambda x, w: fused_all_gather_matmul_serial(x, w, "tp", 0),
        mesh=tp_mesh, in_specs=(P2("tp"), P2(None, "tp")),
        out_specs=P2(None, "tp"), check_vma=False)
    srep = check_collective_overlap(optimized_hlo(ssm, x, w1))
    if srep.ok or not srep.bodies:
        raise AssertionError(
            "negative control failed: the serialized fused all-gather "
            f"matmul must FAIL, got ok={srep.ok} "
            f"bodies={len(srep.bodies)}")
    print("  OK   serialized fused AG-matmul FAILS (negative control)")
    print("hlo_probe self-check PASSED")


if __name__ == "__main__":
    _self_check()
