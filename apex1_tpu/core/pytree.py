"""Pytree/flat-buffer utilities — the ``multi_tensor_apply`` substrate.

Reference: ``apex/multi_tensor_apply/multi_tensor_apply.py :: MultiTensorApply``
packs lists of tensors into chunked kernel launches; ``csrc/
flatten_unflatten.cpp :: flatten/unflatten`` (``apex_C``) flattens DDP buckets.

On TPU the XLA compiler already fuses elementwise updates across parameters
into a few loops, so the *performance* role of multi_tensor_apply is covered
by compilation. What remains useful — and is provided here — is the *shape*
of the API: treating a whole pytree as one logical flat buffer (for fused
global norms, one-kernel optimizer updates over the concatenated buffer, DDP
bucket views, and checkpoint packing). A C++ host-side packer lives in
``apex1_tpu.runtime`` for host RAM staging.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


def tree_float_leaves(tree):
    leaves = [jnp.asarray(x) for x in jax.tree_util.tree_leaves(tree)]
    return [x for x in leaves if jnp.issubdtype(x.dtype, jnp.floating)]


def flatten_tree(tree, dtype=None):
    """Concatenate the *floating* leaves into ONE 1-D buffer; non-float
    leaves (step counters, token ids, bools) are carried through untouched.

    Returns ``(flat, unflatten)`` where ``unflatten(flat) -> tree``.
    Equivalent of ``apex_C.flatten`` + bucket bookkeeping, but done once at
    trace time; XLA turns the concatenation into layout assignment, not a
    copy, when the consumer is elementwise.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    leaves = [jnp.asarray(x) for x in leaves]
    is_float = [jnp.issubdtype(x.dtype, jnp.floating) for x in leaves]
    floats = [x for x, f in zip(leaves, is_float) if f]
    shapes = [x.shape for x in floats]
    dtypes = [x.dtype for x in floats]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = jnp.concatenate(
        [jnp.ravel(x).astype(dtype or dtypes[i])
         for i, x in enumerate(floats)]) if floats else jnp.zeros((0,))

    offsets = np.cumsum([0] + sizes)

    def unflatten(buf):
        outs, j = [], 0
        for leaf, f in zip(leaves, is_float):
            if f:
                piece = buf[offsets[j]:offsets[j + 1]]
                outs.append(piece.reshape(shapes[j]).astype(dtypes[j]))
                j += 1
            else:
                outs.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, outs)

    return flat, unflatten


def global_norm(tree, *, per_leaf: bool = False):
    """Fused global L2 norm (and optionally per-leaf norms, as LAMB needs).

    Reference: ``amp_C.multi_tensor_l2norm`` two-stage grid reduction with
    optional ``per_tensor`` output (``csrc/multi_tensor_l2norm_kernel.cu``).
    """
    leaves = tree_float_leaves(tree)
    if not leaves:
        z = jnp.float32(0)
        return (z, []) if per_leaf else z
    sq = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves]
    gnorm = jnp.sqrt(jnp.sum(jnp.stack(sq)))
    if per_leaf:
        return gnorm, [jnp.sqrt(s) for s in sq]
    return gnorm


def tree_scale(tree, factor):
    """``amp_C.multi_tensor_scale`` — one fused scale over all tensors."""
    def scale(x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        return (x.astype(jnp.float32) * factor).astype(x.dtype)
    return jax.tree_util.tree_map(scale, tree)


def tree_axpby(a, x_tree, b, y_tree, out_dtype=None):
    """``amp_C.multi_tensor_axpby``: out = a*x + b*y, fused across the tree.

    Accumulates in fp32; result keeps x's dtype (or ``out_dtype``), matching
    the kernel's explicit out-tensor dtype. Non-float leaves pass through
    from ``y_tree`` unchanged.
    """
    def axpby(x, y):
        x, y = jnp.asarray(x), jnp.asarray(y)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return y
        acc = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        return acc.astype(out_dtype or x.dtype)
    return jax.tree_util.tree_map(axpby, x_tree, y_tree)


def tree_cast_like(tree, like):
    return jax.tree_util.tree_map(
        lambda x, l: x.astype(jnp.asarray(l).dtype), tree, like)


def tree_map_unzip(f: Callable[..., tuple], n_out: int, *trees):
    """Map ``f`` (returning an ``n_out``-tuple) over leaves of ``trees`` and
    return ``n_out`` trees. Safe for pytrees whose containers are themselves
    tuples (a naive ``tree_map`` + ``is_leaf=isinstance(tuple)`` unzip is
    not)."""
    leaves0, treedef = jax.tree_util.tree_flatten(trees[0])
    rest = [treedef.flatten_up_to(t) for t in trees[1:]]
    outs = [f(*args) for args in zip(leaves0, *rest)]
    return tuple(
        jax.tree_util.tree_unflatten(treedef, [o[i] for o in outs])
        for i in range(n_out))


def named_tree_map(f: Callable[[str, Any], Any], tree, sep: str = "/"):
    """tree_map with a "path/to/leaf" first argument — used by the regex →
    PartitionSpec sharding rules (SNIPPETS.md [1] pattern)."""
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in paths_and_leaves:
        name = sep.join(_path_element_str(p) for p in path)
        out.append(f(name, leaf))
    return jax.tree_util.tree_unflatten(treedef, out)


def _path_element_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)
