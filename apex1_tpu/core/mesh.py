"""Device-mesh construction and logical-axis resources.

TPU-native replacement for the reference's NCCL process-group topology
(``apex/transformer/parallel_state.py :: initialize_model_parallel``): instead
of carving ``world_size`` ranks into TP/PP/DP process groups, we build one
``jax.sharding.Mesh`` whose named axes ARE the groups. Collectives ride ICI
for the inner axes and DCN for the outermost (data) axis on multi-slice —
mirroring the reference's rank layout where TP ranks are contiguous (fastest
ICI links) and DP strides outermost.

Axis names (canonical, innermost last):

    dp    — replica data parallel        (reference: apex DDP / NCCL allreduce)
    fsdp  — sharded data parallel        (reference: contrib DistributedFusedAdam,
                                          ZeRO-style)
    pp    — pipeline stages              (reference: pipeline_parallel)
    cp    — context/sequence parallel    (reference: [absent]; ring attention)
    ep    — expert parallel              (reference: [absent]; transformer.moe
                                          all_to_all dispatch)
    tp    — tensor model parallel        (reference: tensor_parallel; innermost
                                          = contiguous devices, like Megatron's
                                          contiguous TP ranks)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis order: outermost (slowest network, DCN on multislice) first,
# innermost (fastest ICI) last — tp gets device-contiguous placement.
AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_PP = "pp"
AXIS_CP = "cp"
AXIS_EP = "ep"
AXIS_TP = "tp"
MESH_AXES = (AXIS_DP, AXIS_FSDP, AXIS_PP, AXIS_CP, AXIS_EP, AXIS_TP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Parallelism degrees. Product must divide the device count; a degree of
    -1 (at most one) absorbs the remaining devices.

    Reference: ``parallel_state.initialize_model_parallel(tensor_model_parallel_size,
    pipeline_model_parallel_size, ...)`` — dp there is implied
    (world_size / tp / pp); here any axis may be the absorbing one.
    """

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    cp: int = 1
    ep: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        sizes = dataclasses.asdict(self)
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        bad = {k: v for k, v in sizes.items() if v != -1 and v < 1}
        if bad:
            raise ValueError(f"axis sizes must be >= 1 (or -1), got {bad}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"fixed axes product {fixed} does not divide {n_devices}")
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}")
        return MeshConfig(**sizes)

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.dp, self.fsdp, self.pp, self.cp, self.ep, self.tp)


def _normalize_mesh_args(config, axis_sizes, devices):
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis sizes, not both")
    devices = list(jax.devices()) if devices is None else list(devices)
    return config, devices


def make_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    allow_split_physical_axes: bool = False,
    **axis_sizes: int,
) -> Mesh:
    """Build a ``Mesh`` with the canonical six axes.

    ``make_mesh(dp=2, tp=4)`` or ``make_mesh(MeshConfig(dp=2, tp=4))``.
    Uses ``mesh_utils.create_device_mesh`` so the physical ICI topology is
    respected (nearest-neighbour axes get torus links); falls back to a plain
    reshape on CPU/virtual device sets.
    """
    config, devices = _normalize_mesh_args(config, axis_sizes, devices)
    config = config.resolve(len(devices))
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            config.shape,
            devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    except Exception:
        # Virtual/CPU device sets have no physical topology — a plain reshape
        # is exact there. On real accelerators a create_device_mesh failure is
        # a topology problem the caller must see (silent fallback would give
        # TP ranks non-contiguous ICI placement).
        if any(d.platform != "cpu" for d in devices):
            raise
        dev_array = np.asarray(devices).reshape(config.shape)
    return Mesh(dev_array, MESH_AXES)


def make_hybrid_mesh(
    config: MeshConfig | None = None,
    *,
    dcn_dp: int = 1,
    devices: Sequence[jax.Device] | None = None,
    process_is_granule: bool = False,
    granule_ids: Sequence[int] | None = None,
    **axis_sizes: int,
) -> Mesh:
    """Multi-slice mesh: the outer data-parallel axis rides DCN (slice to
    slice), everything else rides ICI within a slice — the mesh-axis →
    fabric mapping of SURVEY §5.8 (≙ the reference's NCCL-over-IB outer
    data parallelism around per-node NVLink groups).

    ``dcn_dp`` slices multiply the ICI mesh's ``dp`` axis: the returned
    mesh has ``dp = dcn_dp * ici_dp`` with slice-major ordering, so the
    gradient psum over ``dp`` decomposes into an intra-slice ICI
    reduction plus one inter-slice DCN exchange — XLA does this split
    automatically for hierarchical device orders. Single-slice
    (``dcn_dp=1``) delegates to `make_mesh`.

    Call from a multi-controller job after ``jax.distributed.initialize``
    (`parallel.multiproc`); ``process_is_granule=True`` is the fallback
    for platforms without ``slice_index`` device attributes.

    ``granule_ids``: explicit per-device slice assignment (one id in
    ``[0, dcn_dp)`` per device, in ``devices`` order). For virtual/CPU
    topologies whose devices carry neither ``slice_index`` nor distinct
    ``process_index`` — e.g. the 8-device CPU mesh the dryrun and tests
    run on — this builds the same slice-major dp ordering with REAL
    (runnable) devices, which the FakeDev path cannot.
    """
    config, devices = _normalize_mesh_args(config, axis_sizes, devices)
    if dcn_dp < 1:
        raise ValueError(f"dcn_dp must be >= 1, got {dcn_dp}")
    if dcn_dp == 1:
        return make_mesh(config, devices=devices)
    if len(devices) % dcn_dp:
        raise ValueError(
            f"{len(devices)} devices do not split into dcn_dp={dcn_dp} "
            "slices")
    per_slice = len(devices) // dcn_dp
    config = config.resolve(per_slice)
    dp_axis = MESH_AXES.index(AXIS_DP)
    if granule_ids is not None:
        if len(granule_ids) != len(devices):
            raise ValueError(
                f"granule_ids has {len(granule_ids)} entries for "
                f"{len(devices)} devices")
        slices: list[list] = [[] for _ in range(dcn_dp)]
        for d, g in zip(devices, granule_ids):
            if not 0 <= g < dcn_dp:
                raise ValueError(f"granule id {g} outside [0, {dcn_dp})")
            slices[g].append(d)
        if any(len(s) != per_slice for s in slices):
            raise ValueError(
                f"granule_ids must assign exactly {per_slice} devices per "
                f"slice, got {[len(s) for s in slices]}")
        # slice-major dp: stack each slice's ICI mesh along the dp axis,
        # so dp index a // ici_dp = slice — identical ordering semantics
        # to create_hybrid_device_mesh
        per_arrays = [
            np.asarray(make_mesh(config, devices=s).devices)
            for s in slices]
        dev_array = np.concatenate(per_arrays, axis=dp_axis)
        return Mesh(dev_array, MESH_AXES)
    from jax.experimental import mesh_utils

    dcn_shape = tuple(dcn_dp if ax == AXIS_DP else 1 for ax in MESH_AXES)
    dev_array = mesh_utils.create_hybrid_device_mesh(
        config.shape, dcn_shape, devices=devices,
        process_is_granule=process_is_granule)
    return Mesh(dev_array, MESH_AXES)


def local_mesh(**axis_sizes: int) -> Mesh:
    """Mesh over all visible devices; convenience for tests and single-host."""
    return make_mesh(MeshConfig(**axis_sizes) if axis_sizes else None)


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def data_parallel_size(mesh: Mesh) -> int:
    """Total gradient-replica count: dp × fsdp (fsdp shards, then psums)."""
    return axis_size(mesh, AXIS_DP) * axis_size(mesh, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshResource:
    """Logical-axis → mesh-axis binding (pattern: SNIPPETS.md [2],
    TransformerEngine-style). Models name logical axes ("batch", "embed",
    "heads", "mlp", "vocab", "seq"); configs bind them to mesh axes, so the
    same model code runs under any parallelism layout.
    """

    batch: str | tuple[str, ...] | None = (AXIS_DP, AXIS_FSDP)
    seq: str | None = AXIS_CP
    embed: str | None = None
    heads: str | None = AXIS_TP
    mlp: str | None = AXIS_TP
    vocab: str | None = AXIS_TP
    kv: str | None = None
    stages: str | None = AXIS_PP

    def spec(self, *logical: str | None) -> PartitionSpec:
        """PartitionSpec from logical axis names; None → replicated dim."""
        out = []
        for name in logical:
            if name is None:
                out.append(None)
            else:
                if not hasattr(self, name):
                    raise ValueError(f"unknown logical axis {name!r}")
                out.append(getattr(self, name))
        return PartitionSpec(*out)

    def sharding(self, mesh: Mesh, *logical: str | None) -> NamedSharding:
        return NamedSharding(mesh, self.spec(*logical))


DEFAULT_RESOURCE = MeshResource()


def shard_batch(mesh: Mesh, batch, resource: MeshResource = DEFAULT_RESOURCE):
    """Place a host batch onto the mesh sharded along the batch logical axis
    (reference DDP's per-rank loader split — here one sharded device_put)."""
    sharding = resource.sharding(mesh, "batch")
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def replicate(mesh: Mesh, tree):
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
