"""Core substrate: device mesh construction, precision policy, loss scaling,
pytree/flattening utilities, RNG plumbing.

Reference counterparts: ``apex/amp/frontend.py :: Properties`` (policy),
``apex/amp/scaler.py :: LossScaler`` (loss scaling),
``apex/transformer/parallel_state.py`` (topology — here a ``jax.sharding.Mesh``).
"""

from apex1_tpu.core.mesh import (  # noqa: F401
    MeshConfig,
    MeshResource,
    make_hybrid_mesh,
    make_mesh,
    local_mesh,
)
from apex1_tpu.core.capability import (  # noqa: F401
    CapabilityError,
    TpuCapability,
    detect_generation,
    get_capability,
    require,
    vmem_budget,
)
from apex1_tpu.core.policy import PrecisionPolicy, get_policy  # noqa: F401
from apex1_tpu.core.loss_scale import (  # noqa: F401
    LossScaleState,
    NoOpLossScale,
    StaticLossScale,
    DynamicLossScale,
    all_finite,
)
