"""Functional loss scaling — reference ``apex/amp/scaler.py :: LossScaler``.

The reference mutates a host-side scaler object and uses a device-side
``noop_flag`` (written by the fused ``amp_C`` kernels) so an overflow aborts
the optimizer kernel without a host sync. Here the whole step is one XLA
program, so the same property falls out naturally: the scale is a traced
``LossScaleState`` threaded through the step, the finite-check is a fused
reduction, and the skip is a ``jax.lax.cond``/``jnp.where`` — no host sync,
ever.

Semantics replicated exactly from the reference:
  - dynamic: init 2**16, double every ``growth_interval`` (2000) consecutive
    clean steps, halve on inf/nan, skip the optimizer step on overflow
    (``scaler.py :: LossScaler.update_scale``).
  - ``min_loss_scale`` / ``max_loss_scale`` clamps
    (``frontend.py :: initialize`` kwargs).
  - TP/PP interaction: the finite flag must agree across the model-parallel
    mesh (``apex/transformer/amp/grad_scaler.py :: GradScaler`` all-reduces
    found_inf) — ``all_finite`` reduces over ALL leaves; under ``shard_map``
    callers psum it over mesh axes via ``axis_names``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

import chex


@chex.dataclass(frozen=True)
class LossScaleState:
    """Carried through the train step; a pytree (jit-friendly)."""

    scale: jnp.ndarray            # f32 scalar
    growth_count: jnp.ndarray     # i32 scalar: consecutive clean steps
    overflow_count: jnp.ndarray   # i32 scalar: total skipped steps (metrics)
    # i32 scalar: overflows left before the scale actually halves
    # (≙ csrc/update_scale_hysteresis.cu's device-side hysteresis counter;
    # 1 ⇒ classic halve-on-every-overflow)
    hysteresis_left: jnp.ndarray = dataclasses.field(
        default_factory=lambda: jnp.int32(1))


def all_finite(tree, axis_names: tuple[str, ...] = ()) -> jnp.ndarray:
    """Fused global finite check over a pytree of grads.

    Reference: ``amp_C.multi_tensor_l2norm``'s in-kernel inf/nan detection
    writing ``noop_flag``; python fallback ``scaler.py :: _has_inf_or_nan``.
    XLA fuses the per-leaf reductions into the backward epilogue.
    """
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    if not leaves:
        finite = jnp.bool_(True)
    else:
        finite = jnp.stack(
            [jnp.all(jnp.isfinite(x)) for x in leaves]).all()
    for ax in axis_names:
        finite = jax.lax.pmin(finite.astype(jnp.int32), ax).astype(jnp.bool_)
    return finite


class _LossScaleBase:
    def init(self) -> LossScaleState:
        raise NotImplementedError

    def scale(self, loss, state: LossScaleState):
        return loss * state.scale.astype(loss.dtype)

    def unscale(self, grads, state: LossScaleState):
        inv = (1.0 / state.scale)

        def unscale_leaf(g):
            g = jnp.asarray(g)
            if not jnp.issubdtype(g.dtype, jnp.floating):
                return g
            return (g.astype(jnp.float32) * inv).astype(g.dtype)

        return jax.tree_util.tree_map(unscale_leaf, grads)

    def adjust(self, state: LossScaleState, grads_finite) -> LossScaleState:
        raise NotImplementedError


class NoOpLossScale(_LossScaleBase):
    """scale==1; used by O0 and bf16 paths (bf16 range ≈ fp32, no scaling)."""

    def init(self) -> LossScaleState:
        return LossScaleState(scale=jnp.float32(1.0),
                              growth_count=jnp.int32(0),
                              overflow_count=jnp.int32(0),
                              hysteresis_left=jnp.int32(1))

    def scale(self, loss, state):
        return loss

    def unscale(self, grads, state):
        return grads

    def adjust(self, state, grads_finite):
        return state


class StaticLossScale(_LossScaleBase):
    """``loss_scale=<float>`` in ``amp.initialize``; never adjusts."""

    def __init__(self, scale: float):
        self._scale = float(scale)

    def init(self) -> LossScaleState:
        return LossScaleState(scale=jnp.float32(self._scale),
                              growth_count=jnp.int32(0),
                              overflow_count=jnp.int32(0),
                              hysteresis_left=jnp.int32(1))

    def adjust(self, state, grads_finite):
        return dataclasses.replace(
            state,
            overflow_count=state.overflow_count
            + jnp.where(grads_finite, 0, 1).astype(jnp.int32))


class DynamicLossScale(_LossScaleBase):
    """Reference dynamic scaling state machine
    (``scaler.py :: LossScaler`` with ``dynamic`` init + the on-device
    hysteresis variant ``csrc/update_scale_hysteresis.cu``)."""

    def __init__(self,
                 init_scale: float = 2.0 ** 16,
                 growth_factor: float = 2.0,
                 backoff_factor: float = 0.5,
                 growth_interval: int = 2000,
                 min_loss_scale: float = 1.0,
                 max_loss_scale: float = 2.0 ** 24,
                 hysteresis: int = 1):
        self.init_scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.min_loss_scale = float(min_loss_scale)
        self.max_loss_scale = float(max_loss_scale)
        self.hysteresis = int(hysteresis)

    def init(self) -> LossScaleState:
        return LossScaleState(scale=jnp.float32(self.init_scale),
                              growth_count=jnp.int32(0),
                              overflow_count=jnp.int32(0),
                              hysteresis_left=jnp.int32(self.hysteresis))

    def adjust(self, state: LossScaleState, grads_finite) -> LossScaleState:
        """Reference semantics (``update_scale_hysteresis.cu``): a clean
        step advances the growth tracker (×growth every
        ``growth_interval``, which also REFILLS the hysteresis budget);
        an overflow zeroes the tracker and spends one unit of budget —
        the scale halves once the budget is exhausted, and KEEPS halving
        on every further overflow until growth refills it (fast recovery
        from a far-too-high scale). ``hysteresis=1`` ⇒ the classic
        ``scaler.py :: LossScaler`` halve-on-every-overflow."""
        grads_finite = jnp.asarray(grads_finite)
        grew = state.growth_count + 1 >= self.growth_interval
        clean_scale = jnp.where(
            grew, state.scale * self.growth_factor, state.scale)
        clean_count = jnp.where(grew, 0, state.growth_count + 1)
        hys_spent = jnp.maximum(state.hysteresis_left - 1, 0)
        backoff = (~grads_finite) & (hys_spent <= 0)
        new_scale = jnp.where(
            grads_finite, clean_scale,
            jnp.where(backoff, state.scale * self.backoff_factor,
                      state.scale))
        new_scale = jnp.clip(new_scale, self.min_loss_scale,
                             self.max_loss_scale)
        new_hys = jnp.where(
            grads_finite,
            jnp.where(grew, self.hysteresis, state.hysteresis_left),
            hys_spent)
        return LossScaleState(
            scale=new_scale.astype(jnp.float32),
            growth_count=jnp.where(grads_finite, clean_count, 0)
            .astype(jnp.int32),
            overflow_count=(state.overflow_count
                            + jnp.where(grads_finite, 0, 1)).astype(jnp.int32),
            hysteresis_left=new_hys.astype(jnp.int32),
        )


def make_loss_scale(spec: Any) -> _LossScaleBase:
    """Resolve the ``loss_scale`` policy property:
    None → no-op, "dynamic" → DynamicLossScale, number → StaticLossScale."""
    if spec is None:
        return NoOpLossScale()
    if isinstance(spec, _LossScaleBase):
        return spec
    if spec == "dynamic":
        return DynamicLossScale()
    return StaticLossScale(float(spec))


def select_tree(pred, on_true, on_false):
    """Per-leaf ``jnp.where`` used for skip-on-overflow: keep old params/opt
    state when the step overflowed (reference: wrapped ``optimizer.step``
    early-return in ``_process_optimizer.py``, in-kernel ``noop_flag``)."""
    return jax.tree_util.tree_map(
        lambda t, f: jnp.where(pred, t, f), on_true, on_false)
