"""TPU-generation capability table — the gating layer that replaces the
reference's build-time flag registry.

Reference: ``setup.py`` (≈800 lines) is apex's de-facto feature-flag
system — every native extension is an opt-in ``--flag`` build gated on the
CUDA version and compute capability (sm70/80/90 lists per extension), and
kernels check ``torch.cuda.get_device_capability`` at runtime
(e.g. fmha requires sm80, head-dim 64). On TPU there is nothing to build —
Pallas kernels ship with the package and lower through Mosaic for whatever
chip is attached — so the *capability* that survives is the per-generation
hardware table: block-shape heuristics read VMEM size, precision policies
check native-dtype support, and ``require()`` gives contrib modules the
same "this kernel needs sm80" style guard (as data, not compiled-out code).

Generation detection prefers the explicit ``PALLAS_AXON_TPU_GEN`` env (set
by the axon tunnel), then ``jax.devices()[0].device_kind``.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import re


@dataclasses.dataclass(frozen=True)
class TpuCapability:
    """Public per-generation facts that gate or tune framework behavior."""

    generation: str           # canonical name: "v4", "v5e", "v5p", "v6e"
    mxu: tuple[int, int]      # systolic array shape
    vmem_bytes: int           # per-core VMEM the kernel block planner sees
    hbm_bytes: int            # per-chip HBM
    hbm_gbps: float           # per-chip HBM bandwidth (GB/s)
    bf16_tflops: float        # peak dense bf16 TFLOP/s per chip
    cores_per_chip: int       # TensorCores per chip (megacore counts as 1)
    ici_axes: int             # torus dimensionality (2 = 2D, 3 = 3D)
    native_fp8: bool          # fp8 matmul support
    sparsecore: bool          # embedding SparseCore present
    ici_gbps: float = 0.0     # per-chip aggregate ICI bandwidth (GB/s,
    #                           spec-sheet "interchip interconnect BW"
    #                           converted from Gbit/s; /ici_axes/2 ≈ one
    #                           link — the ring-neighbor transfer rate
    #                           the overlap roofline comms term prices)


_TABLE = {
    # Public spec-sheet numbers (cloud.google.com/tpu/docs system specs);
    # vmem_bytes is the conservative planning figure, not a spec claim.
    # ici_gbps: spec "interchip interconnect BW" per chip, Gbit/s -> GB/s
    # (v2 496 / v3 656 / v4 2400 / v5e 1600 / v5p 4800 / v6e 3584 Gbps).
    "v2": TpuCapability("v2", (128, 128), 16 * 2**20, 16 * 2**30, 600.0,
                        45.0, 2, 2, False, False, 62.0),
    "v3": TpuCapability("v3", (128, 128), 16 * 2**20, 32 * 2**30, 900.0,
                        123.0, 2, 2, False, False, 82.0),
    "v4": TpuCapability("v4", (128, 128), 32 * 2**20, 32 * 2**30, 1200.0,
                        275.0, 1, 3, False, True, 300.0),
    "v5e": TpuCapability("v5e", (128, 128), 32 * 2**20, 16 * 2**30, 819.0,
                         197.0, 1, 2, False, False, 200.0),
    "v5p": TpuCapability("v5p", (128, 128), 64 * 2**20, 95 * 2**30, 2765.0,
                         459.0, 1, 3, False, True, 600.0),
    "v6e": TpuCapability("v6e", (256, 256), 64 * 2**20, 32 * 2**30, 1640.0,
                         918.0, 1, 2, False, True, 448.0),
}

_KIND_PATTERNS = [
    (re.compile(r"v6e|trillium", re.I), "v6e"),
    (re.compile(r"v5p", re.I), "v5p"),
    (re.compile(r"v5 ?lite|v5e", re.I), "v5e"),
    (re.compile(r"v4", re.I), "v4"),
    (re.compile(r"v3", re.I), "v3"),
    (re.compile(r"v2", re.I), "v2"),
]


def _canonical(kind: str) -> str | None:
    for pat, gen in _KIND_PATTERNS:
        if pat.search(kind):
            return gen
    return None


@functools.cache
def detect_generation() -> str | None:
    """Best-effort generation of the attached TPU; None off-TPU."""
    env = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    if env:
        got = _canonical(env)
        if got:
            return got
    try:
        import jax
        dev = jax.devices()[0]
        if dev.platform in ("tpu",) or "TPU" in dev.device_kind:
            return _canonical(dev.device_kind)
    except Exception:
        pass
    return None


def get_capability(generation: str | None = None) -> TpuCapability:
    """Capability row for ``generation`` (default: detected chip). Off-TPU
    returns the v5e row — the conservative tuning target the CPU interpret
    path should agree with."""
    gen = generation or detect_generation() or "v5e"
    try:
        return _TABLE[gen]
    except KeyError:
        raise ValueError(
            f"unknown TPU generation {gen!r}; known: {sorted(_TABLE)}"
        ) from None


class CapabilityError(RuntimeError):
    """≙ the reference's '<ext> requires compute capability >= sm80'."""


def require(feature: str, *, generation: str | None = None) -> None:
    """Assert the attached chip supports ``feature`` — the runtime analog
    of setup.py's per-extension sm gating. Features: "fp8", "sparsecore",
    "ici_3d", "megacore"."""
    cap = get_capability(generation)
    ok = {
        "fp8": cap.native_fp8,
        "sparsecore": cap.sparsecore,
        "ici_3d": cap.ici_axes >= 3,
        "megacore": cap.cores_per_chip == 1,
    }
    if feature not in ok:
        raise ValueError(f"unknown feature {feature!r}; known: {sorted(ok)}")
    if not ok[feature]:
        raise CapabilityError(
            f"feature {feature!r} requires a newer TPU generation than "
            f"{cap.generation} (≙ apex setup.py sm-arch gate)")


def vmem_budget(generation: str | None = None) -> int:
    """VMEM bytes the Pallas block planners should assume (leaves headroom
    for Mosaic's own double buffering)."""
    return get_capability(generation).vmem_bytes // 2


def ici_link_gbps(generation: str | None = None) -> float:
    """Conservative per-neighbor ICI rate (GB/s): the aggregate per-chip
    spec figure split across the torus's ``2 * ici_axes`` links. This is
    the rate a ring ppermute hop (ONE neighbor transfer) sees — the
    denominator of the roofline comms term (`tools/predict_perf.py`,
    bench.py's ``ici_exposed_bytes`` pricing). 0.0 when the generation
    row carries no ICI figure."""
    cap = get_capability(generation)
    if not cap.ici_gbps:
        return 0.0
    return cap.ici_gbps / (2 * cap.ici_axes)
