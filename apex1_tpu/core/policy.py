"""Precision policy — the TPU-idiomatic equivalent of amp opt levels.

Reference: ``apex/amp/frontend.py :: Properties, O0, O1, O2, O3``. Each opt
level there bundles five properties (``cast_model_type``,
``patch_torch_functions``, ``keep_batchnorm_fp32``, ``master_weights``,
``loss_scale``) and O1 is implemented by monkey-patching torch ops
(``apex/amp/lists/{functional_overrides,torch_overrides}.py``).

JAX is functionally traced, so there is nothing to monkey-patch: the policy is
a frozen dataclass applied at module/param boundaries (jmp-style). The O1
"op lists" survive as *semantics*: compute runs in ``compute_dtype`` while the
numerically fragile ops the reference blacklists (softmax, norms, losses,
exp/pow reductions) run in fp32 — our kernels (`apex1_tpu.ops`) upcast
internally exactly where the reference's FP32_FUNCS list did.

Opt-level mapping (bf16 is the TPU-native half type; fp16 kept for parity):

    O0  — fp32 everything (debug/gold)
    O1  — params fp32, compute bf16/fp16, fragile ops fp32, dynamic loss
          scaling for fp16 (bf16 needs none)
    O2  — params stored fp32 ("master weights" ARE the params), model applied
          in half via cast-on-use inside the jitted step, norms fp32
    O3  — half everything (speed ceiling / debugging)
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

_FLOATS = (jnp.float32, jnp.bfloat16, jnp.float16, jnp.float64)


def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Frozen bundle of dtypes + flags, mirroring amp ``Properties``.

    - ``param_dtype``: storage dtype of parameters (fp32 ⇒ params are the
      fp32 master weights of reference O2 — no separate copy needed).
    - ``compute_dtype``: dtype activations/matmuls run in.
    - ``output_dtype``: dtype of model outputs (``cast_model_outputs``).
    - ``keep_norms_fp32``: reference ``keep_batchnorm_fp32`` generalized to
      all normalization layers (TPU kernels accumulate stats in fp32 anyway).
    - ``fp32_fragile_ops``: the O1-vs-O2 distinction, made explicit. O1's
      monkey-patch lists run FP32_FUNCS (softmax/losses/exp/pow) in fp32;
      O2 casts the whole model and does NOT patch functions, so those ops run
      in half. Our kernels (`apex1_tpu.ops`) consult this flag for their
      input/output dtypes (accumulation is always fp32 on the MXU/VPU).
    - ``loss_scale``: "dynamic", None, or a static float — consumed by
      ``apex1_tpu.core.loss_scale``.
    """

    name: str = "O1"
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    output_dtype: Any = jnp.float32
    keep_norms_fp32: bool = True
    fp32_fragile_ops: bool = True
    loss_scale: Any = None  # None | "dynamic" | float

    # ---- casts (jmp-style) -------------------------------------------------
    def cast_to_compute(self, tree):
        return _cast_floats(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return _cast_floats(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return _cast_floats(tree, self.output_dtype)

    def with_overrides(self, **kw) -> "PrecisionPolicy":
        return dataclasses.replace(self, **kw)

    @property
    def uses_loss_scaling(self) -> bool:
        return self.loss_scale is not None

    @property
    def is_mixed(self) -> bool:
        return self.compute_dtype != self.param_dtype

    # ---- O1 op-registration surface ---------------------------------------
    # ≙ apex/amp/amp.py :: half_function / float_function / promote_function
    # (the user-facing way to extend the FP16_FUNCS/FP32_FUNCS/CASTS lists).
    # No monkey-patching under jit: these return a wrapped callable whose
    # float array inputs are cast per the policy before the op runs.
    def half_function(self, fn):
        """Run ``fn`` with float inputs cast to the compute dtype
        (whitelist ≙ FP16_FUNCS)."""
        def wrapped(*args, **kw):
            args, kw = _cast_floats((args, kw), self.compute_dtype)
            return fn(*args, **kw)
        return wrapped

    def float_function(self, fn):
        """Run ``fn`` with float inputs cast to fp32 (blacklist ≙
        FP32_FUNCS — numerically fragile ops)."""
        def wrapped(*args, **kw):
            args, kw = _cast_floats((args, kw), jnp.float32)
            return fn(*args, **kw)
        return wrapped

    def promote_function(self, fn):
        """Run ``fn`` with float inputs promoted to the WIDEST float dtype
        among them (≙ CASTS promote-widest for ambiguous ops)."""
        def wrapped(*args, **kw):
            leaves = [x for x in jax.tree_util.tree_leaves((args, kw))
                      if _is_float(x)]
            if leaves:
                args, kw = _cast_floats(
                    (args, kw), jnp.result_type(*leaves))
            return fn(*args, **kw)
        return wrapped


def _cast_floats(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float(x) else x, tree)


def _mk(name, **kw) -> PrecisionPolicy:
    return PrecisionPolicy(name=name, **kw)


# Named presets. ``apex/amp/frontend.py :: opt_levels`` dict equivalent.
# "half" resolves per-target: bf16 presets are the TPU-native defaults;
# explicit fp16 variants replicate the reference's loss-scaled path bit-for-
# spirit (dynamic scale init 2^16, ×2/2000 steps, ÷2 on overflow — see
# core/loss_scale.py).
POLICIES = {
    "O0": _mk("O0", compute_dtype=jnp.float32, loss_scale=None),
    "O1": _mk("O1", compute_dtype=jnp.bfloat16),
    "O2": _mk("O2", compute_dtype=jnp.bfloat16, fp32_fragile_ops=False),
    "O3": _mk("O3", param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16,
              output_dtype=jnp.bfloat16, keep_norms_fp32=False,
              fp32_fragile_ops=False),
    "O1_fp16": _mk("O1_fp16", compute_dtype=jnp.float16, loss_scale="dynamic"),
    "O2_fp16": _mk("O2_fp16", compute_dtype=jnp.float16,
                   fp32_fragile_ops=False, loss_scale="dynamic"),
    "O3_fp16": _mk("O3_fp16", param_dtype=jnp.float16,
                   compute_dtype=jnp.float16, output_dtype=jnp.float16,
                   keep_norms_fp32=False, fp32_fragile_ops=False,
                   loss_scale=None),
}


def get_policy(spec: str | PrecisionPolicy, **overrides) -> PrecisionPolicy:
    """Resolve a policy by name with per-property overrides — the equivalent
    of ``amp.initialize(..., opt_level="O2", keep_batchnorm_fp32=True)``
    kwarg-override semantics (``frontend.py :: Properties`` setattr path)."""
    if isinstance(spec, PrecisionPolicy):
        pol = spec
    else:
        try:
            pol = POLICIES[spec]
        except KeyError:
            raise ValueError(
                f"unknown opt level {spec!r}; valid: {sorted(POLICIES)}")
    if overrides:
        pol = pol.with_overrides(**overrides)
    return pol
