"""RNG plumbing — reference ``apex/transformer/tensor_parallel/random.py``.

The reference keeps a ``CudaRNGStatesTracker`` of named CUDA RNG streams so
that dropout differs across TP ranks ("model-parallel-rng", seeded
``seed + 2718 + tp_rank``) while the default stream matches across them, and
its activation ``checkpoint`` snapshots/restores RNG state to replay dropout
exactly on recompute.

JAX's counter-based threefry makes all of that structural:

- per-rank divergence = ``fold_in`` of the mesh axis index;
- recompute replay is free — ``jax.checkpoint`` replays the same key;
- no mutable state to snapshot.

We keep the tracker API shape for parity (named domains → folded keys).
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp

# Stable fold constants per named domain (2718 mirrors the reference's
# model-parallel seed offset in ``model_parallel_cuda_manual_seed``).
_DOMAIN_SALT = {
    "default": 0,
    "model-parallel-rng": 2718,
    "data-parallel-rng": 1042,
}


def domain_key(key: jax.Array, domain: str = "default") -> jax.Array:
    salt = _DOMAIN_SALT.get(domain)
    if salt is None:
        # crc32, not hash(): stable across processes so checkpoint-resume
        # replays identical keys regardless of PYTHONHASHSEED.
        salt = zlib.crc32(domain.encode()) & 0x7FFFFFFF
    return jax.random.fold_in(key, salt)


def model_parallel_key(key: jax.Array, tp_axis: str = "tp") -> jax.Array:
    """Inside ``shard_map``: per-TP-rank dropout key
    (≙ ``model_parallel_cuda_manual_seed``'s ``seed + 2718 + tp_rank``)."""
    idx = jax.lax.axis_index(tp_axis)
    return jax.random.fold_in(domain_key(key, "model-parallel-rng"), idx)


class RNGKeychain:
    """Host-side convenience: split a root seed into named, step-folded keys.

    Usage::

        chain = RNGKeychain(seed)
        dropout_key = chain.key("dropout", step)
    """

    def __init__(self, seed: int):
        self._root = jax.random.PRNGKey(seed)

    def key(self, name: str, step: int | jnp.ndarray = 0) -> jax.Array:
        return jax.random.fold_in(domain_key(self._root, name),
                                  jnp.asarray(step, jnp.uint32))
