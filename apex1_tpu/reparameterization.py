"""Weight reparameterization — reference ``apex/reparameterization/
{weight_norm,reparameterization}.py`` (fp16-safe weight normalization;
deprecated upstream, kept for surface parity).

w = g · v / ||v||, with the norm computed in fp32 regardless of the
parameter dtype (the module's whole reason to exist: fp16 ||v|| overflows
for large fan-in). Functional (`weight_norm`) and flax-module
(`WeightNorm` wrapper around a kernel-carrying module) forms.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


def weight_norm(v, g, *, dim: int | None = 0, eps: float = 1e-12):
    """w = g * v / ||v|| with fp32 norm. ``dim``: the output-channel axis
    kept un-reduced (reference ``dim=0`` convention); None = global norm."""
    v32 = v.astype(jnp.float32)
    if dim is None:
        norm = jnp.sqrt(jnp.sum(jnp.square(v32)) + eps)
    else:
        axes = tuple(a for a in range(v.ndim) if a != dim % v.ndim)
        norm = jnp.sqrt(jnp.sum(jnp.square(v32), axis=axes,
                                keepdims=True) + eps)
    g32 = g.astype(jnp.float32)
    if dim is not None and g32.ndim == 1:
        shape = [1] * v.ndim
        shape[dim % v.ndim] = g32.shape[0]
        g32 = g32.reshape(shape)
    return (g32 * v32 / norm).astype(v.dtype)


class WeightNormDense(nn.Module):
    """Dense layer under weight norm — ≙ applying the reference's
    ``apply_weight_norm(module)`` to a Linear."""

    features: int
    use_bias: bool = True
    dim: int = 1  # kernel is (in, out); out axis carries g

    @nn.compact
    def __call__(self, x):
        fan_in = x.shape[-1]
        v = self.param("v", nn.initializers.lecun_normal(),
                       (fan_in, self.features), jnp.float32)
        g = self.param("g", nn.initializers.ones, (self.features,),
                       jnp.float32)
        w = weight_norm(v, g, dim=self.dim).astype(x.dtype)
        y = x @ w
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros,
                               (self.features,),
                               jnp.float32).astype(x.dtype)
        return y


def remove_weight_norm(params: dict, *, dim: int = 1) -> dict:
    """Collapse {v, g} back into a materialized kernel
    (≙ ``remove_weight_norm(module)``)."""
    out = dict(params)
    if "v" in out and "g" in out:
        out["kernel"] = weight_norm(out.pop("v"), out.pop("g"), dim=dim)
    return out
