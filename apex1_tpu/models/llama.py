"""Llama-3 — BASELINE configs 4/5 model ("Llama-3 8B TP/PP on XLA mesh";
"Llama-3 8B long-ctx, Pallas flash-attn + fused RoPE").

The reference has no model zoo (its test transformers live in
``apex/transformer/testing/standalone_gpt.py``); this is the standalone
decoder built from this framework's fused ops: `apex1_tpu.ops.rms_norm`
(Pallas), `apex1_tpu.ops.attention.flash_attention` (Pallas, GQA, causal),
`apex1_tpu.ops.apply_rotary_pos_emb` (Pallas), fused vocab cross-entropy.

TPU-first design notes:
- all parameters are fp32 masters; compute casts per the precision policy
  (amp-O2 semantics, `apex1_tpu.core.policy`);
- `param_specs` returns a PartitionSpec tree from regex rules
  (SNIPPETS.md pattern [1]) binding head/ffn/vocab dims to the ``tp`` mesh
  axis and (optionally) everything to ``fsdp`` — GSPMD then inserts the
  same collectives the reference's ColumnParallel/RowParallel autograd
  functions issue by hand (SURVEY.md §7.0);
- ``remat`` applies ``jax.checkpoint`` per block (≙ reference activation
  checkpointing, ``tensor_parallel/random.py :: checkpoint``);
- ``seq_shard_axis`` + ring attention turn the same block into its
  context-parallel form (long-ctx config 5) — see
  `apex1_tpu.models.llama.llama_loss_fn` users and tests.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.ops import (apply_rotary_pos_emb, linear_cross_entropy,
                           rms_norm, rope_tables,
                           softmax_cross_entropy_loss)
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.parallel.ring_attention import ring_attention
from apex1_tpu.parallel.ulysses import ulysses_attention
from apex1_tpu.transformer.tensor_parallel.random import checkpoint_policy


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    max_seq_len: int = 8192
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    hidden_size: int = 4096
    ffn_size: int = 14336
    rope_base: float = 500000.0
    norm_eps: float = 1e-5
    remat: bool = False
    # jax.checkpoint_policies name — "nothing_saveable" = full recompute
    # (the reference's activation checkpointing); "dots_saveable" /
    # "dots_with_no_batch_dims_saveable" = SELECTIVE recompute (keep
    # matmul outputs, recompute elementwise/norm/softmax — Megatron's
    # --recompute-activations selective mode, trading a little memory
    # for most of the recompute FLOPs)
    remat_policy: str = "nothing_saveable"
    # MoE (beyond-reference, `transformer.moe`): every N-th block swaps
    # its dense FFN for a top-k-routed expert FFN; 0 = dense everywhere.
    moe_every: int = 0
    num_experts: int = 8
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_weight: float = 1e-2
    # context-parallel attention implementation when seq_shard_axis is
    # set: "ring" (ppermute KV, any device count) or "ulysses"
    # (all-to-all head scatter; the cp axis size must divide the head
    # counts, or the KV count for GQA-repeat)
    cp_impl: str = "ring"
    # route the dense-MLP glu through ops.fused_dense.fused_glu (one
    # Pallas pass over x on TPU; off-TPU the composite is token-for-token
    # the inline expression below, so flipping this is bitwise-neutral
    # on the CPU proxy — pinned by tests/test_fused_glu.py)
    fused_mlp: bool = False
    policy: PrecisionPolicy = dataclasses.field(
        default_factory=lambda: get_policy("O0"))

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        defaults = dict(vocab_size=256, max_seq_len=256, num_layers=2,
                        num_heads=4, num_kv_heads=2, hidden_size=64,
                        ffn_size=128)
        defaults.update(kw)
        return LlamaConfig(**defaults)

    def __post_init__(self):
        if self.cp_impl not in ("ring", "ulysses"):
            raise ValueError(f"cp_impl must be 'ring' or 'ulysses', got "
                             f"{self.cp_impl!r}")
        checkpoint_policy(self.remat_policy)  # fail fast on a typo

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def is_moe_layer(cfg: "LlamaConfig", i: int) -> bool:
    """THE MoE-layer placement rule (`moe_every > 0` => every
    ``moe_every``-th block, counting from the ``moe_every - 1``-th, is
    expert-routed). Single source of truth: `Llama.__call__` and the
    int8 decode path (`models.quant_decode`) must agree layer-by-layer
    or quantization would pick the wrong weight structure."""
    return cfg.moe_every > 0 and i % cfg.moe_every == cfg.moe_every - 1


class LlamaBlock(nn.Module):
    cfg: LlamaConfig
    # mesh axis carrying the sequence shard (ring/context parallel), or None
    seq_shard_axis: Optional[str] = None
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, cos, sin, segment_ids=None, cache=None,
                 cache_index=None, valid_start=None,
                 chunk_decode=False):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        E, H, Hkv, D = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                        cfg.head_dim)
        B, S = x.shape[0], x.shape[1]
        init = nn.initializers.normal(0.02)

        def norm(name, z):
            g = self.param(name, nn.initializers.ones, (E,), jnp.float32)
            if not cfg.policy.keep_norms_fp32:
                g = g.astype(dtype)
            return rms_norm(z, g, eps=cfg.norm_eps)

        h = norm("attn_norm", x).astype(dtype)
        wq = self.param("wq", init, (E, H * D), jnp.float32).astype(dtype)
        wk = self.param("wk", init, (E, Hkv * D), jnp.float32).astype(dtype)
        wv = self.param("wv", init, (E, Hkv * D), jnp.float32).astype(dtype)
        q = (h @ wq).reshape(B, S, H, D)
        k = (h @ wk).reshape(B, S, Hkv, D)
        v = (h @ wv).reshape(B, S, Hkv, D)
        q = apply_rotary_pos_emb(q, cos, sin)
        k = apply_rotary_pos_emb(k, cos, sin)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        new_cache = None
        if cache is not None:
            from apex1_tpu.models.generate import cached_attention
            attn, new_cache = cached_attention(q, k, v, cache,
                                               cache_index,
                                               segment_ids=segment_ids,
                                               valid_start=valid_start,
                                               chunk_decode=chunk_decode)
        elif self.seq_shard_axis is not None:
            if cfg.cp_impl == "ulysses":
                attn = ulysses_attention(q, k, v, self.seq_shard_axis,
                                         causal=True,
                                         segment_ids=segment_ids)
            else:  # "ring" — cp_impl validated in LlamaConfig
                attn = ring_attention(q, k, v, self.seq_shard_axis,
                                      causal=True, segment_ids=segment_ids)
        else:
            attn = flash_attention(q, k, v, causal=True,
                                   segment_ids=segment_ids)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, S, H * D)
        wo = self.param("wo", init, (H * D, E), jnp.float32).astype(dtype)
        x = x + (attn @ wo).astype(x.dtype)

        h = norm("mlp_norm", x).astype(dtype)
        if self.use_moe:
            from apex1_tpu.transformer.moe import MoEConfig, MoEMLP
            y, aux = MoEMLP(
                MoEConfig(num_experts=cfg.num_experts,
                          top_k=cfg.moe_top_k,
                          capacity_factor=cfg.moe_capacity_factor,
                          aux_loss_weight=cfg.moe_aux_loss_weight,
                          hidden_size=E, ffn_size=cfg.ffn_size),
                dtype=dtype, act=jax.nn.silu, name="moe")(
                h, token_mask=(None if segment_ids is None
                               else segment_ids >= 0))
            # surfaced via flax collections; llama_loss_fn adds it
            self.sow("losses", "moe_aux", aux)
            out = x + y.astype(x.dtype)
            return out if new_cache is None else (out, new_cache)
        wg = self.param("w_gate", init, (E, cfg.ffn_size),
                        jnp.float32).astype(dtype)
        wu = self.param("w_up", init, (E, cfg.ffn_size),
                        jnp.float32).astype(dtype)
        wd = self.param("w_down", init, (cfg.ffn_size, E),
                        jnp.float32).astype(dtype)
        if cfg.fused_mlp:
            from apex1_tpu.ops.fused_dense import fused_glu
            y = fused_glu(h, wg, wu, activation="silu") @ wd
        else:
            y = (jax.nn.silu(h @ wg) * (h @ wu)) @ wd
        out = x + y.astype(x.dtype)
        return out if new_cache is None else (out, new_cache)


class Llama(nn.Module):
    """Returns logits (B, S, vocab) in fp32-accumulated compute dtype."""

    cfg: LlamaConfig
    seq_shard_axis: Optional[str] = None

    @nn.compact
    def __call__(self, tokens, *, positions=None, segment_ids=None,
                 return_hidden=False, cache=None, cache_index=None,
                 valid_start=None, chunk_decode=False):
        """``segment_ids`` (B, S) enables PACKED batches (≙ the reference
        fmha's cu_seqlens varlen): tokens attend only within their own
        segment. Pass per-segment ``positions`` (B, S) so RoPE restarts
        at each document (see `pack_documents`).

        ``cache``/``cache_index`` enable KV-cached decoding (see
        `models.generate`): the return becomes ``(logits, new_cache)``;
        prefill (S>1) must start from an empty cache at index 0. With a
        cache, ``segment_ids``/``valid_start`` carry the RAGGED
        left-padded-prompt masking (``generate(prompt_lens=...)``) —
        don't combine the cache with ``seq_shard_axis``."""
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        B, S = tokens.shape
        emb = self.param("tok_embeddings", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        x = emb[tokens].astype(dtype)
        per_row_pos = positions is not None and jnp.ndim(positions) == 2
        if positions is None:
            positions = jnp.arange(S)
            if self.seq_shard_axis is not None:
                # local shard's global positions along the ring
                positions = positions + jax.lax.axis_index(
                    self.seq_shard_axis) * S
        if per_row_pos:
            # (B, S) per-segment positions -> per-row (B, S, half) tables
            cos, sin = rope_tables(positions.reshape(-1), cfg.head_dim,
                                   base=cfg.rope_base)
            cos = cos.reshape(B, S, -1)
            sin = sin.reshape(B, S, -1)
        else:
            cos, sin = rope_tables(positions, cfg.head_dim,
                                   base=cfg.rope_base)
        block = LlamaBlock
        if cfg.remat and cache is None:
            block = nn.remat(LlamaBlock, static_argnums=(),
                             policy=checkpoint_policy(cfg.remat_policy))
        new_cache = {}
        for i in range(cfg.num_layers):
            use_moe = is_moe_layer(cfg, i)
            out = block(cfg, self.seq_shard_axis, use_moe,
                        name=f"layer{i}")(
                x, cos, sin, segment_ids,
                cache=None if cache is None else cache[f"layer{i}"],
                cache_index=cache_index, valid_start=valid_start,
                chunk_decode=chunk_decode)
            if cache is None:
                x = out
            else:
                x, new_cache[f"layer{i}"] = out
        g = self.param("norm", nn.initializers.ones, (cfg.hidden_size,),
                       jnp.float32)
        if not cfg.policy.keep_norms_fp32:
            g = g.astype(dtype)
        x = rms_norm(x, g, eps=cfg.norm_eps)
        if return_hidden:
            # for the fused LM-head+CE path (ops.linear_cross_entropy)
            # and the serving LoRA epilogue (serving.engine computes the
            # head matmul itself so per-slot adapter deltas can fuse in);
            # with a cache the contract mirrors the logits return
            h = x.astype(dtype)
            return h if cache is None else (h, new_cache)
        head = self.param("output", nn.initializers.normal(0.02),
                          (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        logits = jnp.einsum("bsh,vh->bsv", x.astype(dtype),
                            head.astype(dtype),
                            preferred_element_type=jnp.float32)
        return logits if cache is None else (logits, new_cache)


# regex rules over flattened param paths -> PartitionSpec
# (pattern: SNIPPETS.md [1] — rules instead of per-layer hand specs)
_TP_RULES = (
    (r"tok_embeddings$", P("tp", None)),          # vocab-sharded embedding
    (r"output$", P("tp", None)),                   # vocab-sharded lm head
    (r"w[qkv]$", P(None, "tp")),                   # column-parallel qkv
    (r"wo$", P("tp", None)),                       # row-parallel out proj
    (r"w_(gate|up)$", P(None, "tp")),              # column-parallel ffn in
    (r"w_down$", P("tp", None)),                   # row-parallel ffn out
    (r"moe/w[12]$", P("ep", None, None)),          # expert-parallel FFNs
    (r"moe/router$", P()),
    (r".*norm$", P()),                             # replicated norms
)


def param_specs(params, *, rules=_TP_RULES, default=P()):
    """PartitionSpec tree for a Llama param tree (first matching rule wins).

    ≙ reference ``set_tensor_model_parallel_attributes`` on
    Column/RowParallelLinear weights — here a spec tree handed to pjit,
    GSPMD inserts the collectives."""
    from apex1_tpu.parallel.specs import specs_from_rules
    return specs_from_rules(params, rules, default=default)


def llama_loss_fn(model: Llama, *, fuse_head: bool = True):
    """``loss_fn(params, tokens) -> scalar``: next-token CE. Default path
    fuses the (huge — 128k for Llama-3) vocab head matmul into the CE
    kernel (``ops.linear_cross_entropy``); ``fuse_head=False`` keeps the
    materialized-logits gold."""

    moe = model.cfg.moe_every > 0

    def loss_fn(params, tokens, segment_ids=None, positions=None):
        kw = dict(segment_ids=segment_ids, positions=positions)
        mut = ["losses"] if moe else False
        if fuse_head:
            out = model.apply({"params": params}, tokens,
                              return_hidden=True, mutable=mut, **kw)
            h, aux_vars = out if moe else (out, {})
            losses = linear_cross_entropy(
                h[:, :-1], params["output"].astype(h.dtype), tokens[:, 1:])
        else:
            out = model.apply({"params": params}, tokens, mutable=mut, **kw)
            logits, aux_vars = out if moe else (out, {})
            losses = softmax_cross_entropy_loss(
                logits[:, :-1].astype(jnp.float32), tokens[:, 1:])
        if segment_ids is not None:
            from apex1_tpu.ops import masked_next_token_mean
            loss = masked_next_token_mean(losses, segment_ids)
        else:
            loss = jnp.mean(losses)
        if moe:
            # sowed Switch aux losses, one per MoE block
            loss = loss + sum(jnp.sum(jnp.asarray(v)) for v in
                              jax.tree_util.tree_leaves(
                                  aux_vars.get("losses", {})))
        return loss

    return loss_fn
