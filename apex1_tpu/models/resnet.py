"""ResNet-50 — BASELINE config 3 model ("ResNet-50 ImageNet with
SyncBatchNorm + DDP allreduce over ICI").

Reference analogue: ``examples/imagenet/main_amp.py`` (torchvision
resnet50 under amp + apex DDP + ``convert_syncbn_model``) and the fused
NHWC bottleneck of ``apex/contrib/bottleneck/bottleneck.py``. TPU-first
choices: NHWC layout throughout (the only layout TPU convs want — the
reference needed a ``channel_last`` fast path; here it is the default),
`apex1_tpu.parallel.SyncBatchNorm` for cross-replica statistics (psum
Welford merge), XLA fuses conv+BN+ReLU chains (the ``groupbn`` /
``cudnn_gbn`` BN+ReLU fusion is a compiler decision here, not a kernel).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.parallel.sync_batchnorm import SyncBatchNorm


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # resnet-50
    num_classes: int = 1000
    width: int = 64
    # mesh axis for SyncBN cross-replica stats; None = local BN
    bn_axis_name: Optional[str] = None
    bn_group_size: Optional[int] = None
    policy: PrecisionPolicy = dataclasses.field(
        default_factory=lambda: get_policy("O0"))

    @staticmethod
    def resnet50(**kw) -> "ResNetConfig":
        return ResNetConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "ResNetConfig":
        defaults = dict(stage_sizes=(1, 1), num_classes=10, width=8)
        defaults.update(kw)
        return ResNetConfig(**defaults)


class Bottleneck(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with identity/projection shortcut —
    ≙ ``apex/contrib/bottleneck/bottleneck.py :: Bottleneck`` (the fused
    NHWC block; XLA performs the conv+BN+ReLU fusion)."""

    cfg: ResNetConfig
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        bn = partial(SyncBatchNorm, axis_name=cfg.bn_axis_name,
                     group_size=cfg.bn_group_size,
                     use_running_average=not train, dtype=dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=dtype)
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = nn.relu(bn(name="bn1")(y))
        y = conv(self.features, (3, 3), strides=(self.strides,) * 2,
                 name="conv2")(y)
        y = nn.relu(bn(name="bn2")(y))
        y = conv(4 * self.features, (1, 1), name="conv3")(y)
        y = bn(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(4 * self.features, (1, 1),
                            strides=(self.strides,) * 2,
                            name="downsample_conv")(residual)
            residual = bn(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet; input (B, H, W, 3). Returns logits (B, classes)."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        x = x.astype(dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=dtype, name="stem_conv")(x)
        x = SyncBatchNorm(axis_name=cfg.bn_axis_name,
                          group_size=cfg.bn_group_size,
                          use_running_average=not train, dtype=dtype,
                          name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(cfg, cfg.width * 2 ** i, strides,
                               name=f"stage{i}_block{j}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(cfg.num_classes, dtype=dtype, name="fc")(x)
        return logits.astype(jnp.float32)
