"""ResNet-50 — BASELINE config 3 model ("ResNet-50 ImageNet with
SyncBatchNorm + DDP allreduce over ICI").

Reference analogue: ``examples/imagenet/main_amp.py`` (torchvision
resnet50 under amp + apex DDP + ``convert_syncbn_model``) and the fused
NHWC bottleneck of ``apex/contrib/bottleneck/bottleneck.py``. TPU-first
choices: NHWC layout throughout (the only layout TPU convs want — the
reference needed a ``channel_last`` fast path; here it is the default),
`apex1_tpu.parallel.SyncBatchNorm` for cross-replica statistics (psum
Welford merge), XLA fuses conv+BN+ReLU chains (the ``groupbn`` /
``cudnn_gbn`` BN+ReLU fusion is a compiler decision here, not a kernel).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.parallel.sync_batchnorm import SyncBatchNorm


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # resnet-50
    num_classes: int = 1000
    width: int = 64
    # mesh axis for SyncBN cross-replica stats; None = local BN
    bn_axis_name: Optional[str] = None
    bn_group_size: Optional[int] = None
    policy: PrecisionPolicy = dataclasses.field(
        default_factory=lambda: get_policy("O0"))

    @staticmethod
    def resnet50(**kw) -> "ResNetConfig":
        return ResNetConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "ResNetConfig":
        defaults = dict(stage_sizes=(1, 1), num_classes=10, width=8)
        defaults.update(kw)
        return ResNetConfig(**defaults)


class Bottleneck(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with identity/projection shortcut —
    ≙ ``apex/contrib/bottleneck/bottleneck.py :: Bottleneck`` (the fused
    NHWC block; XLA performs the conv+BN+ReLU fusion).

    ``spatial_axis_name`` turns on spatial parallelism (reference
    ``SpatialBottleneck``): the activation arrives H-sharded over that
    mesh axis, the 3×3 conv exchanges one halo row per neighbor
    (`apex1_tpu.parallel.halo`), the 1×1 convs stay local, and the BN
    statistics additionally psum over the spatial axis so they cover the
    FULL activation (otherwise train-mode stats would silently be
    per-shard). Stride-1 only in spatial mode (the spatial-parallel
    sweet spot: high-resolution early stages)."""

    cfg: ResNetConfig
    features: int
    strides: int = 1
    spatial_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        cfg = self.cfg
        spatial = self.spatial_axis_name
        if spatial is not None and self.strides != 1:
            raise ValueError("spatial parallelism supports stride 1 only")
        dtype = cfg.policy.compute_dtype
        # BN stats must span every axis the batch/activation is split over
        bn_axes = tuple(a for a in (cfg.bn_axis_name, spatial)
                        if a is not None)
        bn = partial(SyncBatchNorm,
                     axis_name=(bn_axes if len(bn_axes) > 1 else
                                (bn_axes[0] if bn_axes else None)),
                     group_size=cfg.bn_group_size,
                     use_running_average=not train, dtype=dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=dtype)
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = nn.relu(bn(name="bn1")(y))
        if spatial is not None:
            from apex1_tpu.parallel.halo import halo_exchange

            y = halo_exchange(y, spatial, halo=1, dim=1)
            y = conv(self.features, (3, 3), padding=((0, 0), (1, 1)),
                     name="conv2")(y)      # VALID on H: halo absorbs it
        else:
            y = conv(self.features, (3, 3), strides=(self.strides,) * 2,
                     name="conv2")(y)
        y = nn.relu(bn(name="bn2")(y))
        y = conv(4 * self.features, (1, 1), name="conv3")(y)
        y = bn(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(4 * self.features, (1, 1),
                            strides=(self.strides,) * 2,
                            name="downsample_conv")(residual)
            residual = bn(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """NHWC ResNet; input (B, H, W, 3). Returns logits (B, classes)."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        x = x.astype(dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=dtype, name="stem_conv")(x)
        x = SyncBatchNorm(axis_name=cfg.bn_axis_name,
                          group_size=cfg.bn_group_size,
                          use_running_average=not train, dtype=dtype,
                          name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(cfg, cfg.width * 2 ** i, strides,
                               name=f"stage{i}_block{j}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(cfg.num_classes, dtype=dtype, name="fc")(x)
        return logits.astype(jnp.float32)


def SpatialBottleneck(cfg: ResNetConfig, features: int,
                      spatial_axis_name: str = "cp", **kw) -> Bottleneck:
    """Reference-name alias: ``SpatialBottleneck`` IS `Bottleneck` with
    ``spatial_axis_name`` set (one implementation, no divergence)."""
    return Bottleneck(cfg, features, strides=1,
                      spatial_axis_name=spatial_axis_name, **kw)


def param_specs(params, *, default=None):
    """PartitionSpec tree for ResNet — all-replicated: conv nets scale by
    data parallelism (+ SyncBN stats psum) and by spatial parallelism
    (`SpatialBottleneck` H-sharding with halo exchange), not by weight
    sharding. Provided so every model in the zoo exposes the same API."""
    import jax
    from jax.sharding import PartitionSpec as P
    return jax.tree_util.tree_map(lambda _: default or P(), params)
