"""BERT — BASELINE config 2 model ("BERT-base pretrain with FusedAdam +
FusedLayerNorm → Pallas").

Reference analogue: ``apex/transformer/testing/standalone_bert.py`` (the
reference's test BERT) and the MLPerf BERT lineage of the fmha/multihead
kernels. Built from this framework's fused ops: `apex1_tpu.ops.layer_norm`
(Pallas), `apex1_tpu.ops.attention.flash_attention` (non-causal, padding
via segment ids), fused xentropy for the MLM loss.

Post-LN encoder (original BERT): x = LN(x + Sublayer(x)). Padding is
expressed through ``attention_mask`` (1 = real token): real tokens form
segment 1, pads segment 0, so pads never mix into real positions — the
flash kernel's segment machinery replaces the reference's additive-mask
softmax kernels (``scaled_masked_softmax_cuda``).
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.ops import layer_norm, softmax_cross_entropy_loss
from apex1_tpu.ops.stochastic import (fold_seed,
                                      fused_dropout_add_layer_norm,
                                      seed_from_key)
from apex1_tpu.ops.attention import flash_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    intermediate_size: int = 3072
    dropout: float = 0.0
    policy: PrecisionPolicy = dataclasses.field(
        default_factory=lambda: get_policy("O0"))

    @staticmethod
    def bert_base(**kw) -> "BertConfig":
        return BertConfig(**kw)

    @staticmethod
    def bert_large(**kw) -> "BertConfig":
        defaults = dict(num_layers=24, num_heads=16, hidden_size=1024,
                        intermediate_size=4096)
        defaults.update(kw)
        return BertConfig(**defaults)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        defaults = dict(vocab_size=256, max_seq_len=128, num_layers=2,
                        num_heads=4, hidden_size=64, intermediate_size=128)
        defaults.update(kw)
        return BertConfig(**defaults)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, seg_mask, deterministic: bool = True):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        E, H = cfg.hidden_size, cfg.num_heads
        D = E // H
        B, S = x.shape[0], x.shape[1]

        def norm_params(name):
            g = self.param(f"{name}_scale", nn.initializers.ones, (E,),
                           jnp.float32)
            b = self.param(f"{name}_bias", nn.initializers.zeros, (E,),
                           jnp.float32)
            if not cfg.policy.keep_norms_fp32:
                g, b = g.astype(dtype), b.astype(dtype)
            return g, b

        # one rng draw per layer (make_rng folds the module path, so
        # every layer draws a distinct key); per-site streams split off
        # the int32 seed with fold_seed — the APX103-sanctioned idiom
        active = cfg.dropout > 0.0 and not deterministic
        seed = seed_from_key(self.make_rng("dropout")) if active else None

        qkv = nn.Dense(3 * E, dtype=dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

        # attention-probability dropout rides the flash kernel (the
        # reference fmha fusion point) — no O(S²) tensor materializes
        attn = flash_attention(heads(q), heads(k), heads(v),
                               segment_ids=seg_mask,
                               sm_scale=1.0 / math.sqrt(D),
                               dropout_p=cfg.dropout if active else 0.0,
                               dropout_seed=(fold_seed(seed, 0)
                                             if active else None))
        attn = attn.transpose(0, 2, 1, 3).reshape(B, S, E)
        attn = nn.Dense(E, dtype=dtype, name="attn_out")(attn)
        g, b = norm_params("attn_ln")
        if active:
            # fused dropout(attn)+residual, then the Pallas LN — the
            # Megatron bias_dropout_add epilogue; masks recomputed from
            # seeds in backward (no stored mask tensors)
            x = fused_dropout_add_layer_norm(
                attn, x, g, b, p=cfg.dropout,
                seed=fold_seed(seed, 1)).astype(dtype)
        else:
            x = layer_norm(x + attn, g, b).astype(dtype)

        h = nn.Dense(cfg.intermediate_size, dtype=dtype, name="ffn_in")(x)
        h = nn.gelu(h)
        h = nn.Dense(E, dtype=dtype, name="ffn_out")(h)
        g, b = norm_params("ffn_ln")
        if active:
            return fused_dropout_add_layer_norm(
                h, x, g, b, p=cfg.dropout,
                seed=fold_seed(seed, 2)).astype(dtype)
        return layer_norm(x + h, g, b).astype(dtype)


class Bert(nn.Module):
    """Returns (sequence_output (B,S,E), pooled_output (B,E))."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, token_types=None, attention_mask=None,
                 deterministic: bool = True):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        B, S = tokens.shape
        if token_types is None:
            token_types = jnp.zeros_like(tokens)
        if attention_mask is None:
            attention_mask = jnp.ones_like(tokens)
        wte = self.param("word_embeddings", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        wpe = self.param("position_embeddings",
                         nn.initializers.normal(0.02),
                         (cfg.max_seq_len, cfg.hidden_size), jnp.float32)
        tte = self.param("token_type_embeddings",
                         nn.initializers.normal(0.02),
                         (cfg.type_vocab_size, cfg.hidden_size),
                         jnp.float32)
        x = (wte[tokens] + wpe[:S][None] + tte[token_types]).astype(dtype)
        g = self.param("emb_ln_scale", nn.initializers.ones,
                       (cfg.hidden_size,), jnp.float32)
        b = self.param("emb_ln_bias", nn.initializers.zeros,
                       (cfg.hidden_size,), jnp.float32)
        x = layer_norm(x, g, b).astype(dtype)
        seg = attention_mask.astype(jnp.int32)
        for i in range(cfg.num_layers):
            x = BertLayer(cfg, name=f"layer{i}")(x, seg, deterministic)
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, dtype=dtype,
                                  name="pooler")(x[:, 0]))
        return x, pooled


class BertPretrain(nn.Module):
    """MLM (weight-tied decoder) + NSP heads — the pretrain objective of
    BASELINE config 2."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, token_types=None, attention_mask=None,
                 return_mlm_hidden=False, deterministic: bool = True):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        bert = Bert(cfg, name="bert")
        seq, pooled = bert(tokens, token_types, attention_mask,
                           deterministic)
        h = nn.Dense(cfg.hidden_size, dtype=dtype, name="mlm_transform")(seq)
        h = nn.gelu(h)
        g = self.param("mlm_ln_scale", nn.initializers.ones,
                       (cfg.hidden_size,), jnp.float32)
        b = self.param("mlm_ln_bias", nn.initializers.zeros,
                       (cfg.hidden_size,), jnp.float32)
        h = layer_norm(h, g, b)
        mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                              (cfg.vocab_size,), jnp.float32)
        nsp_logits = nn.Dense(2, dtype=dtype, name="nsp")(pooled)
        if return_mlm_hidden:
            # fused LM-head+CE path: caller feeds (h, wte, mlm_bias) to
            # ops.linear_cross_entropy — the (B, S, V) logits never
            # materialize
            return h.astype(dtype), nsp_logits.astype(jnp.float32)
        wte = self.variables["params"]["bert"]["word_embeddings"]
        mlm_logits = jnp.matmul(
            h.astype(dtype), wte.T.astype(dtype),
            preferred_element_type=jnp.float32) + mlm_bias
        return mlm_logits, nsp_logits.astype(jnp.float32)


# Megatron-style TP rules (see parallel/specs.py): qkv/ffn_in column-
# parallel, attn_out/ffn_out row-parallel, word embeddings (and the tied
# MLM head + its bias) vocab-sharded; pooler/nsp heads replicated.
_TP_RULES = (
    (r"word_embeddings$", P("tp", None)),
    (r"(position|token_type)_embeddings$", P()),
    (r"(qkv|ffn_in)/kernel$", P(None, "tp")),
    (r"(qkv|ffn_in)/bias$", P("tp")),
    (r"(attn_out|ffn_out)/kernel$", P("tp", None)),
    (r"(attn_out|ffn_out)/bias$", P()),
    (r"mlm_bias$", P("tp")),
)


def param_specs(params, *, rules=_TP_RULES, default=P()):
    """PartitionSpec tree for a Bert/BertPretrain param tree (TP over the
    ``tp`` mesh axis) — ≙ ``set_tensor_model_parallel_attributes``."""
    from apex1_tpu.parallel.specs import specs_from_rules
    return specs_from_rules(params, rules, default=default)


def bert_pretrain_loss_fn(model: BertPretrain, *, ignore_index: int = -1,
                          fuse_head: bool = True):
    """MLM CE (``padding_idx``-masked, fp32 in-kernel) + NSP CE.

    ``fuse_head=True`` (default) runs the tied MLM head through
    ``ops.linear_cross_entropy``: the decoder bias is folded into the
    kernel by appending a ones-column to the hidden states and the bias
    as one extra weight column, so the (B, S, V) logits never hit HBM.
    ``False`` keeps the materialized-logits path (the parity gold).

    ``batch``: dict with tokens, mlm_labels (ignore_index where unmasked),
    nsp_labels, optional token_types/attention_mask, optional
    ``dropout_rng`` (a jax.random key) — its presence ACTIVATES the
    model's dropout (cfg.dropout > 0): attention-probability dropout in
    the flash kernels + the fused dropout-add-LN residual epilogues."""
    from apex1_tpu.ops import linear_cross_entropy

    def loss_fn(params, batch):
        labels = batch["mlm_labels"]
        n_masked = jnp.maximum(jnp.sum(labels != ignore_index), 1)
        det = "dropout_rng" not in batch
        rngs = None if det else {"dropout": batch["dropout_rng"]}
        if fuse_head:
            h, nsp_logits = model.apply(
                {"params": params}, batch["tokens"],
                batch.get("token_types"), batch.get("attention_mask"),
                return_mlm_hidden=True, deterministic=det, rngs=rngs)
            wte = params["bert"]["word_embeddings"].astype(h.dtype)
            w = jnp.concatenate(
                [wte, params["mlm_bias"].astype(h.dtype)[:, None]], axis=1)
            ones = jnp.ones(h.shape[:-1] + (1,), h.dtype)
            mlm_losses = linear_cross_entropy(
                jnp.concatenate([h, ones], axis=-1), w, labels,
                padding_idx=ignore_index)
            mlm = jnp.sum(mlm_losses) / n_masked
        else:
            mlm_logits, nsp_logits = model.apply(
                {"params": params}, batch["tokens"],
                batch.get("token_types"), batch.get("attention_mask"),
                deterministic=det, rngs=rngs)
            mlm_losses = softmax_cross_entropy_loss(
                mlm_logits.astype(jnp.float32),
                jnp.maximum(labels, 0)) * (labels != ignore_index)
            mlm = jnp.sum(mlm_losses) / n_masked
        nsp = jnp.mean(softmax_cross_entropy_loss(
            nsp_logits, batch["nsp_labels"]))
        return mlm + nsp

    return loss_fn
