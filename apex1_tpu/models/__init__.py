"""Model zoo for the BASELINE configs (the reference has no model zoo —
its test transformers live in ``apex/transformer/testing/standalone_*``;
these are the standalone equivalents built from this framework's ops).

Every model ships a ``param_specs`` (TP PartitionSpec rules for GSPMD) and
a ``tiny()`` config for tests.
"""

from apex1_tpu.models.bert import (  # noqa: F401
    Bert, BertConfig, BertPretrain, bert_pretrain_loss_fn)
from apex1_tpu.models.gpt2 import (  # noqa: F401
    GPT2, GPT2Config, gpt2_loss_fn)
from apex1_tpu.models.llama import (  # noqa: F401
    Llama, LlamaConfig, llama_loss_fn)
from apex1_tpu.models.resnet import (  # noqa: F401
    ResNet, ResNetConfig)
from apex1_tpu.models.t5 import (  # noqa: F401
    T5, T5Config, t5_loss_fn)
from apex1_tpu.models.generate import (  # noqa: F401
    beam_search, generate, gpt2_decoder, llama_decoder,
    speculative_generate, t5_generate)
from apex1_tpu.models.quant_decode import (  # noqa: F401
    gpt2_quant_decoder, llama_quant_decoder)
