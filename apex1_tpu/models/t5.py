"""T5 encoder-decoder — the model family behind the reference's
variable-shape pipeline machinery (SURVEY #55/#56: ``decoder_seq_length``,
``_communicate`` tensor-shape negotiation exist precisely so Megatron-style
enc-dec models can pipeline stages whose boundary tensors differ between
the encoder and decoder halves).

The reference has no model zoo; like `models.llama` this is a standalone
model built from the framework's fused ops:

- `ops.rms_norm` (Pallas) — T5's LayerNorm is RMSNorm (no mean/bias);
- `ops.flash_attention` (Pallas) with its additive-``bias`` operand for
  the bias-bearing self-attention (T5's learned relative-position bias
  rides the flash kernel — O(S·D) activations, dbias via the kernel's
  broadcast-accumulating backward pass — where the reference composes
  matmul + ``scaled_masked_softmax_cuda``, materializing O(S²); its
  fmha takes no bias at all) and for the bias-free cross-attention;
- `ops.linear_cross_entropy` for the (tied) LM head + CE.

T5-specific semantics kept faithful to the public architecture: pre-norm
blocks, NO attention scaling (folded into init), shared relative-position
bias per stack (bidirectional buckets in the encoder, unidirectional in
the decoder), tied embedding/LM-head with the d_model**-0.5 logit scale,
ReLU FFN (or gated-GELU, t5.1.1 style).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.ops import (NEG_INF, linear_cross_entropy, rms_norm,
                           softmax_cross_entropy_loss)
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.transformer.tensor_parallel.random import checkpoint_policy


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    num_heads: int = 8
    head_dim: int = 64
    d_ff: int = 2048
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    rel_pos_buckets: int = 32
    rel_pos_max_dist: int = 128
    norm_eps: float = 1e-6
    gated_act: bool = False      # True = gated-GELU (t5.1.1)
    tie_word_embeddings: bool = True
    remat: bool = False
    # jax.checkpoint_policies name; see models.llama.LlamaConfig
    remat_policy: str = "nothing_saveable"

    def __post_init__(self):
        checkpoint_policy(self.remat_policy)  # fail fast on a typo
        # the log-spaced bucket formula divides by
        # log(max_distance / max_exact) with max_exact = buckets//2
        # (//4 effective in the bidirectional encoder, which halves
        # num_buckets first) — max_dist <= max_exact makes the
        # denominator zero/negative and silently wraps garbage bucket
        # indices into the bias table (ADVICE r3); fail fast instead,
        # mirroring the remat_policy check above
        if self.rel_pos_max_dist <= self.rel_pos_buckets // 2:
            raise ValueError(
                f"rel_pos_max_dist ({self.rel_pos_max_dist}) must exceed "
                f"rel_pos_buckets // 2 ({self.rel_pos_buckets // 2}) — "
                f"the log-spaced tail of relative_position_bucket needs "
                f"max_distance > max_exact")
    policy: PrecisionPolicy = dataclasses.field(
        default_factory=lambda: get_policy("O0"))

    @staticmethod
    def t5_small(**kw) -> "T5Config":
        return T5Config(**kw)

    @staticmethod
    def t5_large(**kw) -> "T5Config":
        defaults = dict(d_model=1024, num_heads=16, head_dim=64,
                        d_ff=4096, num_encoder_layers=24,
                        num_decoder_layers=24)
        defaults.update(kw)
        return T5Config(**defaults)

    @staticmethod
    def tiny(**kw) -> "T5Config":
        defaults = dict(vocab_size=256, d_model=64, num_heads=4,
                        head_dim=16, d_ff=128, num_encoder_layers=2,
                        num_decoder_layers=2, rel_pos_buckets=8,
                        rel_pos_max_dist=16)
        defaults.update(kw)
        return T5Config(**defaults)


def relative_position_bucket(rel, *, bidirectional: bool,
                             num_buckets: int = 32,
                             max_distance: int = 128):
    """T5's log-spaced relative-position bucketing (public architecture).

    ``rel`` = memory_position − query_position, any integer array.
    Bidirectional stacks split buckets between past/future; unidirectional
    (decoder) buckets only the past and clamps the future to bucket 0.
    Buckets are exact up to num_buckets//2 and log-spaced beyond, saturating
    at ``max_distance``.
    """
    rel = jnp.asarray(rel, jnp.int32)
    ret = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        ret = ret + (rel > 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(rel)
    else:
        n = jnp.maximum(-rel, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    # avoid log(0): the large branch is only selected when n >= max_exact
    nf = jnp.maximum(n, 1).astype(jnp.float32)
    val_large = max_exact + (
        jnp.log(nf / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


class RelPosBias(nn.Module):
    """Learned per-head relative-position bias, shared by every layer of a
    stack (computed once from the stack's single bias table, as in public
    T5 where only the first block owns the table)."""

    cfg: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, q_len: int, k_len: int, q_positions=None):
        """``q_positions``: optional traced (q_len,) global query
        positions — the KV-cached decode path asks for one bias row at
        the current cache index."""
        cfg = self.cfg
        table = self.param("rel_bias",
                           nn.initializers.normal(0.02),
                           (cfg.rel_pos_buckets, cfg.num_heads),
                           jnp.float32)
        if q_positions is None:
            q_positions = jnp.arange(q_len)
        qpos = q_positions[:, None]
        kpos = jnp.arange(k_len)[None, :]
        bucket = relative_position_bucket(
            kpos - qpos, bidirectional=self.bidirectional,
            num_buckets=cfg.rel_pos_buckets,
            max_distance=cfg.rel_pos_max_dist)
        bias = table[bucket]                      # (Sq, Sk, H)
        return bias.transpose(2, 0, 1)[None]      # (1, H, Sq, Sk)


def _causal_mask(sq: int, sk: int):
    q = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return jnp.where(k > q, NEG_INF, 0.0)[None, None]    # (1, 1, Sq, Sk)


class T5Attention(nn.Module):
    """Self- or cross-attention, T5 form (no 1/sqrt(d) scale, no biases
    on the projections). Always the flash kernel: ``bias`` (rel-pos +
    folded causal, broadcast (1, H, Sq, Sk)) rides its additive-bias
    operand and ``kv_keep`` (a (B, Sk) bool key-padding mask) rides its
    ``segment_ids`` — never a materialized O(B·H·S²) mask."""

    cfg: T5Config

    @nn.compact
    def __call__(self, x, kv, bias=None, kv_keep=None, causal=False,
                 cache=None, cache_index=None):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        H, D = cfg.num_heads, cfg.head_dim
        if kv is None:           # self-attention
            kv = x
        B, Sq = x.shape[0], x.shape[1]
        Sk = kv.shape[1]
        if cache is not None and kv_keep is not None:
            raise NotImplementedError(
                "cached_attention has no key-padding channel — a silent "
                "drop would attend padded keys; mask upstream or extend "
                "the cache path")
        init = nn.initializers.normal(cfg.d_model ** -0.5)
        wq = self.param("wq", init, (cfg.d_model, H * D),
                        jnp.float32).astype(dtype)
        wk = self.param("wk", init, (cfg.d_model, H * D),
                        jnp.float32).astype(dtype)
        wv = self.param("wv", init, (cfg.d_model, H * D),
                        jnp.float32).astype(dtype)
        wo = self.param("wo", init, (H * D, cfg.d_model),
                        jnp.float32).astype(dtype)
        q = (x @ wq).reshape(B, Sq, H, D).transpose(0, 2, 1, 3)
        k = (kv @ wk).reshape(B, Sk, H, D).transpose(0, 2, 1, 3)
        v = (kv @ wv).reshape(B, Sk, H, D).transpose(0, 2, 1, 3)
        segs = None
        if kv_keep is not None:
            # key padding as segment ids: every query in segment 0,
            # padded keys in segment 1 — equality masking excludes them
            segs = (jnp.zeros((B, Sq), jnp.int32),
                    jnp.where(kv_keep, 0, 1).astype(jnp.int32))
        new_cache = None
        if cache is not None:
            from apex1_tpu.models.generate import cached_attention
            attn, new_cache = cached_attention(
                q, k, v, cache, cache_index, sm_scale=1.0, bias=bias)
        else:
            # bias (pure rel-pos) rides the flash kernel's additive-bias
            # operand — O(S·D) activations even for the bias-bearing
            # stacks (the kernel's dbias pass handles the rel-pos table
            # gradient) — and causality rides the kernel's causal flag,
            # keeping its above-diagonal block skip (~2x less MXU work
            # than folding the mask into the bias); on non-TPU backends
            # the same call dispatches to the biased XLA composite
            attn = flash_attention(q, k, v, causal=causal, sm_scale=1.0,
                                   bias=bias, segment_ids=segs)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, Sq, H * D)
        out = attn @ wo
        return out if new_cache is None else (out, new_cache)


class T5FFN(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, h):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        init = nn.initializers.normal(cfg.d_model ** -0.5)
        wo = self.param("wo", init, (cfg.d_ff, cfg.d_model),
                        jnp.float32).astype(dtype)
        if cfg.gated_act:
            wg = self.param("wi_0", init, (cfg.d_model, cfg.d_ff),
                            jnp.float32).astype(dtype)
            wu = self.param("wi_1", init, (cfg.d_model, cfg.d_ff),
                            jnp.float32).astype(dtype)
            y = jax.nn.gelu(h @ wg) * (h @ wu)
        else:
            wi = self.param("wi", init, (cfg.d_model, cfg.d_ff),
                            jnp.float32).astype(dtype)
            y = jax.nn.relu(h @ wi)
        return y @ wo


class T5Block(nn.Module):
    cfg: T5Config
    is_decoder: bool

    @nn.compact
    def __call__(self, x, bias, memory=None, kv_keep=None, cache=None,
                 cache_index=None):
        """``kv_keep`` (B, S_enc) bool: encoder key-padding — masks the
        encoder self-attention's keys and the decoder cross-attention's
        memory keys."""
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype

        def norm(name, z):
            g = self.param(name, nn.initializers.ones, (cfg.d_model,),
                           jnp.float32)
            if not cfg.policy.keep_norms_fp32:
                g = g.astype(dtype)
            return rms_norm(z, g, eps=cfg.norm_eps).astype(dtype)

        h = T5Attention(cfg, name="self_attn")(
            norm("self_norm", x), None, bias=bias,
            kv_keep=None if self.is_decoder else kv_keep,
            causal=self.is_decoder,
            cache=cache, cache_index=cache_index)
        new_cache = None
        if cache is not None:
            h, new_cache = h
        x = x + h.astype(x.dtype)
        if self.is_decoder:
            h = T5Attention(cfg, name="cross_attn")(
                norm("cross_norm", x),
                memory.astype(dtype), kv_keep=kv_keep)
            x = x + h.astype(x.dtype)
        h = T5FFN(cfg, name="ffn")(norm("ffn_norm", x))
        out = x + h.astype(x.dtype)
        return out if new_cache is None else (out, new_cache)


class T5Stack(nn.Module):
    cfg: T5Config
    is_decoder: bool

    @nn.compact
    def __call__(self, x, memory=None, enc_pad_mask=None, cache=None,
                 cache_index=None):
        cfg = self.cfg
        S = x.shape[1]
        rel_pos = RelPosBias(cfg, bidirectional=not self.is_decoder,
                             name="rel_pos")
        if cache is not None and S == 1:
            # decode: one bias row at the current position vs all cache
            # slots (cached_attention masks slots > cache_index)
            S_max = cache["layer0"]["k"].shape[2]
            bias = rel_pos(1, S_max,
                           q_positions=jnp.asarray([cache_index],
                                                   jnp.int32))
        else:
            # pure rel-pos bias: decoder causality rides the attention
            # kernel's causal flag (block-skip), not a folded mask
            bias = rel_pos(S, S)
        # enc_pad_mask stays a (B, S_enc) KEY mask end to end (the flash
        # kernel's segment_ids channel) — folding it into the additive
        # bias would batch-expand it to O(B·H·S²)
        n_layers = (cfg.num_decoder_layers if self.is_decoder
                    else cfg.num_encoder_layers)
        block = T5Block
        if cfg.remat and cache is None:
            block = nn.remat(T5Block, static_argnums=(),
                             policy=checkpoint_policy(cfg.remat_policy))
        new_cache = {}
        for i in range(n_layers):
            out = block(cfg, self.is_decoder, name=f"layer{i}")(
                x, bias, memory, enc_pad_mask,
                cache=None if cache is None else cache[f"layer{i}"],
                cache_index=cache_index)
            if cache is None:
                x = out
            else:
                x, new_cache[f"layer{i}"] = out
        g = self.param("final_norm", nn.initializers.ones,
                       (cfg.d_model,), jnp.float32)
        if not cfg.policy.keep_norms_fp32:
            g = g.astype(cfg.policy.compute_dtype)
        out = rms_norm(x, g, eps=cfg.norm_eps)
        return out if cache is None else (out, new_cache)


class T5(nn.Module):
    """Returns decoder logits (B, S_dec, vocab) with fp32 accumulation, or
    the pre-head hidden states with ``return_hidden=True`` (for the fused
    LM-head CE path)."""

    cfg: T5Config

    def setup(self):
        cfg = self.cfg
        self.shared = self.param("shared_embedding",
                                 nn.initializers.normal(1.0),
                                 (cfg.vocab_size, cfg.d_model),
                                 jnp.float32)
        self.encoder = T5Stack(cfg, is_decoder=False, name="encoder")
        self.decoder = T5Stack(cfg, is_decoder=True, name="decoder")
        if not cfg.tie_word_embeddings:
            self.lm_head = self.param("lm_head",
                                      nn.initializers.normal(0.02),
                                      (cfg.vocab_size, cfg.d_model),
                                      jnp.float32)

    def encode(self, enc_tokens, enc_pad_mask=None):
        dtype = self.cfg.policy.compute_dtype
        x = self.shared[enc_tokens].astype(dtype)
        return self.encoder(x, enc_pad_mask=enc_pad_mask)

    def decode(self, dec_tokens, memory, enc_pad_mask=None,
               return_hidden=False, cache=None, cache_index=None):
        """``cache``/``cache_index`` enable KV-cached decoding of the
        self-attention (see `models.generate.t5_generate`; cross-attention
        recomputes its K/V from the fixed memory each step). The return
        becomes ``(logits, new_cache)``."""
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        y = self.shared[dec_tokens].astype(dtype)
        h = self.decoder(y, memory=memory, enc_pad_mask=enc_pad_mask,
                         cache=cache, cache_index=cache_index)
        new_cache = None
        if cache is not None:
            h, new_cache = h
        h = h.astype(dtype)
        if return_hidden:
            return h if cache is None else (h, new_cache)
        logits = jnp.einsum("bsh,vh->bsv", h, self.head_weight(),
                            preferred_element_type=jnp.float32)
        return logits if cache is None else (logits, new_cache)

    def head_weight(self):
        """(vocab, d_model) LM-head weight in compute dtype — tied form
        carries T5's d_model**-0.5 logit scale."""
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        if cfg.tie_word_embeddings:
            return (self.shared * cfg.d_model ** -0.5).astype(dtype)
        return self.lm_head.astype(dtype)

    def __call__(self, enc_tokens, dec_tokens, enc_pad_mask=None,
                 return_hidden=False):
        memory = self.encode(enc_tokens, enc_pad_mask)
        return self.decode(dec_tokens, memory, enc_pad_mask,
                           return_hidden=return_hidden)


# TP rules (pattern: models.llama._TP_RULES — regex over flattened paths)
_TP_RULES = (
    (r"shared_embedding$", P("tp", None)),
    (r"lm_head$", P("tp", None)),
    (r"w[qkv]$", P(None, "tp")),
    (r"wo$", P("tp", None)),
    (r"wi(_[01])?$", P(None, "tp")),
    (r"rel_bias$", P()),
    (r".*norm$", P()),
)


def param_specs(params, *, rules=_TP_RULES, default=P()):
    from apex1_tpu.parallel.specs import specs_from_rules
    return specs_from_rules(params, rules, default=default)


def t5_loss_fn(model: T5, *, fuse_head: bool = True,
               label_pad_id: Optional[int] = None):
    """``loss_fn(params, enc_tokens, dec_tokens) -> scalar``: seq2seq CE,
    teacher-forced — position t predicts ``dec_tokens[t+1]``. Default path
    fuses the LM-head matmul into the CE kernel
    (``ops.linear_cross_entropy``); ``fuse_head=False`` materializes the
    logits (the parity gold). ``label_pad_id`` positions are excluded from
    the mean (≙ ``xentropy``'s padding_idx)."""

    def loss_fn(params, enc_tokens, dec_tokens, enc_pad_mask=None):
        bound = model.bind({"params": params})
        labels = dec_tokens[:, 1:]
        # pad-row zeroing happens inside the CE kernels (padding_idx —
        # zero loss AND grad in-lane); only the mean's denominator is
        # computed here
        if fuse_head:
            h = bound(enc_tokens, dec_tokens[:, :-1],
                      enc_pad_mask=enc_pad_mask, return_hidden=True)
            w = bound.head_weight()
            losses = linear_cross_entropy(h, w, labels,
                                          padding_idx=label_pad_id)
        else:
            logits = bound(enc_tokens, dec_tokens[:, :-1],
                           enc_pad_mask=enc_pad_mask)
            losses = softmax_cross_entropy_loss(
                logits.astype(jnp.float32), labels,
                padding_idx=label_pad_id)
        if label_pad_id is None:
            return jnp.mean(losses)
        keep = jnp.sum((labels != label_pad_id).astype(jnp.float32))
        return jnp.sum(losses) / jnp.maximum(keep, 1.0)

    return loss_fn
