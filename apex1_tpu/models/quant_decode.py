"""Weight-only int8 quantized decode for `models.llama.Llama`.

Beyond-reference serving capability: autoregressive decode streams every
weight from HBM once per emitted token, so at batch sizes that don't
saturate the MXU the step time is weight-bytes / HBM-bandwidth — int8
storage halves it vs bf16. Weights are quantized ONCE
(:func:`quantize_llama_params`, per-out-channel symmetric int8 via
`ops.quantize_int8`) and every decode matmul runs through
`ops.int8_matmul`, whose Pallas kernel dequantizes inside VMEM tiles (the
bf16 weight matrix never exists in HBM).

This is a dedicated inference forward, not the flax module: it mirrors the
cached path of `models.llama.Llama.__call__` (same rms_norm / RoPE /
`generate.cached_attention` calls — the norm/rope/attention ops are shared
code, only the weight matmuls differ) and plugs into `generate` /
`beam_search` through the same ``apply_fn(params, tokens, cache,
cache_index)`` contract as `generate.llama_decoder`. Parity is pinned by
``tests/test_quantized.py``: with weights constructed exactly
representable in int8 the quantized decode must match the full-precision
model to bf16 rounding, and with real weights to quantization tolerance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex1_tpu.models.generate import cached_attention, init_cache
from apex1_tpu.ops import (apply_rotary_pos_emb, int8_matmul, quantize_int8,
                           rms_norm, rope_tables)
from apex1_tpu.models.llama import is_moe_layer
from apex1_tpu.transformer.moe import MoEConfig, router


def quantize_llama_params(params, cfg):
    """Quantize a Llama param tree for decode. Embedding stays a bf16
    gather table; norms stay fp32; every matmul weight becomes
    ``{"q": int8 (out, in), "s": fp32 (out,)}`` (weights stored (in, out)
    in the flax tree are transposed into the kernel's (N, K) layout
    once, here).

    MoE layers (``cfg.moe_every > 0``): the stacked expert FFNs
    ``w1 (E, H, F)`` / ``w2 (E, F, H)`` quantize PER EXPERT per out
    channel — ``{"q": (E, out, in) int8, "s": (E, out) fp32}`` — since
    expert weights are the bulk of an MoE checkpoint's bytes, exactly
    the HBM-bound traffic int8 decode exists to halve. The router gate
    stays fp32 (tiny, and routing decisions feed top-k: quantizing it
    would flip near-tied expert choices for ~zero byte savings)."""
    dt = cfg.policy.compute_dtype

    def qt(w):  # (in, out) -> kernel layout (out, in)
        q, s = quantize_int8(jnp.asarray(w).T)
        return {"q": q, "s": s}

    def qt_experts(w):  # (E, in, out) -> (E, out, in) + (E, out)
        qs = [quantize_int8(jnp.asarray(w[e]).T)
              for e in range(w.shape[0])]
        return {"q": jnp.stack([q for q, _ in qs]),
                "s": jnp.stack([s for _, s in qs])}

    out = {"tok_embeddings": params["tok_embeddings"].astype(dt),
           "norm": params["norm"]}
    for i in range(cfg.num_layers):
        lp = params[f"layer{i}"]
        qlp = {
            "attn_norm": lp["attn_norm"],
            "mlp_norm": lp["mlp_norm"],
            "wq": qt(lp["wq"]), "wk": qt(lp["wk"]), "wv": qt(lp["wv"]),
            "wo": qt(lp["wo"]),
        }
        if is_moe_layer(cfg, i):
            qlp["moe"] = {
                "router": jnp.asarray(lp["moe"]["router"], jnp.float32),
                "w1": qt_experts(lp["moe"]["w1"]),
                "w2": qt_experts(lp["moe"]["w2"]),
            }
        else:
            qlp.update(w_gate=qt(lp["w_gate"]), w_up=qt(lp["w_up"]),
                       w_down=qt(lp["w_down"]))
        out[f"layer{i}"] = qlp
    # head is stored (vocab, hidden) = (N, K) already
    q, s = quantize_int8(jnp.asarray(params["output"]))
    out["output"] = {"q": q, "s": s}
    return out


def llama_quant_decoder(model, params):
    """(apply_fn, make_cache, qparams) for int8 decode of a `Llama`.

    ``apply_fn(qparams, tokens, cache, cache_index)`` has the
    `generate.llama_decoder` contract — pass it (with ``qparams`` as the
    params) to :func:`generate.generate` / :func:`generate.beam_search`.
    """
    cfg = model.cfg
    dt = cfg.policy.compute_dtype
    H, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    qparams = quantize_llama_params(params, cfg)

    def mm(x, qw):
        return int8_matmul(x, qw["q"], qw["s"]).astype(dt)

    def norm_g(g):
        return g if cfg.policy.keep_norms_fp32 else g.astype(dt)

    moecfg = (None if cfg.moe_every <= 0 else MoEConfig(
        num_experts=cfg.num_experts, top_k=cfg.moe_top_k,
        capacity_factor=cfg.moe_capacity_factor,
        aux_loss_weight=cfg.moe_aux_loss_weight,
        hidden_size=cfg.hidden_size, ffn_size=cfg.ffn_size))

    def moe_ffn(h, qm, segment_ids):
        """Dense-dispatch MoE FFN (the `transformer.moe.MoEMLP` decode
        math — same router, same capacity/drop semantics) with the
        expert matmuls through `ops.int8_matmul` per expert. Aux loss is
        computed-and-dropped: decode has no optimizer to feed it."""
        lead, H = h.shape[:-1], h.shape[-1]
        x2 = h.reshape(-1, H)
        mask = (None if segment_ids is None
                else (segment_ids >= 0).reshape(-1))
        dispatch, combine, _aux = router(x2, qm["router"], moecfg, mask)
        xe = jnp.einsum("tec,th->ech", dispatch.astype(dt),
                        x2.astype(dt))                    # (E, C, H)
        q1, s1 = qm["w1"]["q"], qm["w1"]["s"]             # (E, F, H)
        q2, s2 = qm["w2"]["q"], qm["w2"]["s"]             # (E, H, F)
        # vmap over the stacked expert axis (the layout qt_experts
        # already produces) — one batched Pallas GEMM per projection
        # instead of 2E unrolled dispatches (review r5: the unroll
        # bloated the HLO and serialized independent expert matmuls;
        # MoEMLP's bf16 form is one stacked einsum for the same reason)
        ye = jax.vmap(lambda xe_e, q1_e, s1_e, q2_e, s2_e: int8_matmul(
            jax.nn.silu(int8_matmul(xe_e, q1_e, s1_e).astype(dt)),
            q2_e, s2_e))(xe, q1, s1, q2, s2)              # (E, C, H)
        y = jnp.einsum("tec,ech->th", combine.astype(dt),
                       ye.astype(dt))
        return y.reshape(*lead, H)

    def apply_fn(qp, tokens, cache, cache_index, *, positions=None,
                 segment_ids=None, valid_start=None, chunk_decode=False):
        # the keyword-only args carry the RAGGED (left-padded) masking,
        # exactly as in `generate.llama_decoder` — so the int8 path
        # composes with generate(prompt_lens=...)
        B, S = tokens.shape
        idx = jnp.asarray(cache_index, jnp.int32)
        x = qp["tok_embeddings"][tokens].astype(dt)
        if positions is None:
            pos = idx + jnp.arange(S)
            cos, sin = rope_tables(pos, D, base=cfg.rope_base)
        else:  # (B, S) per-row positions -> per-row tables
            cos, sin = rope_tables(
                jnp.asarray(positions).reshape(-1), D, base=cfg.rope_base)
            cos = cos.reshape(B, S, -1)
            sin = sin.reshape(B, S, -1)
        new_cache = {}
        for i in range(cfg.num_layers):
            lp = qp[f"layer{i}"]
            h = rms_norm(x, norm_g(lp["attn_norm"]),
                         eps=cfg.norm_eps).astype(dt)
            q = mm(h, lp["wq"]).reshape(B, S, H, D)
            k = mm(h, lp["wk"]).reshape(B, S, Hkv, D)
            v = mm(h, lp["wv"]).reshape(B, S, Hkv, D)
            q = apply_rotary_pos_emb(q, cos, sin)
            k = apply_rotary_pos_emb(k, cos, sin)
            q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
            attn, new_cache[f"layer{i}"] = cached_attention(
                q, k, v, cache[f"layer{i}"], cache_index,
                segment_ids=segment_ids, valid_start=valid_start,
                chunk_decode=chunk_decode)
            attn = attn.transpose(0, 2, 1, 3).reshape(B, S, H * D)
            x = x + mm(attn, lp["wo"]).astype(x.dtype)
            h = rms_norm(x, norm_g(lp["mlp_norm"]),
                         eps=cfg.norm_eps).astype(dt)
            if is_moe_layer(cfg, i):
                y = moe_ffn(h, lp["moe"], segment_ids)
            else:
                y = mm(jax.nn.silu(mm(h, lp["w_gate"]))
                       * mm(h, lp["w_up"]), lp["w_down"])
            x = x + y.astype(x.dtype)
        x = rms_norm(x, norm_g(qp["norm"]), eps=cfg.norm_eps).astype(dt)
        logits = int8_matmul(x, qp["output"]["q"], qp["output"]["s"])
        return logits, new_cache

    def make_cache(batch: int, max_len: int, dtype=None):
        return init_cache(cfg.num_layers, batch, Hkv, max_len, D,
                          dtype or dt)

    return apply_fn, make_cache, qparams


def quantize_gpt2_params(params, cfg):
    """Quantize a GPT-2 param tree for decode. The tied ``wte`` is kept
    TWICE: as the bf16 gather table (embedding lookup is not a matmul)
    and as the int8 LM head (``(padded_vocab, hidden)`` is already the
    kernel's (N, K) layout). Dense kernels stored (in, out) transpose
    once, here; LayerNorm scale/bias and the dense biases stay fp32."""
    dt = cfg.policy.compute_dtype

    def qt(kernel):  # (in, out) -> (out, in)
        q, s = quantize_int8(jnp.asarray(kernel).T)
        return {"q": q, "s": s}

    out = {"wte": params["wte"].astype(dt),
           "wpe": params["wpe"].astype(dt),
           "lnf_scale": params["lnf_scale"],
           "lnf_bias": params["lnf_bias"]}
    for i in range(cfg.num_layers):
        lp = params[f"h{i}"]
        out[f"h{i}"] = {
            "ln1_scale": lp["ln1_scale"], "ln1_bias": lp["ln1_bias"],
            "ln2_scale": lp["ln2_scale"], "ln2_bias": lp["ln2_bias"],
            "qkv": qt(lp["qkv"]["kernel"]),
            "qkv_b": lp["qkv"]["bias"],
            "proj": qt(lp["proj"]["kernel"]),
            "proj_b": lp["proj"]["bias"],
            "fc_in": qt(lp["fc_in"]["kernel"]),
            "fc_in_b": lp["fc_in"]["bias"],
            "fc_out": qt(lp["fc_out"]["kernel"]),
            "fc_out_b": lp["fc_out"]["bias"],
        }
    q, s = quantize_int8(jnp.asarray(params["wte"]))
    out["head"] = {"q": q, "s": s}
    return out


def gpt2_quant_decoder(model, params):
    """(apply_fn, make_cache, qparams) for int8 decode of a `GPT2` —
    mirrors the flax module's cached path (LN with bias, fused qkv,
    causal cached attention at 1/sqrt(hd), GELU MLP, tied padded-vocab
    head) with every matmul through `ops.int8_matmul`. Same
    `generate.gpt2_decoder` apply_fn contract, ragged kwargs included."""
    import math

    from apex1_tpu.ops import layer_norm

    cfg = model.cfg
    dt = cfg.policy.compute_dtype
    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    qparams = quantize_gpt2_params(params, cfg)

    def mm(x, qw, b):
        y = int8_matmul(x, qw["q"], qw["s"])
        return (y + b.astype(jnp.float32)).astype(dt)

    def ln(x, g, b):
        if not cfg.policy.keep_norms_fp32:
            g, b = g.astype(dt), b.astype(dt)
        return layer_norm(x, g, b)

    def apply_fn(qp, tokens, cache, cache_index, *, positions=None,
                 segment_ids=None, valid_start=None, chunk_decode=False):
        B, S = tokens.shape
        idx = jnp.asarray(cache_index, jnp.int32)
        if positions is None:
            positions = jnp.broadcast_to((idx + jnp.arange(S))[None],
                                         (B, S))
        # mode="fill" NaN mirrors the flax model's loud out-of-range
        # positions (gpt2.py): a cache sized past max_seq_len must go
        # non-finite, not clamp to the last learned position
        x = (qp["wte"][tokens]
             + jnp.take(qp["wpe"], positions, axis=0, mode="fill",
                        fill_value=jnp.nan)).astype(dt)
        new_cache = {}
        for i in range(cfg.num_layers):
            lp = qp[f"h{i}"]
            h = ln(x, lp["ln1_scale"], lp["ln1_bias"]).astype(dt)
            qkv = mm(h, lp["qkv"], lp["qkv_b"])
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q, k, v = (t.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
                       for t in (q, k, v))
            attn, new_cache[f"layer{i}"] = cached_attention(
                q, k, v, cache[f"layer{i}"], cache_index,
                sm_scale=1.0 / math.sqrt(hd),
                segment_ids=segment_ids, valid_start=valid_start,
                chunk_decode=chunk_decode)
            attn = attn.transpose(0, 2, 1, 3).reshape(B, S, nh * hd)
            x = x + mm(attn, lp["proj"], lp["proj_b"])
            y = ln(x, lp["ln2_scale"], lp["ln2_bias"]).astype(dt)
            y = jax.nn.gelu(mm(y, lp["fc_in"], lp["fc_in_b"]))
            x = x + mm(y, lp["fc_out"], lp["fc_out_b"])
        x = ln(x, qp["lnf_scale"], qp["lnf_bias"]).astype(dt)
        logits = int8_matmul(x, qp["head"]["q"], qp["head"]["s"])
        return logits, new_cache

    def make_cache(batch: int, max_len: int, dtype=None):
        return init_cache(cfg.num_layers, batch, nh, max_len, hd,
                          dtype or dt)

    return apply_fn, make_cache, qparams
