"""Llama under full 3D parallelism — dp × pp × tp (+ Megatron sequence
parallelism on tp) in ONE ``shard_map`` train step.

This is BASELINE config 4 ("Llama-3 8B, TP/PP on XLA mesh") as a
reusable step builder: the manual-collective composition of
- ``transformer.tensor_parallel`` mappings/layers (Megatron TP + SP:
  one sequence all-gather feeding the fused-QKV and gate/up matmuls,
  reduce-scatter after the row-parallel projections — ≙ reference
  `tensor_parallel/layers.py :: ColumnParallelLinear/RowParallelLinear`
  with ``sequence_parallel_enabled``),
- ``ops.flash_attention`` (Pallas, GQA) + ``ops.apply_rotary_pos_emb``
  + ``ops.rms_norm`` inside each pipeline stage,
- ``pipeline_parallel.schedules.pipeline_apply`` with the PARTIAL-loss
  convention (grad taken inside the shard_map; see the grad-conventions
  note in `schedules` and docs/parallel.md),
- vocab-parallel embedding + fused LM-head cross-entropy
  (`tensor_parallel.vocab_parallel_linear_cross_entropy`), both
  pp-replicated with embedding-group grad combination
  (`schedules.allreduce_embedding_grads` ≙ reference
  `parallel_state` embedding group).

Pipeline boundary activations are SEQUENCE-SHARDED over tp — the
reference's `p2p_communication.py` scatter-gather-tensors-in-pipeline
optimization (split boundary tensors over the TP group to cut p2p
traffic by tp×) falls out of the SP layout for free here.

With ``moe=True`` the dense FFN becomes an expert-routed FFN on every
layer: each (dp, ep, tp) rank dispatches its sequence-shard tokens over
the ``ep`` axis (double ``all_to_all`` in
`transformer.moe.moe_shard_map_apply`), expert weights ep-sharded —
the full 4-axis dp × pp × ep × tp composition. The router's aux
balance loss IS threaded through the pipeline boundary: each stage's
aux accumulates in a ``with_aux`` side channel carried alongside the
boundary activation, summed into the last-stage loss (see the
``stage`` closure and the ``with_aux=cfg.moe`` schedule call below;
dryrun phase 4 asserts flat-vs-pipelined parity including the aux
term).

With ``cp > 1`` the sequence is additionally sharded over the cp axis
(outer to the tp/SP split): attention becomes `parallel.ring_attention`
(ppermute KV ring, global causal offsets), rope rows are sliced at the
shard's global positions, and the CE covers each cp shard's tokens —
BASELINE config 5's long-context axis inside the same step.

Gradient combination map (inside-grad convention; data replicas on
(dp, ep, cp)):
- replicated leaves: pmean over (dp, ep, cp);
- tp-sharded matmul shards (wq/wk/wv/wo/w_gate/w_up/w_down, emb/head
  rows): exact locally;
- tp-replicated norms + router (computed on per-rank token subsets):
  psum over tp;
- ep-sharded expert weights: psum over tp, pmean over (dp, cp), /ep (the
  all_to_all transpose already SUMMED every ep shard's contribution —
  never pmean across ep, that would mix different experts);
- pp-replicated embedding/head/final_norm (used on first/last stage
  only): psum over pp (the embedding-group all-reduce).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.mesh import (AXIS_CP, AXIS_DP, AXIS_EP, AXIS_PP,
                                 AXIS_TP, make_mesh)
from apex1_tpu.models.llama import LlamaConfig
from apex1_tpu.ops import apply_rotary_pos_emb, rms_norm, rope_tables
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.transformer.pipeline_parallel.schedules import (
    allreduce_embedding_grads, one_f_one_b, pipeline_apply)
from apex1_tpu.transformer.tensor_parallel import mappings as mp
from apex1_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_linear_cross_entropy)
from apex1_tpu.transformer.tensor_parallel.layers import (
    vocab_parallel_embedding)


@dataclasses.dataclass(frozen=True)
class Llama3DConfig:
    model: LlamaConfig
    dp: int = 1
    pp: int = 1
    tp: int = 1
    cp: int = 1                       # context parallel (ring attention)
    ep: int = 1                       # expert parallel (requires moe)
    moe: bool = False                 # every layer's FFN expert-routed
    num_chunks: int = 1               # V>1 = interleaved virtual pipeline
    num_microbatches: int = 4
    microbatch_size: int = 1          # sequences per (dp, ep) replica/mb
    learning_rate: float = 1e-4
    # "scan": pipeline_apply + jax.grad (remat bounds activation memory);
    # "1f1b": schedules.one_f_one_b — the reference 1F1B's staggered
    # fwd/bwd with the VJP-residual ring (true bounded-activations
    # schedule, 2VM stage-works vs remat's 3VM); with num_chunks > 1 it
    # runs the group-cycled interleaved schedule (requires
    # num_microbatches % pp == 0).
    schedule: str = "scan"

    def __post_init__(self):
        m = self.model
        if self.schedule not in ("scan", "1f1b"):
            raise ValueError("schedule must be 'scan' or '1f1b'")
        if self.schedule == "1f1b" and self.num_chunks > 1:
            if self.num_microbatches % self.pp:
                raise ValueError(
                    "interleaved 1F1B requires num_microbatches % pp == "
                    "0 (the group-cycled chunk schedule; ≙ the "
                    "reference's microbatches % pp assertion)")
            if self.pp < 2:
                raise ValueError(
                    "interleaved 1F1B needs pipeline size >= 2")
        if m.num_layers % (self.pp * self.num_chunks):
            raise ValueError("num_layers must divide by pp * num_chunks")
        if m.num_heads % self.tp or m.num_kv_heads % self.tp:
            raise ValueError("head counts must divide by tp")
        if m.vocab_size % self.tp:
            raise ValueError("vocab_size must divide by tp")
        if m.max_seq_len % (self.tp * self.cp):
            raise ValueError(
                "seq len must divide by tp * cp (SP + ring shards)")
        if self.num_chunks > 1 and self.num_microbatches < self.pp:
            raise ValueError("interleaved pipeline needs M >= pp")
        if self.ep > 1 and not self.moe:
            raise ValueError("ep > 1 requires moe=True")
        if self.moe and m.num_experts % self.ep:
            raise ValueError("num_experts must divide by ep")

    @property
    def moe_cfg(self):
        from apex1_tpu.transformer.moe import MoEConfig

        m = self.model
        return MoEConfig(num_experts=m.num_experts, top_k=m.moe_top_k,
                         capacity_factor=m.moe_capacity_factor,
                         aux_loss_weight=m.moe_aux_loss_weight,
                         hidden_size=m.hidden_size, ffn_size=m.ffn_size)

    @property
    def layers_per_stage(self) -> int:
        """Layers per (chunk, stage) slot — model chunk c = v·pp + s
        holds layers [c·lps, (c+1)·lps)."""
        return self.model.num_layers // (self.pp * self.num_chunks)


def _layer_leaf_shapes(cfg: Llama3DConfig):
    m = cfg.model
    E, F = m.hidden_size, m.ffn_size
    HD, KD = m.num_heads * m.head_dim, m.num_kv_heads * m.head_dim
    shapes = {
        "attn_norm": (E,), "mlp_norm": (E,),
        "wq": (E, HD), "wk": (E, KD), "wv": (E, KD), "wo": (HD, E),
    }
    if cfg.moe:
        n = m.num_experts
        shapes.update({"wg": (E, n), "w_moe1": (n, E, F),
                       "w_moe2": (n, F, E)})
    else:
        shapes.update({"w_gate": (E, F), "w_up": (E, F),
                       "w_down": (F, E)})
    return shapes


def chunk_param_specs(cfg: Llama3DConfig):
    """PartitionSpecs for the (num_chunks, pp, layers_per_stage, ...)
    stacked tree (chunk axis replicated; stage axis sharded over pp;
    expert dim over ep when MoE)."""
    col = P(None, AXIS_PP, None, None, AXIS_TP)
    row = P(None, AXIS_PP, None, AXIS_TP, None)
    norm = P(None, AXIS_PP, None, None)
    specs = {
        "attn_norm": norm, "mlp_norm": norm,
        "wq": col, "wk": col, "wv": col, "wo": row,
    }
    if cfg.moe:
        specs.update({
            "wg": P(None, AXIS_PP, None, None, None),
            "w_moe1": P(None, AXIS_PP, None, AXIS_EP, None, None),
            "w_moe2": P(None, AXIS_PP, None, AXIS_EP, None, None),
        })
    else:
        specs.update({"w_gate": col, "w_up": col, "w_down": row})
    return specs


def shared_param_specs():
    return {"emb": P(AXIS_TP, None), "head": P(AXIS_TP, None),
            "final_norm": P()}


def init_params(cfg: Llama3DConfig, seed: int = 0):
    """Global (unsharded) param trees: (chunk_params, shared_params)."""
    m = cfg.model
    rng = np.random.default_rng(seed)
    V, PP, L = m.vocab_size, cfg.pp, cfg.layers_per_stage
    VC = cfg.num_chunks

    def norm_init(shape):
        return jnp.ones((VC, PP, L) + shape, jnp.float32)

    def w_init(shape):
        return jnp.asarray(
            rng.normal(size=(VC, PP, L) + shape) * 0.02, jnp.float32)

    chunk = {k: (norm_init(s) if "norm" in k else w_init(s))
             for k, s in _layer_leaf_shapes(cfg).items()}
    shared = {
        "emb": jnp.asarray(
            rng.normal(size=(V, m.hidden_size)) * 0.02, jnp.float32),
        "head": jnp.asarray(
            rng.normal(size=(V, m.hidden_size)) * 0.02, jnp.float32),
        "final_norm": jnp.ones((m.hidden_size,), jnp.float32),
    }
    return chunk, shared


def abstract_state(cfg: Llama3DConfig, mesh):
    """ShapeDtypeStruct trees (with NamedShardings) for the train state
    and (tokens, labels) — lets AOT checks lower the full 8B-scale step
    without materializing 100+ GB of host arrays."""
    from apex1_tpu.optim.fused_adam import FusedAdamState

    m = cfg.model
    PP, L, V = cfg.pp, cfg.layers_per_stage, m.vocab_size

    def sds(shape, spec, dtype=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dtype,
                                    sharding=NamedSharding(mesh, spec))

    cspecs, sspecs = chunk_param_specs(cfg), shared_param_specs()
    chunk = {k: sds((cfg.num_chunks, PP, L) + shp, cspecs[k])
             for k, shp in _layer_leaf_shapes(cfg).items()}
    shared = {"emb": sds((V, m.hidden_size), sspecs["emb"]),
              "head": sds((V, m.hidden_size), sspecs["head"]),
              "final_norm": sds((m.hidden_size,), sspecs["final_norm"])}
    params = {"chunk": chunk, "shared": shared}
    state = {
        "step": sds((), P(), jnp.int32),
        "params": params,
        "opt": FusedAdamState(
            step=sds((), P(), jnp.int32),
            exp_avg=jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding),
                params),
            exp_avg_sq=jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=x.sharding),
                params)),
    }
    _scaler = _make_scaler(cfg)
    if _scaler is not None:
        state["scale"] = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                jnp.shape(x), x.dtype,
                sharding=NamedSharding(mesh, P())),
            _scaler.init())
    dshape = (cfg.num_microbatches, m.max_seq_len,
              cfg.microbatch_size * cfg.dp * cfg.ep)
    data = sds(dshape, P(None, AXIS_CP, (AXIS_DP, AXIS_EP)), jnp.int32)
    return state, data


def _make_scaler(cfg: Llama3DConfig):
    """The policy's loss-scale machine, or None for unscaled (bf16/fp32)
    policies — the ONE construction point shared by build_step /
    make_train_step / abstract_state so their state trees can't drift."""
    if cfg.model.policy.loss_scale is None:
        return None
    from apex1_tpu.core import loss_scale as ls

    return ls.make_loss_scale(cfg.model.policy.loss_scale)


def reshape_chunks(tree, cfg_to: Llama3DConfig):
    """Re-stack chunk leaves between pipeline topologies (same model,
    different pp / num_chunks). The chunk-major layout assigns global
    layer (v·pp + s)·lps + j to slot (v, s, j) — exactly the row-major
    flattening of the (V, PP, lps) axes — so a plain reshape
    re-partitions the stack for any (V', PP', lps') factorization:
    checkpoint on one pipeline layout, resume on another
    (≙ reference cross-topology resume, SURVEY §5.4)."""
    return jax.tree_util.tree_map(
        lambda x: jnp.reshape(
            jnp.asarray(x),
            (cfg_to.num_chunks, cfg_to.pp, cfg_to.layers_per_stage)
            + x.shape[3:]),
        tree)


def from_llama_params(params, cfg: Llama3DConfig):
    """Convert a `models.llama.Llama` param tree (layer{i}/wq, …,
    tok_embeddings, output, norm) into the stacked 3D trees — the parity
    bridge the tests use."""
    L, PP, VC = cfg.layers_per_stage, cfg.pp, cfg.num_chunks
    # MoE leaves live under the block's "moe" submodule in the flax tree
    path = {"wg": ("moe", "router"), "w_moe1": ("moe", "w1"),
            "w_moe2": ("moe", "w2")}

    def leaf(i, name):
        node = params[f"layer{i}"]
        for part in path.get(name, (name,)):
            node = node[part]
        return node

    def stack(leaf_name):
        # model chunk c = v*PP + s holds layers [c*L, (c+1)*L)
        return jnp.stack([jnp.stack(
            [jnp.stack([leaf((v * PP + s) * L + j, leaf_name)
                        for j in range(L)]) for s in range(PP)])
            for v in range(VC)])

    chunk = {k: stack(k) for k in _layer_leaf_shapes(cfg)}
    shared = {"emb": params["tok_embeddings"],
              "head": params["output"],
              "final_norm": params["norm"]}
    return chunk, shared


def _stage_fn(cfg: Llama3DConfig, cos, sin):
    """One pipeline stage over the LOCAL shards: x (S/(cp*tp), mb, E)
    bf16, sequence-sharded over cp (outer, ring attention) then tp
    (Megatron SP, (s, b, h) layout)."""
    m = cfg.model
    tp = cfg.tp
    Hl, Kl, D = m.num_heads // tp, m.num_kv_heads // tp, m.head_dim
    E = m.hidden_size
    dt = m.policy.compute_dtype

    def layer(x, lp):
        # attention: norm on seq shards, ONE seq all-gather feeds q/k/v
        h = rms_norm(x, lp["attn_norm"], eps=m.norm_eps).astype(dt)
        h = mp.gather_from_sequence_parallel_region(h, AXIS_TP, 0, True)
        S, mb = h.shape[0], h.shape[1]      # S = cp-local sequence
        q = (h @ lp["wq"].astype(dt)).reshape(S, mb, Hl, D)
        k = (h @ lp["wk"].astype(dt)).reshape(S, mb, Kl, D)
        v = (h @ lp["wv"].astype(dt)).reshape(S, mb, Kl, D)
        if cfg.cp > 1:
            # GLOBAL positions for this cp shard's rope rows
            start = jax.lax.axis_index(AXIS_CP) * S
            cos_l = jax.lax.dynamic_slice_in_dim(cos, start, S)
            sin_l = jax.lax.dynamic_slice_in_dim(sin, start, S)
        else:
            cos_l, sin_l = cos, sin
        q = apply_rotary_pos_emb(q.transpose(1, 0, 2, 3), cos_l, sin_l)
        k = apply_rotary_pos_emb(k.transpose(1, 0, 2, 3), cos_l, sin_l)
        v = v.transpose(1, 0, 2, 3)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        if cfg.cp > 1:
            from apex1_tpu.parallel.ring_attention import ring_attention

            attn = ring_attention(q, k, v, AXIS_CP, causal=True)
        else:
            attn = flash_attention(q, k, v, causal=True)
        attn = attn.transpose(2, 0, 1, 3).reshape(S, mb, Hl * D)
        o = attn @ lp["wo"].astype(dt)
        o = mp.reduce_scatter_to_sequence_parallel_region(o, AXIS_TP, 0)
        x = x + o.astype(x.dtype)

        h = rms_norm(x, lp["mlp_norm"], eps=m.norm_eps).astype(dt)
        aux = jnp.zeros([], jnp.float32)
        if cfg.moe:
            # expert FFN on the SEQ-SHARDED tokens: each (tp, dp, ep)
            # rank dispatches its own token subset over the ep axis
            # (double all_to_all inside moe_shard_map_apply); expert
            # weights are ep-sharded, tp/pp-replicated. stats_axes
            # psum-combines the router's load-balance statistics over
            # every axis that shards this microbatch's tokens, so aux is
            # the GLOBAL Switch balance term (≙ the flat model's sowed
            # moe_aux, models/llama.py:152) — returned alongside y and
            # carried out of the pipeline by with_aux.
            from apex1_tpu.transformer.moe import moe_shard_map_apply

            stats_axes = (AXIS_TP, AXIS_DP, AXIS_EP)
            if cfg.cp > 1:
                stats_axes += (AXIS_CP,)
            S_l, mb = h.shape[0], h.shape[1]
            y2, aux = moe_shard_map_apply(
                h.reshape(-1, E), lp["wg"].astype(dt), lp["w_moe1"],
                lp["w_moe2"], cfg.moe_cfg, axis_name=AXIS_EP,
                act=jax.nn.silu, stats_axes=stats_axes)
            y = y2.reshape(S_l, mb, E)
        else:
            # dense MLP: same SP pattern, one gather feeds gate+up
            h = mp.gather_from_sequence_parallel_region(h, AXIS_TP, 0,
                                                        True)
            y = (jax.nn.silu(h @ lp["w_gate"].astype(dt))
                 * (h @ lp["w_up"].astype(dt))) @ lp["w_down"].astype(dt)
            y = mp.reduce_scatter_to_sequence_parallel_region(y, AXIS_TP,
                                                              0)
        return x + y.astype(x.dtype), aux

    if m.remat:
        from apex1_tpu.transformer.tensor_parallel.random import (
            checkpoint_with_policy)
        layer = checkpoint_with_policy(layer, m.remat_policy)

    def stage(p_stage, x):
        # p_stage leaves: (layers_per_stage, ...) — scan keeps the jaxpr
        # O(1) in depth (16 layers/stage at 8B scale); remat(layer) inside
        # scan is the standard activation-checkpoint pattern. Per-layer
        # MoE aux terms come out as scan outputs and sum to the stage's
        # contribution (with_aux pipeline channel).
        x, auxes = jax.lax.scan(lambda x, lp: layer(x, lp), x, p_stage)
        if cfg.moe:
            return x, jnp.sum(auxes)
        return x

    return stage


def _embed_microbatches(cfg: Llama3DConfig, emb_w, tokens):
    """(M, S, mb) tokens -> (M, S/(cp*tp), mb, E) boundary activations:
    vocab-parallel embedding cast to the compute dtype, sequence-scattered
    into the SP region. The ONE embedding-layout definition shared by the
    scan and 1f1b paths (their parity depends on it staying identical)."""
    dt = cfg.model.policy.compute_dtype

    def one(tok_m):  # (S, mb) -> (S/tp, mb, E) seq shard
        y = vocab_parallel_embedding(tok_m, emb_w.astype(dt))
        return mp.scatter_to_sequence_parallel_region(y, AXIS_TP, 0)

    return jax.vmap(one)(tokens)


def loss_fn(cfg: Llama3DConfig, chunk_local, shared_local, tokens, labels,
            cos, sin):
    """PARTIAL loss (sums to the global mean CE over the pp axis). Runs
    inside shard_map over (dp, pp, cp, ep, tp). ``tokens``/``labels``:
    (M, S, mb) int32, sequence cp-sharded and mb (dp, ep)-sharded by the
    in_specs."""
    m = cfg.model
    tp = cfg.tp
    dt = m.policy.compute_dtype
    stage = _stage_fn(cfg, cos, sin)

    h_mb = _embed_microbatches(cfg, shared_local["emb"], tokens)
    local = jax.tree_util.tree_map(lambda p: p[:, 0], chunk_local)
    # bubble-skip contract (schedules.pipeline_apply): ring attention
    # rotates KV with ppermute, which must not sit inside the per-tick
    # validity cond — mask bubbles instead when cp shards the sequence
    outs = pipeline_apply(stage, local, h_mb, num_chunks=cfg.num_chunks,
                          broadcast_outputs=False,
                          skip_bubbles=cfg.cp == 1,
                          with_aux=cfg.moe)
    if cfg.moe:
        outs, moe_aux = outs

    o = rms_norm(outs, shared_local["final_norm"], eps=m.norm_eps)
    o = o.astype(dt)
    # fused LM-head CE: local tokens seq-major-first so the op's internal
    # tp all-gather reconstructs the global token order (dryrun pattern)
    M, S_loc, mb, E = o.shape
    x_tok = o.transpose(1, 0, 2, 3).reshape(-1, E)
    lbl = labels.reshape(M, tp, S_loc, mb).transpose(1, 2, 0, 3)
    lbl = lbl.reshape(-1)
    ce = vocab_parallel_linear_cross_entropy(
        x_tok, shared_local["head"].astype(dt), lbl,
        sequence_parallel_input=True)
    last = (jax.lax.axis_index(AXIS_PP)
            == jax.lax.axis_size(AXIS_PP) - 1).astype(jnp.float32)
    loss = last * jnp.mean(ce)
    if cfg.moe:
        # MoE aux under the PARTIAL-loss convention: each pp rank adds
        # its own stages' (already globally-combined) balance terms, so
        # psum over pp sums distinct layers; aux is per-(microbatch,
        # layer) and the gold averages per-microbatch losses, hence /M.
        #
        # SEED MULTIPLICITY (docs/parallel.md "Pipeline gradient
        # conventions"): the stats psum over (tp, dp, ep[, cp]) makes the
        # aux REPLICATED over those axes, so with grad taken inside the
        # shard_map every rank seeds it and psum's transpose multiplies
        # the aux cotangent by the full group size R = tp·dp·ep·cp.
        # combine_grads expects CE-convention terms — distinct per
        # (dp, ep, cp) rank (pmean'd) and replicated over tp only
        # (psum'd for norm/router leaves) — i.e. multiplicity tp, not R.
        # Seeding aux/tp cancels the excess exactly for every param
        # class; the stop_gradient completion restores the VALUE so the
        # logged loss is CE + full aux.
        inv = 1.0 / cfg.tp
        aux_term = (moe_aux * inv
                    + jax.lax.stop_gradient(moe_aux) * (1.0 - inv))
        loss = loss + aux_term / tokens.shape[0]
    return loss


def loss_and_grads_1f1b(cfg: Llama3DConfig, params, tokens, labels,
                        cos, sin, scale_val):
    """The flagship step's fwd+bwd on the TRUE 1F1B schedule
    (`schedules.one_f_one_b`) — same objective, grads, and partial-loss
    convention as ``jax.grad`` over :func:`loss_fn`, but with the
    staggered-fwd/bwd residual ring instead of remat (bounded in-flight
    activations at 2M stage-works per stage vs the scan path's 3M).
    Runs inside shard_map; returns ``(grads, loss_part)`` with
    ``grads`` SCALED by ``scale_val`` (unscale downstream, as the scan
    path does) and ``loss_part`` the UNSCALED per-rank partial loss
    (CE on the last stage + this rank's MoE aux share).

    Post-process placement: final-norm + vocab-parallel fused CE run
    per-microbatch inside ``loss_mb`` on the last stage (≙ the
    reference's ``post_language_model_processing`` on the last rank),
    with {final_norm, head} as the schedule's ``loss_params`` channel;
    the embedding backward replays `vocab_parallel_embedding`'s VJP
    from the schedule's ``dmicrobatches`` cotangents (real on stage 0).

    MoE aux seed: the scan path seeds ``aux/tp`` per rank so the psum
    transpose's replication (R = tp·dp·ep·cp seeds) collapses to the
    CE-convention multiplicity `combine_grads` expects (tp). Here the
    per-rank VJP runs the SAME psum transpose over the stats axes, so
    the same ``scale/(tp·M)`` cotangent reproduces the scan path's
    gradient exactly (parity-tested vs both the scan schedule and the
    flat model)."""
    m = cfg.model
    tp = cfg.tp
    dt = m.policy.compute_dtype
    stage = _stage_fn(cfg, cos, sin)
    M = tokens.shape[0]
    chunk_local, shared_local = params["chunk"], params["shared"]

    def embed_all(emb_w):
        return _embed_microbatches(cfg, emb_w, tokens)

    h_mb = embed_all(shared_local["emb"])
    VC = cfg.num_chunks
    # (V, pp-local 1, L, ...) -> (V, L, ...) chunk-major local layers
    # (one_f_one_b takes the V axis itself for the interleaved
    # schedule; V=1 squeezes below)
    stage_local = jax.tree_util.tree_map(
        lambda p: p[0, 0] if VC == 1 else p[:, 0], chunk_local)
    lp = {"final_norm": shared_local["final_norm"],
          "head": shared_local["head"]}

    def loss_mb(lp_, y, mi):
        o = rms_norm(y, lp_["final_norm"], eps=m.norm_eps).astype(dt)
        S_loc, mb, E = o.shape
        lbl_m = jax.lax.dynamic_index_in_dim(labels, mi, 0,
                                             keepdims=False)
        # local tokens seq-major; labels in the CE's gathered (tp-major)
        # global order — the per-microbatch form of loss_fn's layout
        ce = vocab_parallel_linear_cross_entropy(
            o.reshape(-1, E), lp_["head"].astype(dt),
            lbl_m.reshape(tp, S_loc, mb).reshape(-1),
            sequence_parallel_input=True)
        return scale_val * jnp.mean(ce) / M

    skip = cfg.cp == 1                 # ring attention => mask, no cond
    if cfg.moe:
        loss_p, g_stage, dmb, dlp, aux_sum = one_f_one_b(
            stage, stage_local, h_mb, loss_mb, loss_params=lp,
            num_chunks=VC, with_aux=True,
            aux_cotangent=scale_val / (tp * M), skip_idle=skip)
    else:
        loss_p, g_stage, dmb, dlp = one_f_one_b(
            stage, stage_local, h_mb, loss_mb, loss_params=lp,
            num_chunks=VC, skip_idle=skip)

    # finish the model backward: embedding VJP from the boundary
    # cotangents (real on stage 0; other pp groups contribute zeros and
    # combine_grads' embedding-group psum completes them)
    _, vjp_e = jax.vjp(embed_all, shared_local["emb"])
    (demb,) = vjp_e(dmb.astype(h_mb.dtype))

    grads = {
        "chunk": jax.tree_util.tree_map(
            lambda g: g[None, None] if VC == 1 else g[:, None], g_stage),
        "shared": {"emb": demb, "head": dlp["head"],
                   "final_norm": dlp["final_norm"]},
    }
    loss_part = loss_p / scale_val     # scale is a power of 2 — exact
    if cfg.moe:
        loss_part = loss_part + aux_sum / M
    return grads, loss_part


def combine_grads(g_chunk, g_shared, cfg: Llama3DConfig):
    """The full combination map for the inside-grad convention. Data
    replicas live on (dp, ep, cp); expert-sharded leaves are special: the
    all_to_all transpose already SUMMED every ep shard's token
    contributions into the local expert shard, so their ep combine is a
    /ep (sum -> replica mean), never a pmean across DIFFERENT experts."""
    ep = cfg.ep
    moe = cfg.moe
    expert_keys = ("w_moe1", "w_moe2")
    data_axes = (AXIS_DP, AXIS_EP, AXIS_CP)

    def chunk_one(k, g):
        if moe and k in expert_keys:
            g = jax.lax.psum(g, AXIS_TP)       # token subsets sum
            return jax.lax.pmean(g, (AXIS_DP, AXIS_CP)) / ep
        g = jax.lax.pmean(g, data_axes)
        if "norm" in k or k == "wg":
            g = jax.lax.psum(g, AXIS_TP)       # SP/token-subset partials
        return g

    g_chunk = {k: chunk_one(k, v) for k, v in g_chunk.items()}
    g_shared = jax.lax.pmean(g_shared, data_axes)
    # final_norm: computed on seq shards (tp-partial) on the last stage
    g_shared["final_norm"] = jax.lax.psum(g_shared["final_norm"], AXIS_TP)
    # embedding group: emb lives on stage 0, head + final_norm on the
    # last stage; psum over pp completes them (middle stages are zero)
    g_shared = allreduce_embedding_grads(g_shared, AXIS_PP)
    return g_chunk, g_shared


def build_step(cfg: Llama3DConfig, mesh):
    """The jitted shard_map train step alone (no state materialization) —
    ``step(state, tokens, labels) -> (state, loss)``. Pair with
    `abstract_state` for AOT lowering at 8B scale.

    When the model policy carries a loss scale (fp16 compute), the step
    threads the dynamic loss-scale state machine: scale the PARTIAL loss
    (linear, so the pp-partial convention is preserved), unscale after
    the grad combines, global finite-check psum across ALL mesh axes
    (≙ the reference's MP-aware GradScaler, `transformer/amp/
    grad_scaler.py` — every dp/pp/tp rank skips together), skip-on-
    overflow via `select_tree`, hysteresis adjust."""
    import optax

    from apex1_tpu.core import loss_scale as ls
    from apex1_tpu.optim.fused_adam import FusedAdamState

    m = cfg.model
    tx = _make_tx(cfg)
    scaler = _make_scaler(cfg)
    param_specs = {"chunk": chunk_param_specs(cfg),
                   "shared": shared_param_specs()}
    state_specs = {"step": P(), "params": param_specs,
                   "opt": FusedAdamState(step=P(), exp_avg=param_specs,
                                         exp_avg_sq=param_specs)}
    if scaler is not None:
        state_specs["scale"] = jax.tree_util.tree_map(
            lambda _: P(), scaler.init())
    cos, sin = rope_tables(jnp.arange(m.max_seq_len), m.head_dim,
                           base=m.rope_base)
    # (M, S, mb): sequence sharded over cp, batch over (dp, ep)
    data_spec = P(None, AXIS_CP, (AXIS_DP, AXIS_EP))

    def train_step(state, tokens, labels):
        if cfg.schedule == "1f1b":
            scale_val = (jnp.float32(1.0) if scaler is None
                         else state["scale"].scale)
            grads, loss_part = loss_and_grads_1f1b(
                cfg, state["params"], tokens, labels, cos, sin,
                scale_val)
        else:
            def scalar(params):
                loss = loss_fn(cfg, params["chunk"], params["shared"],
                               tokens, labels, cos, sin)
                if scaler is None:
                    return loss, loss
                return scaler.scale(loss, state["scale"]), loss

            grads, loss_part = jax.grad(scalar, has_aux=True)(
                state["params"])
        loss = jax.lax.psum(loss_part, AXIS_PP)
        loss = jax.lax.pmean(loss, (AXIS_DP, AXIS_EP, AXIS_CP))
        g_chunk, g_shared = combine_grads(grads["chunk"], grads["shared"],
                                          cfg)
        grads = {"chunk": g_chunk, "shared": g_shared}
        if scaler is not None:
            grads = scaler.unscale(grads, state["scale"])
            finite = ls.all_finite(
                grads,
                axis_names=(AXIS_DP, AXIS_EP, AXIS_CP, AXIS_PP,
                            AXIS_TP))
        updates, new_opt = tx.update(grads, state["opt"], state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        new_state = {"step": state["step"] + 1, "params": new_params,
                     "opt": new_opt}
        if scaler is not None:
            new_state["params"] = ls.select_tree(finite, new_params,
                                                 state["params"])
            new_state["opt"] = ls.select_tree(finite, new_opt,
                                              state["opt"])
            new_state["scale"] = scaler.adjust(state["scale"], finite)
        return new_state, loss

    step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(state_specs, data_spec, data_spec),
        out_specs=(state_specs, P()),
        check_vma=False), donate_argnums=0)
    return step, state_specs, data_spec, tx


def _make_tx(cfg: Llama3DConfig):
    """THE optimizer construction — `build_step` and `state_template`
    both consume this one definition, so the trained state and the
    restore/reshard template structurally cannot drift (a cfg-driven
    optimizer change lands in both or neither)."""
    from apex1_tpu.optim.fused_adam import fused_adam

    return fused_adam(cfg.learning_rate)


def state_template(cfg: Llama3DConfig, params=None):
    """Host-side state pytree with the exact structure/shapes/dtypes
    `make_train_step` trains — built WITHOUT a mesh or any device
    count, which is what makes it usable as a checkpoint restore /
    reshard template on a fleet that can no longer build the saving
    topology (`resilience.reshard_checkpoint`,
    `resilience.elastic_resume`). Shares `_make_tx` (and
    `_make_scaler`) with `build_step`, so the two can't drift."""
    tx = _make_tx(cfg)
    if params is None:
        chunk, shared = init_params(cfg)
        params = {"chunk": chunk, "shared": shared}
    state = {"step": jnp.zeros([], jnp.int32), "params": params,
             "opt": tx.init(params)}
    _scaler = _make_scaler(cfg)
    if _scaler is not None:
        state["scale"] = _scaler.init()
    return state


def make_train_step(cfg: Llama3DConfig, mesh=None, params=None):
    """Returns ``(step, state, data_spec)`` with a materialized initial
    state, fused Adam on fp32 masters. ``params`` overrides the random
    init (e.g. `from_llama_params` output)."""
    if mesh is None:
        mesh = make_mesh(dp=cfg.dp, pp=cfg.pp, cp=cfg.cp, ep=cfg.ep,
                         tp=cfg.tp)
    step, _state_specs, data_spec, _tx = build_step(cfg, mesh)
    state = state_template(cfg, params=params)
    return step, state, data_spec
