"""GPT-2 — BASELINE config 1 model ("GPT-2 125M, amp O1 + Adam").

The reference repo has no model zoo (apex bolts onto user models; its test
models live in ``apex/transformer/testing/standalone_gpt.py``). This is the
equivalent standalone model, built from this framework's fused ops:
FusedLayerNorm, scaled_upper_triang_masked_softmax, softmax_cross_entropy
— pre-LN transformer with learned positions, GELU MLP, weight-tied LM head.

Policy-aware: ``policy.compute_dtype`` drives activations/matmuls; norms and
softmax run fp32 when ``keep_norms_fp32``/``fp32_fragile_ops`` ask for it
(the O1 op-list semantics).
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.ops import (layer_norm, linear_cross_entropy,
                           scaled_upper_triang_masked_softmax,
                           softmax_cross_entropy_loss)
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.ops.stochastic import (fold_seed, fused_bias_dropout_add,
                                      seed_from_key)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    mlp_ratio: int = 4
    dropout: float = 0.0
    use_flash: bool = True
    policy: PrecisionPolicy = dataclasses.field(
        default_factory=lambda: get_policy("O0"))

    @property
    def padded_vocab(self) -> int:
        """Vocab rounded to a lane multiple (Megatron-style padding) so
        the LM-head matmul and CE tile cleanly onto the MXU; padded rows
        exist only in the embedding table, logits are sliced back."""
        return ((self.vocab_size + 127) // 128) * 128

    @staticmethod
    def gpt2_125m(**kw) -> "GPT2Config":
        return GPT2Config(**kw)

    @staticmethod
    def tiny(**kw) -> "GPT2Config":
        defaults = dict(vocab_size=256, max_seq_len=128, num_layers=2,
                        num_heads=4, hidden_size=128)
        defaults.update(kw)
        return GPT2Config(**defaults)


class Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, *, deterministic=True, segment_ids=None,
                 cache=None, cache_index=None, valid_start=None,
                 chunk_decode=False):
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        h = cfg.hidden_size
        nh = cfg.num_heads
        hd = h // nh

        def norm(name, z):
            gamma = self.param(f"{name}_scale", nn.initializers.ones, (h,),
                               jnp.float32)
            beta = self.param(f"{name}_bias", nn.initializers.zeros, (h,),
                              jnp.float32)
            if not cfg.policy.keep_norms_fp32:
                gamma, beta = gamma.astype(dtype), beta.astype(dtype)
            return layer_norm(z, gamma, beta)

        # dropout (cfg.dropout > 0, training): attention-probability
        # dropout fused in the flash kernel + fused dropout-add residual
        # epilogues; one rng draw per block, per-site int32 streams via
        # fold_seed (the APX103-sanctioned idiom)
        active = cfg.dropout > 0.0 and not deterministic and cache is None
        if active and not cfg.use_flash:
            raise ValueError("dropout > 0 needs use_flash=True (the "
                             "composite path has no fused dropout)")
        seed = seed_from_key(self.make_rng("dropout")) if active else None

        # attention — flash kernel (O(S·D) memory; the materialized
        # scores + fused-softmax path is kept via use_flash=False for
        # the kernel-parity cross-check)
        y = norm("ln1", x)
        qkv = nn.Dense(3 * h, dtype=dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        B, S = x.shape[0], x.shape[1]
        q = q.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
        new_cache = None
        if cache is not None:
            from apex1_tpu.models.generate import cached_attention
            attn, new_cache = cached_attention(
                q, k, v, cache, cache_index,
                sm_scale=1.0 / math.sqrt(hd),
                segment_ids=segment_ids, valid_start=valid_start,
                chunk_decode=chunk_decode)
        elif cfg.use_flash:
            attn = flash_attention(q, k, v, causal=True,
                                   segment_ids=segment_ids,
                                   sm_scale=1.0 / math.sqrt(hd),
                                   dropout_p=cfg.dropout if active else 0.0,
                                   dropout_seed=(fold_seed(seed, 0)
                                                 if active else None))
        else:
            if segment_ids is not None:
                raise ValueError("packed batches need use_flash=True")
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32)
            probs = scaled_upper_triang_masked_softmax(
                scores, scale=1.0 / math.sqrt(hd))
            attn = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(dtype), v)
        attn = attn.transpose(0, 2, 1, 3).reshape(B, S, h)
        proj = nn.Dense(h, dtype=dtype, name="proj")(attn)
        if active:
            # Megatron bias_dropout_add epilogue (pre-LN stack: no norm
            # after the add) — mask recomputed from the seed in backward
            x = fused_bias_dropout_add(proj, x, p=cfg.dropout,
                                       seed=fold_seed(seed, 1))
        else:
            x = x + proj

        # MLP
        y = norm("ln2", x)
        y = nn.Dense(cfg.mlp_ratio * h, dtype=dtype, name="fc_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(h, dtype=dtype, name="fc_out")(y)
        if active:
            out = fused_bias_dropout_add(y, x, p=cfg.dropout,
                                         seed=fold_seed(seed, 2))
        else:
            out = x + y
        return out if new_cache is None else (out, new_cache)


class GPT2(nn.Module):
    """Returns logits; `loss` computes the fused CE."""

    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, *, deterministic=True, return_hidden=False,
                 segment_ids=None, positions=None, cache=None,
                 cache_index=None, valid_start=None,
                 chunk_decode=False):
        """``segment_ids``/(B, S) ``positions`` enable packed batches
        (≙ fmha cu_seqlens varlen; see `runtime.pack_documents`) — tokens
        attend within their segment, learned positions gather per row.

        ``cache``/``cache_index`` enable KV-cached decoding (see
        `models.generate`): the return becomes ``(logits, new_cache)``;
        prefill (S>1) must start from an empty cache at index 0. With a
        cache, ``segment_ids``/``valid_start`` carry the ragged
        left-padded-prompt masking (``generate(prompt_lens=...)``)."""
        cfg = self.cfg
        dtype = cfg.policy.compute_dtype
        B, S = tokens.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab, cfg.hidden_size), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.max_seq_len, cfg.hidden_size), jnp.float32)
        if positions is None:
            pos_emb = wpe[:S].astype(dtype)[None]
        else:
            # out-of-range positions (e.g. runtime.pack_documents chunking
            # a long document without restart_chunk_positions=True) must
            # not silently clamp under jit — fill with NaN so the loss
            # goes non-finite and the mistake is visible immediately
            pos_emb = jnp.take(wpe, positions, axis=0, mode="fill",
                               fill_value=jnp.nan).astype(dtype)
        x = wte[tokens].astype(dtype) + pos_emb
        new_cache = {}
        for i in range(cfg.num_layers):
            out = Block(cfg, name=f"h{i}")(
                x, deterministic=deterministic, segment_ids=segment_ids,
                cache=None if cache is None else cache[f"layer{i}"],
                cache_index=cache_index, valid_start=valid_start,
                chunk_decode=chunk_decode)
            if cache is None:
                x = out
            else:
                x, new_cache[f"layer{i}"] = out
        gamma = self.param("lnf_scale", nn.initializers.ones,
                           (cfg.hidden_size,), jnp.float32)
        beta = self.param("lnf_bias", nn.initializers.zeros,
                          (cfg.hidden_size,), jnp.float32)
        x = layer_norm(x, gamma, beta)
        if return_hidden:
            # for the fused LM-head+CE path (ops.linear_cross_entropy):
            # the (B, S, V) logits never hit HBM. With a cache the
            # contract mirrors the logits return — the serving LoRA
            # epilogue replays the tied-head matmul itself so per-slot
            # adapter deltas can fuse in (llama does the same)
            h = x.astype(dtype)
            return h if cache is None else (h, new_cache)
        logits = jnp.einsum("bsh,vh->bsv", x.astype(dtype),
                            wte.astype(dtype),
                            preferred_element_type=jnp.float32)
        # returned over padded_vocab — slice-free; consumers mask with
        # num_classes=cfg.vocab_size (the CE kernel does it in-lane)
        return logits if cache is None else (logits, new_cache)


# Megatron-style TP sharding as path-regex rules (see parallel/specs.py):
# attention qkv + MLP fc_in are column-parallel (output dim sharded, bias
# sharded with it), proj + fc_out row-parallel (input dim sharded, bias
# replicated), embeddings vocab-sharded, positions/norms replicated.
_TP_RULES = (
    (r"wte$", P("tp", None)),
    (r"wpe$", P()),
    (r"(qkv|fc_in)/kernel$", P(None, "tp")),
    (r"(qkv|fc_in)/bias$", P("tp")),
    (r"(proj|fc_out)/kernel$", P("tp", None)),
    (r"(proj|fc_out)/bias$", P()),
)


def param_specs(params, *, rules=_TP_RULES, default=P()):
    """PartitionSpec tree for a GPT-2 param tree (TP over the ``tp`` mesh
    axis) — ≙ ``set_tensor_model_parallel_attributes`` as data."""
    from apex1_tpu.parallel.specs import specs_from_rules
    return specs_from_rules(params, rules, default=default)


def gpt2_loss_fn(model: GPT2, *, fuse_head: bool = True):
    """``loss_fn(params, tokens) -> scalar`` for `Amp.make_train_step`:
    next-token CE (fp32 inside the kernel — O1 FP32_FUNCS semantics).

    ``fuse_head=True`` (default) runs the tied LM head through
    ``ops.linear_cross_entropy`` — head matmul fused into the CE, no
    (B, S, V) logits in HBM. ``False`` keeps the materialized-logits path
    (the parity gold; also what inference uses).

    ``dropout_rng`` (a jax.random key) ACTIVATES the in-kernel dropout
    paths when ``cfg.dropout > 0`` — same contract as
    ``bert_pretrain_loss_fn``'s ``batch["dropout_rng"]``; it rides the
    batch tail positionally through ``Amp.make_train_step``
    (``step(state, tokens, None, None, rng)``). Without it the model
    runs deterministic regardless of ``cfg.dropout`` — passing a key
    with ``cfg.dropout == 0`` is therefore a config mistake and raises."""

    def loss_fn(params, tokens, segment_ids=None, positions=None,
                dropout_rng=None):
        if dropout_rng is not None and model.cfg.dropout == 0.0:
            raise ValueError("dropout_rng passed but cfg.dropout == 0 — "
                             "the key would be silently unused")
        kw = dict(segment_ids=segment_ids, positions=positions,
                  deterministic=dropout_rng is None,
                  rngs=(None if dropout_rng is None
                        else {"dropout": dropout_rng}))
        if fuse_head:
            h = model.apply({"params": params}, tokens, return_hidden=True,
                            **kw)
            w = params["wte"].astype(h.dtype)
            losses = linear_cross_entropy(
                h[:, :-1], w, tokens[:, 1:],
                num_classes=model.cfg.vocab_size)
        else:
            logits = model.apply({"params": params}, tokens, **kw)
            losses = softmax_cross_entropy_loss(
                logits[:, :-1].astype(jnp.float32), tokens[:, 1:],
                num_classes=model.cfg.vocab_size)
        if segment_ids is not None:
            from apex1_tpu.ops import masked_next_token_mean
            return masked_next_token_mean(losses, segment_ids)
        return jnp.mean(losses)

    return loss_fn
