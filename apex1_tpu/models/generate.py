"""Autoregressive generation with a functional KV cache (beyond-reference:
the reference accelerates training only; a complete framework needs the
sampling loop its users run after fine-tuning).

TPU-first design: the cache is an explicit pytree threaded through the
model (no mutable state), so the whole decode loop is ONE ``lax.scan``
inside ONE ``jit`` — token steps never return to the host, and the cache
update is an in-place ``dynamic_update_slice`` XLA aliases into the donated
carry. Prefill runs the normal flash-attention forward (filling the cache
in one pass); each decode step attends over the static-shape cache with a
position mask (S_max is static; no dynamic shapes on the MXU path).

Supported: `models.gpt2.GPT2` and `models.llama.Llama` (GQA included) via
``cache=``/``cache_index=`` on their ``__call__`` (drive with
:func:`generate` below), and `models.t5.T5` seq2seq via
:func:`t5_generate` (encode once; cached decoder self-attention with the
rel-pos bias row at the current index).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.ops import NEG_INF
from apex1_tpu.ops.attention import flash_attention
# the decode-attention composite and the sampling pipeline are owned by
# ops.paged_decode so the paged serving path and this dense reference
# path share ONE implementation (token parity is structural, not tested
# into existence); re-exported here as the documented public surface
from apex1_tpu.ops.paged_decode import (PagedCache,  # noqa: F401
                                        _temperature_top_k, cache_attend,
                                        paged_update_attend, sample_token)


def init_cache(num_layers: int, batch: int, num_kv_heads: int,
               max_len: int, head_dim: int, dtype=jnp.bfloat16):
    """Zeroed per-layer KV cache: {"layer{i}": {"k","v": (B, Hkv, S_max,
    D)}}."""
    one = lambda: {
        "k": jnp.zeros((batch, num_kv_heads, max_len, head_dim), dtype),
        "v": jnp.zeros((batch, num_kv_heads, max_len, head_dim), dtype),
    }
    return {f"layer{i}": one() for i in range(num_layers)}


def cached_attention(q, k_new, v_new, cache, cache_index, *,
                     sm_scale: Optional[float] = None, bias=None,
                     segment_ids=None, valid_start=None,
                     chunk_decode: bool = False):
    """Attention through the KV cache. ``q``/``k_new``/``v_new``:
    (B, H, S, D)/(B, Hkv, S, D) for the CURRENT tokens; ``cache`` holds
    (B, Hkv, S_max, D); ``cache_index`` is the (traced) write position.

    - Prefill (S > 1): must start from an empty cache at index 0 — runs
      the causal flash kernel over the current tokens (with ``bias``
      riding its additive-bias operand — T5's rel-pos path stays
      O(S·D)) and writes them into the cache.
    - Decode (S == 1): composite matvec attention over the cache, masked
      to positions ≤ cache_index (static S_max — no dynamic shapes).

    ``bias``: additive logit bias. For prefill, shaped over the CURRENT
    tokens (1, H, S, S) (causality comes from the kernel's causal flag,
    not the bias); for decode, the query row vs all cache slots
    (1, H, 1, S_max).

    RAGGED batches (left-padded prompts of different lengths — see
    ``generate(prompt_lens=...)``): ``segment_ids`` (B, S) rides the
    flash kernel's varlen operand at prefill so pad and real tokens
    never attend across; ``valid_start`` (B,) masks decode attention to
    cache slots ≥ each row's first real position (the left-pad K/V slots
    are garbage by construction).

    ``chunk_decode=True`` is the third mode (speculative-decoding
    verify): S > 1 NEW tokens against a NON-empty cache — query j
    attends cache positions ≤ cache_index + j (history + causal within
    the chunk), via the composite path with a per-query mask. S == 1
    decode is the chunk_decode special case. An EMPTY cache at
    ``cache_index == 0`` is also legal here (the horizon mask reduces to
    plain causal prefill) — this is the FIXED-SHAPE chunked-prefill mode
    `apex1_tpu.serving`'s engine rides: one (1, C) chunk executable
    serves every prompt length (pad the tail chunk on the RIGHT; query
    j never reaches a pad slot k > cache_index + j, and the next write
    overwrites the pad K/V before any query can see it).

    Returns (attn (B, H, S, D), new_cache_entry).
    """
    B, Hq, S, D = q.shape
    Hkv = k_new.shape[1]
    if isinstance(cache, PagedCache):
        # paged serving tier: K/V live in a shared page pool addressed
        # through the entry's block table; bias/segment_ids/valid_start
        # have no paged consumers (serving prompts are right-padded)
        if (bias is not None or segment_ids is not None
                or valid_start is not None):
            raise ValueError(
                "PagedCache attention does not support bias/"
                "segment_ids/valid_start")
        return paged_update_attend(q, k_new, v_new, cache, cache_index,
                                   sm_scale=sm_scale,
                                   chunk_decode=chunk_decode)
    idx = jnp.asarray(cache_index, jnp.int32)
    k_all = jax.lax.dynamic_update_slice(
        cache["k"], k_new.astype(cache["k"].dtype), (0, 0, idx, 0))
    v_all = jax.lax.dynamic_update_slice(
        cache["v"], v_new.astype(cache["v"].dtype), (0, 0, idx, 0))
    new_entry = {"k": k_all, "v": v_all}
    if S > 1 and not chunk_decode:
        # prefill attends only over the CURRENT tokens — valid only from
        # an empty cache. Fail fast on a concrete nonzero index (the
        # common prefill call passes a Python 0); a traced nonzero index
        # remains the documented precondition (ADVICE r3).
        if isinstance(cache_index, int) and cache_index != 0:
            raise ValueError(
                f"cached_attention prefill (S={S} > 1) requires an empty "
                f"cache at cache_index 0, got {cache_index} — it attends "
                f"only over the new tokens, so a non-empty cache would "
                f"be silently ignored")
        # prefill is always autoregressive; with bias the flash kernel's
        # additive-bias operand keeps this O(S·D) too
        attn = flash_attention(q, k_new, v_new, causal=True,
                               sm_scale=sm_scale, bias=bias,
                               segment_ids=segment_ids)
        return attn, new_entry
    attn = cache_attend(q, k_all, v_all, idx, sm_scale=sm_scale,
                        bias=bias, valid_start=valid_start)
    return attn, new_entry


def last_real_logits(logits, lengths):
    """(B, S, V) chunk logits → (B, V) at each row's LAST REAL token
    (index ``lengths[b] - 1``). The gather behind fixed-shape prefill:
    `apex1_tpu.serving`'s engine pads every prompt's tail chunk up to
    the chunk width, so the logit to sample the first token from sits
    at a per-row TRACED index, not at ``[:, -1]`` — one executable
    serves every prompt length without re-jitting per call."""
    idx = (jnp.asarray(lengths, jnp.int32) - 1).reshape(-1, 1, 1)
    return jnp.take_along_axis(logits, idx, axis=1)[:, 0]


def generate(apply_fn: Callable, params, prompt_tokens, *,
             max_new_tokens: int, cache,
             temperature: float = 0.0, top_k: Optional[int] = None,
             rng=None, eos_id: Optional[int] = None, pad_id: int = 0,
             vocab_size: Optional[int] = None, prompt_lens=None,
             cache_start: int = 0, return_cache: bool = False):
    """Prefill + single-dispatch decode loop.

    ``apply_fn(params, tokens, cache, cache_index) -> (logits, cache)``
    — the model's cached forward (see `models.gpt2`/`models.llama`
    ``cache=`` support). ``cache`` must be sized >= prompt_len +
    max_new_tokens. Returns (B, max_new_tokens) generated ids; sequences
    that emit ``eos_id`` are padded with ``pad_id`` afterwards.

    RAGGED batches: pass ``prompt_lens`` (B,) with ``prompt_tokens``
    right-padded to a common S0. TPU-first shape discipline — instead of
    per-row dynamic cache indices (a scatter per step), rows are
    LEFT-aligned once up front so every row's last real token sits at
    S0−1: the cache write index stays one scalar, decode steps stay one
    ``dynamic_update_slice``, and the pad prefix is masked out by the
    flash kernel's ``segment_ids`` at prefill and a per-row
    ``valid_start`` at decode (garbage pad K/V slots are never read).
    Each row's positions count from ITS OWN start (RoPE/learned
    positions see 0..len−1), so short rows decode exactly as if they
    were alone. Requires an ``apply_fn`` with the
    ``positions``/``segment_ids``/``valid_start`` kwargs
    (`gpt2_decoder`/`llama_decoder` provide them).

    PREFIX CACHING: ``cache_start > 0`` continues from a cache already
    holding that many positions — a shared system-prompt prefix
    prefilled ONCE via ``apply_fn(params, prefix, cache, 0)``, or the
    cache a previous ``generate(..., return_cache=True)`` handed back.
    ``prompt_tokens`` are the NEW tokens appended after it. The
    continuation prefill rides the chunk-decode attention mode (new
    tokens attend the cached prefix + their own causal prefix), so the
    shared prefix is never re-computed. Not combinable with
    ``prompt_lens``.

    ``return_cache=True`` returns ``(tokens, cache)`` — the cache after
    the final decode step. The FINAL sampled token is never fed back
    through the model, so its K/V is absent: the cache holds
    ``cache_start + S0 + max_new_tokens - 1`` positions, and a
    continuation must pass ``cache_start=cache_start + S0 +
    max_new_tokens - 1`` with the final emitted token as the FIRST
    token of its continuation prompt (see
    ``test_chained_generate_via_return_cache``). Continuing at
    ``+ max_new_tokens`` instead would leave a zero-K/V slot that
    chunk-decode attention still attends and silently drop the last
    token from context. Not combinable with ``prompt_lens``: a
    ragged-produced cache carries garbage left-pad K/V the
    continuation would attend (loud ValueError).

    The decode loop is a ``lax.scan`` — jit the whole call (e.g.
    ``jax.jit(functools.partial(generate, apply_fn, max_new_tokens=...,
    ...))``) for one-dispatch generation.
    """
    B, S0 = prompt_tokens.shape
    if rng is None:
        rng = jax.random.key(0)
    s_max = jax.tree_util.tree_leaves(cache)[0].shape[2]
    if s_max < cache_start + S0 + max_new_tokens:
        # dynamic_update_slice CLAMPS out-of-range writes: an undersized
        # cache would repeatedly overwrite its last slot and silently
        # diverge — the exact hazard speculative_generate also guards
        raise ValueError(
            f"cache holds {s_max} positions but this call needs "
            f"cache_start + prompt + max_new_tokens = "
            f"{cache_start + S0 + max_new_tokens}")
    kw = {}
    lens = None
    if return_cache and prompt_lens is not None:
        # the continuation API (cache_start, scalar positions) has no
        # channel for per-row valid_start/lens, so a ragged-produced
        # cache would be continued attending its garbage left-pad K/V
        # slots with uniformly-shifted RoPE positions — silently wrong
        # tokens for every short row. Refuse loudly (docs/serving.md
        # composition matrix: ragged x prefix-cache-production is an
        # unsupported cell).
        raise ValueError(
            "return_cache and prompt_lens cannot be combined — the "
            "returned cache's left-pad slots hold garbage K/V that a "
            "cache_start continuation would attend; produce "
            "continuation caches from dense (non-ragged) prompts")
    if cache_start:
        if prompt_lens is not None:
            raise ValueError(
                "cache_start (prefix caching) and prompt_lens (ragged "
                "batches) cannot be combined — left-aligned rows would "
                "shear against the shared cached prefix")
        kw = dict(chunk_decode=True)
    elif prompt_lens is not None:
        prompt_tokens, kw, pad = _ragged_align(prompt_tokens, prompt_lens)
        lens = S0 - pad
    logits, cache = apply_fn(params, prompt_tokens, cache, cache_start,
                             **kw)
    rng, sub = jax.random.split(rng)
    nxt = sample_token(logits[:, -1], sub, temperature=temperature,
                       top_k=top_k, vocab_size=vocab_size)
    done = jnp.zeros((B,), bool) if eos_id is None else (nxt == eos_id)

    def body(carry, _):
        tok, idx, cache, rng, done = carry
        if lens is None:
            dkw = {}
        else:
            # per-row positions continue each row's own count; the scalar
            # cache index keeps advancing uniformly past S0
            dkw = dict(positions=(lens + (idx - S0))[:, None],
                       valid_start=S0 - lens)
        logits, cache = apply_fn(params, tok[:, None], cache, idx, **dkw)
        rng, sub = jax.random.split(rng)
        new = sample_token(logits[:, -1], sub, temperature=temperature,
                           top_k=top_k, vocab_size=vocab_size)
        new = jnp.where(done, pad_id, new)
        if eos_id is not None:
            done = done | (new == eos_id)
        return (new, idx + 1, cache, rng, done), new

    (_, _, cache, _, _), rest = jax.lax.scan(
        body, (nxt, jnp.asarray(cache_start + S0, jnp.int32), cache, rng,
               done),
        None, length=max_new_tokens - 1)
    toks = jnp.concatenate([nxt[:, None], rest.T], axis=1)
    return (toks, cache) if return_cache else toks


def _ragged_align(prompt_tokens, prompt_lens):
    """LEFT-align a right-padded ragged batch and build the prefill
    masking kwargs — the shared mechanics behind ``prompt_lens`` in
    :func:`generate` AND :func:`speculative_generate` (contract
    documented on `generate`). Returns ``(aligned_tokens, prefill_kw,
    pad)`` where ``pad`` (B,) is each row's left-pad width (== its
    decode-time ``valid_start``)."""
    B, S0 = prompt_tokens.shape
    try:  # fail fast on concrete out-of-range lengths (a traced
        # lens skips the check); pad/position math below silently
        # scrambles the row otherwise
        lv = np.asarray(prompt_lens)
    except Exception:
        lv = None
    if lv is not None and ((lv < 1).any() or (lv > S0).any()):
        raise ValueError(
            f"prompt_lens must lie in [1, {S0}] (the padded prompt "
            f"width), got {lv.tolist()}")
    lens = jnp.asarray(prompt_lens, jnp.int32)
    pad = S0 - lens                             # left-pad widths (B,)
    # left-align: row b shifts right by pad_b (one gather); the
    # wrapped-in entries land in the pad region and are masked
    gidx = (jnp.arange(S0)[None, :] - pad[:, None]) % S0
    aligned = jnp.take_along_axis(prompt_tokens, gidx, axis=1)
    # pad slots get segment -1, the repo-wide padding convention
    # (`pack_documents`, xentropy's `label >= 0`): the flash kernel's
    # equality mask only needs "different from the real segment", but
    # MoE routing masks tokens with `segment_ids >= 0` — a 0-valued pad
    # would be ROUTED and claim expert capacity, silently perturbing
    # other rows' tokens (review r5)
    kw = dict(
        positions=jnp.maximum(
            jnp.arange(S0)[None, :] - pad[:, None], 0),
        segment_ids=jnp.where(
            jnp.arange(S0)[None, :] >= pad[:, None], 1, -1
        ).astype(jnp.int32),
        valid_start=pad)
    return aligned, kw, pad


def counter_sample(logits, seed, positions, *, temperature: float = 0.0,
                   top_k: Optional[int] = None,
                   vocab_size: Optional[int] = None):
    """Counter-keyed sampling over an (S, V) logits chunk: the token at
    output position ``positions[j]`` is drawn with
    ``fold_in(key(seed), positions[j])`` — the per-request counter-PRNG
    contract (`docs/serving.md` § Per-request sampling seeds) as ONE
    shared function. The serving engine's speculative verify executable
    samples the target's canonical stream through this, which is what
    makes a draft/verify round emit tokens BIT-IDENTICAL to plain
    step-decode of the same (params, prompt, seed) at any temperature —
    and therefore resubmission-safe and hedging-compatible. ``seed`` and
    ``positions`` (S,) may be traced."""
    seed = jnp.asarray(seed, jnp.int32)

    def one(lg, p):
        key = jax.random.fold_in(jax.random.key(seed), p)
        return sample_token(lg[None], key, temperature=temperature,
                            top_k=top_k, vocab_size=vocab_size)[0]

    return jax.vmap(one)(logits, jnp.asarray(positions, jnp.int32))


def _masked_probs(logits, *, temperature: float, top_k: Optional[int],
                  vocab_size: Optional[int]):
    """The probability distribution `sample_token` samples from: fp32,
    padded-vocab tail masked, then the SHARED `_temperature_top_k`
    pipeline (one implementation — a fix to the masking reaches both
    the sampler and the speculative accept rule). (..., V) logits."""
    lg = logits.astype(jnp.float32)
    V = lg.shape[-1]
    if vocab_size is not None and vocab_size < V:
        lg = jnp.where(jnp.arange(V) < vocab_size, lg, NEG_INF)
    return jax.nn.softmax(
        _temperature_top_k(lg, temperature, top_k, vocab_size), axis=-1)


def _speculative_accept(p, q, drafts, key):
    """One round of the speculative-sampling accept/resample rule
    (Leviathan et al. 2023; Chen et al. 2023): accept draft ``x_j`` with
    probability ``min(1, p_j(x_j) / q_j(x_j))``; at the first rejection
    emit a sample of the residual ``norm(max(p_j − q_j, 0))``; if all K
    accepted emit a bonus sample of ``p_K``. The emitted sequence is
    distributed EXACTLY as ancestral sampling from ``p``.

    ``p``: (K+1, V) target probs, ``q``: (K, V) draft probs, ``drafts``:
    (K,) proposed tokens. Returns ``(a, correction)`` — the accepted
    count and the token to emit at position ``a``.
    """
    K = drafts.shape[0]
    key_u, key_c = jax.random.split(key)
    j = jnp.arange(K)
    p_at = p[j, drafts]                               # p_j(x_j)
    q_at = jnp.maximum(q[j, drafts], 1e-30)           # x_j ~ q_j => > 0
    accept = jax.random.uniform(key_u, (K,)) < jnp.minimum(
        1.0, p_at / q_at)
    a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
    p_row = p[a]                                      # (V,) row a<=K
    q_row = jnp.where(a == K, 0.0, q[jnp.minimum(a, K - 1)])
    r = jnp.maximum(p_row - q_row, 0.0)               # residual (bonus:
    s = jnp.sum(r)                                    #  q_row=0 => p_K)
    r = jnp.where(s > 0, r / jnp.maximum(s, 1e-30), p_row)
    corr = jax.random.categorical(
        key_c, jnp.where(r > 0, jnp.log(jnp.maximum(r, 1e-30)),
                         NEG_INF)).astype(jnp.int32)
    return a, corr


def speculative_generate(target_fn, target_params, draft_fn, draft_params,
                         prompt_tokens, *, max_new_tokens: int,
                         target_cache, draft_cache, num_draft: int = 4,
                         temperature: float = 0.0,
                         top_k: Optional[int] = None, rng=None,
                         eos_id: Optional[int] = None, pad_id: int = 0,
                         vocab_size: Optional[int] = None,
                         prompt_lens=None):
    """Speculative decoding: a cheap DRAFT model proposes ``num_draft``
    tokens autoregressively; the TARGET model scores all of them in ONE
    chunk-verify forward (``chunk_decode=True`` — K+1 new tokens against
    its cache, causal within the chunk); the longest accepted prefix
    plus one correction token are emitted per round. The draft only
    changes how many target forwards it takes (1 per ~(accepted+1)
    tokens instead of 1 per token; decode is HBM-bound, so fewer target
    weight streams ≈ proportional speedup when the draft is much
    smaller).

    - ``temperature == 0`` (default): GREEDY — accept while the draft
      matches the target's argmax; output is TOKEN-IDENTICAL to plain
      greedy decoding of the target alone.
    - ``temperature > 0``: SPECULATIVE SAMPLING — drafts are sampled
      from the draft's (temperature/top-k) distribution and accepted by
      the `_speculative_accept` rejection rule, so the emitted sequence
      is distributed EXACTLY as ancestral sampling from the target's
      (temperature/top-k) distribution; with draft == target the
      acceptance ratio is 1 up to chunk-verify-vs-step-decode numerics
      (~1e-4 rel on logits), so essentially every proposal is
      accepted.

    TPU-first shape discipline: every round is fixed-size (K draft
    steps + one (K+1)-token verify); per-row acceptance raggedness lives
    in a ``lax.while_loop`` carried per row under ``jax.vmap`` (the
    batching rule runs until every row finishes, masking finished rows)
    — one dispatch, no host round-trips, static shapes throughout.

    ``target_fn``/``draft_fn`` take the `llama_decoder`/`gpt2_decoder`
    apply contract (incl. the ``chunk_decode`` kwarg). Caches must be
    sized >= prompt_len + max_new_tokens + num_draft + 1 (rejected
    speculative entries briefly occupy the tail before being
    overwritten).

    RAGGED batches: pass ``prompt_lens`` (B,) with ``prompt_tokens``
    right-padded to a common S0 — the same left-align contract as
    :func:`generate` (rows realigned once; per-row positions and
    ``valid_start`` thread through BOTH models' draft steps and the
    chunk-verify, so each row speculates exactly as if it were alone).
    The draft and target see identical alignment, so acceptance
    statistics are unaffected by padding.

    The draft is ANY apply_fn with the decoder contract — including the
    int8 `models.quant_decode` decoders (an int8 draft under a bf16
    target changes only acceptance rates at temperature > 0; at
    temperature 0 the output stays token-identical to the target's own
    greedy decode, whatever the draft).

    Returns (tokens (B, max_new_tokens), target_forwards (B,)) — the
    second output counts verify rounds per row (+1 prefill is implied),
    the observable the speedup comes from.
    """
    B, S0 = prompt_tokens.shape
    K = int(num_draft)
    if K < 1:
        raise ValueError(f"num_draft must be >= 1, got {K}")
    for nm, c in (("target_cache", target_cache),
                  ("draft_cache", draft_cache)):
        s_max = jax.tree_util.tree_leaves(c)[0].shape[2]
        if s_max < S0 + max_new_tokens + K + 1:
            # dynamic_update_slice CLAMPS out-of-range writes — an
            # undersized cache would silently overwrite earlier K/V and
            # diverge from target-only greedy; fail at trace time
            raise ValueError(
                f"{nm} holds {s_max} positions but speculative decoding "
                f"needs >= prompt + max_new_tokens + num_draft + 1 = "
                f"{S0 + max_new_tokens + K + 1} (rejected speculative "
                f"entries briefly occupy the tail)")

    sampled = temperature != 0.0
    if rng is None:
        rng = jax.random.key(0)

    def greedy(logits):
        # sample_token's temperature-0 path: fp32 + padded-vocab mask +
        # argmax (rng unused)
        return sample_token(logits, None, vocab_size=vocab_size)

    def probs(logits):
        return _masked_probs(logits, temperature=temperature,
                             top_k=top_k, vocab_size=vocab_size)

    # prefill both models at batch B (ordinary flash prefill); ragged
    # rows are left-aligned ONCE and both models see the same alignment
    pad = None
    pre_kw = {}
    if prompt_lens is not None:
        prompt_tokens, pre_kw, pad = _ragged_align(prompt_tokens,
                                                   prompt_lens)
    logits_t, target_cache = target_fn(target_params, prompt_tokens,
                                       target_cache, 0, **pre_kw)
    _, draft_cache = draft_fn(draft_params, prompt_tokens, draft_cache, 0,
                              **pre_kw)
    rng, sub = jax.random.split(rng)
    t0 = sample_token(logits_t[:, -1], sub, temperature=temperature,
                      top_k=top_k, vocab_size=vocab_size)
    row_keys = jax.random.split(rng, B)

    def row_loop(t0_row, cache_t_row, cache_d_row, row_key,
                 pad_row=None):
        buf0 = jnp.full((max_new_tokens,), pad_id, jnp.int32)
        buf0 = buf0.at[0].set(t0_row)
        done0 = (jnp.asarray(False) if eos_id is None
                 else (t0_row == eos_id))

        def cond(carry):
            _, count, _, _, done, _, _, _, _ = carry
            return (count < max_new_tokens) & ~done

        def body(carry):
            (buf, count, last, idx, done, cache_t, cache_d, rounds,
             key) = carry
            key, key_d, key_a = jax.random.split(key, 3)

            def dstep(c, step_key):
                tok, dc, di = c
                # ragged rows: the token at cache slot di is the row's
                # (di - pad_row)-th token; left-pad K/V slots stay masked
                dkw = ({} if pad_row is None else dict(
                    positions=(di - pad_row).reshape(1, 1),
                    valid_start=pad_row.reshape(1)))
                lg, dc = draft_fn(draft_params, tok.reshape(1, 1),
                                  jax.tree_util.tree_map(
                                      lambda x: x[None], dc), di, **dkw)
                dc = jax.tree_util.tree_map(lambda x: x[0], dc)
                if sampled:
                    q_row = probs(lg[0, -1])
                    nxt = jax.random.categorical(
                        step_key, jnp.where(
                            q_row > 0, jnp.log(jnp.maximum(q_row, 1e-30)),
                            NEG_INF)).astype(jnp.int32)
                else:
                    # greedy never divides by temperature=0 and carries
                    # no (V,)-sized scan output
                    q_row = jnp.zeros((lg.shape[-1],), jnp.float32)
                    nxt = greedy(lg[0, -1])
                return (nxt, dc, di + 1), (nxt, q_row)

            # K+1 steps, not K: the last step feeds drafts[K-1] so its
            # K/V lands in the draft cache (slot idx+K). Without it an
            # all-accept round left that slot permanently zero yet
            # attended, silently collapsing later acceptance rates (the
            # extra draft forward is the cheap model — the premise of
            # speculation)
            (_, cache_d, _), (drafts_ext, q_ext) = jax.lax.scan(
                dstep, (last, cache_d, idx),
                jax.random.split(key_d, K + 1))
            drafts = drafts_ext[:K]

            verify = jnp.concatenate([last[None], drafts])   # (K+1,)
            vkw = ({} if pad_row is None else dict(
                positions=(idx - pad_row
                           + jnp.arange(K + 1)).reshape(1, K + 1),
                valid_start=pad_row.reshape(1)))
            lg_t, cache_t = target_fn(
                target_params, verify[None],
                jax.tree_util.tree_map(lambda x: x[None], cache_t), idx,
                chunk_decode=True, **vkw)
            cache_t = jax.tree_util.tree_map(lambda x: x[0], cache_t)

            j = jnp.arange(K + 1)
            if sampled:
                a, corr = _speculative_accept(probs(lg_t[0]), q_ext[:K],
                                              drafts, key_a)
                toks = jnp.where(
                    j < a, jnp.concatenate([drafts, drafts[-1:]]),
                    corr)
            else:
                tgt_next = greedy(lg_t[0])                   # (K+1,)
                matches = (tgt_next[:K] == drafts).astype(jnp.int32)
                a = jnp.sum(jnp.cumprod(matches))  # leading agreements
                toks = jnp.where(
                    j < a, jnp.concatenate([drafts, drafts[-1:]]),
                    tgt_next)
            keep = (j <= a) & (count + j < max_new_tokens)
            if eos_id is not None:
                prior_eos = jnp.cumsum(
                    (toks == eos_id).astype(jnp.int32)) - (
                        toks == eos_id).astype(jnp.int32)
                keep = keep & (prior_eos == 0)
            # one scatter: invalid lanes are routed out of range and
            # dropped (kept indices are distinct, so no overlap)
            buf = buf.at[jnp.where(keep, count + j, max_new_tokens)].set(
                toks, mode="drop")
            n_emit = jnp.sum(keep.astype(jnp.int32))
            count = count + n_emit
            if eos_id is not None:
                done = done | jnp.any((toks == eos_id) & keep)
            last = toks[a]
            idx = idx + a + 1
            return (buf, count, last, idx, done, cache_t, cache_d,
                    rounds + 1, key)

        init = (buf0, jnp.asarray(1, jnp.int32), t0_row,
                jnp.asarray(S0, jnp.int32), done0, cache_t_row,
                cache_d_row, jnp.asarray(0, jnp.int32), row_key)
        buf, _, _, _, _, _, _, rounds, _ = jax.lax.while_loop(cond, body,
                                                              init)
        return buf, rounds

    if pad is None:
        return jax.vmap(row_loop)(t0, target_cache, draft_cache, row_keys)
    return jax.vmap(row_loop)(t0, target_cache, draft_cache, row_keys,
                              pad)

def beam_search(apply_fn: Callable, params, prompt_tokens, *,
                max_new_tokens: int, cache, num_beams: int = 4,
                length_penalty: float = 0.0,
                eos_id: Optional[int] = None, pad_id: int = 0,
                vocab_size: Optional[int] = None):
    """Beam search over the same cached decode step as :func:`generate`.

    TPU-first shape discipline: beams ride the batch axis — the cache
    and every decode step run at batch B·K (``cache`` must be built for
    batch ``B * num_beams``), and each step's beam reorder is one
    gather over that axis (XLA fuses it into the cache update). Prefill
    runs ONCE at batch B (the first B cache lanes) and the filled cache
    is tiled K-fold; the first expansion then takes the per-batch top-K
    tokens from that single distribution, one per lane.

    Scoring: sum of token log-probs over the VALID vocab (``vocab_size``
    masks padded-vocab logits BEFORE the softmax, as `sample_token`
    does). With ``length_penalty`` > 0, candidates compete at EVERY
    step on GNMT length-normalized scores ``sum / length**penalty``
    (length counts each beam's tokens up to and including its
    ``eos_id``), so a short finished hypothesis is never pruned by a
    longer unfinished one merely for having fewer summed terms; the
    carried scores stay unnormalized sums so accumulation is exact.
    ``length_penalty=0`` reduces to pure-sum ranking. Finished beams
    stop accumulating and pad with ``pad_id``. Returns
    (tokens (B, max_new_tokens), scores (B,)) for the best beam, scored
    by the same normalization.
    """
    B, S0 = prompt_tokens.shape
    K = num_beams

    def masked_logp(logits_row):
        lg = logits_row.astype(jnp.float32)
        if vocab_size is not None and vocab_size < lg.shape[-1]:
            lg = jnp.where(jnp.arange(lg.shape[-1]) < vocab_size, lg,
                           NEG_INF)
        return jax.nn.log_softmax(lg, -1)

    # prefill once at batch B on the cache's first B lanes, tile K-fold
    pre_cache = jax.tree_util.tree_map(lambda c: c[:B], cache)
    logits, pre_cache = apply_fn(params, prompt_tokens, pre_cache, 0)
    cache = jax.tree_util.tree_map(
        lambda c: jnp.repeat(c, K, axis=0), pre_cache)
    logp = masked_logp(logits[:, -1])                     # (B, V)
    V = logp.shape[-1]
    scores, nxt = jax.lax.top_k(logp, K)                  # (B, K)
    nxt = nxt.astype(jnp.int32)
    done = (jnp.zeros((B, K), bool) if eos_id is None
            else (nxt == eos_id))
    lens = jnp.ones((B, K), jnp.float32)

    # static-shape token buffer: the scan carries (B*K, max_new) and
    # writes one column per step (a growing concat would re-trace)
    toks_buf = jnp.full((B * K, max_new_tokens), pad_id, jnp.int32)
    toks_buf = toks_buf.at[:, 0].set(nxt.reshape(-1))

    def body(carry, t):
        nxt, idx, cache, scores, done, lens, buf = carry
        logits, cache = apply_fn(params, nxt.reshape(B * K, 1), cache,
                                 idx)
        logp = masked_logp(logits[:, -1]).reshape(B, K, V)
        # a finished beam proposes exactly one zero-score continuation
        # (pad) so its total never moves
        pad_row = jnp.where(jnp.arange(V) == pad_id, 0.0, NEG_INF)
        logp = jnp.where(done[..., None], pad_row, logp)
        cand = scores[..., None] + logp
        # rank on length-normalized scores (ADVICE r3: pure-sum in-beam
        # pruning under length_penalty > 0 let longer unfinished beams
        # evict shorter finished ones); carry the raw sums forward
        cand_len = (lens + jnp.where(done, 0.0, 1.0))[..., None]
        cand_rank = (cand / jnp.maximum(cand_len, 1.0) ** length_penalty
                     if length_penalty else cand)
        _, flat_idx = jax.lax.top_k(cand_rank.reshape(B, K * V), K)
        new_scores = jnp.take_along_axis(cand.reshape(B, K * V),
                                         flat_idx, axis=1)
        beam_src = flat_idx // V
        token = (flat_idx % V).astype(jnp.int32)
        gidx = (jnp.arange(B)[:, None] * K + beam_src).reshape(-1)
        cache = jax.tree_util.tree_map(lambda c: c[gidx], cache)
        done = jnp.take_along_axis(done, beam_src, axis=1)
        lens = jnp.take_along_axis(lens, beam_src, axis=1)
        buf = buf[gidx]
        # the emitted token counts toward length unless the beam had
        # already finished BEFORE this step (eos itself counts)
        lens = lens + jnp.where(done, 0.0, 1.0)
        if eos_id is not None:
            done = done | (token == eos_id)
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, token.reshape(-1), t, axis=1)
        return (token, idx + 1, cache, new_scores, done, lens,
                buf), None

    (nxt, _, cache, scores, done, lens, toks_buf), _ = jax.lax.scan(
        body, (nxt, jnp.asarray(S0, jnp.int32), cache, scores, done,
               lens, toks_buf),
        jnp.arange(1, max_new_tokens))
    norm = scores / jnp.maximum(lens, 1.0) ** length_penalty
    best = jnp.argmax(norm, axis=1)                      # (B,)
    toks = toks_buf.reshape(B, K, -1)[jnp.arange(B), best]
    return toks, jnp.take_along_axis(norm, best[:, None], 1)[:, 0]


def _decoder(model, num_kv_heads: int, head_dim: int):
    """Shared (apply_fn, make_cache) builder: both models take the same
    ``positions``/``cache``/``cache_index`` kwargs, so the cached forward
    is one code path and only the cache geometry differs. The optional
    keyword-only args carry the RAGGED (left-padded) batch masking —
    ``generate(prompt_lens=...)`` supplies them; plain calls never do."""
    cfg = model.cfg

    def apply_fn(params, tokens, cache, cache_index, *, positions=None,
                 segment_ids=None, valid_start=None, chunk_decode=False,
                 return_hidden=False):
        B, S = tokens.shape
        if positions is None:
            pos = jnp.asarray(cache_index, jnp.int32) + jnp.arange(S)
            positions = jnp.broadcast_to(pos[None], (B, S))
        # return_hidden is forwarded only when asked: models without
        # the kwarg keep working, and the serving engine's LoRA
        # epilogue path gets the pre-head hidden states it recomputes
        # the head matmul from (gpt2 and llama both support it)
        kw = {"return_hidden": True} if return_hidden else {}
        out, new_cache = model.apply(
            {"params": params}, tokens, positions=positions,
            cache=cache, cache_index=cache_index,
            segment_ids=segment_ids, valid_start=valid_start,
            chunk_decode=chunk_decode, **kw)
        return out, new_cache

    def make_cache(batch: int, max_len: int, dtype=None):
        return init_cache(cfg.num_layers, batch, num_kv_heads, max_len,
                          head_dim, dtype or cfg.policy.compute_dtype)

    return apply_fn, make_cache


def gpt2_decoder(model):
    """(apply_fn, make_cache) for `models.gpt2.GPT2`."""
    cfg = model.cfg
    return _decoder(model, cfg.num_heads, cfg.hidden_size // cfg.num_heads)


def t5_generate(model, params, enc_tokens, *, max_new_tokens: int,
                dec_start_id: int = 0, enc_pad_mask=None,
                temperature: float = 0.0, top_k: Optional[int] = None,
                rng=None, eos_id: Optional[int] = None, pad_id: int = 0,
                num_beams: int = 1, length_penalty: float = 0.0):
    """Seq2seq generation for `models.t5.T5`: encode once, then KV-cached
    decoder sampling seeded with ``dec_start_id`` (T5's decoder start =
    the pad token, id 0). Returns (B, max_new_tokens) ids. Decoder
    self-attention is cached; cross-attention recomputes K/V from the
    fixed memory each step (caching them per layer is a further
    optimization the adapter keeps out of the model).

    ``num_beams > 1`` switches to :func:`beam_search` (sampling args
    must be defaults — beam search is deterministic): the encoder still
    runs ONCE at batch B; its memory and ``enc_pad_mask`` are tiled
    K-fold for the beam lanes."""
    cfg = model.cfg
    K = num_beams
    if K > 1 and (temperature != 0.0 or top_k is not None):
        # validate BEFORE the encoder forward — a bad call must not pay
        # (or OOM on) a full encode first
        raise ValueError("beam search is deterministic — "
                         "temperature/top_k require num_beams=1")
    bound = model.bind({"params": params})
    memory = bound.encode(enc_tokens, enc_pad_mask)
    B = enc_tokens.shape[0]
    # beam lanes are b-major (b·K + k): prefill runs at batch B against
    # the UNtiled memory; decode steps run at B·K against the K-fold
    # tile (memory[:B] of the tile would be b0 repeated — wrong batch)
    memory_tiled = jnp.repeat(memory, K, axis=0) if K > 1 else memory
    mask_tiled = (jnp.repeat(enc_pad_mask, K, axis=0)
                  if K > 1 and enc_pad_mask is not None else enc_pad_mask)

    def apply_fn(params, tokens, cache, cache_index):
        pre = tokens.shape[0] == B
        mem = memory if pre else memory_tiled
        mask = enc_pad_mask if pre else mask_tiled
        return model.apply(
            {"params": params}, tokens, mem,
            enc_pad_mask=mask, cache=cache,
            cache_index=cache_index, method=model.decode)

    # 1 (start token) + max_new_tokens slots — generate() writes at
    # indices 0..prompt_len+max_new-2, but sizing to the documented
    # prompt_len + max_new_tokens contract keeps a slot of slack rather
    # than relying on the final token never being written back
    cache = init_cache(cfg.num_decoder_layers, B * K, cfg.num_heads,
                       1 + max_new_tokens, cfg.head_dim,
                       cfg.policy.compute_dtype)
    prompt = jnp.full((B, 1), dec_start_id, jnp.int32)
    if K > 1:
        toks, _ = beam_search(apply_fn, params, prompt,
                              max_new_tokens=max_new_tokens, cache=cache,
                              num_beams=K, length_penalty=length_penalty,
                              eos_id=eos_id, pad_id=pad_id)
        return toks
    return generate(apply_fn, params, prompt,
                    max_new_tokens=max_new_tokens, cache=cache,
                    temperature=temperature, top_k=top_k, rng=rng,
                    eos_id=eos_id, pad_id=pad_id)


def llama_decoder(model):
    """(apply_fn, make_cache) for `models.llama.Llama` (GQA-aware)."""
    cfg = model.cfg
    return _decoder(model, cfg.num_kv_heads, cfg.head_dim)
