"""Fused Adam/AdamW — reference ``apex/optimizers/fused_adam.py :: FusedAdam``
(kernel: ``csrc/multi_tensor_adam.cu :: AdamFunctor``).

The reference's value is launching ONE multi-tensor kernel for all params.
On TPU the jitted update over the whole pytree compiles to a handful of fused
elementwise loops (XLA does the multi-tensor batching), so the math here is
the contract: exact AdamFunctor semantics —

    ADAM_MODE_0 (adam_w_mode=True, default): decoupled weight decay
        p -= lr * (m_hat / (sqrt(v_hat) + eps) + wd * p)
    ADAM_MODE_1 (adam_w_mode=False): L2 regularization
        g = g + wd * p  before the moment updates

with optional bias correction (``bias_correction=1``): m_hat = m/(1-β1^t).

All moment math runs in fp32 regardless of grad dtype (the kernel templates
on MATH_T=float) — here grads are upcast before the moment update.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.pytree import tree_map_unzip


class FusedAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: optax.Updates      # m, fp32
    exp_avg_sq: optax.Updates   # v, fp32


def fused_adam(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
) -> optax.GradientTransformation:
    """Build the update transform. ``optimizer.step`` ≙ ``update`` + apply."""

    def init(params):
        zeros = lambda t: jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), t)
        return FusedAdamState(step=jnp.zeros([], jnp.int32),
                              exp_avg=zeros(params),
                              exp_avg_sq=zeros(params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        step = state.step + 1
        lr = learning_rate(step) if callable(learning_rate) else learning_rate

        if bias_correction:
            bc1 = 1.0 - jnp.power(jnp.float32(b1), step.astype(jnp.float32))
            bc2 = 1.0 - jnp.power(jnp.float32(b2), step.astype(jnp.float32))
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def per_param(g, p, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not adam_w_mode and weight_decay:
                g32 = g32 + weight_decay * p32
            m = b1 * m + (1.0 - b1) * g32
            v = b2 * v + (1.0 - b2) * jnp.square(g32)
            m_hat = m / bc1
            v_hat = v / bc2
            upd = m_hat / (jnp.sqrt(v_hat) + eps)
            if adam_w_mode and weight_decay:
                upd = upd + weight_decay * p32
            return (-lr * upd).astype(p.dtype), m, v

        updates, new_m, new_v = tree_map_unzip(
            per_param, 3, grads, params, state.exp_avg, state.exp_avg_sq)
        return updates, FusedAdamState(step=step, exp_avg=new_m,
                                       exp_avg_sq=new_v)

    return optax.GradientTransformation(init, update)
