"""Fused Adagrad — reference ``apex/optimizers/fused_adagrad.py ::
FusedAdagrad`` (kernel ``csrc/multi_tensor_adagrad.cu``).

    h += g²
    p -= lr * g / (sqrt(h) + eps)

``adagrad_w_mode``: decoupled weight decay (p -= lr*wd*p) instead of L2
(g += wd*p), mirroring the reference flag.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.pytree import tree_map_unzip


class FusedAdagradState(NamedTuple):
    step: jnp.ndarray
    sum_sq: optax.Updates


def fused_adagrad(
    learning_rate: optax.ScalarOrSchedule = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
) -> optax.GradientTransformation:

    def init(params):
        return FusedAdagradState(
            step=jnp.zeros([], jnp.int32),
            sum_sq=jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params")
        step = state.step + 1
        lr = learning_rate(step) if callable(learning_rate) else learning_rate

        def per_param(g, p, h):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay and not adagrad_w_mode:
                g32 = g32 + weight_decay * p32
            h = h + jnp.square(g32)
            upd = g32 / (jnp.sqrt(h) + eps)
            if weight_decay and adagrad_w_mode:
                upd = upd + weight_decay * p32
            return (-lr * upd).astype(p.dtype), h

        updates, new_h = tree_map_unzip(
            per_param, 2, grads, params, state.sum_sq)
        return updates, FusedAdagradState(step=step, sum_sq=new_h)

    return optax.GradientTransformation(init, update)
