"""LARC — reference ``apex/parallel/LARC.py :: LARC``.

Layer-wise Adaptive Rate Clipping: wraps any optimizer; before the wrapped
step, per-parameter gradients are rescaled by an adaptive local LR

    local_lr = trust_coefficient * ||p|| / (||g|| + wd * ||p|| + eps)

- ``clip=True`` (LARC): effective lr = min(local_lr / global_lr, 1) — the
  adaptive rate CLIPS the global schedule. Implemented, as in the reference,
  by scaling the gradient so the wrapped optimizer's lr*g gives the clipped
  step.
- ``clip=False`` (LARS): gradient scaled by local_lr directly.

The reference mutates ``p.grad`` in-place then restores weight-decay
bookkeeping; functionally this is an ``optax``-style gradient pre-transform
chained before the inner optimizer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def larc(
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    learning_rate: optax.ScalarOrSchedule | None = None,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Gradient pre-transform; chain as
    ``optax.chain(larc(..., learning_rate=lr, weight_decay=wd),
    fused_sgd(lr, weight_decay=wd))``.
    ``learning_rate`` is needed only for ``clip=True`` (to form the ratio
    against the global schedule, as the reference divides by ``group['lr']``);
    ``weight_decay`` must match the wrapped optimizer's so the denominator
    ``||g|| + wd*||p||`` matches the reference (which reads it from the
    param group)."""

    def init(params):
        del params
        return jnp.zeros([], jnp.int32)  # step count (for lr schedules)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("larc requires params")
        step = state + 1
        if clip:
            if learning_rate is None:
                raise ValueError("clip=True requires learning_rate")
            lr = (learning_rate(step) if callable(learning_rate)
                  else learning_rate)

        def per_param(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            local_lr = trust_coefficient * p_norm / (
                g_norm + weight_decay * p_norm + eps)
            # reference guards: only adapt when both norms are nonzero
            ok = (p_norm > 0) & (g_norm > 0)
            if clip:
                factor = jnp.minimum(local_lr / lr, 1.0)
            else:
                factor = local_lr
            factor = jnp.where(ok, factor, 1.0)
            return (g32 * factor).astype(g.dtype)

        return (jax.tree_util.tree_map(per_param, grads, params), step)

    return optax.GradientTransformation(init, update)


# reference name parity: ``apex.parallel.LARC.LARC`` is a wrapper
# class; here the same math is an optax transform — same knobs
LARC = larc
