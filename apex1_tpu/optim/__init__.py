"""Fused optimizers — reference ``apex/optimizers`` + ``apex/contrib/clip_grad``
+ ``apex/parallel/LARC.py``.

Each optimizer is an ``optax.GradientTransformation`` whose update math is
bit-faithful to the corresponding ``csrc/multi_tensor_*.cu`` functor (moments
in fp32, same weight-decay modes and flags). The multi-tensor "one kernel for
all params" property is XLA's job here: the jitted update over the whole
pytree compiles to a few fused loops.

A thin class facade (`Optimizer`) provides the torch-like
``opt.step(grads, params)`` shape for users porting from the reference.
"""

from __future__ import annotations

from typing import Any

import optax

from apex1_tpu.optim.fused_adam import fused_adam, FusedAdamState  # noqa: F401
from apex1_tpu.optim.fused_lamb import fused_lamb, FusedLAMBState  # noqa: F401
from apex1_tpu.optim.fused_sgd import fused_sgd, FusedSGDState  # noqa: F401
from apex1_tpu.optim.fused_novograd import (  # noqa: F401
    fused_novograd, FusedNovoGradState)
from apex1_tpu.optim.fused_adagrad import (  # noqa: F401
    fused_adagrad, FusedAdagradState)
from apex1_tpu.optim.larc import larc  # noqa: F401
from apex1_tpu.optim.clip_grad import (  # noqa: F401
    clip_grad_norm, clip_grad_norm as clip_grad_norm_)


class Optimizer:
    """Torch-shaped facade over a GradientTransformation.

    ``opt = FusedAdam(lr=1e-3); state = opt.init(params);
    params, state = opt.step(grads, state, params)``
    """

    def __init__(self, tx: optax.GradientTransformation):
        self.tx = tx

    def init(self, params):
        return self.tx.init(params)

    def update(self, grads, state, params):
        return self.tx.update(grads, state, params)

    def step(self, grads, state, params):
        updates, new_state = self.tx.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state


def FusedAdam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
              adam_w_mode=True, bias_correction=True, **_ignored: Any):
    """Reference-signature constructor (``fused_adam.py :: FusedAdam``)."""
    return Optimizer(fused_adam(lr, betas[0], betas[1], eps, weight_decay,
                                adam_w_mode, bias_correction))


def FusedLAMB(lr=1e-3, betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
              bias_correction=True, max_grad_norm=1.0, use_nvlamb=False,
              **_ignored: Any):
    return Optimizer(fused_lamb(lr, betas[0], betas[1], eps, weight_decay,
                                bias_correction, max_grad_norm, use_nvlamb))


def FusedSGD(lr=1e-3, momentum=0.0, dampening=0.0, weight_decay=0.0,
             nesterov=False, wd_after_momentum=False, **_ignored: Any):
    return Optimizer(fused_sgd(lr, momentum, dampening, weight_decay,
                               nesterov, wd_after_momentum))


def FusedNovoGrad(lr=1e-3, betas=(0.95, 0.98), eps=1e-8, weight_decay=0.0,
                  grad_averaging=True, init_zero=False, norm_type=2,
                  bias_correction=True, **_ignored: Any):
    return Optimizer(fused_novograd(lr, betas[0], betas[1], eps, weight_decay,
                                    grad_averaging, init_zero, norm_type,
                                    bias_correction))


def FusedAdagrad(lr=1e-2, eps=1e-10, weight_decay=0.0, adagrad_w_mode=False,
                 **_ignored: Any):
    return Optimizer(fused_adagrad(lr, eps, weight_decay, adagrad_w_mode))
