"""Fused momentum SGD — reference ``apex/optimizers/fused_sgd.py :: FusedSGD``
(kernel ``csrc/multi_tensor_sgd_kernel.cu :: SGDFunctor``).

torch-SGD semantics preserved (the reference is a drop-in ``torch.optim.SGD``):

    g = g + wd * p
    buf = momentum * buf + (1 - dampening) * g     (buf := g on first step)
    g = g + momentum * buf   if nesterov else buf
    p -= lr * g

``wd_after_momentum`` (reference ctor flag) applies weight decay to the
post-momentum update instead.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.pytree import tree_map_unzip


class FusedSGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: optax.Updates


def fused_sgd(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    wd_after_momentum: bool = False,
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("nesterov requires momentum > 0 and dampening == 0")

    def init(params):
        return FusedSGDState(
            step=jnp.zeros([], jnp.int32),
            momentum_buf=jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params")
        step = state.step + 1
        lr = learning_rate(step) if callable(learning_rate) else learning_rate
        first = state.step == 0

        def per_param(g, p, buf):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay and not wd_after_momentum:
                g32 = g32 + weight_decay * p32
            if momentum:
                new_buf = jnp.where(first, g32,
                                    momentum * buf + (1.0 - dampening) * g32)
                d = g32 + momentum * new_buf if nesterov else new_buf
            else:
                new_buf = buf
                d = g32
            if weight_decay and wd_after_momentum:
                d = d + weight_decay * p32
            return (-lr * d).astype(p.dtype), new_buf

        updates, bufs = tree_map_unzip(
            per_param, 2, grads, params, state.momentum_buf)
        return updates, FusedSGDState(step=step, momentum_buf=bufs)

    return optax.GradientTransformation(init, update)
