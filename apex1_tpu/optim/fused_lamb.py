"""Fused LAMB — reference ``apex/optimizers/fused_lamb.py :: FusedLAMB``
(kernels: ``csrc/multi_tensor_lamb.cu :: LAMBStage1Functor/LAMBStage2Functor``,
norms via ``multi_tensor_l2norm``).

Reference structure, preserved exactly:
  pass 1 — ``multi_tensor_l2norm`` computes the GLOBAL grad norm (and
           per-tensor norms);
  stage 1 — scaled_grad = grad / max(1, global_norm / max_grad_norm);
           m, v moment updates (bias-corrected); per-param update
           u = m_hat / (sqrt(v_hat) + eps) + wd * p
  stage 2 — trust ratio: r = ||p|| / ||u|| where both norms > 0 else 1;
           with ``use_nvlamb`` the ratio applies even when wd == 0
           (otherwise params with no weight decay skip adaptation);
           p -= lr * r * u

Here pass 1/stage 1/stage 2 are one traced function; XLA fuses the norm
reductions with the elementwise update (same no-extra-pass property the
two-kernel CUDA split was buying).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.pytree import global_norm, tree_map_unzip


class FusedLAMBState(NamedTuple):
    step: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates


def fused_lamb(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
) -> optax.GradientTransformation:

    def init(params):
        zeros = lambda t: jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), t)
        return FusedLAMBState(step=jnp.zeros([], jnp.int32),
                              exp_avg=zeros(params),
                              exp_avg_sq=zeros(params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        step = state.step + 1
        lr = learning_rate(step) if callable(learning_rate) else learning_rate

        # pass 1: global grad-norm clip factor
        gnorm = global_norm(grads)
        clip = jnp.maximum(jnp.float32(1.0), gnorm / max_grad_norm)

        if bias_correction:
            bc1 = 1.0 - jnp.power(jnp.float32(b1), step.astype(jnp.float32))
            bc2 = 1.0 - jnp.power(jnp.float32(b2), step.astype(jnp.float32))
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def stage12(g, p, m, v):
            g32 = g.astype(jnp.float32) / clip
            p32 = p.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g32
            v = b2 * v + (1.0 - b2) * jnp.square(g32)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                u = u + weight_decay * p32
            # stage 2: layerwise trust ratio
            if weight_decay or use_nvlamb:
                w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
                u_norm = jnp.sqrt(jnp.sum(jnp.square(u)))
                ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                                  w_norm / u_norm, 1.0)
            else:
                ratio = jnp.float32(1.0)
            return (-lr * ratio * u).astype(p.dtype), m, v

        updates, new_m, new_v = tree_map_unzip(
            stage12, 3, grads, params, state.exp_avg, state.exp_avg_sq)
        return updates, FusedLAMBState(step=step, exp_avg=new_m,
                                       exp_avg_sq=new_v)

    return optax.GradientTransformation(init, update)
