"""Fused NovoGrad — reference ``apex/optimizers/fused_novograd.py ::
FusedNovoGrad`` (kernel ``csrc/multi_tensor_novograd.cu``).

NovoGrad = Adam with a PER-TENSOR (layer-wise) second moment:

    v_t   = β2 * v + (1-β2) * ||g||²        (scalar per tensor;
                                             init ||g||² on first step, or 0
                                             with ``init_zero``)
    g'    = g / (sqrt(v_t) + eps) + wd * p  (``reg_inside_moment``)
    m_t   = β1 * m + c * g'                 (c = 1-β1 if grad_averaging else 1)
    p    -= lr * m_hat

``norm_type`` 2 (L2) supported; the reference also allows inf-norm.
Bias correction follows the reference's ``bias_correction`` flag applied to
both moments.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.pytree import tree_map_unzip


class FusedNovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: optax.Updates        # m, per-element fp32
    exp_avg_sq: optax.Updates     # v, ONE fp32 scalar per tensor


def fused_novograd(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    b1: float = 0.95,
    b2: float = 0.98,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    init_zero: bool = False,
    norm_type: int = 2,
    bias_correction: bool = True,
) -> optax.GradientTransformation:
    if norm_type not in (2, float("inf")):
        raise ValueError("norm_type must be 2 or inf")

    def tensor_norm_sq(g):
        if norm_type == 2:
            return jnp.sum(jnp.square(g))
        return jnp.square(jnp.max(jnp.abs(g)))

    def init(params):
        return FusedNovoGradState(
            step=jnp.zeros([], jnp.int32),
            exp_avg=jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params),
            exp_avg_sq=jax.tree_util.tree_map(
                lambda p: jnp.zeros([], jnp.float32), params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params")
        step = state.step + 1
        lr = learning_rate(step) if callable(learning_rate) else learning_rate
        first = state.step == 0
        if bias_correction:
            bc1 = 1.0 - jnp.power(jnp.float32(b1), step.astype(jnp.float32))
            bc2 = 1.0 - jnp.power(jnp.float32(b2), step.astype(jnp.float32))
        else:
            bc1 = bc2 = jnp.float32(1.0)
        c = (1.0 - b1) if grad_averaging else 1.0

        def per_param(g, p, m, v):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            nsq = tensor_norm_sq(g32)
            v_init = jnp.float32(0.0) if init_zero else nsq
            new_v = jnp.where(first, v_init, b2 * v + (1.0 - b2) * nsq)
            denom = jnp.sqrt(new_v / bc2) + eps
            gp = g32 / denom
            if weight_decay:
                gp = gp + weight_decay * p32
            new_m = b1 * m + c * gp
            return (-lr * (new_m / bc1)).astype(p.dtype), new_m, new_v

        updates, new_m, new_v = tree_map_unzip(
            per_param, 3, grads, params, state.exp_avg, state.exp_avg_sq)
        return updates, FusedNovoGradState(step=step, exp_avg=new_m,
                                           exp_avg_sq=new_v)

    return optax.GradientTransformation(init, update)
