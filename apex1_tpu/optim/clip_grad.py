"""Fused gradient clipping — reference ``apex/contrib/clip_grad/clip_grad.py
:: clip_grad_norm_`` (drop-in ``torch.nn.utils.clip_grad_norm_`` built on
``multi_tensor_l2norm`` + ``multi_tensor_scale``).

Functional form: returns (clipped_grads, total_norm). The norm reduction and
the scale are fused by XLA into the surrounding step, matching the two fused
kernels of the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex1_tpu.core.pytree import global_norm, tree_scale


def clip_grad_norm(grads, max_norm: float, *, eps: float = 1e-6):
    """Clip the global L2 norm of ``grads`` to ``max_norm``.

    Unlike the torch API this cannot mutate in place; use the returned tree.
    ``total_norm`` is returned unclipped (reference return value).
    """
    total_norm = global_norm(grads)
    scale = jnp.minimum(jnp.float32(1.0), max_norm / (total_norm + eps))
    return tree_scale(grads, scale), total_norm
