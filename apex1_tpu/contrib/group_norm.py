"""Fast NHWC GroupNorm (+ SiLU fusion) — reference
``apex/contrib/group_norm/group_norm.py :: GroupNorm`` (+ csrc
``group_norm``, tuned for diffusion-model shapes).

TPU-native: NHWC is already the TPU conv layout; the normalize +
affine + SiLU chain is one XLA fusion over a two-pass moment reduction.
``act="silu"`` mirrors the reference's fused-activation flag."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def group_norm(x, num_groups: int, gamma=None, beta=None, *,
               eps: float = 1e-5, act: Optional[str] = None):
    """``x``: (..., C) channel-last; stats over (spatial..., C/G)."""
    C = x.shape[-1]
    if C % num_groups:
        raise ValueError(f"channels {C} not divisible by groups "
                         f"{num_groups}")
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    xg = xf.reshape(x.shape[0], -1, num_groups, C // num_groups)
    mean = jnp.mean(xg, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=(1, 3), keepdims=True)
    y = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(xf.shape)
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    elif act not in (None, "none"):
        raise ValueError(f"unsupported act {act!r}")
    return y.astype(orig_dtype)


class GroupNorm(nn.Module):
    """Module form, ``apex.contrib.group_norm.GroupNorm(num_groups,
    num_channels, eps, affine, act)``."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        gamma = beta = None
        if self.affine:
            gamma = self.param("weight", nn.initializers.ones,
                               (self.num_channels,), jnp.float32)
            beta = self.param("bias", nn.initializers.zeros,
                              (self.num_channels,), jnp.float32)
        return group_norm(x, self.num_groups, gamma, beta, eps=self.eps,
                          act=self.act)
