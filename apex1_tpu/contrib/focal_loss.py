"""Fused focal loss — reference ``apex/contrib/focal_loss/focal_loss.py``
(+ ``apex/contrib/csrc/focal_loss``, detection/RetinaNet lineage).

Sigmoid focal loss FL(p_t) = -α_t (1-p_t)^γ log(p_t) over per-class
logits, computed in one traced region (XLA fuses the sigmoid/log1p/power
chain — the reference needed a kernel to avoid five eager launches).
Numerically stable via log-sigmoid identities; ``label_smoothing`` as in
the reference kernel."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def focal_loss(logits, targets, *, num_classes: int | None = None,
               alpha: float = 0.25, gamma: float = 2.0,
               label_smoothing: float = 0.0, reduction: str = "sum"):
    """``logits``: (..., C); ``targets``: (...,) int class ids, or (..., C)
    {0,1} one-hot/multi-label floats. Class id < 0 ≙ background-only row
    (all-negative, as anchors with no assignment)."""
    C = logits.shape[-1]
    if num_classes is not None and num_classes != C:
        raise ValueError(f"num_classes={num_classes} != logits C={C}")
    x = logits.astype(jnp.float32)
    if targets.ndim == x.ndim - 1:
        t = jax.nn.one_hot(targets, C, dtype=jnp.float32)
    else:
        t = targets.astype(jnp.float32)
    if label_smoothing:
        t = t * (1.0 - label_smoothing) + 0.5 * label_smoothing
    p = jax.nn.sigmoid(x)
    # stable CE pieces: log(p) = -softplus(-x), log(1-p) = -softplus(x)
    ce_pos = jax.nn.softplus(-x)
    ce_neg = jax.nn.softplus(x)
    loss = (t * alpha * jnp.power(1.0 - p, gamma) * ce_pos
            + (1.0 - t) * (1.0 - alpha) * jnp.power(p, gamma) * ce_neg)
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "none":
        return loss
    raise ValueError(f"unknown reduction {reduction!r}")
