"""``apex.contrib.xentropy.SoftmaxCrossEntropyLoss`` — class-shaped parity
wrapper over the fused kernel in `apex1_tpu.ops.xentropy`.

Reference: ``apex/contrib/xentropy/softmax_xentropy.py ::
SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing, padding_idx,
half_to_float)``.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex1_tpu.ops.xentropy import softmax_cross_entropy_loss


class SoftmaxCrossEntropyLoss:
    """Callable/``apply``-style wrapper; returns per-token losses
    (reduce yourself, as the reference does)."""

    def __init__(self, smoothing: float = 0.0,
                 padding_idx: int | None = None):
        self.smoothing = smoothing
        self.padding_idx = padding_idx

    def __call__(self, logits, labels):
        return softmax_cross_entropy_loss(
            logits, labels, smoothing=self.smoothing,
            padding_idx=self.padding_idx)

    @staticmethod
    def apply(logits, labels, smoothing: float = 0.0,
              padding_idx: int | None = None,
              half_to_float: bool = False):
        if half_to_float:
            logits = logits.astype(jnp.float32)
        return softmax_cross_entropy_loss(
            logits, labels, smoothing=smoothing, padding_idx=padding_idx)
