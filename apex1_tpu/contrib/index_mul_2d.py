"""index_mul_2d — reference ``apex/contrib/index_mul_2d`` (+ csrc;
OpenFold/protein workloads): ``out[i] = in1[idx[i]] * in2[i]`` fused
gather-multiply with hand-written bwd kernels (scatter-add for d_in1).

TPU-native: one jnp expression — XLA fuses the gather into the multiply,
and AD emits the same scatter-add the reference hand-writes. Provided for
API parity; gradient correctness is covered by tests."""

from __future__ import annotations

import jax.numpy as jnp


def index_mul_2d(in1, in2, idx):
    """``in1``: (N, D); ``in2``: (M, D); ``idx``: (M,) int into N.
    Returns (M, D) = in1[idx] * in2."""
    if in2.shape[0] != idx.shape[0]:
        raise ValueError(f"in2 rows {in2.shape[0]} != idx len "
                         f"{idx.shape[0]}")
    return jnp.take(in1, idx, axis=0) * in2
