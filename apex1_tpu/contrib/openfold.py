"""OpenFold kernels — reference ``apex/contrib/openfold_triton/`` (the one
*Triton* component of the reference: ``_layer_norm_*.py`` fwd/bwd LN,
``mha.py :: _attention_core`` (softmax(s·q·kᵀ + bias₁ + bias₂)·v with
sigmoid gating), ``fused_adam_swa.py``, and the DAP — dynamic axial
parallelism — host glue).

TPU-native mapping: the LN capability IS ``ops.layer_norm`` (same Pallas
kernel as the core FusedLayerNorm); the Evoformer attention core is the
pair-bias attention below (two additive biases — XLA fuses the bias adds
into the softmax; for long sequences the flash kernel can't take dense
pair biases, which matches the reference: its triton MHA also materializes
the (…, S, S) bias); SwiGLU is an XLA one-fusion composite; DAP ≙
``parallel.halo``/``parallel.ring_attention`` over a mesh axis.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from apex1_tpu.ops import NEG_INF
from apex1_tpu.ops import layer_norm as _layer_norm_op
from apex1_tpu.ops.softmax import scaled_masked_softmax

__all__ = ["layer_norm", "attention_core", "swiglu", "swish"]


def layer_norm(x, gamma, beta, *, eps: float = 1e-5):
    """``openfold_triton._layer_norm_config :: LayerNormSmallShapeOptImpl``
    capability — dispatches to the framework LN kernel (Pallas on TPU)."""
    return _layer_norm_op(x, gamma, beta, eps=eps)


def attention_core(q, k, v, *, bias1=None, bias2=None, mask=None,
                   gate=None, sm_scale: Optional[float] = None):
    """Evoformer attention — ``openfold_triton/mha.py :: _attention_core``:

        out = softmax(scale·q·kᵀ [+ bias1] [+ bias2] [+ mask·-inf]) · v
        [out = out * sigmoid(gate)]            (row-gating, MSA attention)

    Shapes: ``q``/``k``/``v`` (..., H, S, D); ``bias1`` broadcastable to
    (..., 1, 1, S) (MSA row mask bias), ``bias2`` to (..., 1, S, S)
    (pair bias); ``mask`` boolean, True = attend. fp32 softmax.
    """
    scale = (1.0 / math.sqrt(q.shape[-1]) if sm_scale is None
             else float(sm_scale))
    s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                   k.astype(jnp.float32))
    # fold both biases and the boolean mask (True = attend) into ONE
    # additive mask consumed inside the softmax kernel — keeps broadcast
    # dims size-1 into the kernel instead of materializing a biased
    # (..., H, S, S) score copy on the XLA side of the kernel boundary
    add = None
    for b in (bias1, bias2):
        if b is not None:
            b = b.astype(jnp.float32)
            add = b if add is None else add + b
    if mask is not None:
        neg = jnp.where(mask, 0.0, NEG_INF).astype(jnp.float32)
        add = neg if add is None else add + neg
    p = scaled_masked_softmax(s, add, scale=scale)
    out = jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)
    if gate is not None:
        out = out * jax.nn.sigmoid(gate.astype(out.dtype))
    return out


def swish(x):
    """SiLU — ``openfold_triton/swish.py`` capability (XLA fuses it into
    the surrounding matmul epilogue; no kernel needed on TPU)."""
    return jax.nn.silu(x)


def swiglu(x, w_gate, w_up, w_down):
    """Gated-SiLU MLP: ``silu(x·Wg) ⊙ (x·Wu) · Wd`` — one XLA fusion
    group between the three matmuls."""
    return (jax.nn.silu(x @ w_gate) * (x @ w_up)) @ w_down
