"""``apex.contrib`` facade — the reference's optional production
components, re-exported under their reference names so users of
``apex.contrib.*`` find the same surface here (SURVEY.md Appendix B).

Implementations live where they belong in the TPU-native layout
(`apex1_tpu.ops`, `apex1_tpu.optim`, `apex1_tpu.parallel`); this package
binds them to the reference's import paths:

- ``contrib.fmha``             → `apex1_tpu.ops.attention.fmha`
- ``contrib.multihead_attn``   → `SelfMultiheadAttn`, `EncdecMultiheadAttn`
- ``contrib.xentropy``         → `SoftmaxCrossEntropyLoss`
- ``contrib.clip_grad``        → `clip_grad_norm_`
- ``contrib.optimizers``       → `distributed_fused_adam` (ZeRO-style)

- ``contrib.sparsity``        → `ASP`, `permutation_search` (masks +
  accuracy-preserving channel-permutation search; the 2:4 *speedup* is
  N/A on TPU — no sparse MXU mode — see docs/ops.md)

Documented N/A on TPU (SURVEY.md §2.3): ``nccl_allocator`` (NVLS/SHARP),
``peer_memory`` (CUDA IPC — superseded by ICI collectives).
"""

from apex1_tpu.contrib import openfold  # noqa: F401
from apex1_tpu.contrib.focal_loss import focal_loss  # noqa: F401
from apex1_tpu.contrib.group_norm import GroupNorm, group_norm  # noqa: F401
from apex1_tpu.contrib.index_mul_2d import index_mul_2d  # noqa: F401
from apex1_tpu.contrib.multihead_attn import (  # noqa: F401
    EncdecMultiheadAttn, SelfMultiheadAttn)
from apex1_tpu.contrib.sparsity import (  # noqa: F401
    ASP, compute_m4n2_mask, permutation_search)
from apex1_tpu.contrib.transducer import (  # noqa: F401
    TransducerJoint, TransducerLoss, transducer_joint, transducer_loss)
from apex1_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss  # noqa: F401
from apex1_tpu.ops.attention import fmha  # noqa: F401
from apex1_tpu.optim.clip_grad import (  # noqa: F401
    clip_grad_norm as clip_grad_norm_)
from apex1_tpu.parallel.distributed_optimizer import (  # noqa: F401
    distributed_fused_adam, distributed_fused_lamb)
from apex1_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm as GroupBatchNorm2d)  # groupbn/cudnn_gbn capability:
# NHWC (channel-last default here) BN with cross-replica "group" stats —
# reference ``apex/contrib/groupbn :: BatchNorm2d_NHWC`` /
# ``cudnn_gbn :: GroupBatchNorm2d``; use ``group_size`` for subgroup stats.
