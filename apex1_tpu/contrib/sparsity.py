"""ASP (2:4 structured sparsity) — reference ``apex/contrib/sparsity/
asp.py :: ASP``, ``sparse_masklib.py``, ``permutation_search_kernels``.

**Speedup documented N/A on TPU** (SURVEY.md §2.3 row 47): the
reference's speed value is NVIDIA Ampere's 2:4 sparse tensor cores —
hardware the TPU MXU does not have, so pruning to the 2:4 pattern buys
no TPU speedup. The ACCURACY machinery is provided in full: mask
computation, train-with-frozen-sparsity re-application, and the
channel-permutation search (``permutation_search`` ≙ the reference's
``permutation_search_kernels``: permute input channels so the 2:4
pattern retains more magnitude — the accuracy-preserving half of ASP).
The search is the reference's greedy channel-swap strategy, vectorized
as dense XLA ops (an all-pairs swap-gain tensor per iteration) instead
of CUDA kernels.

The reference physically permutes adjacent layers to compensate; that
model-surgery step stays with the caller (same as the reference's
offline flow), with the returned permutation as the contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def compute_m4n2_mask(w) -> jnp.ndarray:
    """2:4 mask along the last dim: keep the 2 largest-|w| of each group
    of 4 (``sparse_masklib :: m4n2_1d`` pattern)."""
    if w.shape[-1] % 4:
        raise ValueError("last dim must be a multiple of 4 for 2:4")
    groups = w.reshape(*w.shape[:-1], -1, 4)
    ranks = jnp.argsort(jnp.argsort(-jnp.abs(groups), axis=-1), axis=-1)
    return (ranks < 2).reshape(w.shape)


def mask_efficacy(w, mask=None) -> jnp.ndarray:
    """|w| retained by the 2:4 mask / total |w| — the quantity the
    permutation search maximizes."""
    if mask is None:
        mask = compute_m4n2_mask(w)
    aw = jnp.abs(w)
    return jnp.sum(aw * mask) / jnp.sum(aw)


@jax.jit
def _swap_gains(aw_perm):
    """All-pairs column-swap gain matrix for the 2:4 retained magnitude.

    ``aw_perm``: (R, C) |w| with columns in the CURRENT permutation order;
    groups are consecutive 4-column stripes. Returns (C, C) ``gain`` where
    ``gain[i, j]`` is the change in total retained magnitude from swapping
    columns at permuted positions i and j (same-group pairs are 0).

    Per (group g, slot m): with the slot's column removed, sort the 3
    remaining values per row as a ≤ b ≤ c; for a replacement value x the
    top-2 sum of {a, b, c, x} is ``max(c, x) + max(b, min(c, x))`` — an
    elementwise formula, so the whole (G, 4, R, C) candidate space is a
    few fused max/min ops instead of per-candidate sorts (the vectorized
    form of the reference's per-swap CUDA evaluation).
    """
    R, C = aw_perm.shape
    G = C // 4
    g_vals = aw_perm.T.reshape(G, 4, R)                 # (G, 4, R)
    top2 = jnp.sum(jnp.sort(g_vals, axis=1)[:, 2:], axis=1)   # (G, R)
    q_cur = jnp.sum(top2, axis=1)                       # (G,)

    # remaining-3 statistics per (g, slot): b = 2nd largest, c = largest
    idx = jnp.arange(4)
    keep = idx[None, :] != idx[:, None]                 # (slot, member)
    rem = jnp.where(keep[None, :, :, None], g_vals[:, None, :, :],
                    0.0)                                # (G, 4slot, 4, R)
    rem_sorted = jnp.sort(rem, axis=2)                  # zeros sort first
    b3, c3 = rem_sorted[:, :, 2], rem_sorted[:, :, 3]   # (G, 4, R)

    # Q of group g with slot m replaced by column x, for every column x
    x = aw_perm                                          # (R, C)
    b3e, c3e = b3[..., None], c3[..., None]              # (G, 4, R, 1)
    top2_rep = (jnp.maximum(c3e, x) +
                jnp.maximum(b3e, jnp.minimum(c3e, x)))   # (G, 4, R, C)
    q_rep = jnp.sum(top2_rep, axis=2)                    # (G, 4, C)

    # dq[i, j] = gain on i's group from replacing column i with column j
    dq = (q_rep - q_cur[:, None, None]).reshape(C, C)
    gain = dq + dq.T
    same_group = (jnp.arange(C)[:, None] // 4) == (jnp.arange(C)[None] // 4)
    return jnp.where(same_group, 0.0, gain)


def permutation_search(w, *, max_swaps: int = 256, tol: float = 1e-6):
    """Greedy channel-permutation search — reference
    ``permutation_search_kernels`` (``Exhaustive_Search``/channel-swap
    strategy). Returns ``(perm, mask, efficacy)``:

    - ``perm``: int array (C,), the input-channel order that maximizes the
      magnitude retained by the 2:4 pattern (apply to this weight's
      columns AND compensate in the producing layer, as the reference's
      offline flow does);
    - ``mask``: boolean mask in the ORIGINAL column order implementing the
      permuted 2:4 pattern (usable directly by :class:`ASP`);
    - ``efficacy``: retained/total |w| under the permuted mask.

    Greedy: evaluate the all-pairs swap-gain matrix, apply the best swap,
    repeat until no swap improves by more than ``tol`` (or ``max_swaps``).
    """
    if w.ndim != 2 or w.shape[-1] % 4:
        raise ValueError("permutation_search expects (rows, cols) with "
                         "cols a multiple of 4")
    aw = jnp.abs(jnp.asarray(w, jnp.float32))
    C = aw.shape[1]
    perm = np.arange(C)
    for _ in range(max_swaps):
        gain = np.asarray(_swap_gains(aw[:, perm]))
        i, j = np.unravel_index(np.argmax(gain), gain.shape)
        if gain[i, j] <= tol:
            break
        perm[i], perm[j] = perm[j], perm[i]
    perm = jnp.asarray(perm)
    mask_permuted = compute_m4n2_mask(jnp.asarray(w)[:, perm])
    inv = jnp.argsort(perm)
    mask = mask_permuted[:, inv]
    return perm, mask, mask_efficacy(jnp.asarray(w), mask)


class ASP:
    """Mask bookkeeping: ``compute_sparse_masks(params)`` then
    ``apply_masks(params)`` after each optimizer step (the reference
    monkey-patches ``optimizer.step``; here call it in your train step —
    one fused multiply under jit).

    No TPU speedup is claimed — see module docstring."""

    def __init__(self, mask_fn=compute_m4n2_mask):
        self.mask_fn = mask_fn
        self.masks = None

    def compute_sparse_masks(self, params, *, predicate=None):
        predicate = predicate or (
            lambda path, x: jnp.ndim(x) >= 2 and x.shape[-1] % 4 == 0)
        self.masks = {
            jax.tree_util.keystr(p): self.mask_fn(x)
            for p, x in jax.tree_util.tree_flatten_with_path(params)[0]
            if predicate(p, x)}
        return self.masks

    def apply_masks(self, params):
        if self.masks is None:
            raise RuntimeError("call compute_sparse_masks first")
        masks = self.masks

        def mask_leaf(path, x):
            m = masks.get(jax.tree_util.keystr(path))
            return x if m is None else x * m.astype(x.dtype)

        return jax.tree_util.tree_map_with_path(mask_leaf, params)
