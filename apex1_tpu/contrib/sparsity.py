"""ASP (2:4 structured sparsity) — reference ``apex/contrib/sparsity/
asp.py :: ASP``, ``sparse_masklib.py``, ``permutation_search_kernels``.

**Documented N/A on TPU** (SURVEY.md §2.3 row 47): the reference's value
is NVIDIA Ampere's 2:4 sparse tensor cores — hardware the TPU MXU does
not have, so pruning to the 2:4 pattern buys no TPU speedup. The MASKING
capability (train-with-frozen-sparsity, mask re-applied after each
optimizer step) is still provided for model-portability experiments; the
permutation search and the speedup expectation are not.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def compute_m4n2_mask(w) -> jnp.ndarray:
    """2:4 mask along the last dim: keep the 2 largest-|w| of each group
    of 4 (``sparse_masklib :: m4n2_1d`` pattern)."""
    if w.shape[-1] % 4:
        raise ValueError("last dim must be a multiple of 4 for 2:4")
    groups = w.reshape(*w.shape[:-1], -1, 4)
    ranks = jnp.argsort(jnp.argsort(-jnp.abs(groups), axis=-1), axis=-1)
    return (ranks < 2).reshape(w.shape)


class ASP:
    """Mask bookkeeping: ``compute_sparse_masks(params)`` then
    ``apply_masks(params)`` after each optimizer step (the reference
    monkey-patches ``optimizer.step``; here call it in your train step —
    one fused multiply under jit).

    No TPU speedup is claimed — see module docstring."""

    def __init__(self, mask_fn=compute_m4n2_mask):
        self.mask_fn = mask_fn
        self.masks = None

    def compute_sparse_masks(self, params, *, predicate=None):
        predicate = predicate or (
            lambda path, x: jnp.ndim(x) >= 2 and x.shape[-1] % 4 == 0)
        self.masks = {
            jax.tree_util.keystr(p): self.mask_fn(x)
            for p, x in jax.tree_util.tree_flatten_with_path(params)[0]
            if predicate(p, x)}
        return self.masks

    def apply_masks(self, params):
        if self.masks is None:
            raise RuntimeError("call compute_sparse_masks first")
        masks = self.masks

        def mask_leaf(path, x):
            m = masks.get(jax.tree_util.keystr(path))
            return x if m is None else x * m.astype(x.dtype)

        return jax.tree_util.tree_map_with_path(mask_leaf, params)
