"""Fused multi-head attention modules — reference
``apex/contrib/multihead_attn/{self,encdec}_multihead_attn.py`` (+ the
``*_func.py`` fused CUDA variants, ``fast_self_multihead_attn_func`` etc.).

The reference ships hand-written fwd/bwd CUDA kernel chains per variant
(QKV projection → scaled masked softmax → dropout → AV → out projection,
optionally fused with a pre-LayerNorm + residual add, the "norm_add"
variant). Here the whole block is expressed once; the attention core
dispatches to the Pallas flash kernel
(`apex1_tpu.ops.attention.flash_attention`), and XLA fuses the
projection/bias/residual epilogues — the per-variant kernel zoo collapses.

Layout parity: inputs are **(S, B, E)** seq-first, like the reference
(fairseq/Megatron convention). Attention-probability dropout is FUSED
into the flash kernel between softmax and AV (``dropout_p`` +
counter-based seed — exactly the reference's in-kernel fusion point), so
``dropout > 0`` no longer forces the O(S²) composite: training configs
with attention dropout stay on the flash path. The dropout seed is
derived once per call from the flax ``"dropout"`` rng stream
(`ops.stochastic.seed_from_key` — the sanctioned one-consumption idiom)
and per-site streams are split off with `ops.stochastic.fold_seed`.

``include_norm_add`` fuses the reference "norm_add" variant: pre-LN on
the input and a dropout(out)+residual epilogue riding the fused
`ops.stochastic.fused_bias_dropout_add` row kernel (mask recomputed from
the seed in backward — no stored mask tensor).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp

from apex1_tpu.ops import layer_norm
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.ops.stochastic import (fold_seed, fused_bias_dropout_add,
                                      seed_from_key)

# per-site salts for fold_seed — attention-probability dropout and the
# norm_add output dropout must draw DISJOINT streams from one rng draw
_SALT_ATTN = 0
_SALT_RESID = 1


def _attend(q, k, v, *, causal, mask_additive, dropout, deterministic,
            dropout_seed, sm_scale):
    """(B,H,S,D) attention core — ALWAYS the flash kernel: additive
    masks ride its bias operand and probability dropout is fused
    in-kernel (both paths compute dropout(softmax(scale·qk + mask))·V
    with no materialized S×S tensor)."""
    bias = mask_additive
    if bias is not None:
        # the kernel validates bias as (1|B, 1|H, Sq, Sk) with the seq
        # dims FULL — broadcast a (B, 1, 1, Sk)-style mask's seq dims up
        # front (batch/head dims stay size-1 into the kernel)
        sq, sk = q.shape[2], k.shape[2]
        while bias.ndim < 4:
            bias = bias[None]
        bias = jnp.broadcast_to(
            bias, bias.shape[:2] + (sq, sk)).astype(jnp.float32)
    p = 0.0 if deterministic else float(dropout)
    return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                           bias=bias, dropout_p=p,
                           dropout_seed=dropout_seed if p > 0.0 else None)


class SelfMultiheadAttn(nn.Module):
    """``apex.contrib.multihead_attn.SelfMultiheadAttn`` equivalent.

    ``include_norm_add``: fuse pre-LayerNorm + dropout-residual add
    around the attention block (the reference's "norm_add" kernel
    variants — the output dropout shares the module's ``dropout`` rate,
    as the reference's ``self_multihead_attn_norm_add_func`` does).
    ``separate_qkv_params``: three (E,E) projections instead of one packed
    (E,3E) — reference ``separate_qkv_params`` flag.
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    separate_qkv_params: bool = False
    impl: str = "fast"  # parity knob; both map to the Pallas path

    @nn.compact
    def __call__(self, query, *, attn_mask=None, causal: bool = False,
                 is_training: bool = True):
        """query: (S, B, E) seq-first. ``attn_mask``: additive mask
        broadcastable to (B, H, S, S). Returns (S, B, E)."""
        E, H = self.embed_dim, self.num_heads
        D = E // H
        S, B = query.shape[0], query.shape[1]
        dtype = query.dtype
        residual = query
        if self.include_norm_add:
            g = self.param("lyr_nrm_gamma_weights", nn.initializers.ones,
                           (E,), jnp.float32)
            b = self.param("lyr_nrm_beta_weights", nn.initializers.zeros,
                           (E,), jnp.float32)
            query = layer_norm(query, g, b).astype(dtype)

        init = nn.initializers.xavier_uniform()
        if self.separate_qkv_params:
            ws = [self.param(f"{n}_weight", init, (E, E), jnp.float32)
                  for n in ("q", "k", "v")]
            qkv = jnp.concatenate(ws, axis=-1)
        else:
            qkv = self.param("in_proj_weight", init, (E, 3 * E),
                             jnp.float32)
        x = query @ qkv.astype(dtype)
        if self.bias:
            x = x + self.param("in_proj_bias", nn.initializers.zeros,
                               (3 * E,), jnp.float32).astype(dtype)
        q, k, v = jnp.split(x, 3, axis=-1)

        def heads(t):  # (S, B, E) -> (B, H, S, D)
            return t.reshape(S, B, H, D).transpose(1, 2, 0, 3)

        active = self.dropout > 0.0 and is_training
        seed = (seed_from_key(self.make_rng("dropout")) if active
                else None)
        ctx = _attend(heads(q), heads(k), heads(v), causal=causal,
                      mask_additive=attn_mask, dropout=self.dropout,
                      deterministic=not is_training,
                      dropout_seed=(fold_seed(seed, _SALT_ATTN)
                                    if active else None),
                      sm_scale=1.0 / math.sqrt(D))
        ctx = ctx.transpose(2, 0, 1, 3).reshape(S, B, E)
        wo = self.param("out_proj_weight", init, (E, E), jnp.float32)
        out = ctx @ wo.astype(dtype)
        if self.bias:
            out = out + self.param("out_proj_bias", nn.initializers.zeros,
                                   (E,), jnp.float32).astype(dtype)
        if self.include_norm_add:
            # reference norm_add epilogue: residual + dropout(out) — the
            # fused row kernel recomputes the mask from the seed in its
            # backward; p=0 lowers to the plain add (pre-PR behavior)
            out = fused_bias_dropout_add(
                out, residual, p=self.dropout if active else 0.0,
                seed=fold_seed(seed, _SALT_RESID) if active else None)
        return out


class EncdecMultiheadAttn(nn.Module):
    """``apex.contrib.multihead_attn.EncdecMultiheadAttn`` equivalent:
    Q from the decoder stream, packed KV from the encoder stream."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"

    @nn.compact
    def __call__(self, query, key, *, attn_mask=None,
                 is_training: bool = True):
        """query: (Sq, B, E); key (= encoder output, used for K and V):
        (Sk, B, E). Returns (Sq, B, E)."""
        E, H = self.embed_dim, self.num_heads
        D = E // H
        Sq, B = query.shape[0], query.shape[1]
        Sk = key.shape[0]
        dtype = query.dtype
        residual = query
        if self.include_norm_add:
            g = self.param("lyr_nrm_gamma_weights", nn.initializers.ones,
                           (E,), jnp.float32)
            b = self.param("lyr_nrm_beta_weights", nn.initializers.zeros,
                           (E,), jnp.float32)
            query = layer_norm(query, g, b).astype(dtype)

        init = nn.initializers.xavier_uniform()
        wq = self.param("q_weight", init, (E, E), jnp.float32)
        wkv = self.param("kv_weight", init, (E, 2 * E), jnp.float32)
        q = query @ wq.astype(dtype)
        kv = key @ wkv.astype(dtype)
        k, v = jnp.split(kv, 2, axis=-1)

        def heads(t, s):
            return t.reshape(s, B, H, D).transpose(1, 2, 0, 3)

        active = self.dropout > 0.0 and is_training
        seed = (seed_from_key(self.make_rng("dropout")) if active
                else None)
        ctx = _attend(heads(q, Sq), heads(k, Sk), heads(v, Sk),
                      causal=False, mask_additive=attn_mask,
                      dropout=self.dropout, deterministic=not is_training,
                      dropout_seed=(fold_seed(seed, _SALT_ATTN)
                                    if active else None),
                      sm_scale=1.0 / math.sqrt(D))
        ctx = ctx.transpose(2, 0, 1, 3).reshape(Sq, B, E)
        wo = self.param("out_proj_weight", init, (E, E), jnp.float32)
        out = ctx @ wo.astype(dtype)
        if self.bias:
            out = out + self.param("out_proj_bias", nn.initializers.zeros,
                                   (E,), jnp.float32).astype(dtype)
        if self.include_norm_add:
            out = fused_bias_dropout_add(
                out, residual, p=self.dropout if active else 0.0,
                seed=fold_seed(seed, _SALT_RESID) if active else None)
        return out
