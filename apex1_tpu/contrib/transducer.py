"""RNN-T transducer joint + loss — reference
``apex/contrib/transducer/transducer.py :: TransducerJoint,
TransducerLoss`` (+ ``apex/contrib/csrc/transducer`` fused α/β DP
kernels).

TPU-native redesign:
- **joint**: broadcast-add f (B,T,H) + g (B,U,H) (+ReLU/+dropout) in one
  fusion. The reference's "packed" variant exists to skip padding compute
  under varlen batches — with XLA's static shapes the equivalent is
  masking; lengths are honored in the loss instead.
- **loss**: the forward α recursion
      α[t,u] = logaddexp(α[t-1,u] + blank[t-1,u],  α[t,u-1] + emit[t,u-1])
  is a first-order linear recurrence along u in the (log,+) semiring, so
  each row is computed with ``jax.lax.associative_scan`` (parallel prefix,
  wavefront-free) inside a ``lax.scan`` over t — O(T) sequential steps of
  O(log U) depth instead of the reference's per-(t,u) kernel wavefront.
  Gradients come from jax AD through the scans (the reference hand-writes
  the β pass; AD's transposed scan computes the same quantity).

Losses are per-utterance negative log-likelihoods (sum/mean reduce as the
reference flags do); ``f_len``/``y_len`` give varlen audio/text lengths.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG = -1e30


def transducer_joint(f, g, *, relu: bool = False, dropout: float = 0.0,
                     dropout_rng=None, deterministic: bool = True):
    """``f``: (B, T, H) audio encodings; ``g``: (B, U, H) text
    predictions. Returns (B, T, U, H)."""
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout > 0.0 and not deterministic:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout), 0.0)
    return h


def _row_recurrence(base, emit_coeff):
    """x[u] = logaddexp(base[u], x[u-1] + emit_coeff[u]) via associative
    scan over the affine maps x ↦ logaddexp(b, a + x)."""

    def compose(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 + a2, jnp.logaddexp(b2, a2 + b1)

    a, b = jax.lax.associative_scan(compose, (emit_coeff, base), axis=-1)
    return b


def transducer_loss(logits, targets, f_len, y_len, *, blank_idx: int = 0,
                    reduction: str = "mean"):
    """``logits``: (B, T, U, V) joint outputs (U = max_target_len + 1);
    ``targets``: (B, U-1) label ids; ``f_len``: (B,) valid time steps;
    ``y_len``: (B,) valid target lengths. Returns per-utterance NLL
    (``reduction`` none) or its sum/mean."""
    B, T, U, V = logits.shape
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    blank = lp[..., blank_idx]                       # (B, T, U)
    emit = jnp.take_along_axis(
        lp[:, :, :-1, :], targets[:, None, :, None].astype(jnp.int32),
        axis=-1)[..., 0]                             # (B, T, U-1)
    # mask invalid u transitions (u >= y_len): no emission possible
    u_ids = jnp.arange(U - 1)[None, None, :]
    emit = jnp.where(u_ids < y_len[:, None, None], emit, NEG)

    def first_row(_):
        # t = 0: α[0,u] = Σ emits along u
        base = jnp.concatenate(
            [jnp.zeros((B, 1)), jnp.full((B, U - 1), NEG)], axis=1)
        return _row_recurrence(base, jnp.concatenate(
            [jnp.full((B, 1), NEG), emit[:, 0]], axis=1))

    alpha0 = first_row(None)

    def step(alpha_prev, t):
        # base[u] = α[t-1,u] + blank[t-1,u]; then emit recurrence along u
        base = alpha_prev + blank[:, t - 1]
        coeff = jnp.concatenate(
            [jnp.full((B, 1), NEG), emit[:, t]], axis=1)
        alpha = _row_recurrence(base, coeff)
        return alpha, alpha

    _, alphas = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, U)

    # ll = α[f_len-1, y_len] + blank[f_len-1, y_len]
    t_last = jnp.clip(f_len - 1, 0, T - 1).astype(jnp.int32)
    u_last = jnp.clip(y_len, 0, U - 1).astype(jnp.int32)
    b_ids = jnp.arange(B)
    final_alpha = alphas[t_last, b_ids, u_last]
    final_blank = blank[b_ids, t_last, u_last]
    nll = -(final_alpha + final_blank)
    if reduction == "none":
        return nll
    if reduction == "sum":
        return jnp.sum(nll)
    if reduction == "mean":
        return jnp.mean(nll)
    raise ValueError(f"unknown reduction {reduction!r}")


class TransducerJoint:
    """Class-shaped parity wrapper (``pack_output`` etc. are accepted for
    signature parity; packing is subsumed by masking — see module doc)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0):
        if pack_output:
            raise NotImplementedError(
                "packed varlen output is a CUDA-memory-layout optimization;"
                " on TPU use masking (see transducer_loss f_len/y_len)")
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g, *, dropout_rng=None, deterministic=True):
        return transducer_joint(f, g, relu=self.relu, dropout=self.dropout,
                                dropout_rng=dropout_rng,
                                deterministic=deterministic)


class TransducerLoss:
    def __init__(self, blank_idx: int = 0, reduction: str = "mean"):
        self.blank_idx = blank_idx
        self.reduction = reduction

    def __call__(self, logits, targets, f_len, y_len):
        return transducer_loss(logits, targets, f_len, y_len,
                               blank_idx=self.blank_idx,
                               reduction=self.reduction)
