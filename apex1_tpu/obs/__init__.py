"""Observability subsystem — the measurement flywheel (ROADMAP item 5).

Three cooperating layers, each usable alone:

- `spine` — ONE run-scoped telemetry schema (spans, counters, gauges,
  events) banked as JSONL. `bench.timed_steps`, the examples' training
  loops (via `utils.observability.MetricsLogger`), `tools/tune_kernels`
  sweeps, `serving.ServingMetrics`, and the resilience sentinel all emit
  through it, so one run's records JOIN across subsystems instead of
  each inventing a JSON shape. Activated by ``APEX1_OBS_DIR``; inert
  (zero I/O) otherwise.
- `xspace` — dependency-free parser for the ``*.xplane.pb`` traces
  ``jax.profiler.trace`` writes, with per-op device-time aggregation
  and Pallas-kernel / collective / XLA-op bucketing. The engine behind
  ``tools/trace_report.py``: any banked ``profile_artifact`` becomes a
  per-op breakdown persisted next to the record. CPU-rehearsable —
  ``jax.profiler.trace`` works on the CPU backend.
- `calibrate` — fits per-config / per-kernel correction factors from
  the accumulated (predicted, measured) pairs across banked bench logs
  and tuning tables, and feeds them back into
  ``bench._attach_roofline`` / ``tools/predict_perf.py`` so roofline
  ratios price what silicon actually did (CPU-proxy pairs are labelled
  and never applied to on-silicon predictions).

See docs/observability.md for the schema and contracts.
"""

from apex1_tpu.obs import calibrate, spine, xspace  # noqa: F401
from apex1_tpu.obs.spine import (ObsRun, StopWatch,  # noqa: F401
                                 default_run, emit, read_events)
from apex1_tpu.obs.xspace import (TraceError, build_report,  # noqa: F401
                                  parse_xspace, write_report)
