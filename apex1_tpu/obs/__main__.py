"""``python -m apex1_tpu.obs --smoke`` — the check_all ``== obs smoke ==``
gate: exercise the whole measurement flywheel on the CPU backend.

1. spine: open a run in a temp dir, emit a span/counter/event, read the
   file back through `read_events` — schema round-trip.
2. trace -> report: capture a REAL ``jax.profiler.trace`` of one tiny
   jitted step, parse the xplane files with the dependency-free parser,
   build + persist the per-op report, assert it attributed ops.
3. calibrate: fit factors from the repo's banked corpus (bench logs +
   tuning tables) and assert the fit is non-empty — the flywheel stays
   verified with no hardware attached.

Everything runs in a few seconds; failures exit non-zero with the
failing stage named.
"""

import argparse
import json
import os
import sys
import tempfile


def smoke() -> int:
    from apex1_tpu.obs import calibrate, spine, xspace

    # -- 1. spine round-trip ----------------------------------------------
    with tempfile.TemporaryDirectory(prefix="obs_smoke_") as tmp:
        with spine.ObsRun(dir=tmp, component="obs_smoke") as run:
            with run.span("smoke.step", iters=1):
                pass
            run.counter("smoke.count", 2)
            run.event("smoke.note", detail="hello")
            path = run.path
        events = spine.read_events(path)
        kinds = [e["kind"] for e in events]
        assert kinds == ["run", "span", "counter", "event"], kinds
        assert events[0]["schema"] == spine.SCHEMA
        print(f"spine OK: {len(events)} events round-tripped", flush=True)

        # -- 2. trace -> per-op report ------------------------------------
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.sum(x @ x)

        x = jnp.ones((256, 256), jnp.float32)
        step(x).block_until_ready()          # compile outside the trace
        tdir = os.path.join(tmp, "trace")
        with jax.profiler.trace(tdir):
            out = step(x)
            out.block_until_ready()
        report = xspace.build_report(tdir, steps=1)
        rpath = xspace.write_report(tdir, report=report)
        with open(rpath) as f:
            banked = json.load(f)
        assert banked["schema"] == xspace.REPORT_SCHEMA
        assert banked["n_ops"] > 0 and banked["total_op_ms"] > 0, banked
        assert set(banked["buckets"]) == set(xspace.BUCKETS)
        print(f"trace OK: {banked['n_ops']} ops attributed "
              f"({banked['plane_class']}), report at {rpath}", flush=True)

    # -- 3. calibration on the banked corpus ------------------------------
    doc = calibrate.build_calibration()
    n_factors = len(doc["factors"]) + len(doc["proxy_factors"])
    assert doc["n_pairs"] > 0 and n_factors > 0, (
        "calibration fitted nothing from the banked corpus "
        f"(pairs={doc['n_pairs']})")
    print(f"calibrate OK: {doc['n_pairs']} pairs -> "
          f"{len(doc['factors'])} tpu + {len(doc['proxy_factors'])} "
          f"cpu-proxy factors, {len(doc['excluded'])} excluded",
          flush=True)
    print("OBS SMOKE OK", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="run the flywheel smoke (check_all gate)")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke()
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
