"""Dependency-free XSpace trace parser + per-op attribution.

``jax.profiler.trace`` banks ``*.xplane.pb`` files — XSpace protobufs.
The stock decoders (``tensorflow.tsl...xplane_pb2`` et al.) drag a
multi-second TensorFlow import through import-location roulette that
differs per image (`tools/profile_step.py` shipped a three-way probe
for exactly this). The XSpace wire format itself is tiny, so this
module reads it directly: a ~100-line protobuf wire-format walker over
the four message types we need, validated field-for-field against the
``xplane_pb2`` parse on this image (PR 10). No imports beyond stdlib —
usable from tests, tools, and the check_all smoke without jax or TF.

Field numbers (tensorflow/tsl/profiler/protobuf/xplane.proto)::

    XSpace:  planes = 1
    XPlane:  id = 1, name = 2, lines = 3, event_metadata = 4 (map)
    XLine:   id = 1, name = 2, timestamp_ns = 3, events = 4,
             display_name = 11
    XEvent:  metadata_id = 1, offset_ps = 2, duration_ps = 3,
             num_occurrences = 5
    XEventMetadata: id = 1, name = 2

Every malformed input path (truncated varint, over-long length prefix,
unknown wire type, bad gzip, empty dir) raises the typed `TraceError` —
a corrupt banked trace yields a diagnosable error, never a traceback
from the middle of a byte walker (and never a silently-empty report).

Attribution model:

- **device rows** — on TPU/GPU traces, per-op events live on device
  planes (name contains ``/device:`` or ``TPU``) in the "XLA Ops"
  lines. On CPU-backend traces there is no device plane; the XLA
  runtime's per-op events live on the host plane's
  ``tf_XLATfrtCpuClient/...`` executor lines instead, and the report
  is labelled ``plane_class: "host-xla-proxy"`` — op *shares* are
  meaningful there, absolute times are host wall-clock (see
  docs/observability.md, "What CPU-proxy numbers mean").
- **buckets** — each op name lands in exactly one of ``collective``
  (the ICI ops: exposed-collective time is directly readable),
  ``pallas`` (custom-call/Mosaic kernels — the HLO cost model's blind
  spot), or ``xla`` (everything else). Name-based and best-effort, the
  rules are in `bucket_of`.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import os
import re
import zlib
from typing import Iterator, Optional

REPORT_SCHEMA = "apex1-trace-report-v1"
REPORT_NAME = "trace_report.json"

BUCKETS = ("pallas", "collective", "xla")

_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all|collective-broadcast|ppermute|send|recv)\b", re.I)
_PALLAS_RE = re.compile(
    r"(custom-call|custom_call|tpu_custom_call|pallas|mosaic)", re.I)


class TraceError(RuntimeError):
    """Typed failure for unreadable/corrupt/empty traces — callers get
    ``.path`` and ``.reason``, never a byte-walker traceback."""

    def __init__(self, path: str, reason: str):
        self.path = os.fspath(path)
        self.reason = reason
        super().__init__(f"unreadable trace at {self.path}: {reason}")


# -- protobuf wire-format walker -------------------------------------------

def _varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = 0
    val = 0
    n = len(buf)
    while True:
        if i >= n:
            raise ValueError("truncated varint")
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7
        if shift > 70:
            raise ValueError("varint overlong")


def _fields(buf: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield ``(field_no, wire_type, value)`` over one message's bytes.
    Length-delimited values come back as bytes; varints as ints."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:                      # varint
            val, i = _varint(buf, i)
        elif wt == 2:                    # length-delimited
            ln, i = _varint(buf, i)
            if i + ln > n:
                raise ValueError("truncated length-delimited field")
            val = buf[i:i + ln]
            i += ln
        elif wt == 5:                    # fixed32
            if i + 4 > n:
                raise ValueError("truncated fixed32")
            val = buf[i:i + 4]
            i += 4
        elif wt == 1:                    # fixed64
            if i + 8 > n:
                raise ValueError("truncated fixed64")
            val = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, val


@dataclasses.dataclass
class Event:
    metadata_id: int
    duration_ps: int
    occurrences: int        # num_occurrences when aggregated, else 1


@dataclasses.dataclass
class Line:
    name: str
    events: list            # [Event]


@dataclasses.dataclass
class Plane:
    name: str
    lines: list             # [Line]
    event_names: dict       # metadata_id -> op name


def _parse_event(buf: bytes) -> Event:
    mid = dur = 0
    occ = 1
    for fno, wt, val in _fields(buf):
        if wt != 0:
            continue
        if fno == 1:
            mid = val
        elif fno == 3:
            dur = val
        elif fno == 5:
            occ = val
    return Event(metadata_id=mid, duration_ps=dur, occurrences=occ)


def _parse_line(buf: bytes) -> Line:
    name = ""
    events = []
    for fno, wt, val in _fields(buf):
        if fno == 2 and wt == 2:
            name = val.decode("utf-8", "replace")
        elif fno == 4 and wt == 2:
            events.append(_parse_event(val))
    return Line(name=name, events=events)


def _parse_emeta_entry(buf: bytes) -> tuple[int, str]:
    key = 0
    name = ""
    for fno, wt, val in _fields(buf):
        if fno == 1 and wt == 0:
            key = val
        elif fno == 2 and wt == 2:       # XEventMetadata
            for f2, w2, v2 in _fields(val):
                if f2 == 2 and w2 == 2:
                    name = v2.decode("utf-8", "replace")
    return key, name


def _parse_plane(buf: bytes) -> Plane:
    name = ""
    lines = []
    emeta: dict[int, str] = {}
    for fno, wt, val in _fields(buf):
        if fno == 2 and wt == 2:
            name = val.decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            lines.append(_parse_line(val))
        elif fno == 4 and wt == 2:
            k, v = _parse_emeta_entry(val)
            emeta[k] = v
    return Plane(name=name, lines=lines, event_names=emeta)


def parse_xspace(path: str | os.PathLike) -> list[Plane]:
    """Parse one ``*.xplane.pb`` (``.gz`` transparently) into planes.
    Raises `TraceError` on any unreadable/corrupt input."""
    path = os.fspath(path)
    try:
        with open(path, "rb") as f:
            data = f.read()
        if path.endswith(".gz"):
            data = gzip.decompress(data)
    # zlib.error: a valid gzip HEADER over a corrupt deflate body —
    # BadGzipFile alone misses it and the typed-error contract breaks
    except (OSError, gzip.BadGzipFile, EOFError, zlib.error) as e:
        raise TraceError(path, f"cannot read: {e}") from e
    planes = []
    try:
        for fno, wt, val in _fields(data):
            if fno == 1 and wt == 2:
                planes.append(_parse_plane(val))
    except ValueError as e:
        raise TraceError(path, f"corrupt protobuf: {e}") from e
    if not planes:
        raise TraceError(path, "no XPlane messages (empty or foreign file)")
    return planes


def find_xplane_files(trace_dir: str | os.PathLike) -> list[str]:
    """Every ``*.xplane.pb[.gz]`` under ``trace_dir`` (the layout
    ``jax.profiler.trace`` writes: ``plugins/profile/<ts>/...``)."""
    trace_dir = os.fspath(trace_dir)
    out = []
    for pat in ("*.xplane.pb", "*.xplane.pb.gz"):
        out += glob.glob(os.path.join(trace_dir, "**", pat),
                         recursive=True)
    return sorted(out)


# -- attribution -----------------------------------------------------------

def bucket_of(op_name: str) -> str:
    """``collective`` | ``pallas`` | ``xla`` for one op name.
    Name-based, best-effort: collectives first (a fused
    collective-permute must read as ICI time even if spelled inside a
    custom call wrapper), then the custom-call/Mosaic family, then
    everything else."""
    if _COLLECTIVE_RE.search(op_name):
        return "collective"
    if _PALLAS_RE.search(op_name):
        return "pallas"
    return "xla"


def _is_device_plane(name: str) -> bool:
    return "/device:" in name or "TPU" in name or "gpu" in name.lower()


def _is_op_line(line_name: str, *, device: bool) -> bool:
    if device:
        return "XLA Ops" in line_name or "XLA Op" in line_name \
            or line_name.startswith("XLA")
    # CPU backend: the XLA executor threads carry the per-op events
    return line_name.startswith("tf_XLA")


def op_totals(planes: list) -> tuple[dict, str]:
    """Aggregate per-op ``{name: [total_ps, count]}`` over the op lines.
    Returns ``(totals, plane_class)`` where plane_class is ``"device"``
    (real accelerator planes) or ``"host-xla-proxy"`` (CPU backend —
    shares meaningful, absolute times are host wall-clock)."""
    for device in (True, False):
        totals: dict[str, list] = {}
        for plane in planes:
            if _is_device_plane(plane.name) != device:
                continue
            for line in plane.lines:
                if not _is_op_line(line.name, device=device):
                    continue
                for ev in line.events:
                    name = plane.event_names.get(
                        ev.metadata_id, str(ev.metadata_id))
                    a = totals.setdefault(name, [0, 0])
                    a[0] += ev.duration_ps
                    a[1] += max(int(ev.occurrences), 1)
        if totals:
            return totals, ("device" if device else "host-xla-proxy")
    return {}, "none"


def build_report(trace_dir: str | os.PathLike, *,
                 steps: Optional[int] = None,
                 top: int = 200) -> dict:
    """Per-op device-time breakdown for one banked trace directory.

    Raises `TraceError` when the dir holds no xplane files, none
    parses, or no op events were found (an empty report would read as
    "nothing ran" when the truth is "nothing was attributable")."""
    trace_dir = os.fspath(trace_dir)
    paths = find_xplane_files(trace_dir)
    if not paths:
        raise TraceError(trace_dir, "no *.xplane.pb files under dir")
    planes = []
    for p in paths:
        planes += parse_xspace(p)
    totals, plane_class = op_totals(planes)
    if not totals:
        lines = sorted({(pl.name, ln.name)
                        for pl in planes for ln in pl.lines})
        raise TraceError(
            trace_dir, "no per-op events on any known op line; "
            f"planes/lines seen: {lines[:12]}")
    total_ps = sum(ps for ps, _n in totals.values())
    buckets = {b: 0 for b in BUCKETS}
    ops = []
    for name, (ps, n) in sorted(totals.items(), key=lambda kv: -kv[1][0]):
        b = bucket_of(name)
        buckets[b] += ps
        ops.append({"name": name, "bucket": b,
                    "ms": round(ps / 1e9, 6), "count": int(n),
                    "share": round(ps / total_ps, 4) if total_ps else 0.0})
    report = {
        "schema": REPORT_SCHEMA,
        "trace_dir": trace_dir,
        "plane_class": plane_class,
        "total_op_ms": round(total_ps / 1e9, 6),
        "buckets": {b: {"ms": round(buckets[b] / 1e9, 6),
                        "share": (round(buckets[b] / total_ps, 4)
                                  if total_ps else 0.0)}
                    for b in BUCKETS},
        "n_ops": len(ops),
        "ops": ops[:top],
    }
    if steps:
        report["steps"] = int(steps)
        report["per_step_ms"] = round(total_ps / 1e9 / steps, 6)
    return report


def write_report(trace_dir: str | os.PathLike, *,
                 report: Optional[dict] = None,
                 steps: Optional[int] = None,
                 path: Optional[str] = None) -> str:
    """Build (unless given) and atomically persist the report NEXT TO
    the trace it describes (``<trace_dir>/trace_report.json``), so a
    banked ``profile_artifact`` directory carries its own breakdown."""
    from apex1_tpu.resilience.manifest import atomic_write_json

    if report is None:
        report = build_report(trace_dir, steps=steps)
    if path is None:
        path = os.path.join(os.fspath(trace_dir), REPORT_NAME)
    atomic_write_json(path, report)
    return path


def format_report(report: dict, top: int = 25) -> str:
    """Human-readable rendering (the trace_report/profile_step CLIs)."""
    lines = [f"plane class: {report['plane_class']}   "
             f"total op time: {report['total_op_ms']:.3f} ms"
             + (f"   ({report['per_step_ms']:.3f} ms/step x "
                f"{report['steps']})" if report.get("steps") else "")]
    bk = report["buckets"]
    lines.append("buckets: " + "  ".join(
        f"{b}={bk[b]['ms']:.3f}ms ({bk[b]['share'] * 100:.1f}%)"
        for b in BUCKETS))
    for op in report["ops"][:top]:
        lines.append(f"{op['ms']:10.3f} ms {op['count']:6d}x "
                     f"{op['share'] * 100:5.1f}%  [{op['bucket']:10s}] "
                     f"{op['name'][:100]}")
    return "\n".join(lines)
