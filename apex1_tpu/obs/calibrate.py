"""Calibration — fit (predicted → measured) correction factors from the
banked corpus, and feed them back to the predictors.

`tools/predict_perf.py` prices every bench config and kernel with an
analytic roofline that has never been corrected against measurement:
resnet banked 0.22x its prediction, gpt2 0.53x, and every future
planner decision (ROADMAP item 1 — AMP-style layout pricing) would
inherit those uncorrected errors. This module closes the loop:

- **pairs** — every banked measurement that can be joined to its own
  prediction: on-silicon ``perf_results/bench_*.log`` records against
  the newest ``predicted_*.json`` step rows (the
  `tools/measured_vs_predicted.py` join, generalized), and tuning-table
  entries that carry the per-sweep analytic ``predicted.ms``
  `tools/tune_kernels.py` now banks beside each ``time_ms``.
- **factors** — per key (``step:<config>`` / ``kernel:<name>``), the
  geometric-mean SLOWDOWN ``predicted_rate / measured_rate`` (equiv.
  ``measured_time / predicted_time``; > 1 = slower than the roofline).
  TPU-backed factors land in ``factors``; interpret/CPU-proxy pairs are
  fitted too but land in ``proxy_factors`` and are NEVER applied to
  on-silicon predictions — interpret-mode time is plumbing evidence,
  not silicon (docs/observability.md, "What CPU-proxy numbers mean").
- **feedback** — ``bench._attach_roofline`` stamps
  ``calibrated_predicted`` / ``calibrated_ratio`` on measured records
  (a calibrated ratio near 1.0 = performing as banked history says;
  the RAW ``roofline_ratio`` keeps its absolute-localizer meaning),
  and ``tools/predict_perf.py`` tables the factors beside its
  predictions. `step_slowdown` / `kernel_slowdown` are the lookup API.

Exclusions are explicit and banked: the decode configs' predictions
are known-garbage (the HLO cost model counts a scanned loop's weight
buffers once, not once per decode step — see predict_perf's
"SCANNED-LOOP BLIND SPOT"), so they are excluded with that reason
rather than silently fitted into a meaningless factor.

The banked table (``perf_results/calibration.json``,
`resilience.manifest.atomic_write_json`) is refreshed by the
``calibrate_refresh`` entries ``tools/tpu_watch.sh`` runs after each
bench group, so every hardware window re-fits the factors.

CLI::

    python -m apex1_tpu.obs.calibrate [--results perf_results]
        [--out perf_results/calibration.json] [--generation v5e]
        [--dry-run]
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import math
import os
import time
from typing import Optional

SCHEMA = "apex1-calibration-v1"
CAL_NAME = "calibration.json"

#: step configs whose analytic prediction is structurally meaningless —
#: excluded from fitting WITH the reason banked in the table
EXCLUDED_STEP_CONFIGS = {
    "decode": "scanned-loop blind spot: cost model counts streamed "
              "weights once, not per decode step (predict_perf.py)",
    "decode_int8": "scanned-loop blind spot (see decode)",
}

#: queue-log filename -> bench config. MUST mirror bench._BANKED_LOGS
#: (tests/test_obs.py pins the two in sync); duplicated rather than
#: imported because bench.py initializes jax at import and this module
#: must stay importable by light tools.
LOG_TO_CONFIG = {
    "bench_bert.log": "bert",
    "bench_bert_drop.log": "bert_dropout",
    "bench_bert_lg.log": "bert_large",
    "bench_decode.log": "decode",
    "bench_dec_int8.log": "decode_int8",
    "bench_gpt2.log": "gpt2",
    "bench_gpt2_b24.log": "gpt2",
    "bench_gpt2_fp16.log": "gpt2_fp16",
    # the planner-driven 3D config: joins no single-chip prediction
    # row (the planner prices it), so records land in `excluded` with
    # that reason rather than a bogus factor
    "bench_llama3d.log": "llama_3d",
    "bench_llama_blk.log": "llama_block",
    "bench_llama16k.log": "llama_longctx",
    "bench_resnet.log": "resnet",
    "bench_t5.log": "t5",
}


def default_results_dir() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "perf_results")


def roofline_ms(flops: float, nbytes: float,
                generation: Optional[str] = None) -> float:
    """Analytic roofline milliseconds for one kernel invocation at a
    capability row — what `tools/tune_kernels.py` banks as
    ``predicted.ms`` beside every sweep winner."""
    from apex1_tpu.core.capability import get_capability

    cap = get_capability(generation)
    t = max(flops / (cap.bf16_tflops * 1e12),
            nbytes / (cap.hbm_gbps * 1e9))
    return t * 1e3


# -- prediction-table resolution (the ONE newest-by-mtime rule) ------------

def newest_prediction_path(results_dir: Optional[str] = None
                           ) -> Optional[str]:
    """Newest banked ``predicted_*.json`` by mtime — the same rule
    ``bench._predicted_row`` applies (lexicographic order breaks at
    r10 vs r9). `tools/measured_vs_predicted.py` resolves through this
    too, so a new prediction round can never be silently scored against
    a stale table."""
    d = results_dir or default_results_dir()
    paths = glob.glob(os.path.join(d, "predicted_*.json"))
    if not paths:
        return None
    return max(paths, key=os.path.getmtime)


def newest_prediction(results_dir: Optional[str] = None) -> Optional[dict]:
    path = newest_prediction_path(results_dir)
    if path is None:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    doc["_path"] = path
    return doc


def predicted_step_rate(row: dict, generation: str = "v5e"
                        ) -> Optional[float]:
    """Roofline units/sec for one prediction-step row at an EXPLICIT
    capability generation (bench._predicted_rate prices at the current
    chip; offline calibration must price at the chip the banked logs
    came from). Comms term included, same as bench."""
    from apex1_tpu.core.capability import get_capability, ici_link_gbps

    try:
        cap = get_capability(generation)
        t = max(row["flops"] / (cap.bf16_tflops * 1e12),
                row["bytes"] / (cap.hbm_gbps * 1e9))
        exposed = row.get("ici_exposed_bytes", 0.0)
        if exposed:
            link = ici_link_gbps(generation)
            if link:
                t += exposed / (link * 1e9)
        if t <= 0:
            return None
        return row["units_per_step"] / t
    except (KeyError, TypeError, ValueError):
        return None


# -- pair collection -------------------------------------------------------

@dataclasses.dataclass
class Pair:
    """One (predicted, measured) joinable observation."""

    key: str          # "step:<config>" | "kernel:<name>"
    predicted: float  # step: units/sec; kernel: ms
    measured: float   # same unit as predicted
    slowdown: float   # predicted_rate/measured_rate == meas_t/pred_t
    backend: str      # "tpu" | "cpu-proxy"
    source: str       # log / table file the measurement came from
    detail: dict      # free-form provenance

    def to_json(self) -> dict:
        return {"key": self.key, "predicted": self.predicted,
                "measured": self.measured,
                "slowdown": round(self.slowdown, 4),
                "backend": self.backend, "source": self.source,
                **({"detail": self.detail} if self.detail else {})}


def json_lines(path: str) -> list[dict]:
    """Lenient JSON-record scan of a bench queue log: every parseable
    one-line {...} object, in order; unreadable file -> []. The ONE
    scanner for queue logs (tools/trace_report.py shares it)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not (line.startswith("{") and line.endswith("}")):
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def collect_step_pairs(results_dir: Optional[str] = None,
                       generation: str = "v5e"
                       ) -> tuple[list[Pair], list[dict]]:
    """On-silicon bench records joined against the newest prediction
    table. Returns ``(pairs, excluded)`` — excluded rows carry their
    reason (decode blind spot, no prediction row, cpu-only record).

    The join is RATE-based (units/sec vs predicted units/sec), which
    tolerates batch-size overrides to first order — flops and time
    both scale ~linearly with B, so bench_gpt2_b24's record pairs
    fairly with the B=16 prediction row. A step_ms-based join would
    NOT (that is measured_vs_predicted.py's per-shape constraint on
    its LOG_FOR_CONFIG table)."""
    d = results_dir or default_results_dir()
    pred = newest_prediction(d)
    rows = ({r.get("name"): r for r in pred.get("steps", [])
             if isinstance(r, dict) and "flops" in r} if pred else {})
    pairs: list[Pair] = []
    excluded: list[dict] = []
    for logname, config in sorted(LOG_TO_CONFIG.items()):
        path = os.path.join(d, logname)
        if not os.path.exists(path):
            continue
        for rec in json_lines(path):
            val = rec.get("value")
            if isinstance(val, bool) or not isinstance(val, (int, float)) \
                    or not math.isfinite(val) or val <= 0:
                continue
            if "[tpu]" not in rec.get("metric", ""):
                continue   # cpu smoke / unreachable records measure
                # nothing calibratable — skip silently, they are not
                # "excluded measurements", they are non-measurements
            if config in EXCLUDED_STEP_CONFIGS:
                excluded.append({
                    "key": f"step:{config}", "source": logname,
                    "reason": EXCLUDED_STEP_CONFIGS[config]})
                continue
            row = rows.get(config)
            if row is None:
                excluded.append({
                    "key": f"step:{config}", "source": logname,
                    "reason": "no prediction row in newest "
                              "predicted_*.json"})
                continue
            rate = predicted_step_rate(row, generation)
            if not rate:
                excluded.append({
                    "key": f"step:{config}", "source": logname,
                    "reason": "prediction row unpriceable"})
                continue
            pairs.append(Pair(
                key=f"step:{config}", predicted=round(rate, 1),
                measured=float(val), slowdown=rate / float(val),
                backend="tpu", source=logname,
                detail={k: rec[k] for k in ("batch", "step_ms")
                        if k in rec}))
    return pairs, excluded


def collect_kernel_pairs(tuning_dir: Optional[str] = None) -> list[Pair]:
    """Tuning-table winners that bank both ``time_ms`` and the analytic
    ``predicted.ms`` (tune_kernels writes both since PR 10). Interpret-
    timed entries become cpu-proxy pairs — fitted, labelled, never
    applied to silicon predictions."""
    if tuning_dir is None:
        from apex1_tpu.tuning import default_tuning_dir
        tuning_dir = default_tuning_dir()
    pairs: list[Pair] = []
    if not os.path.isdir(tuning_dir):
        return pairs
    for name in sorted(os.listdir(tuning_dir)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(tuning_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
            kernel = doc.get("kernel") or name[:-5]
            entries = doc.get("entries") or {}
        except (OSError, json.JSONDecodeError, AttributeError):
            continue   # corrupt table: lookup already degrades, so here
        if not isinstance(entries, dict):
            continue
        for key, entry in sorted(entries.items()):
            if not isinstance(entry, dict):
                continue
            t = entry.get("time_ms")
            p = (entry.get("predicted") or {}).get("ms") \
                if isinstance(entry.get("predicted"), dict) else None
            if not isinstance(t, (int, float)) or isinstance(t, bool) \
                    or not isinstance(p, (int, float)) \
                    or isinstance(p, bool) or t <= 0 or p <= 0:
                continue
            backend = ("tpu" if entry.get("timing") == "measured"
                       else "cpu-proxy")
            pairs.append(Pair(
                key=f"kernel:{kernel}", predicted=float(p),
                measured=float(t), slowdown=float(t) / float(p),
                backend=backend, source=os.path.join("tuning", name),
                detail={"entry": key, "blocks": entry.get("blocks")}))
    return pairs


def collect_pairs(results_dir: Optional[str] = None,
                  generation: str = "v5e",
                  tuning_dir: Optional[str] = None
                  ) -> tuple[list[Pair], list[dict]]:
    d = results_dir or default_results_dir()
    if tuning_dir is None:
        # the tuning corpus lives BESIDE the bench logs (never fall
        # back to the repo's tables when an explicit results dir lacks
        # them — a foreign corpus must not leak in); APEX1_TUNING_DIR
        # overrides, same as the tuning package itself
        env = os.environ.get("APEX1_TUNING_DIR", "").strip()
        tuning_dir = env or os.path.join(d, "tuning")
    step_pairs, excluded = collect_step_pairs(d, generation)
    return step_pairs + collect_kernel_pairs(tuning_dir), excluded


# -- fitting ---------------------------------------------------------------

def _geomean(xs: list[float]) -> float:
    return math.exp(sum(math.log(x) for x in xs) / len(xs))


def fit(pairs: list[Pair]) -> tuple[dict, dict]:
    """Per-key geometric-mean slowdown. Returns ``(factors,
    proxy_factors)``: tpu-backed keys in the first (the appliable
    ones), cpu-proxy-only evidence in the second."""
    by: dict[tuple, list[Pair]] = {}
    for p in pairs:
        by.setdefault((p.key, p.backend), []).append(p)
    factors: dict[str, dict] = {}
    proxy: dict[str, dict] = {}
    for (key, backend), ps in sorted(by.items()):
        geo = _geomean([p.slowdown for p in ps])
        residuals = [p.slowdown / geo for p in ps]
        doc = {"slowdown": round(geo, 4), "n": len(ps),
               "backend": backend,
               "residual_spread": [round(min(residuals), 4),
                                   round(max(residuals), 4)],
               "sources": sorted({p.source for p in ps})}
        (factors if backend == "tpu" else proxy)[key] = doc
    return factors, proxy


def build_calibration(results_dir: Optional[str] = None,
                      generation: str = "v5e",
                      tuning_dir: Optional[str] = None) -> dict:
    pairs, excluded = collect_pairs(results_dir, generation, tuning_dir)
    factors, proxy = fit(pairs)
    pred_path = newest_prediction_path(results_dir)
    return {"schema": SCHEMA,
            "generation": generation,
            "generated_unix": round(time.time(), 1),
            "prediction_table": (os.path.basename(pred_path)
                                 if pred_path else None),
            "n_pairs": len(pairs),
            "factors": factors,
            "proxy_factors": proxy,
            "excluded": excluded,
            "pairs": [p.to_json() for p in pairs]}


def save_calibration(doc: dict, path: Optional[str] = None,
                     results_dir: Optional[str] = None) -> str:
    from apex1_tpu.resilience.manifest import atomic_write_json

    if path is None:
        path = os.path.join(results_dir or default_results_dir(),
                            CAL_NAME)
    atomic_write_json(path, doc)
    return path


# -- lookup (the consumer API) ---------------------------------------------

def load_calibration(results_dir: Optional[str] = None,
                     path: Optional[str] = None) -> Optional[dict]:
    """Banked calibration table, or None. Fail-safe: a corrupt or
    foreign-schema file is a miss, never an exception — the consumers
    decorate measurement records and must not break them."""
    if path is None:
        path = os.path.join(results_dir or default_results_dir(),
                            CAL_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
        return None
    return doc


def _slowdown(key: str, results_dir: Optional[str] = None
              ) -> Optional[dict]:
    doc = load_calibration(results_dir)
    if doc is None:
        return None
    f = doc.get("factors", {}).get(key)
    if not isinstance(f, dict):
        return None
    s = f.get("slowdown")
    if not isinstance(s, (int, float)) or isinstance(s, bool) or s <= 0:
        return None
    return f


def step_slowdown(config: str, results_dir: Optional[str] = None
                  ) -> Optional[dict]:
    """TPU-backed factor doc for a bench config, or None. cpu-proxy
    factors are deliberately unreachable here — they must never
    recalibrate an on-silicon prediction."""
    return _slowdown(f"step:{config}", results_dir)


def kernel_slowdown(kernel: str, results_dir: Optional[str] = None
                    ) -> Optional[dict]:
    return _slowdown(f"kernel:{kernel}", results_dir)


# -- CLI -------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--results", default=None,
                    help="perf_results dir (default: the repo's)")
    ap.add_argument("--out", default=None,
                    help=f"output path (default <results>/{CAL_NAME})")
    ap.add_argument("--generation", default="v5e",
                    help="capability row the banked tpu logs came from")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the fit; don't write the table")
    args = ap.parse_args(argv)

    doc = build_calibration(args.results, args.generation)
    print(f"calibration: {doc['n_pairs']} pairs -> "
          f"{len(doc['factors'])} tpu factor(s), "
          f"{len(doc['proxy_factors'])} cpu-proxy factor(s), "
          f"{len(doc['excluded'])} excluded "
          f"(prediction table: {doc['prediction_table']})", flush=True)
    for label, fs in (("tpu", doc["factors"]),
                      ("cpu-proxy", doc["proxy_factors"])):
        for key, f in sorted(fs.items()):
            lo, hi = f["residual_spread"]
            print(f"  [{label}] {key:28s} slowdown {f['slowdown']:8.3f}  "
                  f"n={f['n']}  residual x{lo:.2f}..x{hi:.2f}")
    for e in doc["excluded"]:
        print(f"  [excluded] {e['key']:25s} {e['reason'][:80]}")
    if not args.dry_run:
        path = save_calibration(doc, args.out, args.results)
        print(f"wrote {path}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
