"""Telemetry spine — one run-scoped event schema for every subsystem.

Before this module, each measuring subsystem invented its own JSON
shape (`bench.py` records, `tune_kernels` sweep logs, serving lifecycle
events, sentinel diagnostics), so nothing could be joined across a run.
The spine fixes the SCHEMA and the SINK:

- A **run** is one process-level measurement context. Its events land
  in one JSONL file ``<dir>/<component>_<pid>_<t0>.jsonl``.
- Line 1 is the run header::

    {"schema": "apex1-obs-v1", "kind": "run", "run": "<id>",
     "component": "<argv0>", "pid": 1234, "t0_unix": 1759...,
     "meta": {...}}

- Every following line is one event::

    {"kind": "span",    "name": ..., "t": <s since t0>, "dur_s": ...}
    {"kind": "counter", "name": ..., "t": ..., "value": <cumulative>}
    {"kind": "gauge",   "name": ..., "t": ..., "value": <sample>}
    {"kind": "event",   "name": ..., "t": ..., **fields}

  Extra keyword fields ride along verbatim (JSON-safe scalars only —
  the emitter does not fetch device arrays; callers hand host scalars).

Durability contract: events are APPENDED and flushed per line, so a
crash keeps every line that printed and at most the LAST line can be
torn (`read_events` skips unparseable lines). Derived artifacts (trace
reports, calibration tables) use `resilience.manifest.atomic_write_json`
instead — those are rewritten whole, so the atomic form is the right
one there; a streaming event log must not lose its history to a crash
before an atomic commit point.

Activation: the module-level `emit`/`default_run` helpers are inert
(no file, no I/O beyond one getenv) until ``APEX1_OBS_DIR`` is set —
instrumented hot paths cost a dict lookup when observability is off.
`StopWatch` is the ONE host-side wall-clock timing primitive; the
`utils.observability.Timers` surface, `serving.metrics` wall-clock
handling, and `bench.timed_steps` all sit on it.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import re
import sys
import threading
import time
from typing import Any, Optional

SCHEMA = "apex1-obs-v1"

#: event kinds the schema admits (plus the "run" header line)
KINDS = ("span", "counter", "gauge", "event")

monotonic = time.monotonic   # the ONE clock origin helper (see ObsRun)


def obs_dir() -> Optional[str]:
    """``APEX1_OBS_DIR`` when set and non-empty, else None (spine off)."""
    d = os.environ.get("APEX1_OBS_DIR", "").strip()
    return d or None


class StopWatch:
    """Cumulative named-timer primitive: ``start()`` / ``stop(sync=...)``.

    ``stop(sync=tree)`` blocks on the tree first so device work is
    attributed to the timed region (the `apex/transformer` ``timers``
    contract). Attributes ``elapsed_`` / ``count`` / ``last_s`` are
    public; `elapsed(reset=True)` reads-and-clears.
    """

    def __init__(self):
        self.elapsed_ = 0.0
        self.count = 0
        self.last_s: Optional[float] = None
        self._t0: Optional[float] = None

    def start(self) -> "StopWatch":
        self._t0 = time.perf_counter()
        return self

    def stop(self, sync: Any = None) -> float:
        if sync is not None:
            import jax           # lazy: the spine imports without jax
            jax.block_until_ready(sync)
        dt = time.perf_counter() - self._t0
        self.elapsed_ += dt
        self.count += 1
        self.last_s = dt
        self._t0 = None
        return dt

    def elapsed(self, reset: bool = False) -> float:
        e = self.elapsed_
        if reset:
            self.elapsed_, self.count = 0.0, 0
        return e


def _component() -> str:
    base = os.path.basename(sys.argv[0] or "") or "python"
    base = re.sub(r"\.py$", "", base)
    return re.sub(r"[^A-Za-z0-9_.-]", "_", base) or "python"


#: per-process sequence folded into run ids — two runs opened in the
#: same second must not append into one file
_RUN_SEQ = itertools.count()


class ObsRun:
    """One run's event sink. Thread-safe; every write is flushed so the
    file tails live. Use as a context manager, or `close()` explicitly
    (the file is also usable after the process dies mid-run — that is
    the point)."""

    def __init__(self, dir: Optional[str] = None, *,
                 run_id: Optional[str] = None,
                 component: Optional[str] = None,
                 meta: Optional[dict] = None,
                 path: Optional[str] = None):
        self.component = component or _component()
        t0_unix = time.time()
        self.run_id = run_id or (f"{self.component}_{os.getpid()}_"
                                 f"{int(t0_unix)}_{next(_RUN_SEQ)}")
        if path is None:
            d = dir or obs_dir()
            if d is None:
                raise ValueError("ObsRun needs dir=, path=, or "
                                 "APEX1_OBS_DIR")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, self.run_id + ".jsonl")
        self.path = path
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")
        self._write({"schema": SCHEMA, "kind": "run", "run": self.run_id,
                     "component": self.component, "pid": os.getpid(),
                     "t0_unix": round(t0_unix, 3),
                     "meta": dict(meta or {})})

    # -- sink --------------------------------------------------------------

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def emit(self, kind: str, name: str, *, t: Optional[float] = None,
             **fields) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown event kind {kind!r}; one of {KINDS}")
        t = (time.monotonic() - self._t0) if t is None else t
        self._write({"kind": kind, "name": str(name),
                     "t": round(float(t), 6), **fields})

    def counter(self, name: str, value, **fields) -> None:
        self.emit("counter", name, value=value, **fields)

    def gauge(self, name: str, value, **fields) -> None:
        self.emit("gauge", name, value=value, **fields)

    def event(self, name: str, **fields) -> None:
        self.emit("event", name, **fields)

    @contextlib.contextmanager
    def span(self, name: str, *, sync: Any = None, **attrs):
        """Time the enclosed block as one span event. ``sync=tree``
        blocks on the tree before stopping the clock (device work
        attribution, same contract as `StopWatch.stop`)."""
        t_rel = time.monotonic() - self._t0
        sw = StopWatch().start()
        try:
            yield sw
        finally:
            dur = sw.stop(sync=sync)
            self.emit("span", name, t=t_rel, dur_s=round(dur, 6), **attrs)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                try:
                    os.fsync(self._f.fileno())
                except OSError:
                    pass
                self._f.close()

    def __enter__(self) -> "ObsRun":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- module-level default run (the zero-threading integration path) --------
#
# Subsystems call `spine.emit(...)` unconditionally; with APEX1_OBS_DIR
# unset that is a no-op, with it set the process lazily opens ONE run
# (keyed on (pid, dir) so forks and env changes get fresh files).

_DEFAULT: dict = {"run": None, "key": None}
_DEFAULT_LOCK = threading.Lock()


def default_run() -> Optional[ObsRun]:
    """The process-wide run (lazily created iff ``APEX1_OBS_DIR`` is
    set), or None. Never raises — a broken obs dir must not take down
    the instrumented subsystem."""
    d = obs_dir()
    key = (os.getpid(), d)
    if _DEFAULT["key"] == key:
        return _DEFAULT["run"]
    with _DEFAULT_LOCK:
        if _DEFAULT["key"] == key:
            return _DEFAULT["run"]
        old = _DEFAULT["run"]
        run = None
        if d is not None:
            try:
                run = ObsRun(dir=d)
            except OSError:
                run = None
        _DEFAULT.update(run=run, key=key)
    if old is not None:
        try:
            old.close()
        except Exception:
            pass
    return _DEFAULT["run"]


def set_default_run(run: Optional[ObsRun]) -> None:
    """Install an explicit run as the process default (tests, tools
    that own their run). Pass None to clear."""
    with _DEFAULT_LOCK:
        _DEFAULT.update(run=run,
                        key=(os.getpid(), obs_dir()) if run else None)


def emit(kind: str, name: str, **fields) -> None:
    """Fire-and-forget emission through the default run. No-op when the
    spine is off; swallows I/O errors — instrumentation must never cost
    the instrumented path its result."""
    run = default_run()
    if run is None:
        return
    try:
        run.emit(kind, name, **fields)
    except Exception:
        pass


# -- reader ----------------------------------------------------------------

def read_events(path: str, *, kinds: Optional[tuple] = None) -> list[dict]:
    """Parse one run file back into a list of dicts (header included).
    Unparseable lines — the torn tail a crash can leave — are skipped,
    not fatal: the durability contract is per-line."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if kinds is not None and rec.get("kind") not in kinds:
                continue
            out.append(rec)
    return out
