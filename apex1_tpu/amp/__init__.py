"""Mixed-precision training services — reference ``apex/amp``.

The reference's ``amp.initialize(model, optimizer, opt_level)`` mutates a
torch model/optimizer in place (monkey-patching ops for O1, casting the
model + building fp32 master weights for O2) and ``amp.scale_loss`` wraps
``backward()``. In JAX the whole step is one traced function, so the same
capabilities become explicit state + a step builder:

    amp = Amp(tx=fused_adam(1e-4), opt_level="O2")
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(loss_fn))
    state, metrics = step(state, batch)

Correspondence:
- fp32 master weights (O2)  → ``state.params`` are ALWAYS fp32 (policy
  ``param_dtype``); compute sees ``policy.cast_to_compute(params)`` inside
  the grad, so grads arrive in fp32 against the masters
  (``_process_optimizer.py :: _master_params_to_model_params`` has no
  equivalent code — the cast is re-traced each step, free under jit).
- op lists (O1)             → ``policy.fp32_fragile_ops`` consumed by
  `apex1_tpu.ops` kernels.
- ``scale_loss`` + overflow skip → ``loss_scale`` state threaded through;
  non-finite grads skip the update via ``select_tree`` (device-side, no
  host sync — ≙ ``amp_C`` noop_flag) and halve the scale.
- ``amp.state_dict()``      → ``state.loss_scale`` is part of the pytree
  and checkpoints with everything else.

Reference anchors: ``apex/amp/frontend.py :: initialize``,
``apex/amp/handle.py :: scale_loss``, ``apex/amp/_process_optimizer.py``,
``apex/amp/scaler.py :: LossScaler``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import chex
import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.loss_scale import (LossScaleState, all_finite,
                                       make_loss_scale, select_tree)
from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.core.pytree import global_norm


@chex.dataclass
class AmpState:
    """Train state: fp32 master params + optimizer state + loss-scale state.

    ≙ the (model, optimizer, amp.state_dict()) triple the reference
    checkpoints (README "checkpointing" recipe).
    """

    step: jnp.ndarray
    params: Any
    opt_state: Any
    loss_scale: Any  # LossScaleState, or a tuple of them (num_losses > 1)


class Amp:
    """Bundle of precision policy + optimizer transform.

    ``opt_level``/overrides mirror ``amp.initialize`` kwargs:
    ``Amp(tx, opt_level="O2", loss_scale=128.0, keep_norms_fp32=False)``.
    """

    def __init__(self, tx: optax.GradientTransformation,
                 opt_level: str | PrecisionPolicy = "O1",
                 max_grad_norm: float | None = None,
                 grad_psum_axes: tuple[str, ...] = (),
                 num_losses: int = 1,
                 cast_model_outputs=None,
                 min_loss_scale: float | None = None,
                 max_loss_scale: float | None = None,
                 **policy_overrides):
        self.tx = tx
        self.policy = get_policy(opt_level, **policy_overrides)
        self.scaler = make_loss_scale(self.policy.loss_scale)
        # ≙ amp.initialize(min_loss_scale=, max_loss_scale=) clamps
        if min_loss_scale is not None or max_loss_scale is not None:
            from apex1_tpu.core.loss_scale import DynamicLossScale
            if not isinstance(self.scaler, DynamicLossScale):
                raise ValueError("min/max_loss_scale require a dynamic "
                                 "loss scale")
            import copy
            self.scaler = copy.copy(self.scaler)  # never mutate a
            if min_loss_scale is not None:        # caller-supplied scaler
                self.scaler.min_loss_scale = float(min_loss_scale)
            if max_loss_scale is not None:
                self.scaler.max_loss_scale = float(max_loss_scale)
        self.max_grad_norm = max_grad_norm
        # mesh axes to pmean grads over (shard_map DDP; pjit needs none)
        self.grad_psum_axes = tuple(grad_psum_axes)
        # ≙ amp.initialize(num_losses=N): independent scaler state per
        # loss; steps pick one via loss_id (GAN D/G, multi-task)
        if num_losses < 1:
            raise ValueError("num_losses must be >= 1")
        self.num_losses = int(num_losses)
        # ≙ amp.initialize(cast_model_outputs=dtype) for make_forward
        self.cast_model_outputs = cast_model_outputs

    # -- setup (≙ amp.initialize) ------------------------------------------
    def init(self, params) -> AmpState:
        params = self.policy.cast_to_param(params)
        ls = (self.scaler.init() if self.num_losses == 1
              else tuple(self.scaler.init()
                         for _ in range(self.num_losses)))
        return AmpState(step=jnp.zeros([], jnp.int32),
                        params=params,
                        opt_state=self.tx.init(params),
                        loss_scale=ls)

    def _get_ls(self, state: AmpState, loss_id: int) -> LossScaleState:
        if self.num_losses == 1:
            return state.loss_scale
        return state.loss_scale[loss_id]

    def _set_ls(self, state_ls, loss_id: int, new: LossScaleState):
        if self.num_losses == 1:
            return new
        return tuple(new if i == loss_id else s
                     for i, s in enumerate(state_ls))

    # -- per-step (≙ scale_loss + optimizer.step) --------------------------
    def make_train_step(self, loss_fn: Callable, *,
                        has_aux: bool = False,
                        loss_id: int = 0,
                        accum_steps: int = 1) -> Callable:
        """``loss_fn(params_compute, *batch) -> loss`` (or ``(loss, aux)``).

        The returned function is pure — wrap it in ``jax.jit`` / ``pjit`` /
        ``shard_map``. Under data parallelism with pjit, gradient psums come
        from sharding; under shard_map pass ``grad_psum_axes=("dp",)``.
        ``loss_id`` selects the scaler when ``num_losses > 1``
        (≙ ``amp.scale_loss(loss, opt, loss_id=i)``).

        ``accum_steps > 1``: gradient accumulation — every batch leaf must
        lead with the accumulation axis (``(accum_steps, ...)``); the
        microbatch loop rides ONE ``lax.scan`` (grads averaged, one
        optimizer step — ≙ the reference's grad-accumulation recipe and
        ``fwd_bwd_no_pipelining``'s grad-sync-on-last semantics under jit;
        activation memory is one microbatch's).
        """
        if not 0 <= loss_id < self.num_losses:
            raise ValueError(f"loss_id {loss_id} outside num_losses="
                             f"{self.num_losses}")
        if accum_steps < 1:
            raise ValueError("accum_steps must be >= 1")
        policy, scaler = self.policy, self.scaler

        # graftlint: hot -- returned for the caller to jax.jit (the
        # examples' `jax.jit(amp.make_train_step(...), donate...)`);
        # the call graph can't see through the closure return
        def train_step(state: AmpState, *batch):
            ls = self._get_ls(state, loss_id)

            def scaled_loss_fn(master_params, *mb):
                compute_params = policy.cast_to_compute(master_params)
                out = loss_fn(compute_params, *mb)
                loss, aux = out if has_aux else (out, None)
                return scaler.scale(loss.astype(jnp.float32),
                                    ls), (loss, aux)

            if accum_steps == 1:
                grads, (loss, aux) = jax.grad(
                    scaled_loss_fn, has_aux=True)(state.params, *batch)
            else:
                def body(carry, mb):
                    gacc, lacc = carry
                    g, (l, aux_mb) = jax.grad(scaled_loss_fn,
                                              has_aux=True)(
                        state.params, *mb)
                    return (jax.tree_util.tree_map(jnp.add, gacc, g),
                            lacc + l), aux_mb

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(jnp.shape(p), jnp.float32),
                    state.params)
                (grads, loss), aux = jax.lax.scan(
                    body, (zeros, jnp.zeros([], jnp.float32)), batch)
                inv = 1.0 / accum_steps
                # accumulate in fp32, then restore the accum_steps=1 dtype
                # contract (grads wrt masters carry the master dtype, which
                # is half under O3-style half-master policies)
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g * inv).astype(p.dtype), grads,
                    state.params)
                loss = loss * inv
                if has_aux:
                    # keep metrics["aux"] shape-stable across accum_steps:
                    # float leaves average over microbatches, other dtypes
                    # (counters/flags) keep the LAST microbatch's value
                    aux = jax.tree_util.tree_map(
                        lambda a: (jnp.mean(a, axis=0)
                                   if jnp.issubdtype(a.dtype, jnp.floating)
                                   else a[-1]), aux)
                else:
                    aux = None
            for ax in self.grad_psum_axes:
                grads = jax.lax.pmean(grads, ax)
                loss = jax.lax.pmean(loss, ax)  # report the GLOBAL mean
            grads = scaler.unscale(grads, ls)
            finite = all_finite(grads, axis_names=self.grad_psum_axes)
            gnorm = global_norm(grads)
            if self.max_grad_norm is not None:
                from apex1_tpu.optim.clip_grad import clip_grad_norm
                grads, _ = clip_grad_norm(grads, self.max_grad_norm)

            updates, new_opt_state = self.tx.update(grads, state.opt_state,
                                                    state.params)
            new_params = optax.apply_updates(state.params, updates)
            # skip-on-overflow: keep old params/opt state (≙ noop_flag)
            new_params = select_tree(finite, new_params, state.params)
            new_opt_state = select_tree(finite, new_opt_state,
                                        state.opt_state)
            new_ls = scaler.adjust(ls, finite)
            new_state = AmpState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                loss_scale=self._set_ls(state.loss_scale, loss_id, new_ls),
            )
            metrics = {
                "loss": loss.astype(jnp.float32),
                "grad_norm": gnorm,
                "loss_scale": ls.scale,
                "grads_finite": finite,
                "skipped_steps": new_ls.overflow_count,
            }
            if has_aux:
                metrics["aux"] = aux
            return new_state, metrics

        return train_step

    # -- parity helpers ----------------------------------------------------
    def master_params(self, state: AmpState):
        """≙ ``amp.master_params(optimizer)`` — the fp32 weights."""
        return state.params

    def model_params(self, state: AmpState):
        """The compute-dtype view the model consumes (O2's fp16 model)."""
        return self.policy.cast_to_compute(state.params)

    def make_forward(self, forward_fn: Callable) -> Callable:
        """O2-style patched forward for eval/inference: casts params (and
        float inputs) to the compute dtype, and the outputs to
        ``cast_model_outputs`` if set
        (≙ ``_initialize.py :: patch_forward`` + ``cast_model_outputs``)."""
        policy = self.policy

        def fwd(state_or_params, *inputs):
            params = (state_or_params.params
                      if isinstance(state_or_params, AmpState)
                      else state_or_params)
            params = policy.cast_to_compute(params)
            inputs = jax.tree_util.tree_map(
                lambda x: (x.astype(policy.compute_dtype)
                           if hasattr(x, "dtype")
                           and jnp.issubdtype(x.dtype, jnp.floating)
                           else x), inputs)
            out = forward_fn(params, *inputs)
            if self.cast_model_outputs is not None:
                out = jax.tree_util.tree_map(
                    lambda x: x.astype(self.cast_model_outputs), out)
            return out

        return fwd

    # ≙ amp.half_function / float_function / promote_function, bound to
    # THIS Amp's policy (the module-level forms take the policy explicitly)
    def half_function(self, fn):
        return self.policy.half_function(fn)

    def float_function(self, fn):
        return self.policy.float_function(fn)

    def promote_function(self, fn):
        return self.policy.promote_function(fn)

    @staticmethod
    def _one_sd(ls: LossScaleState):
        return {"loss_scale": ls.scale,
                "growth_count": ls.growth_count,
                "overflow_count": ls.overflow_count,
                "hysteresis_left": ls.hysteresis_left}

    def _one_ls(self, sd) -> LossScaleState:
        return LossScaleState(
            scale=jnp.asarray(sd["loss_scale"], jnp.float32),
            growth_count=jnp.asarray(sd["growth_count"], jnp.int32),
            overflow_count=jnp.asarray(sd["overflow_count"], jnp.int32),
            hysteresis_left=jnp.asarray(
                sd.get("hysteresis_left",
                       getattr(self.scaler, "hysteresis", 1)),
                jnp.int32))

    def state_dict(self, state: AmpState):
        """≙ ``amp.state_dict()`` — loss-scaler state for checkpointing
        (``loss_scaler{i}`` sub-dicts when ``num_losses > 1``, like the
        reference's per-loss scalers)."""
        if self.num_losses == 1:
            return self._one_sd(state.loss_scale)
        return {f"loss_scaler{i}": self._one_sd(s)
                for i, s in enumerate(state.loss_scale)}

    def load_state_dict(self, state: AmpState, sd) -> AmpState:
        if self.num_losses == 1:
            return dataclasses.replace(state, loss_scale=self._one_ls(sd))
        ls = tuple(self._one_ls(sd[f"loss_scaler{i}"])
                   for i in range(self.num_losses))
        return dataclasses.replace(state, loss_scale=ls)


def initialize(params, tx, opt_level: str = "O1", **overrides):
    """One-call form mirroring ``amp.initialize(model, optimizer,
    opt_level)``: returns ``(amp, state)``."""
    amp = Amp(tx=tx, opt_level=opt_level, **overrides)
    return amp, amp.init(params)


def half_function(fn, policy):
    """≙ ``amp.half_function`` (O1 FP16_FUNCS registration): returns
    ``fn`` with float inputs cast to the policy's compute dtype. Pass the
    policy (or opt-level name) you train with — or use the bound form
    ``Amp.half_function`` which uses the Amp's own policy."""
    return get_policy(policy).half_function(fn)


def float_function(fn, policy="O0"):
    """≙ ``amp.float_function`` (FP32_FUNCS): float inputs cast fp32
    (policy-independent — fp32 is fp32 under every opt level)."""
    return get_policy(policy).float_function(fn)


def promote_function(fn, policy="O0"):
    """≙ ``amp.promote_function`` (CASTS): promote-widest inputs
    (policy-independent — promotion looks only at the input dtypes)."""
    return get_policy(policy).promote_function(fn)


def scale_loss(loss, loss_scale_state: LossScaleState):
    """Shape-parity helper for hand-rolled steps
    (≙ ``with amp.scale_loss(loss, opt) as scaled:``)."""
    return loss * loss_scale_state.scale.astype(loss.dtype)
