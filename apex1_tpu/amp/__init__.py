"""Mixed-precision training services — reference ``apex/amp``.

The reference's ``amp.initialize(model, optimizer, opt_level)`` mutates a
torch model/optimizer in place (monkey-patching ops for O1, casting the
model + building fp32 master weights for O2) and ``amp.scale_loss`` wraps
``backward()``. In JAX the whole step is one traced function, so the same
capabilities become explicit state + a step builder:

    amp = Amp(tx=fused_adam(1e-4), opt_level="O2")
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(loss_fn))
    state, metrics = step(state, batch)

Correspondence:
- fp32 master weights (O2)  → ``state.params`` are ALWAYS fp32 (policy
  ``param_dtype``); compute sees ``policy.cast_to_compute(params)`` inside
  the grad, so grads arrive in fp32 against the masters
  (``_process_optimizer.py :: _master_params_to_model_params`` has no
  equivalent code — the cast is re-traced each step, free under jit).
- op lists (O1)             → ``policy.fp32_fragile_ops`` consumed by
  `apex1_tpu.ops` kernels.
- ``scale_loss`` + overflow skip → ``loss_scale`` state threaded through;
  non-finite grads skip the update via ``select_tree`` (device-side, no
  host sync — ≙ ``amp_C`` noop_flag) and halve the scale.
- ``amp.state_dict()``      → ``state.loss_scale`` is part of the pytree
  and checkpoints with everything else.

Reference anchors: ``apex/amp/frontend.py :: initialize``,
``apex/amp/handle.py :: scale_loss``, ``apex/amp/_process_optimizer.py``,
``apex/amp/scaler.py :: LossScaler``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import chex
import jax
import jax.numpy as jnp
import optax

from apex1_tpu.core.loss_scale import (LossScaleState, all_finite,
                                       make_loss_scale, select_tree)
from apex1_tpu.core.policy import PrecisionPolicy, get_policy
from apex1_tpu.core.pytree import global_norm


@chex.dataclass
class AmpState:
    """Train state: fp32 master params + optimizer state + loss-scale state.

    ≙ the (model, optimizer, amp.state_dict()) triple the reference
    checkpoints (README "checkpointing" recipe).
    """

    step: jnp.ndarray
    params: Any
    opt_state: Any
    loss_scale: LossScaleState


class Amp:
    """Bundle of precision policy + optimizer transform.

    ``opt_level``/overrides mirror ``amp.initialize`` kwargs:
    ``Amp(tx, opt_level="O2", loss_scale=128.0, keep_norms_fp32=False)``.
    """

    def __init__(self, tx: optax.GradientTransformation,
                 opt_level: str | PrecisionPolicy = "O1",
                 max_grad_norm: float | None = None,
                 grad_psum_axes: tuple[str, ...] = (),
                 **policy_overrides):
        self.tx = tx
        self.policy = get_policy(opt_level, **policy_overrides)
        self.scaler = make_loss_scale(self.policy.loss_scale)
        self.max_grad_norm = max_grad_norm
        # mesh axes to pmean grads over (shard_map DDP; pjit needs none)
        self.grad_psum_axes = tuple(grad_psum_axes)

    # -- setup (≙ amp.initialize) ------------------------------------------
    def init(self, params) -> AmpState:
        params = self.policy.cast_to_param(params)
        return AmpState(step=jnp.zeros([], jnp.int32),
                        params=params,
                        opt_state=self.tx.init(params),
                        loss_scale=self.scaler.init())

    # -- per-step (≙ scale_loss + optimizer.step) --------------------------
    def make_train_step(self, loss_fn: Callable, *,
                        has_aux: bool = False) -> Callable:
        """``loss_fn(params_compute, *batch) -> loss`` (or ``(loss, aux)``).

        The returned function is pure — wrap it in ``jax.jit`` / ``pjit`` /
        ``shard_map``. Under data parallelism with pjit, gradient psums come
        from sharding; under shard_map pass ``grad_psum_axes=("dp",)``.
        """
        policy, scaler = self.policy, self.scaler

        def train_step(state: AmpState, *batch):
            def scaled_loss_fn(master_params):
                compute_params = policy.cast_to_compute(master_params)
                out = loss_fn(compute_params, *batch)
                loss, aux = out if has_aux else (out, None)
                return scaler.scale(loss.astype(jnp.float32),
                                    state.loss_scale), (loss, aux)

            grads, (loss, aux) = jax.grad(scaled_loss_fn, has_aux=True)(
                state.params)
            for ax in self.grad_psum_axes:
                grads = jax.lax.pmean(grads, ax)
                loss = jax.lax.pmean(loss, ax)  # report the GLOBAL mean
            grads = scaler.unscale(grads, state.loss_scale)
            finite = all_finite(grads, axis_names=self.grad_psum_axes)
            gnorm = global_norm(grads)
            if self.max_grad_norm is not None:
                from apex1_tpu.optim.clip_grad import clip_grad_norm
                grads, _ = clip_grad_norm(grads, self.max_grad_norm)

            updates, new_opt_state = self.tx.update(grads, state.opt_state,
                                                    state.params)
            new_params = optax.apply_updates(state.params, updates)
            # skip-on-overflow: keep old params/opt state (≙ noop_flag)
            new_params = select_tree(finite, new_params, state.params)
            new_opt_state = select_tree(finite, new_opt_state,
                                        state.opt_state)
            new_state = AmpState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                loss_scale=scaler.adjust(state.loss_scale, finite),
            )
            metrics = {
                "loss": loss.astype(jnp.float32),
                "grad_norm": gnorm,
                "loss_scale": state.loss_scale.scale,
                "grads_finite": finite,
                "skipped_steps": new_state.loss_scale.overflow_count,
            }
            if has_aux:
                metrics["aux"] = aux
            return new_state, metrics

        return train_step

    # -- parity helpers ----------------------------------------------------
    def master_params(self, state: AmpState):
        """≙ ``amp.master_params(optimizer)`` — the fp32 weights."""
        return state.params

    def model_params(self, state: AmpState):
        """The compute-dtype view the model consumes (O2's fp16 model)."""
        return self.policy.cast_to_compute(state.params)

    def state_dict(self, state: AmpState):
        """≙ ``amp.state_dict()`` — loss-scaler state for checkpointing."""
        return {"loss_scale": state.loss_scale.scale,
                "growth_count": state.loss_scale.growth_count,
                "overflow_count": state.loss_scale.overflow_count,
                "hysteresis_left": state.loss_scale.hysteresis_left}

    def load_state_dict(self, state: AmpState, sd) -> AmpState:
        return dataclasses.replace(
            state,
            loss_scale=LossScaleState(
                scale=jnp.asarray(sd["loss_scale"], jnp.float32),
                growth_count=jnp.asarray(sd["growth_count"], jnp.int32),
                overflow_count=jnp.asarray(sd["overflow_count"],
                                           jnp.int32),
                hysteresis_left=jnp.asarray(
                    sd.get("hysteresis_left",
                           getattr(self.scaler, "hysteresis", 1)),
                    jnp.int32)))


def initialize(params, tx, opt_level: str = "O1", **overrides):
    """One-call form mirroring ``amp.initialize(model, optimizer,
    opt_level)``: returns ``(amp, state)``."""
    amp = Amp(tx=tx, opt_level=opt_level, **overrides)
    return amp, amp.init(params)


def scale_loss(loss, loss_scale_state: LossScaleState):
    """Shape-parity helper for hand-rolled steps
    (≙ ``with amp.scale_loss(loss, opt) as scaled:``)."""
    return loss * loss_scale_state.scale.astype(loss.dtype)
