"""`apex1_tpu.autopilot` — the telemetry-driven fleet control loop
(ROADMAP item 4).

PR 10 made the fleet observable (obs spine, `ServingMetrics`); PR 7
made it controllable (QoS ladder, degrade profiles, replica
supervision). This package connects the two: a controller that
consumes rolling per-class latency/TTFT percentiles and actuates the
`ServingFrontend` knob surface — replica scale-up/down, overload-mode
selection, admission setpoints, per-tenant hedge budgets — with every
actuation banked beside the evidence that triggered it.

- `policy` — the PURE decision core (`decide`: snapshot + state →
  actions; hysteresis, escalation ladder, setpoint fits).
- `controller` — `Autopilot`: measure → decide → actuate → bank
  against a live frontend.
- `testing.fleetsim` — the replayable fleet simulator the whole loop
  is validated on (virtual clock, seed-keyed traces + chaos,
  bit-deterministic episodes).

``python -m apex1_tpu.autopilot --smoke`` replays the headline drill
(static threshold ladder misses guaranteed-class p99 on an overload
trace, the autopilot holds it, the episode replays bit-identically) —
check_all's ``== autopilot smoke ==`` step. See docs/autopilot.md.
"""

from apex1_tpu.autopilot.controller import Autopilot  # noqa: F401
from apex1_tpu.autopilot.policy import (Action,  # noqa: F401
                                        AutopilotConfig,
                                        ControllerState, FleetView,
                                        SLOTarget, decide,
                                        default_slo)
