"""Autopilot policy — the PURE decision core of the fleet control loop.

Everything here is a function of (snapshot, controller state, config):
no clocks, no I/O, no frontend handles — `decide` is unit-testable and
replay-deterministic by construction. The side-effecting half
(`controller.Autopilot`) builds the `FleetView` snapshot from
`ServingFrontend.summary()` and applies the returned `Action`s to the
frontend's knob surface.

The control contract (docs/autopilot.md):

- **Signal**: per-class rolling-window latency/TTFT p99s
  (`ServingMetrics` ring buffer) against per-class `SLOTarget`s — NOT
  raw queue depth; queue depth says a queue exists, percentiles say
  users are hurting.
- **Hysteresis**: a breach must hold for ``breach_sustain``
  consecutive ticks before anything actuates (a burst is not an
  overload), relief must hold for the LONGER ``clear_sustain`` before
  anything relaxes, and every actuation starts a ``cooldown_ticks``
  refractory period — the anti-flap triad the oscillation tests pin.
- **Escalation ladder** (cheapest relief first):
  ``shed sheddable load → add replicas (to max_replicas) → degrade →
  tighten the admission setpoint``; relaxation unwinds the same ladder
  in reverse, one rung per sustained-clear window.
- **Setpoint fitting**: per-tenant hedge/TTFT budgets are FIT from the
  measured windowed TTFT distribution (``multiplier x p99``, floored),
  replacing the hand-tuned global ``hedge_after_s`` — the same
  measured-not-hand-picked move the planner (PR 12) made for parallel
  layouts.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = [
    "SLOTarget", "AutopilotConfig", "FleetView", "ControllerState",
    "Action", "decide", "default_slo",
]


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """Per-class objective; None disables that dimension.

    ``success_rate`` is the windowed fraction of terminal outcomes
    that are "done" — the dimension that sees ADMISSION-induced
    misses: under a hard overload the accepted requests' latency can
    look healthy precisely BECAUSE the front door is rejecting the
    excess, so a percentile-only controller would sleep through the
    worst failure mode (latency percentiles survive only on accepted
    traffic)."""

    latency_p99_ms: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    tpot_p99_ms: Optional[float] = None   # decode-phase time per
    #  output token — with ttft_p99_ms this is the PER-PHASE pair the
    #  pool-ratio actuator balances on a disaggregated fleet
    success_rate: Optional[float] = None


def default_slo() -> Dict[str, SLOTarget]:
    """Guard the guaranteed class only — best_effort/sheddable are,
    definitionally, what gets traded away under pressure."""
    return {"guaranteed": SLOTarget(latency_p99_ms=1000.0,
                                    success_rate=0.95)}


@dataclasses.dataclass
class AutopilotConfig:
    """Control-loop knobs. Tick cadence is owned by the caller (the
    simulator ticks on virtual time); everything here counts TICKS."""

    slo: Dict[str, SLOTarget] = dataclasses.field(
        default_factory=default_slo)
    min_replicas: int = 1
    max_replicas: int = 4
    breach_sustain: int = 3        # ticks in breach before actuating
    clear_sustain: int = 8         # ticks clear before relaxing (slower
    #                                down than up — the asymmetry that
    #                                keeps relief from flapping)
    cooldown_ticks: int = 4        # refractory period after any rung
    min_window: int = 8            # windowed samples needed to act on a
    #                                class (thin evidence actuates
    #                                nothing, in either direction)
    scale_down_headroom: float = 0.5   # p99 must sit under
    #                                    headroom x target to shrink
    load_scale_down: float = 0.35      # ... AND load under this
    admission_decrease: float = 0.85   # AIMD tighten factor (x current
    #                                    inflight) on the last rung
    fit_hedge: bool = True
    fit_every: int = 16            # hedge-budget refit cadence (ticks)
    hedge_multiplier: float = 3.0  # budget = mult x windowed ttft_p99
    hedge_floor_s: float = 0.05
    hedge_rel_tol: float = 0.1     # refit only on >10% movement
    # ---- pool-ratio actuator (disaggregated fleets only: inert
    # unless the view carries a `pools` snapshot AND some SLO'd class
    # targets both ttft_p99_ms and tpot_p99_ms)
    pool_ratio: bool = True
    pool_deadband: float = 1.3     # one phase's normalized pressure
    #  must exceed the other's by this factor before the imbalance
    #  even counts — the hysteresis band that keeps the ratio from
    #  thrashing on noise
    pool_sustain: int = 4          # ticks the SAME side must stay
    #                                pressured before a shift
    pool_cooldown: int = 6         # refractory ticks after a shift (a
    #  moved replica needs a window's worth of traffic to show up in
    #  the percentiles — reacting faster would double-correct)


@dataclasses.dataclass
class FleetView:
    """The normalized snapshot `decide` consumes — built by the
    controller from `ServingFrontend.summary()` (so policy tests can
    hand-build one)."""

    mode: str
    load_fraction: float
    inflight: int
    capacity: int
    n_replicas: int                # supervisors ever built
    n_alive: int                   # routable now (excl. retiring)
    admission_limit: Optional[int]
    window: dict                   # summary()["window"]["per_class"]
    per_tenant: dict               # summary()["window"]["per_tenant"]
    pools: Optional[dict] = None   # DisaggFrontend.pool_view() on a
    #  disaggregated fleet ({"prefill": {...}, "decode": {...}});
    #  None on a unified fleet — the pool-ratio law stays inert


@dataclasses.dataclass
class ControllerState:
    """Mutable controller memory between ticks."""

    ticks: int = 0
    breach_ticks: int = 0
    clear_ticks: int = 0
    cooldown: int = 0
    hedge_budgets: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # pool-ratio hysteresis (separate counters: the ratio law and the
    # capacity ladder must not share a refractory period)
    pool_side: str = ""            # which phase is pressured: ""/
    #                                "prefill"/"decode"
    pool_imbalance_ticks: int = 0
    pool_cooldown: int = 0


@dataclasses.dataclass
class Action:
    """One actuation: ``kind`` picks the frontend knob, ``params``
    feed it, ``evidence`` is the triggering measurement banked beside
    the actuation (spine + transitions)."""

    kind: str      # escalate|deescalate|scale_up|scale_down|
    #                set_admission|fit_hedge|shift_pool
    params: dict
    evidence: dict


def _breaches(view: FleetView, cfg: AutopilotConfig) -> List[dict]:
    """Every (class, metric) whose windowed p99 exceeds its SLO target,
    with the numbers attached. Classes with fewer than ``min_window``
    samples contribute nothing — no evidence, no verdict."""
    out = []
    for cls, target in sorted(cfg.slo.items()):
        stats = view.window.get(cls)
        if not stats or stats.get("n", 0) < cfg.min_window:
            continue
        for metric, want in (("latency_p99_ms", target.latency_p99_ms),
                             ("ttft_p99_ms", target.ttft_p99_ms),
                             ("tpot_p99_ms", target.tpot_p99_ms)):
            got = stats.get(metric)
            if want is not None and got is not None and got > want:
                out.append({"class": cls, "metric": metric,
                            "value": round(got, 3), "target": want,
                            "n": stats["n"]})
        if target.success_rate is not None:
            got = stats["done"] / stats["n"]
            if got < target.success_rate:
                out.append({"class": cls, "metric": "success_rate",
                            "value": round(got, 4),
                            "target": target.success_rate,
                            "n": stats["n"]})
    return out


def _has_evidence(view: FleetView, cfg: AutopilotConfig) -> bool:
    """True when at least one SLO'd class has a full-enough window to
    judge. With NO evidence the controller must freeze — counting
    evidence-free ticks as "clear" would relax straight back into a
    live overload whose guaranteed entries were merely crowded out of
    the shared ring."""
    return any(
        (view.window.get(cls) or {}).get("n", 0) >= cfg.min_window
        for cls in cfg.slo)


def _headroom_ok(view: FleetView, cfg: AutopilotConfig) -> bool:
    """True when every SLO'd class with evidence sits comfortably
    under its targets — the precondition for giving capacity back."""
    for cls, target in cfg.slo.items():
        stats = view.window.get(cls)
        if not stats or stats.get("n", 0) < cfg.min_window:
            continue
        for metric, want in (("latency_p99_ms", target.latency_p99_ms),
                             ("ttft_p99_ms", target.ttft_p99_ms),
                             ("tpot_p99_ms", target.tpot_p99_ms)):
            got = stats.get(metric)
            if want is not None and got is not None \
                    and got > cfg.scale_down_headroom * want:
                return False
        if target.success_rate is not None \
                and stats["done"] / stats["n"] < target.success_rate:
            return False
    return True


def _escalation(view: FleetView, cfg: AutopilotConfig,
                evidence: dict) -> Optional[Action]:
    """One rung up the relief ladder, cheapest first."""
    if view.mode == "normal":
        return Action("escalate", {"mode": "shedding"}, evidence)
    if view.n_alive < cfg.max_replicas:
        return Action("scale_up", {}, evidence)
    if view.mode == "shedding":
        return Action("escalate", {"mode": "degraded"}, evidence)
    # everything cheaper is spent: tighten the admission setpoint so
    # queueing delay stops compounding (AIMD decrease; rejected load
    # retries against a 429 instead of rotting in the queue)
    limit = max(view.n_alive, int(view.inflight
                                  * cfg.admission_decrease))
    if view.admission_limit is None or limit < view.admission_limit:
        return Action("set_admission", {"limit": limit}, evidence)
    return None


def _relaxation(view: FleetView, cfg: AutopilotConfig,
                evidence: dict) -> Optional[Action]:
    """One rung back down, unwinding `_escalation` in reverse."""
    if view.admission_limit is not None:
        return Action("set_admission", {"limit": None}, evidence)
    if view.mode == "degraded":
        return Action("deescalate", {"mode": "shedding"}, evidence)
    if (view.n_alive > cfg.min_replicas
            and view.load_fraction <= cfg.load_scale_down
            and _headroom_ok(view, cfg)):
        # capacity is the most expensive rung, so it unwinds as soon as
        # load AND percentiles prove it idle — but never on load alone:
        # a breach-free window under p99 headroom is required too
        return Action("scale_down", {}, evidence)
    if view.mode == "shedding":
        return Action("deescalate", {"mode": "normal"}, evidence)
    return None


def _pool_pressures(view: FleetView,
                    cfg: AutopilotConfig) -> Optional[dict]:
    """Normalized per-phase pressure of a disaggregated fleet: over
    every SLO'd class with enough window samples, the worst
    ``measured p99 / target`` for TTFT (prefill-tier pressure) and for
    TPOT (decode-tier pressure). None unless BOTH phases have a target
    and a measurement — a one-sided reading says which phase is slow,
    not which phase is slowER, and the ratio actuator must never act
    on half a comparison."""
    pre = dec = None
    ev = {}
    for cls, target in sorted(cfg.slo.items()):
        stats = view.window.get(cls)
        if not stats or stats.get("n", 0) < cfg.min_window:
            continue
        if target.ttft_p99_ms is not None:
            got = stats.get("ttft_p99_ms")
            if got is not None:
                p = got / target.ttft_p99_ms
                if pre is None or p > pre:
                    pre = p
                    ev["ttft"] = {"class": cls,
                                  "value": round(got, 3),
                                  "target": target.ttft_p99_ms}
        if target.tpot_p99_ms is not None:
            got = stats.get("tpot_p99_ms")
            if got is not None:
                p = got / target.tpot_p99_ms
                if dec is None or p > dec:
                    dec = p
                    ev["tpot"] = {"class": cls,
                                  "value": round(got, 3),
                                  "target": target.tpot_p99_ms}
    if pre is None or dec is None:
        return None
    return {"prefill": pre, "decode": dec, "evidence": ev}


def _pool_ratio(view: FleetView, state: ControllerState,
                cfg: AutopilotConfig) -> Optional[Action]:
    """The pool-RATIO law: when one phase's normalized pressure has
    exceeded the other's by ``pool_deadband`` for ``pool_sustain``
    consecutive ticks, shift one replica toward the pressured phase —
    capacity conserved, balance moved. Guardrails: the donor pool must
    keep >= 1 replica (enforced here on the view AND again by
    `shift_pool` itself), and every shift starts its own
    ``pool_cooldown`` refractory period."""
    if not cfg.pool_ratio or view.pools is None:
        return None
    p = _pool_pressures(view, cfg)
    if p is None:
        state.pool_side = ""
        state.pool_imbalance_ticks = 0
        return None
    if p["prefill"] > cfg.pool_deadband * p["decode"]:
        side = "prefill"
    elif p["decode"] > cfg.pool_deadband * p["prefill"]:
        side = "decode"
    else:
        side = ""
    if side != state.pool_side:
        state.pool_side = side
        state.pool_imbalance_ticks = 1 if side else 0
    elif side:
        state.pool_imbalance_ticks += 1
    if (not side or state.pool_imbalance_ticks < cfg.pool_sustain
            or state.pool_cooldown > 0):
        return None
    donor = "decode" if side == "prefill" else "prefill"
    if view.pools.get(donor, {}).get("n_alive", 0) <= 1:
        return None                  # each phase always keeps a pool
    evidence = {
        "pressure_prefill": round(p["prefill"], 4),
        "pressure_decode": round(p["decode"], 4),
        "deadband": cfg.pool_deadband,
        "imbalance_ticks": state.pool_imbalance_ticks,
        "pools": view.pools, **p["evidence"]}
    state.pool_imbalance_ticks = 0
    state.pool_cooldown = cfg.pool_cooldown
    return Action("shift_pool", {"to": side}, evidence)


def _fit_hedges(view: FleetView, state: ControllerState,
                cfg: AutopilotConfig) -> List[Action]:
    """Refit per-tenant hedge/TTFT budgets from the measured windowed
    TTFT distribution; emit only on material movement."""
    out = []
    for tenant, stats in sorted(view.per_tenant.items()):
        if stats.get("n", 0) < cfg.min_window:
            continue
        p99 = stats.get("ttft_p99_ms")
        if p99 is None:
            continue
        budget = max(cfg.hedge_floor_s,
                     cfg.hedge_multiplier * p99 / 1e3)
        prev = state.hedge_budgets.get(tenant)
        if prev is not None and abs(budget - prev) \
                <= cfg.hedge_rel_tol * prev:
            continue
        state.hedge_budgets[tenant] = budget
        out.append(Action(
            "fit_hedge", {"tenant": tenant,
                          "budget_s": round(budget, 6)},
            {"ttft_p99_ms": p99, "n": stats["n"],
             "multiplier": cfg.hedge_multiplier}))
    return out


def decide(view: FleetView, state: ControllerState,
           cfg: AutopilotConfig) -> List[Action]:
    """One control tick: update the hysteresis counters, emit at most
    one ladder action (plus any hedge-budget refits). Mutates
    ``state``; pure in everything else."""
    state.ticks += 1
    if state.cooldown > 0:
        state.cooldown -= 1
    if state.pool_cooldown > 0:
        state.pool_cooldown -= 1
    if not _has_evidence(view, cfg):
        # thin evidence actuates nothing, in EITHER direction: freeze
        # the hysteresis counters (an evidence-free tick is not a
        # "clear" tick) and emit only the self-gated hedge refits
        actions: List[Action] = []
        if cfg.fit_hedge and state.ticks % cfg.fit_every == 0:
            actions.extend(_fit_hedges(view, state, cfg))
        return actions
    breaches = _breaches(view, cfg)
    if breaches:
        state.breach_ticks += 1
        state.clear_ticks = 0
    else:
        state.clear_ticks += 1
        state.breach_ticks = 0
    evidence = {
        "breaches": breaches, "breach_ticks": state.breach_ticks,
        "clear_ticks": state.clear_ticks, "mode": view.mode,
        "load_fraction": round(view.load_fraction, 4),
        "inflight": view.inflight, "n_alive": view.n_alive,
    }
    actions: List[Action] = []
    if state.breach_ticks >= cfg.breach_sustain and state.cooldown == 0:
        act = _escalation(view, cfg, evidence)
        if act is not None:
            actions.append(act)
            state.cooldown = cfg.cooldown_ticks
            state.breach_ticks = 0
    elif state.clear_ticks >= cfg.clear_sustain and state.cooldown == 0:
        act = _relaxation(view, cfg, evidence)
        if act is not None:
            actions.append(act)
            state.cooldown = cfg.cooldown_ticks
            state.clear_ticks = 0
    # the ratio law runs BESIDE the capacity ladder (own hysteresis,
    # own cooldown): rebalancing a fixed fleet and resizing it are
    # orthogonal corrections
    pool_act = _pool_ratio(view, state, cfg)
    if pool_act is not None:
        actions.append(pool_act)
    if cfg.fit_hedge and state.ticks % cfg.fit_every == 0:
        actions.extend(_fit_hedges(view, state, cfg))
    return actions
