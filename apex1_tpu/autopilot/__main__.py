"""``python -m apex1_tpu.autopilot --smoke`` — the ``== autopilot
smoke ==`` step in tools/check_all.sh (~10 s, CPU, jax on the toy
decoder only).

Replays the headline drill (`autopilot.drill`): the static
threshold-ladder sweep misses guaranteed-class SLO attainment on the
adversarial-overload trace, the autopilot holds it from the same
baseline provisioning, every actuation is banked with evidence, and
the autopilot episode replays BIT-IDENTICALLY (fingerprint equality
across two runs of the same (trace, seed))."""

from __future__ import annotations

import sys
from typing import Optional, Sequence


def _smoke() -> int:
    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   force_virtual_cpu_devices)

    force_virtual_cpu_devices(1)
    enable_persistent_compilation_cache()

    from apex1_tpu.autopilot import drill
    from apex1_tpu.testing.fleetsim import run_fleet

    res = drill.run_headline()
    v = res.verdict()
    for name, att in sorted(v["static"].items()):
        print(f"  {name:16s} guaranteed attainment {att:6.1%}  "
              f"(SLO {drill.SLO_ATTAINMENT:.0%} within "
              f"{drill.SLO_LATENCY_S}s)")
    print(f"  {'autopilot':16s} guaranteed attainment "
          f"{v['autopilot']:6.1%}  ({v['n_actions']} banked actuations)")
    assert v["every_static_misses"], (
        f"a static config held the SLO — the drill premise broke: "
        f"{v['static']}")
    assert v["autopilot_holds"], (
        f"autopilot missed the SLO: {v['autopilot']:.3f} < "
        f"{drill.SLO_ATTAINMENT}")
    print(f"autopilot smoke [1/2] OK: every static ladder config "
          f"missed, autopilot held ({v['autopilot']:.1%}) with "
          f"{v['n_actions']} actuations banked")

    # bit-determinism: replay the autopilot arm, same (trace, seed)
    rerun = run_fleet(res.trace, drill.frontend_config(),
                      sim=drill.sim_config(),
                      autopilot=drill.autopilot_config())
    assert rerun.fingerprint() == res.auto.fingerprint(), \
        "replay diverged: same (trace, seed) must be bit-identical"
    print(f"autopilot smoke [2/2] OK: replay bit-identical "
          f"(fingerprint {res.auto.fingerprint()[:16]}…)")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="run the headline overload drill + "
                         "determinism replay (CPU, ~10s)")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
