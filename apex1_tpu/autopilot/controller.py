"""Autopilot controller — binds the pure policy to a live
`ServingFrontend`.

`Autopilot.tick()` is the whole loop: snapshot the frontend
(`summary()` → `FleetView`), run `policy.decide`, apply each returned
`Action` through the frontend's actuation surface, and BANK it —
every actuation lands (1) in ``self.actions`` (the in-memory episode
log the drills assert on), (2) as a ``ServingMetrics.transition``
(event ``"autopilot"``) beside the mode/shed/restart history, and
(3) as an ``autopilot.action`` event on the telemetry spine when
``APEX1_OBS_DIR`` is set — with the triggering evidence (the breached
percentiles, the sustain counters, the load fraction) attached at
every layer, so a whole episode is reconstructable from banked events
alone (the headline drill's assertion).

Attaching an Autopilot flips the frontend to
``mode_control="external"``: from then on overload-mode transitions
are driven by per-class latency/TTFT percentiles, not the built-in
load-fraction ladder. The caller owns tick cadence — call `tick()`
from the supervision loop (`testing.fleetsim` ticks on virtual time
every ``control_interval_s``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from apex1_tpu.autopilot.policy import (Action, AutopilotConfig,
                                        ControllerState, FleetView,
                                        decide)
from apex1_tpu.obs import spine

__all__ = ["Autopilot"]

MODES_DOWN = {"degraded": "shedding", "shedding": "normal"}


class Autopilot:
    """The fleet control loop: measure → decide → actuate → bank.

    ``frontend`` is a `serving.ServingFrontend`; ``config`` an
    `AutopilotConfig` (default: guard guaranteed-class p99 latency at
    1s). ``clock`` defaults to the frontend's own (virtual under
    `testing.fleetsim`).
    """

    def __init__(self, frontend, config: Optional[AutopilotConfig] = None,
                 *, clock: Optional[Callable[[], float]] = None):
        self.frontend = frontend
        self.cfg = config or AutopilotConfig()
        self.clock = clock or frontend.clock
        self.state = ControllerState()
        self.actions: List[dict] = []
        frontend.mode_control = "external"
        frontend.metrics.transition(
            "autopilot_attached",
            slo={cls: dataclasses.asdict(t)
                 for cls, t in sorted(self.cfg.slo.items())},
            min_replicas=self.cfg.min_replicas,
            max_replicas=self.cfg.max_replicas)

    # ---- measure ---------------------------------------------------------

    def view(self) -> FleetView:
        """Snapshot the frontend into the policy's input shape — via
        the O(window) accessor, never `summary()` (whole-run
        percentile sorts grow with every request ever served; a
        per-tick read must not pay that under the metrics lock)."""
        f = self.frontend
        win = f.metrics.window_summary()
        pv = getattr(f, "pool_view", None)   # DisaggFrontend only —
        #  a unified frontend's view carries pools=None and the
        #  pool-ratio law stays inert
        return FleetView(
            mode=f.mode, load_fraction=f.load_fraction,
            inflight=f.total_inflight, capacity=f.capacity,
            n_replicas=len(f.replicas), n_alive=f.n_alive,
            admission_limit=f.admission_limit,
            window=win.get("per_class", {}),
            per_tenant=win.get("per_tenant", {}),
            pools=pv() if callable(pv) else None)

    # ---- the loop --------------------------------------------------------

    def tick(self) -> List[Action]:
        """One control tick; returns the actions applied (often
        none — hysteresis is the point)."""
        v = self.view()
        actions = decide(v, self.state, self.cfg)
        for act in actions:
            self._apply(act, v)
        return actions

    # ---- actuate + bank --------------------------------------------------

    def _apply(self, act: Action, view: FleetView):
        f = self.frontend
        result: dict = {}
        if act.kind == "escalate" or act.kind == "deescalate":
            f.set_mode(act.params["mode"], by="autopilot",
                       evidence=act.evidence)
            result["mode"] = f.mode
        elif act.kind == "scale_up":
            result["replica"] = f.add_replica(by="autopilot",
                                              evidence=act.evidence)
        elif act.kind == "scale_down":
            rid = f.retire_replica(by="autopilot",
                                   evidence=act.evidence)
            result["replica"] = rid
            if rid is None:            # nothing retirable after all —
                result["noop"] = True  # banked as such, not hidden
        elif act.kind == "set_admission":
            f.set_admission_limit(act.params["limit"], by="autopilot",
                                  evidence=act.evidence)
            result["limit"] = act.params["limit"]
        elif act.kind == "fit_hedge":
            f.set_hedge_budget(act.params["budget_s"],
                               tenant=act.params["tenant"],
                               by="autopilot", evidence=act.evidence)
            result.update(act.params)
        elif act.kind == "shift_pool":
            shifted = f.shift_pool(act.params["to"], by="autopilot",
                                   evidence=act.evidence)
            if shifted is None:        # donor at minimum after all —
                result["noop"] = True  # banked as such, not hidden
            else:
                result.update(shifted)
        else:                          # a policy/controller version skew
            raise ValueError(f"unknown action kind {act.kind!r}")
        rec = {"t": round(self.clock(), 6), "tick": self.state.ticks,
               "action": act.kind, "params": act.params,
               "result": result, "evidence": act.evidence}
        self.actions.append(rec)
        # the dedicated spine event (the knob calls above ALSO mirror
        # through serving.transition; this one carries the full record
        # under one greppable name). The controller clock's origin is
        # its own (virtual under fleetsim) — it must not land on the
        # spine's run-relative `t` axis (same origin rule as
        # serving.metrics' t_serving).
        spine.emit("event", "autopilot.action",
                   **{("t_ctrl" if k == "t" else k): v
                      for k, v in rec.items()})
        f.metrics.transition("autopilot", action=act.kind,
                             params=act.params, result=result,
                             evidence=act.evidence)
