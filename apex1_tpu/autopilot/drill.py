"""The headline autopilot drill — one scenario, three consumers
(tier-1 `tests/test_autopilot.py`, ``python -m apex1_tpu.autopilot
--smoke``, `tools/bench_autopilot.py`), so the claim every surface
makes is the SAME claim.

THE CLAIM (ROADMAP item 4's "done" line): on a replayed
adversarial-overload trace whose guaranteed-class demand alone exceeds
the provisioned fleet's service rate, EVERY static `FrontendConfig` in
the stated sweep — the hand-tunable threshold-ladder knobs at baseline
provisioning, from lenient to panic — misses the guaranteed-class SLO,
while the autopilot (same baseline provisioning, same trace, same
seed) holds it by actuating what no static ladder can: elastic
capacity, percentile-driven mode selection, admission setpoints. And
the whole episode is reconstructable from banked events and replays
bit-identically.

THE SWEEP IS STATED, NOT IMPLIED: it varies every knob the static
overload ladder HAS (thresholds, sustain, degrade caps) at the
baseline ``N_BASELINE`` replicas. A static config with the
autopilot's peak fleet size pre-provisioned would of course hold the
SLO — by paying for peak capacity all day; the autopilot's point is
holding it from baseline provisioning, scaling back after
(`SimReport.summary["replicas"]` shows the retirements).

Provisioning arithmetic (`FleetSimConfig` docstring): one replica
serves ``slots / (mean_new_tokens * dt_s)`` ≈ 29 req/s here; the
overload phase offers ~120 req/s with half guaranteed, so guaranteed
demand (~60 req/s) alone exceeds the 2-replica fleet (~57 req/s) no
matter what the ladder sheds, and fits easily at the autopilot's
4-replica ceiling (~114 req/s).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from apex1_tpu.autopilot.policy import AutopilotConfig, SLOTarget
from apex1_tpu.testing.fleetsim import (FleetSimConfig, SimReport,
                                        Trace, run_fleet,
                                        synthetic_trace)

__all__ = [
    "SLO_LATENCY_S", "SLO_ATTAINMENT", "N_BASELINE", "overload_trace",
    "static_sweep", "autopilot_config", "sim_config", "frontend_config",
    "run_headline",
]

#: the guaranteed-class SLO the drill holds: this fraction of OFFERED
#: guaranteed load must finish within this many (virtual) seconds
SLO_LATENCY_S = 1.0
SLO_ATTAINMENT = 0.90

#: baseline provisioning — both the static sweep and the autopilot
#: start here; only the autopilot may leave it
N_BASELINE = 2
N_MAX = 4


def sim_config(**over) -> FleetSimConfig:
    return FleetSimConfig(**{**dict(dt_s=0.02, control_interval_s=0.1,
                                    slots_per_replica=4), **over})


def overload_trace(seed: int = 20260804, *, scale: float = 1.0,
                   horizon_s: float = 6.0) -> Trace:
    """The adversarial-overload replay input: ~40 req/s baseline,
    3x that for the middle 55% of the horizon, half guaranteed.
    ``scale`` multiplies the rate (benches crank it; tier-1 keeps
    1.0 ≈ 450 requests)."""
    return synthetic_trace(
        "adversarial_overload", seed=seed, horizon_s=horizon_s,
        base_rate=40.0 * scale, overload_mult=3.0,
        overload_span=(0.25, 0.80),
        class_mix={"guaranteed": 0.5, "best_effort": 0.25,
                   "sheddable": 0.25})


def frontend_config(**over):
    """Baseline frontend: the shape both arms share. Hedging is off so
    the capacity arithmetic above stays exact (the hedge-budget FIT is
    exercised by its own test + the diurnal bench trace)."""
    from apex1_tpu.serving import FrontendConfig, ReplicaConfig

    kw = dict(n_replicas=N_BASELINE, capacity_per_replica=16,
              hedge_after_s=None, seed=7,
              replica=ReplicaConfig(watchdog_s=1e9))
    kw.update(over)
    return FrontendConfig(**kw)


def static_sweep() -> List[Tuple[str, object]]:
    """The stated sweep: every hand-tunable knob of the static
    overload ladder, at baseline provisioning, lenient → panic."""
    from apex1_tpu.serving import DegradeProfile

    return [
        ("static-lenient", frontend_config(
            enter_shed=0.90, enter_degraded=0.98, exit_overload=0.6,
            sustain_rounds=8)),
        ("static-default", frontend_config()),
        ("static-panic", frontend_config(
            enter_shed=0.45, enter_degraded=0.70, exit_overload=0.3,
            sustain_rounds=2,
            degrade=DegradeProfile(max_new_tokens_cap=4))),
    ]


def autopilot_config(**over) -> AutopilotConfig:
    kw = dict(
        slo={"guaranteed": SLOTarget(
            latency_p99_ms=1e3 * SLO_LATENCY_S, success_rate=0.95)},
        min_replicas=N_BASELINE, max_replicas=N_MAX,
        breach_sustain=3, clear_sustain=8, cooldown_ticks=3,
        min_window=8, fit_hedge=False)
    kw.update(over)
    return AutopilotConfig(**kw)


@dataclasses.dataclass
class HeadlineResult:
    """The drill's verdict surface."""

    trace: Trace
    static: Dict[str, SimReport]
    auto: SimReport

    def attainment(self, report: SimReport) -> float:
        return report.slo_attainment("guaranteed", SLO_LATENCY_S)

    @property
    def static_attainments(self) -> Dict[str, float]:
        return {name: self.attainment(r)
                for name, r in self.static.items()}

    @property
    def auto_attainment(self) -> float:
        return self.attainment(self.auto)

    def verdict(self) -> dict:
        return {
            "slo": {"latency_s": SLO_LATENCY_S,
                    "attainment": SLO_ATTAINMENT,
                    "class": "guaranteed"},
            "static": {n: round(a, 4)
                       for n, a in self.static_attainments.items()},
            "autopilot": round(self.auto_attainment, 4),
            "every_static_misses": all(
                a < SLO_ATTAINMENT
                for a in self.static_attainments.values()),
            "autopilot_holds": self.auto_attainment >= SLO_ATTAINMENT,
            "n_actions": len(self.auto.actions),
            "auto_fingerprint": self.auto.fingerprint(),
        }


def run_headline(seed: int = 20260804, *, scale: float = 1.0,
                 sim: Optional[FleetSimConfig] = None
                 ) -> HeadlineResult:
    """Replay the overload trace through the whole static sweep and
    the autopilot arm."""
    trace = overload_trace(seed, scale=scale)
    simcfg = sim or sim_config()
    static = {name: run_fleet(trace, cfg, sim=simcfg)
              for name, cfg in static_sweep()}
    auto = run_fleet(trace, frontend_config(),
                     sim=simcfg, autopilot=autopilot_config())
    return HeadlineResult(trace=trace, static=static, auto=auto)
