"""Flash attention — Pallas TPU kernels.

Reference capability: ``apex/contrib/fmha/fmha.py :: FMHAFun`` (+
``apex/contrib/csrc/fmha/``, seqlen ≤ 512, head-dim 64, varlen via
cu_seqlens) and ``apex/contrib/multihead_attn`` (fused full-MHA blocks).
The reference kernels materialize (or tile) the full score matrix per CTA;
the TPU-native design is a flash/online-softmax kernel with NO seqlen cap:

- **forward**: grid ``(B, H, num_q_blocks, num_k_blocks)`` with the key axis
  innermost; VMEM scratch carries the running ``(max, sum, acc)`` across key
  blocks (TPU grid iteration is sequential, so scratch persists); saves only
  ``(out, logsumexp)`` — activation memory O(S·D), not O(S²).
- **backward**: recomputes probabilities from ``q·kᵀ`` and the saved
  logsumexp (the same recompute-instead-of-save trade the reference's
  xentropy kernel makes); two kernels — dq (key-innermost) and dk/dv
  (query-innermost accumulation).
- **varlen**: ``segment_ids`` — positions in different segments never
  attend (≙ the reference fmha's cu_seqlens packed batches).
- **GQA/MQA**: ``k``/``v`` may have fewer heads than ``q`` (grouped by
  index-map arithmetic, no materialized repeat).
- **ring/context parallel**: traced ``q_offset``/``k_offset`` scalars (SMEM)
  shift the global positions used by the causal mask, and the op can return
  the per-shard ``lse`` so `apex1_tpu.parallel.ring_attention` can merge
  partial results around an ICI ring — differentiably (the custom VJP
  handles the lse cotangent: ∂lse/∂s = softmax(s) ⇒ ds += p·dlse).

Shapes: ``q`` (B, Hq, Sq, D); ``k``/``v`` (B, Hkv, Sk, D), Hq % Hkv == 0.
Accumulation is fp32 regardless of input dtype (bf16 inputs feed the MXU
directly; only the running statistics are fp32) — matching the reference's
fp16-in/fp32-accumulate kernels.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (NEG_INF, interpret_mode,
                                    out_struct, pad_to, to_mosaic,
                                    use_pallas)
from apex1_tpu.ops.stochastic import (attn_keep_mask, threshold_u32,
                                      tile_keep_mask)

_LANES = 128


def _keep_tile(sd_ref, qo_ref, ko_ref, qi, ki, bq, bk, b, h, *,
               dropout_p, n_h, interp):
    """Attention-probability keep mask for the (qi, ki) score tile —
    counter-based on (seed, batch·n_h+head, GLOBAL q start, GLOBAL k
    start), so the mask is independent of grid iteration order and of
    ring-shard visiting order, and context-parallel shards (whose
    ``k_off`` differs) draw disjoint, shift-invariant streams. Forward
    and both backward kernels call this with identical arguments per
    tile — the recompute identity the custom VJPs rely on."""
    return tile_keep_mask(
        (bq, bk), threshold_u32(dropout_p), sd_ref[0, 0], b * n_h + h,
        qi * bq + qo_ref[0, 0], ki * bk + ko_ref[0, 0], interp=interp)


def _block(size: int, requested: int) -> int:
    """Block size: the requested tile, shrunk for tiny inputs (≥16-aligned
    so bf16 (16, 128) sublane tiling stays legal)."""
    return min(requested, max(16, ((size + 15) // 16) * 16))


def _env_block(name):
    """Documented MANUAL override (``APEX1_ATTN_BLOCK_Q/K``) — for pinning
    a block size on hardware without code edits. Read at TRACE time, so
    the jit cache does NOT key on it: changing the env mid-process serves
    stale executables. For sweeps, pass explicit ``block_q/block_k``
    instead (static args — N candidates compile N executables in one
    process; ``tools/tune_kernels.py`` drives this)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val <= 0 or val % 16:
        raise ValueError(f"{name} must be a positive multiple of 16 "
                         f"(TPU sublane tiling), got {val}")
    return val


def _auto_blocks(D, block_q, block_k, dtype=jnp.bfloat16, seq=128):
    """Resolve block sizes with the documented precedence (docs/ops.md):

        explicit argument > APEX1_ATTN_BLOCK_Q/K env override
        > tuning-table winner (`apex1_tpu.tuning`, keyed on generation
          x dtype x padded head dim x the power-of-two bucket of the
          key sequence length — block preference shifts with grid size,
          so a 1k-seq winner never governs a 16k program)
        > analytic heuristic.

    The heuristic: small tiles (128×128) make the grid huge and the
    per-step MXU work tiny — grid/DMA overheads then dominate (round-1
    v5e profile attributed ~5× to the 128×128 grid on GPT-2 shapes,
    BASELINE.md "Round 1 measurements"). Defaults target a ≤1 MiB fp32
    score tile (512×512) and shrink with the padded head dim so q/k/v
    blocks + accumulators + double-buffered operands stay inside the
    generation's VMEM budget (`core.capability.vmem_budget` — the
    runtime analog of the reference's per-sm kernel specialization in
    csrc/fmha). 512 block_k keeps the fp32 score tile at 1 MiB (bq=512);
    the step from 1024 halves peak usage for one extra grid level."""
    from apex1_tpu.core.capability import vmem_budget

    Dp = max(_LANES, ((D + _LANES - 1) // _LANES) * _LANES)
    # env consulted ONLY for unresolved blocks: explicit arguments stay
    # immune to a stale/malformed pin in the environment (the sweep
    # driver passes explicit candidates and must not die on one)
    env_q = _env_block("APEX1_ATTN_BLOCK_Q") if block_q is None else None
    env_k = _env_block("APEX1_ATTN_BLOCK_K") if block_k is None else None
    tuned = {}
    if (block_q is None and env_q is None) or \
            (block_k is None and env_k is None):
        from apex1_tpu import tuning
        tuned = tuning.lookup(
            "flash_attention",
            {"Dp": Dp, "Sb": tuning.seq_bucket(seq)}, dtype) or {}
    small_vmem = vmem_budget() < 12 * 2**20
    default = 256 if (Dp > 512 or small_vmem) else 512
    if block_q is None:
        block_q = env_q or tuned.get("block_q") or default
    if block_k is None:
        block_k = env_k or tuned.get("block_k") or default
    return block_q, block_k


def _mask_for(qi, ki, bq, bk, *, causal, true_sq, true_sk, q_off, k_off,
              qseg, kseg):
    """(bq, bk) validity mask for one score block. Padded rows/cols are
    invalid; causal compares GLOBAL positions (local + traced offset)."""
    row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + qi * bq
    col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ki * bk
    mask = (col < true_sk) & (row < true_sq)
    if causal:
        mask &= (col + k_off) <= (row + q_off)
    if qseg is not None:
        mask &= qseg == kseg  # (bq,1) == (1,bk) broadcast
    return mask


def _fwd_kernel(q_ref, k_ref, v_ref, qo_ref, ko_ref, *seg_and_out,
                scale, causal, true_sq, true_sk, has_segs, has_bias, n_k,
                dropout_p=0.0, n_h=0, interp=False):
    rest = list(seg_and_out)
    sd_ref = rest.pop(0) if dropout_p > 0.0 else None
    if has_segs:
        qseg_ref, kseg_ref = rest[0], rest[1]
        rest = rest[2:]
        qseg, kseg = qseg_ref[0], kseg_ref[0]  # (bq,1), (1,bk)
    else:
        qseg = kseg = None
    bias_ref = rest.pop(0) if has_bias else None
    o_ref, lse_ref, acc, m_scr, l_scr = rest
    qi, ki = pl.program_id(2), pl.program_id(3)
    if dropout_p > 0.0:
        # program ids hoisted OUT of the pl.when-guarded compute: inside
        # the cond body the primitive has no interpret-mode lowering;
        # guarded so the p=0 kernel jaxpr stays identical to pre-dropout
        b, h = pl.program_id(0), pl.program_id(1)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(ki == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        # native-dtype operands: bf16 inputs ride the MXU's bf16 path with
        # fp32 accumulation (an fp32 upcast before the dot would run the MXU
        # ~8x slower); running statistics stay fp32
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            # additive logit bias (T5 rel-pos / arbitrary masks):
            # s = qk·scale + bias, matching scaled_masked_softmax
            s = s + bias_ref[0, 0].astype(jnp.float32)
        mask = _mask_for(qi, ki, bq, bk, causal=causal, true_sq=true_sq,
                         true_sk=true_sk, q_off=qo_ref[0, 0],
                         k_off=ko_ref[0, 0], qseg=qseg, kseg=kseg)
        s = jnp.where(mask, s, NEG_INF)
        m_prev, l_prev = m_scr[:, :1], l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        e = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_new = l_prev * corr + jnp.sum(e, axis=1, keepdims=True)
        v = v_ref[0, 0]
        if dropout_p > 0.0:
            # dropout BETWEEN softmax and AV (the reference fmha fusion
            # point): the softmax denominator l accumulates the
            # UNdropped e, only the AV contribution is masked+rescaled,
            # so (out, lse) merge exactly across ring shards
            keep = _keep_tile(sd_ref, qo_ref, ko_ref, qi, ki, bq, bk,
                              b, h, dropout_p=dropout_p, n_h=n_h,
                              interp=interp)
            e_av = jnp.where(keep, e * (1.0 / (1.0 - dropout_p)), 0.0)
        else:
            e_av = e
        acc[...] = acc[...] * corr + jax.lax.dot_general(
            e_av.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip blocks entirely above the diagonal (no valid positions):
        # saves the strictly-upper-triangular ~half of the MXU work
        pl.when((ki * bk + ko_ref[0, 0])
                <= (qi * bq + bq - 1 + qo_ref[0, 0]))(compute)
    else:
        compute()

    @pl.when(ki == n_k - 1)
    def _():
        l = l_scr[:, :1]
        safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0, 0] = (acc[...] / safe).astype(o_ref.dtype)
        # finite NEG_INF sentinel for empty rows keeps ring merges exact
        lse_ref[0, 0] = jnp.where(l > 0.0, m_scr[:, :1] + jnp.log(safe),
                                  NEG_INF)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dlse_ref,
                   qo_ref, ko_ref, *seg_and_out,
                   scale, causal, true_sq, true_sk, has_segs, has_bias,
                   n_k, dropout_p=0.0, n_h=0, interp=False):
    rest = list(seg_and_out)
    sd_ref = rest.pop(0) if dropout_p > 0.0 else None
    if has_segs:
        qseg_ref, kseg_ref = rest[0], rest[1]
        rest = rest[2:]
        qseg, kseg = qseg_ref[0], kseg_ref[0]
    else:
        qseg = kseg = None
    bias_ref = rest.pop(0) if has_bias else None
    dq_ref, dq_acc = rest
    qi, ki = pl.program_id(2), pl.program_id(3)
    if dropout_p > 0.0:
        b, h = pl.program_id(0), pl.program_id(1)  # hoisted, see _fwd
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(ki == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        mask = _mask_for(qi, ki, bq, bk, causal=causal, true_sq=true_sq,
                         true_sk=true_sk, q_off=qo_ref[0, 0],
                         k_off=ko_ref[0, 0], qseg=qseg, kseg=kseg)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0, 0]), 0.0)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            # out = Σ drop∘softmax(s)·v with drop a CONSTANT mask ⇒
            # ds = p·(drop·dp − δ + dlse): the recomputed mask scales
            # only the dp term (δ already carries the dropped weights
            # through do·out)
            keep = _keep_tile(sd_ref, qo_ref, ko_ref, qi, ki, bq, bk,
                              b, h, dropout_p=dropout_p, n_h=n_h,
                              interp=interp)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        ds = p * (dp - dlt_ref[0, 0] + dlse_ref[0, 0]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when((ki * bk + ko_ref[0, 0])
                <= (qi * bq + bq - 1 + qo_ref[0, 0]))(compute)
    else:
        compute()

    @pl.when(ki == n_k - 1)
    def _():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dlse_ref,
                    qo_ref, ko_ref, *seg_and_out,
                    scale, causal, true_sq, true_sk, has_segs, has_bias,
                    n_q, group, dropout_p=0.0, n_h=0, interp=False):
    # Grid (b, hkv, ki, gi, qi): the GQA group axis sits between the key
    # block and the (innermost) query block, so dk/dv for one kv head
    # accumulate across the whole group in VMEM scratch and are written
    # ONCE at Hkv granularity — no (B, Hq, Sk, D) fp32 partials in HBM
    # (VERDICT r1 weak#4), and each k/v block is fetched once per group
    # sweep instead of once per q head.
    rest = list(seg_and_out)
    sd_ref = rest.pop(0) if dropout_p > 0.0 else None
    if has_segs:
        qseg_ref, kseg_ref = rest[0], rest[1]
        rest = rest[2:]
        qseg, kseg = qseg_ref[0], kseg_ref[0]
    else:
        qseg = kseg = None
    bias_ref = rest.pop(0) if has_bias else None
    dk_ref, dv_ref, dk_acc, dv_acc = rest
    ki, gi, qi = pl.program_id(2), pl.program_id(3), pl.program_id(4)
    if dropout_p > 0.0:
        # hoisted (see _fwd_kernel); q head on this grid is hkv·group+gi
        b, hq = pl.program_id(0), pl.program_id(1) * group + gi
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when((gi == 0) & (qi == 0))
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        mask = _mask_for(qi, ki, bq, bk, causal=causal, true_sq=true_sq,
                         true_sk=true_sk, q_off=qo_ref[0, 0],
                         k_off=ko_ref[0, 0], qseg=qseg, kseg=kseg)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0, 0]), 0.0)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        if dropout_p > 0.0:
            # hq = hkv·group + gi — the SAME salt the forward used for
            # this (b, h, qi, ki) tile
            keep = _keep_tile(
                sd_ref, qo_ref, ko_ref, qi, ki, bq, bk, b, hq,
                dropout_p=dropout_p, n_h=n_h, interp=interp)
            inv = 1.0 / (1.0 - dropout_p)
            p_av = jnp.where(keep, p * inv, 0.0)  # dv sees DROPPED probs
        else:
            keep = None
            p_av = p
        dv_acc[...] += jax.lax.dot_general(                  # p_avᵀ · do
            p_av.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - dlt_ref[0, 0] + dlse_ref[0, 0]) * scale
        dk_acc[...] += jax.lax.dot_general(                  # dsᵀ · q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when((qi * bq + bq - 1 + qo_ref[0, 0])
                >= (ki * bk + ko_ref[0, 0]))(compute)
    else:
        compute()

    @pl.when((gi == group - 1) & (qi == n_q - 1))
    def _():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _dbias_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dlse_ref,
                  qo_ref, ko_ref, *seg_and_out,
                  scale, causal, true_sq, true_sk, has_segs, n_r,
                  rh=1, dropout_p=0.0, n_h=0, interp=False):
    """dbias = Σ_broadcast p·(dp − δ + dlse) — one extra recompute pass.
    Grid (Bb, Hb, qi, ki, r) with the broadcast sweep r INNERMOST: every
    revisit of a dbias output block is consecutive, so accumulation
    lives in VMEM scratch and each block is written once (no O(B·H·S²)
    partials in HBM — the whole point of biasing the flash kernel).
    ``rh`` is the head broadcast factor Hq//Hb — with the grid sizes it
    reconstructs the TRUE (b, h) this sweep step visits, so the dropout
    mask salt matches the forward's."""
    rest = list(seg_and_out)
    sd_ref = rest.pop(0) if dropout_p > 0.0 else None
    if has_segs:
        qseg_ref, kseg_ref = rest[0], rest[1]
        rest = rest[2:]
        qseg, kseg = qseg_ref[0], kseg_ref[0]
    else:
        qseg = kseg = None
    bias_ref, dbias_ref, db_acc = rest
    qi, ki, r = pl.program_id(2), pl.program_id(3), pl.program_id(4)
    if dropout_p > 0.0:
        # true (b, h) of this sweep step (bidx/hidx inverted from the
        # index maps) — hoisted out of the pl.when-guarded compute
        b = pl.program_id(0) + (r // rh) * pl.num_programs(0)
        h = pl.program_id(1) + (r % rh) * pl.num_programs(1)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(r == 0)
    def _():
        db_acc[...] = jnp.zeros_like(db_acc)

    def compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        # p must come from the FULL logits (qk·scale + bias) minus the
        # saved lse, which was computed over the biased scores
        s = s + bias_ref[0, 0].astype(jnp.float32)
        mask = _mask_for(qi, ki, bq, bk, causal=causal, true_sq=true_sq,
                         true_sk=true_sk, q_off=qo_ref[0, 0],
                         k_off=ko_ref[0, 0], qseg=qseg, kseg=kseg)
        p = jnp.where(mask, jnp.exp(s - lse_ref[0, 0]), 0.0)
        do = do_ref[0, 0]
        v = v_ref[0, 0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_tile(sd_ref, qo_ref, ko_ref, qi, ki, bq, bk,
                              b, h, dropout_p=dropout_p, n_h=n_h,
                              interp=interp)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        # dS w.r.t. the PRE-scale logits s_full — no trailing ·scale
        # (that factor belongs to d(qk), not d(bias))
        db_acc[...] += p * (dp - dlt_ref[0, 0] + dlse_ref[0, 0])

    if causal:
        pl.when((ki * bk + ko_ref[0, 0])
                <= (qi * bq + bq - 1 + qo_ref[0, 0]))(compute)
    else:
        compute()

    @pl.when(r == n_r - 1)
    def _():
        dbias_ref[0, 0] = db_acc[...].astype(dbias_ref.dtype)


def _prep(q, k, v, qseg, kseg, has_segs, block_q, block_k):
    """Pad operands to block multiples; returns padded arrays + geometry."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    bq, bk = _block(Sq, block_q), _block(Sk, block_k)
    qp, _ = pad_to(q, 2, bq)
    qp, _ = pad_to(qp, 3, _LANES)
    kp, _ = pad_to(k, 2, bk)
    kp, _ = pad_to(kp, 3, _LANES)
    vp, _ = pad_to(v, 2, bk)
    vp, _ = pad_to(vp, 3, _LANES)
    if has_segs:
        # qseg → (B, Sq, 1) / kseg → (B, 1, Sk): 2-D refs, no in-kernel
        # transpose; pad value -1 ≠ -2 so padded q never matches padded k
        qs, _ = pad_to(qseg.astype(jnp.int32)[:, :, None], 1, bq, value=-1)
        ks, _ = pad_to(kseg.astype(jnp.int32)[:, None, :], 2, bk, value=-2)
    else:
        qs = ks = None
    geom = dict(B=B, Hq=Hq, Hkv=Hkv, group=Hq // Hkv, Sq=Sq, Sk=Sk, D=D,
                bq=bq, bk=bk, n_q=qp.shape[2] // bq, n_k=kp.shape[2] // bk,
                Dp=qp.shape[3])
    return qp, kp, vp, qs, ks, geom


def _common_specs(g):
    """Block specs shared by the fwd and dq kernels — grid (b, h, qi, ki)."""
    group = g["group"]
    q_spec = pl.BlockSpec((1, 1, g["bq"], g["Dp"]),
                          lambda b, h, qi, ki: (b, h, qi, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, g["bk"], g["Dp"]),
                           lambda b, h, qi, ki: (b, h // group, ki, 0),
                           memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((1, 1, g["bq"], 1),
                             lambda b, h, qi, ki: (b, h, qi, 0),
                             memory_space=pltpu.VMEM)
    off_spec = pl.BlockSpec((1, 1), lambda *_: (0, 0),
                            memory_space=pltpu.SMEM)
    qseg_spec = pl.BlockSpec((1, g["bq"], 1),
                             lambda b, h, qi, ki: (b, qi, 0),
                             memory_space=pltpu.VMEM)
    kseg_spec = pl.BlockSpec((1, 1, g["bk"]),
                             lambda b, h, qi, ki: (b, 0, ki),
                             memory_space=pltpu.VMEM)
    return q_spec, kv_spec, stat_spec, off_spec, qseg_spec, kseg_spec


def _dkv_specs(g):
    """Block specs for the dk/dv kernel — grid (b, hkv, ki, gi, qi): the
    q head is ``hkv * group + gi``; dk/dv blocks index (b, hkv, ki)."""
    group = g["group"]
    q_spec = pl.BlockSpec(
        (1, 1, g["bq"], g["Dp"]),
        lambda b, hkv, ki, gi, qi: (b, hkv * group + gi, qi, 0),
        memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, g["bk"], g["Dp"]),
                           lambda b, hkv, ki, gi, qi: (b, hkv, ki, 0),
                           memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec(
        (1, 1, g["bq"], 1),
        lambda b, hkv, ki, gi, qi: (b, hkv * group + gi, qi, 0),
        memory_space=pltpu.VMEM)
    off_spec = pl.BlockSpec((1, 1), lambda *_: (0, 0),
                            memory_space=pltpu.SMEM)
    qseg_spec = pl.BlockSpec((1, g["bq"], 1),
                             lambda b, hkv, ki, gi, qi: (b, qi, 0),
                             memory_space=pltpu.VMEM)
    kseg_spec = pl.BlockSpec((1, 1, g["bk"]),
                             lambda b, hkv, ki, gi, qi: (b, 0, ki),
                             memory_space=pltpu.VMEM)
    dkv_spec = pl.BlockSpec((1, 1, g["bk"], g["Dp"]),
                            lambda b, hkv, ki, gi, qi: (b, hkv, ki, 0),
                            memory_space=pltpu.VMEM)
    return q_spec, kv_spec, stat_spec, off_spec, qseg_spec, kseg_spec, \
        dkv_spec


def _off_arrays(q_off, k_off):
    return (jnp.asarray(q_off, jnp.int32).reshape(1, 1),
            jnp.asarray(k_off, jnp.int32).reshape(1, 1))


def _prep_bias(bias, g):
    """Pad the additive-bias operand to block multiples. Accepts
    (1|B, 1|Hq, Sq, Sk); broadcast dims stay size-1 all the way into the
    kernels via their index maps."""
    B, Hq = g["B"], g["Hq"]
    if bias.ndim != 4:
        raise ValueError(f"bias must be (1|B, 1|H, Sq, Sk), got rank "
                         f"{bias.ndim}")
    Bb, Hb, sq, sk = bias.shape
    if Bb not in (1, B) or Hb not in (1, Hq):
        raise ValueError(f"bias batch/head dims {Bb, Hb} must be 1 or "
                         f"match (B={B}, H={Hq})")
    if (sq, sk) != (g["Sq"], g["Sk"]):
        raise ValueError(f"bias trailing dims {sq, sk} must equal "
                         f"(Sq={g['Sq']}, Sk={g['Sk']})")
    bp, _ = pad_to(bias, 2, g["bq"])
    bp, _ = pad_to(bp, 3, g["bk"])
    return bp, Bb, Hb


def _bias_spec(g, Bb, Hb, *, dkv=False):
    """Bias block spec for the fwd/dq grid (b, h, qi, ki) or — with
    ``dkv`` — the dk/dv grid (b, hkv, ki, gi, qi)."""
    group = g["group"]
    if dkv:
        return pl.BlockSpec(
            (1, 1, g["bq"], g["bk"]),
            lambda b, hkv, ki, gi, qi: (
                b if Bb > 1 else 0,
                (hkv * group + gi) if Hb > 1 else 0, qi, ki),
            memory_space=pltpu.VMEM)
    return pl.BlockSpec(
        (1, 1, g["bq"], g["bk"]),
        lambda b, h, qi, ki: (b if Bb > 1 else 0, h if Hb > 1 else 0,
                              qi, ki),
        memory_space=pltpu.VMEM)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11, 12, 13))
def _flash(q, k, v, qseg, kseg, q_off, k_off, seed,
           scale, causal, has_segs, block_q, block_k, dropout_p):
    out, lse, _ = _flash_fwd_impl(q, k, v, qseg, kseg, q_off, k_off,
                                  scale, causal, has_segs, block_q,
                                  block_k, dropout_p=dropout_p, seed=seed)
    return out, lse


def _drop_kw(dropout_p, g):
    """Kernel kwargs for the dropout path. EMPTY at p == 0 so the
    pallas_call partials (and the lowered kernels) stay byte-identical
    to the pre-dropout programs — the pinned bit-for-bit contract."""
    if dropout_p <= 0.0:
        return {}
    return dict(dropout_p=dropout_p, n_h=g["Hq"], interp=interpret_mode())


def _flash_fwd_impl(q, k, v, qseg, kseg, q_off, k_off,
                    scale, causal, has_segs, block_q, block_k,
                    bias=None, dropout_p=0.0, seed=None):
    qp, kp, vp, qs, ks, g = _prep(q, k, v, qseg, kseg, has_segs,
                                  block_q, block_k)
    q_spec, kv_spec, stat_spec, off_spec, qseg_spec, kseg_spec = \
        _common_specs(g)
    in_specs = [q_spec, kv_spec, kv_spec, off_spec, off_spec]
    args = [qp, kp, vp, *_off_arrays(q_off, k_off)]
    if dropout_p > 0.0:
        in_specs += [off_spec]
        args += [jnp.asarray(seed, jnp.int32).reshape(1, 1)]
    if has_segs:
        in_specs += [qseg_spec, kseg_spec]
        args += [qs, ks]
    has_bias = bias is not None
    if has_bias:
        bp, Bb, Hb = _prep_bias(bias, g)
        in_specs += [_bias_spec(g, Bb, Hb)]
        args += [bp]
    Sqp = g["n_q"] * g["bq"]
    out_p, lse_p = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          true_sq=g["Sq"], true_sk=g["Sk"],
                          has_segs=has_segs, has_bias=has_bias,
                          n_k=g["n_k"], **_drop_kw(dropout_p, g)),
        grid=(g["B"], g["Hq"], g["n_q"], g["n_k"]),
        in_specs=in_specs,
        out_specs=(q_spec, stat_spec),
        out_shape=(
            out_struct((g["B"], g["Hq"], Sqp, g["Dp"]), q.dtype,
                       qp, kp, vp),
            out_struct((g["B"], g["Hq"], Sqp, 1), jnp.float32,
                       qp, kp, vp)),
        scratch_shapes=[
            pltpu.VMEM((g["bq"], g["Dp"]), jnp.float32),
            pltpu.VMEM((g["bq"], _LANES), jnp.float32),
            pltpu.VMEM((g["bq"], _LANES), jnp.float32)],
        interpret=interpret_mode(),
    )(*args)
    out = out_p[:, :, :g["Sq"], :g["D"]]
    lse = lse_p[:, :, :g["Sq"], 0]
    return out, lse, lse_p


def _flash_fwd(q, k, v, qseg, kseg, q_off, k_off, seed,
               scale, causal, has_segs, block_q, block_k, dropout_p):
    out, lse, lse_p = _flash_fwd_impl(q, k, v, qseg, kseg, q_off, k_off,
                                      scale, causal, has_segs,
                                      block_q, block_k,
                                      dropout_p=dropout_p, seed=seed)
    return (out, lse), (q, k, v, qseg, kseg, q_off, k_off, seed, out,
                        lse_p)


def _flash_bwd_impl(scale, causal, has_segs, block_q, block_k, res, cts,
                    bias=None, cast=True, dropout_p=0.0):
    """``cast=False`` returns dk/dv in their native fp32 kernel output
    dtype (dq is q.dtype either way — the dq kernel's out_shape): the
    ring backward accumulates per-shard dk/dv across the ring and a
    round-trip through k.dtype before that fp32 sum would discard the
    very precision the kernels paid for.

    With ``dropout_p > 0`` every backward kernel recomputes the
    forward's keep mask from the seed residual — the same
    recompute-instead-of-save trade the kernels already make for the
    probabilities."""
    q, k, v, qseg, kseg, q_off, k_off, seed, out, lse_p = res
    dout, dlse = cts
    qp, kp, vp, qs, ks, g = _prep(q, k, v, qseg, kseg, has_segs,
                                  block_q, block_k)
    Sqp = g["n_q"] * g["bq"]
    dop, _ = pad_to(dout.astype(q.dtype), 2, g["bq"])
    dop, _ = pad_to(dop, 3, _LANES)
    # δ_i = Σ_d dout·out — padded regions are zero so no masking needed
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    dlt_p, _ = pad_to(delta[..., None], 2, g["bq"])
    dlse_p, _ = pad_to(dlse.astype(jnp.float32)[..., None], 2, g["bq"])

    stat_args = [lse_p, dlt_p, dlse_p, *_off_arrays(q_off, k_off)]
    n_seed = 0
    if dropout_p > 0.0:
        stat_args += [jnp.asarray(seed, jnp.int32).reshape(1, 1)]
        n_seed = 1  # one extra SMEM scalar operand per launch
    has_bias = bias is not None
    if has_bias:
        bp, Bb, Hb = _prep_bias(bias, g)
    kern = dict(scale=scale, causal=causal, true_sq=g["Sq"],
                true_sk=g["Sk"], has_segs=has_segs,
                **_drop_kw(dropout_p, g))

    # dq: grid (b, h, qi, ki), key axis innermost
    q_spec, kv_spec, stat_spec, off_spec, qseg_spec, kseg_spec = \
        _common_specs(g)
    in_specs = [q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec,
                stat_spec, off_spec, off_spec]
    in_specs += [off_spec] * n_seed
    args = [qp, kp, vp, dop] + stat_args
    if has_segs:
        in_specs += [qseg_spec, kseg_spec]
        args += [qs, ks]
    if has_bias:
        in_specs += [_bias_spec(g, Bb, Hb)]
        args += [bp]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, n_k=g["n_k"],
                          has_bias=has_bias, **kern),
        grid=(g["B"], g["Hq"], g["n_q"], g["n_k"]),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=out_struct((g["B"], g["Hq"], Sqp, g["Dp"]), q.dtype,
                             qp, kp, vp, dop),
        scratch_shapes=[pltpu.VMEM((g["bq"], g["Dp"]), jnp.float32)],
        interpret=interpret_mode(),
    )(*args)[:, :, :g["Sq"], :g["D"]]

    # dk/dv: grid (b, hkv, ki, gi, qi) — query axis innermost, GQA group
    # axis above it, so group accumulation happens in VMEM scratch and the
    # outputs are written at Hkv granularity (no Hq-sized fp32 partials)
    q_spec, kv_spec, stat_spec, off_spec, qseg_spec, kseg_spec, dkv_spec = \
        _dkv_specs(g)
    in_specs = [q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec,
                stat_spec, off_spec, off_spec]
    in_specs += [off_spec] * n_seed
    args = [qp, kp, vp, dop] + stat_args
    if has_segs:
        in_specs += [qseg_spec, kseg_spec]
        args += [qs, ks]
    if has_bias:
        in_specs += [_bias_spec(g, Bb, Hb, dkv=True)]
        args += [bp]
    Skp = g["n_k"] * g["bk"]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, n_q=g["n_q"], group=g["group"],
                          has_bias=has_bias, **kern),
        grid=(g["B"], g["Hkv"], g["n_k"], g["group"], g["n_q"]),
        in_specs=in_specs,
        out_specs=(dkv_spec, dkv_spec),
        out_shape=(
            out_struct((g["B"], g["Hkv"], Skp, g["Dp"]), jnp.float32,
                       qp, kp, vp, dop),
            out_struct((g["B"], g["Hkv"], Skp, g["Dp"]), jnp.float32,
                       qp, kp, vp, dop)),
        scratch_shapes=[pltpu.VMEM((g["bk"], g["Dp"]), jnp.float32),
                        pltpu.VMEM((g["bk"], g["Dp"]), jnp.float32)],
        interpret=interpret_mode(),
    )(*args)
    dk = dk[:, :, :g["Sk"], :g["D"]]
    dv = dv[:, :, :g["Sk"], :g["D"]]

    dbias = None
    if has_bias:
        # dbias pass: grid (Bb, Hb, qi, ki, r) — the broadcast sweep r
        # is innermost so the (bb, hb, qi, ki) output block's revisits
        # are consecutive and accumulate in scratch
        RB, RH = g["B"] // Bb, g["Hq"] // Hb
        n_r = RB * RH

        def bidx(bb, r):
            return bb + (r // RH) * Bb

        def hidx(hb, r):
            return hb + (r % RH) * Hb

        def spec4(blk, imap):
            return pl.BlockSpec(blk, imap, memory_space=pltpu.VMEM)

        q_spec_b = spec4((1, 1, g["bq"], g["Dp"]),
                         lambda bb, hb, qi, ki, r:
                         (bidx(bb, r), hidx(hb, r), qi, 0))
        kv_spec_b = spec4((1, 1, g["bk"], g["Dp"]),
                          lambda bb, hb, qi, ki, r:
                          (bidx(bb, r), hidx(hb, r) // g["group"], ki, 0))
        stat_spec_b = spec4((1, 1, g["bq"], 1),
                            lambda bb, hb, qi, ki, r:
                            (bidx(bb, r), hidx(hb, r), qi, 0))
        off_spec_b = pl.BlockSpec((1, 1), lambda *_: (0, 0),
                                  memory_space=pltpu.SMEM)
        qseg_spec_b = spec4((1, g["bq"], 1),
                            lambda bb, hb, qi, ki, r: (bidx(bb, r), qi, 0))
        kseg_spec_b = spec4((1, 1, g["bk"]),
                            lambda bb, hb, qi, ki, r: (bidx(bb, r), 0, ki))
        bias_spec_b = spec4((1, 1, g["bq"], g["bk"]),
                            lambda bb, hb, qi, ki, r: (bb, hb, qi, ki))
        db_spec = spec4((1, 1, g["bq"], g["bk"]),
                        lambda bb, hb, qi, ki, r: (bb, hb, qi, ki))
        in_specs = [q_spec_b, kv_spec_b, kv_spec_b, q_spec_b, stat_spec_b,
                    stat_spec_b, stat_spec_b, off_spec_b, off_spec_b]
        in_specs += [off_spec_b] * n_seed
        args = [qp, kp, vp, dop] + stat_args
        if has_segs:
            in_specs += [qseg_spec_b, kseg_spec_b]
            args += [qs, ks]
        in_specs += [bias_spec_b]
        args += [bp]
        dbias_p = pl.pallas_call(
            functools.partial(_dbias_kernel, n_r=n_r, **kern,
                              **({"rh": RH} if dropout_p > 0.0 else {})),
            grid=(Bb, Hb, g["n_q"], g["n_k"], n_r),
            in_specs=in_specs,
            out_specs=db_spec,
            out_shape=out_struct(
                (Bb, Hb, Sqp, g["n_k"] * g["bk"]), jnp.float32,
                qp, kp, vp, dop, bp),
            scratch_shapes=[pltpu.VMEM((g["bq"], g["bk"]), jnp.float32)],
            interpret=interpret_mode(),
        )(*args)
        dbias = dbias_p[:, :, :g["Sq"], :g["Sk"]]

    f0 = lambda x: np.zeros(jnp.shape(x), dtype=jax.dtypes.float0)
    if cast:
        dk, dv = dk.astype(k.dtype), dv.astype(v.dtype)
    grads = (dq.astype(q.dtype), dk, dv,
             f0(qseg), f0(kseg), f0(q_off), f0(k_off), f0(seed))
    return grads, dbias


def _flash_bwd(scale, causal, has_segs, block_q, block_k, dropout_p,
               res, cts):
    grads, _ = _flash_bwd_impl(scale, causal, has_segs, block_q, block_k,
                               res, cts, dropout_p=dropout_p)
    return grads


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13, 14))
def _flash_with_bias(q, k, v, bias, qseg, kseg, q_off, k_off, seed,
                     scale, causal, has_segs, block_q, block_k, dropout_p):
    out, lse, _ = _flash_fwd_impl(q, k, v, qseg, kseg, q_off, k_off,
                                  scale, causal, has_segs, block_q,
                                  block_k, bias=bias, dropout_p=dropout_p,
                                  seed=seed)
    return out, lse


def _flash_with_bias_fwd(q, k, v, bias, qseg, kseg, q_off, k_off, seed,
                         scale, causal, has_segs, block_q, block_k,
                         dropout_p):
    out, lse, lse_p = _flash_fwd_impl(q, k, v, qseg, kseg, q_off, k_off,
                                      scale, causal, has_segs,
                                      block_q, block_k, bias=bias,
                                      dropout_p=dropout_p, seed=seed)
    return (out, lse), (q, k, v, bias, qseg, kseg, q_off, k_off, seed,
                        out, lse_p)


def _flash_with_bias_bwd(scale, causal, has_segs, block_q, block_k,
                         dropout_p, res, cts):
    q, k, v, bias, qseg, kseg, q_off, k_off, seed, out, lse_p = res
    grads, dbias = _flash_bwd_impl(
        scale, causal, has_segs, block_q, block_k,
        (q, k, v, qseg, kseg, q_off, k_off, seed, out, lse_p), cts,
        bias=bias, dropout_p=dropout_p)
    dq, dk, dv, fqs, fks, fqo, fko, fsd = grads
    return (dq, dk, dv, dbias.astype(bias.dtype), fqs, fks, fqo, fko, fsd)


_flash_with_bias.defvjp(_flash_with_bias_fwd, _flash_with_bias_bwd)


def _xla_attention(q, k, v, qseg, kseg, q_off, k_off, scale, causal,
                   with_lse=False, bias=None, dropout_p=0.0, seed=None):
    """XLA-composite gold: identical semantics incl. empty-row handling.
    Probability dropout uses the SAME counter hash at global positions
    as the interpret-mode kernels — bit-identical masks on CPU."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    if Hq != Hkv:
        k = jnp.repeat(k, Hq // Hkv, axis=1)
        v = jnp.repeat(v, Hq // Hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    row = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
    mask = jnp.ones((B, 1, Sq, Sk), bool)
    if causal:
        mask &= ((col + k_off) <= (row + q_off))[None, None]
    if qseg is not None:
        mask &= (qseg[:, None, :, None] == kseg[:, None, None, :])
    # masked scores (not raw s) inside exp: for rows with NO valid keys
    # m == NEG_INF and exp(s - m) would overflow to inf, poisoning the VJP
    # with inf·0 = NaN; exp(sm - m) is exp(0) = 1 there (then zeroed), and
    # the inner where blocks the masked-branch gradient entirely
    sm = jnp.where(mask, s, NEG_INF)
    m = jnp.max(sm, axis=-1, keepdims=True)
    e = jnp.where(mask, jnp.exp(sm - m), 0.0)
    l = jnp.sum(e, axis=-1, keepdims=True)
    probs = e / jnp.where(l > 0, l, 1.0)
    if dropout_p > 0.0:
        keep = attn_keep_mask(seed, B, Hq, row + q_off, col + k_off,
                              dropout_p)
        # denominator l stays UNdropped (lse is dropout-free); only the
        # AV weights are masked+rescaled — matches the kernels
        probs = jnp.where(keep, probs * (1.0 / (1.0 - dropout_p)), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs,
                     v.astype(jnp.float32)).astype(q.dtype)
    if not with_lse:
        return out
    lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)),
                    NEG_INF)[..., 0]
    return out, lse


def _norm_segments(segment_ids, Sq, Sk):
    if segment_ids is None:
        return False, None, None
    if isinstance(segment_ids, (tuple, list)):
        qseg, kseg = segment_ids
    else:
        if Sq != Sk:
            raise ValueError("pass (q_seg, k_seg) when Sq != Sk")
        qseg = kseg = segment_ids
    return True, qseg, kseg


def flash_attention(q, k, v, *, causal: bool = False, segment_ids=None,
                    sm_scale: float | None = None, q_offset=0, k_offset=0,
                    block_q: int | None = None, block_k: int | None = None,
                    return_lse: bool = False, bias=None,
                    dropout_p: float = 0.0, dropout_seed=None):
    """Flash attention over (B, H, S, D) operands.

    ``segment_ids``: (B, S) int array (self-attention) or a
    ``(q_seg, k_seg)`` pair — tokens attend only within equal ids
    (≙ fmha's cu_seqlens varlen batches).
    ``q_offset``/``k_offset``: traced global-position offsets for the
    causal mask (used by ring/context parallelism; 0 for plain use).
    ``block_q``/``block_k``: static kernel tile sizes. ``None`` (the
    default) resolves via `apex1_tpu.tuning`: env override
    (``APEX1_ATTN_BLOCK_Q/K``) > persisted tuning-table winner for this
    (generation, dtype, padded head dim) > analytic heuristic. Explicit
    values are honored verbatim — they are static arguments, so an
    in-process sweep of N candidates (``tools/tune_kernels.py``)
    compiles exactly N executables with no jit-cache
    cross-contamination.
    ``return_lse``: also return the fp32 logsumexp (B, H, Sq) — needed to
    merge partial-attention results (ring attention).
    ``bias``: additive logit bias (1|B, 1|H, Sq, Sk) — T5-style relative
    position bias or an arbitrary additive mask; differentiable (dbias
    via a dedicated broadcast-accumulating backward pass), so the O(S²)
    composite path is never needed for bias-bearing attention.
    ``dropout_p``/``dropout_seed``: attention-probability dropout FUSED
    between softmax and AV inside the kernels (≙ the reference fmha /
    multihead_attn fusion point) — no mask tensor is ever stored; the
    backward recomputes the mask from the int32 seed. The mask is
    counter-based on (seed, batch·H+head, global q pos, global k pos),
    so it is deterministic per (seed, backend), independent of grid
    order, and ring/context-parallel shards draw disjoint streams via
    their ``k_offset``. Derive seeds per call site with
    `apex1_tpu.ops.stochastic.seed_from_key` / `fold_seed`. ``lse`` (and
    the softmax denominator) stay dropout-free, which is what keeps ring
    merges exact. dropout_p=0 lowers to the exact pre-dropout kernel.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("expected (B, H, S, D) operands")
    if q.shape[1] % k.shape[1] != 0:
        raise ValueError(f"Hq={q.shape[1]} not a multiple of "
                         f"Hkv={k.shape[1]}")
    scale = (1.0 / float(np.sqrt(q.shape[-1]))
             if sm_scale is None else float(sm_scale))
    # fp16 (the O*_fp16 AMP policies) is a storage dtype on TPU: Mosaic
    # has no f16, so compiled kernels run bf16 and the result is cast
    # back — see ops._common.mosaic_dtype. Resolved BEFORE the block
    # lookup so the tuning table keys on the dtype the kernel compiles.
    io_dtype = q.dtype
    if use_pallas():
        # an f16 bias hits the same Mosaic f16 wall as q/k/v
        q, k, v, bias = to_mosaic(q, k, v, bias)
    block_q, block_k = _auto_blocks(q.shape[3], block_q, block_k, q.dtype,
                                    k.shape[2])
    has_segs, qseg, kseg = _norm_segments(segment_ids, q.shape[2],
                                          k.shape[2])
    if bias is not None:
        # validate for BOTH backends: a bias shape the kernel rejects
        # must not silently broadcast on the XLA fallback (code
        # validated on CPU would then crash on TPU)
        B, Hq, Sq = q.shape[0], q.shape[1], q.shape[2]
        Sk = k.shape[2]
        if bias.ndim != 4:
            raise ValueError(f"bias must be (1|B, 1|H, Sq, Sk), got "
                             f"rank {bias.ndim}")
        if (bias.shape[0] not in (1, B) or bias.shape[1] not in (1, Hq)
                or bias.shape[2:] != (Sq, Sk)):
            raise ValueError(f"bias shape {bias.shape} must be "
                             f"(1|{B}, 1|{Hq}, {Sq}, {Sk})")
    dropout_p = float(dropout_p)
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 needs an explicit int32 "
                         "dropout_seed (ops.stochastic.seed_from_key / "
                         "fold_seed at the call site)")
    seed = (jnp.asarray(dropout_seed, jnp.int32) if dropout_p > 0.0
            else jnp.zeros((), jnp.int32))
    if use_pallas():
        dummy = jnp.zeros((1, 1), jnp.int32)
        if bias is not None:
            out, lse = _flash_with_bias(
                q, k, v, bias,
                qseg if has_segs else dummy,
                kseg if has_segs else dummy,
                q_offset, k_offset, seed,
                scale, causal, has_segs, block_q, block_k, dropout_p)
        else:
            out, lse = _flash(q, k, v,
                              qseg if has_segs else dummy,
                              kseg if has_segs else dummy,
                              q_offset, k_offset, seed,
                              scale, causal, has_segs, block_q, block_k,
                              dropout_p)
    else:
        out, lse = _xla_attention(q, k, v, qseg, kseg, q_offset, k_offset,
                                  scale, causal, with_lse=True, bias=bias,
                                  dropout_p=dropout_p, seed=seed)
    if out.dtype != io_dtype:
        out = out.astype(io_dtype)  # fp16 storage dtype restored
    return (out, lse) if return_lse else out


def fmha(qkv, *, segment_ids=None, causal: bool = True,
         sm_scale: float | None = None, dropout_p: float = 0.0,
         dropout_seed=None):
    """``apex.contrib.fmha.FMHAFun`` equivalent: packed (B, S, 3, H, D)
    QKV, varlen via ``segment_ids`` instead of cu_seqlens. No seqlen-512 or
    head-dim-64 cap — the flash kernel serves all sizes. ``dropout_p``
    is the reference's in-kernel probability dropout (seeded, fused)."""
    q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
    out = flash_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                          sm_scale=sm_scale, dropout_p=dropout_p,
                          dropout_seed=dropout_seed)
    return out.transpose(0, 2, 1, 3)
