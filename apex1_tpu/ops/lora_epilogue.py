"""Multi-tenant LoRA decode epilogue — paged adapters in the LM-head matmul.

One deployed base model, many tenants: each tenant's low-rank adapter
(A (H, r), B (r, V), scale pre-folded into B) is stored as ``r`` PAGES in
a pool beside the KV pool (`serving.lora.LoraAdapterStore`, page-granular
alloc reused from `serving.kv_pool`), and each serving slot carries a
rank-length BLOCK-TABLE row of page ids — exactly the `ops.paged_decode`
indirection, scalar-prefetched so Mosaic pipelines the gathers.

The delta this module computes is

    delta[n] = Σ_j (h[n] · A_pages[bt[n, j]]) * B_pages[bt[n, j]]

i.e. ``(h @ A) @ B`` with the rank dimension streamed page-by-page, fused
into the decode step as a logits EPILOGUE (`serving.engine` adds it to the
base head matmul) instead of a separate gather + two-matmul pass per
tenant (arXiv 2502.17728's operation-fusion argument).  Page 0 is the
pool's zero page, so a slot with no adapter (all-zero block-table row)
contributes an exactly-zero delta — LoRA-off slots ride the same
executable with no retrace and the engine keeps its two-executable gate.

Grid is (rows, vocab tiles, rank): rank is a GRID axis, not a VMEM frame
dim, so the per-step footprint is one A page + one (8-sublane) B vocab
tile regardless of rank — priced by ``vmem_model.lora_epilogue_check``
and validated loudly by `check_lora_geometry` (the
`paged_decode.check_paged_geometry` contract).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (
    interpret_mode, out_struct, pad_to, to_mosaic, use_pallas)

_LANES = 128


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def check_lora_geometry(rank: int, hidden: int, vocab: int,
                        block_v: int, *, es: int = 4) -> int:
    """Validate LoRA-epilogue geometry LOUDLY at trace time: a bad rank
    or vocab tile raises with the priced VMEM estimate instead of
    falling back silently (`paged_decode.check_paged_geometry`)."""
    if rank < 1:
        raise ValueError(f"lora_epilogue: rank={rank} must be >= 1")
    if block_v < _LANES or block_v % _LANES:
        raise ValueError(
            f"lora_epilogue: block_v={block_v} must be a multiple of "
            f"{_LANES} (vocab tiles are lane-aligned)")
    from apex1_tpu.vmem_model import CHECKS, budget_bytes
    hp = _ceil_to(hidden, _LANES)
    vp = _ceil_to(vocab, _LANES)
    ok, est = CHECKS["lora_epilogue"](
        {"block_v": block_v}, {"Hp": hp, "Vp": vp}, es, budget_bytes())
    if not ok:
        raise ValueError(
            f"lora_epilogue: block_v={block_v} (Hp={hp}, Vp={vp}) prices "
            f"at ~{est} B of VMEM > budget {budget_bytes()} B; shrink "
            f"block_v or re-tune (tools/tune_kernels.py)")
    return block_v


def _auto_block_v(hidden, vocab, block_v, dtype):
    """Explicit > tuning table > shrink-to-fit heuristic (docs/ops.md)."""
    es = jnp.dtype(dtype).itemsize
    if block_v is not None:
        return check_lora_geometry(1, hidden, vocab, int(block_v), es=es)
    hp = _ceil_to(hidden, _LANES)
    vp = _ceil_to(vocab, _LANES)
    from apex1_tpu import tuning
    hit = tuning.lookup("lora_epilogue", {"Hp": hp, "Vp": vp}, dtype)
    if hit is not None:
        try:
            return check_lora_geometry(1, hidden, vocab,
                                       int(hit["block_v"]), es=es)
        except (KeyError, ValueError):
            pass  # fail-safe: stale table entries fall back to heuristic
    from apex1_tpu.vmem_model import CHECKS, budget_bytes
    bv = min(2048, vp)
    while bv > _LANES and not CHECKS["lora_epilogue"](
            {"block_v": bv}, {"Hp": hp, "Vp": vp}, es, budget_bytes())[0]:
        bv //= 2
    return check_lora_geometry(1, hidden, vocab, bv, es=es)


def _lora_delta_ref(h, a_pages, b_pages, block_table):
    """Composite gold: gather the pages dense, then the two rank matmuls.
    Row-independent by construction — row n touches only bt[n] — which is
    what makes mixed-tenant batches bitwise equal to solo runs."""
    a = a_pages[block_table]                         # (N, R, H)
    b = b_pages[block_table]                         # (N, R, V)
    coef = jnp.einsum("nh,nrh->nr", h.astype(jnp.float32),
                      a.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    return jnp.einsum("nr,nrv->nv", coef, b.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def _lora_kernel(bt_ref, h_ref, a_ref, b_ref, o_ref, acc, *, n_r):
    r = pl.program_id(2)

    @pl.when(r == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)

    hv = h_ref[0].astype(jnp.float32)                # (1, Hp)
    av = a_ref[0].astype(jnp.float32)                # (1, Hp) — page r
    coef = jnp.sum(hv * av)                          # h[n] · A[:, j]
    bv = b_ref[0].astype(jnp.float32)                # (1, bv) — page r
    acc[...] += coef * jnp.broadcast_to(bv, acc.shape)

    @pl.when(r == n_r - 1)
    def _():
        o_ref[0] = acc[:1, :]


def lora_delta(h, a_pages, b_pages, block_table, *, block_v=None):
    """Per-row paged LoRA logit delta: ``h`` (N, H) hidden rows,
    ``a_pages`` (P, H) / ``b_pages`` (P, V) the adapter page pools,
    ``block_table`` (N, R) int32 page ids (page 0 = zero page ⇒ exact
    0.0 delta for adapterless rows).  Returns (N, V) fp32."""
    N, H = h.shape
    R = block_table.shape[1]
    V = b_pages.shape[1]
    if not use_pallas():
        return _lora_delta_ref(h, a_pages, b_pages, block_table)
    bv = _auto_block_v(H, V, block_v, h.dtype)
    check_lora_geometry(R, H, V, bv, es=jnp.dtype(h.dtype).itemsize)
    hm, am, bm = to_mosaic(h, a_pages, b_pages)
    hp, _ = pad_to(hm, 1, _LANES)
    ap, _ = pad_to(am, 1, _LANES)
    bp, _ = pad_to(bm, 1, bv)
    Hp = hp.shape[1]
    Vp = bp.shape[1]
    # singleton sublane dim: Mosaic wants the last two block dims
    # (8, 128)-divisible OR equal to the array dims — a (1, Hp) block on
    # a (P, Hp) array is neither, but (1, 1, Hp) on (P, 1, Hp) is
    hp = hp.reshape(N, 1, Hp)
    ap = ap.reshape(-1, 1, Hp)
    bp = bp.reshape(-1, 1, Vp)
    btf = block_table.reshape(-1).astype(jnp.int32)  # scalar-prefetched

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N, Vp // bv, R),
        in_specs=[
            pl.BlockSpec((1, 1, Hp), lambda n, v, r, bt: (n, 0, 0)),
            pl.BlockSpec((1, 1, Hp),
                         lambda n, v, r, bt: (bt[n * R + r], 0, 0)),
            pl.BlockSpec((1, 1, bv),
                         lambda n, v, r, bt: (bt[n * R + r], 0, v)),
        ],
        out_specs=pl.BlockSpec((1, 1, bv), lambda n, v, r, bt: (n, 0, v)),
        scratch_shapes=[pltpu.VMEM((8, bv), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_lora_kernel, n_r=R),
        grid_spec=grid_spec,
        out_shape=out_struct((N, 1, Vp), jnp.float32, hm, am, bm),
        interpret=interpret_mode(),
    )(btf, hp, ap, bp)
    return out[:, 0, :V]
