"""Paged ragged decode attention over KV-pool pages + fused sampling.

The serving engine's decode step was XLA-composed attention over DENSE
per-slot KV lanes: every token paid full-``max_len`` attention reads, a
separate dequant pass on the int8 cache tier, and a host round trip for
sampling. This module is the kernel-shaped answer (ROADMAP item 5; the
op-fusion results in PAPERS.md 2502.17728 are the motivating numbers):

- :func:`cache_attend` — the decode/chunk attention composite, extracted
  from ``models.generate.cached_attention`` so the dense reference path
  and the paged path share ONE implementation (bit-identical logits on
  the CPU proxy is a structural property, not a test accident).
- :class:`PagedCache` + :func:`paged_update_attend` — the per-layer
  cache entry the models thread opaquely: K/V live in a shared PAGE
  pool ``(num_pages, Hkv, page, D)`` addressed through a per-row block
  table, so prefix pages are shared by reference (no copy-on-admit) and
  the decode working set is proportional to actual lengths.
- :func:`paged_attend` — the Pallas kernel: grid ``(N, Hkv, pages)``
  with the page axis innermost; each step DMAs ONE page block selected
  by the scalar-prefetched block table (``PrefetchScalarGridSpec`` —
  the index map reads ``bt[n·T + t]``, so the gather IS the pipeline),
  dequantizes int8/bf16 pages to f32 in-register (the ``cache_dtype``
  tier stops paying a separate dequant op), and folds an online-softmax
  flash update across pages. Pages past a row's horizon are skipped
  entirely (``pl.when`` on the traced length — the RAGGED part).
- :func:`fused_sample` — the sampling epilogue: logits → vocab mask →
  temperature → counter-keyed gumbel draw → argmax, one kernel per row
  batch. The in-kernel PRNG re-derives the exact jax 0.4.x
  threefry-2x32 stream (`_uniform_bits` — pinned bitwise against
  ``jax.random`` in ``tests/test_paged_decode.py``), so the kernel
  emits the SAME token ids as ``fold_in(key(seed), pos)`` +
  ``jax.random.categorical`` — the per-request counter-PRNG contract
  (resubmission idempotency, speculative exact-match accept) survives
  the fusion verbatim.

Dispatch follows `ops._common`: XLA composite on CPU/GPU (the parity
gold — tier-1 pins the serving engine's paged path bit-identical to the
dense path through it), Pallas on TPU (interpret-mode tested here).
What the CPU proxy does NOT measure is documented in
``docs/paged_decode.md``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (NEG_INF, interpret_mode, out_struct,
                                   pad_to, use_pallas)

_LANES = 128
_SUBLANES = 8
_TINY = np.float32(np.finfo(np.float32).tiny)


# ---- shared attention composite (the ONE decode-attention math) --------


def cache_attend(q, k_all, v_all, cache_index, *,
                 sm_scale: Optional[float] = None, bias=None,
                 valid_start=None):
    """Masked composite attention of (B, Hq, S, D) queries against a
    FULL cache (B, Hkv, S_max, D) — the decode/chunk-decode math of
    ``models.generate.cached_attention``, factored out so the paged
    path attends through the SAME ops (gather pages → dense → here)
    and token parity with the dense engine is bit-exact by
    construction. ``cache_index`` may be a scalar (the dense path) or
    a per-row (B,) vector (the paged batch path — rows at different
    depths). Query j sees cache slots <= index + j."""
    B, Hq, S, D = q.shape
    Hkv = k_all.shape[1]
    idx = jnp.asarray(cache_index, jnp.int32)
    scale = (D ** -0.5) if sm_scale is None else sm_scale
    # GQA without materializing a repeated cache: group the q heads onto
    # the kv-head axis and contract against the cache as-is (a repeated
    # (B, Hq, S_max, D) copy would multiply the decode loop's memory
    # traffic by the group factor)
    group = Hq // Hkv
    qg = q.reshape(B, Hkv, group, S, D)
    scores = jnp.einsum("bhgsd,bhkd->bhgsk", qg, k_all,
                        preferred_element_type=jnp.float32) * scale
    if bias is None:
        scores_b = scores
    else:
        scores_b = scores + bias.astype(jnp.float32).reshape(
            bias.shape[0], Hkv, group, S, -1)
    S_max = k_all.shape[2]
    pos = jnp.arange(S_max)
    # per-query horizon: query j sees cache slots <= idx + j (S == 1
    # decode reduces to pos <= idx)
    if idx.ndim == 0:
        horizon = idx + jnp.arange(S)[None, None, None, :, None]
    else:
        horizon = (idx.reshape(B, 1, 1, 1, 1)
                   + jnp.arange(S)[None, None, None, :, None])
    keep = pos[None, None, None, None, :] <= horizon
    if valid_start is not None:
        keep = keep & (pos[None, None, None, None, :]
                       >= valid_start.reshape(B, 1, 1, 1, 1))
    scores_b = jnp.where(keep, scores_b, NEG_INF)
    probs = jax.nn.softmax(scores_b, axis=-1).astype(q.dtype)
    attn = jnp.einsum("bhgsk,bhkd->bhgsd", probs, v_all)
    return attn.reshape(B, Hq, S, D)


# ---- sampling (shared pipeline + fused kernel) -------------------------


def _temperature_top_k(logits, temperature, top_k, vocab_size):
    """Shared temperature + top-k masking over (..., V) fp32 logits
    (the padded-vocab tail must already be NEG_INF-masked)."""
    logits = logits / temperature
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        # clamp to the VALID vocab: a larger top_k would (a) raise an
        # opaque trace-time IndexError past the full width and (b) pick
        # a NEG_INF masked-tail entry as the kth threshold, silently
        # disabling truncation (ADVICE r3)
        eff_v = logits.shape[-1]
        if vocab_size is not None and vocab_size < eff_v:
            eff_v = vocab_size
        k = min(int(top_k), eff_v)
        kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
        logits = jnp.where(logits >= kth, logits, NEG_INF)
    return logits


def sample_token(logits, rng, *, temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 vocab_size: Optional[int] = None):
    """One sampling step from (B, V) logits. ``temperature == 0`` =
    greedy argmax; otherwise softmax sampling, optionally truncated to the
    ``top_k`` highest-probability tokens. ``vocab_size`` masks padded
    vocab tail (GPT-2's padded_vocab)."""
    logits = logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        mask = jnp.arange(logits.shape[-1]) < vocab_size
        logits = jnp.where(mask, logits, NEG_INF)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _temperature_top_k(logits, temperature, top_k, vocab_size)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _threefry2x32(k1, k2, x0, x1):
    """The 20-round threefry-2x32 block as pure uint32 jnp ops — runs
    identically inside a Pallas body and in plain XLA. Reproduces jax
    0.4.x ``jax._src.prng.threefry2x32`` op-for-op (key schedule,
    rotation constants, round-group injections); the bitwise match
    against ``jax.random`` is pinned in ``tests/test_paged_decode.py``
    (a silent divergence here would break the serving engine's
    counter-seed resubmission contract, not just perf)."""
    rotations = ((13, 15, 26, 6), (17, 29, 16, 24))
    ks = (k1, k2, k1 ^ k2 ^ np.uint32(0x1BD11BDA))
    x0 = x0 + ks[0]
    x1 = x1 + ks[1]
    for i in range(5):
        for r in rotations[i % 2]:
            x0 = x0 + x1
            x1 = (x1 << np.uint32(r)) | (x1 >> np.uint32(32 - r))
            x1 = x1 ^ x0
        x0 = x0 + ks[(i + 1) % 3]
        x1 = x1 + ks[(i + 2) % 3] + np.uint32(i + 1)
    return x0, x1


def _uniform_bits(k1, k2, col, n: int,
                  partitionable: Optional[bool] = None):
    """The uint32 draw at flat position ``col`` of an n-element
    ``jax.random`` uniform over key (k1, k2), for EITHER threefry
    stream (``partitionable`` defaults to the live
    ``jax_threefry_partitionable`` config — the tier-1 harness runs
    True, the jax 0.4.x default is False; the kernel must match
    whichever stream the composite engine draws from):

    - partitionable: per-position 64-bit counter split into uint32
      halves — position ``col`` is the pair (0, col) for any n < 2^32,
      output ``y0 ^ y1``. Trivially position-wise.
    - original: counts = iota(n) (zero-padded to even), split in
      halves, one threefry-2x32 pass. Each lane recomputes its
      half-pair partner (2x the threefry work, fully vectorized) so
      the whole draw is position-wise and fuses into the kernel."""
    if partitionable is None:
        partitionable = bool(jax.config.jax_threefry_partitionable)
    if partitionable:
        y0, y1 = _threefry2x32(k1, k2, jnp.zeros_like(col).astype(
            jnp.uint32), col.astype(jnp.uint32))
        return y0 ^ y1
    odd = n % 2
    h = (n + odd) // 2
    lo = col < h
    a_idx = jnp.where(lo, col, col - h)
    b_idx = a_idx + h
    aval = a_idx.astype(jnp.uint32)
    if odd:
        # the odd count is zero-PADDED before the split, so the last
        # second-half lane's counter is the pad zero, not its index
        bval = jnp.where(b_idx == n, 0, b_idx).astype(jnp.uint32)
    else:
        bval = b_idx.astype(jnp.uint32)
    y0, y1 = _threefry2x32(k1, k2, aval, bval)
    return jnp.where(lo, y0, y1)


def _bits_to_gumbel(bits):
    """uint32 → standard gumbel, op-for-op the jax 0.4.x
    ``_uniform``/``_gumbel`` pipeline (mantissa fill to [1, 2), subtract
    1, affine to [tiny, 1), −log(−log(u)))."""
    fb = (bits >> np.uint32(9)) | np.uint32(0x3F800000)
    u = jax.lax.bitcast_convert_type(fb, jnp.float32) - np.float32(1.0)
    u = u * np.float32(np.float32(1.0) - _TINY) + _TINY
    u = jnp.maximum(_TINY, u)
    return -jnp.log(-jnp.log(u))


def _row_keys(seeds, positions):
    """(R, 2) uint32 key data for ``fold_in(key(seed), position)`` per
    row — derived through jax.random itself (tiny per-row scalar work;
    reusing the canonical implementation removes any reimplementation
    risk from the key-derivation half of the contract)."""

    def one(s, p):
        return jax.random.key_data(
            jax.random.fold_in(jax.random.key(s), p))

    return jax.vmap(one)(jnp.asarray(seeds, jnp.int32),
                         jnp.asarray(positions, jnp.int32))


def _fused_sample_kernel(key_ref, lg_ref, o_ref, m_scr, i_scr, *, n,
                         v_eff, temperature, scale_in_kernel, greedy,
                         bv, total):
    t = pl.program_id(1)
    T = pl.num_programs(1)

    @pl.when(t == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        i_scr[...] = jnp.full_like(i_scr, total)

    lg = lg_ref[...].astype(jnp.float32)        # (_SUBLANES, bv)
    col = t * bv + jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    if scale_in_kernel:
        lg = jnp.where(col < v_eff, lg, NEG_INF)
        if not greedy:
            lg = lg / temperature
    if greedy:
        vals = lg
    else:
        # per-row keys broadcast down the vocab lanes; row-pad keys are
        # zeros drawing over NEG_INF logits — argmax 0, sliced away
        k1 = key_ref[:, 0:1].astype(jnp.uint32)
        k2 = key_ref[:, 1:2].astype(jnp.uint32)
        g = _bits_to_gumbel(_uniform_bits(k1, k2, col, n))
        vals = g + lg
    # first-index-of-max == jnp.argmax, via max + masked-min (Mosaic has
    # no direct argmax reduction, and no INTEGER reductions at all — the
    # index min runs in f32, exact for any index < 2^24, far past any
    # vocab). f32 max is exact, so the running (max, first-index) fold
    # across vocab blocks is bitwise the single-block argmax whatever
    # block_v splits the row into.
    bm = jnp.max(vals, axis=-1, keepdims=True)
    bi = jnp.min(jnp.where(vals == bm, col.astype(jnp.float32),
                           jnp.float32(total)),
                 axis=-1, keepdims=True).astype(jnp.int32)
    m_prev, i_prev = m_scr[:, :1], i_scr[:, :1]
    new_i = jnp.where(bm > m_prev, bi,
                      jnp.where(bm == m_prev,
                                jnp.minimum(i_prev, bi), i_prev))
    m_scr[...] = jnp.broadcast_to(jnp.maximum(m_prev, bm), m_scr.shape)
    i_scr[...] = jnp.broadcast_to(new_i, i_scr.shape)

    @pl.when(t == T - 1)
    def _():
        o_ref[...] = i_scr[...]


def _fused_sample_ref(logits, seeds, positions, *, temperature, top_k,
                      vocab_size):
    """The composite: per-row ``fold_in(key(seed), pos)`` +
    `sample_token` — literally the dense engine's sampling ops under
    one vmap, so the CPU paged path emits bit-identical tokens."""

    def one(lg, s, p):
        key = jax.random.fold_in(jax.random.key(s), p)
        return sample_token(lg[None], key, temperature=temperature,
                            top_k=top_k, vocab_size=vocab_size)[0]

    return jax.vmap(one)(logits, jnp.asarray(seeds, jnp.int32),
                         jnp.asarray(positions, jnp.int32))


def fused_sample(logits, seeds, positions, *, temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 vocab_size: Optional[int] = None,
                 block_v: Optional[int] = None):
    """Counter-keyed sampling over (R, V) logits rows: row r draws with
    ``fold_in(key(seeds[r]), positions[r])`` — `sample_token` semantics,
    per-row seeds. On the Pallas path the whole epilogue (vocab mask,
    temperature, gumbel draw, argmax) runs in ONE kernel per row batch
    and only the (R,) token ids leave the device — the fused sampling
    epilogue of the paged decode step. ``top_k`` keeps its sort outside
    the kernel (the reference `_temperature_top_k` pipeline runs first;
    the kernel then draws from the pre-truncated logits). ``block_v``
    tiles the vocab axis (None = tuning-table winner for the padded
    vocab, else one full-row block); any split is bitwise-equivalent —
    the in-kernel fold is an exact f32 (max, first-index) reduction."""
    R, V = logits.shape
    seeds = jnp.asarray(seeds, jnp.int32)
    positions = jnp.asarray(positions, jnp.int32)
    if not use_pallas():
        return _fused_sample_ref(logits, seeds, positions,
                                 temperature=temperature, top_k=top_k,
                                 vocab_size=vocab_size)
    lg = logits.astype(jnp.float32)
    v_eff = V if (vocab_size is None or vocab_size >= V) else int(
        vocab_size)
    greedy = temperature == 0.0
    scale_in_kernel = top_k is None
    if not scale_in_kernel:
        # sort-based truncation stays in XLA; mask + scale ride along so
        # the kernel sees exactly the reference's post-pipeline logits
        lg = jnp.where(jnp.arange(V) < v_eff, lg, NEG_INF)
        if not greedy:
            lg = _temperature_top_k(lg, temperature, top_k, vocab_size)
    # sublane-aligned row blocks (Mosaic requires 8x128-tileable block
    # shapes): rows pad with NEG_INF logits + zero keys, sliced away
    lgp, _ = pad_to(lg, 1, _LANES, value=NEG_INF)
    lgp, _ = pad_to(lgp, 0, _SUBLANES, value=NEG_INF)
    Rp, Vp = lgp.shape
    if block_v is None:
        from apex1_tpu import tuning
        tuned = tuning.lookup("fused_sample", {"Vp": Vp}, jnp.float32)
        block_v = int(tuned["block_v"]) if tuned else Vp
    bv = max(_LANES, min(-(-int(block_v) // _LANES) * _LANES, Vp))
    lgp, _ = pad_to(lgp, 1, bv, value=NEG_INF)   # grid tiles exactly
    Vp2 = lgp.shape[1]
    keys = jax.lax.bitcast_convert_type(
        _row_keys(seeds, positions), jnp.int32)
    keysp = jnp.zeros((Rp, _LANES), jnp.int32).at[:R, :2].set(keys)
    out = pl.pallas_call(
        functools.partial(_fused_sample_kernel, n=V, v_eff=v_eff,
                          temperature=temperature,
                          scale_in_kernel=scale_in_kernel,
                          greedy=greedy, bv=bv, total=Vp2),
        grid=(Rp // _SUBLANES, Vp2 // bv),
        in_specs=[pl.BlockSpec((_SUBLANES, _LANES),
                               lambda b, t: (b, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((_SUBLANES, bv), lambda b, t: (b, t),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda b, t: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((Rp, _LANES), jnp.int32, lgp),
        scratch_shapes=[pltpu.VMEM((_SUBLANES, _LANES), jnp.float32),
                        pltpu.VMEM((_SUBLANES, _LANES), jnp.int32)],
        interpret=interpret_mode(),
    )(keysp, lgp)
    return out[:R, 0]


# ---- page pytree plumbing ----------------------------------------------


def gather_pages(pages, block_table, total_len: int):
    """Assemble dense (N, Hkv, total_len, D) lanes from a page pool
    (num_pages, Hkv, page, D) through an (N, T) block table — the
    composite read path (and the CPU engine's bridge onto the UNCHANGED
    dense reference executables: gather → reference ops → scatter)."""
    g = jnp.take(pages, jnp.asarray(block_table, jnp.int32), axis=0)
    g = jnp.swapaxes(g, 1, 2)                    # (N, Hkv, T, P, D)
    N, Hkv, T, P, D = g.shape
    return g.reshape(N, Hkv, T * P, D)[:, :, :total_len, :]


def scatter_pages(pages, block_table, values, start):
    """Write (N, Hkv, W, D) ``values`` into the page pool at positions
    ``[start, start + W)`` per row (page-spanning windows handled by
    position-wise scatter — no page-alignment requirement). Rows whose
    block-table entries are the trash page (id 0, freed slots) write
    harmless garbage there; page 0 is never attended."""
    P, T = pages.shape[2], block_table.shape[1]
    W = values.shape[2]
    start = jnp.asarray(start, jnp.int32).reshape(-1)
    pos = start[:, None] + jnp.arange(W, dtype=jnp.int32)[None]
    pid = jnp.take_along_axis(jnp.asarray(block_table, jnp.int32),
                              jnp.clip(pos // P, 0, T - 1), axis=1)
    off = pos % P
    vals = jnp.swapaxes(values, 1, 2)            # (N, W, Hkv, D)
    return pages.at[pid, :, off, :].set(vals.astype(pages.dtype))


@jax.tree_util.register_pytree_node_class
class PagedCache:
    """One layer's paged KV cache entry: K/V page pools plus the block
    table that maps (row, page-slot) → pool page. Threads through the
    models' ``cache[f"layer{i}"]`` slot opaquely — `cached_attention`
    detects it and routes to :func:`paged_update_attend`. ``length`` is
    the STATIC dense-equivalent lane length (attention mask geometry);
    the block table rides as a pytree child shared (by reference)
    across every layer's entry."""

    def __init__(self, k_pages, v_pages, block_table, length: int):
        self.k_pages = k_pages
        self.v_pages = v_pages
        self.block_table = block_table
        self.length = int(length)

    def tree_flatten(self):
        return ((self.k_pages, self.v_pages, self.block_table),
                (self.length,))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def paged_update_attend(q, k_new, v_new, pc: PagedCache, cache_index, *,
                        sm_scale: Optional[float] = None,
                        chunk_decode: bool = False):
    """The paged counterpart of dense ``cached_attention``: scatter the
    new tokens' K/V into their pages (dtype cast = the int8 tier's
    quantized write, unchanged), then attend the updated pages —
    composite gather + :func:`cache_attend` off-TPU (the parity gold),
    the :func:`paged_attend` kernel on TPU. ``cache_index`` may be a
    scalar or per-row (B,) vector. Returns (attn, new PagedCache)."""
    B, Hq, S, D = q.shape
    if S > 1 and not chunk_decode:
        raise ValueError(
            "PagedCache prefill must use chunk_decode=True (the paged "
            "pipeline has no flash-prefill mode; an empty cache at "
            "index 0 is the chunk mode's degenerate case)")
    idx = jnp.asarray(cache_index, jnp.int32)
    idx = jnp.broadcast_to(idx.reshape(-1)[:1] if idx.ndim == 0
                           else idx, (B,))
    kp = scatter_pages(pc.k_pages, pc.block_table, k_new, idx)
    vp = scatter_pages(pc.v_pages, pc.block_table, v_new, idx)
    new = PagedCache(kp, vp, pc.block_table, pc.length)
    attn = paged_attend(q, kp, vp, pc.block_table, idx,
                        sm_scale=sm_scale, total_len=pc.length)
    return attn, new


# ---- the paged ragged attention kernel ---------------------------------


def _paged_attn_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                       acc, m_scr, l_scr, *, scale, S, P, T, n_rows):
    n, t = pl.program_id(0), pl.program_id(2)

    @pl.when(t == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    idx = len_ref[n]

    def compute():
        # fused dequant: int8/bf16 pages convert to f32 on the VMEM
        # tile, inside the same kernel that consumes them — the
        # cache_dtype tier's separate dequant op is gone
        q = q_ref[0, 0].astype(jnp.float32)              # (Rq, Dp)
        k = k_ref[0, 0].astype(jnp.float32)              # (P, Dp)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # rows are (g, s) pairs of the GQA group: query s of the chunk
        # sees global positions <= idx + s; padded rows stay empty
        keep = ((row < n_rows)
                & (t * P + col <= idx + row % S))
        s = jnp.where(keep, s, NEG_INF)
        m_prev, l_prev = m_scr[:, :1], l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        e = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        l_new = l_prev * corr + jnp.sum(e, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        acc[...] = acc[...] * corr + jax.lax.dot_general(
            e, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    # ragged skip: pages wholly past this row's horizon (idx + S - 1)
    # are never read — per-token work tracks ACTUAL depth, not max_len
    pl.when(t * P <= idx + S - 1)(compute)

    @pl.when(t == T - 1)
    def _():
        l = l_scr[:, :1]
        safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0, 0] = (acc[...] / safe).astype(o_ref.dtype)


def check_paged_geometry(page: int, head_dim: int, group: int, s: int):
    """Loud validation of a paged-kernel geometry: sublane-aligned page,
    VMEM-budget fit under the shared `vmem_model` formula. Raised at
    trace time on the kernel path and re-checked by ``tools/aot_check``
    for every engine-configured shape (including the int8 and bf16
    cache dtypes) — an unregistered/unfittable shape fails loudly, it
    never silently falls back."""
    from apex1_tpu.vmem_model import CHECKS, budget_bytes
    if page % 8 != 0 or page < 8:
        raise ValueError(
            f"paged_decode needs a sublane-aligned page size (multiple "
            f"of 8), got {page} — set EngineConfig.page_size")
    dp = max(_LANES, ((head_dim + _LANES - 1) // _LANES) * _LANES)
    rq = max(8, ((group * s + 7) // 8) * 8)
    fits, est = CHECKS["paged_decode"]({"page_p": page},
                                      {"Dp": dp, "Rq": rq}, 4,
                                      budget_bytes())
    if not fits:
        raise ValueError(
            f"paged_decode geometry page={page} Dp={dp} Rq={rq} needs "
            f"~{est} B of VMEM — over budget; shrink page_size")
    return dp, rq


def paged_attend(q, k_pages, v_pages, block_table, lengths, *,
                 sm_scale: Optional[float] = None,
                 total_len: Optional[int] = None):
    """Ragged paged decode attention: (N, Hq, S, D) queries against
    (num_pages, Hkv, page, D) K/V pools through an (N, T) block table,
    each row masked to its own ``lengths[n] + j`` horizon. Composite
    path gathers dense lanes and runs :func:`cache_attend` (bitwise the
    dense engine's math); Pallas path streams pages via
    scalar-prefetched block-table indices with int8 dequant fused
    in-kernel."""
    N, Hq, S, D = q.shape
    num_pages, Hkv, P, _ = k_pages.shape
    T = block_table.shape[1]
    if Hq % Hkv != 0:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    lengths = jnp.asarray(lengths, jnp.int32).reshape(-1)
    L = T * P if total_len is None else int(total_len)
    if not use_pallas():
        k_all = gather_pages(k_pages, block_table, L)
        v_all = gather_pages(v_pages, block_table, L)
        return cache_attend(q, k_all, v_all, lengths, sm_scale=sm_scale)
    G = Hq // Hkv
    scale = (D ** -0.5) if sm_scale is None else sm_scale
    Dp, Rqp = check_paged_geometry(P, D, G, S)
    qv = q.reshape(N, Hkv, G * S, D)
    qv, _ = pad_to(qv, 2, Rqp)
    qv, _ = pad_to(qv, 3, Dp)
    kp, _ = pad_to(k_pages, 3, Dp)
    vp, _ = pad_to(v_pages, 3, Dp)
    btf = jnp.asarray(block_table, jnp.int32).reshape(-1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(N, Hkv, T),
        in_specs=[
            pl.BlockSpec((1, 1, Rqp, Dp),
                         lambda n, h, t, bt, ln: (n, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, P, Dp),
                         lambda n, h, t, bt, ln: (bt[n * T + t], h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, P, Dp),
                         lambda n, h, t, bt, ln: (bt[n * T + t], h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, Rqp, Dp),
                               lambda n, h, t, bt, ln: (n, h, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((Rqp, Dp), jnp.float32),
            pltpu.VMEM((Rqp, _LANES), jnp.float32),
            pltpu.VMEM((Rqp, _LANES), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, scale=scale, S=S, P=P,
                          T=T, n_rows=G * S),
        grid_spec=grid_spec,
        out_shape=out_struct((N, Hkv, Rqp, Dp), q.dtype, qv, kp, vp),
        interpret=interpret_mode(),
    )(btf, lengths, qv, kp, vp)
    return out[:, :, :G * S, :D].reshape(N, Hq, S, D)


# ---- the parity drill (check_all's paged gate) --------------------------


def _drill():
    """Standalone paged-vs-reference parity drill — `check_all.sh`'s
    `== paged parity drill ==` step. Forces the Pallas kernels (CPU =
    interpret mode; on a real TPU the same drill exercises actual
    Mosaic) against the XLA-composed reference on ragged pools in BOTH
    cache dtypes, decode AND verify shapes, and the fused sampler at
    every tier-1 temperature with a non-trivial ``block_v`` split.
    Attention compares at the suite's f32 tolerance (flash fold vs
    composite softmax differ at the ulp, by construction); TOKENS are
    exact equality — the same contract tier-1 pins through the engine
    (`tests/test_paged_decode.py`)."""
    from apex1_tpu.ops._common import force_impl

    rng = np.random.default_rng(0)
    N, Hq, Hkv, D, P, T = 4, 8, 2, 64, 16, 6
    n_pg = 1 + N * T
    bt = jnp.asarray(
        np.arange(1, n_pg, dtype=np.int32).reshape(N, T))
    lens = jnp.asarray([1, P - 1, P + 3, T * P - 6], dtype=jnp.int32)
    q1 = jnp.asarray(rng.standard_normal((N, Hq, 1, D)), jnp.float32)
    S_v = 5
    qv = jnp.asarray(rng.standard_normal((N, Hq, S_v, D)), jnp.float32)
    raw = rng.standard_normal((2, n_pg, Hkv, P, D))
    for name, cast in (
            ("bf16", lambda a: jnp.asarray(a, jnp.bfloat16)),
            ("int8", lambda a: jnp.asarray(
                np.clip(np.round(a * 30.0), -127, 127), jnp.int8))):
        kp, vp = cast(raw[0]), cast(raw[1])
        for tag, q, ln in (("decode", q1, lens),
                           ("verify", qv, lens)):
            with force_impl("xla"):
                ref = paged_attend(q, kp, vp, bt, ln, total_len=T * P)
            with force_impl("pallas"):
                ker = paged_attend(q, kp, vp, bt, ln, total_len=T * P)
            np.testing.assert_allclose(
                np.asarray(ker, np.float32), np.asarray(ref, np.float32),
                rtol=1e-5, atol=1e-6)
            print(f"paged_attend {name} {tag}: kernel == reference OK")
    R, V = 8, 1024
    lg = jnp.asarray(rng.standard_normal((R, V)), jnp.float32)
    seeds = jnp.asarray(rng.integers(0, 2**31 - 1, R), jnp.int32)
    pos = jnp.asarray(rng.integers(0, 4096, R), jnp.int32)
    for temp in (0.0, 0.7, 1.3):
        with force_impl("xla"):
            ref = fused_sample(lg, seeds, pos, temperature=temp,
                               vocab_size=V - 175)
        with force_impl("pallas"):
            ker = fused_sample(lg, seeds, pos, temperature=temp,
                               vocab_size=V - 175, block_v=256)
        np.testing.assert_array_equal(np.asarray(ker), np.asarray(ref))
        print(f"fused_sample T={temp} block_v=256: tokens == "
              f"composite OK")
    print("paged parity drill PASSED")


if __name__ == "__main__":
    import sys

    if "--drill" in sys.argv:
        _drill()
    else:
        sys.exit("usage: python -m apex1_tpu.ops.paged_decode --drill")
