"""Fused dense layers — reference ``apex/fused_dense/fused_dense.py ::
FusedDense, FusedDenseGeluDense`` (+ ``csrc/fused_dense*.cu``) and
``apex/mlp/mlp.py :: MLP`` (+ ``csrc/mlp*.cu``).

**Documented "XLA already fuses this" decision (SURVEY.md §7.0):** the
reference needs cuBLASLt epilogue fusion (``CUBLASLT_EPILOGUE_{BIAS,
GELU_AUX_BIAS,DGELU_BGRAD}``) and a bespoke GEMM-chain kernel because eager
torch launches matmul/bias/activation as separate kernels. Under XLA the
matmul lands on the MXU and the bias/GELU/ReLU epilogues are fused into its
output stage by the compiler — a hand-written Pallas GEMM would have to beat
XLA's own matmul emitter to win, which is expected not to happen for plain
dense shapes. The confirming roofline A/B (``tools/bench_kernels.py dense``,
achieved-TFLOPs vs MXU peak) is queued in the hardware revival queue and has
NOT yet run (docs/perf_playbook.md §2) — the decision currently rests on the
architecture argument plus AOT lowering checks, not a measurement. So these
are thin modules with the reference's API over ``jnp`` compute, with fp32
MXU accumulation (``preferred_element_type``) matching the reference's
fp16-in/fp32-accumulate GEMMs. The backward (dgelu+bgrad, wgrad chain) is
jax AD, which XLA fuses the same way.

**The documented exception — gated MLPs (``fused_glu``):** llama-family
SwiGLU/GeGLU is ``act(x @ w_gate) * (x @ w_up)`` — TWO matmuls sharing one
``x`` whose outputs meet in an elementwise product. XLA schedules them as
two independent GEMMs, so ``x`` streams from HBM twice and the (T, F)
``gate`` product round-trips through HBM before the multiply. The Pallas
kernel below computes both dots and the glu product per (block_t, block_f)
tile in one pass over ``x`` — the arXiv 2502.17728 operation-fusion point.
H is deliberately NOT tiled (one MXU dot per operand per tile), so the
per-element reduction order matches the unfused XLA dot and the parity
check can be exact. The composite path IS the inline llama expression,
token-for-token, so routing `models/llama.py` through ``fused_glu`` is
bitwise-neutral on the CPU proxy (asserted in tests/test_fused_glu.py).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (
    interpret_mode, out_struct, pad_to, to_mosaic, use_pallas)

_LANES = 128


def fused_dense(x, weight, bias=None):
    """y = x @ Wᵀ + b. ``weight`` is (out, in) — torch convention, like the
    reference's ``FusedDenseFunc``."""
    y = jnp.matmul(x, weight.T, preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias
    return y.astype(x.dtype)


def fused_dense_gelu_dense(x, w1, b1, w2, b2):
    """Linear+bias+GELU+Linear+bias in one traced region (reference
    ``FusedDenseGeluDenseFunc``); XLA fuses the epilogues."""
    h = fused_dense(x, w1, b1)
    h = jax.nn.gelu(h, approximate=True)
    return fused_dense(h, w2, b2)


class FusedDense(nn.Module):
    """``apex.fused_dense.FusedDense(in_features, out_features, bias)``."""

    in_features: int
    out_features: int
    bias: bool = True

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features), jnp.float32)
        b = (self.param("bias", nn.initializers.zeros,
                        (self.out_features,), jnp.float32)
             if self.bias else None)
        return fused_dense(x, w.astype(x.dtype),
                           None if b is None else b.astype(x.dtype))


class FusedDenseGeluDense(nn.Module):
    """``apex.fused_dense.FusedDenseGeluDense(in, intermediate, out)``."""

    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True

    @nn.compact
    def __call__(self, x):
        k = nn.initializers.lecun_normal()
        w1 = self.param("weight1", k, (self.intermediate_features,
                                       self.in_features), jnp.float32)
        w2 = self.param("weight2", k, (self.out_features,
                                       self.intermediate_features),
                        jnp.float32)
        b1 = b2 = None
        if self.bias:
            b1 = self.param("bias1", nn.initializers.zeros,
                            (self.intermediate_features,), jnp.float32)
            b2 = self.param("bias2", nn.initializers.zeros,
                            (self.out_features,), jnp.float32)
        cast = lambda t: None if t is None else t.astype(x.dtype)
        return fused_dense_gelu_dense(x, cast(w1), cast(b1), cast(w2),
                                      cast(b2))


_ACTIVATIONS: dict[str, Optional[Callable]] = {
    "none": None,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


# ---------------------------------------------------------------------------
# Fused SwiGLU / GeGLU — the gated-MLP exception to "XLA already fuses this"
# ---------------------------------------------------------------------------

_GLU_ACTS: dict[str, Callable] = {
    "silu": jax.nn.silu,                                    # SwiGLU (llama)
    "gelu": functools.partial(jax.nn.gelu, approximate=True),  # GeGLU
}


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def check_glu_geometry(block_t: int, block_f: int, hidden: int, *,
                       es: int = 4) -> tuple[int, int]:
    """Validate a fused-glu tile LOUDLY at trace time (the
    `ops.paged_decode.check_paged_geometry` contract): misaligned or
    over-budget tiles raise with the priced estimate instead of falling
    back silently and OOMing Mosaic on silicon."""
    if block_t < 8 or block_t % 8:
        raise ValueError(
            f"fused_glu: block_t={block_t} must be a multiple of 8 "
            f"(sublane tiling)")
    if block_f < _LANES or block_f % _LANES:
        raise ValueError(
            f"fused_glu: block_f={block_f} must be a multiple of {_LANES}")
    from apex1_tpu.vmem_model import CHECKS, budget_bytes
    hp = _ceil_to(hidden, _LANES)
    ok, est = CHECKS["fused_swiglu"](
        {"block_t": block_t, "block_f": block_f}, {"Hp": hp}, es,
        budget_bytes())
    if not ok:
        raise ValueError(
            f"fused_glu: blocks ({block_t}, {block_f}) at Hp={hp} price "
            f"at ~{est} B of VMEM > budget {budget_bytes()} B; shrink the "
            f"tile or re-tune (tools/tune_kernels.py)")
    return block_t, block_f


def _auto_glu_blocks(T, F, hidden, block_t, block_f, dtype):
    """Explicit > tuning table > shrink-to-fit heuristic (docs/ops.md)."""
    es = jnp.dtype(dtype).itemsize
    if block_t is not None or block_f is not None:
        return check_glu_geometry(int(block_t or 128), int(block_f or 256),
                                  hidden, es=es)
    hp = _ceil_to(hidden, _LANES)
    from apex1_tpu import tuning
    hit = tuning.lookup("fused_swiglu", {"Hp": hp}, dtype)
    if hit is not None:
        try:
            return check_glu_geometry(int(hit["block_t"]),
                                      int(hit["block_f"]), hidden, es=es)
        except (KeyError, ValueError):
            pass  # fail-safe: stale table entries fall back to heuristic
    from apex1_tpu.vmem_model import CHECKS, budget_bytes
    bt = min(128, max(8, _ceil_to(T, 8)))
    bf = min(512, max(_LANES, _ceil_to(F, _LANES)))
    while bf > _LANES and not CHECKS["fused_swiglu"](
            {"block_t": bt, "block_f": bf}, {"Hp": hp}, es,
            budget_bytes())[0]:
        bf //= 2
    while bt > 8 and not CHECKS["fused_swiglu"](
            {"block_t": bt, "block_f": bf}, {"Hp": hp}, es,
            budget_bytes())[0]:
        bt //= 2
    return check_glu_geometry(bt, bf, hidden, es=es)


def _glu_kernel(x_ref, g_ref, u_ref, o_ref, *, activation):
    # ONE full-H dot per operand (H is never split across grid steps),
    # so each output element's reduction order matches the unfused dot.
    x = x_ref[...]
    g = jax.lax.dot_general(x, g_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    u = jax.lax.dot_general(x, u_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[...] = (_GLU_ACTS[activation](g) * u).astype(o_ref.dtype)


def _glu_call(x2, wg, wu, activation, bt, bf):
    T, H = x2.shape
    F = wg.shape[1]
    xm, wgm, wum = to_mosaic(x2, wg, wu)
    xp, _ = pad_to(xm, 0, bt)
    xp, _ = pad_to(xp, 1, _LANES)
    Hp = xp.shape[1]
    wgp, _ = pad_to(wgm, 0, Hp)
    wgp, _ = pad_to(wgp, 1, bf)
    wup, _ = pad_to(wum, 0, Hp)
    wup, _ = pad_to(wup, 1, bf)
    Tp, Fp = xp.shape[0], wgp.shape[1]
    out = pl.pallas_call(
        functools.partial(_glu_kernel, activation=activation),
        grid=(Tp // bt, Fp // bf),
        in_specs=[
            pl.BlockSpec((bt, Hp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Hp, bf), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Hp, bf), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bt, bf), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((Tp, Fp), xm.dtype, xm, wgm, wum),
        interpret=interpret_mode(),
    )(xp, wgp, wup)
    return out[:T, :F].astype(x2.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _glu_fused(x2, wg, wu, activation, bt, bf):
    return _glu_fwd(x2, wg, wu, activation, bt, bf)[0]


def _glu_fwd(x2, wg, wu, activation, bt, bf):
    return _glu_call(x2, wg, wu, activation, bt, bf), (x2, wg, wu)


def _glu_bwd(activation, bt, bf, res, dy):
    # Recompute-in-VJP: the fp32 gate/up activations are never saved —
    # the residuals are just the operands (the Liger/chunked-loss play).
    x2, wg, wu = res
    act = _GLU_ACTS[activation]
    xf = x2.astype(jnp.float32)
    wgf = wg.astype(jnp.float32)
    wuf = wu.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    g = xf @ wgf
    u = xf @ wuf
    a, act_vjp = jax.vjp(act, g)
    du = dyf * a
    dg = act_vjp(dyf * u)[0]
    dx = dg @ wgf.T + du @ wuf.T
    return (dx.astype(x2.dtype), (xf.T @ dg).astype(wg.dtype),
            (xf.T @ du).astype(wu.dtype))


_glu_fused.defvjp(_glu_fwd, _glu_bwd)


def fused_glu(x, w_gate, w_up, *, activation: str = "silu",
              block_t: int | None = None, block_f: int | None = None):
    """``act(x @ w_gate) * (x @ w_up)`` in one pass over ``x``.

    ``x`` (..., H); ``w_gate``/``w_up`` (H, F) — the (in, out) layout
    `models/llama.py` stores (NOT the torch (out, in) of `fused_dense`).
    ``activation``: "silu" (SwiGLU) | "gelu" (GeGLU, tanh approximation).
    Returns (..., F) in ``x.dtype``; the down projection stays an
    ordinary XLA matmul (a lone GEMM is exactly what the module
    docstring says not to hand-write).

    The XLA path is token-for-token the inline llama expression, so the
    `LlamaConfig.fused_mlp` flag is bitwise-neutral off-TPU; the Pallas
    path computes fp32 tiles with an XLA-identical reduction order.
    Differentiable via a recompute VJP (gate/up activations never saved).
    """
    if activation not in _GLU_ACTS:
        raise ValueError(f"fused_glu: activation must be one of "
                         f"{sorted(_GLU_ACTS)}, got {activation!r}")
    act = _GLU_ACTS[activation]
    if not use_pallas():
        return (act(x @ w_gate) * (x @ w_up)).astype(x.dtype)
    lead = x.shape[:-1]
    H = x.shape[-1]
    x2 = x.reshape(-1, H)
    bt, bf = _auto_glu_blocks(x2.shape[0], w_gate.shape[1], H,
                              block_t, block_f, x.dtype)
    out = _glu_fused(x2, w_gate, w_up, activation, bt, bf)
    return out.reshape(*lead, w_gate.shape[1])


class MLP(nn.Module):
    """``apex.mlp.MLP(mlp_sizes, bias=True, relu=True)`` equivalent.

    A stack of Linear(+bias)(+activation) layers evaluated as one traced
    region — the reference fuses the chain into one autograd node
    (``MlpFunction``) over cuBLAS calls; here the whole chain is one XLA
    fusion domain. ``activation``: "none" | "relu" | "sigmoid" (reference
    flags). No activation after the final layer, matching the reference.
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"

    @nn.compact
    def __call__(self, x):
        if len(self.mlp_sizes) < 2:
            raise ValueError("mlp_sizes needs >= 2 entries")
        act = _ACTIVATIONS[self.activation]
        k = nn.initializers.lecun_normal()
        h = x
        for i, (fan_in, fan_out) in enumerate(
                zip(self.mlp_sizes[:-1], self.mlp_sizes[1:])):
            w = self.param(f"weight_{i}", k, (fan_out, fan_in),
                           jnp.float32)
            b = (self.param(f"bias_{i}", nn.initializers.zeros,
                            (fan_out,), jnp.float32)
                 if self.bias else None)
            h = fused_dense(h, w.astype(h.dtype),
                            None if b is None else b.astype(h.dtype))
            if act is not None and i < len(self.mlp_sizes) - 2:
                h = act(h)
        return h
