"""Fused dense layers — reference ``apex/fused_dense/fused_dense.py ::
FusedDense, FusedDenseGeluDense`` (+ ``csrc/fused_dense*.cu``) and
``apex/mlp/mlp.py :: MLP`` (+ ``csrc/mlp*.cu``).

**Documented "XLA already fuses this" decision (SURVEY.md §7.0):** the
reference needs cuBLASLt epilogue fusion (``CUBLASLT_EPILOGUE_{BIAS,
GELU_AUX_BIAS,DGELU_BGRAD}``) and a bespoke GEMM-chain kernel because eager
torch launches matmul/bias/activation as separate kernels. Under XLA the
matmul lands on the MXU and the bias/GELU/ReLU epilogues are fused into its
output stage by the compiler — a hand-written Pallas GEMM would have to beat
XLA's own matmul emitter to win, which is expected not to happen for plain
dense shapes. The confirming roofline A/B (``tools/bench_kernels.py dense``,
achieved-TFLOPs vs MXU peak) is queued in the hardware revival queue and has
NOT yet run (docs/perf_playbook.md §2) — the decision currently rests on the
architecture argument plus AOT lowering checks, not a measurement. So these
are thin modules with the reference's API over ``jnp`` compute, with fp32
MXU accumulation (``preferred_element_type``) matching the reference's
fp16-in/fp32-accumulate GEMMs. The backward (dgelu+bgrad, wgrad chain) is
jax AD, which XLA fuses the same way.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def fused_dense(x, weight, bias=None):
    """y = x @ Wᵀ + b. ``weight`` is (out, in) — torch convention, like the
    reference's ``FusedDenseFunc``."""
    y = jnp.matmul(x, weight.T, preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias
    return y.astype(x.dtype)


def fused_dense_gelu_dense(x, w1, b1, w2, b2):
    """Linear+bias+GELU+Linear+bias in one traced region (reference
    ``FusedDenseGeluDenseFunc``); XLA fuses the epilogues."""
    h = fused_dense(x, w1, b1)
    h = jax.nn.gelu(h, approximate=True)
    return fused_dense(h, w2, b2)


class FusedDense(nn.Module):
    """``apex.fused_dense.FusedDense(in_features, out_features, bias)``."""

    in_features: int
    out_features: int
    bias: bool = True

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features), jnp.float32)
        b = (self.param("bias", nn.initializers.zeros,
                        (self.out_features,), jnp.float32)
             if self.bias else None)
        return fused_dense(x, w.astype(x.dtype),
                           None if b is None else b.astype(x.dtype))


class FusedDenseGeluDense(nn.Module):
    """``apex.fused_dense.FusedDenseGeluDense(in, intermediate, out)``."""

    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True

    @nn.compact
    def __call__(self, x):
        k = nn.initializers.lecun_normal()
        w1 = self.param("weight1", k, (self.intermediate_features,
                                       self.in_features), jnp.float32)
        w2 = self.param("weight2", k, (self.out_features,
                                       self.intermediate_features),
                        jnp.float32)
        b1 = b2 = None
        if self.bias:
            b1 = self.param("bias1", nn.initializers.zeros,
                            (self.intermediate_features,), jnp.float32)
            b2 = self.param("bias2", nn.initializers.zeros,
                            (self.out_features,), jnp.float32)
        cast = lambda t: None if t is None else t.astype(x.dtype)
        return fused_dense_gelu_dense(x, cast(w1), cast(b1), cast(w2),
                                      cast(b2))


_ACTIVATIONS: dict[str, Optional[Callable]] = {
    "none": None,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


class MLP(nn.Module):
    """``apex.mlp.MLP(mlp_sizes, bias=True, relu=True)`` equivalent.

    A stack of Linear(+bias)(+activation) layers evaluated as one traced
    region — the reference fuses the chain into one autograd node
    (``MlpFunction``) over cuBLAS calls; here the whole chain is one XLA
    fusion domain. ``activation``: "none" | "relu" | "sigmoid" (reference
    flags). No activation after the final layer, matching the reference.
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"

    @nn.compact
    def __call__(self, x):
        if len(self.mlp_sizes) < 2:
            raise ValueError("mlp_sizes needs >= 2 entries")
        act = _ACTIVATIONS[self.activation]
        k = nn.initializers.lecun_normal()
        h = x
        for i, (fan_in, fan_out) in enumerate(
                zip(self.mlp_sizes[:-1], self.mlp_sizes[1:])):
            w = self.param(f"weight_{i}", k, (fan_out, fan_in),
                           jnp.float32)
            b = (self.param(f"bias_{i}", nn.initializers.zeros,
                            (fan_out,), jnp.float32)
                 if self.bias else None)
            h = fused_dense(h, w.astype(h.dtype),
                            None if b is None else b.astype(h.dtype))
            if act is not None and i < len(self.mlp_sizes) - 2:
                h = act(h)
        return h
