"""Fused computation-collective Pallas forms — ROADMAP item 3.

PR 4 overlapped the Megatron-SP boundary collectives at the XLA schedule
level (`transformer.tensor_parallel.mappings.all_gather_matmul` /
`matmul_reduce_scatter`: chunk-pipelined ppermute rings whose transfers
have no data dependence into the per-chunk dots). The collective still
runs *beside* the compute, bounded by what the scheduler will overlap.
This module moves the boundary INTO the kernels (arxiv 2305.06942's
fused computation-collective operations; the epilogue-fusion playbook of
2502.17728), in three forms:

- **`fused_matmul_reduce_scatter` / `fused_all_gather_matmul`** — the SP
  boundary matmuls with the per-chunk dot running in a Pallas kernel
  (`_chunk_matmul`) instead of an XLA dot. The ring schedule and the
  travelling-accumulator adds are bit-for-bit PR 4's (same hops, same
  add order — the carry-add must precede the hop it feeds, so it stays
  an XLA op on purpose; see the dataflow note below), which is what
  makes the fused forms bitwise-pinnable against their decomposed
  counterparts on the CPU mesh. The kernel is the execution-tested tile
  loop that the RDMA form below extends.
- **`fused_matmul_reduce_scatter(..., impl="rdma")`** — the paper-shape
  kernel: ONE `pallas_call` whose grid walks the ring steps, computing
  the partial dot for chunk t+1 while the epilogue's
  `make_async_remote_copy` ships the travelling fp32 accumulator for
  chunk t to the downstream neighbor. No XLA collective exists in the
  program at all. Compiled-TPU only (inter-chip DMA has no interpret
  lowering on this jax); numerics are gated by the AOT Mosaic compile
  (`tools/aot_check.py`) and UNVERIFIED on silicon until the next
  hardware window — opt-in, never the default.
- **`all_gather_flash_attention`** — ring/context attention where the
  partial-result MERGE rides the flash kernel's final-key-block epilogue
  instead of a per-step XLA read-modify-write of the (B, H, S, D) output
  (`_agf_kernel`: the standard flash forward extended with carried
  (out, lse) operands). The K/V gather hops keep PR 4's double-buffered
  schedule (probe-pinned); the backward reuses
  `parallel.ring_attention`'s inverted-permutation ring. Bitwise equal
  to `ring_attention` on the CPU mesh by construction (same attend math,
  same merge formula, same order).
- **`fused_vocab_parallel_merge`** — the vocab-parallel `linear_xent`
  cross-shard merge with the per-shard stats PACKED into one kernel
  output by the final vocab tile (`ops.linear_xent.shard_stats_packed`)
  and the pmax/psum ladder collapsed from four collectives to two (one
  pmax + ONE packed psum). Bitwise equal to the decomposed
  `_vp_merge` path (packed psum reduces each lane independently).

**Dataflow note (why the travelling-accumulator add is NOT in the
kernel on the ppermute path):** the reduce-scatter hop at step t ships
``acc_t + pend_t`` where ``acc_t`` arrives from step t−1's hop. Any
schedule that hops a kernel-produced sum one step late pairs a stale
accumulator with a fresh partial and sums the wrong chunks (verified by
simulation); computing the sum inside the step's dot kernel would make
the hop wait on the whole kernel. The add therefore stays a carry-only
XLA add at the body top — PR 4's form, whose overlap hlo_probe pins —
and the add-in-epilogue design is exactly what the RDMA kernel is for
(inside one kernel the grid sequencing, not the XLA scheduler, provides
the overlap).

Every executable form here keeps a bitwise-parity pin against its
decomposed PR 4 counterpart on the CPU mesh (interpret AND
XLA-composite paths, `tests/test_fused_collective.py`), a dependence-
mode `testing.hlo_probe` pin in tier-1, and an async-mode probe +
Mosaic-lowering gate in `tools/aot_check.py`. `tools/bench_fused_comm.py`
is the wall-clock A/B (queued as ``fused_comm_ab`` in tpu_watch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.core.mesh import AXIS_TP
from apex1_tpu.ops._common import (NEG_INF, interpret_mode, out_struct,
                                    pad_to, to_mosaic, use_pallas)
from apex1_tpu.ops._common import vary as _vary

_LANES = 128


def _axis_size(axis_name):
    return jax.lax.axis_size(axis_name)


def _axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def _chunk(x, seq_dim, start, size):
    return jax.lax.dynamic_slice_in_dim(x, start, size, axis=seq_dim)


# ---------------------------------------------------------------------------
# chunk matmul kernel — the tile loop shared by the ppermute ring forms
# and (as its grid body) the RDMA kernel
# ---------------------------------------------------------------------------

def _cm_whole_kernel(x_ref, w_ref, o_ref):
    # ONE dot over the full operands with jnp.dot's dimension numbers:
    # in interpret mode this is literally the same dot_general the
    # decomposed loop's jnp.dot lowers to — the bitwise-parity anchor
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[...],
        (((x_ref.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _cm_tile_kernel(x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _cm_blocks(Kp, block_m, block_n, dtype):
    """(block_m, block_n) for the tiled chunk matmul: explicit > tuning
    table (`fused_collective_matmul`, keyed on the padded depth Kp) >
    heuristic (256 x 512, halved while the registry VMEM model says the
    frame exceeds the generation's budget)."""
    if block_m is not None and block_n is not None:
        return block_m, block_n
    from apex1_tpu import tuning
    tuned = tuning.lookup("fused_collective_matmul", {"Kp": Kp},
                          dtype) or {}
    bm = block_m or tuned.get("block_m")
    bn = block_n or tuned.get("block_n")
    if bm is None or bn is None:
        from apex1_tpu.core.capability import vmem_budget
        from apex1_tpu.tuning.registry import SPECS
        cand_m, cand_n = bm or 256, bn or 512
        es = np.dtype(dtype).itemsize
        check = SPECS["fused_collective_matmul"].check
        while cand_m > 16:
            ok, _ = check({"block_m": cand_m, "block_n": cand_n},
                          {"Kp": Kp}, es, vmem_budget())
            if ok:
                break
            cand_m, cand_n = max(16, cand_m // 2), max(128, cand_n // 2)
        bm, bn = cand_m, cand_n
    return bm, bn


def _chunk_matmul(rows, w, block_m=None, block_n=None):
    """``rows @ w`` (fp32 accumulate/result) as a Pallas kernel.

    ``rows`` (..., K), ``w`` (K, N). With unresolved blocks in interpret
    mode the kernel is ONE whole-operand tile whose dot_general is
    bit-identical to ``jnp.dot(rows, w, preferred_element_type=f32)`` —
    the anchor for the fused-vs-decomposed bitwise pins. The compiled
    path (and interpret with explicit blocks, for grid-logic tests)
    tiles (M, N) with K untiled, so each output tile is one MXU dot and
    no cross-grid accumulation is needed.
    """
    if interpret_mode() and block_m is None and block_n is None:
        out_shape = rows.shape[:-1] + (w.shape[-1],)
        return pl.pallas_call(
            _cm_whole_kernel,
            out_shape=out_struct(out_shape, jnp.float32, rows, w),
            interpret=True,
        )(rows, w)
    rows, w = to_mosaic(rows, w)
    lead = rows.shape[:-1]
    K = rows.shape[-1]
    N = w.shape[-1]
    x2 = rows.reshape(-1, K)
    Kp = max(_LANES, ((K + _LANES - 1) // _LANES) * _LANES)
    bm, bn = _cm_blocks(Kp, block_m, block_n, rows.dtype)
    bm = min(bm, max(16, ((x2.shape[0] + 15) // 16) * 16))
    bn = min(bn, max(_LANES, ((N + _LANES - 1) // _LANES) * _LANES))
    xp, _ = pad_to(x2, 0, bm)
    xp, _ = pad_to(xp, 1, _LANES)
    wp, _ = pad_to(w, 0, _LANES)
    wp, _ = pad_to(wp, 1, bn)
    n_m, n_n = xp.shape[0] // bm, wp.shape[1] // bn
    out = pl.pallas_call(
        _cm_tile_kernel,
        grid=(n_m, n_n),
        in_specs=[pl.BlockSpec((bm, xp.shape[1]), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((wp.shape[0], bn), lambda i, j: (0, j),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((xp.shape[0], wp.shape[1]), jnp.float32,
                             xp, wp),
        interpret=interpret_mode(),
    )(xp, wp)
    return out[:x2.shape[0], :N].reshape(lead + (N,))


def _part_dot(rows, w, block_m, block_n):
    """One chunk partial product: the Pallas chunk kernel on the Pallas
    path, the decomposed loop's own jnp.dot on the XLA path — both fp32."""
    if use_pallas():
        return _chunk_matmul(rows, w, block_m, block_n)
    return jnp.dot(rows, w, preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# fused matmul -> reduce-scatter (ppermute ring form)
# ---------------------------------------------------------------------------

def _fused_mrs_loop(x, w, axis_name, seq_dim, block_m, block_n):
    """PR 4's `mappings._mrs_loop` dataflow with the per-chunk dot in the
    Pallas chunk kernel: hop ships ``acc + pend`` (both carries, add at
    body top — see the module dataflow note), the kernel's dot lands in
    the carry untouched, n hops total (one zero-valued seed hop). Chunk
    summation order is identical to the decomposed form, so the result
    is bitwise the same wherever the kernel's dot is (interpret mode /
    the XLA path)."""
    n = _axis_size(axis_name)
    S = x.shape[seq_dim]
    if S % n:
        raise ValueError(f"seq dim {seq_dim} size {S} not divisible by "
                         f"ring size {n}")
    chunk = S // n

    def part(c):
        return _part_dot(_chunk(x, seq_dim, c * chunk, chunk), w,
                         block_m, block_n)

    if n == 1:
        return part(0)
    idx = _axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    shape = list(x.shape)
    shape[seq_dim] = chunk
    shape[-1] = w.shape[-1]
    acc = _vary(jnp.zeros(tuple(shape), jnp.float32), axis_name)
    pend = _vary(jnp.zeros(tuple(shape), jnp.float32), axis_name)

    def step(carry, t):
        acc, pend = carry
        acc = jax.lax.ppermute(acc + pend, axis_name, perm)
        pend = part((idx - 1 - t) % n)
        return (acc, pend), None

    (acc, pend), _ = jax.lax.scan(step, (acc, pend), jnp.arange(0, n))
    return acc + pend


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def fused_matmul_reduce_scatter(x, w, axis_name=AXIS_TP, seq_dim=0,
                                block_m=None, block_n=None):
    """``psum_scatter(x @ w, seq_dim)`` with the reduce-scatter
    decomposed into the PR 4 travelling-accumulator ppermute ring and
    the per-chunk dot fused into a Pallas kernel (`_chunk_matmul`).

    Bitwise equal to `mappings.matmul_reduce_scatter` on the CPU mesh
    (both dispatch paths); the custom VJP routes dx through
    `fused_all_gather_matmul` (the all-gather dual). Returns this rank's
    sequence chunk in fp32, like the decomposed form. For the
    single-kernel RDMA form see `matmul_reduce_scatter_rdma`.
    """
    return _fused_mrs_loop(x, w, axis_name, seq_dim, block_m, block_n)


def _fused_mrs_fwd(x, w, axis_name, seq_dim, block_m, block_n):
    return _fused_mrs_loop(x, w, axis_name, seq_dim, block_m,
                           block_n), (x, w)


def _fused_mrs_bwd(axis_name, seq_dim, block_m, block_n, res, g):
    x, w = res
    # dx through the all-gather dual (overlapped, fused); dw contracts
    # the re-gathered cotangent — the same shape as the decomposed VJP
    dx = fused_all_gather_matmul(g, jnp.swapaxes(w, 0, 1), axis_name,
                                 seq_dim, block_m, block_n)
    gg = jax.lax.all_gather(g, axis_name, axis=seq_dim, tiled=True)
    dw = jnp.matmul(x.reshape(-1, x.shape[-1]).T,
                    gg.reshape(-1, gg.shape[-1]),
                    preferred_element_type=jnp.float32)
    return dx.astype(x.dtype), dw.astype(w.dtype)


fused_matmul_reduce_scatter.defvjp(_fused_mrs_fwd, _fused_mrs_bwd)


# ---------------------------------------------------------------------------
# fused all-gather -> matmul (ppermute ring form) + its serialized
# negative control
# ---------------------------------------------------------------------------

def _fused_agm_loop(x, w, axis_name, seq_dim, block_m, block_n,
                    serialize=False):
    """PR 4's `mappings._agm_loop` with the per-chunk dot in the Pallas
    chunk kernel; prologue + n−2 in-loop hops, each issued before the
    dot that overlaps it. ``serialize=True`` is the rotate-THEN-dot
    schedule (the dot consumes this step's permute) — the falsifiable
    negative control for the overlap probes and the A/B baseline."""
    n = _axis_size(axis_name)
    chunk = x.shape[seq_dim]

    def dot(c):
        return _part_dot(c, w, block_m, block_n)

    if n == 1:
        return dot(x)
    idx = _axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out_shape = list(x.shape)
    out_shape[seq_dim] = chunk * n
    out_shape[-1] = w.shape[-1]
    y = _vary(jnp.zeros(tuple(out_shape), jnp.float32), axis_name)

    def place(y, part, src):
        return jax.lax.dynamic_update_slice_in_dim(
            y, part, src * chunk, axis=seq_dim)

    if serialize:
        y = place(y, dot(x), idx)

        def sstep(carry, t):
            cur, y = carry
            cur = jax.lax.ppermute(cur, axis_name, perm)
            y = place(y, dot(cur), (idx - t) % n)
            return (cur, y), None

        (_, y), _ = jax.lax.scan(sstep, (x, y), jnp.arange(1, n))
        return y

    cur = jax.lax.ppermute(x, axis_name, perm)
    y = place(y, dot(x), idx)

    def step(carry, t):
        cur, y = carry
        nxt = jax.lax.ppermute(cur, axis_name, perm)
        y = place(y, dot(cur), (idx - t) % n)
        return (nxt, y), None

    if n > 2:
        (cur, y), _ = jax.lax.scan(step, (cur, y), jnp.arange(1, n - 1))
    return place(y, dot(cur), (idx - (n - 1)) % n)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def fused_all_gather_matmul(x, w, axis_name=AXIS_TP, seq_dim=0,
                            block_m=None, block_n=None):
    """``all_gather(x, seq_dim) @ w`` over the PR 4 chunk-pipelined
    ppermute ring with the per-chunk dot fused into a Pallas kernel.
    Bitwise equal to `mappings.all_gather_matmul` on the CPU mesh; the
    custom VJP routes dx through `fused_matmul_reduce_scatter` (its
    reduce-scatter dual). fp32 result."""
    return _fused_agm_loop(x, w, axis_name, seq_dim, block_m, block_n)


def _fused_agm_fwd(x, w, axis_name, seq_dim, block_m, block_n):
    return _fused_agm_loop(x, w, axis_name, seq_dim, block_m,
                           block_n), (x, w)


def _fused_agm_bwd(axis_name, seq_dim, block_m, block_n, res, g):
    x, w = res
    dx = fused_matmul_reduce_scatter(g, jnp.swapaxes(w, 0, 1), axis_name,
                                     seq_dim, block_m, block_n)
    gx = jax.lax.all_gather(x, axis_name, axis=seq_dim, tiled=True)
    dw = jnp.matmul(gx.reshape(-1, gx.shape[-1]).T,
                    g.reshape(-1, g.shape[-1]),
                    preferred_element_type=jnp.float32)
    return dx.astype(x.dtype), dw.astype(w.dtype)


fused_all_gather_matmul.defvjp(_fused_agm_fwd, _fused_agm_bwd)


def fused_all_gather_matmul_serial(x, w, axis_name=AXIS_TP, seq_dim=0,
                                   block_m=None, block_n=None):
    """Serialized rotate-then-dot all-gather matmul: every chunk dot
    consumes the permute issued in the same step, so ALL n−1 transfers
    are exposed. Retained as the falsifiable negative control for the
    overlap probes (dependence mode in tier-1, async mode in the AOT
    gate) and as the A/B floor in tools/bench_fused_comm.py. Numerics
    match the overlapped form (same dots, same placement order)."""
    return _fused_agm_loop(x, w, axis_name, seq_dim, block_m, block_n,
                           serialize=True)


# ---------------------------------------------------------------------------
# all-gather-fused flash attention: the ring merge rides the kernel's
# final-key-block epilogue
# ---------------------------------------------------------------------------

def _agf_kernel(q_ref, k_ref, v_ref, qo_ref, ko_ref, *rest,
                scale, causal, true_sq, true_sk, has_segs, n_k):
    """`ops.attention._fwd_kernel`'s exact compute (no bias/dropout
    operands) extended with carried (prev_out fp32, prev_lse) inputs:
    the final key block's epilogue performs `parallel.ring_attention.
    _merge` in VMEM instead of a per-ring-step XLA read-modify-write of
    the full (B, H, S, D) output in HBM. The attend math and the merge
    formula replicate their decomposed counterparts op for op — the
    bitwise-parity contract of the fused form."""
    rest = list(rest)
    if has_segs:
        qseg_ref, kseg_ref = rest[0], rest[1]
        rest = rest[2:]
        qseg, kseg = qseg_ref[0], kseg_ref[0]
    else:
        qseg = kseg = None
    po_ref, pl_ref, o_ref, lse_ref, acc, m_scr, l_scr = rest
    qi, ki = pl.program_id(2), pl.program_id(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(ki == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    def compute():
        from apex1_tpu.ops.attention import _mask_for
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_for(qi, ki, bq, bk, causal=causal, true_sq=true_sq,
                         true_sk=true_sk, q_off=qo_ref[0, 0],
                         k_off=ko_ref[0, 0], qseg=qseg, kseg=kseg)
        s = jnp.where(mask, s, NEG_INF)
        m_prev, l_prev = m_scr[:, :1], l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        e = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_new = l_prev * corr + jnp.sum(e, axis=1, keepdims=True)
        v = v_ref[0, 0]
        acc[...] = acc[...] * corr + jax.lax.dot_general(
            e.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        pl.when((ki * bk + ko_ref[0, 0])
                <= (qi * bq + bq - 1 + qo_ref[0, 0]))(compute)
    else:
        compute()

    @pl.when(ki == n_k - 1)
    def _():
        # this shard's (out_t, lse_t) exactly as the plain flash kernel
        # emits them (incl. the q.dtype round-trip the decomposed ring's
        # flash output makes), then `_merge` op for op
        l = l_scr[:, :1]
        safe = jnp.where(l > 0.0, l, 1.0)
        o_t = (acc[...] / safe).astype(q_ref.dtype)
        lse_t = jnp.where(l > 0.0, m_scr[:, :1] + jnp.log(safe), NEG_INF)
        prev_lse = pl_ref[0, 0]
        lse_new = jnp.logaddexp(prev_lse, lse_t)
        w_a = jnp.exp(prev_lse - lse_new)
        w_b = jnp.exp(lse_t - lse_new)
        o_ref[0, 0] = po_ref[0, 0] * w_a + o_t.astype(jnp.float32) * w_b
        lse_ref[0, 0] = lse_new


def _agf_blocks(D, block_q, block_k, dtype, seq):
    """explicit > tuning table (`fused_ag_flash`) > the flash-attention
    resolution chain (its table, then the analytic heuristic)."""
    from apex1_tpu import tuning
    from apex1_tpu.ops.attention import _auto_blocks
    Dp = max(_LANES, ((D + _LANES - 1) // _LANES) * _LANES)
    if block_q is None or block_k is None:
        tuned = tuning.lookup("fused_ag_flash",
                              {"Dp": Dp, "Sb": tuning.seq_bucket(seq)},
                              dtype) or {}
        block_q = block_q or tuned.get("block_q")
        block_k = block_k or tuned.get("block_k")
    return _auto_blocks(D, block_q, block_k, dtype, seq)


def _agf_call(q, k, v, qseg, kseg, q_off, k_off, prev_out, prev_lse,
              scale, causal, has_segs, block_q, block_k):
    """One ring step: attend the visiting K/V shard AND fold the result
    into the carried (out, lse) — one pallas_call."""
    from apex1_tpu.ops.attention import (_common_specs, _off_arrays,
                                         _prep)
    q, k, v = to_mosaic(q, k, v)
    qp, kp, vp, qs, ks, g = _prep(q, k, v, qseg, kseg, has_segs,
                                  block_q, block_k)
    q_spec, kv_spec, stat_spec, off_spec, qseg_spec, kseg_spec = \
        _common_specs(g)
    po, _ = pad_to(prev_out, 2, g["bq"])
    po, _ = pad_to(po, 3, _LANES)
    plse, _ = pad_to(prev_lse[..., None], 2, g["bq"], value=NEG_INF)
    pout_spec = pl.BlockSpec((1, 1, g["bq"], g["Dp"]),
                             lambda b, h, qi, ki: (b, h, qi, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec, off_spec, off_spec]
    args = [qp, kp, vp, *_off_arrays(q_off, k_off)]
    if has_segs:
        in_specs += [qseg_spec, kseg_spec]
        args += [qs, ks]
    in_specs += [pout_spec, stat_spec]
    args += [po, plse]
    Sqp = g["n_q"] * g["bq"]
    out_p, lse_p = pl.pallas_call(
        functools.partial(_agf_kernel, scale=scale, causal=causal,
                          true_sq=g["Sq"], true_sk=g["Sk"],
                          has_segs=has_segs, n_k=g["n_k"]),
        grid=(g["B"], g["Hq"], g["n_q"], g["n_k"]),
        in_specs=in_specs,
        out_specs=(pout_spec, stat_spec),
        out_shape=(
            out_struct((g["B"], g["Hq"], Sqp, g["Dp"]), jnp.float32,
                       qp, kp, vp, po, plse),
            out_struct((g["B"], g["Hq"], Sqp, 1), jnp.float32,
                       qp, kp, vp, po, plse)),
        scratch_shapes=[
            pltpu.VMEM((g["bq"], g["Dp"]), jnp.float32),
            pltpu.VMEM((g["bq"], _LANES), jnp.float32),
            pltpu.VMEM((g["bq"], _LANES), jnp.float32)],
        interpret=interpret_mode(),
    )(*args)
    return (out_p[:, :, :g["Sq"], :g["D"]], lse_p[:, :, :g["Sq"], 0])


def _agf_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale, has_segs,
                  block_q, block_k):
    """Double-buffered K/V gather ring (PR 4's hop-before-attend
    schedule, hlo_probe-pinned) with the per-step merge fused into the
    flash kernel epilogue. Off the Pallas path this IS the decomposed
    ring (`parallel.ring_attention._ring_fwd_loop`) — bitwise by
    construction. Returns (out fp32, lse)."""
    from apex1_tpu.parallel.ring_attention import (_merge,
                                                   _ring_fwd_loop)
    if not use_pallas():
        return _ring_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale,
                              has_segs, block_q, block_k)
    n = _axis_size(axis_name)
    B, Hq, Sq, D = q.shape
    Sk = k.shape[2]
    scale = (1.0 / float(np.sqrt(D)) if sm_scale is None
             else float(sm_scale))
    block_q, block_k = _agf_blocks(D, block_q, block_k, q.dtype, Sk)
    if causal:
        idx = _axis_index(axis_name)
        q_off = idx * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = _vary(jnp.zeros(q.shape, jnp.promote_types(q.dtype,
                                                     jnp.float32)),
                axis_name)
    lse = _vary(jnp.full((B, Hq, Sq), NEG_INF, jnp.float32), axis_name)

    def attend(k_cur, v_cur, kseg_cur, t, out, lse):
        if causal:
            src = (idx - t) % n
            qo, ko = q_off, src * Sk
        else:
            qo = ko = 0

        def run(_):
            return _agf_call(q, k_cur, v_cur, qseg,
                             kseg_cur if has_segs else None, qo, ko,
                             out, lse, scale, causal, has_segs,
                             block_q, block_k)

        def skip(_):
            # the decomposed ring merges a (zeros, NEG_INF) partial for
            # fully-masked shards; replicate that exact merge (identity
            # up to fp edge cases like -0 + 0) instead of passing the
            # carry through, so the pin stays bitwise
            return _merge(out, lse,
                          _vary(jnp.zeros(q.shape, q.dtype), axis_name),
                          _vary(jnp.full((B, Hq, Sq), NEG_INF,
                                         jnp.float32), axis_name))

        if causal:
            return jax.lax.cond(ko > qo + Sq - 1, skip, run, None)
        return run(None)

    kseg0 = qseg if has_segs else jnp.zeros((), jnp.int32)
    if n == 1:
        return attend(k, v, kseg0, 0, out, lse)

    k_cur = jax.lax.ppermute(k, axis_name, perm)
    v_cur = jax.lax.ppermute(v, axis_name, perm)
    kseg_cur = (jax.lax.ppermute(kseg0, axis_name, perm) if has_segs
                else kseg0)
    out, lse = attend(k, v, kseg0, 0, out, lse)

    def step(carry, t):
        k_cur, v_cur, kseg_cur, out, lse = carry
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        kseg_nxt = (jax.lax.ppermute(kseg_cur, axis_name, perm)
                    if has_segs else kseg_cur)
        out, lse = attend(k_cur, v_cur, kseg_cur, t, out, lse)
        return (k_nxt, v_nxt, kseg_nxt, out, lse), None

    if n > 2:
        (k_cur, v_cur, kseg_cur, out, lse), _ = jax.lax.scan(
            step, (k_cur, v_cur, kseg_cur, out, lse), jnp.arange(1, n - 1))
    return attend(k_cur, v_cur, kseg_cur, n - 1, out, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _agf(q, k, v, qseg, axis_name, causal, sm_scale, has_segs, block_q,
         block_k):
    out, _ = _agf_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale,
                           has_segs, block_q, block_k)
    return out.astype(q.dtype)


def _agf_fwd_rule(q, k, v, qseg, axis_name, causal, sm_scale, has_segs,
                  block_q, block_k):
    out, lse = _agf_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale,
                             has_segs, block_q, block_k)
    out = out.astype(q.dtype)
    return out, (q, k, v, qseg, out, lse)


def _agf_bwd_rule(axis_name, causal, sm_scale, has_segs, block_q,
                  block_k, res, do):
    # the inverted-permutation double-buffered ring backward of PR 4,
    # unchanged: the fused forward saves the same (out, lse) residuals
    from apex1_tpu.parallel.ring_attention import _ring_bwd_loop
    q, k, v, qseg, out, lse = res
    dq, dk, dv = _ring_bwd_loop(q, k, v, qseg, out, lse, do, axis_name,
                                causal, sm_scale, has_segs, block_q,
                                block_k)
    f0 = np.zeros(jnp.shape(qseg), dtype=jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            f0)


_agf.defvjp(_agf_fwd_rule, _agf_bwd_rule)


def all_gather_flash_attention(q, k, v, axis_name, *,
                               causal: bool = False,
                               sm_scale: float | None = None,
                               segment_ids=None,
                               block_q: int | None = None,
                               block_k: int | None = None):
    """Ring/context flash attention with the K/V all-gather riding the
    kernel schedule: each ring step's shard hop is issued before the
    attend (PR 4's double-buffered schedule, hlo_probe-pinned) and the
    partial-result merge runs in the flash kernel's final-key-block
    epilogue instead of a per-step XLA read-modify-write of the full
    (B, H, S, D) output in HBM — at the 16k GQA shape that epilogue
    fusion removes n−1 full passes over the output per layer.

    Semantics (and, on the CPU mesh, bits) match
    `parallel.ring_attention`: ``q``/``k``/``v`` are local sequence
    shards over ``axis_name``; returns the local output shard. The
    backward is the same inverted-permutation ring as PR 4's custom
    VJP. Attention-probability dropout is NOT supported on this entry —
    use `parallel.ring_attention` for dropout-bearing training paths.
    """
    sm_scale = None if sm_scale is None else float(sm_scale)
    has_segs = segment_ids is not None
    qseg = (segment_ids if has_segs else jnp.zeros((1, 1), jnp.int32))
    return _agf(q, k, v, qseg, axis_name, causal, sm_scale, has_segs,
                block_q, block_k)


# ---------------------------------------------------------------------------
# vocab-parallel linear_xent merge: packed stats, two collectives
# ---------------------------------------------------------------------------

def fused_vocab_parallel_merge(stats, axis_name=AXIS_TP):
    """Cross-shard merge of PACKED per-shard online-softmax stats
    (``ops.linear_xent.shard_stats_packed``'s (T, 4) ``[m, l, tgt,
    sumx]``, emitted by the kernel's final vocab tile in one output
    stream instead of four): ONE pmax for the global max, then ONE psum
    of the (T, 3) pack ``[l·exp(m − gmax), tgt, sumx]`` — two
    collective rendezvous where the decomposed `_vp_merge` ladder pays
    four. Bitwise equal to the decomposed merge: an all-reduce sums
    each lane independently, so packing changes neither the reduction
    order nor a single bit (pinned by test_fused_collective +
    the hlo_probe collective-count check). Returns (lse, tgt, sumx)."""
    m = stats[:, 0]
    gmax = jax.lax.pmax(m, axis_name)
    packed = jnp.stack([stats[:, 1] * jnp.exp(m - gmax),
                        stats[:, 2], stats[:, 3]], axis=-1)
    red = jax.lax.psum(packed, axis_name)
    return gmax + jnp.log(red[:, 0]), red[:, 1], red[:, 2]


# ---------------------------------------------------------------------------
# the paper-shape form: matmul -> reduce-scatter in ONE kernel, the
# epilogue shipping chunk t over ICI while the grid computes chunk t+1
# ---------------------------------------------------------------------------

_RDMA_COLLECTIVE_ID = 7  # arbitrary but stable; one fused collective
                         # kernel shape runs at a time in our programs


def _mrs_rdma_kernel(cs_ref, x_ref, w_ref, o_ref, acc_buf, send_buf,
                     send_sem, recv_sem, cap_sem, *, n, axis_name):
    """Reduce-scatter-in-the-matmul-epilogue (arxiv 2305.06942): grid
    step t computes this device's partial for chunk ``cs[t]`` on the
    MXU, folds in the travelling fp32 accumulator that arrived from the
    upstream neighbor during step t−1, and ships the sum downstream
    with `make_async_remote_copy` — the RDMA flies while grid step t+1's
    dot runs. Double-buffered recv/send slots with a credit semaphore
    (the downstream consumer returns a credit as it drains a slot) keep
    a fast producer from overwriting an unconsumed slot. n−1 transfers,
    none of them visible to XLA — the overlap is the grid's sequencing,
    not the scheduler's.

    Numerics are the ppermute form's by construction (same per-chunk
    partial order: upstream partials in ring order, own partial last),
    but this kernel cannot execute off-TPU (inter-chip DMA has no
    interpret lowering on this jax) — it is Mosaic-compile-gated by
    tools/aot_check.py and UNVERIFIED on silicon until the next
    hardware window. Keep it opt-in.
    """
    t = pl.program_id(0)
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, n)
    left = jax.lax.rem(my + n - 1, n)

    def dev(i):
        # MESH device id: full coordinate tuple over the canonical mesh
        # axes, the ring axis replaced by the neighbor index (all six
        # axes are bound inside shard_map over a make_mesh mesh)
        from apex1_tpu.core.mesh import MESH_AXES
        return tuple(i if a == axis_name else jax.lax.axis_index(a)
                     for a in MESH_AXES)

    @pl.when(t == 0)
    def _():
        # both neighbors' kernels must be live before any RDMA targets
        # their buffers
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=dev(left))
        pltpu.semaphore_signal(barrier, inc=1, device_id=dev(right))
        pltpu.semaphore_wait(barrier, 2)

    # MXU work for chunk cs[t] (the x block spec already routed the
    # right rows here via the scalar-prefetch schedule)
    partial = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    slot = jax.lax.rem(t, 2)

    def send_desc(s):
        return pltpu.make_async_remote_copy(
            send_buf.at[s], acc_buf.at[s],
            send_sem.at[s], recv_sem.at[s],
            device_id=dev(right))

    @pl.when(t == 0)
    def _():
        send_buf[0] = partial

    @pl.when(t > 0)
    def _():
        # wait the accumulator the upstream neighbor shipped during
        # step t-1 and fold it into this chunk's partial (the fused
        # "epilogue add" the ppermute form cannot express)
        prev = jax.lax.rem(t + 1, 2)   # (t-1) % 2
        pltpu.make_async_remote_copy(
            send_buf.at[prev], acc_buf.at[prev],
            send_sem.at[prev], recv_sem.at[prev],
            device_id=dev(right)).wait_recv()

        ship = acc_buf[prev] + partial

        # return the drained slot's credit to upstream AFTER the
        # acc_buf[prev] read above (signalling first would let an
        # eager upstream DMA overwrite the slot mid-read), and ONLY if
        # upstream will reuse it (its steps 2..n-2) — t <= n-3 — so
        # every credit signal pairs with exactly one wait and the
        # semaphore is zero at kernel exit
        @pl.when(t < n - 2)
        def _():
            pltpu.semaphore_signal(cap_sem, inc=1, device_id=dev(left))

        @pl.when(t < n - 1)
        def _():
            # slot reuse (t >= 2): BEFORE overwriting send_buf[slot],
            # (a) the local t-2 DMA must have finished READING it
            # (send_sem), and (b) the downstream consumer must have
            # drained its previous payload (credit) — both waits must
            # precede the write, or a lagging neighbor reads a
            # half-overwritten slot
            @pl.when(t >= 2)
            def _():
                send_desc(slot).wait_send()
                pltpu.semaphore_wait(cap_sem, 1)
            send_buf[slot] = ship

        @pl.when(t == n - 1)
        def _():
            o_ref[...] = ship

    @pl.when(t < n - 1)
    def _():
        send_desc(slot).start()

    @pl.when(t == n - 1)
    def _():
        # drain: of the n-1 sends, the reuse waits above consumed n-3
        # send_sems (steps 2..n-2); the LAST TWO (steps n-3 and n-2 for
        # n > 2, step 0 alone for n == 2) are consumed here so every
        # DMA semaphore is zero at kernel exit
        send_desc(jax.lax.rem(t + 1, 2)).wait_send()

        @pl.when(n > 2)
        def _():
            send_desc(slot).wait_send()


def matmul_reduce_scatter_rdma(x, w, axis_name=AXIS_TP):
    """``psum_scatter(x @ w, 0)`` as ONE Pallas kernel with in-kernel
    ICI RDMA (see `_mrs_rdma_kernel`). ``x`` (S, K) 2-D with S/n a
    multiple of 16 and K, N multiples of 128 (pad at the call site —
    this entry is deliberately strict: it exists for the AOT gate, the
    A/B tool and the hardware window, not as a general dispatch
    target). Compiled-TPU only; raises off-TPU. Forward-only (no VJP):
    training paths use `fused_matmul_reduce_scatter`.

    VMEM sizing rule (established by the aot_check gate, enforced here
    and machine-checked by graftlint APX208): the kernel holds four
    fp32 chunk slots (2 recv + 2 send double buffers) beside the
    double-buffered x/w/out blocks — ``apex1_tpu.vmem_model.
    rdma_check`` is the ONE formula (shared with ``tuning.registry``'s
    gating and ``tools/aot_check.py``); chunk=512 x N=1024 measured
    RESOURCE_EXHAUSTED on v5e, 256 x 512 fits with margin. An
    over-budget shape raises here instead of dying in Mosaic with
    RESOURCE_EXHAUSTED mid-hardware-window.
    """
    if interpret_mode():
        raise NotImplementedError(
            "matmul_reduce_scatter_rdma is compiled-TPU only: "
            "inter-chip RDMA has no interpret lowering on this jax — "
            "use fused_matmul_reduce_scatter (the ppermute ring form) "
            "everywhere else")
    if x.ndim != 2:
        raise ValueError(f"x must be (S, K), got {x.shape}")
    n = _axis_size(axis_name)
    if n < 2:
        # the grid writes o_ref only at t > 0 and the drain waits a
        # send that never starts — on one device that is an in-kernel
        # HANG, not a wrong answer; fail loudly instead (the ppermute
        # forms handle n == 1 with a plain chunk dot)
        raise ValueError("matmul_reduce_scatter_rdma needs a ring of "
                         ">= 2 devices; use fused_matmul_reduce_scatter "
                         "for the single-device case")
    S, K = x.shape
    N = w.shape[-1]
    if S % n:
        raise ValueError(f"S={S} not divisible by ring size {n}")
    chunk = S // n
    if chunk % 16 or K % _LANES or N % _LANES:
        raise ValueError(
            f"rdma form needs chunk % 16 == 0 and K, N % 128 == 0; got "
            f"chunk={chunk}, K={K}, N={N} (pad at the call site)")
    x, w = to_mosaic(x, w)
    from apex1_tpu.vmem_model import budget_bytes, rdma_check
    fits, est = rdma_check(chunk, K, N, x.dtype.itemsize,
                           budget_bytes())
    if not fits:
        raise ValueError(
            f"rdma kernel frame ~{est / 2**20:.1f} MiB (4 fp32 chunk "
            f"slots + double-buffered x/w/out blocks, vmem_model."
            f"rdma_check) exceeds the VMEM planning budget "
            f"{budget_bytes() / 2**20:.1f} MiB — shrink chunk*N "
            f"(chunk=512 x N=1024 measured RESOURCE_EXHAUSTED on v5e)")
    idx = _axis_index(axis_name)
    # chunk visiting schedule, ring order: own chunk LAST (same
    # summation order as the ppermute form / a monolithic ring
    # reduce-scatter)
    cs = jnp.mod(idx - 1 - jnp.arange(n, dtype=jnp.int32), n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((chunk, K), lambda t, cs: (cs[t], 0)),
            pl.BlockSpec((K, N), lambda t, cs: (0, 0)),
        ],
        out_specs=pl.BlockSpec((chunk, N), lambda t, cs: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, N), jnp.float32),   # recv slots
            pltpu.VMEM((2, chunk, N), jnp.float32),   # send slots
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ])
    out = pl.pallas_call(
        functools.partial(_mrs_rdma_kernel, n=n, axis_name=axis_name),
        grid_spec=grid_spec,
        out_shape=out_struct((chunk, N), jnp.float32, x, w),
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_RDMA_COLLECTIVE_ID),
    )(cs, x, w)
    return out
