"""Fused LM-head + softmax cross-entropy ("vocab flash") — Pallas TPU.

Capability extension of ``apex/contrib/xentropy`` (see ``ops/xentropy.py``):
the reference kernel fuses softmax+CE but still takes materialized logits.
For an LM head the logits tensor ``x @ Wᵀ`` is (tokens, vocab) — at fp32,
1.6 GB for GPT-2 (50k vocab, 8k tokens) and 4.2 GB for Llama-3 (128k vocab)
per step, twice (forward write + backward read). On TPU the HBM traffic for
that tensor dominates the whole loss computation, so this kernel fuses the
head matmul INTO the cross entropy with the flash-attention recipe
(``ops/attention.py``): the vocab axis is tiled onto the sequential Pallas
grid, each (token-block × vocab-block) logit tile lives only in
VMEM/registers, and the running (max, sum-exp, target-logit, sum-logits)
statistics ride in VMEM scratch. Backward recomputes the tile logits from
``(x, W, lse)`` — the same recompute-instead-of-save trade the reference's
xentropy kernel makes — and accumulates ``dx = g·W`` (vocab-innermost grid)
and ``dW = gᵀ·x`` (token-innermost grid) in fp32 scratch.

Loss semantics match ``softmax_cross_entropy_loss`` exactly (label
smoothing ε, ``padding_idx`` rows → zero loss/grad, ``num_classes`` masks
lane-padded vocab rows of W in-kernel).

**Tensor-parallel form**: a traced ``col_offset`` scalar (SMEM, like the
ring offsets in ``ops/attention.py``) shifts the global column ids, and
``shard_stats``/``shard_grads`` expose the per-shard partial statistics /
gradients so ``transformer.tensor_parallel.cross_entropy ::
vocab_parallel_linear_cross_entropy`` can merge them across the ``tp``
axis (pmax/psum) — the Megatron vocab-parallel CE with the head matmul
fused in, which the reference does not have.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (NEG_INF, interpret_mode, mosaic_dtype,
                                    out_struct, pad_to, to_mosaic,
                                    use_pallas)

_LANES = 128


def _blk(size: int, requested: int) -> int:
    return min(requested, max(16, ((size + 15) // 16) * 16))


def _tile(x_ref, w_ref):
    """One (bt, bv) logit tile on the MXU — native-dtype operands (bf16
    rides the fast MXU path), fp32 accumulation."""
    return jax.lax.dot_general(x_ref[...], w_ref[...],
                               (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _cols(s_shape, vi, bv, off, true_v, true_k):
    """(local col, global col, validity) for one tile. Validity needs BOTH
    bounds: local (pad rows of this W shard) and global (lane-padded or
    shard-truncated vocab)."""
    lcol = jax.lax.broadcasted_iota(jnp.int32, s_shape, 1) + vi * bv
    gcol = lcol + off
    return gcol, (lcol < true_v) & (gcol < true_k)


def _grad_tile(s, t, lse, gcol, valid, smoothing, true_k, padding_idx, dl):
    """dloss/dlogits for one tile: softmax − (1−ε)·onehot − ε/K, scaled by
    the (padding-masked) upstream cotangent."""
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    g = p - (1.0 - smoothing) * (gcol == t) - smoothing / true_k
    g = jnp.where(valid, g, 0.0)
    if padding_idx is not None:
        dl = jnp.where(t == padding_idx, 0.0, dl)
    return g * dl


def _fwd_kernel(x_ref, w_ref, t_ref, off_ref, *out_and_scratch,
                smoothing, true_k, true_v, padding_idx, bv, n_v,
                emit_stats):
    # emit_stats: False = loss+lse outputs; True = four (bt, 1) stat
    # outputs; "packed" = ONE (bt, 4) [m, l, tgt, sumx] output written
    # by the final vocab tile (the fused-collective form: one stat
    # stream to HBM instead of four, consumed by
    # ops.fused_collective.fused_vocab_parallel_merge)
    if emit_stats == "packed":
        pk_ref = out_and_scratch[0]
    elif emit_stats:
        m_ref, l_ref, tgt_ref, sx_ref = out_and_scratch[:4]
    else:
        loss_ref, lse_ref = out_and_scratch[:2]
    m_scr, l_scr, tgt_scr, sx_scr = out_and_scratch[-4:]
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        tgt_scr[...] = jnp.zeros_like(tgt_scr)
        sx_scr[...] = jnp.zeros_like(sx_scr)

    s = _tile(x_ref, w_ref)
    t = t_ref[...]  # (bt, 1) int32
    gcol, valid = _cols(s.shape, vi, bv, off_ref[0, 0], true_v, true_k)
    sm = jnp.where(valid, s, NEG_INF)
    m_prev, l_prev = m_scr[:, :1], l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(sm, axis=1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    e = jnp.where(valid, jnp.exp(sm - m_new), 0.0)
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_prev * corr
                                  + jnp.sum(e, axis=1, keepdims=True),
                                  l_scr.shape)
    tgt_scr[...] += jnp.sum(jnp.where(gcol == t, s, 0.0), axis=1,
                            keepdims=True)
    sx_scr[...] += jnp.sum(jnp.where(valid, s, 0.0), axis=1, keepdims=True)

    @pl.when(vi == n_v - 1)
    def _():
        if emit_stats == "packed":
            pk_ref[...] = jnp.concatenate(
                [m_scr[:, :1], l_scr[:, :1], tgt_scr[:, :1],
                 sx_scr[:, :1]], axis=1)
        elif emit_stats:
            m_ref[...] = m_scr[:, :1]
            l_ref[...] = l_scr[:, :1]
            tgt_ref[...] = tgt_scr[:, :1]
            sx_ref[...] = sx_scr[:, :1]
        else:
            lse = m_scr[:, :1] + jnp.log(l_scr[:, :1])
            loss = ((1.0 - smoothing) * (lse - tgt_scr[:, :1])
                    + smoothing * (lse - sx_scr[:, :1] / true_k))
            if padding_idx is not None:
                loss = jnp.where(t == padding_idx, 0.0, loss)
            loss_ref[...] = loss
            lse_ref[...] = lse


def _bwd_dx_kernel(x_ref, w_ref, t_ref, off_ref, lse_ref, dl_ref,
                   dx_ref, dx_acc, *,
                   smoothing, true_k, true_v, padding_idx, bv, n_v):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _():
        dx_acc[...] = jnp.zeros_like(dx_acc)

    s = _tile(x_ref, w_ref)
    gcol, valid = _cols(s.shape, vi, bv, off_ref[0, 0], true_v, true_k)
    g = _grad_tile(s, t_ref[...], lse_ref[...], gcol, valid,
                   smoothing, true_k, padding_idx, dl_ref[...])
    w = w_ref[...]
    dx_acc[...] += jax.lax.dot_general(
        g.astype(w.dtype), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vi == n_v - 1)
    def _():
        dx_ref[...] = dx_acc[...].astype(dx_ref.dtype)


def _bwd_dw_kernel(x_ref, w_ref, t_ref, off_ref, lse_ref, dl_ref,
                   dw_ref, dw_acc, *,
                   smoothing, true_k, true_v, padding_idx, bv, n_t):
    vi, ti = pl.program_id(0), pl.program_id(1)  # token axis innermost

    @pl.when(ti == 0)
    def _():
        dw_acc[...] = jnp.zeros_like(dw_acc)

    s = _tile(x_ref, w_ref)
    gcol, valid = _cols(s.shape, vi, bv, off_ref[0, 0], true_v, true_k)
    g = _grad_tile(s, t_ref[...], lse_ref[...], gcol, valid,
                   smoothing, true_k, padding_idx, dl_ref[...])
    x = x_ref[...]
    dw_acc[...] += jax.lax.dot_general(            # gᵀ · x
        g.astype(x.dtype), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ti == n_t - 1)
    def _():
        dw_ref[...] = dw_acc[...].astype(dw_ref.dtype)


def _auto_blocks(Hp, block_t, block_v, dtype=jnp.bfloat16):
    """Resolve (block_t, block_v) with the documented precedence
    (docs/ops.md): explicit argument > tuning-table winner
    (`apex1_tpu.tuning`, keyed on generation x dtype x padded hidden)
    > the analytic heuristic below.

    The heuristic shrinks default blocks so the fp32 accumulators
    (dx_acc (bt, Hp), dw_acc (bv, Hp)) + operand blocks stay within ~a
    quarter of the generation's VMEM budget
    (`core.capability.vmem_budget`) at large hidden sizes (Llama-3 8B:
    H=4096; 70B: 8192). Explicitly requested blocks are honored
    as-is."""
    from apex1_tpu.core.capability import vmem_budget
    req_t, req_v = block_t, block_v  # caller-explicit (for the OOM warn)
    if block_t is None or block_v is None:
        from apex1_tpu import tuning
        tuned = tuning.lookup("linear_xent", {"Hp": Hp}, dtype) or {}
        block_t = block_t if block_t is not None else tuned.get("block_t")
        block_v = block_v if block_v is not None else tuned.get("block_v")
    acc_budget = vmem_budget() // 4
    # BOTH fp32 accumulators (dx (bt, Hp) + dw (bv, Hp)) share the frame
    # with double-buffered operand tiles; bound their SUM, with the 3/4
    # headroom established by AOT memory analysis at H=4096 (bt+bv=512
    # OOMs, 384 fits — tools/aot_check.py --flagship,
    # perf_results/aot_full_r3.log; not yet timed on hardware)
    cap_total = max(32, int(acc_budget * 0.75) // (4 * Hp) // 16 * 16)
    bt = block_t if block_t is not None else min(
        256, max(16, cap_total // 3 // 16 * 16))
    bv = block_v if block_v is not None else min(
        512, max(16, cap_total - bt))
    if bt + bv > cap_total:
        # only reachable when at least one block is EXPLICIT — auto
        # sizing stays within cap_total and tuning-table entries are
        # VMEM-validated against the same accumulator bound before the
        # lookup serves them. Warn (not clamp: the caller may know their
        # generation better than the capability table) so a hardware OOM
        # is attributable to the request, not to mis-sized defaults.
        import warnings
        desc = " + ".join(
            f"{name}={val} ({'requested' if req is not None else 'auto'})"
            for name, val, req in (("block_t", bt, req_t),
                                   ("block_v", bv, req_v)))
        warnings.warn(
            f"linear_cross_entropy: {desc} exceed the AOT-verified VMEM "
            f"headroom ({cap_total} rows at Hp={Hp}) for this TPU "
            f"generation — expect Mosaic VMEM OOM; drop the explicit "
            f"block(s) to use auto sizing", stacklevel=3)
    return bt, bv


def _prep(x2, weight, t2, block_t, block_v):
    T, H = x2.shape
    V = weight.shape[0]
    Hp = ((H + _LANES - 1) // _LANES) * _LANES
    block_t, block_v = _auto_blocks(Hp, block_t, block_v, x2.dtype)
    bt, bv = _blk(T, block_t), _blk(V, block_v)
    xp, _ = pad_to(x2, 0, bt)
    xp, _ = pad_to(xp, 1, _LANES)
    wp, _ = pad_to(weight, 0, bv)
    wp, _ = pad_to(wp, 1, _LANES)
    tp, _ = pad_to(t2, 0, bt, value=-1)
    g = dict(T=T, H=H, V=V, bt=bt, bv=bv, Hp=xp.shape[1],
             n_t=xp.shape[0] // bt, n_v=wp.shape[0] // bv)
    return xp, wp, tp, g


def _specs(g, *, for_dw=False):
    """Grid is (ti, vi) for fwd/dx and (vi, ti) for dW (``for_dw``)."""
    def ix(i0, i1):
        return (i1, i0) if for_dw else (i0, i1)

    x_spec = pl.BlockSpec((g["bt"], g["Hp"]),
                          lambda i0, i1: (ix(i0, i1)[0], 0),
                          memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((g["bv"], g["Hp"]),
                          lambda i0, i1: (ix(i0, i1)[1], 0),
                          memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((g["bt"], 1),
                             lambda i0, i1: (ix(i0, i1)[0], 0),
                             memory_space=pltpu.VMEM)
    off_spec = pl.BlockSpec((1, 1), lambda *_: (0, 0),
                            memory_space=pltpu.SMEM)
    return x_spec, w_spec, stat_spec, off_spec


def _off_array(off):
    return jnp.asarray(off, jnp.int32).reshape(1, 1)


def shard_stats(x2, w_shard, t2, *, col_offset=0, num_classes=None,
                block_t=None, block_v=None):
    """Per-shard online-softmax partials ``(m, l, tgt, sumx)`` — each
    (T,) fp32 — over the GLOBAL columns ``[col_offset, col_offset + V_l)``
    this shard's ``w_shard`` (V_l, H) covers. NOT differentiable on its
    own; the vocab-parallel wrapper owns the VJP."""
    xp, wp, tp, g = _prep(x2, w_shard, t2, block_t, block_v)
    k = num_classes if num_classes is not None else g["V"]
    x_spec, w_spec, stat_spec, off_spec = _specs(g)
    Tp = g["n_t"] * g["bt"]
    outs = pl.pallas_call(
        functools.partial(_fwd_kernel, smoothing=0.0, true_k=k,
                          true_v=g["V"], padding_idx=None, bv=g["bv"],
                          n_v=g["n_v"], emit_stats=True),
        grid=(g["n_t"], g["n_v"]),
        in_specs=[x_spec, w_spec, stat_spec, off_spec],
        out_specs=(stat_spec,) * 4,
        out_shape=(out_struct((Tp, 1), jnp.float32, xp, wp, tp),) * 4,
        scratch_shapes=[pltpu.VMEM((g["bt"], _LANES), jnp.float32)] * 4,
        interpret=interpret_mode(),
    )(xp, wp, tp, _off_array(col_offset))
    return tuple(o[:g["T"], 0] for o in outs)


def shard_stats_packed(x2, w_shard, t2, *, col_offset=0, num_classes=None,
                       block_t=None, block_v=None):
    """`shard_stats` with the four per-shard stats PACKED into one
    (T, 4) ``[m, l, tgt, sumx]`` output by the kernel's final vocab
    tile — one stat stream to HBM instead of four, and the shape
    `ops.fused_collective.fused_vocab_parallel_merge` consumes with a
    single packed psum (two collectives total instead of four). The
    packed values are bit-identical to `shard_stats`' (same scratch
    reads, same tile). NOT differentiable on its own; the vocab-parallel
    wrapper owns the VJP."""
    xp, wp, tp, g = _prep(x2, w_shard, t2, block_t, block_v)
    k = num_classes if num_classes is not None else g["V"]
    x_spec, w_spec, stat_spec, off_spec = _specs(g)
    pk_spec = pl.BlockSpec((g["bt"], 4), lambda i0, i1: (i0, 0),
                           memory_space=pltpu.VMEM)
    Tp = g["n_t"] * g["bt"]
    packed = pl.pallas_call(
        functools.partial(_fwd_kernel, smoothing=0.0, true_k=k,
                          true_v=g["V"], padding_idx=None, bv=g["bv"],
                          n_v=g["n_v"], emit_stats="packed"),
        grid=(g["n_t"], g["n_v"]),
        in_specs=[x_spec, w_spec, stat_spec, off_spec],
        out_specs=pk_spec,
        out_shape=out_struct((Tp, 4), jnp.float32, xp, wp, tp),
        scratch_shapes=[pltpu.VMEM((g["bt"], _LANES), jnp.float32)] * 4,
        interpret=interpret_mode(),
    )(xp, wp, tp, _off_array(col_offset))
    return packed[:g["T"]]


def shard_grads(x2, w_shard, t2, lse, dloss, *, col_offset=0,
                smoothing=0.0, padding_idx=None, num_classes=None,
                block_t=None, block_v=None):
    """Per-shard gradients given the GLOBAL logsumexp: returns
    ``(dx_partial, dw_shard)`` — dx must still be summed across shards
    (each shard only saw its own vocab columns)."""
    xp, wp, tp, g = _prep(x2, w_shard, t2, block_t, block_v)
    k = num_classes if num_classes is not None else g["V"]
    lse_p, _ = pad_to(lse.reshape(-1, 1).astype(jnp.float32), 0, g["bt"])
    dl, _ = pad_to(dloss.reshape(-1, 1).astype(jnp.float32), 0, g["bt"])
    off = _off_array(col_offset)
    kern = dict(smoothing=smoothing, true_k=k, true_v=g["V"],
                padding_idx=padding_idx, bv=g["bv"])

    x_spec, w_spec, stat_spec, off_spec = _specs(g)
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, n_v=g["n_v"], **kern),
        grid=(g["n_t"], g["n_v"]),
        in_specs=[x_spec, w_spec, stat_spec, off_spec, stat_spec,
                  stat_spec],
        out_specs=x_spec,
        out_shape=out_struct(xp.shape, x2.dtype, xp, wp, tp, lse_p, dl),
        scratch_shapes=[pltpu.VMEM((g["bt"], g["Hp"]), jnp.float32)],
        interpret=interpret_mode(),
    )(xp, wp, tp, off, lse_p, dl)[:g["T"], :g["H"]]

    x_spec, w_spec, stat_spec, off_spec = _specs(g, for_dw=True)
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, n_t=g["n_t"], **kern),
        grid=(g["n_v"], g["n_t"]),
        in_specs=[x_spec, w_spec, stat_spec, off_spec, stat_spec,
                  stat_spec],
        out_specs=w_spec,
        out_shape=out_struct(wp.shape, w_shard.dtype, xp, wp, tp,
                             lse_p, dl),
        scratch_shapes=[pltpu.VMEM((g["bv"], g["Hp"]), jnp.float32)],
        interpret=interpret_mode(),
    )(xp, wp, tp, off, lse_p, dl)[:g["V"], :g["H"]]
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _fused(x2, weight, t2, smoothing, padding_idx, num_classes,
           block_t, block_v):
    return _fused_fwd(x2, weight, t2, smoothing, padding_idx, num_classes,
                      block_t, block_v)[0]


def _fused_fwd(x2, weight, t2, smoothing, padding_idx, num_classes,
               block_t, block_v):
    xp, wp, tp, g = _prep(x2, weight, t2, block_t, block_v)
    k = num_classes if num_classes is not None else g["V"]
    x_spec, w_spec, stat_spec, off_spec = _specs(g)
    Tp = g["n_t"] * g["bt"]
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, smoothing=smoothing, true_k=k,
                          true_v=g["V"], padding_idx=padding_idx,
                          bv=g["bv"], n_v=g["n_v"], emit_stats=False),
        grid=(g["n_t"], g["n_v"]),
        in_specs=[x_spec, w_spec, stat_spec, off_spec],
        out_specs=(stat_spec, stat_spec),
        out_shape=(out_struct((Tp, 1), jnp.float32, xp, wp, tp),
                   out_struct((Tp, 1), jnp.float32, xp, wp, tp)),
        scratch_shapes=[pltpu.VMEM((g["bt"], _LANES), jnp.float32)] * 4,
        interpret=interpret_mode(),
    )(xp, wp, tp, _off_array(0))
    return loss[:g["T"], 0], (x2, weight, t2, lse[:g["T"], 0])


def _fused_bwd(smoothing, padding_idx, num_classes, block_t, block_v,
               res, dloss):
    x2, weight, t2, lse = res
    dx, dw = shard_grads(x2, weight, t2, lse, dloss,
                         smoothing=smoothing, padding_idx=padding_idx,
                         num_classes=num_classes,
                         block_t=block_t, block_v=block_v)
    f0 = np.zeros(t2.shape, dtype=jax.dtypes.float0)
    return dx, dw, f0


_fused.defvjp(_fused_fwd, _fused_bwd)


def _xla_linear_xent(x, weight, labels, smoothing, padding_idx, num_classes):
    """Composite gold: materializes logits (what this kernel avoids)."""
    from apex1_tpu.ops.xentropy import _xla_xent
    logits = jnp.einsum("th,vh->tv", x.astype(jnp.float32),
                        weight.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
    return _xla_xent(logits, labels, smoothing, padding_idx, num_classes)


def linear_cross_entropy(x, weight, labels, *, smoothing: float = 0.0,
                         padding_idx: int | None = None,
                         num_classes: int | None = None,
                         block_t: int | None = None,
                         block_v: int | None = None):
    """Per-token CE of ``softmax(x @ weightᵀ)`` without materializing the
    logits — ``x`` (..., H), ``weight`` (V, H) (an embedding table for tied
    LM heads), ``labels`` (...,) int. Returns (...,) fp32 losses.

    Semantics ≡ ``softmax_cross_entropy_loss(x @ weightᵀ, labels, ...)``
    (``ops/xentropy.py``): label ``smoothing``, zero loss/grad at
    ``padding_idx`` rows, ``num_classes`` masking of lane-padded vocab rows.
    """
    if x.shape[-1] != weight.shape[-1]:
        raise ValueError(f"hidden mismatch: x {x.shape} vs weight "
                         f"{weight.shape}")
    if num_classes is not None and not (0 < num_classes <= weight.shape[0]):
        raise ValueError(f"num_classes {num_classes} must be in "
                         f"(0, {weight.shape[0]}]")
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    t2 = labels.reshape(-1, 1).astype(jnp.int32)
    if use_pallas():
        # fp16 is a storage dtype on TPU (Mosaic has no f16): the kernel
        # takes bf16; the fp32 loss output needs no restore — see
        # ops._common.mosaic_dtype
        x2, weight = to_mosaic(x2, weight)
        loss = _fused(x2, weight, t2, float(smoothing), padding_idx,
                      num_classes, block_t, block_v)
    else:
        loss = _xla_linear_xent(x2, weight, t2[:, 0], smoothing,
                                padding_idx, num_classes)
    return loss.reshape(lead)
