"""Pallas TPU kernels + XLA composites — the ``csrc/`` of this framework.

Each op has a Pallas kernel (TPU) and an XLA-composite fallback/gold; see
`apex1_tpu.ops._common` for dispatch. Decisions of the form "XLA already
fuses this" (fused_dense, MLP epilogues) are documented in `ops.fused_dense`.
"""

from apex1_tpu.ops._common import (  # noqa: F401
    NEG_INF, force_impl, get_impl, set_impl, use_pallas)
from apex1_tpu.ops.layer_norm import (  # noqa: F401
    FusedLayerNorm, FusedRMSNorm, layer_norm, rms_norm)
from apex1_tpu.ops.softmax import (  # noqa: F401
    FusedScaleMaskSoftmax, scaled_masked_softmax,
    scaled_upper_triang_masked_softmax)
from apex1_tpu.ops.xentropy import (  # noqa: F401
    masked_next_token_mean, softmax_cross_entropy_loss)
from apex1_tpu.ops.linear_xent import linear_cross_entropy  # noqa: F401
from apex1_tpu.ops.chunked_loss import (  # noqa: F401
    chunked_dpo_loss, chunked_kl_loss, chunked_logprob,
    chunked_orpo_loss)
from apex1_tpu.ops.fused_dense import fused_glu  # noqa: F401
from apex1_tpu.ops.lora_epilogue import lora_delta  # noqa: F401
from apex1_tpu.ops.rope import (  # noqa: F401
    apply_rotary_pos_emb, rope_tables)
from apex1_tpu.ops.attention import flash_attention, fmha  # noqa: F401
from apex1_tpu.ops.quantized import (  # noqa: F401
    int8_matmul, quantize_int8)
from apex1_tpu.ops.stochastic import (  # noqa: F401
    fold_seed, fused_bias_dropout_add, fused_dropout_add_layer_norm,
    seed_from_key)
from apex1_tpu.ops.fused_collective import (  # noqa: F401
    all_gather_flash_attention, fused_all_gather_matmul,
    fused_matmul_reduce_scatter)
