"""Shared kernel-dispatch machinery for `apex1_tpu.ops`.

Every op ships two implementations:

- a **Pallas TPU kernel** (the ``csrc/`` equivalent), used on TPU backends;
- an **XLA composite** (pure jnp; also the parity "gold"), used on CPU/GPU
  and wherever profiling shows XLA's fusion already wins (the reference's
  ``is_kernel_available`` fallback pattern,
  ``apex/transformer/functional/fused_softmax.py :: FusedScaleMaskSoftmax``).

Dispatch is controllable for tests/benchmarks via ``set_impl`` /
``force_impl`` ("auto" | "pallas" | "xla"). On non-TPU backends "pallas"
runs the kernel in interpreter mode so kernel logic is testable on the CPU
mesh harness.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp

_IMPL = "auto"  # "auto" | "pallas" | "xla"


def set_impl(mode: str) -> None:
    global _IMPL
    if mode not in ("auto", "pallas", "xla"):
        raise ValueError(f"impl must be auto|pallas|xla, got {mode!r}")
    _IMPL = mode


def get_impl() -> str:
    return _IMPL


@contextlib.contextmanager
def force_impl(mode: str):
    prev = _IMPL
    set_impl(mode)
    try:
        yield
    finally:
        set_impl(prev)


@functools.cache
def _default_backend() -> str:
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def on_tpu() -> bool:
    # the axon PJRT plugin reports platform "axon" but is a TPU
    return _default_backend() in ("tpu", "axon")


def use_pallas() -> bool:
    if _IMPL == "pallas":
        return True
    if _IMPL == "xla":
        return False
    return on_tpu()


def interpret_mode() -> bool:
    """Interpret Pallas kernels when not on a real TPU."""
    return not on_tpu()


def mosaic_dtype(dtype):
    """The dtype a COMPILED Pallas kernel runs for ``dtype`` operands.

    Mosaic has no IEEE float16 ("Unsupported type: 'f16'" at lowering),
    so under the fp16 AMP policies fp16 is a STORAGE dtype only: kernel
    entry points cast f16 operands to bf16 on the compiled-TPU path and
    cast results back (XLA itself upcasts f16 dots on TPU — neither path
    computes IEEE-f16 products). Identity everywhere else: interpret
    mode and the XLA composites take f16 directly, so CPU tier-1
    behavior is unchanged. The cast is a plain convert_element_type —
    autodiff transposes it, so custom_vjp kernels only ever see bf16."""
    if dtype == jnp.float16 and not interpret_mode():
        return jnp.bfloat16
    return dtype


def to_mosaic(*arrays):
    """Cast each array to its `mosaic_dtype` (f16 -> bf16 on the
    compiled-TPU path, identity otherwise). ``None`` passes through;
    one array in -> one array out. Kernel entry points run EVERY
    floating-point operand through this so per-operand coverage is
    auditable at the call site."""
    out = tuple(a if a is None or a.dtype == mosaic_dtype(a.dtype)
                else a.astype(mosaic_dtype(a.dtype)) for a in arrays)
    return out[0] if len(out) == 1 else out


def out_struct(shape, dtype, *like):
    """``ShapeDtypeStruct`` for a ``pallas_call`` output whose ``vma``
    (varying-across-mesh-axes set) is the union of the ``like`` inputs'.

    Under ``jax.shard_map(..., check_vma=True)`` — the default — every
    pallas_call output must declare its vma or tracing fails with
    "`vma` on `jax.ShapeDtypeStruct` must not be `None`" (review r5:
    this made the Pallas path of ring/Ulysses attention untraceable in
    the shipped TPU configuration while the CPU/XLA fallback hid it
    from the suite). A kernel output varies exactly like the inputs it
    is computed from, so the union is the right declaration; outside
    shard_map every vma is the empty frozenset, which pallas_call
    accepts in plain jit.
    """
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        # older jax (< 0.6): no vma concept on avals and no `vma=`
        # parameter on ShapeDtypeStruct — shard_map there has no
        # check_vma gate either, so the plain struct is complete
        return jax.ShapeDtypeStruct(shape, dtype)
    vma = frozenset()
    for x in like:
        vma |= typeof(x).vma
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def vary(x, axis_name):
    """Mark ``x`` as device-varying over ``axis_name`` (ring/scan carry
    typing under ``check_vma``; the 0.4.x compat bridge makes pcast the
    identity there). The sibling of `out_struct`'s vma declaration —
    keep the shim HERE so a jax-compat fix lands once, not per caller
    (parallel.ring_attention, tensor_parallel.mappings)."""
    return jax.lax.pcast(x, axis_name, to="varying")


def pad_to(x: jnp.ndarray, axis: int, multiple: int, value=0.0):
    """Pad ``axis`` up to a multiple; returns (padded, original_size).

    Client-side neutral-element padding keeps kernels free of ragged-edge
    masking (XLA fuses the pad/slice into the surrounding program).
    """
    size = x.shape[axis]
    rem = (-size) % multiple
    if rem == 0:
        return x, size
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, rem)
    return jnp.pad(x, pads, constant_values=value), size


def as_rows(x: jnp.ndarray) -> tuple[jnp.ndarray, tuple[int, ...]]:
    """Collapse leading dims: (..., H) -> (R, H)."""
    shape = x.shape
    return x.reshape(-1, shape[-1]), shape


NEG_INF = -1e30  # finite mask value, reference kernels use -10000/-inf


def row_block(lanes: int, *, rows: int | None = None,
              budget_bytes: int = 1 << 20, lo: int = 8,
              hi: int = 512) -> int:
    """Rows per grid step for row-wise kernels (LN, softmax, xentropy…).

    Tiny fixed blocks make the grid huge and per-step DMA/launch overheads
    dominate (round-1 on-device profile attributed ~5× to small tiles on
    GPT-2 shapes — BASELINE.md "Round 1 measurements"; the raw trace was
    not retained, block-sweep re-measurement queued in
    tools/bench_kernels.py); this targets ``budget_bytes``
    of fp32 per row-block operand (keep it ≤1 MiB — Pallas double-buffers
    every operand and bwd kernels carry 3+ row blocks), clamped to
    [``lo``, ``hi``] and — when ``rows`` is given — to the actual row
    count (8-aligned) so small inputs aren't padded up to dead work.
    ``lanes`` is the RAW last-dim size; rounded to 128 internally."""
    lanes_p = max(128, ((lanes + 127) // 128) * 128)
    br = max(lo, min(hi, budget_bytes // (4 * lanes_p) // 8 * 8))
    if rows is not None:
        br = min(br, max(lo, ((rows + 7) // 8) * 8))
    return br
