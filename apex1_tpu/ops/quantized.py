"""Weight-only int8 quantized matmul — the TPU decode path.

Beyond-reference capability (the reference accelerates training only; its
closest artifact is the fp16 weight cast of amp O2, `apex/amp/_initialize.py
:: _initialize`): autoregressive decode is HBM-bandwidth-bound — every step
streams every weight once for a handful of rows of compute — so halving
weight bytes nearly halves step time. Weights are stored int8 with
per-output-channel fp32 scales and dequantized INSIDE the Pallas kernel's
VMEM tiles (bf16 cast → MXU matmul → fp32 accumulate → scale on the final
K block), so the bf16 weight matrix is never materialized in HBM.

- :func:`quantize_int8` — symmetric per-out-channel quantization of a
  ``(N, K)`` weight (max-abs / 127).
- :func:`int8_matmul` — ``y = x @ (wq * scale).T`` with the dequant fused;
  differentiable in ``x`` only (weights are frozen at decode time).

Dispatch follows `ops._common` (``set_impl`` / ``force_impl``): the XLA
composite (explicit dequant then matmul) is the parity gold and the
fallback for unaligned shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import interpret_mode, out_struct, use_pallas


def quantize_int8(w, *, axis: int = -1):
    """Symmetric per-channel int8 quantization of a 2-D weight.

    ``w``: (N, K) with ``axis`` the contraction (K) axis — each of the N
    output channels gets one fp32 scale = max|w| / 127 over its K entries.
    Returns ``(wq int8 (N, K), scale fp32 (N,))`` with
    ``w ≈ wq * scale[:, None]``.
    """
    if w.ndim != 2:
        raise ValueError(f"quantize_int8 expects a 2-D weight, got "
                         f"{w.shape}")
    if axis not in (0, 1, -1, -2):
        raise ValueError(f"axis must name one of the 2 dims, got {axis}")
    if axis in (0, -2):
        w = w.T
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=1) / 127.0
    scale = jnp.maximum(scale, 1e-12)  # all-zero channels stay zero
    wq = jnp.clip(jnp.round(wf / scale[:, None]), -127, 127)
    return wq.astype(jnp.int8), scale


def _dequant_matmul_xla(x, wq, scale):
    """Gold composite: explicit dequant then matmul (XLA fuses the dequant
    into the dot's operand stream, but still reads int8 + writes bf16
    unless it fuses — the kernel guarantees the fusion). The per-channel
    scale stays fp32 and multiplies the fp32 accumulator output, exactly
    as the Pallas kernel does — both paths share one numerics contract
    (a bf16-cast scale here would make the gold ~0.4% noisier than the
    kernel it golds, and shape-dependent, since this composite is also
    the unaligned-shape fallback). The ACTIVATION is cast to bf16 for
    the same reason: the kernel feeds the MXU bf16 activations, and an
    fp32-x composite would make fp32 callers' results shape-dependent
    (kernel on aligned shapes, more-precise composite on unaligned —
    found by the int8 shape fuzz, round 5). Production decode passes
    bf16 activations, where this cast is a no-op."""
    y = jnp.matmul(x.astype(jnp.bfloat16), wq.astype(jnp.bfloat16).T,
                   preferred_element_type=jnp.float32)
    return y * scale.astype(jnp.float32)


def _int8_mm_kernel(x_ref, wq_ref, scale_ref, o_ref):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    xb = x_ref[...]
    wb = wq_ref[...].astype(jnp.bfloat16)          # dequant lives in VMEM
    o_ref[...] += jnp.dot(xb, wb.T,
                          preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(1) - 1)
    def _scale():
        o_ref[...] *= scale_ref[...].astype(jnp.float32)


def _fit_block(size: int, want: int) -> int:
    """Largest multiple-of-128 DIVISOR of ``size`` that is <= ``want``.
    Blocks must tile the dim exactly: a pl.cdiv ragged tail block would
    read out-of-bounds K columns and accumulate garbage into every
    output (there is no pad_to here — weights are static, callers
    shouldn't pay a per-call pad copy). The gate guarantees
    ``size % 128 == 0``, so 128 always divides."""
    units = size // 128
    for cand in range(min(want // 128, units), 0, -1):
        if units % cand == 0:
            return cand * 128
    return 128


def _pallas_int8_matmul(x, wq, scale, block_n: int, block_k: int):
    T, K = x.shape
    N = wq.shape[0]
    bn = _fit_block(N, block_n)
    bk = _fit_block(K, block_k)
    grid = (N // bn, K // bk)
    return pl.pallas_call(
        _int8_mm_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((T, bk), lambda n, k: (0, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, bk), lambda n, k: (n, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda n, k: (0, n),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((T, bn), lambda n, k: (0, n),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((T, N), jnp.float32, x, wq, scale),
        interpret=interpret_mode(),
    )(x, wq, scale.reshape(1, N))


def _aligned_for_kernel(T, N, K):
    # int8 VMEM tiles are (32, 128); bf16 (16, 128). Demand lane (128)
    # alignment on both matmul dims and a sublane-friendly row count —
    # everything else takes the composite (decode shapes from real models
    # are 128-aligned; tiny test configs are not, and padding tiny cases
    # would be pure overhead).
    return N % 128 == 0 and K % 128 == 0 and T <= 1024


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def int8_matmul(x, wq, scale, block_n: int | None = None,
                block_k: int | None = None):
    """``y = x @ (wq * scale[:, None]).T`` — (T, K) @ (K, N) -> (T, N).

    ``x`` bf16/fp32 activations, ``wq`` int8 (N, K), ``scale`` fp32 (N,)
    (from :func:`quantize_int8`). fp32 accumulation; output fp32 (cast at
    the call site). Differentiable in ``x`` only — weight cotangents are
    zero (decode-time weights are frozen; quantization is not trained
    through). ``block_n``/``block_k``: static Pallas tile requests
    (divisor-fitted to N/K); ``None`` resolves tuning-table winner for
    this (generation, N, K) > the (256, 512) defaults.
    """
    return _int8_matmul_fwd(x, wq, scale, block_n, block_k)[0]


def _resolve_blocks(N, K, block_n, block_k):
    """Explicit request > tuning table (keyed on the weight dims — both
    128-aligned by `_aligned_for_kernel`) > the (256, 512) defaults."""
    if block_n is None or block_k is None:
        from apex1_tpu import tuning
        tuned = tuning.lookup("int8_matmul", {"N": N, "K": K},
                              "int8") or {}
        block_n = block_n if block_n is not None else tuned.get("block_n")
        block_k = block_k if block_k is not None else tuned.get("block_k")
    return block_n or 256, block_k or 512


def _int8_matmul_fwd(x, wq, scale, block_n, block_k):
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = wq.shape[0]
    x2 = x.reshape(-1, K)
    if use_pallas() and _aligned_for_kernel(x2.shape[0], N, K):
        block_n, block_k = _resolve_blocks(N, K, block_n, block_k)
        x8 = x2
        if x8.shape[0] % 8:  # sublane-pad the (tiny) row dim
            pad = 8 - x8.shape[0] % 8
            x8 = jnp.pad(x8, ((0, pad), (0, 0)))
        y = _pallas_int8_matmul(x8.astype(jnp.bfloat16), wq, scale,
                                block_n, block_k)[:x2.shape[0]]
    else:
        y = _dequant_matmul_xla(x2, wq, scale)
    # residuals carry only what bwd reads: the weights and x's DTYPE (as
    # a 0-sized proto array — saving x itself would keep the whole
    # (..., K) activation alive just to call .astype on dx)
    return y.reshape(*lead, N), (jnp.zeros((0,), x.dtype), wq, scale)


def _int8_matmul_bwd(block_n, block_k, res, dy):
    x_proto, wq, scale = res
    # fp32 AD transpose of the fwd contract y = (x₁₆ @ wq₁₆ᵀ)·s₃₂: the
    # scale rides the fp32 cotangent and the whole dot runs fp32 (int8
    # weight values are exact in any float width, and dx is a
    # test/tooling path — decode weights are frozen — so precision
    # beats MXU-operand casting). The previous form cast BOTH dy and
    # the scale to bf16, the same shape-dependent-numerics class
    # ADVICE r4 flagged on the fwd composite — caught by the int8
    # shape fuzz.
    dx = jnp.matmul(dy.astype(jnp.float32) * scale.astype(jnp.float32),
                    wq.astype(jnp.float32),
                    preferred_element_type=jnp.float32).astype(
                        x_proto.dtype)
    return dx, jnp.zeros_like(wq), jnp.zeros_like(scale)


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)
