"""Fused LayerNorm / RMSNorm — Pallas TPU kernels.

Reference: ``csrc/layer_norm_cuda_kernel.cu :: cuApplyLayerNorm,
cuComputeGradInput`` (exposed as ``fused_layer_norm_cuda``), the faster
``apex/contrib/csrc/layer_norm`` ("fast layer norm"), and the Python wrappers
``apex/normalization/fused_layer_norm.py :: FusedLayerNorm, FusedRMSNorm,
MixedFusedLayerNorm``.

Reference semantics preserved:
- forward saves per-row ``mean`` and ``invvar`` (rstd) for the backward;
- "Mixed" dtype behaviour: bf16/fp16 input with fp32 γ/β; stats always
  accumulated in fp32 (the CUDA kernels template on ACC_T=float);
- RMSNorm variant (no mean subtraction, no β);
- ``memory_efficient``: recompute in backward instead of saving activations
  (`jax.checkpoint` around the op — RNG-exact replay is free in JAX).

TPU design: rows tiled (BLOCK_ROWS, H) into VMEM; one grid step normalizes a
row block on the VPU — the CUDA Welford loop collapses to a two-moment
reduction because the whole row is VMEM-resident. The backward emits dx in
the same pass and accumulates dγ/dβ across row blocks in a VMEM accumulator
mapped to a fixed output block (grid steps are sequential on a TensorCore),
≙ the reference's staged column-reduction second kernel. Ragged edges are
handled by client-side neutral padding (rows to BLOCK_ROWS, H to lane
multiples) — XLA fuses the pad/slice.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (as_rows, interpret_mode, mosaic_dtype,
                                   out_struct, pad_to, to_mosaic,
                                   use_pallas)
from apex1_tpu.tuning import tuned_row_block


# --------------------------------------------------------------------------
# kernels
# --------------------------------------------------------------------------

def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *,
                eps: float, true_h: int, rms: bool):
    x = x_ref[...].astype(jnp.float32)
    inv_h = 1.0 / true_h
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
    else:
        mean = jnp.sum(x, axis=1, keepdims=True) * inv_h
    xc = x - mean
    # zero-padded H columns contribute (0-mean)^2 to the raw sum; correct by
    # summing x*x and x separately over true_h instead
    if rms:
        var = jnp.sum(x * x, axis=1, keepdims=True) * inv_h
    else:
        var = jnp.sum(x * x, axis=1, keepdims=True) * inv_h - mean * mean
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    y = xhat * g_ref[...].astype(jnp.float32)
    if b_ref is not None:
        y = y + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref,
                dx_ref, dg_ref, db_ref, *, true_h: int, rms: bool):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    gamma = g_ref[...].astype(jnp.float32)
    mean = mean_ref[...]
    rstd = rstd_ref[...]
    xhat = (x - mean) * rstd
    wdy = dy * gamma
    inv_h = 1.0 / true_h
    c1 = jnp.sum(xhat * wdy, axis=1, keepdims=True) * inv_h
    if rms:
        dx = (wdy - xhat * c1) * rstd
    else:
        c2 = jnp.sum(wdy, axis=1, keepdims=True) * inv_h
        dx = (wdy - c2 - xhat * c1) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        if db_ref is not None:
            db_ref[...] = jnp.zeros_like(db_ref)

    dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    if db_ref is not None:
        db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def _specs(h, br):
    row = pl.BlockSpec((br, h), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    stat = pl.BlockSpec((br, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return row, vec, stat


def _pallas_fwd(x2, gamma2, beta2, eps, true_h, rms, br):
    rows, h = x2.shape
    row, vec, stat = _specs(h, br)
    if beta2 is not None:
        kernel = functools.partial(_fwd_kernel, eps=eps, true_h=true_h,
                                   rms=rms)
        in_specs, args = [row, vec, vec], (x2, gamma2, beta2)
    else:
        kernel = functools.partial(
            lambda xr, gr, yr, mr, rr, **kw: _fwd_kernel(
                xr, gr, None, yr, mr, rr, **kw),
            eps=eps, true_h=true_h, rms=rms)
        in_specs, args = [row, vec], (x2, gamma2)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=in_specs,
        out_specs=(row, stat, stat),
        out_shape=(out_struct((rows, h), x2.dtype, x2, gamma2),
                   out_struct((rows, 1), jnp.float32, x2, gamma2),
                   out_struct((rows, 1), jnp.float32, x2, gamma2)),
        interpret=interpret_mode(),
    )(*args)


def _pallas_bwd(x2, gamma2, mean, rstd, dy2, true_h, rms, with_beta, br):
    rows, h = x2.shape
    row, vec, stat = _specs(h, br)
    if with_beta:
        kernel = functools.partial(_bwd_kernel, true_h=true_h, rms=rms)
        out_specs = (row, vec, vec)
        out_shape = (out_struct((rows, h), x2.dtype, x2, gamma2, dy2),
                     out_struct((1, h), jnp.float32, x2, gamma2, dy2),
                     out_struct((1, h), jnp.float32, x2, gamma2, dy2))
    else:
        kernel = functools.partial(
            lambda xr, gr, mr, rr, dyr, dxr, dgr, **kw: _bwd_kernel(
                xr, gr, mr, rr, dyr, dxr, dgr, None, **kw),
            true_h=true_h, rms=rms)
        out_specs = (row, vec)
        out_shape = (out_struct((rows, h), x2.dtype, x2, gamma2, dy2),
                     out_struct((1, h), jnp.float32, x2, gamma2, dy2))
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=[row, vec, stat, stat, row],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret_mode(),
    )(x2, gamma2, mean, rstd, dy2)


# --------------------------------------------------------------------------
# custom_vjp plumbing
# --------------------------------------------------------------------------

def _prep(x, gamma, beta, block_rows=None):
    x2, shape = as_rows(x)
    h = x2.shape[-1]
    # computed ONCE; launchers take it. None = table > heuristic.
    br = tuned_row_block("layer_norm", h, rows=x2.shape[0],
                         dtype=x.dtype, requested=block_rows)
    x2p, rows = pad_to(x2, 0, br)
    x2p, _ = pad_to(x2p, 1, 128)
    g2 = pad_to(gamma.reshape(1, -1), 1, 128)[0]
    b2 = pad_to(beta.reshape(1, -1), 1, 128)[0] if beta is not None else None
    return x2p, g2, b2, shape, h, rows, br


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_norm(x, gamma, beta, eps, rms, block_rows):
    return _fused_norm_fwd(x, gamma, beta, eps, rms, block_rows)[0]


def _fused_norm_fwd(x, gamma, beta, eps, rms, block_rows):
    x2p, g2, b2, shape, h, rows, br = _prep(x, gamma, beta, block_rows)
    y, mean, rstd = _pallas_fwd(x2p, g2, b2, eps, h, rms, br)
    y = y[:rows, :h].reshape(shape)
    return y, (x, gamma, beta, mean, rstd)


def _fused_norm_bwd(eps, rms, block_rows, res, dy):
    x, gamma, beta, mean, rstd = res
    x2p, g2, _, shape, h, rows, br = _prep(x, gamma, beta, block_rows)
    dy2, _ = as_rows(dy)
    dy2p, _ = pad_to(dy2, 0, br)
    dy2p, _ = pad_to(dy2p, 1, 128)
    outs = _pallas_bwd(x2p, g2, mean, rstd, dy2p, h, rms,
                       with_beta=beta is not None, br=br)
    dx = outs[0][:rows, :h].reshape(shape)
    dg = outs[1][0, :h].astype(gamma.dtype)
    if beta is not None:
        db = outs[2][0, :h].astype(beta.dtype)
        return dx, dg, db
    return dx, dg, None


_fused_norm.defvjp(_fused_norm_fwd, _fused_norm_bwd)


# --------------------------------------------------------------------------
# XLA composite (gold / fallback)
# --------------------------------------------------------------------------

def _xla_norm(x, gamma, beta, eps, rms):
    x32 = x.astype(jnp.float32)
    mean = 0.0 if rms else jnp.mean(x32, axis=-1, keepdims=True)
    xc = x32 - mean
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    if not rms:
        var = var - jnp.square(mean)
    y = xc * jax.lax.rsqrt(var + eps) * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y.astype(x.dtype)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

def layer_norm(x, gamma, beta, *, eps: float = 1e-5,
               block_rows: int | None = None):
    """Fused LayerNorm over the last axis. bf16/fp16 ``x`` with fp32 ``γ/β``
    is the reference "MixedFused" path; output keeps ``x.dtype``.
    ``block_rows``: static rows-per-grid-step; ``None`` resolves tuning
    table > heuristic (`apex1_tpu.tuning.tuned_row_block`)."""
    if use_pallas():
        kdt = mosaic_dtype(x.dtype)  # fp16 -> bf16 on compiled TPU
        gamma, beta = to_mosaic(gamma, beta)  # O3_fp16 params
        if kdt != x.dtype:
            return _fused_norm(x.astype(kdt), gamma, beta, eps, False,
                               block_rows).astype(x.dtype)
        return _fused_norm(x, gamma, beta, eps, False, block_rows)
    return _xla_norm(x, gamma, beta, eps, False)


def rms_norm(x, gamma, *, eps: float = 1e-6,
             block_rows: int | None = None):
    """Fused RMSNorm (``FusedRMSNorm`` — stock torch lacked it)."""
    if use_pallas():
        kdt = mosaic_dtype(x.dtype)  # fp16 -> bf16 on compiled TPU
        gamma = to_mosaic(gamma)  # O3_fp16 params
        if kdt != x.dtype:
            return _fused_norm(x.astype(kdt), gamma, None, eps, True,
                               block_rows).astype(x.dtype)
        return _fused_norm(x, gamma, None, eps, True, block_rows)
    return _xla_norm(x, gamma, None, eps, True)


# --------------------------------------------------------------------------
# module API — drop-in parity with apex.normalization
# --------------------------------------------------------------------------

import flax.linen as nn  # noqa: E402


def _flat_h(normalized_shape) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    h = 1
    for s in normalized_shape:
        h *= s
    return h


class FusedLayerNorm(nn.Module):
    """``apex.normalization.FusedLayerNorm(normalized_shape, eps,
    elementwise_affine, memory_efficient)`` equivalent (flax module).
    Multi-dim ``normalized_shape`` is flattened into the fused kernel's row
    axis, as the reference wrapper does. γ/β live in fp32 ("mixed" kernels).
    """

    normalized_shape: int | Sequence[int]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x):
        h = _flat_h(self.normalized_shape)
        orig = x.shape
        x = x.reshape(orig[: x.ndim - (1 if isinstance(
            self.normalized_shape, int) else len(self.normalized_shape))]
            + (h,))
        if self.elementwise_affine:
            gamma = self.param("scale", nn.initializers.ones, (h,),
                               jnp.float32)
            beta = self.param("bias", nn.initializers.zeros, (h,),
                              jnp.float32)
        else:
            gamma, beta = jnp.ones((h,), jnp.float32), None
        fn = functools.partial(layer_norm, eps=self.eps)
        if self.memory_efficient:
            fn = jax.checkpoint(fn)
        return fn(x, gamma, beta).reshape(orig)


class FusedRMSNorm(nn.Module):
    """``apex.normalization.FusedRMSNorm`` equivalent."""

    normalized_shape: int | Sequence[int]
    eps: float = 1e-6
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x):
        h = _flat_h(self.normalized_shape)
        orig = x.shape
        x = x.reshape(orig[: x.ndim - (1 if isinstance(
            self.normalized_shape, int) else len(self.normalized_shape))]
            + (h,))
        if self.elementwise_affine:
            gamma = self.param("scale", nn.initializers.ones, (h,),
                               jnp.float32)
        else:
            gamma = jnp.ones((h,), jnp.float32)
        fn = functools.partial(rms_norm, eps=self.eps)
        if self.memory_efficient:
            fn = jax.checkpoint(fn)
        return fn(x, gamma).reshape(orig)
