"""In-kernel stochasticity — counter-based dropout masks + the fused
bias-dropout-add(-LayerNorm) Pallas family.

Reference capability: the ``csrc/multihead_attn``/fmha kernels fuse
attention-probability dropout between softmax and AV inside every
forward/backward pair, and Megatron-style stacks fuse the
``bias_dropout_add`` residual epilogue (flash-attn's
``fused_dropout_add_ln``). The TPU-native answer is COUNTER-BASED masks:

- **no mask tensor is ever stored** — forward and backward both derive
  the keep mask from an int32 seed plus position counters (the same
  recompute-instead-of-save trade the flash kernels already make for
  probabilities), so dropout adds zero activation memory;
- **on TPU** the mask comes from the hardware PRNG: each kernel grid
  step re-seeds with ``pltpu.prng_seed(seed, salt, row0, col0)`` (salt ≙
  batch·H+head for attention, 0 for row kernels; row0/col0 are GLOBAL
  tile offsets) and draws one ``pltpu.prng_random_bits`` tile — streams
  are keyed on position, so the mask is independent of grid iteration
  order and of ring-shard visiting order, and context-parallel shards
  draw disjoint, shift-invariant streams (their global k-offset is
  folded into the counter);
- **off TPU** (Pallas interpret mode + the XLA composites, where the
  Mosaic PRNG primitives do not lower) the same counters feed a uint32
  avalanche hash evaluated per element at its GLOBAL position — the
  interpret-mode kernels and the XLA gold produce BIT-IDENTICAL masks,
  which is what makes the recompute-identity testable on the CPU suite.

Determinism contract (docs/perf_playbook.md "In-kernel dropout"): same
(seed, shape, positions) → bit-identical mask across calls and jit
boundaries, per backend. The mask is NOT bitwise-matched to a
``jax.random.bernoulli`` composite (different PRNG) — statistical
parity only; and the TPU hardware-PRNG mask differs bitwise from the
CPU hash mask (each is internally consistent between forward and
backward).

Seeds are PLAIN int32 words, not ``jax.random`` keys: deriving one per
call site via ``jax.random.randint(rng, (), 0, SEED_MAX)`` (or
``fold_seed`` for per-layer streams) is the sanctioned idiom — graftlint
APX103 knows a seed consumed by ``pltpu.prng_seed`` is not key reuse.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (as_rows, interpret_mode, mosaic_dtype,
                                   out_struct, pad_to, to_mosaic,
                                   use_pallas)
from apex1_tpu.ops.layer_norm import layer_norm, rms_norm
from apex1_tpu.tuning import tuned_row_block

SEED_MAX = 0x7FFFFFFF  # jax.random.randint upper bound for seed derivation

_GOLDEN = 0x9E3779B9   # 2^32/φ — Weyl increment for salting
_C_ROW = 0x85EBCA6B    # odd multipliers: murmur3 finalizer constants
_C_COL = 0xC2B2AE35


def _mix32(x):
    """'lowbias32' avalanche finalizer on uint32 lanes (bijective).
    Constants are NUMPY scalars: they fold into the kernel jaxpr as
    literals instead of captured traced constants (pallas_call rejects
    closure-captured arrays)."""
    x = x ^ (x >> 16)
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * np.uint32(0x846CA68B)
    return x ^ (x >> 16)


def hash_bits_u32(seed, salt, row, col):
    """Counter-based uint32 stream: one word per (seed, salt, row, col).

    ``seed``/``salt`` are int32 scalars (or broadcastable arrays);
    ``row``/``col`` int32 position counters. Chained bijective mixes:
    for a fixed (seed, salt) the map row→h is a bijection and col
    perturbs a fully-mixed word, so neighbouring positions decorrelate
    (keep-rate tests in tests/test_stochastic.py hold at p=0.1/0.5).
    The salt branch gets its own avalanche before row enters — salt and
    row must NOT be algebraically interchangeable, or (salt=a, row=b)
    and (salt=b, row=a) would draw identical streams and per-head masks
    would be pairwise correlated across (batch·head, q-row) pairs.
    """
    s = _mix32(jnp.asarray(seed).astype(jnp.uint32) + np.uint32(_GOLDEN))
    s = _mix32(s ^ jnp.asarray(salt).astype(jnp.uint32) * np.uint32(_C_ROW))
    h = _mix32(s ^ row.astype(jnp.uint32) * np.uint32(_C_ROW))
    return _mix32(h ^ col.astype(jnp.uint32) * np.uint32(_C_COL))


def threshold_u32(p: float) -> np.uint32:
    """Drop threshold: keep iff bits >= round(p·2^32) (uint32 compare).
    A numpy scalar (static per-trace), never a traced array — kernels
    consume it as a literal."""
    if not 0.0 < p < 1.0:
        raise ValueError(f"dropout p must be in (0, 1), got {p}")
    return np.uint32(min(int(round(p * 4294967296.0)), 0xFFFFFFFF))


def attn_keep_mask(seed, num_batch, num_heads, rows, cols, p):
    """Attention-probability keep mask at GLOBAL positions — the XLA
    composite analog of the kernels' tile draws. ``rows``/``cols`` are
    (Sq, Sk) int32 global-position grids (caller folds in its q/k
    offsets); returns bool (num_batch, num_heads, Sq, Sk).

    The single source of truth for the composite mask: the flash
    composite forward (`attention._xla_attention`) and the ring backward
    (`parallel.ring_attention`) both derive it here, so the
    forward/backward recompute identity cannot drift between files.
    Per-(batch, head) streams fold ``b·H + h`` into the salt — the same
    keying as the kernels."""
    shp = (num_batch, num_heads, 1, 1)
    salt = (jax.lax.broadcasted_iota(jnp.int32, shp, 0) * num_heads
            + jax.lax.broadcasted_iota(jnp.int32, shp, 1))
    bits = hash_bits_u32(jnp.asarray(seed, jnp.int32), salt,
                         rows[None, None], cols[None, None])
    return bits >= threshold_u32(p)


def tile_keep_mask(shape, thr, seed, salt, row0, col0, *, interp: bool):
    """(bool) keep mask for one kernel tile at GLOBAL offset (row0, col0).

    ``interp`` is the kernel's static interpret flag: on real TPU the
    tile is one hardware-PRNG draw seeded on the position counters; in
    interpret mode each element hashes its global position (bit-equal to
    the XLA composites' mask). Forward and backward kernels call this
    with identical arguments — that IS the recompute identity.
    """
    if interp:
        row = jax.lax.broadcasted_iota(jnp.int32, shape, 0) + row0
        col = jax.lax.broadcasted_iota(jnp.int32, shape, 1) + col0
        bits = hash_bits_u32(seed, salt, row, col)
    else:
        pltpu.prng_seed(seed, salt, row0, col0)
        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= thr


def seed_from_key(key):
    """Derive an int32 dropout seed from a ``jax.random`` key — the
    sanctioned call-site idiom (one consumption of the key; the seed
    itself is reused freely by forward+backward recompute)."""
    return jax.random.randint(key, (), 0, SEED_MAX, jnp.int32)


def fold_seed(seed, salt: int):
    """Per-site stream derivation from one base seed (≙ ``fold_in`` for
    int32 seeds): call sites that share a base seed MUST fold distinct
    static salts or they draw identical masks."""
    s = jnp.asarray(seed, jnp.int32).astype(jnp.uint32)
    s = _mix32(s + np.uint32((_GOLDEN * (salt + 1)) & 0xFFFFFFFF))
    # int32 seeds stay non-negative so they round-trip through SMEM refs
    # and jax.random.randint-derived seeds share the same value range
    return (s & np.uint32(SEED_MAX)).astype(jnp.int32)


# --------------------------------------------------------------------------
# fused bias + dropout + residual-add (row kernel)
# --------------------------------------------------------------------------

def _bda_fwd_kernel(seed_ref, x_ref, b_ref, r_ref, o_ref, *,
                    thr, inv_keep, br, interp):
    x = x_ref[...].astype(jnp.float32)
    if b_ref is not None:
        x = x + b_ref[...].astype(jnp.float32)
    keep = tile_keep_mask(x.shape, thr, seed_ref[0, 0], 0,
                          pl.program_id(0) * br, 0, interp=interp)
    y = jnp.where(keep, x * inv_keep, 0.0) + r_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _bda_bwd_kernel(seed_ref, dy_ref, dx_ref, db_ref, *,
                    thr, inv_keep, br, interp):
    dy = dy_ref[...].astype(jnp.float32)
    keep = tile_keep_mask(dy.shape, thr, seed_ref[0, 0], 0,
                          pl.program_id(0) * br, 0, interp=interp)
    dx = jnp.where(keep, dy * inv_keep, 0.0)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if db_ref is not None:
        @pl.when(pl.program_id(0) == 0)
        def _():
            db_ref[...] = jnp.zeros_like(db_ref)

        # padded rows carry zero dy — their contribution is exact zero
        db_ref[...] += jnp.sum(dx, axis=0, keepdims=True)


def _bda_prep(x, block_rows):
    x2, shape = as_rows(x)
    h = x2.shape[-1]
    br = tuned_row_block("bias_dropout_add", h, rows=x2.shape[0],
                         dtype=x.dtype, requested=block_rows)
    x2p, rows = pad_to(x2, 0, br)
    x2p, _ = pad_to(x2p, 1, 128)
    return x2p, shape, h, rows, br


def _bda_specs(h, br):
    row = pl.BlockSpec((br, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    smem = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    return row, vec, smem


def _bda_pallas_fwd(x2p, b2, r2p, seed, p, br):
    rows, hp = x2p.shape
    row, vec, smem = _bda_specs(hp, br)
    sarr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    kw = dict(thr=threshold_u32(p), inv_keep=1.0 / (1.0 - p), br=br,
              interp=interpret_mode())
    if b2 is not None:
        kernel = functools.partial(_bda_fwd_kernel, **kw)
        in_specs, args = [smem, row, vec, row], (sarr, x2p, b2, r2p)
    else:
        kernel = functools.partial(
            lambda sr, xr, rr, orf, **k: _bda_fwd_kernel(
                sr, xr, None, rr, orf, **k), **kw)
        in_specs, args = [smem, row, row], (sarr, x2p, r2p)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=in_specs,
        out_specs=row,
        out_shape=out_struct((rows, hp), x2p.dtype, x2p, r2p),
        interpret=interpret_mode(),
    )(*args)


def _bda_pallas_bwd(dy2p, seed, p, br, with_bias):
    rows, hp = dy2p.shape
    row, vec, smem = _bda_specs(hp, br)
    sarr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    kw = dict(thr=threshold_u32(p), inv_keep=1.0 / (1.0 - p), br=br,
              interp=interpret_mode())
    if with_bias:
        kernel = functools.partial(_bda_bwd_kernel, **kw)
        out_specs = (row, vec)
        out_shape = (out_struct((rows, hp), dy2p.dtype, dy2p),
                     out_struct((1, hp), jnp.float32, dy2p))
    else:
        kernel = functools.partial(
            lambda sr, dyr, dxr, **k: _bda_bwd_kernel(
                sr, dyr, dxr, None, **k), **kw)
        out_specs = row
        out_shape = out_struct((rows, hp), dy2p.dtype, dy2p)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=[smem, row],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret_mode(),
    )(sarr, dy2p)


def _bda_xla_mask(seed, rows, h):
    row = jax.lax.broadcasted_iota(jnp.int32, (rows, h), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (rows, h), 1)
    return hash_bits_u32(seed, 0, row, col)


def _bda_xla(x, residual, bias, seed, p):
    """XLA composite — the SAME counter hash at global positions, so the
    interpret-mode kernel and this gold are bit-identical on CPU."""
    x2, shape = as_rows(x)
    rows, h = x2.shape
    xb = x2.astype(jnp.float32)
    if bias is not None:
        xb = xb + bias.reshape(1, -1).astype(jnp.float32)
    keep = _bda_xla_mask(seed, rows, h) >= threshold_u32(p)
    r2, _ = as_rows(residual)
    y = (jnp.where(keep, xb * (1.0 / (1.0 - p)), 0.0)
         + r2.astype(jnp.float32))
    return y.astype(x.dtype).reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _bda(x, residual, bias, seed, p, has_bias, block_rows):
    return _bda_fwd(x, residual, bias, seed, p, has_bias, block_rows)[0]


def _bda_fwd(x, residual, bias, seed, p, has_bias, block_rows):
    x2p, shape, h, rows, br = _bda_prep(x, block_rows)
    r2, _ = as_rows(residual)
    r2p, _ = pad_to(r2, 0, br)
    r2p, _ = pad_to(r2p, 1, 128)
    b2 = (pad_to(bias.reshape(1, -1), 1, 128)[0] if has_bias else None)
    y = _bda_pallas_fwd(x2p, b2, r2p, seed, p, br)
    y = y[:rows, :h].reshape(shape)
    # dtype tokens (zero-size, never materialized) instead of the live
    # activations: the backward needs only the seed — that is the whole
    # zero-mask-storage point of the counter-based design
    return y, (seed, jnp.zeros((0,), residual.dtype),
               jnp.zeros((0,) + jnp.shape(bias)[1:], bias.dtype))


def _bda_bwd(p, has_bias, block_rows, res, dy):
    seed, rtok, btok = res
    xdtype = dy.dtype  # the fwd output carries x.dtype
    dy2, _ = as_rows(dy)
    h = dy2.shape[-1]
    br = tuned_row_block("bias_dropout_add", h, rows=dy2.shape[0],
                         dtype=xdtype, requested=block_rows)
    dy2p, rows = pad_to(dy2, 0, br)
    dy2p, _ = pad_to(dy2p, 1, 128)
    outs = _bda_pallas_bwd(dy2p.astype(xdtype), seed, p, br, has_bias)
    if has_bias:
        dx = outs[0][:rows, :h].reshape(dy.shape)
        db = outs[1][0, :h].astype(btok.dtype)
    else:
        dx = outs[:rows, :h].reshape(dy.shape)
        db = jnp.zeros((1,), btok.dtype)  # the dummy bias operand's ct
    f0 = np.zeros((), dtype=jax.dtypes.float0)
    return (dx.astype(xdtype), dy.astype(rtok.dtype), db, f0)


_bda.defvjp(_bda_fwd, _bda_bwd)


def fused_bias_dropout_add(x, residual, *, p: float, seed=None, bias=None,
                           block_rows: int | None = None):
    """``dropout(x + bias)/(1-p) + residual`` in one row-kernel pass —
    the Megatron ``bias_dropout_add`` / flash-attn ``dropout_add``
    epilogue, with the keep mask recomputed from ``seed`` in the
    backward (zero mask storage).

    ``p == 0.0`` lowers to the plain composite add (bit-for-bit the
    pre-existing epilogue — there is nothing stochastic to fuse).
    ``seed``: int32 scalar (required when p > 0); derive per call site
    via `seed_from_key` / `fold_seed` — two sites sharing a seed draw
    IDENTICAL masks. ``bias``: optional (H,) vector, differentiable.
    ``block_rows``: static rows-per-grid-step; None resolves tuning
    table > heuristic (kernel ``bias_dropout_add`` in tuning.registry).
    """
    if residual.shape != x.shape:
        raise ValueError(f"residual shape {residual.shape} != x shape "
                         f"{x.shape}")
    if bias is not None and bias.shape != (x.shape[-1],):
        raise ValueError(f"bias must be ({x.shape[-1]},), got "
                         f"{bias.shape}")
    p = float(p)
    if p == 0.0:
        y = x if bias is None else x + bias.astype(x.dtype)
        return y + residual.astype(x.dtype)
    if seed is None:
        raise ValueError("dropout p > 0 needs an explicit int32 seed "
                         "(seed_from_key/fold_seed at the call site)")
    if use_pallas():
        # fp16 is a storage dtype on TPU (Mosaic has no f16): compiled
        # kernels take bf16 and the result is cast back — identity off
        # TPU (see ops._common.mosaic_dtype)
        io_dtype = x.dtype
        kdt = mosaic_dtype(io_dtype)
        x, residual, bias = to_mosaic(x, residual, bias)
        dummy = jnp.zeros((1,), jnp.float32)
        out = _bda(x, residual, bias if bias is not None else dummy,
                   jnp.asarray(seed, jnp.int32), p, bias is not None,
                   block_rows)
        return out.astype(io_dtype) if kdt != io_dtype else out
    return _bda_xla(x, residual, bias, jnp.asarray(seed, jnp.int32), p)


def fused_dropout_add_layer_norm(x, residual, gamma, beta, *, p: float,
                                 seed=None, bias=None, eps: float = 1e-5,
                                 rms: bool = False, prenorm: bool = False,
                                 block_rows: int | None = None):
    """``LN(dropout(x + bias)/(1-p) + residual)`` — the reference's
    ``fused_dropout_add_ln`` / Megatron pre-LN residual epilogue. The
    dropout-add rides the row kernel above; the norm rides the existing
    Pallas LN (`apex1_tpu.ops.layer_norm`), so both memory-bound
    elementwise chains stay fused on TPU.

    ``prenorm=True`` also returns the pre-norm sum z (the residual
    stream the next layer consumes): ``(y, z)``; else just ``y``.
    ``rms=True`` swaps LayerNorm for RMSNorm (``beta`` ignored).
    """
    z = fused_bias_dropout_add(x, residual, p=p, seed=seed, bias=bias,
                               block_rows=block_rows)
    if rms:
        y = rms_norm(z, gamma, eps=eps)
    else:
        y = layer_norm(z, gamma, beta, eps=eps)
    return (y, z) if prenorm else y
