"""Chunked preference / distillation losses that never materialize logits.

The [B·S, V] logits tensor dominates fine-tuning memory: at Llama-3 vocab
(128256) one 8k-token batch is 4 GiB of fp32 logits — more than the model
shard.  Liger Kernel (arXiv 2410.10989) showed the fix: compute losses a
vocab-CHUNK at a time with online-softmax merging, and recompute each
chunk's logits inside the VJP instead of saving them.  This module is that
play on the `linear_xent` machinery:

- ``chunked_logprob`` — per-token log p(target) via per-chunk
  `shard_stats_packed` calls (the PR 9 packed-stats epilogue: one (T, 4)
  ``[m, l, tgt, sumx]`` stream per chunk) merged online, with a custom VJP
  that re-runs `shard_grads` per chunk.  The XLA path streams the same
  chunks through a `fori_loop` so even the composite never holds a
  (T, V) buffer — only one (T, chunk_v) tile is live at a time.
- ``chunked_dpo_loss`` / ``chunked_orpo_loss`` — preference losses
  composed from ``chunked_logprob`` by ordinary autodiff (the chunk
  recompute lives in the logprob VJP, so the preference algebra stays
  readable jnp).
- ``chunked_kl_loss`` — streaming KL(teacher ‖ student) distillation:
  a single pass carries both models' online-softmax stats plus the two
  cross moments ``Σ e^{s_t−m} s_t`` and ``Σ e^{s_t−m} s_s``, so the KL
  needs no second sweep and no logits tensor for either model.

Chunk geometry is priced by the shared `apex1_tpu.vmem_model`
(``CHECKS["chunked_loss"]``) and resolved with the documented precedence
(docs/ops.md): explicit ``chunk_v`` > tuning-table winner > heuristic.
``check_chunk_geometry`` raises loudly at trace time on misaligned or
over-budget chunks — same contract as `ops.paged_decode.check_paged_geometry`.

The no-materialization property is ASSERTED, not assumed: tier-1
(tests/test_chunked_loss.py) compiles grad(chunked_dpo_loss) and checks
both the optimized HLO (no (T, V)-shaped buffer anywhere) and, where the
backend reports it, AOT ``memory_analysis()`` peak temp bytes against the
chunk geometry bound.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.ops._common import NEG_INF, pad_to, use_pallas
from apex1_tpu.ops.linear_xent import shard_grads, shard_stats_packed

_LANES = 128


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def check_chunk_geometry(chunk_v: int, hidden: int, *, es: int = 4) -> int:
    """Validate a chunked-loss vocab chunk LOUDLY at trace time.

    Silent fallback on a bad explicit chunk would hide an OOM (or a
    mis-tuned table) until real-silicon runtime; instead this raises with
    the priced estimate so the failure names itself.  Mirrors
    `ops.paged_decode.check_paged_geometry`.
    """
    if chunk_v < _LANES or chunk_v % _LANES:
        raise ValueError(
            f"chunked_loss: chunk_v={chunk_v} must be a multiple of "
            f"{_LANES} (vocab tiles are lane-aligned)")
    from apex1_tpu.vmem_model import CHECKS, budget_bytes
    hp = _ceil_to(hidden, _LANES)
    ok, est = CHECKS["chunked_loss"]({"chunk_v": chunk_v}, {"Hp": hp},
                                     es, budget_bytes())
    if not ok:
        raise ValueError(
            f"chunked_loss: chunk_v={chunk_v} (Hp={hp}) prices at ~{est} B"
            f" of VMEM > budget {budget_bytes()} B; shrink chunk_v or"
            f" re-tune (tools/tune_kernels.py)")
    return chunk_v


def _auto_chunk(V: int, H: int, chunk_v, dtype) -> int:
    """Resolve chunk_v: explicit > tuning table > heuristic (docs/ops.md)."""
    hp = _ceil_to(H, _LANES)
    if chunk_v is not None:
        return check_chunk_geometry(int(chunk_v), H)
    from apex1_tpu import tuning
    hit = tuning.lookup("chunked_loss", {"Hp": hp}, dtype)
    if hit is not None:
        try:
            return check_chunk_geometry(int(hit["chunk_v"]), H)
        except (KeyError, ValueError):
            pass  # fail-safe: a stale table entry falls back to heuristic
    return min(_ceil_to(V, _LANES), 8192)


def _chunks(V: int, cv: int) -> int:
    return -(-V // cv)


# ---------------------------------------------------------------------------
# chunked_logprob: per-token log p(target) with per-chunk-recompute VJP
# ---------------------------------------------------------------------------


def _merge_stats(m, l, tgt, mc, lc, tc):
    """Online-softmax merge of one chunk's (m, l) into the running pair;
    tgt is exact per chunk (out-of-chunk labels contribute 0) so it sums."""
    mn = jnp.maximum(m, mc)
    l = l * jnp.exp(m - mn) + lc * jnp.exp(mc - mn)
    return mn, l, tgt + tc


def _pallas_stats(x2, wp, t2, n_c, cv, k, block_t, block_v):
    T = x2.shape[0]
    tcol = t2.reshape(T, 1)  # the kernels tile targets as (bt, 1)

    def body(c, carry):
        wc = jax.lax.dynamic_slice_in_dim(wp, c * cv, cv, 0)
        pk = shard_stats_packed(x2, wc, tcol, col_offset=c * cv,
                                num_classes=k, block_t=block_t,
                                block_v=block_v)
        return _merge_stats(*carry, pk[:, 0], pk[:, 1], pk[:, 2])

    init = (jnp.full((T,), NEG_INF, jnp.float32),
            jnp.zeros((T,), jnp.float32), jnp.zeros((T,), jnp.float32))
    return jax.lax.fori_loop(0, n_c, body, init)


def _xla_stats(x2, wp, t2, n_c, cv, k):
    """Composite gold — SAME streaming structure as the kernel path: a
    fori_loop whose only live tile is the (T, cv) chunk, so the CPU proxy
    exhibits (and tier-1 can assert) the no-logits-tensor property."""
    T = x2.shape[0]
    xf = x2.astype(jnp.float32)
    tcol = t2.reshape(T, 1)

    def body(c, carry):
        wc = jax.lax.dynamic_slice_in_dim(wp, c * cv, cv, 0)
        s = xf @ wc.astype(jnp.float32).T  # (T, cv): the ONLY logits tile
        gcol = c * cv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = gcol < k
        sm = jnp.where(valid, s, NEG_INF)
        mc = jnp.max(sm, axis=1)
        m, l, tgt = carry
        mn = jnp.maximum(m, mc)
        l = (l * jnp.exp(m - mn)
             + jnp.sum(jnp.where(valid, jnp.exp(sm - mn[:, None]), 0.0),
                       axis=1))
        tgt = tgt + jnp.sum(jnp.where(gcol == tcol, s, 0.0), axis=1)
        return mn, l, tgt

    init = (jnp.full((T,), NEG_INF, jnp.float32),
            jnp.zeros((T,), jnp.float32), jnp.zeros((T,), jnp.float32))
    return jax.lax.fori_loop(0, n_c, body, init)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _logprob(x2, weight, t2, chunk_v, num_classes, block_t, block_v):
    return _logprob_fwd(x2, weight, t2, chunk_v, num_classes,
                        block_t, block_v)[0]


def _logprob_fwd(x2, weight, t2, chunk_v, num_classes, block_t, block_v):
    V = weight.shape[0]
    k = num_classes if num_classes is not None else V
    wp, _ = pad_to(weight, 0, chunk_v)
    n_c = _chunks(V, chunk_v)
    if use_pallas():
        m, l, tgt = _pallas_stats(x2, wp, t2, n_c, chunk_v, k,
                                  block_t, block_v)
    else:
        m, l, tgt = _xla_stats(x2, wp, t2, n_c, chunk_v, k)
    lse = m + jnp.log(l)
    return tgt - lse, (x2, weight, t2, lse)


def _logprob_bwd(chunk_v, num_classes, block_t, block_v, res, g):
    x2, weight, t2, lse = res
    T = x2.shape[0]
    V = weight.shape[0]
    k = num_classes if num_classes is not None else V
    cv = chunk_v
    n_c = _chunks(V, cv)
    wp, _ = pad_to(weight, 0, cv)
    Vp = wp.shape[0]
    # loss = lse − tgt (smoothing 0) has logp = −loss, so the chunk
    # gradient machinery consumes the NEGATED cotangent.
    dl = (-g).astype(jnp.float32)
    dx0 = jnp.zeros(x2.shape, jnp.float32)
    dw0 = jnp.zeros((Vp, x2.shape[1]), jnp.float32)

    if use_pallas():
        tcol = t2.reshape(T, 1)  # the kernels tile targets as (bt, 1)

        def body(c, carry):
            dx, dwp = carry
            wc = jax.lax.dynamic_slice_in_dim(wp, c * cv, cv, 0)
            dxc, dwc = shard_grads(x2, wc, tcol, lse, dl,
                                   col_offset=c * cv,
                                   num_classes=k, block_t=block_t,
                                   block_v=block_v)
            dwp = jax.lax.dynamic_update_slice_in_dim(
                dwp, dwc.astype(jnp.float32), c * cv, 0)
            return dx + dxc.astype(jnp.float32), dwp
    else:
        xf = x2.astype(jnp.float32)
        tcol = t2.reshape(T, 1)

        def body(c, carry):
            dx, dwp = carry
            wc = jax.lax.dynamic_slice_in_dim(wp, c * cv, cv, 0)
            wcf = wc.astype(jnp.float32)
            s = xf @ wcf.T  # recompute: the only live (T, cv) tile
            gcol = c * cv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            valid = gcol < k
            p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
            onehot = jnp.where(valid & (gcol == tcol), 1.0, 0.0)
            gt = (p - onehot) * dl[:, None]
            dwp = jax.lax.dynamic_update_slice_in_dim(
                dwp, gt.T @ xf, c * cv, 0)
            return dx + gt @ wcf, dwp

    dx, dwp = jax.lax.fori_loop(0, n_c, body, (dx0, dw0))
    f0 = np.zeros(t2.shape, dtype=jax.dtypes.float0)
    return (dx.astype(x2.dtype), dwp[:V].astype(weight.dtype), f0)


_logprob.defvjp(_logprob_fwd, _logprob_bwd)


def chunked_logprob(x, weight, targets, *, chunk_v=None, num_classes=None,
                    block_t=None, block_v=None):
    """Per-token ``log p(target)`` of ``softmax(x @ weightᵀ)`` without a
    logits tensor — ``x`` (..., H), ``weight`` (V, H), ``targets`` (...,)
    int.  Returns (...,) fp32.  Differentiable in ``x`` and ``weight``;
    the VJP recomputes each vocab chunk (never saves logits)."""
    lead = targets.shape
    H = x.shape[-1]
    x2 = x.reshape(-1, H)
    t2 = targets.reshape(-1).astype(jnp.int32)
    cv = _auto_chunk(weight.shape[0], H, chunk_v, x.dtype)
    lp = _logprob(x2, weight, t2, cv, num_classes, block_t, block_v)
    return lp.reshape(lead)


# ---------------------------------------------------------------------------
# Preference losses (DPO / ORPO) — composed from chunked_logprob
# ---------------------------------------------------------------------------


def _seq_logp(hidden, weight, targets, padding_idx, kw):
    lp = chunked_logprob(hidden, weight, targets, **kw)
    if padding_idx is not None:
        mask = (targets != padding_idx).astype(jnp.float32)
    else:
        mask = jnp.ones(targets.shape, jnp.float32)
    return jnp.sum(lp * mask, axis=-1), jnp.sum(mask, axis=-1)


def chunked_dpo_loss(hidden_chosen, hidden_rejected, weight,
                     targets_chosen, targets_rejected,
                     ref_chosen_logp, ref_rejected_logp, *,
                     beta: float = 0.1, padding_idx=None, num_classes=None,
                     chunk_v=None, block_t=None, block_v=None):
    """DPO loss (Rafailov et al.) over chunked per-sequence logps.

    ``hidden_*`` (B, S, H) policy hidden states, ``targets_*`` (B, S) int,
    ``ref_*_logp`` (B,) PRE-COMPUTED reference-policy sequence logps
    (compute them with ``chunked_logprob`` under ``stop_gradient`` — the
    reference model needs no VJP).  Returns the scalar mean
    ``−log σ(β·((π_c − π_r) − (ref_c − ref_r)))``.
    """
    kw = dict(num_classes=num_classes, chunk_v=chunk_v,
              block_t=block_t, block_v=block_v)
    seq_c, _ = _seq_logp(hidden_chosen, weight, targets_chosen,
                         padding_idx, kw)
    seq_r, _ = _seq_logp(hidden_rejected, weight, targets_rejected,
                         padding_idx, kw)
    margin = beta * ((seq_c - seq_r)
                     - (ref_chosen_logp - ref_rejected_logp))
    return -jnp.mean(jax.nn.log_sigmoid(margin))


def _log_odds(avg_logp):
    """log(p / (1−p)) from an average token logp, clamped away from the
    p→1 pole (degenerate sequences with probability ~1)."""
    p = jnp.clip(jnp.exp(avg_logp), None, 1.0 - 1e-6)
    return avg_logp - jnp.log1p(-p)


def chunked_orpo_loss(hidden_chosen, hidden_rejected, weight,
                      targets_chosen, targets_rejected, *,
                      lam: float = 0.1, padding_idx=None, num_classes=None,
                      chunk_v=None, block_t=None, block_v=None):
    """ORPO (Hong et al.): chosen-NLL plus λ·odds-ratio penalty, both from
    chunked logps (no reference model, no logits tensor).  Returns the
    scalar ``mean(NLL_c) + λ·mean(−log σ(log-odds(avg_c) − log-odds(avg_r)))``.
    """
    kw = dict(num_classes=num_classes, chunk_v=chunk_v,
              block_t=block_t, block_v=block_v)
    seq_c, len_c = _seq_logp(hidden_chosen, weight, targets_chosen,
                             padding_idx, kw)
    seq_r, len_r = _seq_logp(hidden_rejected, weight, targets_rejected,
                             padding_idx, kw)
    len_c = jnp.maximum(len_c, 1.0)
    len_r = jnp.maximum(len_r, 1.0)
    nll = -seq_c / len_c
    ratio = _log_odds(seq_c / len_c) - _log_odds(seq_r / len_r)
    return jnp.mean(nll) + lam * jnp.mean(-jax.nn.log_sigmoid(ratio))


# ---------------------------------------------------------------------------
# Streaming KL distillation
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _kl(xs2, ws, xt2, wt, chunk_v, num_classes, temperature):
    return _kl_fwd(xs2, ws, xt2, wt, chunk_v, num_classes, temperature)[0]


def _kl_fwd(xs2, ws, xt2, wt, cv, num_classes, temp):
    T = xs2.shape[0]
    V = ws.shape[0]
    k = num_classes if num_classes is not None else V
    n_c = _chunks(V, cv)
    wsp, _ = pad_to(ws, 0, cv)
    wtp, _ = pad_to(wt, 0, cv)
    xsf = xs2.astype(jnp.float32) / temp
    xtf = xt2.astype(jnp.float32) / temp

    def body(c, carry):
        m_s, l_s, m_t, l_t, u_tt, u_ts = carry
        wsc = jax.lax.dynamic_slice_in_dim(wsp, c * cv, cv, 0)
        wtc = jax.lax.dynamic_slice_in_dim(wtp, c * cv, cv, 0)
        ss = xsf @ wsc.astype(jnp.float32).T  # (T, cv)
        st = xtf @ wtc.astype(jnp.float32).T
        gcol = c * cv + jax.lax.broadcasted_iota(jnp.int32, ss.shape, 1)
        valid = gcol < k
        ssm = jnp.where(valid, ss, NEG_INF)
        stm = jnp.where(valid, st, NEG_INF)
        mn_s = jnp.maximum(m_s, jnp.max(ssm, axis=1))
        l_s = (l_s * jnp.exp(m_s - mn_s)
               + jnp.sum(jnp.where(valid, jnp.exp(ssm - mn_s[:, None]), 0.0),
                         axis=1))
        mn_t = jnp.maximum(m_t, jnp.max(stm, axis=1))
        corr = jnp.exp(m_t - mn_t)
        e_t = jnp.where(valid, jnp.exp(stm - mn_t[:, None]), 0.0)
        l_t = l_t * corr + jnp.sum(e_t, axis=1)
        # cross moments under the TEACHER measure, exp-corrected like l_t
        u_tt = u_tt * corr + jnp.sum(e_t * jnp.where(valid, st, 0.0), axis=1)
        u_ts = u_ts * corr + jnp.sum(e_t * jnp.where(valid, ss, 0.0), axis=1)
        return mn_s, l_s, mn_t, l_t, u_tt, u_ts

    neg = jnp.full((T,), NEG_INF, jnp.float32)
    zero = jnp.zeros((T,), jnp.float32)
    m_s, l_s, m_t, l_t, u_tt, u_ts = jax.lax.fori_loop(
        0, n_c, body, (neg, zero, neg, zero, zero, zero))
    lse_s = m_s + jnp.log(l_s)
    lse_t = m_t + jnp.log(l_t)
    # KL = Σ_v p_t (s_t − s_s) − lse_t + lse_s with Σ p_t s_• = u_t• / l_t
    kl = (u_tt - u_ts) / l_t - lse_t + lse_s
    return kl, (xs2, ws, xt2, wt, lse_s, lse_t)


def _kl_bwd(cv, num_classes, temp, res, g):
    xs2, ws, xt2, wt, lse_s, lse_t = res
    T = xs2.shape[0]
    V = ws.shape[0]
    k = num_classes if num_classes is not None else V
    n_c = _chunks(V, cv)
    wsp, _ = pad_to(ws, 0, cv)
    wtp, _ = pad_to(wt, 0, cv)
    Vp = wsp.shape[0]
    xsf = xs2.astype(jnp.float32) / temp
    xtf = xt2.astype(jnp.float32) / temp
    xs_raw = xs2.astype(jnp.float32)
    gl = (g.astype(jnp.float32) / temp)[:, None]

    def body(c, carry):
        dx, dwp = carry
        wsc = jax.lax.dynamic_slice_in_dim(wsp, c * cv, cv, 0)
        wtc = jax.lax.dynamic_slice_in_dim(wtp, c * cv, cv, 0)
        wscf = wsc.astype(jnp.float32)
        ss = xsf @ wscf.T  # recompute (T, cv) — never saved
        st = xtf @ wtc.astype(jnp.float32).T
        gcol = c * cv + jax.lax.broadcasted_iota(jnp.int32, ss.shape, 1)
        valid = gcol < k
        ps = jnp.where(valid, jnp.exp(ss - lse_s[:, None]), 0.0)
        pt = jnp.where(valid, jnp.exp(st - lse_t[:, None]), 0.0)
        gt = (ps - pt) * gl  # dKL/ds_s = p_s − p_t, scaled by g / T
        dwp = jax.lax.dynamic_update_slice_in_dim(
            dwp, gt.T @ xs_raw, c * cv, 0)
        return dx + gt @ wscf, dwp

    dx0 = jnp.zeros(xs2.shape, jnp.float32)
    dw0 = jnp.zeros((Vp, xs2.shape[1]), jnp.float32)
    dx, dwp = jax.lax.fori_loop(0, n_c, body, (dx0, dw0))
    # teacher is stop-grad by construction: zero cotangents
    return (dx.astype(xs2.dtype), dwp[:V].astype(ws.dtype),
            jnp.zeros_like(xt2), jnp.zeros_like(wt))


_kl.defvjp(_kl_fwd, _kl_bwd)


def chunked_kl_loss(student_hidden, student_weight, teacher_hidden,
                    teacher_weight, *, temperature: float = 1.0,
                    num_classes=None, chunk_v=None):
    """Per-token ``KL(teacher ‖ student)`` over temperature-scaled heads,
    streamed a vocab chunk at a time (neither model's logits tensor ever
    exists).  ``*_hidden`` (..., H), ``*_weight`` (V, H); returns (...,)
    fp32.  Teacher inputs are stop-grad (zero cotangents); the student VJP
    recomputes both chunks per step.  Both dispatch paths run the same
    streamed jnp chunks — the chunking (not a bespoke kernel) is the win,
    and XLA's MXU matmuls inside the loop are already optimal."""
    lead = student_hidden.shape[:-1]
    H = student_hidden.shape[-1]
    if teacher_weight.shape[0] != student_weight.shape[0]:
        raise ValueError(
            f"chunked_kl_loss: student V={student_weight.shape[0]} != "
            f"teacher V={teacher_weight.shape[0]} (distill over one vocab)")
    xs2 = student_hidden.reshape(-1, H)
    xt2 = teacher_hidden.reshape(-1, teacher_hidden.shape[-1])
    cv = _auto_chunk(student_weight.shape[0], H, chunk_v,
                     student_hidden.dtype)
    kl = _kl(xs2, student_weight, xt2, teacher_weight, cv, num_classes,
             float(temperature))
    return kl.reshape(lead)
