"""Fused scale+mask+softmax — Pallas TPU kernels.

Reference: ``csrc/megatron/scaled_masked_softmax{,_cuda}.cu``,
``scaled_upper_triang_masked_softmax*``, ``generic_scaled_masked_softmax*``
(warp-level fused fwd+bwd, seqlen-specialized), exposed through
``apex/transformer/functional/fused_softmax.py :: FusedScaleMaskSoftmax``.

Semantics:
    y  = softmax(scale * x + mask)        (mask additive, -inf-style)
    causal ("upper_triang") variant applies the upper-triangular -inf mask
    dx = scale * y * (dy - Σ_k dy·y)      (saved: y — same as reference bwd)

TPU design: scores are processed as (B, H, Sq, Sk) blocks — grid
(B, H, Sq-blocks) with the key axis as the lane dim — so a broadcast mask
(B, 1, Sq, Sk) is indexed per block and never materialized at full
(B, H, Sq, Sk) size. Padded key lanes are excluded from the sum (zeroed
after exp), so fully-masked rows match the XLA gold exactly. The
seqlen-specialized CUDA templates (≤2k/4k) are unnecessary — one kernel
serves all sizes via the grid.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (NEG_INF, interpret_mode, out_struct,
                                   pad_to, use_pallas)
from apex1_tpu.tuning import tuned_row_block


def _fwd_kernel(x_ref, mask_ref, y_ref, *, scale, causal, true_k):
    x = x_ref[...].astype(jnp.float32) * scale  # (1, 1, BQ, K)
    if mask_ref is not None:
        x = x + mask_ref[...].astype(jnp.float32)  # broadcasts over dims of 1
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 3)
    if causal:
        q0 = pl.program_id(2) * x.shape[2]
        q_idx = q0 + jax.lax.broadcasted_iota(jnp.int32, x.shape, 2)
        x = jnp.where(col > q_idx, NEG_INF, x)
    m = jnp.max(x, axis=3, keepdims=True)
    e = jnp.exp(x - m)
    if true_k != x.shape[3]:
        e = jnp.where(col < true_k, e, 0.0)  # padded lanes leave the sum
    s = jnp.sum(e, axis=3, keepdims=True)
    y_ref[...] = (e / s).astype(y_ref.dtype)


def _bwd_kernel(y_ref, dy_ref, dx_ref, *, scale):
    y = y_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    dot = jnp.sum(y * dy, axis=1, keepdims=True)
    dx_ref[...] = (scale * y * (dy - dot)).astype(dx_ref.dtype)


def _pallas_softmax_fwd(x4, mask4, scale, causal, true_k, bq):
    b, h, sq, k = x4.shape
    x_spec = pl.BlockSpec((1, 1, bq, k),
                          lambda bi, hi, qi: (bi, hi, qi, 0),
                          memory_space=pltpu.VMEM)
    grid = (b, h, pl.cdiv(sq, bq))
    if mask4 is not None:
        mb, mh, msq, msk = mask4.shape
        mq_block = bq if msq != 1 else 1
        mk_block = k if msk != 1 else 1  # size-1 key dim stays broadcast

        def mask_index(bi, hi, qi):
            return (bi if mb != 1 else 0, hi if mh != 1 else 0,
                    qi if msq != 1 else 0, 0)

        m_spec = pl.BlockSpec((1, 1, mq_block, mk_block), mask_index,
                              memory_space=pltpu.VMEM)
        kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                                   true_k=true_k)
        in_specs, args = [x_spec, m_spec], (x4, mask4)
    else:
        kernel = functools.partial(
            lambda xr, yr, **kw: _fwd_kernel(xr, None, yr, **kw),
            scale=scale, causal=causal, true_k=true_k)
        in_specs, args = [x_spec], (x4,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=x_spec,
        out_shape=out_struct(x4.shape, x4.dtype, *args),
        interpret=interpret_mode(),
    )(*args)


def _pallas_softmax_bwd(y2, dy2, scale, bq):
    rows, k = y2.shape
    row = pl.BlockSpec((bq, k), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(pl.cdiv(rows, bq),),
        in_specs=[row, row],
        out_specs=row,
        out_shape=out_struct((rows, k), y2.dtype, y2, dy2),
        interpret=interpret_mode(),
    )(y2, dy2)


def _as4d(x):
    """(..., sq, sk) -> (B, H, sq, sk) with leading dims split B=prod[:-3]."""
    shape = x.shape
    if x.ndim == 2:
        return x.reshape(1, 1, *shape), shape
    if x.ndim == 3:
        return x.reshape(shape[0], 1, shape[1], shape[2]), shape
    b = 1
    for s in shape[:-3]:
        b *= s
    return x.reshape(b, shape[-3], shape[-2], shape[-1]), shape


def _mask4d(mask, x_shape4):
    """Reshape a broadcastable mask to 4-D with dims in {1, full}."""
    b, h, sq, sk = x_shape4
    mshape = mask.shape
    # left-pad to 4 dims
    m = mask.reshape((1,) * (4 - mask.ndim) + mshape) if mask.ndim < 4 \
        else mask.reshape((-1,) + mshape[-3:])
    for ax, full in enumerate((b, h, sq, sk)):
        if m.shape[ax] not in (1, full):
            raise ValueError(
                f"mask shape {mask.shape} not broadcastable to {x_shape4}")
    return m


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused_softmax(x, mask, scale, causal, block_rows):
    return _fused_softmax_fwd(x, mask, scale, causal, block_rows)[0]


def _fused_softmax_fwd(x, mask, scale, causal, block_rows):
    x4, shape = _as4d(x)
    true_k = x4.shape[-1]
    bq = tuned_row_block("fused_softmax", x4.shape[3], rows=x4.shape[2],
                         dtype=x.dtype, requested=block_rows)
    x4p, sq = pad_to(x4, 2, bq)
    x4p, _ = pad_to(x4p, 3, 128)
    if mask is not None:
        m4 = _mask4d(mask, x4.shape)
        if m4.shape[2] != 1:
            m4, _ = pad_to(m4, 2, bq)
        if m4.shape[3] != 1:  # size-1 key dim rides kernel broadcast
            m4, _ = pad_to(m4, 3, 128)
    else:
        m4 = None
    y = _pallas_softmax_fwd(x4p, m4, scale, causal, true_k, bq)
    y = y[:, :, :sq, :true_k].reshape(shape)
    return y, y


def _fused_softmax_bwd(scale, causal, block_rows, y, dy):
    y2 = y.reshape(-1, y.shape[-1])
    true_k = y2.shape[1]
    bq = tuned_row_block("fused_softmax", y2.shape[1], rows=y2.shape[0],
                         dtype=y.dtype, requested=block_rows)
    y2p, rows = pad_to(y2, 0, bq)
    y2p, _ = pad_to(y2p, 1, 128)
    dy2 = dy.reshape(-1, dy.shape[-1])
    dy2p, _ = pad_to(dy2, 0, bq)
    dy2p, _ = pad_to(dy2p, 1, 128)
    dx = _pallas_softmax_bwd(y2p, dy2p, scale, bq)
    dx = dx[:rows, :true_k].reshape(y.shape)
    return dx, None


_fused_softmax.defvjp(_fused_softmax_fwd, _fused_softmax_bwd)


def _xla_softmax(x, mask, scale, causal):
    x32 = x.astype(jnp.float32) * scale
    if mask is not None:
        x32 = x32 + mask.astype(jnp.float32)
    if causal:
        sq, sk = x.shape[-2], x.shape[-1]
        q = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kk = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        x32 = jnp.where(kk > q, NEG_INF, x32)
    return jax.nn.softmax(x32, axis=-1).astype(x.dtype)


def scaled_masked_softmax(x, mask=None, *, scale: float = 1.0,
                          block_rows: int | None = None):
    """``scaled_masked_softmax_cuda`` equivalent.

    ``x``: (..., sq, sk) attention scores; ``mask``: additive mask
    broadcastable to ``x`` (use large negative values for masked positions,
    e.g. ``ops.NEG_INF``) — broadcast dims stay size-1 all the way into the
    kernel. ``block_rows``: static rows-per-grid-step; ``None`` resolves
    tuning table > heuristic (`apex1_tpu.tuning.tuned_row_block`).
    """
    if use_pallas():
        return _fused_softmax(x, mask, float(scale), False, block_rows)
    return _xla_softmax(x, mask, scale, False)


def scaled_upper_triang_masked_softmax(x, *, scale: float = 1.0,
                                       block_rows: int | None = None):
    """``scaled_upper_triang_masked_softmax_cuda`` equivalent (causal)."""
    if use_pallas():
        return _fused_softmax(x, None, float(scale), True, block_rows)
    return _xla_softmax(x, None, scale, True)


class FusedScaleMaskSoftmax:
    """API-parity adapter — reference ``apex/transformer/functional/
    fused_softmax.py :: FusedScaleMaskSoftmax`` (chooses kernel vs fallback
    via ``is_kernel_available``; here dispatch is `_common.use_pallas`).

    ``attn_mask_type``: "causal" or "padding" (or the
    `transformer.enums.AttnMaskType` enum).
    """

    def __init__(self, attn_mask_type="padding",
                 scale: float | None = None,
                 scaled_masked_softmax_fusion: bool = True):
        if hasattr(attn_mask_type, "name"):  # AttnMaskType enum
            attn_mask_type = attn_mask_type.name
        self.attn_mask_type = attn_mask_type
        self.scale = 1.0 if scale is None else scale
        self.fusion = scaled_masked_softmax_fusion

    def is_kernel_available(self, *_):
        return self.fusion and use_pallas()

    def __call__(self, x, mask=None):
        if self.attn_mask_type == "causal":
            return scaled_upper_triang_masked_softmax(x, scale=self.scale)
        return scaled_masked_softmax(x, mask, scale=self.scale)
