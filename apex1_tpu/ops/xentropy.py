"""Fused softmax cross-entropy with label smoothing — Pallas TPU kernels.

Reference: ``apex/contrib/csrc/xentropy/xentropy_kernel.cu`` wrapped by
``apex/contrib/xentropy/softmax_xentropy.py :: SoftmaxCrossEntropyLoss``.

The reference's win is ACTIVATION MEMORY: forward saves only per-row
stats (not the softmax probabilities); backward recomputes ``softmax(x)``
from logits + the saved logsumexp and writes the gradient "in-place" into
the logits buffer. Exactly reproduced here: residuals are
``(logits, labels, lse)`` and the bwd kernel recomputes ``exp(x - lse)`` —
for a 50k+ vocab this saves the full (tokens × vocab) probability tensor. (With
``jax.jit`` donation the dx buffer aliases the logits buffer, matching the
in-place trick.)

Loss (label smoothing ε, ``smoothing``):
    loss_i = (1-ε) * (lse_i - x_i[t_i]) + ε * (lse_i - mean_k x_i[k])
    dx_i   = softmax(x_i) - (1-ε)·onehot(t_i) - ε/K
``padding_idx`` rows (``ignore_index``) produce loss 0 and zero grad.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import (NEG_INF, interpret_mode, out_struct,
                                   pad_to, use_pallas)
from apex1_tpu.tuning import tuned_row_block



def _fwd_kernel(x_ref, t_ref, loss_ref, lse_ref, *,
                smoothing, true_k, padding_idx):
    x = x_ref[...].astype(jnp.float32)
    t = t_ref[...]  # (rows, 1) int32
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = col < true_k
    xm = jnp.where(valid, x, NEG_INF)
    m = jnp.max(xm, axis=1, keepdims=True)
    e = jnp.where(valid, jnp.exp(xm - m), 0.0)
    s = jnp.sum(e, axis=1, keepdims=True)
    lse = m + jnp.log(s)
    tgt_logit = jnp.sum(jnp.where(col == t, x, 0.0), axis=1, keepdims=True)
    sum_x = jnp.sum(jnp.where(valid, x, 0.0), axis=1, keepdims=True)
    loss = ((1.0 - smoothing) * (lse - tgt_logit)
            + smoothing * (lse - sum_x / true_k))
    if padding_idx is not None:
        loss = jnp.where(t == padding_idx, 0.0, loss)
    loss_ref[...] = loss
    lse_ref[...] = lse


def _bwd_kernel(x_ref, t_ref, lse_ref, dloss_ref, dx_ref, *,
                smoothing, true_k, padding_idx):
    x = x_ref[...].astype(jnp.float32)
    t = t_ref[...]
    lse = lse_ref[...]
    dloss = dloss_ref[...]
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = col < true_k
    p = jnp.where(valid, jnp.exp(x - lse), 0.0)  # recomputed softmax
    grad = p - (1.0 - smoothing) * (col == t) - smoothing / true_k
    grad = jnp.where(valid, grad, 0.0)
    if padding_idx is not None:
        dloss = jnp.where(t == padding_idx, 0.0, dloss)
    dx_ref[...] = (grad * dloss).astype(dx_ref.dtype)


def _specs(k, br):
    row = pl.BlockSpec((br, k), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    stat = pl.BlockSpec((br, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return row, stat


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _fused_xent(logits, labels, smoothing, padding_idx, num_classes,
                block_rows):
    return _fused_xent_fwd(logits, labels, smoothing, padding_idx,
                           num_classes, block_rows)[0]


def _fused_xent_fwd(logits, labels, smoothing, padding_idx, num_classes,
                    block_rows):
    shape = logits.shape
    k = shape[-1] if num_classes is None else num_classes
    x2 = logits.reshape(-1, shape[-1])
    t2 = labels.reshape(-1, 1).astype(jnp.int32)
    br = tuned_row_block("xentropy", x2.shape[1], rows=x2.shape[0],
                         dtype=logits.dtype, requested=block_rows)
    x2p, rows = pad_to(x2, 0, br)
    x2p, _ = pad_to(x2p, 1, 128)
    t2p, _ = pad_to(t2, 0, br, value=-1)
    row, stat = _specs(x2p.shape[1], br)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, smoothing=smoothing, true_k=k,
                          padding_idx=padding_idx),
        grid=(pl.cdiv(x2p.shape[0], br),),
        in_specs=[row, stat],
        out_specs=(stat, stat),
        out_shape=(out_struct((x2p.shape[0], 1), jnp.float32, x2p, t2p),
                   out_struct((x2p.shape[0], 1), jnp.float32, x2p, t2p)),
        interpret=interpret_mode(),
    )(x2p, t2p)
    loss = loss[:rows, 0].reshape(shape[:-1])
    return loss, (logits, labels, lse)


def _fused_xent_bwd(smoothing, padding_idx, num_classes, block_rows, res,
                    dloss):
    logits, labels, lse = res
    shape = logits.shape
    k = shape[-1] if num_classes is None else num_classes
    x2 = logits.reshape(-1, shape[-1])
    t2 = labels.reshape(-1, 1).astype(jnp.int32)
    d2 = dloss.reshape(-1, 1).astype(jnp.float32)
    br = tuned_row_block("xentropy", x2.shape[1], rows=x2.shape[0],
                         dtype=logits.dtype, requested=block_rows)
    x2p, rows = pad_to(x2, 0, br)
    x2p, _ = pad_to(x2p, 1, 128)
    t2p, _ = pad_to(t2, 0, br, value=-1)
    d2p, _ = pad_to(d2, 0, br)
    row, stat = _specs(x2p.shape[1], br)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, smoothing=smoothing, true_k=k,
                          padding_idx=padding_idx),
        grid=(pl.cdiv(x2p.shape[0], br),),
        in_specs=[row, stat, stat, stat],
        out_specs=row,
        out_shape=out_struct(x2p.shape, logits.dtype, x2p, t2p, lse, d2p),
        interpret=interpret_mode(),
    )(x2p, t2p, lse, d2p)
    return dx[:rows, :shape[-1]].reshape(shape), None


_fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)


def _xla_xent(logits, labels, smoothing, padding_idx, num_classes=None):
    if num_classes is not None and num_classes != logits.shape[-1]:
        logits = logits[..., :num_classes]
    x = logits.astype(jnp.float32)
    k = x.shape[-1]
    lse = jax.nn.logsumexp(x, axis=-1, keepdims=True)
    tgt = jnp.take_along_axis(x, labels[..., None].astype(jnp.int32),
                              axis=-1)
    loss = ((1.0 - smoothing) * (lse - tgt)
            + smoothing * (lse - jnp.mean(x, axis=-1, keepdims=True)))
    loss = loss[..., 0]
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    return loss


def softmax_cross_entropy_loss(logits, labels, *, smoothing: float = 0.0,
                               padding_idx: int | None = None,
                               num_classes: int | None = None,
                               block_rows: int | None = None):
    """``apex.contrib.xentropy.SoftmaxCrossEntropyLoss.apply(logits, labels,
    smoothing, padding_idx, half_to_float)`` equivalent.

    Returns per-token loss (reduce with mean/sum yourself, as the reference
    does). ``padding_idx`` tokens contribute zero loss and zero gradient.
    ``num_classes``: treat only the first N logit columns as real classes —
    lets callers keep Megatron-style lane-padded vocab logits (the extra
    columns are masked in-kernel, no slice copy; their grads are zero).
    ``block_rows``: static rows-per-grid-step; ``None`` resolves tuning
    table > heuristic (`apex1_tpu.tuning.tuned_row_block`).
    """
    if num_classes is not None and not (
            0 < num_classes <= logits.shape[-1]):
        raise ValueError(f"num_classes {num_classes} must be in "
                         f"(0, {logits.shape[-1]}]")
    if use_pallas():
        return _fused_xent(logits, labels, float(smoothing), padding_idx,
                           num_classes, block_rows)
    return _xla_xent(logits, labels, smoothing, padding_idx, num_classes)


def masked_next_token_mean(losses, segment_ids):
    """Mean of next-token losses over VALID targets in a packed batch:
    a target in a different segment than its input token (document
    boundary) or in the padding segment (< 0) is not a target.
    ``losses``: (B, S-1) per-position CE of predicting token t+1;
    ``segment_ids``: (B, S). Shared by the packed GPT-2/Llama loss fns."""
    valid = ((segment_ids[:, :-1] == segment_ids[:, 1:])
             & (segment_ids[:, :-1] >= 0)).astype(losses.dtype)
    return jnp.sum(losses * valid) / jnp.maximum(jnp.sum(valid), 1.0)
