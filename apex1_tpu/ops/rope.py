"""Fused rotary positional embedding — Pallas TPU kernel.

Reference: ``csrc/megatron/fused_rotary_positional_embedding.{cpp,_cuda.cu}``
(fwd/bwd apply, sbhd/thd layouts).

Both rotation conventions are provided:
- ``interleaved=False`` (NeoX/Llama "half" style, the reference's
  ``rotate_half``): x1 = x[..., :d/2], x2 = x[..., d/2:],
  out = [x1·cos − x2·sin, x2·cos + x1·sin]
- ``interleaved=True`` (GPT-J style): even/odd lanes form the pairs.

The backward of a rotation is the rotation by −θ — implemented as the same
kernel with sin negated (what the reference's bwd kernel does), exposed via
``custom_vjp`` so autodiff never materializes the big intermediate.

Layout: (..., seq, heads, head_dim) or (..., seq, head_dim); cos/sin are
(seq, head_dim/2) fp32 tables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex1_tpu.ops._common import interpret_mode, out_struct, use_pallas
from apex1_tpu.tuning import tuned_row_block


def rope_tables(positions, head_dim: int, *, base: float = 10000.0,
                dtype=jnp.float32):
    """cos/sin tables: (len(positions), head_dim/2)."""
    half = head_dim // 2
    inv_freq = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def _rope_kernel(x1_ref, x2_ref, cos_ref, sin_ref, o1_ref, o2_ref):
    x1 = x1_ref[...].astype(jnp.float32)
    x2 = x2_ref[...].astype(jnp.float32)
    c = cos_ref[...]
    s = sin_ref[...]
    o1_ref[...] = (x1 * c - x2 * s).astype(o1_ref.dtype)
    o2_ref[...] = (x2 * c + x1 * s).astype(o2_ref.dtype)


def _pallas_rope(x1, x2, cos_r, sin_r, block_rows=None):
    rows, half = x1.shape
    # 4 ins + 2 outs double-buffered; None = table > heuristic
    br = tuned_row_block("rope", half, rows=rows, dtype=x1.dtype,
                         requested=block_rows)
    row = pl.BlockSpec((br, half), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _rope_kernel,
        grid=(pl.cdiv(rows, br),),
        in_specs=[row, row, row, row],
        out_specs=(row, row),
        out_shape=(out_struct(x1.shape, x1.dtype, x1, x2, cos_r, sin_r),
                   out_struct(x2.shape, x2.dtype, x1, x2, cos_r, sin_r)),
        interpret=interpret_mode(),
    )(x1, x2, cos_r, sin_r)


def _split(x, interleaved):
    if interleaved:
        return x[..., 0::2], x[..., 1::2]
    half = x.shape[-1] // 2
    return x[..., :half], x[..., half:]


def _merge(o1, o2, interleaved):
    if interleaved:
        return jnp.stack([o1, o2], axis=-1).reshape(
            o1.shape[:-1] + (o1.shape[-1] * 2,))
    return jnp.concatenate([o1, o2], axis=-1)


def _infer_seq_axis(x, seq_len: int) -> int:
    """Pick the sequence axis: prefer -3 ("seq, heads, head_dim" layout),
    then -2 ("seq, head_dim"); both must match the table length."""
    for ax in (x.ndim - 3, x.ndim - 2):
        if ax >= 0 and x.shape[ax] == seq_len:
            return ax
    raise ValueError(
        f"cannot infer sequence axis: no axis of {x.shape} at -3/-2 matches "
        f"the cos/sin table length {seq_len}; pass seq_axis explicitly")


def _apply(x, cos, sin, interleaved, seq_axis, block_rows=None):
    """Shared fwd path; bwd = fwd with −sin (rotation transpose)."""
    shape = x.shape
    half = shape[-1] // 2
    seq = shape[seq_axis]
    x1, x2 = _split(x, interleaved)
    # broadcast tables over batch/heads -> row layout (R, half)
    bshape = [1] * x.ndim
    bshape[seq_axis] = seq
    bshape[-1] = half
    if cos.ndim == 3:
        # per-row tables (B, seq, half) — packed/varlen batches where
        # positions restart per segment (≙ the reference's thd variant)
        bshape[0] = cos.shape[0]
    c = jnp.broadcast_to(cos.astype(jnp.float32).reshape(bshape),
                         x1.shape).reshape(-1, half)
    s = jnp.broadcast_to(sin.astype(jnp.float32).reshape(bshape),
                         x1.shape).reshape(-1, half)
    if use_pallas() and half % 128 == 0:
        o1, o2 = _pallas_rope(x1.reshape(-1, half), x2.reshape(-1, half),
                              c, s, block_rows)
        o1 = o1.reshape(x1.shape)
        o2 = o2.reshape(x2.shape)
    else:
        c = c.reshape(x1.shape)
        s = s.reshape(x1.shape)
        x1f = x1.astype(jnp.float32)
        x2f = x2.astype(jnp.float32)
        o1 = (x1f * c - x2f * s).astype(x.dtype)
        o2 = (x2f * c + x1f * s).astype(x.dtype)
    return _merge(o1, o2, interleaved).reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _rope(x, cos, sin, interleaved, seq_axis, block_rows):
    return _apply(x, cos, sin, interleaved, seq_axis, block_rows)


def _rope_fwd(x, cos, sin, interleaved, seq_axis, block_rows):
    return _apply(x, cos, sin, interleaved, seq_axis, block_rows), \
        (cos, sin)


def _rope_bwd(interleaved, seq_axis, block_rows, res, dy):
    cos, sin = res
    return _apply(dy, cos, -sin, interleaved, seq_axis, block_rows), \
        None, None


_rope.defvjp(_rope_fwd, _rope_bwd)


def apply_rotary_pos_emb(x, cos, sin, *, interleaved: bool = False,
                         seq_axis: int | None = None,
                         block_rows: int | None = None):
    """Apply RoPE. ``x``: (..., seq, heads, head_dim) or (..., seq,
    head_dim); ``cos/sin``: (seq, head_dim/2) from `rope_tables`, or
    (B, seq, head_dim/2) per-row tables for packed/varlen batches
    (positions restarting per segment — the reference's thd variant).
    The sequence axis is inferred from the table length (prefer -3, then
    -2); pass ``seq_axis`` when ambiguous. ``block_rows``: static
    rows-per-grid-step; ``None`` resolves tuning table > heuristic
    (`apex1_tpu.tuning.tuned_row_block`)."""
    if x.shape[-1] % 2:
        raise ValueError("head_dim must be even for RoPE")
    if cos.ndim == 3 and cos.shape[0] != x.shape[0]:
        raise ValueError(
            f"per-row tables {cos.shape} need leading dim == batch "
            f"{x.shape[0]}")
    seq_len = cos.shape[1] if cos.ndim == 3 else cos.shape[0]
    if seq_axis is None:
        seq_axis = _infer_seq_axis(x, seq_len)
    else:
        seq_axis = seq_axis % x.ndim
    return _rope(x, cos, sin, interleaved, seq_axis, block_rows)
