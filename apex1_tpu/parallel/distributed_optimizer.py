"""ZeRO-style sharded optimizers — reference
``apex/contrib/optimizers/distributed_fused_adam.py :: DistributedFusedAdam``
(and ``distributed_fused_lamb.py``).

The reference flattens params into fixed-size blocks, backward hooks
reduce-scatter gradient buckets into per-rank shards on side streams, a
fused Adam updates each rank's shard, and updated shards all-gather back —
overlapped with compute, with fp16-allreduce and redundant-group options.

TPU-native (SURVEY §2.6 "ZeRO-style sharded DP" row): sharding the
optimizer *state* (and optionally the flat param buffer) over the dp/fsdp
axis IS the algorithm — XLA emits the same reduce-scatter → local-update →
all-gather sequence, overlapped by the latency-hiding scheduler. Two forms:

1. **GSPMD (recommended)**: `shard_opt_state_specs` produces PartitionSpecs
   that shard every optimizer-state leaf over ``fsdp``; pass them to pjit —
   zero new math (ZeRO-1/2 as sharding specs).
2. **Explicit shard_map**: `distributed_fused_adam` — grads reduce-scatter
   over the flat buffer, shard-local fused Adam, param all-gather; the
   reference's dataflow, one traced program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex1_tpu.core.mesh import AXIS_FSDP
from apex1_tpu.core.pytree import flatten_tree
from apex1_tpu.optim.fused_adam import fused_adam


def shard_opt_state_specs(opt_state, *, axis=AXIS_FSDP):
    """PartitionSpecs sharding every ≥1-D float leaf of the optimizer state
    over ``axis`` (dim 0) — ZeRO-1 as data. Scalars stay replicated."""
    from jax.sharding import PartitionSpec as P

    def spec(leaf):
        shape = jnp.shape(leaf)
        if len(shape) == 0:
            return P()
        return P(axis, *([None] * (len(shape) - 1)))

    return jax.tree_util.tree_map(spec, opt_state)


class DistributedAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg_shard: jnp.ndarray     # (flat/N,) this rank's slice
    exp_avg_sq_shard: jnp.ndarray


def distributed_fused_adam(
    learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
    adam_w_mode=True, bias_correction=True, *, axis_name=AXIS_FSDP,
):
    """Explicit-dataflow sharded Adam for the shard_map path.

    Returned object has ``init(params) -> state`` (call inside shard_map:
    state shards are per-rank) and ``step(grads, state, params) ->
    (new_params, new_state)`` implementing:
        flat grads --psum_scatter--> grad shard        (≙ bucket RS hooks)
        shard-local fused Adam on (param shard, m, v)  (≙ per-shard kernel)
        updated param shard --all_gather--> new params (≙ AG of shards)
    """
    inner = fused_adam(learning_rate, b1, b2, eps, weight_decay,
                       adam_w_mode, bias_correction)

    class _DistAdam:
        @staticmethod
        def _flat_len(params):
            flat, _ = flatten_tree(params, dtype=jnp.float32)
            return flat.shape[0]

        @staticmethod
        def _pad(n, world):
            return (-n) % world

        def init(self, params, world: int | None = None):
            """Inside shard_map ``world`` is inferred from the axis; outside
            (host-side state setup) pass it explicitly."""
            if world is None:
                world = jax.lax.axis_size(axis_name)
            n = self._flat_len(params)
            shard = (n + self._pad(n, world)) // world
            return DistributedAdamState(
                step=jnp.zeros([], jnp.int32),
                exp_avg_shard=jnp.zeros((shard,), jnp.float32),
                exp_avg_sq_shard=jnp.zeros((shard,), jnp.float32))

        def step(self, grads, state, params):
            world = jax.lax.axis_size(axis_name)
            idx = jax.lax.axis_index(axis_name)
            gflat, _ = flatten_tree(grads, dtype=jnp.float32)
            pflat, unflatten = flatten_tree(params, dtype=jnp.float32)
            n = gflat.shape[0]
            pad = self._pad(n, world)
            if pad:
                gflat = jnp.pad(gflat, (0, pad))
                pflat = jnp.pad(pflat, (0, pad))
            shard = gflat.shape[0] // world
            # reduce-scatter: mean grads, each rank keeps its slice
            gshard = jax.lax.psum_scatter(
                gflat.reshape(world, shard), axis_name,
                scatter_dimension=0, tiled=False) / world
            pshard = jax.lax.dynamic_slice_in_dim(pflat, idx * shard,
                                                  shard)
            # shard-local fused Adam via the single-tensor transform
            from apex1_tpu.optim.fused_adam import FusedAdamState
            st = FusedAdamState(step=state.step,
                                exp_avg={"p": state.exp_avg_shard},
                                exp_avg_sq={"p": state.exp_avg_sq_shard})
            upd, st2 = inner.update({"p": gshard}, st, {"p": pshard})
            new_pshard = pshard + upd["p"]
            # all-gather updated shards → full flat params
            new_pflat = jax.lax.all_gather(new_pshard, axis_name,
                                           tiled=True)
            if pad:
                new_pflat = new_pflat[:n]
            return unflatten(new_pflat), DistributedAdamState(
                step=st2.step,
                exp_avg_shard=st2.exp_avg["p"],
                exp_avg_sq_shard=st2.exp_avg_sq["p"])

    return _DistAdam()
