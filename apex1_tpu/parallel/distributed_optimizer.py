"""ZeRO-style sharded optimizers — reference
``apex/contrib/optimizers/distributed_fused_adam.py :: DistributedFusedAdam``
(and ``distributed_fused_lamb.py``).

The reference flattens params into fixed-size blocks, backward hooks
reduce-scatter gradient buckets into per-rank shards on side streams, a
fused Adam updates each rank's shard, and updated shards all-gather back —
overlapped with compute, with fp16-allreduce and redundant-group options.

TPU-native (SURVEY §2.6 "ZeRO-style sharded DP" row): sharding the
optimizer *state* (and optionally the flat param buffer) over the dp/fsdp
axis IS the algorithm — XLA emits the same reduce-scatter → local-update →
all-gather sequence, overlapped by the latency-hiding scheduler. Two forms:

1. **GSPMD (recommended)**: `shard_opt_state_specs` produces PartitionSpecs
   that shard every optimizer-state leaf over ``fsdp``; pass them to pjit —
   zero new math (ZeRO-1/2 as sharding specs).
2. **Explicit shard_map**: `distributed_fused_adam` — grads reduce-scatter
   over the flat buffer, shard-local fused Adam, param all-gather; the
   reference's dataflow, one traced program.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex1_tpu.core.mesh import AXIS_FSDP
from apex1_tpu.core.pytree import flatten_tree
from apex1_tpu.optim.fused_adam import fused_adam


def shard_opt_state_specs(opt_state, *, axis=AXIS_FSDP, param_specs=None):
    """PartitionSpecs for optimizer state — ZeRO-1 as data.

    With ``param_specs`` (the tree `fsdp_param_specs` returned): any
    sub-tree of ``opt_state`` with the params' structure (optax moment
    trees: ``exp_avg``, ``exp_avg_sq``, …) gets the params' specs
    verbatim, so moments shard on the SAME dim as their param and the
    update stays shard-local (no per-step resharding). Without it, every
    ≥1-D float leaf shards dim 0. Scalars stay replicated."""
    from jax.sharding import PartitionSpec as P

    def dim0(leaf):
        shape = jnp.shape(leaf)
        if len(shape) == 0:
            return P()
        return P(axis, *([None] * (len(shape) - 1)))

    if param_specs is None:
        return jax.tree_util.tree_map(dim0, opt_state)

    pstruct = jax.tree_util.tree_structure(
        param_specs, is_leaf=lambda v: isinstance(v, P))

    def specs_fit(node):
        """Structure match is not enough: a degenerate params tree (e.g. a
        single leaf) structurally matches every scalar opt-state leaf, and
        substituting a rank-k spec onto a 0-d step/count leaf is invalid.
        Require len(spec) <= leaf rank for each candidate leaf (JAX treats
        trailing unspecified dims as replicated, so SHORT specs are valid;
        a spec LONGER than the rank is not)."""
        leaves = jax.tree_util.tree_leaves(node)
        specs = jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda v: isinstance(v, P))
        return all(len(sp) <= len(jnp.shape(lf))
                   for sp, lf in zip(specs, leaves))  # short specs: JAX
        # leaves trailing dims replicated, so len(sp) <= rank is valid

    def walk(node):
        try:
            if (jax.tree_util.tree_structure(node) == pstruct
                    and specs_fit(node)):
                return param_specs
        except Exception:
            pass
        if isinstance(node, dict):
            return type(node)({k: walk(v) for k, v in node.items()})
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*[walk(v) for v in node])
        if isinstance(node, (tuple, list)):
            return type(node)(walk(v) for v in node)
        return dim0(node)

    return walk(opt_state)


def fsdp_param_specs(params, *, axis=AXIS_FSDP, min_size: int = 2 ** 12,
                     divisor: int | None = None):
    """ZeRO-3 as data: PartitionSpecs sharding one dim of each param over
    ``axis`` — the largest dim divisible by ``divisor`` (pass the fsdp
    mesh-axis size to avoid GSPMD shard padding), else simply the largest.
    With params (and `shard_opt_state_specs` state) handed to pjit this
    way, GSPMD emits the reference DistributedFusedAdam dataflow —
    all-gather params before use, reduce-scatter grads, shard-local
    update — scheduled/overlapped by XLA instead of the reference's side
    streams and buckets.

    Small params (< ``min_size`` elements) stay replicated: gathering
    them costs more latency than their shard saves (the same reason the
    reference packs params into fixed-size blocks before sharding).
    """
    from jax.sharding import PartitionSpec as P

    def spec(leaf):
        shape = jnp.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) < min_size:
            return P()
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        if divisor:
            divisible = [i for i in order if shape[i] % divisor == 0]
            d = divisible[0] if divisible else order[0]
        else:
            d = order[0]
        return P(*[axis if i == d else None for i in range(len(shape))])

    return jax.tree_util.tree_map(spec, params)


def flat_param_len(params) -> int:
    """True (unpadded) length of the flat float buffer `flatten_tree`
    packs for ``params`` — float leaves only, in tree order, exactly
    the set `distributed_fused_adam`/`_lamb` shard. Host-side: this is
    the reshard hook `resilience.reshard` uses to strip/re-apply the
    per-world padding of a checkpointed ``…_shard`` buffer."""
    return sum(int(np.prod(jnp.shape(p)) or 1)
               for p in jax.tree_util.tree_leaves(params)
               if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating))


def shard_padded_len(n: int, world: int) -> int:
    """Flat length after padding ``n`` to a multiple of ``world`` (the
    `_pad` rule both distributed optimizers apply)."""
    return int(n) + (-int(n)) % int(world)


def repack_flat_shard(flat, *, flat_len: int, world_from: int,
                      world_to: int) -> np.ndarray:
    """Remap a GLOBAL flat optimizer-shard buffer (the host view of a
    dp-sharded ``exp_avg_shard``-class leaf: ``world_from`` per-rank
    slices concatenated) from one world size to another: strip the old
    padding at ``flat_len``, zero-pad for ``world_to``.

    Zero-padding is EXACT, not approximate: the padded tail of the
    flat buffer carries zero params and zero grads on every step, so
    Adam/LAMB moments there stay identically zero (``m = b1·0 +
    (1-b1)·0``) — the repacked buffer equals what a from-scratch run
    at ``world_to`` would have accumulated. Host-side numpy; the
    reshard hook for `resilience.reshard_state`."""
    a = np.asarray(flat)
    if a.ndim != 1:
        raise ValueError(f"flat shard buffer must be 1-D, got {a.shape}")
    want = shard_padded_len(flat_len, world_from)
    if a.shape[0] != want:
        raise ValueError(
            f"flat shard buffer has {a.shape[0]} elements, expected "
            f"{want} (= {flat_len} padded for world {world_from})")
    pad = shard_padded_len(flat_len, world_to) - int(flat_len)
    core = a[:int(flat_len)]
    if pad == 0:
        return core.copy()
    return np.concatenate([core, np.zeros((pad,), a.dtype)])


class DistributedAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg_shard: jnp.ndarray     # (flat/N,) this rank's slice
    exp_avg_sq_shard: jnp.ndarray


def distributed_fused_adam(
    learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
    adam_w_mode=True, bias_correction=True, *, axis_name=AXIS_FSDP,
    overlap_grad_sync: bool = True, bucket_cap_mb: float | None = None,
    process_group_size: int | None = None,
):
    """Explicit-dataflow sharded Adam for the shard_map path.

    Returned object has ``init(params) -> state`` (call inside shard_map:
    state shards are per-rank) and ``step(grads, state, params) ->
    (new_params, new_state)`` implementing:
        flat grads --psum_scatter--> grad shard        (≙ bucket RS hooks)
        shard-local fused Adam on (param shard, m, v)  (≙ per-shard kernel)
        updated param shard --all_gather--> new params (≙ AG of shards)

    ``overlap_grad_sync`` / ``bucket_cap_mb`` / ``process_group_size`` are
    accepted for reference-signature parity
    (``DistributedFusedAdam(overlap_grad_sync, bucket_cap_mb,
    process_group_size)``) and stored on the returned object, but have no
    mechanism here: the XLA latency-hiding scheduler overlaps the RS/AG
    with compute and chooses transfer granularity itself, and the
    "process group" is the mesh axis (``axis_name``). They exist so
    reference configs port 1:1.
    """
    inner = fused_adam(learning_rate, b1, b2, eps, weight_decay,
                       adam_w_mode, bias_correction)

    _ogs, _bcm, _pgs = overlap_grad_sync, bucket_cap_mb, process_group_size

    class _DistAdam:
        # reference-signature knobs, recorded for config round-tripping
        # (no mechanism on TPU — see docstring)
        overlap_grad_sync = _ogs
        bucket_cap_mb = _bcm
        process_group_size = _pgs

        @staticmethod
        def _flat_len(params):
            flat, _ = flatten_tree(params, dtype=jnp.float32)
            return flat.shape[0]

        @staticmethod
        def _pad(n, world):
            return (-n) % world

        def init(self, params, world: int | None = None):
            """Inside shard_map ``world`` is inferred from the axis; outside
            (host-side state setup) pass it explicitly."""
            if world is None:
                world = jax.lax.axis_size(axis_name)
            n = self._flat_len(params)
            shard = (n + self._pad(n, world)) // world
            return DistributedAdamState(
                step=jnp.zeros([], jnp.int32),
                exp_avg_shard=jnp.zeros((shard,), jnp.float32),
                exp_avg_sq_shard=jnp.zeros((shard,), jnp.float32))

        def step(self, grads, state, params):
            world = jax.lax.axis_size(axis_name)
            idx = jax.lax.axis_index(axis_name)
            gflat, _ = flatten_tree(grads, dtype=jnp.float32)
            pflat, unflatten = flatten_tree(params, dtype=jnp.float32)
            n = gflat.shape[0]
            pad = self._pad(n, world)
            if pad:
                gflat = jnp.pad(gflat, (0, pad))
                pflat = jnp.pad(pflat, (0, pad))
            shard = gflat.shape[0] // world
            # reduce-scatter: mean grads, each rank keeps its slice
            gshard = jax.lax.psum_scatter(
                gflat.reshape(world, shard), axis_name,
                scatter_dimension=0, tiled=False) / world
            pshard = jax.lax.dynamic_slice_in_dim(pflat, idx * shard,
                                                  shard)
            # shard-local fused Adam via the single-tensor transform
            from apex1_tpu.optim.fused_adam import FusedAdamState
            st = FusedAdamState(step=state.step,
                                exp_avg={"p": state.exp_avg_shard},
                                exp_avg_sq={"p": state.exp_avg_sq_shard})
            upd, st2 = inner.update({"p": gshard}, st, {"p": pshard})
            new_pshard = pshard + upd["p"]
            # all-gather updated shards → full flat params
            new_pflat = jax.lax.all_gather(new_pshard, axis_name,
                                           tiled=True)
            if pad:
                new_pflat = new_pflat[:n]
            return unflatten(new_pflat), DistributedAdamState(
                step=st2.step,
                exp_avg_shard=st2.exp_avg["p"],
                exp_avg_sq_shard=st2.exp_avg_sq["p"])

    return _DistAdam()


class DistributedLambState(NamedTuple):
    step: jnp.ndarray
    exp_avg_shard: jnp.ndarray
    exp_avg_sq_shard: jnp.ndarray


def distributed_fused_lamb(
    learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.01,
    bias_correction=True, max_grad_norm=1.0, use_nvlamb=False, *,
    axis_name=AXIS_FSDP,
):
    """Explicit-dataflow sharded LAMB — reference
    ``apex/contrib/optimizers/distributed_fused_lamb.py ::
    DistributedFusedLAMB`` (MLPerf BERT recipe).

    Same reduce-scatter → shard-local update → all-gather dataflow as
    `distributed_fused_adam`, with LAMB's two norm passes reconstructed
    over the sharded flat buffer: the global grad-norm clip and the
    PER-TENSOR ||p||/||u|| trust ratios are computed as shard-local
    segment sums (segment = source tensor) + one small psum — the
    TPU-native equivalent of the reference's sharded
    ``multi_tensor_l2norm`` stages.
    """

    class _DistLamb:
        @staticmethod
        def _geometry(params, world):
            # float leaves only — the exact set flatten_tree packs, so the
            # segment ids line up with the flat buffer element-for-element
            sizes = [int(np.prod(jnp.shape(p)) or 1)
                     for p in jax.tree_util.tree_leaves(params)
                     if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating)]
            n = sum(sizes)
            pad = (-n) % world
            seg = np.repeat(np.arange(len(sizes)), sizes)
            seg = np.concatenate([seg, np.full(pad, len(sizes))])
            return n, pad, jnp.asarray(seg, jnp.int32), len(sizes) + 1

        def init(self, params, world: int | None = None):
            if world is None:
                world = jax.lax.axis_size(axis_name)
            n, pad, _, _ = self._geometry(params, world)
            shard = (n + pad) // world
            return DistributedLambState(
                step=jnp.zeros([], jnp.int32),
                exp_avg_shard=jnp.zeros((shard,), jnp.float32),
                exp_avg_sq_shard=jnp.zeros((shard,), jnp.float32))

        def step(self, grads, state, params):
            world = jax.lax.axis_size(axis_name)
            idx = jax.lax.axis_index(axis_name)
            n, pad, seg_full, n_seg = self._geometry(params, world)
            gflat, _ = flatten_tree(grads, dtype=jnp.float32)
            pflat, unflatten = flatten_tree(params, dtype=jnp.float32)
            if pad:
                gflat = jnp.pad(gflat, (0, pad))
                pflat = jnp.pad(pflat, (0, pad))
            shard = gflat.shape[0] // world
            gshard = jax.lax.psum_scatter(
                gflat.reshape(world, shard), axis_name,
                scatter_dimension=0, tiled=False) / world
            pshard = jax.lax.dynamic_slice_in_dim(pflat, idx * shard,
                                                  shard)
            seg_shard = jax.lax.dynamic_slice_in_dim(seg_full, idx * shard,
                                                     shard)
            # pass 1: global grad-norm clip (psum of shard partials)
            gsq = jax.lax.psum(jnp.sum(jnp.square(gshard)), axis_name)
            clip = jnp.maximum(jnp.float32(1.0),
                               jnp.sqrt(gsq) / max_grad_norm)
            step = state.step + 1
            lr = (learning_rate(step) if callable(learning_rate)
                  else learning_rate)
            if bias_correction:
                bc1 = 1.0 - jnp.power(jnp.float32(b1),
                                      step.astype(jnp.float32))
                bc2 = 1.0 - jnp.power(jnp.float32(b2),
                                      step.astype(jnp.float32))
            else:
                bc1 = bc2 = jnp.float32(1.0)
            g = gshard / clip
            m = b1 * state.exp_avg_shard + (1.0 - b1) * g
            v = b2 * state.exp_avg_sq_shard + (1.0 - b2) * jnp.square(g)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                u = u + weight_decay * pshard
            # stage 2: per-TENSOR trust ratios from sharded segment sums
            if weight_decay or use_nvlamb:
                w_sq = jax.lax.psum(jax.ops.segment_sum(
                    jnp.square(pshard), seg_shard, num_segments=n_seg),
                    axis_name)
                u_sq = jax.lax.psum(jax.ops.segment_sum(
                    jnp.square(u), seg_shard, num_segments=n_seg),
                    axis_name)
                ratio = jnp.where((w_sq > 0) & (u_sq > 0),
                                  jnp.sqrt(w_sq) / jnp.sqrt(
                                      jnp.maximum(u_sq, 1e-30)), 1.0)
                scale = ratio[seg_shard]
            else:
                scale = jnp.float32(1.0)
            new_pshard = pshard - lr * scale * u
            new_pflat = jax.lax.all_gather(new_pshard, axis_name,
                                           tiled=True)
            if pad:
                new_pflat = new_pflat[:n]
            return unflatten(new_pflat), DistributedLambState(
                step=step, exp_avg_shard=m, exp_avg_sq_shard=v)

    return _DistLamb()
