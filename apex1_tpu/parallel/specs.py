"""Regex-rule PartitionSpec trees — the GSPMD face of tensor parallelism.

Reference counterpart: ``apex/transformer/tensor_parallel/layers.py ::
set_tensor_model_parallel_attributes`` — the reference marks each weight
with (is_parallel, partition_dim, stride) and its Column/RowParallel
autograd Functions issue the matching collectives by hand. Here the same
information is a `PartitionSpec` per param, produced by path-regex rules
(pattern: SNIPPETS.md [1]); pjit/GSPMD then inserts identical collectives.

`specs_from_rules` is the generic engine; each model module ships its rule
table (`models.llama.param_specs`, `models.gpt2.param_specs`,
`models.bert.param_specs`).
"""

from __future__ import annotations

import re

import jax
from jax.sharding import PartitionSpec as P


def specs_from_rules(params, rules, *, default=P()):
    """PartitionSpec tree for ``params``: each leaf's flattened path
    (``"layer0/qkv/kernel"``) is matched against ``rules`` —
    ``((regex, spec), ...)`` — first match wins, else ``default``."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def spec_for(path):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        for pat, spec in rules:
            if re.search(pat, name):
                return spec
        return default

    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params),
        [spec_for(path) for path, _ in flat])
