"""Ulysses-style all-to-all sequence parallelism — the second
context-parallel form (complement of `parallel.ring_attention`).

**Beyond-reference capability** (SURVEY.md §2.6 marks Ulysses *[absent]*
in apex). Mechanism (DeepSpeed-Ulysses lineage): tokens arrive sharded
over the ``cp`` axis; one ``all_to_all`` re-shards attention inputs from
sequence-sharded (B, H, S/n, D) to HEAD-sharded (B, H/n, S, D), each
device runs ordinary (flash) attention over the FULL sequence for its
head subset, and a second ``all_to_all`` restores sequence sharding.

Trade-offs vs ring attention (both provided so configs can pick):
- Ulysses: 2 all-to-alls per attention (O(S·D·H/n) bytes each), full-seq
  attention locally — simple, exact, great when heads ≥ devices;
  requires Hq and Hkv divisible by the axis size.
- Ring: n−1 neighbor ppermutes of K/V, attention stays seq-local —
  scales to more devices than heads and overlaps transfer with compute:
  the double-buffered `parallel.ring_attention` schedule issues each
  shard's ppermute before the previous shard's attend, forward and
  backward (the overlap is pinned on optimized HLO by
  `testing.hlo_probe`, not just claimed here), at the cost of the
  lse-merge machinery.

When head counts do NOT divide the axis size, ``fallback="ring"``
routes the call through that overlapped ring instead of raising — one
config knob serves both regimes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_CP
from apex1_tpu.ops.attention import flash_attention


def ulysses_attention(q, k, v, axis_name=AXIS_CP, *, causal: bool = False,
                      sm_scale: float | None = None, segment_ids=None,
                      block_q: int | None = None,
                      block_k: int | None = None,
                      fallback: str = "error"):
    """Attention over a sequence sharded on ``axis_name`` via head
    scatter / sequence gather all-to-alls. Call inside ``shard_map``.

    ``q`` (B, Hq, S_local, D); ``k``/``v`` (B, Hkv, S_local, D) with Hq
    and Hkv divisible by the axis size. ``segment_ids``: local (B,
    S_local) shard (all-gathered internally — after the first a2a every
    device sees the full sequence). Returns the local output shard.

    ``fallback``: what to do when the head counts do not divide the
    axis size — ``"error"`` (default) raises; ``"ring"`` routes through
    the overlapped double-buffered `parallel.ring_attention` carry
    (same semantics, no head-divisibility requirement).
    """
    if fallback not in ("error", "ring"):
        raise ValueError(f"fallback must be 'error' or 'ring', got "
                         f"{fallback!r}")
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return flash_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k)
    Hq, Hkv = q.shape[1], k.shape[1]
    # validate BEFORE the GQA repeat below mutates Hkv: the error must
    # name the USER'S head counts, and the repeat work must not run
    # just to be thrown away (review r5)
    hkv_eff = n if (Hkv % n and n % Hkv == 0) else Hkv
    if Hq % n or hkv_eff % n:
        if fallback == "ring":
            from apex1_tpu.parallel.ring_attention import ring_attention
            return ring_attention(q, k, v, axis_name, causal=causal,
                                  sm_scale=sm_scale,
                                  segment_ids=segment_ids,
                                  block_q=block_q, block_k=block_k)
        raise ValueError(
            f"ulysses needs head counts divisible by the axis size: "
            f"Hq={Hq}, Hkv={Hkv}, n={n} (use ring_attention or "
            f"fallback='ring' otherwise)")
    if Hkv % n:
        # GQA with fewer KV heads than devices: materialize the group
        # repeat (exactly how GQA attention is defined) so KV heads
        # split evenly; costs KV bandwidth, preserves semantics
        rep = n // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
        Hkv = n

    def seq_to_heads(t):   # (B, H, S_l, D) -> (B, H/n, S, D)
        return jax.lax.all_to_all(t, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def heads_to_seq(t):   # (B, H/n, S, D) -> (B, H, S_l, D)
        return jax.lax.all_to_all(t, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if segment_ids is not None:
        segment_ids = jax.lax.all_gather(segment_ids, axis_name, axis=1,
                                         tiled=True)  # full (B, S)
    out = flash_attention(qg, kg, vg, causal=causal,
                          segment_ids=segment_ids, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k)
    return heads_to_seq(out)
