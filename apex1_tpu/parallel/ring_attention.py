"""Ring attention — context parallelism for long sequences over ICI.

The reference has NO long-context attention mechanism (SURVEY.md §5.7:
``apex/contrib/fmha`` caps seqlen at 512; Megatron SP shards LN/dropout
activations only). Its closest pattern is the spatial-parallel halo
exchange (``apex/contrib/bottleneck/halo_exchangers.py :: HaloExchangerNccl``
— activation-domain decomposition with neighbor transfers), which this
module generalizes to attention: shard the SEQUENCE over a mesh axis and
rotate K/V shards around the ring with ``jax.lax.ppermute`` (ICI
neighbor transfers), merging partial-attention results with the
numerically-stable logsumexp merge.

Per ring step each device computes flash attention of its local Q shard
against the visiting K/V shard (`apex1_tpu.ops.attention.flash_attention`
with traced global offsets for the causal mask), yielding ``(out_t,
lse_t)``; partials combine exactly:

    lse   = logaddexp(lse_a, lse_b)
    out   = out_a·exp(lse_a − lse) + out_b·exp(lse_b − lse)

**Double-buffered schedule** (the ``apex.parallel.DDP`` bucketed-overlap
optimization restated for ICI): the ppermute that fetches the K/V shard
for step t+1 is issued BEFORE ``attend(shard t)`` runs, so the attention
dots of step t have no data dependence on the in-flight transfer and
XLA's async collectives (``collective-permute-start``/``-done``) hide
the ICI latency behind the MXU work. Two K/V buffers are live per step
(the one being attended and the one in flight) — that is the double
buffer. The property is PINNED on optimized HLO text by
`apex1_tpu.testing.hlo_probe` (tools/aot_check.py probes the v5e
executables; a serialized rotate→attend loop fails the probe).

Fully-masked (future, under causal) visiting shards are skipped with
``lax.cond`` — their transfer still rides the ring but their FLOPs are
not spent. The backward is a ``jax.custom_vjp``: its own double-buffered
ring with the INVERTED permutation, reusing the flash kernels'
lse-residual backward per visiting shard (global-statistics trick: each
per-shard backward is evaluated with the FINAL merged ``(out, lse)``,
which makes the per-shard cotangents exact without storing any per-step
statistics). dK/dV partial sums ride the ring back to their owning
device alongside the shards themselves. Pass ``use_custom_vjp=False``
to fall back to XLA's transpose of the forward scan (the pre-overlap
behavior for the backward; forward stays double-buffered).

`ring_attention_serial` retains the original rotate-first-then-attend
schedule (every transfer exposed) for A/B timing
(``tools/bench_ring_ab.py``) and as the parity anchor in tests.

Use inside ``jax.shard_map`` with the sequence dimension sharded over
``axis_name``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from apex1_tpu.ops._common import NEG_INF, use_pallas
from apex1_tpu.ops._common import vary as _vary
from apex1_tpu.ops.attention import flash_attention
from apex1_tpu.ops.stochastic import attn_keep_mask


def _axis_size(axis_name) -> int:
    return jax.lax.axis_size(axis_name)


def _merge(out_a, lse_a, out_b, lse_b):
    """Exact combine of two normalized partial attentions (fp32 stats)."""
    lse = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.exp(lse_a - lse)[..., None]
    w_b = jnp.exp(lse_b - lse)[..., None]
    return out_a * w_a + out_b.astype(out_a.dtype) * w_b, lse


def _ring_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale, has_segs,
                   block_q, block_k, dropout_p=0.0, seed=None,
                   skip_masked=True):
    """Double-buffered forward ring. Returns (out_fp32, lse).

    Schedule: the ppermute for the NEXT visiting shard is issued before
    the current shard is attended (no data dependence between them), so
    all n−1 neighbor transfers overlap the n attends. Attend/merge order
    is identical to the serialized schedule — forward numerics are
    bit-for-bit the same; only the permutes' dataflow changes.

    ``dropout_p``/``seed``: in-kernel probability dropout — every shard
    step passes its TRUE global offsets so the counter-based mask is
    keyed on global positions: shards draw disjoint streams and the mask
    is invariant to the visiting order (serial and overlapped schedules
    drop identical weights). ``seed`` must be replicated over the ring.
    ``skip_masked=False`` disables the causal lax.cond shard skip (the
    fully-masked attend runs and merges a NEG_INF partial — numerically
    identical); kept for the A/B timing in tools/bench_cond_elision.py.
    """
    n = _axis_size(axis_name)
    B, Hq, Sq, _ = q.shape
    Sk = k.shape[2]
    # axis_index only when the causal mask (or the dropout counter,
    # which keys on global positions) consumes it: a dead partition-id
    # chain in the custom_vjp jaxpr breaks XLA sharding propagation
    # (consumer-less partition-id is UNIMPLEMENTED there)
    needs_offs = causal or dropout_p > 0.0
    if needs_offs:
        idx = jax.lax.axis_index(axis_name)
        q_off = idx * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]

    out = _vary(jnp.zeros(q.shape, jnp.promote_types(q.dtype, jnp.float32)),
                axis_name)
    lse = _vary(jnp.full((B, Hq, Sq), NEG_INF, jnp.float32), axis_name)

    def attend(k_cur, v_cur, kseg_cur, t, out, lse):
        # offsets are consumed only by the causal mask / dropout
        # counter; computing them unconditionally would leave a dead
        # partition-id chain in the custom_vjp jaxpr (not DCE'd before
        # XLA sharding propagation, which then fails on the
        # consumer-less partition-id)
        if needs_offs:
            src = (idx - t) % n       # who this K/V shard belongs to
            k_off = src * Sk
            qo, ko = q_off, k_off
        else:
            qo = ko = 0

        def run(_):
            return flash_attention(
                q, k_cur, v_cur, causal=causal,
                segment_ids=(qseg, kseg_cur) if has_segs else None,
                sm_scale=sm_scale, q_offset=qo, k_offset=ko,
                block_q=block_q, block_k=block_k, return_lse=True,
                dropout_p=dropout_p, dropout_seed=seed)

        def skip(_):
            return (_vary(jnp.zeros(q.shape, q.dtype), axis_name),
                    _vary(jnp.full((B, Hq, Sq), NEG_INF, jnp.float32),
                          axis_name))

        if causal and skip_masked:
            # visiting shard strictly in the future → fully masked
            out_t, lse_t = jax.lax.cond(k_off > q_off + Sq - 1, skip, run,
                                        None)
        else:
            out_t, lse_t = run(None)
        return _merge(out, lse, out_t, lse_t)

    kseg0 = qseg if has_segs else jnp.zeros((), jnp.int32)
    if n == 1:
        return attend(k, v, kseg0, 0, out, lse)

    # prologue: issue the transfer for step 1 BEFORE attending the local
    # shard — attend(t=0) has no data dependence on it, so the transfer
    # flies behind the first attend's dots
    k_cur = jax.lax.ppermute(k, axis_name, perm)
    v_cur = jax.lax.ppermute(v, axis_name, perm)
    kseg_cur = (jax.lax.ppermute(kseg0, axis_name, perm) if has_segs
                else kseg0)
    out, lse = attend(k, v, kseg0, 0, out, lse)

    def step(carry, t):
        # issue the transfer for shard t+1, THEN attend shard t: the
        # dots consume only the carry (double buffer), never this
        # step's permute — the overlap property hlo_probe pins
        k_cur, v_cur, kseg_cur, out, lse = carry
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        kseg_nxt = (jax.lax.ppermute(kseg_cur, axis_name, perm)
                    if has_segs else kseg_cur)
        out, lse = attend(k_cur, v_cur, kseg_cur, t, out, lse)
        return (k_nxt, v_nxt, kseg_nxt, out, lse), None

    if n > 2:
        (k_cur, v_cur, kseg_cur, out, lse), _ = jax.lax.scan(
            step, (k_cur, v_cur, kseg_cur, out, lse), jnp.arange(1, n - 1))
    # epilogue: last visiting shard — no transfer left to issue, so the
    # ring does exactly n−1 permutes, all overlapped
    return attend(k_cur, v_cur, kseg_cur, n - 1, out, lse)


def _resolve_scale(q, sm_scale):
    return (1.0 / float(np.sqrt(q.shape[-1]))
            if sm_scale is None else float(sm_scale))


def _step_grads_pallas(q, k_cur, v_cur, qseg, kseg_cur, q_off, k_off, out,
                       lse, do, scale, causal, has_segs, block_q, block_k,
                       dropout_p=0.0, seed=None):
    """One visiting shard's (dq_t, dk_t, dv_t) via the flash backward
    kernels, evaluated with the FINAL merged (out, lse): p_t =
    exp(s_t − lse_global) is each key's true global softmax weight, so
    the per-shard cotangents are exact (the same lse-residual backward
    the single-shard flash custom VJP runs, with dlse = 0 since the
    ring consumes lse internally)."""
    from apex1_tpu.ops.attention import (_auto_blocks, _block,
                                         _flash_bwd_impl)
    from apex1_tpu.ops._common import pad_to

    block_q, block_k = _auto_blocks(q.shape[3], block_q, block_k, q.dtype,
                                    k_cur.shape[2])
    Sq = q.shape[2]
    bq = _block(Sq, block_q)
    lse_p, _ = pad_to(lse[..., None], 2, bq, value=NEG_INF)
    dummy = jnp.zeros((1, 1), jnp.int32)
    sd = (jnp.asarray(seed, jnp.int32) if dropout_p > 0.0
          else jnp.zeros((), jnp.int32))
    res = (q, k_cur, v_cur,
           qseg if has_segs else dummy,
           kseg_cur if has_segs else dummy,
           q_off, k_off, sd, out, lse_p)
    cts = (do, jnp.zeros(lse.shape, jnp.float32))
    # cast=False: dk/dv stay in the kernels' native fp32 so the ring
    # accumulation is exact (dq is q.dtype — the dq kernel's output
    # dtype, same per-shard precision as single-shard flash). With
    # dropout the backward kernels recompute the mask from (seed,
    # global offsets) — identical to what the forward shard drew.
    grads, _ = _flash_bwd_impl(scale, causal, has_segs, block_q, block_k,
                               res, cts, cast=False, dropout_p=dropout_p)
    return grads[0], grads[1], grads[2]


def _step_grads_xla(q, k_cur, v_cur, qseg, kseg_cur, q_off, k_off, lse,
                    delta, do, scale, causal, has_segs, dropout_p=0.0,
                    seed=None):
    """XLA-composite per-shard backward (CPU/GPU gold): same math as
    `_step_grads_pallas` with the local S×S score block materialized."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k_cur.shape[1], k_cur.shape[2]
    group = Hq // Hkv
    kr, vr = k_cur, v_cur
    if group > 1:
        kr = jnp.repeat(k_cur, group, axis=1)
        vr = jnp.repeat(v_cur, group, axis=1)
    qf = q.astype(jnp.float32)
    kf = kr.astype(jnp.float32)
    vf = vr.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf,
                   preferred_element_type=jnp.float32) * scale
    row = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
    mask = jnp.ones((B, 1, Sq, Sk), bool)
    if causal:
        mask = mask & ((col + k_off) <= (row + q_off))[None, None]
    if has_segs:
        mask = mask & (qseg[:, None, :, None] == kseg_cur[:, None, None, :])
    # lse is the GLOBAL logsumexp; rows with no valid keys carry the
    # NEG_INF sentinel — their exp overflows but the mask zeroes p
    p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
    if dropout_p > 0.0:
        keep = attn_keep_mask(seed, B, Hq, row + q_off, col + k_off,
                              dropout_p)
        inv = 1.0 / (1.0 - dropout_p)
        p_av = jnp.where(keep, p * inv, 0.0)   # dv sees DROPPED probs
    else:
        p_av = p
    dv_full = jnp.einsum("bhqk,bhqd->bhkd", p_av, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    if dropout_p > 0.0:
        dp = jnp.where(keep, dp * inv, 0.0)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk_full = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    if group > 1:
        dk_full = dk_full.reshape(B, Hkv, group, Sk, D).sum(axis=2)
        dv_full = dv_full.reshape(B, Hkv, group, Sk, D).sum(axis=2)
    return dq, dk_full, dv_full


def _ring_bwd_loop(q, k, v, qseg, out, lse, do, axis_name, causal,
                   sm_scale, has_segs, block_q, block_k, dropout_p=0.0,
                   seed=None, skip_masked=True):
    """Double-buffered backward ring over the INVERTED permutation.

    Shards flow backward (device i sends to i−1), so this device visits
    shards idx+1, idx+2, …, idx−1 in that order; the local shard's
    grads are computed in the prologue (overlapping the first hop) and
    folded in at the end. Travelling dK/dV accumulators hop alongside
    the shard they belong to and arrive home after n−1 hops — every
    transfer overlaps a per-shard flash backward.
    """
    n = _axis_size(axis_name)
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    # offsets exist only for the causal mask / dropout counter — see
    # _ring_fwd_loop on why a dead partition-id chain must not be traced
    needs_offs = causal or dropout_p > 0.0
    if needs_offs:
        idx = jax.lax.axis_index(axis_name)
        q_off = idx * Sq
    else:
        q_off = 0
    scale = _resolve_scale(q, sm_scale)
    inv = [(i, (i - 1) % n) for i in range(n)]
    pallas = use_pallas()
    # δ_i = Σ_d do·out — shared by every per-shard backward
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)

    def step_grads(k_cur, v_cur, kseg_cur, src):
        """fp32 (dq_t, dk_t, dv_t) for one visiting shard. ``src`` is the
        shard's owner (consumed by the causal mask only; 0 off-causal).
        fp32 so the cond branches agree and the dk/dv ring accumulation
        stays exact (the Pallas path hands its dk/dv over uncast via
        ``cast=False``; dq contributions carry the dq kernel's q.dtype
        precision, as in single-shard flash)."""
        k_off = src * Sk

        def run(_):
            if pallas:
                g = _step_grads_pallas(
                    q, k_cur, v_cur, qseg, kseg_cur, q_off, k_off, out,
                    lse, do, scale, causal, has_segs, block_q, block_k,
                    dropout_p=dropout_p, seed=seed)
            else:
                g = _step_grads_xla(
                    q, k_cur, v_cur, qseg, kseg_cur, q_off, k_off, lse,
                    delta, do, scale, causal, has_segs,
                    dropout_p=dropout_p, seed=seed)
            return tuple(t.astype(jnp.float32) for t in g)

        def skip(_):
            z = lambda shape: _vary(jnp.zeros(shape, jnp.float32),
                                    axis_name)
            return (z(q.shape), z(k.shape), z(v.shape))

        if causal and skip_masked:
            # visiting shard strictly in the future → zero cotangents;
            # the cond skips the FLOPs, the transfer still rides
            return jax.lax.cond(k_off > q_off + Sq - 1, skip, run, None)
        return run(None)

    kseg0 = qseg if has_segs else jnp.zeros((), jnp.int32)
    f32 = jnp.float32
    dq_own, dk_own, dv_own = step_grads(k, v, kseg0,
                                        idx if needs_offs else 0)
    dq = dq_own.astype(f32)
    dk_own = dk_own.astype(f32)
    dv_own = dv_own.astype(f32)
    if n == 1:
        return dq, dk_own, dv_own

    # prologue hop (issued before the local backward above in dataflow —
    # the local grads have no dependence on it)
    k_cur = jax.lax.ppermute(k, axis_name, inv)
    v_cur = jax.lax.ppermute(v, axis_name, inv)
    kseg_cur = (jax.lax.ppermute(kseg0, axis_name, inv) if has_segs
                else kseg0)
    zeros = lambda: _vary(jnp.zeros((B, Hkv, Sk, D), f32), axis_name)
    # travelling accumulators + one-step-delayed "pending" contributions:
    # each hop ships acc+pend where BOTH are carry values, so no permute
    # in the loop body depends on this step's backward kernels — XLA can
    # schedule every collective-permute-start before the dots and every
    # -done after them (the hlo_probe-pinned property; an add-then-hop
    # accumulator would chain the dk/dv transfer behind the compute and
    # the TPU scheduler then refuses to hoist ANY of the step's
    # permutes). Cost: one extra seed/return hop per buffer (n instead
    # of n−1), fully overlapped — latency hiding is first-order at 16k,
    # the ~1/(n−1) extra ICI bytes are not.
    dk_acc, dv_acc = zeros(), zeros()
    dk_pend, dv_pend = zeros(), zeros()

    def body(carry, t):
        (k_cur, v_cur, kseg_cur, dk_acc, dv_acc, dk_pend, dv_pend,
         dq) = carry
        # hop the accumulator completed through this device last step,
        # and prefetch shard t+1 — all carry-only dependences
        dk_acc = jax.lax.ppermute(dk_acc + dk_pend, axis_name, inv)
        dv_acc = jax.lax.ppermute(dv_acc + dv_pend, axis_name, inv)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, inv)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, inv)
        kseg_nxt = (jax.lax.ppermute(kseg_cur, axis_name, inv)
                    if has_segs else kseg_cur)
        src = (idx + 1 + t) % n if needs_offs else 0
        dq_t, dk_pend, dv_pend = step_grads(k_cur, v_cur, kseg_cur, src)
        dq = dq + dq_t.astype(f32)
        return (k_nxt, v_nxt, kseg_nxt, dk_acc, dv_acc, dk_pend,
                dv_pend, dq), None

    (_, _, _, dk_acc, dv_acc, dk_pend, dv_pend, dq), _ = jax.lax.scan(
        body,
        (k_cur, v_cur, kseg_cur, dk_acc, dv_acc, dk_pend, dv_pend, dq),
        jnp.arange(0, n - 1))
    # final hop carries the last pending contribution to each shard's
    # owner, where the prologue's local term folds in (order-free adds)
    dk = jax.lax.ppermute(dk_acc + dk_pend, axis_name, inv) + dk_own
    dv = jax.lax.ppermute(dv_acc + dv_pend, axis_name, inv) + dv_own
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12))
def _ring(q, k, v, qseg, seed, axis_name, causal, sm_scale, has_segs,
          block_q, block_k, dropout_p, skip_masked):
    out, _ = _ring_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale,
                            has_segs, block_q, block_k,
                            dropout_p=dropout_p, seed=seed,
                            skip_masked=skip_masked)
    return out.astype(q.dtype)


def _ring_fwd_rule(q, k, v, qseg, seed, axis_name, causal, sm_scale,
                   has_segs, block_q, block_k, dropout_p, skip_masked):
    out, lse = _ring_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale,
                              has_segs, block_q, block_k,
                              dropout_p=dropout_p, seed=seed,
                              skip_masked=skip_masked)
    out = out.astype(q.dtype)
    return out, (q, k, v, qseg, seed, out, lse)


def _ring_bwd_rule(axis_name, causal, sm_scale, has_segs, block_q, block_k,
                   dropout_p, skip_masked, res, do):
    q, k, v, qseg, seed, out, lse = res
    dq, dk, dv = _ring_bwd_loop(q, k, v, qseg, out, lse, do, axis_name,
                                causal, sm_scale, has_segs, block_q,
                                block_k, dropout_p=dropout_p, seed=seed,
                                skip_masked=skip_masked)
    f0 = np.zeros(jnp.shape(qseg), dtype=jax.dtypes.float0)
    f0s = np.zeros(jnp.shape(seed), dtype=jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            f0, f0s)


_ring.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_attention(q, k, v, axis_name, *, causal: bool = False,
                   sm_scale: float | None = None, segment_ids=None,
                   block_q: int | None = None, block_k: int | None = None,
                   use_custom_vjp: bool = True, dropout_p: float = 0.0,
                   dropout_seed=None, skip_masked: bool = True):
    """Attention over a sequence sharded on mesh axis ``axis_name``.

    ``q``: local shard (B, Hq, S_local, D); ``k``/``v``: (B, Hkv, S_local,
    D). The global sequence is ``ring_size * S_local``, laid out in
    axis-index order. ``segment_ids``: local (B, S_local) shard of the
    global segment ids (rides the ring alongside K/V). Returns the local
    output shard (B, Hq, S_local, D).

    The schedule is double-buffered: each ring step issues the ppermute
    for the NEXT K/V shard before attending the current one, so the ICI
    transfer hides behind the attention dots (forward AND backward; the
    property is pinned on optimized HLO by `testing.hlo_probe`).
    ``use_custom_vjp=False`` reverts the backward to XLA's transpose of
    the forward scan (serialized transfers) — kept for parity tests and
    as an escape hatch; forward numerics are identical either way.
    ``dropout_p``/``dropout_seed``: in-kernel attention-probability
    dropout (`ops.attention.flash_attention`); the seed must be
    REPLICATED over the ring (every device passes the same int32) — the
    counter-based mask keys on each shard's global k-offset, so shards
    draw disjoint streams and serial/overlapped schedules drop
    identical weights. ``skip_masked=False`` disables the causal
    lax.cond shard skip (A/B knob for tools/bench_cond_elision.py;
    numerics identical).
    """
    sm_scale = None if sm_scale is None else float(sm_scale)
    dropout_p = float(dropout_p)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 needs an explicit int32 "
                         "dropout_seed (replicated over the ring)")
    seed = (jnp.asarray(dropout_seed, jnp.int32) if dropout_p > 0.0
            else jnp.zeros((), jnp.int32))
    has_segs = segment_ids is not None
    qseg = (segment_ids if has_segs
            else jnp.zeros((1, 1), jnp.int32))
    if use_custom_vjp:
        return _ring(q, k, v, qseg, seed, axis_name, causal, sm_scale,
                     has_segs, block_q, block_k, dropout_p, skip_masked)
    out, _ = _ring_fwd_loop(q, k, v, qseg, axis_name, causal, sm_scale,
                            has_segs, block_q, block_k,
                            dropout_p=dropout_p, seed=seed,
                            skip_masked=skip_masked)
    return out.astype(q.dtype)


def ring_attention_serial(q, k, v, axis_name, *, causal: bool = False,
                          sm_scale: float | None = None, segment_ids=None,
                          block_q: int | None = None,
                          block_k: int | None = None,
                          dropout_p: float = 0.0, dropout_seed=None,
                          skip_masked: bool = True):
    """The ORIGINAL serialized schedule — rotate first, then attend, so
    every one of the n−1 ICI transfers is exposed (the attend consumes
    the permute it just issued). Retained as the A/B baseline
    (``tools/bench_ring_ab.py``), the parity anchor for the
    double-buffered rewrite, and the hlo_probe negative control (this
    loop body must FAIL the overlap probe). Backward is XLA's transpose
    of the scan. Numerics are identical to `ring_attention` (same
    attend/merge order)."""
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Hq, Sq, _ = q.shape
    Sk = k.shape[2]
    q_off = idx * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_segs = segment_ids is not None
    qseg = segment_ids
    dropout_p = float(dropout_p)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 needs an explicit int32 "
                         "dropout_seed (replicated over the ring)")

    out0 = _vary(jnp.zeros(q.shape, jnp.promote_types(q.dtype,
                                                      jnp.float32)),
                 axis_name)
    lse0 = _vary(jnp.full((B, Hq, Sq), NEG_INF, jnp.float32), axis_name)

    def attend(k_cur, v_cur, kseg_cur, t, out, lse):
        src = (idx - t) % n           # who this K/V shard belongs to
        k_off = src * Sk

        def run(_):
            return flash_attention(
                q, k_cur, v_cur, causal=causal,
                segment_ids=(qseg, kseg_cur) if has_segs else None,
                sm_scale=sm_scale, q_offset=q_off, k_offset=k_off,
                block_q=block_q, block_k=block_k, return_lse=True,
                dropout_p=dropout_p, dropout_seed=dropout_seed)

        def skip(_):
            return (_vary(jnp.zeros(q.shape, q.dtype), axis_name),
                    _vary(jnp.full((B, Hq, Sq), NEG_INF, jnp.float32),
                          axis_name))

        if causal and skip_masked:
            # visiting shard strictly in the future → fully masked
            out_t, lse_t = jax.lax.cond(k_off > q_off + Sq - 1, skip, run,
                                        None)
        else:
            out_t, lse_t = run(None)
        return _merge(out, lse, out_t, lse_t)

    def step(carry, t):
        # rotate first, then attend: the attend CONSUMES this step's
        # permute, so the transfer latency is fully exposed
        k_cur, v_cur, kseg_cur, out, lse = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        if has_segs:
            kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        out, lse = attend(k_cur, v_cur, kseg_cur, t, out, lse)
        return (k_cur, v_cur, kseg_cur, out, lse), None

    kseg0 = qseg if has_segs else jnp.zeros((), jnp.int32)
    out, lse = attend(k, v, kseg0, 0, out0, lse0)  # local shard, no comm
    if n > 1:
        (_, _, _, out, lse), _ = jax.lax.scan(
            step, (k, v, kseg0, out, lse), jnp.arange(1, n))
    return out.astype(q.dtype)
