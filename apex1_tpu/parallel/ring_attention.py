"""Ring attention — context parallelism for long sequences over ICI.

The reference has NO long-context attention mechanism (SURVEY.md §5.7:
``apex/contrib/fmha`` caps seqlen at 512; Megatron SP shards LN/dropout
activations only). Its closest pattern is the spatial-parallel halo
exchange (``apex/contrib/bottleneck/halo_exchangers.py :: HaloExchangerNccl``
— activation-domain decomposition with neighbor transfers), which this
module generalizes to attention: shard the SEQUENCE over a mesh axis and
rotate K/V shards around the ring with ``jax.lax.ppermute`` (ICI
neighbor transfers), merging partial-attention results with the
numerically-stable logsumexp merge.

Per ring step each device computes flash attention of its local Q shard
against the visiting K/V shard (`apex1_tpu.ops.attention.flash_attention`
with traced global offsets for the causal mask), yielding ``(out_t,
lse_t)``; partials combine exactly:

    lse   = logaddexp(lse_a, lse_b)
    out   = out_a·exp(lse_a − lse) + out_b·exp(lse_b − lse)

Fully-masked (future, under causal) visiting shards are skipped with
``lax.cond`` — their transfer still rides the ring but their FLOPs are not
spent. The whole loop is a ``lax.scan`` (static trip count = ring size),
so reverse-mode AD works end-to-end: the backward pass is the transposed
ring (ppermute with inverted permutation), inserted by XLA automatically.

Use inside ``jax.shard_map`` with the sequence dimension sharded over
``axis_name``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex1_tpu.ops._common import NEG_INF
from apex1_tpu.ops.attention import flash_attention


def _axis_size(axis_name) -> int:
    return jax.lax.axis_size(axis_name)


def _merge(out_a, lse_a, out_b, lse_b):
    """Exact combine of two normalized partial attentions (fp32 stats)."""
    lse = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.exp(lse_a - lse)[..., None]
    w_b = jnp.exp(lse_b - lse)[..., None]
    return out_a * w_a + out_b.astype(out_a.dtype) * w_b, lse


def ring_attention(q, k, v, axis_name, *, causal: bool = False,
                   sm_scale: float | None = None, segment_ids=None,
                   block_q: int | None = None, block_k: int | None = None):
    """Attention over a sequence sharded on mesh axis ``axis_name``.

    ``q``: local shard (B, Hq, S_local, D); ``k``/``v``: (B, Hkv, S_local,
    D). The global sequence is ``ring_size * S_local``, laid out in
    axis-index order. ``segment_ids``: local (B, S_local) shard of the
    global segment ids (rides the ring alongside K/V). Returns the local
    output shard (B, Hq, S_local, D).
    """
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Hq, Sq, _ = q.shape
    Sk = k.shape[2]
    q_off = idx * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_segs = segment_ids is not None
    qseg = segment_ids

    def _vary(x):  # mark as device-varying over the ring axis (scan/cond
        return jax.lax.pcast(x, axis_name, to="varying")  # carry typing)

    out0 = _vary(jnp.zeros(q.shape, jnp.promote_types(q.dtype, jnp.float32)))
    lse0 = _vary(jnp.full((B, Hq, Sq), NEG_INF, jnp.float32))

    def attend(k_cur, v_cur, kseg_cur, t, out, lse):
        src = (idx - t) % n           # who this K/V shard belongs to
        k_off = src * Sk

        def run(_):
            return flash_attention(
                q, k_cur, v_cur, causal=causal,
                segment_ids=(qseg, kseg_cur) if has_segs else None,
                sm_scale=sm_scale, q_offset=q_off, k_offset=k_off,
                block_q=block_q, block_k=block_k, return_lse=True)

        def skip(_):
            return (_vary(jnp.zeros(q.shape, q.dtype)),
                    _vary(jnp.full((B, Hq, Sq), NEG_INF, jnp.float32)))

        if causal:
            # visiting shard strictly in the future → fully masked
            out_t, lse_t = jax.lax.cond(k_off > q_off + Sq - 1, skip, run,
                                        None)
        else:
            out_t, lse_t = run(None)
        return _merge(out, lse, out_t, lse_t)

    def step(carry, t):
        # rotate first, then attend: n attends, n−1 neighbor transfers
        k_cur, v_cur, kseg_cur, out, lse = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        if has_segs:
            kseg_cur = jax.lax.ppermute(kseg_cur, axis_name, perm)
        out, lse = attend(k_cur, v_cur, kseg_cur, t, out, lse)
        return (k_cur, v_cur, kseg_cur, out, lse), None

    kseg0 = qseg if has_segs else jnp.zeros((), jnp.int32)
    out, lse = attend(k, v, kseg0, 0, out0, lse0)  # local shard, no comm
    if n > 1:
        (_, _, _, out, lse), _ = jax.lax.scan(
            step, (k, v, kseg0, out, lse), jnp.arange(1, n))
    return out.astype(q.dtype)
