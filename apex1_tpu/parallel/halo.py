"""Halo exchange — spatial parallelism for convolutions.

Reference: ``apex/contrib/bottleneck/halo_exchangers.py ::
HaloExchangerPeer / HaloExchangerNccl`` (+ csrc ``peer_memory``,
``nccl_p2p``): a conv layer's activations are split across GPUs along H;
each step pushes boundary rows ("halos") to spatial neighbors via CUDA IPC
peer copies or raw ncclSend/Recv.

TPU-native: one ``jax.lax.ppermute`` per direction over the mesh axis —
the ICI neighbor transfer IS the halo push, no peer-memory pool or p2p
plumbing to manage (SURVEY.md §2.6 "Spatial parallelism"). Non-periodic
boundaries zero-fill (conv SAME-padding semantics at the global edge).

``halo_exchange`` returns the local shard extended with its neighbors'
boundary slices; `spatial_conv2d` shows the full pattern: exchange →
conv 'VALID' on the extended shard ≙ global conv 'SAME' on the unsplit
tensor (asserted in tests).

`exchange_overlap` is the communication-overlap entry (the reference's
``HaloExchangerPeer`` issues its peer copies on a side stream for the
same reason): both directional ppermutes are issued BEFORE the
caller-supplied interior compute runs, and since that compute has no
data dependence on the in-flight halos, XLA's async collectives hide
the neighbor transfers behind it — the same prefetch shape as the
double-buffered `parallel.ring_attention`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _boundary_transfers(x, axis_name: str, *, halo: int, dim: int,
                        periodic: bool):
    """Issue the two directional halo ppermutes; returns the incoming
    ``(from_prev, from_next)`` boundary slices (zero-masked at the global
    edges unless ``periodic``)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    def take(arr, lo, hi):
        sl = [slice(None)] * arr.ndim
        sl[dim] = slice(lo, hi)
        return arr[tuple(sl)]

    size = x.shape[dim]
    if halo > size:
        raise ValueError(f"halo {halo} exceeds local extent {size}")
    # my top rows go to the previous rank (they become its bottom halo)
    fwd = [(i, (i + 1) % n) for i in range(n)]   # send downward
    bwd = [(i, (i - 1) % n) for i in range(n)]   # send upward
    from_prev = jax.lax.ppermute(take(x, size - halo, size), axis_name, fwd)
    from_next = jax.lax.ppermute(take(x, 0, halo), axis_name, bwd)
    if not periodic:
        zero = jnp.zeros_like(from_prev)
        from_prev = jnp.where(idx == 0, zero, from_prev)
        from_next = jnp.where(idx == n - 1, jnp.zeros_like(from_next),
                              from_next)
    return from_prev, from_next


def halo_exchange(x, axis_name: str, *, halo: int, dim: int = 1,
                  periodic: bool = False):
    """Extend local shard ``x`` with ``halo`` boundary slices from both
    spatial neighbors along sharded dimension ``dim``."""
    if halo <= 0:
        return x
    from_prev, from_next = _boundary_transfers(
        x, axis_name, halo=halo, dim=dim, periodic=periodic)
    return jnp.concatenate([from_prev, x, from_next], axis=dim)


def exchange_overlap(x, interior_fn, axis_name: str, *, halo: int,
                     dim: int = 1, periodic: bool = False):
    """Halo exchange with the neighbor transfers overlapped by
    ``interior_fn``.

    Issues both directional ppermutes FIRST, then runs
    ``interior_fn(x)`` — compute that depends only on the local shard
    (the interior rows of a conv, a pointwise prologue, statistics…) —
    while the halos are in flight, and only then concatenates the
    extended shard. Returns ``(extended, interior)`` where ``extended``
    is exactly ``halo_exchange(x, ...)`` and ``interior`` is exactly
    ``interior_fn(x)`` — the overlap changes scheduling, not values
    (pinned by tests; the ordering property itself is checkable with
    `apex1_tpu.testing.hlo_probe` on loops built from this pattern).
    """
    if halo <= 0:
        return x, interior_fn(x)
    from_prev, from_next = _boundary_transfers(
        x, axis_name, halo=halo, dim=dim, periodic=periodic)
    # interior compute has no data dependence on the in-flight halos —
    # XLA schedules it between the permute start/done pair
    interior = interior_fn(x)
    return (jnp.concatenate([from_prev, x, from_next], axis=dim),
            interior)


def spatial_conv2d(x, kernel, axis_name: str, *, dim: int = 1):
    """SAME-padded NHWC conv over a spatially-sharded activation: halo
    exchange on the sharded axis (``dim``: 1 = H-split, 2 = W-split), then
    a conv that is VALID on the sharded axis and SAME-padded on the other
    — ≙ the reference's ``SpatialBottleneck`` conv split
    (``apex/contrib/bottleneck/bottleneck.py :: SpatialBottleneck``)."""
    if dim not in (1, 2):
        raise ValueError("dim must be 1 (H-sharded) or 2 (W-sharded)")
    kh, kw = kernel.shape[0], kernel.shape[1]
    if kh % 2 == 0 or kw % 2 == 0:
        raise ValueError("odd kernel sizes only")
    halo = (kh if dim == 1 else kw) // 2
    other_pad = (kw if dim == 1 else kh) // 2
    ext = halo_exchange(x, axis_name, halo=halo, dim=dim)
    padding = (((0, 0), (other_pad, other_pad)) if dim == 1
               else ((other_pad, other_pad), (0, 0)))
    return jax.lax.conv_general_dilated(
        ext, kernel, window_strides=(1, 1), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
