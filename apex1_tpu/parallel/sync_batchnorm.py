"""SyncBatchNorm — reference ``apex/parallel/optimized_sync_batchnorm.py``
(+ ``csrc/syncbn.cpp / welford.cu``) and ``apex/parallel/sync_batchnorm.py``.

Reference forward (§3.5 call stack): local per-channel Welford mean/var →
all-gather stats over the process group (optionally a ``group_size``
subgroup) → parallel Welford merge → normalize; backward all-reduces the
two grad-stat sums. Channel-last fast path.

TPU-native: the Welford merge collapses to a psum of (Σx, Σx², n) — a
single fused collective on the VPU (count-weighted two-moment merge is
algebraically identical to parallel Welford, and fp32 accumulation gives
the same stability on TPU). ``group_size`` subgrouping maps to
``axis_index_groups`` of the psum. The backward comes out of ``jax.grad``
with exactly the reference's two cross-replica sums because the stats are
computed through the psum (its transpose re-broadcasts the cotangents).

`convert_syncbn_model` walks a flax module tree replacing BatchNorm with
SyncBatchNorm, ≙ the reference's recursive module converter.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_DP


def sync_batch_stats(x, *, axis_name=AXIS_DP, reduce_axes, group_size=None):
    """Cross-replica per-channel (mean, var, count): psum of
    (Σx, Σx², n) — the fused ``welford_parallel`` merge."""
    n_local = 1
    for ax in reduce_axes:
        n_local *= x.shape[ax]
    s1 = jnp.sum(x.astype(jnp.float32), axis=reduce_axes)
    s2 = jnp.sum(jnp.square(x.astype(jnp.float32)), axis=reduce_axes)
    groups = None
    if group_size is not None:
        world = jax.lax.axis_size(axis_name)
        if world % group_size:
            raise ValueError(f"group_size {group_size} must divide dp world "
                             f"{world}")
        groups = [list(range(g * group_size, (g + 1) * group_size))
                  for g in range(world // group_size)]
    s1 = jax.lax.psum(s1, axis_name, axis_index_groups=groups)
    s2 = jax.lax.psum(s2, axis_name, axis_index_groups=groups)
    n = n_local * (group_size or jax.lax.axis_size(axis_name))
    mean = s1 / n
    var = s2 / n - jnp.square(mean)
    return mean, var, n


class SyncBatchNorm(nn.Module):
    """``apex.parallel.SyncBatchNorm(num_features, eps, momentum, affine,
    track_running_stats, process_group, channel_last)`` equivalent.

    Input layout: channel-last (..., C) — the reference's NHWC fast path is
    the only layout TPU wants. ``use_running_average`` switches to inference
    stats. Running stats live in the ``batch_stats`` flax collection with
    the reference's momentum convention
    (new = (1−momentum)·old + momentum·batch)."""

    num_features: Optional[int] = None  # inferred from input if None
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    use_scale: bool = True   # affine granularity (flax use_scale/use_bias)
    use_bias: bool = True
    track_running_stats: bool = True
    use_running_average: Optional[bool] = None
    feature_axis: int = -1
    axis_name: Optional[str] = AXIS_DP
    group_size: Optional[int] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        feat_ax = self.feature_axis % x.ndim
        C = self.num_features or x.shape[feat_ax]
        reduce_axes = tuple(a for a in range(x.ndim) if a != feat_ax)
        stat_shape = tuple(1 if a != feat_ax else C for a in range(x.ndim))
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((C,), jnp.float32))
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # Probe axis binding with the cheap size query only, so a real
            # NameError inside sync_batch_stats is never swallowed.
            axis_bound = False
            if self.axis_name is not None:
                try:
                    jax.lax.axis_size(self.axis_name)
                    axis_bound = True
                except NameError:  # single-replica / untraced test context
                    axis_bound = False
            if axis_bound:
                mean, var, n = sync_batch_stats(
                    x, axis_name=self.axis_name,
                    reduce_axes=reduce_axes,
                    group_size=self.group_size)
            else:
                x32 = x.astype(jnp.float32)
                mean = jnp.mean(x32, axis=reduce_axes)
                var = jnp.var(x32, axis=reduce_axes)
                n = 1
                for ax in reduce_axes:
                    n *= x.shape[ax]
            if self.track_running_stats and not self.is_initializing():
                # running_var stores the UNBIASED variance (reference /
                # torch convention), batch normalization uses the biased one
                unbiased = var * (n / max(n - 1, 1))
                ra_mean.value = ((1 - self.momentum) * ra_mean.value
                                 + self.momentum * mean)
                ra_var.value = ((1 - self.momentum) * ra_var.value
                                + self.momentum * unbiased)
        mean = mean.reshape(stat_shape)
        var = var.reshape(stat_shape)
        y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine and self.use_scale:
            scale = self.param("scale", nn.initializers.ones, (C,),
                               jnp.float32)
            y = y * scale.reshape(stat_shape)
        if self.affine and self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (C,),
                              jnp.float32)
            y = y + bias.reshape(stat_shape)
        return y.astype(x.dtype)


def convert_syncbn_model(module: nn.Module, *, axis_name=AXIS_DP,
                         group_size=None) -> nn.Module:
    """≙ ``apex.parallel.convert_syncbn_model(net)``: return a copy of a
    flax module tree with every ``nn.BatchNorm`` swapped for
    `SyncBatchNorm`. Flax modules are frozen dataclasses, so this clones
    with replaced submodules (same param tree structure)."""
    import dataclasses as dc

    def convert(m):
        if isinstance(m, nn.BatchNorm):
            return SyncBatchNorm(
                eps=m.epsilon, momentum=1.0 - m.momentum,
                affine=m.use_scale or m.use_bias,
                use_scale=m.use_scale, use_bias=m.use_bias,
                use_running_average=m.use_running_average,
                feature_axis=(m.axis if isinstance(m.axis, int) else -1),
                axis_name=axis_name, group_size=group_size,
                name=m.name)
        if isinstance(m, nn.Module):
            changes = {}
            for f in dc.fields(m):
                if f.name in ("parent", "name"):
                    continue
                v = getattr(m, f.name, None)
                nv = convert(v)
                if nv is not v:
                    changes[f.name] = nv
            return m.clone(**changes) if changes else m
        # recurse into containers so BatchNorms inside Sequence/dict fields
        # (e.g. nn.Sequential's layers tuple) are found
        if isinstance(m, (list, tuple)):
            nv = [convert(v) for v in m]
            if all(a is b for a, b in zip(nv, m)):
                return m
            if isinstance(m, tuple) and hasattr(m, "_fields"):
                return type(m)(*nv)  # NamedTuple: positional fields
            return type(m)(nv)
        if isinstance(m, dict):
            nv = {k: convert(v) for k, v in m.items()}
            if all(nv[k] is m[k] for k in m):
                return m
            return nv
        return m

    return convert(module)
