"""Multi-process launcher — reference ``apex/parallel/multiproc.py`` (the
tiny pre-``torchrun`` launcher spawning ``world_size`` script copies with
``--rank i``).

JAX is multi-controller: one process per HOST (not per chip), each seeing
its local chips, joined by ``jax.distributed.initialize``. This module
provides both halves:

- `launch(script, num_processes)` — spawn N local processes wired with
  the JAX distributed env (coordinator address, process ids). With
  ``cpu_devices_per_process`` it builds a multi-process CPU cluster on one
  machine — the harness for multi-controller tests without a pod
  (SURVEY.md §4.2.4).
- `init_distributed()` — in-process entry: call at the top of a training
  script on each host (reads the env `launch` sets, or GKE/TPU-pod env).

``python -m apex1_tpu.parallel.multiproc train.py ...`` mirrors the
reference's CLI shape.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Sequence


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """≙ ``torch.distributed.init_process_group`` at script top. On TPU
    pods with no args, jax auto-discovers topology from the environment."""
    import jax

    # a sitecustomize may pin jax_platforms via jax.config, which an env
    # var cannot override — re-assert the env var's choice explicitly so
    # `launch(cpu_devices_per_process=...)` children actually run on CPU
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def launch(script: str, args: Sequence[str] = (), *,
           num_processes: int = 2, coordinator_port: int = 12355,
           cpu_devices_per_process: int = 0,
           env: Optional[dict] = None) -> int:
    """Spawn ``num_processes`` copies of ``script``; returns the first
    nonzero exit code (0 if all succeeded). Each child gets
    ``APEX1_COORDINATOR/APEX1_NUM_PROCESSES/APEX1_PROCESS_ID`` plus the
    standard JAX distributed variables."""
    procs = []
    for rank in range(num_processes):
        child_env = dict(os.environ)
        child_env.update(env or {})
        child_env.update({
            "APEX1_COORDINATOR": f"127.0.0.1:{coordinator_port}",
            "APEX1_NUM_PROCESSES": str(num_processes),
            "APEX1_PROCESS_ID": str(rank),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{coordinator_port}",
            "JAX_NUM_PROCESSES": str(num_processes),
            "JAX_PROCESS_ID": str(rank),
        })
        if cpu_devices_per_process:
            child_env["JAX_PLATFORMS"] = "cpu"
            child_env["XLA_FLAGS"] = (
                child_env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                  f"{cpu_devices_per_process}")
        procs.append(subprocess.Popen(
            [sys.executable, script, *args], env=child_env))
    # poll rather than wait serially: if one rank dies, its peers may be
    # blocked in a collective forever — reap them instead of hanging
    import time as _time
    first_bad = 0
    while procs:
        alive = []
        for p in procs:
            code = p.poll()
            if code is None:
                alive.append(p)
            elif code and not first_bad:
                first_bad = code
        if first_bad and alive:
            deadline = _time.time() + 10  # grace for co-failing ranks
            while alive and _time.time() < deadline:
                alive = [p for p in alive if p.poll() is None]
                _time.sleep(0.1)
            for p in alive:
                p.terminate()
            for p in alive:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
            return first_bad
        procs = alive
        if procs:
            _time.sleep(0.05)
    return first_bad


def init_from_env() -> None:
    """Child-side convenience: initialize from `launch`'s env vars."""
    init_distributed(
        coordinator_address=os.environ["APEX1_COORDINATOR"],
        num_processes=int(os.environ["APEX1_NUM_PROCESSES"]),
        process_id=int(os.environ["APEX1_PROCESS_ID"]))


def main(argv: Sequence[str] = ()) -> int:
    argv = list(argv) or sys.argv[1:]
    if not argv:
        print("usage: python -m apex1_tpu.parallel.multiproc [--nproc N] "
              "script.py [args...]", file=sys.stderr)
        return 2
    nproc = 2
    if argv[0] == "--nproc":
        nproc = int(argv[1])
        argv = argv[2:]
    return launch(argv[0], argv[1:], num_processes=nproc)


if __name__ == "__main__":
    sys.exit(main())
