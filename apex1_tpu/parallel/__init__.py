"""Distributed training services — reference ``apex/parallel`` +
``apex/contrib/optimizers``."""

from apex1_tpu.parallel.ddp import (  # noqa: F401
    DistributedDataParallel, allreduce_grads, broadcast_params)
from apex1_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm, convert_syncbn_model, sync_batch_stats)
from apex1_tpu.parallel.distributed_optimizer import (  # noqa: F401
    distributed_fused_adam, distributed_fused_lamb, fsdp_param_specs,
    shard_opt_state_specs)
from apex1_tpu.parallel.halo import (  # noqa: F401
    exchange_overlap, halo_exchange, spatial_conv2d)
from apex1_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention, ring_attention_serial)
from apex1_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
