"""Data-parallel gradient synchronization — reference
``apex/parallel/distributed.py :: DistributedDataParallel``.

The reference registers per-grad backward hooks that fill flat buckets
(``message_size`` elements), all-reduces each bucket on a side CUDA stream
overlapped with the remaining backward, with ``delay_allreduce``,
``gradient_predivide_factor`` and ``retain_allreduce_buffers`` knobs, and
first-iteration bucket-structure discovery.

TPU-native: under ``pjit`` with batch sharded over dp, XLA inserts ONE fused
gradient psum and overlaps it with the backward automatically (async
collectives + latency-hiding scheduler) — the hook/bucket/stream machinery
has no equivalent code (SURVEY §7.0). What remains meaningful, and is
provided here:

- an explicit ``allreduce_grads`` for the ``shard_map`` path (with the
  reference's predivide semantics);
- a `DistributedDataParallel` wrapper keeping the reference's constructor
  surface so ported training loops read the same, implemented as a
  loss-fn transformer;
- parameter broadcast at init (≙ the reference broadcasting params from
  rank 0 so replicas start identical).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from apex1_tpu.core.mesh import AXIS_DP, AXIS_FSDP


def allreduce_grads(grads, *, axis_names=(AXIS_DP,),
                    gradient_predivide_factor: float = 1.0):
    """Mean-reduce grads over the dp axes (inside shard_map).

    Reference semantics: predivide by ``gradient_predivide_factor``, sum,
    postdivide by ``world/factor`` — net effect a mean, with the factor
    trading overflow headroom (fp16) for underflow; reproduced exactly.
    """
    world = 1
    for ax in axis_names:
        world *= jax.lax.axis_size(ax)
    pre = 1.0 / gradient_predivide_factor
    post = gradient_predivide_factor / world

    def sync(g):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        g = g * pre
        for ax in axis_names:
            g = jax.lax.psum(g, ax)
        return g * post

    return jax.tree_util.tree_map(sync, grads)


def broadcast_params(params, *, axis_names=(AXIS_DP, AXIS_FSDP)):
    """Make params bit-identical across dp ranks (rank-0 wins) — ≙ the
    init-time ``flat_dist_call`` broadcast. Under single-controller JAX
    replicas are already identical; this is the shard_map-path guard."""
    def bcast(p):
        idx = 0
        for ax in axis_names:
            idx = idx + jax.lax.axis_index(ax)
        is0 = (idx == 0)
        send = jnp.where(is0, p, jnp.zeros_like(p))
        for ax in axis_names:
            send = jax.lax.psum(send, ax)
        return send

    return jax.tree_util.tree_map(bcast, params)


class DistributedDataParallel:
    """Constructor-surface parity wrapper
    (``DistributedDataParallel(module, message_size, delay_allreduce, ...)``).

    Wraps a ``loss_fn(params, batch)``; `value_and_grad` returns grads
    already synchronized over dp. ``message_size``/``delay_allreduce``/
    ``retain_allreduce_buffers`` are accepted and recorded but have no
    effect — bucketing and overlap are XLA's job (documented N/A,
    SURVEY §2.6 DP row).
    """

    def __init__(self, loss_fn: Callable, *,
                 message_size: int = 10_000_000,
                 delay_allreduce: bool = False,
                 gradient_predivide_factor: float = 1.0,
                 retain_allreduce_buffers: bool = False,
                 axis_names=(AXIS_DP,)):
        self.loss_fn = loss_fn
        self.message_size = message_size
        self.delay_allreduce = delay_allreduce
        self.gradient_predivide_factor = gradient_predivide_factor
        self.retain_allreduce_buffers = retain_allreduce_buffers
        self.axis_names = tuple(axis_names)

    def __call__(self, params, *batch):
        return self.loss_fn(params, *batch)

    def value_and_grad(self):
        vg = jax.value_and_grad(self.loss_fn)

        def f(params, *batch):
            loss, grads = vg(params, *batch)
            grads = allreduce_grads(
                grads, axis_names=self.axis_names,
                gradient_predivide_factor=self.gradient_predivide_factor)
            return loss, grads

        return f
