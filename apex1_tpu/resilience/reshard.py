"""Deterministic host-side checkpoint resharding between
``apex1-plan-v1`` layouts — the bridge between the PR 6 resilience
substrate (bit-exact single-topology resume) and the PR 12 planner
(which can pick a legal layout for ANY surviving chip count).

A committed checkpoint that banks its producing plan in the manifest
``meta["plan"]`` (`ResilientCheckpointer(plan=...)`) is
SELF-DESCRIBING: `reshard_state` can remap its state tree onto any
other legal plan for the same model without asking the training
program anything. Three leaf classes, derived from the plans alone:

- **pipeline-stacked leaves** (``['chunk']`` in the key path, leading
  dims ``(num_chunks, pp, layers_per_stage)``): the chunk-major
  layout assigns global layer ``(v·pp + s)·lps + j`` to slot
  ``(v, s, j)`` — the row-major flattening of the stack axes
  (`models.llama_3d.reshape_chunks`'s contract) — so re-partitioning
  for any other ``(V', PP', lps')`` factorization of the same
  ``num_layers`` is a plain reshape. Applies identically to params
  and to optimizer moments mirroring the param tree.
- **ZeRO flat shards** (``…_shard`` leaves of
  `parallel.distributed_optimizer` states, 1-D, padded to a multiple
  of the plan's dp): repacked via
  `parallel.distributed_optimizer.repack_flat_shard` — strip the old
  world's zero padding at the true flat length, re-pad for the new
  world. Zero padding is EXACT, not approximate: the padded tail of
  the flat buffer carries zero params and zero grads, so Adam/LAMB
  moments there stay identically zero on every step — the repacked
  state equals what a from-scratch run at the new world size would
  have banked.
- **everything else** (shared/vocab params, loss-scale state, step
  counters, sentinel counters): layout-independent host bytes, copied
  verbatim.

NEVER TRUSTED, ALWAYS VERIFIED — the contract that makes a resharded
checkpoint restorable with a straight face:

1. the SOURCE is digest-verified before the remap (`verify_files` +
   `verify_tree` against its manifest);
2. the remap itself is conservation-checked (restacked leaves:
   byte-identical flat content; repacked shards: byte-identical
   unpadded prefix + all-zero new padding);
3. every remapped leaf is re-digested through `manifest.tree_entries`
   into a FRESH manifest, committed with the same
   temp-dir → manifest → rename chain as a live save, and the result
   is `verify_files`-checked before the path is returned — so the
   later restore re-verifies leaves end-to-end exactly like any other
   checkpoint.

DETERMINISM: pure numpy on host bytes, no clocks, no environment
probes — the same (checkpoint, target plan) always produces the same
leaf digests (pinned in tests/test_elastic.py), which is what lets an
elastic resume and its from-checkpoint control run start bit-equal.

Structure CHANGES are refused, not guessed at: flipping ``zero`` on or
off between plans changes the optimizer state's tree structure
(moments-as-param-tree vs flat shards) — that is a re-plan constraint
(`elastic_resume` pins the search via ``require_zero``), not a leaf
remap, and raises a typed :class:`LayoutMismatch`.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Any, Optional, Tuple

import numpy as np

from apex1_tpu.checkpoint import (CheckpointError, restore_checkpoint,
                                  save_checkpoint)
from apex1_tpu.resilience.manifest import (Manifest, read_manifest,
                                           tree_entries, verify_files,
                                           verify_tree, write_manifest)

#: must match planner.emit.PLAN_SCHEMA (asserted by test_elastic) —
#: spelled here so reading a manifest's plan meta stays jax/planner-free
PLAN_SCHEMA = "apex1-plan-v1"

_STATE_SUBDIR = "state"


class LayoutMismatch(CheckpointError):
    """The checkpoint's banked layout (the ``apex1-plan-v1`` spec in
    its manifest meta) and the layout being asked for disagree — or
    the checkpoint has no banked plan at all. Subclasses
    `checkpoint.CheckpointError` so existing typed handling still
    catches it; the message always says what to do next (resume
    through `resilience.elastic_resume` / `reshard_checkpoint`, or
    re-save with ``ResilientCheckpointer(plan=...)``)."""


def plan_meta(manifest: Manifest, path: str | os.PathLike) -> dict:
    """The producing plan banked in a manifest's meta, or a typed
    :class:`LayoutMismatch` — old checkpoints without it get a clear
    error, never a traceback from whatever consumed the None."""
    plan = manifest.meta.get("plan")
    if not isinstance(plan, dict) or plan.get("schema") != PLAN_SCHEMA:
        raise LayoutMismatch(
            path, "no plan meta: this checkpoint does not bank its "
            f"producing {PLAN_SCHEMA} spec, so it cannot be resharded "
            "or layout-checked; re-save it with "
            "ResilientCheckpointer(plan=...) (docs/robustness.md "
            "§ Elastic resume)")
    return plan


def mesh_str(plan: dict) -> str:
    """Compact ``dp2 pp2 cp1 ep1 tp2 /8`` label for messages/events."""
    m = plan.get("mesh", {})
    return (" ".join(f"{a}{m.get(a, '?')}"
                     for a in ("dp", "pp", "cp", "ep", "tp"))
            + f" /{plan.get('n_devices', '?')}")


# -- remap geometry from the plans ------------------------------------------

def _stack_dims(plan: dict, path: str) -> Tuple[int, int, int]:
    """(num_chunks, pp, layers_per_stage) — the chunk-stack leading
    dims the plan implies (`models.llama_3d` stacking)."""
    layers = int(plan["model"]["num_layers"])
    chunks = int(plan["schedule"]["num_chunks"])
    pp = int(plan["mesh"]["pp"])
    if chunks < 1 or pp < 1 or layers % (chunks * pp):
        raise LayoutMismatch(
            path, f"plan stacking is inconsistent: num_layers={layers} "
            f"does not factor as num_chunks={chunks} x pp={pp} x "
            f"layers_per_stage")
    return chunks, pp, layers // (chunks * pp)


def _zero_world(plan: dict) -> Optional[int]:
    """dp world of the flat optimizer shards, or None when the plan
    runs the unsharded optimizer."""
    return (int(plan["mesh"]["dp"])
            if plan.get("zero", {}).get("enabled") else None)


def _sha(a: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()


def _bytes_equal(x: np.ndarray, y: np.ndarray) -> bool:
    """Bytewise equality — NaN-safe (a diverged-but-saved checkpoint
    must not spuriously fail conservation: NaN != NaN under
    array_equal) and dtype-agnostic (int8 rejects equal_nan), without
    paying a hash over multi-GB leaves."""
    x, y = np.ascontiguousarray(x), np.ascontiguousarray(y)
    return (x.dtype == y.dtype and x.shape == y.shape
            and np.array_equal(x.view(np.uint8), y.view(np.uint8)))


# -- the remap --------------------------------------------------------------

def reshard_state(state: Any, plan_from: dict, plan_to: dict, *,
                  flat_len: Optional[int] = None,
                  path: str = "<state>") -> Tuple[Any, dict]:
    """Remap a HOST state pytree saved under ``plan_from`` onto
    ``plan_to``. Returns ``(new_state, report)`` where ``report``
    carries the banked evidence (leaf counts per remap class and the
    conservation verdicts). Pure host-side numpy; deterministic.

    ``flat_len`` is the true (unpadded) flat float-param length the
    ZeRO shards pack; when None and shard leaves are present it is
    derived from ``state["params"]`` via
    `parallel.distributed_optimizer.flat_param_len`.
    """
    import jax

    for key in ("model",):
        if plan_from.get(key) != plan_to.get(key):
            raise LayoutMismatch(
                path, f"plans disagree on {key!r}: elastic resume "
                "changes the topology, never the model "
                f"({plan_from.get(key)} != {plan_to.get(key)})")
    if bool(plan_from.get("zero", {}).get("enabled")) != \
            bool(plan_to.get("zero", {}).get("enabled")):
        raise LayoutMismatch(
            path, "optimizer-shard layout change (zero on<->off) is a "
            "tree-STRUCTURE change, not a leaf remap — re-plan with "
            "the source checkpoint's zero setting (elastic_resume "
            "pins the search via require_zero)")
    stack_from = _stack_dims(plan_from, path)
    stack_to = _stack_dims(plan_to, path)
    w_from, w_to = _zero_world(plan_from), _zero_world(plan_to)

    n_flat = flat_len
    counts = {"restacked": 0, "repacked": 0, "copied": 0}
    checks: list[dict] = []

    def need_flat_len() -> int:
        nonlocal n_flat
        if n_flat is None:
            from apex1_tpu.parallel.distributed_optimizer import (
                flat_param_len)

            params = state.get("params") if isinstance(state, dict) \
                else None
            if params is None:
                raise LayoutMismatch(
                    path, "cannot derive the flat shard length: state "
                    "has no 'params' subtree — pass flat_len= "
                    "explicitly")
            n_flat = flat_param_len(params)
        return n_flat

    def leaf(kp, x) -> np.ndarray:
        key = jax.tree_util.keystr(kp)
        a = np.asarray(x)
        if ("['chunk']" in key and a.ndim >= 3
                and a.shape[:3] == stack_from):
            if stack_from == stack_to:
                counts["copied"] += 1
                return a.copy()
            out = np.ascontiguousarray(a).reshape(stack_to + a.shape[3:])
            counts["restacked"] += 1
            # INDEPENDENT per-layer provenance check — NOT a reshape
            # compared to itself: global layer l must sit at
            # unravel(l, stack) on each side, recomputed here by
            # integer indexing, so a wrong remap (column-major,
            # swapped stack axes) fails this even though it would
            # pass any whole-buffer comparison of reshapes.
            # bytewise, not hashed: same strictness, NaN-safe, and a
            # multi-GB resume should not pay 2x sha256 per leaf
            n_layers = stack_from[0] * stack_from[1] * stack_from[2]
            ok = all(
                _bytes_equal(a[np.unravel_index(layer, stack_from)],
                             out[np.unravel_index(layer, stack_to)])
                for layer in range(n_layers))
            checks.append({"leaf": key, "kind": "restack", "ok": ok})
            return out
        if "['chunk']" in key and a.ndim >= 3:
            raise LayoutMismatch(
                path, f"leaf {key}: shape {a.shape} does not start "
                f"with the banked plan's stack {stack_from} — the "
                "checkpoint disagrees with its own plan meta")
        if "_shard" in key and a.ndim == 1 and w_from is not None:
            from apex1_tpu.parallel.distributed_optimizer import (
                repack_flat_shard, shard_padded_len)

            n = need_flat_len()
            if a.shape[0] != shard_padded_len(n, w_from):
                raise LayoutMismatch(
                    path, f"leaf {key}: length {a.shape[0]} != flat "
                    f"length {n} padded for dp={w_from} — the "
                    "checkpoint disagrees with its own plan meta")
            out = repack_flat_shard(a, flat_len=n, world_from=w_from,
                                    world_to=w_to)
            counts["repacked"] += 1
            # the meaningful tail check is on the SOURCE: a nonzero
            # padded tail means the zero-padding invariant broke
            # upstream and the repack would silently discard data —
            # refuse loudly (out's tail is zero by construction and
            # proves nothing)
            checks.append({"leaf": key, "kind": "repack",
                           "ok": _bytes_equal(a[:n], out[:n])
                           and not a[n:].any()})
            return out
        counts["copied"] += 1
        return a.copy()

    new_state = jax.tree_util.tree_map_with_path(leaf, state)
    report = {
        "n_leaves": sum(counts.values()),
        "n_restacked": counts["restacked"],
        "n_repacked": counts["repacked"],
        "n_copied": counts["copied"],
        "stack_from": list(stack_from), "stack_to": list(stack_to),
        "conserved": all(c["ok"] for c in checks),
        "n_checks": len(checks),
    }
    if not report["conserved"]:
        bad = [c["leaf"] for c in checks if not c["ok"]]
        raise LayoutMismatch(
            path, f"reshard conservation check failed for {bad[:4]} — "
            "remapped bytes do not match the source")
    return new_state, report


# -- checkpoint-level reshard ----------------------------------------------

def reshard_checkpoint(src_dir: str | os.PathLike, template: Any,
                       plan_to: dict, out_dir: str | os.PathLike, *,
                       fingerprint: Optional[int] = None,
                       flat_len: Optional[int] = None,
                       manifest: Optional[Manifest] = None
                       ) -> Tuple[str, Manifest, dict]:
    """Reshard a COMMITTED checkpoint onto ``plan_to`` as a fresh
    committed checkpoint at ``out_dir``. Returns
    ``(out_dir, new_manifest, report)``.

    ``template`` is a host-buildable state pytree with the SOURCE
    plan's structure/shapes/dtypes (e.g.
    `models.llama_3d.state_template` of the source config — no mesh
    or device count required). The full verification chain from the
    module docstring runs here; the returned directory restores
    through `ResilientCheckpointer.restore(path=...)` like any other
    checkpoint, re-verifying every leaf digest. Pass ``manifest`` when
    the caller JUST ran `verify_files` on the source itself (what
    `elastic_resume` does) — the file digests are skipped here, the
    leaf-level `verify_tree` after restore still runs; a multi-GB
    checkpoint should not be re-hashed back-to-back for nothing."""
    src_dir = os.fspath(src_dir)
    out_dir = os.fspath(os.path.abspath(out_dir))
    man = manifest if manifest is not None else verify_files(src_dir)
    plan_from = plan_meta(man, src_dir)
    state = restore_checkpoint(os.path.join(src_dir, _STATE_SUBDIR),
                               template=template)
    verify_tree(src_dir, state, man)
    new_state, report = reshard_state(state, plan_from, plan_to,
                                      flat_len=flat_len, path=src_dir)

    meta = dict(man.meta)
    meta["plan"] = plan_to
    meta["resharded_from"] = {
        "path": src_dir, "step": man.step,
        "mesh": mesh_str(plan_from), "to_mesh": mesh_str(plan_to),
        "n_leaves": report["n_leaves"],
        "n_restacked": report["n_restacked"],
        "n_repacked": report["n_repacked"],
    }
    tmp = f"{out_dir}.tmp-{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(os.path.dirname(out_dir) or ".", exist_ok=True)
    os.makedirs(tmp)
    try:
        save_checkpoint(os.path.join(tmp, _STATE_SUBDIR), new_state)
        write_manifest(tmp, step=man.step, tree=tree_entries(new_state),
                       fingerprint=fingerprint, meta=meta)
        old = None
        if os.path.exists(out_dir):
            old = f"{out_dir}.old-{os.getpid()}"
            shutil.rmtree(old, ignore_errors=True)
            os.rename(out_dir, old)
        os.rename(tmp, out_dir)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    new_man = verify_files(out_dir)
    return out_dir, new_man, report


def read_plan(ckpt_dir: str | os.PathLike) -> dict:
    """The banked producing plan of a committed checkpoint dir (typed
    errors for uncommitted/plan-less dirs)."""
    ckpt_dir = os.fspath(ckpt_dir)
    return plan_meta(read_manifest(ckpt_dir), ckpt_dir)
