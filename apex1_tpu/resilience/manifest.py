"""Per-checkpoint integrity manifest — the thing that turns "orbax
didn't crash" into "this checkpoint is the one we wrote".

Two layers of evidence, both in one ``manifest.json`` next to the
checkpoint payload:

- **file digests** — relative path, byte size, sha256 of every file the
  backend wrote. Cheap to re-verify WITHOUT restoring (a directory walk),
  which is what lets `find_restorable` scan backward past truncated /
  bit-flipped checkpoints instead of dying inside tensorstore.
- **leaf digests** — tree structure (key paths), shape, dtype, sha256 of
  each leaf's host bytes at save time. Re-checked after restore, so a
  wrong-but-readable restore (stale file swapped in, dtype drift) is a
  typed error, never silently wrong params.

The manifest also round-trips the resume tuple's scalar half: ``step``,
the program/config ``fingerprint`` (`utils.debug.program_fingerprint` —
resume onto a CHANGED program is refused, not silent), and a free-form
JSON ``meta`` dict (data-iterator position, PRNG seed, loss-scale
summary — whatever the training loop needs to continue exactly).

The manifest file itself is written temp-file + ``os.replace`` and is
the COMMIT MARKER: no manifest ⇒ the checkpoint never finished.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Optional

import numpy as np

MANIFEST_NAME = "manifest.json"
_FORMAT = "apex1-resilient-ckpt-v1"


def atomic_write_text(path: str | os.PathLike, text: str) -> None:
    """Temp file + flush + fsync + ``os.replace`` — the ONE
    torn-write-proof file commit for the resilience layer (manifests,
    the ``latest`` pointer, diagnostic records). A crash at any point
    leaves either the old file or the new one, never a truncated mix.
    (`bench._emit` keeps its own inline copy: its fallback path must
    not depend on importing this package.)"""
    path = os.fspath(path)
    tmp = os.path.join(os.path.dirname(path),
                       f".{os.path.basename(path)}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_json(path: str | os.PathLike, doc: Any) -> None:
    atomic_write_text(path, json.dumps(doc, indent=1, sort_keys=True))


class IntegrityError(RuntimeError):
    """Manifest mismatch: the checkpoint's content does not match what
    was recorded at save time (corruption, truncation, wrong restore)."""

    def __init__(self, path: str | os.PathLike, reason: str):
        self.path = os.fspath(path)
        self.reason = reason
        super().__init__(f"integrity check failed at {self.path}: {reason}")


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _leaf_digest(x: np.ndarray) -> str:
    """sha256 over the C-contiguous little-endian bytes of ``x`` —
    layout-independent so a restore onto a different sharding/mesh still
    matches."""
    a = np.ascontiguousarray(x)
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("<"))
    return hashlib.sha256(a.tobytes()).hexdigest()


def _host_leaves(tree: Any):
    """[(keypath-str, numpy array)] for every leaf, via jax tree paths.
    jax PRNG key arrays are digested over their key DATA (uint32)."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if hasattr(leaf, "dtype") and jax.dtypes.issubdtype(
                leaf.dtype, jax.dtypes.prng_key):
            leaf = jax.random.key_data(leaf)
        out.append((jax.tree_util.keystr(path), np.asarray(leaf)))
    return out


@dataclasses.dataclass
class Manifest:
    """Parsed manifest — `write_manifest`/`read_manifest` round-trip."""

    step: int
    fingerprint: Optional[str]          # hex string or None
    meta: dict                          # resume extras (JSON-safe)
    tree: list                          # [{path, shape, dtype, sha256}]
    files: list                         # [{path, bytes, sha256}]

    def to_json(self) -> dict:
        return {"format": _FORMAT, "step": self.step,
                "fingerprint": self.fingerprint, "meta": self.meta,
                "tree": self.tree, "files": self.files}


def tree_entries(state: Any) -> list:
    """Per-leaf manifest entries from a (host or device) pytree."""
    return [{"path": p, "shape": list(a.shape), "dtype": str(a.dtype),
             "sha256": _leaf_digest(a)}
            for p, a in _host_leaves(state)]


def _walk_files(ckpt_dir: str) -> list:
    out = []
    for root, _dirs, files in os.walk(ckpt_dir):
        for name in sorted(files):
            if name == MANIFEST_NAME:
                continue
            full = os.path.join(root, name)
            out.append(os.path.relpath(full, ckpt_dir))
    return sorted(out)


def write_manifest(ckpt_dir: str | os.PathLike, *, step: int,
                   state: Any = None, tree: Optional[list] = None,
                   fingerprint: Optional[int] = None,
                   meta: Optional[dict] = None) -> Manifest:
    """Digest every payload file under ``ckpt_dir`` (+ the leaf digests
    of ``state``, or precomputed ``tree`` entries) and atomically write
    ``manifest.json``. Call AFTER the backend finished writing."""
    ckpt_dir = os.fspath(ckpt_dir)
    if tree is None:
        tree = tree_entries(state) if state is not None else []
    files = []
    for rel in _walk_files(ckpt_dir):
        full = os.path.join(ckpt_dir, rel)
        files.append({"path": rel, "bytes": os.path.getsize(full),
                      "sha256": _sha256_file(full)})
    m = Manifest(step=int(step),
                 fingerprint=(None if fingerprint is None
                              else f"{int(fingerprint):#x}"),
                 meta=dict(meta or {}), tree=tree, files=files)
    atomic_write_json(os.path.join(ckpt_dir, MANIFEST_NAME), m.to_json())
    return m


def read_manifest(ckpt_dir: str | os.PathLike) -> Manifest:
    """Parse ``manifest.json``; raises `IntegrityError` when missing or
    unparseable (no manifest ⇒ the save never committed)."""
    ckpt_dir = os.fspath(ckpt_dir)
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise IntegrityError(ckpt_dir, f"manifest missing ({e})") from e
    except json.JSONDecodeError as e:
        raise IntegrityError(ckpt_dir, f"manifest unparseable ({e})") from e
    if doc.get("format") != _FORMAT:
        raise IntegrityError(
            ckpt_dir, f"unknown manifest format {doc.get('format')!r}")
    try:
        return Manifest(step=int(doc["step"]),
                        fingerprint=doc.get("fingerprint"),
                        meta=doc.get("meta", {}), tree=doc["tree"],
                        files=doc["files"])
    except (KeyError, TypeError, ValueError) as e:
        raise IntegrityError(ckpt_dir, f"manifest malformed ({e})") from e


def verify_files(ckpt_dir: str | os.PathLike,
                 manifest: Optional[Manifest] = None) -> Manifest:
    """Re-digest the payload files against the manifest. Catches
    truncation (size mismatch / missing file) and bit flips (sha256)
    without restoring. Returns the manifest on success."""
    ckpt_dir = os.fspath(ckpt_dir)
    m = manifest if manifest is not None else read_manifest(ckpt_dir)
    recorded = {e["path"]: e for e in m.files}
    on_disk = set(_walk_files(ckpt_dir))
    missing = set(recorded) - on_disk
    if missing:
        raise IntegrityError(ckpt_dir,
                             f"missing files: {sorted(missing)[:4]}")
    extra = on_disk - set(recorded)
    if extra:
        # extra payload files mean the dir is not the one we digested
        raise IntegrityError(ckpt_dir,
                             f"unrecorded files: {sorted(extra)[:4]}")
    for rel, e in recorded.items():
        full = os.path.join(ckpt_dir, rel)
        size = os.path.getsize(full)
        if size != e["bytes"]:
            raise IntegrityError(
                ckpt_dir, f"{rel}: {size} bytes, manifest says "
                f"{e['bytes']} (truncated?)")
        got = _sha256_file(full)
        if got != e["sha256"]:
            raise IntegrityError(
                ckpt_dir, f"{rel}: content digest mismatch (bit flip?)")
    return m


def verify_tree(ckpt_dir: str | os.PathLike, state: Any,
                manifest: Optional[Manifest] = None) -> None:
    """Verify a RESTORED pytree against the manifest's leaf digests:
    structure, shapes, dtypes, content. A mismatch is a typed error —
    never a silent wrong restore."""
    ckpt_dir = os.fspath(ckpt_dir)
    m = manifest if manifest is not None else read_manifest(ckpt_dir)
    got = {e["path"]: e for e in tree_entries(state)}
    want = {e["path"]: e for e in m.tree}
    if set(got) != set(want):
        raise IntegrityError(
            ckpt_dir, "tree structure mismatch: "
            f"missing {sorted(set(want) - set(got))[:4]}, "
            f"unexpected {sorted(set(got) - set(want))[:4]}")
    for p, w in want.items():
        g = got[p]
        for field in ("shape", "dtype", "sha256"):
            if g[field] != w[field]:
                raise IntegrityError(
                    ckpt_dir, f"leaf {p}: {field} mismatch "
                    f"({g[field]!r} != recorded {w[field]!r})")
