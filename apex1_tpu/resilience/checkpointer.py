"""Async, integrity-checked, ring-kept checkpoints over the orbax
backend in `apex1_tpu.checkpoint` — the training-runtime-facing half of
SURVEY §5.2's missing elastic recovery.

Design:

- **async double-buffering** — ``save(step, state)`` takes a cheap
  DEVICE-side snapshot (``jnp.copy`` per leaf: async dispatch, and
  donation-safe — the caller's next ``donate_argnums=0`` step may
  invalidate the live buffers while the save is still running) and hands
  it to ONE background worker. Step N+k trains while step N fetches to
  host and writes. At most two snapshots ever exist (one writing, one
  queued — the slot is reserved before the copy is made); a third
  ``save`` blocks until the writer drains.
- **atomic commit + integrity manifest** — the payload lands in
  ``step_XXXXXXXX.tmp-<pid>/state`` via the (itself atomic)
  `checkpoint.save_checkpoint`; `manifest.write_manifest` digests every
  file and leaf; the temp dir is renamed to ``step_XXXXXXXX`` and only
  then is the ``latest`` pointer file atomically promoted. A crash at
  ANY point leaves either a complete committed checkpoint or ignorable
  debris — never a half-directory that looks restorable.
- **ring keep-policy** — last ``keep`` checkpoints survive; saves with
  ``milestone=True`` are pinned outside the ring (manifest
  ``meta["milestone"]``). GC runs after each commit.
- **backward scan** — `find_restorable` walks newest→oldest past
  truncated / bit-flipped / uncommitted checkpoints to the newest VALID
  one instead of surfacing a tensorstore traceback from the corpse.
- **exact resume** — the manifest round-trips ``step`` + a JSON ``meta``
  dict (data-iterator position, PRNG seed, anything the loop needs; the
  array half — params, opt state, loss-scale state — IS the state tree)
  and a program ``fingerprint`` that refuses silent resume onto a
  changed program.

Scope: single-controller processes (the CPU proxy, single-chip bench
runs, each rank of a multi-controller job checkpointing its own
addressable shards via ``to_global`` upstream). Multi-controller barrier
coordination stays with `checkpoint.CheckpointManager`.
"""

from __future__ import annotations

import os
import queue
import re
import shutil
import threading
from typing import Any, Optional, Tuple

import numpy as np

from apex1_tpu.checkpoint import (CheckpointError, restore_checkpoint,
                                  save_checkpoint)
from apex1_tpu.resilience.manifest import (IntegrityError, Manifest,
                                           atomic_write_text,
                                           read_manifest, tree_entries,
                                           verify_files, verify_tree,
                                           write_manifest)
from apex1_tpu.resilience.reshard import (PLAN_SCHEMA, LayoutMismatch,
                                          mesh_str)

_STEP_RE = re.compile(r"^step_(\d{8})$")
_LATEST = "latest"
_STATE_SUBDIR = "state"


def step_dir_name(step: int) -> str:
    if step < 0:
        raise ValueError("step must be >= 0")
    return f"step_{int(step):08d}"


def _list_step_dirs(directory: str) -> list[Tuple[int, str]]:
    """[(step, absolute path)] sorted ascending; ignores temp debris."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = _STEP_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def is_valid_checkpoint(path: str | os.PathLike) -> bool:
    """Committed + passes the file-level integrity manifest."""
    try:
        verify_files(path)
        return True
    except IntegrityError:
        return False


def find_restorable(directory: str | os.PathLike) -> Optional[str]:
    """Newest VALID checkpoint dir under ``directory``, or None.

    Scans every ``step_*`` dir newest→oldest, verifying each file
    manifest, so a truncated newest checkpoint (killed save) or a
    bit-flipped middle one degrades to the next older valid snapshot
    instead of an unrecoverable job. The ``latest`` pointer file is
    deliberately NOT trusted here: a kill between the commit rename
    and the pointer promote leaves a newer fully-valid checkpoint the
    pointer doesn't know about, and "newest valid" must win (the
    pointer remains as an operator-facing breadcrumb, and the newest
    dir is the first one verified anyway, so the scan costs nothing
    extra in the healthy case)."""
    directory = os.fspath(directory)
    for _step, path in reversed(_list_step_dirs(directory)):
        if is_valid_checkpoint(path):
            return path
    return None


class ResilientCheckpointer:
    """Train-loop API: ``save(step, state)`` (async) / ``save_sync`` /
    ``restore(template)`` / ``latest_valid()``. See module docstring."""

    def __init__(self, directory: str | os.PathLike, *, keep: int = 3,
                 fingerprint: Optional[int] = None,
                 plan: Optional[dict] = None):
        self.directory = os.fspath(os.path.abspath(directory))
        os.makedirs(self.directory, exist_ok=True)
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.keep = int(keep)
        self.fingerprint = fingerprint
        # the producing apex1-plan-v1 spec: banked in every save's
        # manifest meta (self-describing, reshardable checkpoints) and
        # compared on restore — a layout change is a typed
        # LayoutMismatch pointing at elastic resume, never a shape
        # error from deep inside the restore
        if plan is not None and (not isinstance(plan, dict)
                                 or plan.get("schema") != PLAN_SCHEMA):
            raise ValueError(
                f"plan= must be an {PLAN_SCHEMA} document "
                "(planner.make_plan / planner.plan_for_layout)")
        self.plan = plan
        self._q: queue.Queue = queue.Queue()
        # the real memory bound: a slot is taken BEFORE the device-side
        # snapshot is built and released only after the worker dropped
        # it, so at most two snapshots ever coexist (one writing, one
        # queued) — a queue maxsize can't give this bound, because the
        # third save() would build its snapshot before put() blocks
        self._slots = threading.Semaphore(2)
        self._errors: list[BaseException] = []
        self._lock = threading.Lock()
        self._worker = threading.Thread(target=self._work, daemon=True)
        self._worker.start()

    # -- save path ---------------------------------------------------------

    def _snapshot(self, state):
        """Device-side copy of every jax leaf (async dispatch): the live
        buffers may be donated to the very next train step."""
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True)
            if isinstance(x, jax.Array) else x, state)

    def save(self, step: int, state: Any, *, meta: Optional[dict] = None,
             milestone: bool = False) -> None:
        """Queue an async snapshot of ``state`` at ``step``. Blocks only
        while two snapshots are already outstanding (one writing, one
        queued) — the slot is reserved BEFORE the snapshot is built, so
        the two-snapshot memory bound holds. Background failures
        surface on the NEXT save/wait/close."""
        self._raise_pending()
        self._slots.acquire()
        try:
            snap = self._snapshot(state)
            m = dict(meta or {})
            if milestone:
                m["milestone"] = True
            if self.plan is not None and "plan" not in m:
                m["plan"] = self.plan
            self._q.put((int(step), snap, m))
        except BaseException:
            self._slots.release()
            raise

    def save_sync(self, step: int, state: Any, *,
                  meta: Optional[dict] = None,
                  milestone: bool = False) -> str:
        """Synchronous save (the preemption-grace path): returns the
        committed checkpoint dir."""
        self.save(step, state, meta=meta, milestone=milestone)
        self.wait()
        return os.path.join(self.directory, step_dir_name(step))

    def wait(self) -> None:
        """Block until every queued save committed (or failed)."""
        self._q.join()
        self._raise_pending()

    def _raise_pending(self):
        with self._lock:
            if self._errors:
                err = self._errors[:]
                self._errors.clear()
                raise CheckpointError(
                    self.directory,
                    f"background save failed: {err[0]!r}") from err[0]

    def _work(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            step, snap, meta = item
            try:
                self._write_one(step, snap, meta)
            except BaseException as e:
                with self._lock:
                    self._errors.append(e)
            finally:
                del item, snap
                self._slots.release()
                self._q.task_done()

    def _write_one(self, step: int, snap, meta: dict):
        import jax

        host = jax.device_get(snap)
        host = jax.tree_util.tree_map(np.asarray, host)
        final = os.path.join(self.directory, step_dir_name(step))
        tmp = f"{final}.tmp-{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        try:
            save_checkpoint(os.path.join(tmp, _STATE_SUBDIR), host)
            write_manifest(tmp, step=step, tree=tree_entries(host),
                           fingerprint=self.fingerprint, meta=meta)
            # re-save of an existing step: move the old dir aside
            # before the commit rename so there is no instant with
            # zero committed copies of this step, then drop it
            old = None
            if os.path.exists(final):
                old = f"{final}.old-{os.getpid()}"
                shutil.rmtree(old, ignore_errors=True)
                os.rename(final, old)
            os.rename(tmp, final)
            if old is not None:
                shutil.rmtree(old, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._promote_latest(step)
        self._gc()

    def _promote_latest(self, step: int):
        atomic_write_text(os.path.join(self.directory, _LATEST),
                          step_dir_name(step) + "\n")

    def _gc(self):
        dirs = _list_step_dirs(self.directory)
        if len(dirs) <= self.keep:
            return
        for _step, path in dirs[:-self.keep]:
            try:
                if read_manifest(path).meta.get("milestone"):
                    continue            # pinned outside the ring
            except IntegrityError:
                pass                    # corrupt/uncommitted: collectable
            shutil.rmtree(path, ignore_errors=True)

    # -- restore path ------------------------------------------------------

    def latest_valid(self) -> Optional[str]:
        return find_restorable(self.directory)

    def restore(self, template: Any, *, path: Optional[str] = None,
                expect_fingerprint: Optional[int] = None,
                allow_fingerprint_mismatch: bool = False
                ) -> Tuple[Any, Manifest]:
        """Restore the newest valid checkpoint (or ``path``): verify the
        file manifest, restore, verify the restored LEAVES against the
        recorded digests, enforce the program fingerprint. Returns
        ``(state, manifest)`` — ``manifest.step`` / ``manifest.meta``
        carry the resume position."""
        if path is None:
            path = self.latest_valid()
            if path is None:
                raise CheckpointError(self.directory,
                                      "no valid checkpoint to restore")
        manifest = verify_files(path)
        if self.plan is not None:
            # the layout check FIRST: a topology change flips the
            # program fingerprint too, and "your layout changed — go
            # through elastic resume" is the actionable diagnosis,
            # not "the program changed". Replaces the blanket
            # fingerprint refusal for plan-aware checkpoints.
            from apex1_tpu.planner.emit import plan_spec

            ckpt_plan = manifest.meta.get("plan")
            if not isinstance(ckpt_plan, dict):
                raise LayoutMismatch(
                    path, "no plan meta: this checkpoint predates "
                    "plan-aware saves and cannot be layout-checked "
                    "against the current plan; restore it with a "
                    "plan-less checkpointer, or reshard it via "
                    "resilience.reshard_checkpoint")
            if plan_spec(ckpt_plan) != plan_spec(self.plan):
                raise LayoutMismatch(
                    path, f"checkpoint layout [{mesh_str(ckpt_plan)}] "
                    f"!= current plan [{mesh_str(self.plan)}] — the "
                    "mesh/schedule changed; resume through "
                    "resilience.elastic_resume (planner re-plan + "
                    "manifest-verified reshard), not an in-place "
                    "restore")
        want_fp = (expect_fingerprint if expect_fingerprint is not None
                   else self.fingerprint)
        if (want_fp is not None and manifest.fingerprint is not None
                and not allow_fingerprint_mismatch
                and int(manifest.fingerprint, 16) != int(want_fp)):
            raise CheckpointError(
                path, f"program fingerprint mismatch: checkpoint "
                f"{manifest.fingerprint}, current {int(want_fp):#x} — "
                "the program changed since this checkpoint was written; "
                "pass allow_fingerprint_mismatch=True to resume anyway")
        state = restore_checkpoint(os.path.join(path, _STATE_SUBDIR),
                                   template=template)
        verify_tree(path, state, manifest)
        return state, manifest

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self.wait()
        self._q.put(None)
        self._worker.join(timeout=60.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
