"""Elastic resume — survive a mesh shrink/grow by re-planning on
purpose: detect the surviving device count, ask the planner
(`apex1_tpu.planner.make_plan`) for a fresh legal layout, reshard the
newest restorable checkpoint onto it (`resilience.reshard`,
manifest-verified end-to-end), and hand the training loop a plan it
can rebuild from.

This is the bridge ISSUE 14 names between PR 6 (bit-exact
single-topology resume: the manifest fingerprint rightly REFUSES a
silently changed program) and PR 12 (the planner knows a legal
dp×tp×pp×cp×ep for any chip count): the path that changes the
program ON PURPOSE, with every decision banked.

EVIDENCE DISCIPLINE (the PR 13 rule — an episode must be
reconstructable from banked telemetry alone): every decision emits an
obs-spine event (`apex1_tpu.obs.spine`, inert without
``APEX1_OBS_DIR``):

- ``elastic.detect``  — surviving device count, the checkpoint found,
  its step/data_step, its banked layout;
- ``elastic.replan``  — old and new plan specs (mesh strings + the
  full layout-identity `planner.plan_spec` dicts), the search size,
  the calibrated price of the pick;
- ``elastic.reshard`` — leaf counts per remap class
  (restacked/repacked/copied) and the output path;
- ``elastic.verify``  — the digest verdicts: source files + leaves
  verified, remap conservation checks, fresh tree digest count;
- ``elastic.resume``  — the path the loop should restore, and whether
  a reshard happened at all (same-layout relaunches take the plain
  resume path, banked as such).

THE DRILL (`drill`, ``python -m apex1_tpu.resilience.elastic
--drill`` = check_all's ``== elastic drill ==``, also pinned tier-1
in tests/test_elastic.py): train a tiny llama_3d on an 8-device CPU
mesh under a stated dp2·pp2·tp2 plan, kill it mid-run at a
seed-keyed step (`chaos.shrink_schedule` — committed checkpoints up
to the kill, in-flight work lost), then resume in a FRESH PROCESS
that owns exactly 4 devices — what a real relaunch on a shrunken
fleet is — through `elastic_resume` (planner re-plan + reshard), and
run a CONTROL there: an independent second reshard of the same
checkpoint (byte-identical leaf digests — the determinism pin)
restored into a fresh 4-device state and trained on the same banked
data order. The elastic leg's loss trajectory and final params must
match the control BIT-EXACTLY, and the episode summary is re-derived
in the parent from the spine events alone (both processes bank into
one obs dir) and checked against the leg's ground truth. What the
CPU drill does NOT prove: silicon wall-clock and real multi-host
orchestration — the ``elastic_ab`` tpu_watch queue entry (the
``--real`` in-process form: a TPU job cannot boot a second process
against chips it holds) carries that claim (docs/robustness.md
§ Elastic resume).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

from apex1_tpu.checkpoint import CheckpointError
from apex1_tpu.resilience.checkpointer import (find_restorable,
                                               step_dir_name)
from apex1_tpu.resilience.manifest import Manifest, verify_files
from apex1_tpu.resilience.reshard import (LayoutMismatch, mesh_str,
                                          plan_meta, reshard_checkpoint)


@dataclasses.dataclass
class ElasticDecision:
    """What `elastic_resume` decided, with the evidence attached.
    ``path`` is the directory the loop should restore (the resharded
    checkpoint, or the source itself when no reshard was needed)."""

    ckpt_dir: str
    source: str                 # the checkpoint that was found
    path: str                   # what to restore from
    old_plan: dict
    plan: dict                  # the plan the resumed loop should run
    resharded: bool
    step: int
    data_step: Optional[int]
    manifest: Manifest          # manifest of `path`
    report: Optional[dict]      # reshard report (None when resharded
    #                             is False)


def elastic_resume(ckpt_dir: str | os.PathLike, *,
                   n_devices: Optional[int] = None,
                   make_template: Callable[[dict], Any],
                   generation: Optional[str] = None,
                   results_dir: Optional[str] = None,
                   out_root: Optional[str] = None,
                   planner_kw: Optional[dict] = None
                   ) -> ElasticDecision:
    """The elastic-resume driver. Finds the newest restorable
    checkpoint under ``ckpt_dir``, reads its banked producing plan
    (typed :class:`LayoutMismatch` when absent), and:

    - same device count ⇒ plain resume (``resharded=False``, the
      source path);
    - different count ⇒ ``planner.make_plan(model_shape, n_devices)``
      for a fresh legal plan, then a manifest-verified reshard of the
      checkpoint onto it.

    ``make_template(plan) -> host state pytree`` builds the SOURCE
    plan's state template (e.g. `models.llama_3d.state_template` of
    the plan-derived config) — mesh-free, so it works on the shrunken
    fleet. ``n_devices`` defaults to ``len(jax.devices())`` (detect
    the surviving fleet). ``planner_kw`` forwards to ``make_plan``;
    ``require_zero`` defaults to the SOURCE plan's zero setting — the
    re-plan searches ONLY layouts with the same optimizer-shard
    structure, because flipping it is a state-structure change the
    reshard refuses (no legal matching layout ⇒ a loud PlanError).
    Every decision is banked as an obs-spine event (module
    docstring)."""
    from apex1_tpu.obs import spine

    ckpt_dir = os.fspath(ckpt_dir)
    if n_devices is None:
        import jax

        n_devices = len(jax.devices())
    src = find_restorable(ckpt_dir)
    if src is None:
        raise CheckpointError(ckpt_dir,
                              "no valid checkpoint to resume from")
    man = verify_files(src)
    old_plan = plan_meta(man, src)
    data_step = man.meta.get("data_step")
    spine.emit("event", "elastic.detect", n_devices=int(n_devices),
               ckpt=src, step=int(man.step), data_step=data_step,
               mesh=mesh_str(old_plan),
               banked_devices=old_plan.get("n_devices"))

    if int(old_plan.get("n_devices", -1)) == int(n_devices):
        spine.emit("event", "elastic.resume", resharded=False,
                   path=src, mesh=mesh_str(old_plan),
                   step=int(man.step), data_step=data_step)
        return ElasticDecision(
            ckpt_dir=ckpt_dir, source=src, path=src,
            old_plan=old_plan, plan=old_plan, resharded=False,
            step=int(man.step), data_step=data_step, manifest=man,
            report=None)

    from apex1_tpu import planner

    shape = planner.model_shape_from_plan(old_plan)
    kw = dict(planner_kw or {})
    kw.setdefault("require_zero",
                  bool(old_plan.get("zero", {}).get("enabled")))
    gen = generation or old_plan.get("generation") or "v5e"
    new_plan = planner.make_plan(shape, int(n_devices), generation=gen,
                                 results_dir=results_dir, **kw)
    spine.emit("event", "elastic.replan",
               old_mesh=mesh_str(old_plan), new_mesh=mesh_str(new_plan),
               old_spec=planner.plan_spec(old_plan),
               new_spec=planner.plan_spec(new_plan),
               n_enumerated=new_plan["search"]["n_enumerated"],
               calibrated_step_ms=new_plan["predicted"]
               ["calibrated_step_ms"])

    root = out_root or os.path.join(ckpt_dir, "elastic")
    out_dir = os.path.join(
        root, f"{step_dir_name(man.step)}_to{int(n_devices)}dev")
    out_path, new_man, report = reshard_checkpoint(
        src, make_template(old_plan), new_plan, out_dir, manifest=man)
    spine.emit("event", "elastic.reshard", src=src, out=out_path,
               n_leaves=report["n_leaves"],
               n_restacked=report["n_restacked"],
               n_repacked=report["n_repacked"],
               n_copied=report["n_copied"],
               stack_from=report["stack_from"],
               stack_to=report["stack_to"])
    spine.emit("event", "elastic.verify", path=out_path,
               source_verified=True, files_verified=True,
               conserved=report["conserved"],
               n_conservation_checks=report["n_checks"],
               n_tree_digests=len(new_man.tree))
    spine.emit("event", "elastic.resume", resharded=True,
               path=out_path, mesh=mesh_str(new_plan),
               step=int(new_man.step), data_step=data_step)
    return ElasticDecision(
        ckpt_dir=ckpt_dir, source=src, path=out_path,
        old_plan=old_plan, plan=new_plan, resharded=True,
        step=int(new_man.step), data_step=data_step, manifest=new_man,
        report=report)


# -- the acceptance drill ---------------------------------------------------

def _drill_fixture(seed: int):
    """The drill's model/config constants, shared by BOTH sides of
    the process boundary (the n_from-device trainer and the
    n_to-device resume leg), so the two provably describe the same
    job. Returns ``(shape, cfg_of, make_template, batch_at)``."""
    from apex1_tpu import planner
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import LlamaConfig

    hidden, seq, vocab, layers = 64, 32, 128, 4
    shape = planner.ModelShape(
        name="elastic-drill", num_layers=layers, hidden_size=hidden,
        ffn_size=2 * hidden, num_heads=4, num_kv_heads=2,
        head_dim=hidden // 4, vocab_size=vocab, seq_len=seq,
        global_batch=8)
    mcfg = LlamaConfig.tiny(
        num_layers=layers, max_seq_len=seq, vocab_size=vocab,
        num_heads=4, num_kv_heads=2, hidden_size=hidden,
        ffn_size=2 * hidden, policy=get_policy("O2"))

    def cfg_of(plan):
        return planner.llama3d_config_from_plan(plan, mcfg,
                                                learning_rate=3e-3,
                                                ignore_zero=True)

    def make_template(plan):
        from apex1_tpu.models.llama_3d import state_template

        return state_template(cfg_of(plan))

    def batch_at(i, cfg):
        # canonical (global_batch, seq) draw regrouped per the
        # layout's (M, B) factorization (sequence g = m*B + b), so
        # the pre-kill and post-reshard layouts train the SAME
        # sequences at step i — the "same data order" half of the
        # drill's claim (mirrors examples/llama_3d.py batch_at)
        import jax.numpy as jnp
        import numpy as np

        rng = np.random.default_rng([seed, i])
        cols = cfg.microbatch_size * cfg.dp * cfg.ep
        canon = rng.integers(
            0, vocab,
            (cfg.num_microbatches * cols, seq)).astype(np.int32)
        toks = canon.reshape(cfg.num_microbatches, cols,
                             seq).transpose(0, 2, 1)
        return jnp.asarray(toks), jnp.asarray(np.roll(toks, -1,
                                                      axis=1))

    return shape, cfg_of, make_template, batch_at


def _resume_leg(ckpt_dir: str, work: str, n_to: int, seed: int,
                steps_total: int, devices=None,
                verbose: bool = True) -> dict:
    """Drill phases 2+3: elastic resume on the SHRUNKEN fleet + the
    from-checkpoint control, asserted bit-exact. Runs in the shrunken
    fleet's own process in the tier-1/check_all drill (`drill` spawns
    a fresh n_to-device process — what a real relaunch is); the
    ``--real`` queue entry runs it in-process over ``devices[:n_to]``
    (a TPU job cannot boot a second process against held chips).
    Returns the leg's facts for the parent to cross-check against the
    banked spine events."""
    import jax
    import numpy as np

    from apex1_tpu.checkpoint import restore_checkpoint
    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.models import llama_3d as l3d
    from apex1_tpu.resilience.checkpointer import ResilientCheckpointer
    from apex1_tpu.resilience.manifest import tree_entries, verify_tree

    def say(msg):
        if verbose:
            print(f"[elastic drill] {msg}", flush=True)

    # tiny compiles, zero cache value — and on jax 0.4.x XLA:CPU,
    # RELOADING a persistent-cached executable whose device assignment
    # is a proper subset of the visible devices is unreliable
    # (segfaults reproduced on this image), which the --real in-process
    # path would otherwise hit. Correctness beats cached seconds.
    cache_was = bool(jax.config.jax_enable_compilation_cache)
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        _shape, cfg_of, make_template, batch_at = _drill_fixture(seed)
        decision = elastic_resume(ckpt_dir, n_devices=n_to,
                                  make_template=make_template,
                                  planner_kw={"allow_zero": False})
        assert decision.resharded, \
            "drill expected a layout change; got a same-layout resume"
        plan_a, plan_b = decision.old_plan, decision.plan
        cfg_b = cfg_of(plan_b)
        devs = (list(devices) if devices is not None
                else jax.devices())[:n_to]
        mesh_b = make_mesh(dp=cfg_b.dp, pp=cfg_b.pp, cp=cfg_b.cp,
                           ep=cfg_b.ep, tp=cfg_b.tp, devices=devs)
        step_b, state_b_init, _ = l3d.make_train_step(cfg_b,
                                                      mesh=mesh_b)
        ck_b = ResilientCheckpointer(ckpt_dir, keep=8, plan=plan_b)
        state_e, man_e = ck_b.restore(template=state_b_init,
                                      path=decision.path)
        start = int(man_e.meta["data_step"])
        say(f"phase 2: elastic resume {mesh_str(plan_a)} -> "
            f"{mesh_str(plan_b)} at data step {start} "
            f"({decision.report['n_restacked']} restacked / "
            f"{decision.report['n_copied']} copied leaves, all "
            f"digest-verified)")
        losses_e = []
        for i in range(start, steps_total):
            t, lbl = batch_at(i, cfg_b)
            state_e, loss = step_b(state_e, t, lbl)
            losses_e.append(float(loss))
            ck_b.save(int(state_e["step"]), state_e,
                      meta={"data_step": i + 1})
        ck_b.close()

        # -- the 4-device from-checkpoint CONTROL ----------------------
        # independent second reshard of the same source: byte-identical
        # leaf digests = the determinism pin
        out2, man_c, _rep2 = reshard_checkpoint(
            decision.source, make_template(plan_a), plan_b,
            os.path.join(work, "control_reshard"))
        dig_e = [(e["path"], e["sha256"])
                 for e in decision.manifest.tree]
        dig_c = [(e["path"], e["sha256"]) for e in man_c.tree]
        assert dig_e == dig_c, \
            "reshard is not deterministic: two reshards of the same " \
            "(checkpoint, target plan) produced different leaf digests"
        state_c = restore_checkpoint(os.path.join(out2, "state"),
                                     template=make_template(plan_b))
        verify_tree(out2, state_c, man_c)
        losses_c = []
        for i in range(start, steps_total):
            t, lbl = batch_at(i, cfg_b)
            state_c, loss = step_b(state_c, t, lbl)
            losses_c.append(float(loss))

        assert losses_e == losses_c, \
            f"elastic loss trajectory diverged from the " \
            f"from-checkpoint control: {losses_e} != {losses_c}"
        pe = tree_entries(jax.device_get(state_e["params"]))
        pc = tree_entries(jax.device_get(state_c["params"]))
        assert pe == pc, "final params differ between the elastic " \
                         "leg and the control"
        say(f"bit-exact: {len(losses_e)} resumed steps match the "
            f"control (losses {['%.4f' % l for l in losses_e]})")
        return {
            "data_step": start, "n_to": n_to,
            "old_mesh": mesh_str(plan_a),
            "new_mesh": mesh_str(plan_b),
            "losses": losses_e,
            "n_leaves": decision.report["n_leaves"],
            "n_restacked": decision.report["n_restacked"],
            "n_tree_digests": len(decision.manifest.tree),
            "path": decision.path,
        }
    finally:
        jax.config.update("jax_enable_compilation_cache", cache_was)


def drill(n_from: int = 8, n_to: Optional[int] = None, *,
          seed: int = 20260804, steps_total: int = 6,
          work_dir: Optional[str] = None, verbose: bool = True,
          subprocess_resume: bool = True) -> dict:
    """The elastic acceptance drill (module docstring). Phase 1
    trains on ``n_from`` devices and dies mid-run; phases 2+3 (the
    elastic resume + its from-checkpoint control) run in a FRESH
    process that owns exactly ``n_to`` devices — what a real relaunch
    on a shrunken fleet is (``subprocess_resume=False`` runs them
    in-process over ``devices[:n_to]`` instead: the --real form,
    because a live TPU job cannot boot a second process against chips
    it holds). Raises ``AssertionError`` naming the broken property;
    returns the episode summary dict on success."""
    import contextlib
    import json
    import subprocess
    import sys
    import tempfile

    import jax

    from apex1_tpu import planner
    from apex1_tpu.core.mesh import make_mesh
    from apex1_tpu.models import llama_3d as l3d
    from apex1_tpu.obs import spine
    from apex1_tpu.resilience.checkpointer import ResilientCheckpointer
    from apex1_tpu.testing import chaos

    def say(msg):
        if verbose:
            print(f"[elastic drill] {msg}", flush=True)

    devices = jax.devices()
    if len(devices) < n_from:
        raise AssertionError(
            f"drill needs {n_from} devices, have {len(devices)}")
    kill_step, auto_to = chaos.shrink_schedule(
        seed, n_devices=n_from, lo=2, hi=max(3, steps_total - 1))
    n_to = n_to or auto_to

    shape, cfg_of, _make_template, batch_at = _drill_fixture(seed)
    if n_from == 8:
        # stated dp2·pp2·tp2 with an INTERLEAVED stack (num_chunks=2):
        # the planner never searches num_chunks > 1 (docs/planner.md
        # "does NOT do"), so any re-plan lands on chunks=1 and the
        # resume exercises a genuine (2,2,1)->(1,pp',lps') chunk-stack
        # remap, never a trivial copy
        lay_a = planner.Layout(dp=2, pp=2, tp=2, num_microbatches=4,
                               num_chunks=2)
        plan_a = planner.plan_for_layout(shape, lay_a)
    else:
        plan_a = planner.make_plan(shape, n_from, allow_zero=False)

    with contextlib.ExitStack() as stack:
        work = work_dir or stack.enter_context(
            tempfile.TemporaryDirectory(prefix="elastic_drill_"))
        obs_dir = os.path.join(work, "obs")
        old_env = os.environ.get("APEX1_OBS_DIR")
        os.environ["APEX1_OBS_DIR"] = obs_dir
        stack.callback(lambda: (
            os.environ.__setitem__("APEX1_OBS_DIR", old_env)
            if old_env is not None
            else os.environ.pop("APEX1_OBS_DIR", None)))
        ckdir = os.path.join(work, "ckpt")

        # -- phase 1: train on n_from devices, die mid-run --------------
        cfg_a = cfg_of(plan_a)
        mesh_a = make_mesh(dp=cfg_a.dp, pp=cfg_a.pp, cp=cfg_a.cp,
                           ep=cfg_a.ep, tp=cfg_a.tp,
                           devices=devices[:n_from])
        step_a, state_a, _ = l3d.make_train_step(cfg_a, mesh=mesh_a)
        say(f"phase 1: {mesh_str(plan_a)} — {steps_total} steps "
            f"planned, kill after {kill_step} committed saves")
        with ResilientCheckpointer(ckdir, keep=8, plan=plan_a) as ck_a:
            for i in range(steps_total):
                t, lbl = batch_at(i, cfg_a)
                state_a, _loss = step_a(state_a, t, lbl)
                if i < kill_step:
                    ck_a.save(int(state_a["step"]), state_a,
                              meta={"data_step": i + 1})
            ck_a.wait()
        # "kill": everything after the last committed save is lost —
        # steps [kill_step, steps_total) trained but never banked
        del state_a, step_a

        # -- phases 2+3: the shrunken fleet ----------------------------
        if subprocess_resume:
            # a REAL relaunch: a fresh process owning exactly n_to
            # devices (the submesh never exists there)
            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            out_json = os.path.join(work, "resume_leg.json")
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       APEX1_OBS_DIR=obs_dir)
            env["PYTHONPATH"] = repo + os.pathsep + env.get(
                "PYTHONPATH", "")
            cmd = [sys.executable, "-m",
                   "apex1_tpu.resilience.elastic", "--resume-leg",
                   "--ckpt-dir", ckdir, "--work", work,
                   "--to-devices", str(n_to), "--seed", str(seed),
                   "--steps", str(steps_total),
                   "--out-json", out_json]
            r = subprocess.run(cmd, env=env, cwd=repo,
                               capture_output=True, text=True,
                               timeout=600)
            if verbose and r.stdout:
                for line in r.stdout.splitlines():
                    if line.startswith("[elastic drill]"):
                        print(line, flush=True)
            if r.returncode != 0:
                raise AssertionError(
                    f"resume leg failed (rc={r.returncode}):\n"
                    f"{r.stdout[-2000:]}\n{r.stderr[-2000:]}")
            with open(out_json) as f:
                leg = json.load(f)
        else:
            leg = _resume_leg(ckdir, work, n_to, seed, steps_total,
                              devices=devices, verbose=verbose)

        assert int(leg["data_step"]) == kill_step, \
            (leg["data_step"], kill_step)
        if n_from == 8:
            assert leg["n_restacked"] > 0, \
                "8-dev drill must exercise a real chunk-stack remap"
        assert len(leg["losses"]) >= 1          # resumed steps ran

        # -- phase 4: reconstruct the episode from banked events alone --
        events = []
        for name in sorted(os.listdir(obs_dir)):
            if name.endswith(".jsonl"):
                events += spine.read_events(
                    os.path.join(obs_dir, name))
        ev = {e["name"]: e for e in events
              if str(e.get("name", "")).startswith("elastic.")}
        for need in ("elastic.detect", "elastic.replan",
                     "elastic.reshard", "elastic.verify",
                     "elastic.resume"):
            assert need in ev, f"episode not reconstructable: {need} " \
                               f"missing from the spine"
        assert ev["elastic.detect"]["n_devices"] == n_to
        assert ev["elastic.detect"]["data_step"] == kill_step
        assert ev["elastic.replan"]["old_mesh"] == mesh_str(plan_a) \
            == leg["old_mesh"]
        assert ev["elastic.replan"]["new_mesh"] == leg["new_mesh"]
        assert (ev["elastic.reshard"]["n_leaves"]
                == leg["n_tree_digests"])
        assert ev["elastic.verify"]["conserved"] is True
        assert ev["elastic.resume"]["path"] == leg["path"]
        say("episode reconstructed from banked obs-spine events alone "
            "(detect -> replan -> reshard -> verify -> resume)")

        return {
            "kill_step": kill_step, "n_from": n_from, "n_to": n_to,
            "old_mesh": leg["old_mesh"], "new_mesh": leg["new_mesh"],
            "losses": leg["losses"],
            "n_leaves": leg["n_leaves"],
            "n_restacked": leg["n_restacked"],
            "events": sorted(ev),
        }


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--drill", action="store_true",
                    help="run the 8->4-device elastic acceptance "
                         "drill (CPU virtual mesh; the check_all "
                         "'== elastic drill ==' step)")
    ap.add_argument("--real", action="store_true",
                    help="use the live backend's devices (the "
                         "elastic_ab queue entry): shrink "
                         "n -> n/2 in-process; skip record below 2 "
                         "devices; falls back to the virtual CPU "
                         "form when JAX_PLATFORMS=cpu (rehearsal)")
    ap.add_argument("--from-devices", type=int, default=8)
    ap.add_argument("--to-devices", type=int, default=None)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--seed", type=int, default=20260804)
    # internal: the shrunken fleet's half of the drill (spawned by
    # drill() in its own n_to-device process)
    ap.add_argument("--resume-leg", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--work", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--out-json", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.resume_leg:
        from apex1_tpu.resilience.manifest import atomic_write_json
        from apex1_tpu.testing import force_virtual_cpu_devices

        force_virtual_cpu_devices(args.to_devices)
        leg = _resume_leg(args.ckpt_dir, args.work, args.to_devices,
                          args.seed, args.steps)
        atomic_write_json(args.out_json, leg)
        return 0

    if not args.drill:
        ap.print_help()
        return 0
    if args.real and os.environ.get("JAX_PLATFORMS",
                                    "").strip() != "cpu":
        import jax

        n = jax.device_count()
        if n < 2:
            print(f"[skip] elastic_ab: {n} device(s) — the shrink "
                  "drill needs >= 2 (record this window as skipped, "
                  "not failed)", flush=True)
            return 0
        n_from, n_to, sub = n, args.to_devices, False
    else:
        from apex1_tpu.testing import force_virtual_cpu_devices

        force_virtual_cpu_devices(args.from_devices)
        n_from, n_to, sub = args.from_devices, args.to_devices, True
    try:
        res = drill(n_from, n_to, seed=args.seed,
                    steps_total=args.steps, subprocess_resume=sub)
    except Exception as e:
        from apex1_tpu.planner import PlanError

        if args.real and isinstance(e, PlanError):
            # an odd live chip count can have no legal drill layout —
            # record the window as skipped, never as failed
            print(f"[skip] elastic_ab: no legal drill layout for "
                  f"{n_from} device(s): {e}", flush=True)
            return 0
        raise
    print(f"elastic drill OK: {res['old_mesh']} -> {res['new_mesh']} "
          f"(killed after step {res['kill_step']}, "
          f"{res['n_restacked']}/{res['n_leaves']} leaves restacked, "
          f"{len(res['losses'])} resumed steps bit-exact vs control, "
          f"episode reconstructed from {len(res['events'])} banked "
          f"event kinds)", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
