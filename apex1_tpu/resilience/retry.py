"""Bounded exponential backoff with deterministic jitter — the ONE
retry policy shared by the resilient runtime (`checkpointer` backend
writes, `runtime.RequestFeeder` backpressure, `tools/tpu_watch.sh`'s
python helpers).

Deliberately jax-free (stdlib only): retry decisions run on the host
control plane, never inside a traced program, and the chaos harness
(`apex1_tpu.testing.chaos`) must be able to exercise the policy in a
subprocess without paying a backend init.

Jitter is SEEDED (splitmix-style hash of (seed, attempt)), not
``random.random()``: two runs with the same seed retry on the same
schedule, which is what makes backoff behavior assertable in tier-1
instead of flaky.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional, Sequence, Type


class TransientError(Exception):
    """A failure worth retrying (backend unreachable, tunnel blip).
    The chaos harness raises exactly this class to verify retry paths."""


def _mix32(x: int) -> int:
    """Deterministic 32-bit avalanche (xorshift-multiply); stdlib-only
    sibling of ops.stochastic's hash — good enough for jitter."""
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    x ^= x >> 16
    return x


def backoff_delays(retries: int, *, base_s: float = 0.01,
                   cap_s: float = 2.0, factor: float = 2.0,
                   jitter: float = 0.5, seed: int = 0
                   ) -> Iterator[float]:
    """Yield ``retries`` sleep durations: ``base * factor**i`` capped at
    ``cap_s``, each scaled by a deterministic jitter in
    ``[1 - jitter, 1]`` keyed on ``(seed, attempt)``. ``jitter=0`` gives
    the exact exponential schedule."""
    if retries < 0:
        raise ValueError("retries must be >= 0")
    if not 0.0 <= jitter <= 1.0:
        raise ValueError("jitter must be in [0, 1]")
    for i in range(retries):
        d = min(float(cap_s), float(base_s) * float(factor) ** i)
        if jitter:
            u = _mix32(seed ^ _mix32(i + 1)) / 0xFFFFFFFF
            d *= 1.0 - jitter * u
        yield d


def retry_call(fn: Callable, *, retries: int = 5, base_s: float = 0.01,
               cap_s: float = 2.0, jitter: float = 0.5, seed: int = 0,
               deadline_s: Optional[float] = None,
               retry_on: Sequence[Type[BaseException]] = (TransientError,),
               sleep: Callable[[float], None] = time.sleep,
               on_retry: Optional[Callable[[int, BaseException], None]]
               = None):
    """Call ``fn()``; on an exception in ``retry_on``, back off and retry
    up to ``retries`` times. ``deadline_s`` bounds TOTAL time spent
    (drop-after-deadline: once exceeded, the pending exception is
    re-raised even with retries left — an overloaded queue must shed
    load, not stretch latency unboundedly). ``on_retry(attempt, exc)``
    is the metrics hook. Exceptions outside ``retry_on`` propagate
    immediately."""
    t0 = time.monotonic()
    delays = backoff_delays(retries, base_s=base_s, cap_s=cap_s,
                            jitter=jitter, seed=seed)
    attempt = 0
    while True:
        try:
            return fn()
        except tuple(retry_on) as e:
            attempt += 1
            try:
                d = next(delays)
            except StopIteration:
                raise e
            if deadline_s is not None and (
                    time.monotonic() - t0 + d) > deadline_s:
                raise e
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(d)
