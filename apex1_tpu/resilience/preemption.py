"""Preemption-safe shutdown: turn SIGTERM into a banked checkpoint and
a machine-readable "re-queue me" exit code.

The hardware this repo targets is preemptible and scarce (ROADMAP: the
measurement queue has been armed since round 1 waiting for a window) —
a run that dies mid-window must bank partial progress and exit in a way
the watcher (`tools/tpu_watch.sh`) can distinguish from a real failure.

Contract:

- `PreemptionHandler` installs SIGTERM/SIGINT handlers (main thread
  only — a Python signal-handler restriction) that SET A FLAG; the
  training loop checks ``handler.triggered`` at step boundaries, writes
  one final SYNCHRONOUS checkpoint, and calls ``exit_resumable()``.
- A SECOND delivery of any installed signal — the impatient scheduler
  double-tap, typically landing while the drain/final checkpoint is
  still in flight — escalates to an immediate ``os._exit(75)``
  (`EXIT_RESUMABLE`). Immediate because the scheduler is done waiting;
  resumable (75, never ``128+signum``) because the last COMMITTED
  checkpoint is still valid by the manifest/ring design — the job
  should be re-queued, not recorded as a failed round. The escalation
  is cross-signal on purpose (SIGINT then SIGTERM must escalate, not
  be swallowed as a "different" first signal).
- `EXIT_RESUMABLE` (75, BSD ``EX_TEMPFAIL``) is the exit-code half of
  the contract: ``tools/tpu_watch.sh`` re-queues an entry that exits 75
  at the head of the queue instead of recording a failed round, and the
  relaunch resumes via ``--resume auto`` / `find_restorable`.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Optional, Sequence

# BSD EX_TEMPFAIL: "temporary failure, retry later" — distinct from 0
# (done), 1 (real failure), and 124/137 (timeout kills), and stable
# across shells. tools/tpu_watch.sh greps for exactly this value.
EXIT_RESUMABLE = 75


class PreemptionHandler:
    """Grace-period SIGTERM/SIGINT hook for training loops.

    ::

        with PreemptionHandler() as pre:
            for step in range(start, total):
                state, metrics = train_step(state, batch_at(step))
                if pre.triggered:
                    ckptr.save_sync(step, state, meta={"data_step": step})
                    pre.exit_resumable(f"preempted at step {step}")

    ``grace_s`` documents the window the loop has to reach the next step
    boundary; ``deadline_exceeded()`` lets long steps bail early (skip
    the final checkpoint rather than be SIGKILLed mid-write — the
    previous async checkpoint is still valid, which is the point of the
    manifest/ring design).
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,
                                                 signal.SIGINT),
                 *, grace_s: float = 30.0):
        self.signals = tuple(signals)
        self.grace_s = float(grace_s)
        self._event = threading.Event()
        self._signum: Optional[int] = None
        self._t_signal: Optional[float] = None
        self._old = {}

    # -- install/uninstall -------------------------------------------------

    def install(self) -> "PreemptionHandler":
        for s in self.signals:
            self._old[s] = signal.signal(s, self._on_signal)
        return self

    def uninstall(self) -> None:
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old.clear()

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def _on_signal(self, signum, frame):
        if self._event.is_set():
            # double-tap while the drain/final checkpoint is in
            # flight: exit NOW (the scheduler stopped waiting), but
            # RESUMABLY — the previous committed checkpoint is valid,
            # so 75 re-queues the job where 128+signum would record a
            # failure and a swallowed flag would hang the drain.
            # os.write, not print: a signal handler must not re-enter
            # buffered I/O the interrupted frame may hold.
            os.write(2, b"[preemption] second signal during drain: "
                        b"immediate resumable exit (75)\n")
            os._exit(EXIT_RESUMABLE)
        self._signum = signum
        self._t_signal = time.monotonic()
        self._event.set()

    # -- loop-facing state -------------------------------------------------

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def deadline_exceeded(self) -> bool:
        """True once more than ``grace_s`` elapsed since the signal."""
        return (self._t_signal is not None
                and time.monotonic() - self._t_signal > self.grace_s)

    def exit_resumable(self, msg: str = "preempted; checkpoint banked"
                       ) -> None:
        """Exit with `EXIT_RESUMABLE` after flushing the message."""
        print(f"[preemption] {msg} (exit {EXIT_RESUMABLE}: resumable)",
              flush=True)
        sys.exit(EXIT_RESUMABLE)
