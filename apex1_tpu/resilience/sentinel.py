"""Divergence sentinel — a finite/divergence guard for ALL dtypes.

The fp16 path already skips non-finite-grad steps inside
`core.loss_scale` (device-side ``all_finite`` + ``select_tree``), but
bf16/fp32 runs train unguarded: a NaN loss at step k silently poisons
every parameter after it, and the failure is discovered hours later in
a loss curve. The sentinel closes that hole with a three-rung
escalation ladder:

1. **skip-step** (device side, every step, free): `guard_train_step`
   wraps any ``(state, *batch) -> (state, metrics)`` train step. It
   derives a fused health flag from the step's own metrics
   (``isfinite(loss) & isfinite(grad_norm) [& grads_finite]
   [& grad_norm < threshold]``) and keeps the OLD params/opt state on an
   unhealthy step via `core.loss_scale.select_tree` — the same where-keep
   machinery as the fp16 overflow skip, so no host sync is introduced:
   the flag is a carried `SentinelState` scalar, and the wrapped step's
   jaxpr contains no callbacks (pinned by test + graftlint).
2. **rollback** (host side, every ``check_every`` steps): `Sentinel.poll`
   reads the carried counters — the only device sync, amortized over N
   steps — and once ``consecutive_bad >= rollback_after`` (default 2)
   directs the loop to restore the last-good checkpoint via the
   `ResilientCheckpointer` and re-fold its PRNG stream (`refold_key` /
   `refold_seed`) so the retried trajectory doesn't replay the exact
   batch/noise sequence that diverged.
3. **abort** (host side): ``consecutive_bad >= abort_after``, or the
   rollback budget exhausted, or no valid checkpoint to roll back to —
   a `DivergenceError` carrying the banked diagnostic record (JSON on
   disk: step, counters, last loss/grad-norm) instead of a mystery hang.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Optional, Tuple

import chex
import jax
import jax.numpy as jnp

from apex1_tpu.core.loss_scale import select_tree
from apex1_tpu.resilience.manifest import atomic_write_json
from apex1_tpu.resilience.retry import _mix32


@chex.dataclass(frozen=True)
class SentinelState:
    """Device-carried counters (a pytree — checkpoint it with the rest
    of the train state so resume keeps the escalation context)."""

    steps_seen: jnp.ndarray       # i32: wrapped steps executed
    consecutive_bad: jnp.ndarray  # i32: current unhealthy streak
    total_bad: jnp.ndarray        # i32: lifetime unhealthy steps
    last_bad_step: jnp.ndarray    # i32: steps_seen index, -1 = never
    last_loss: jnp.ndarray        # f32: most recent loss (diagnostics)
    last_grad_norm: jnp.ndarray   # f32


def sentinel_init() -> SentinelState:
    return SentinelState(steps_seen=jnp.int32(0),
                         consecutive_bad=jnp.int32(0),
                         total_bad=jnp.int32(0),
                         last_bad_step=jnp.int32(-1),
                         last_loss=jnp.float32(0.0),
                         last_grad_norm=jnp.float32(0.0))


def health_flag(metrics: dict, *, gnorm_threshold: Optional[float] = None,
                axis_names: Tuple[str, ...] = ()) -> jnp.ndarray:
    """Fused scalar health predicate from a train step's metrics dict:
    loss/grad_norm finite, ``grads_finite`` honored when present, and an
    optional hard grad-norm ceiling (divergence is not only NaN). Under
    ``shard_map`` pass ``axis_names`` so ranks agree (pmin)."""
    flags = []
    for key in ("loss", "grad_norm"):
        if key in metrics:
            v = jnp.asarray(metrics[key])
            if jnp.issubdtype(v.dtype, jnp.floating):
                flags.append(jnp.all(jnp.isfinite(v)))
    if "grads_finite" in metrics:
        flags.append(jnp.asarray(metrics["grads_finite"]))
    if gnorm_threshold is not None and "grad_norm" in metrics:
        flags.append(jnp.asarray(metrics["grad_norm"])
                     < jnp.float32(gnorm_threshold))
    if not flags:
        healthy = jnp.bool_(True)
    else:
        healthy = flags[0]
        for f in flags[1:]:
            healthy = jnp.logical_and(healthy, f)
    for ax in axis_names:
        healthy = jax.lax.pmin(healthy.astype(jnp.int32),
                               ax).astype(jnp.bool_)
    return healthy


def guard_train_step(train_step: Callable, *,
                     gnorm_threshold: Optional[float] = None,
                     axis_names: Tuple[str, ...] = ()) -> Callable:
    """Wrap ``train_step(state, *batch) -> (new_state, metrics)`` into
    ``guarded((state, sentinel_state), *batch) -> ((state', sentinel'),
    metrics)``. Unhealthy steps keep the old state (a ``step`` field, if
    the state has one, still advances — matching the fp16 overflow-skip
    contract so data progress is not replayed). Pure and host-sync-free:
    wrap the RESULT in ``jax.jit``/``shard_map``."""

    # graftlint: hot -- returned for the caller to jax.jit (same
    # closure-return edge as amp.make_train_step)
    def guarded(carry, *batch):
        state, s = carry
        new_state, metrics = train_step(state, *batch)
        healthy = health_flag(metrics, gnorm_threshold=gnorm_threshold,
                              axis_names=axis_names)
        kept = select_tree(healthy, new_state, state)
        if dataclasses.is_dataclass(kept) and hasattr(kept, "step"):
            kept = dataclasses.replace(kept, step=new_state.step)
        bad = jnp.logical_not(healthy)
        loss = jnp.asarray(metrics.get("loss", jnp.float32(0.0)))
        gnorm = jnp.asarray(metrics.get("grad_norm", jnp.float32(0.0)))
        new_s = SentinelState(
            steps_seen=s.steps_seen + 1,
            consecutive_bad=jnp.where(bad, s.consecutive_bad + 1,
                                      0).astype(jnp.int32),
            total_bad=(s.total_bad + bad.astype(jnp.int32)),
            last_bad_step=jnp.where(bad, s.steps_seen,
                                    s.last_bad_step).astype(jnp.int32),
            last_loss=loss.astype(jnp.float32),
            last_grad_norm=gnorm.astype(jnp.float32))
        metrics = dict(metrics)
        metrics["sentinel_healthy"] = healthy
        return (kept, new_s), metrics

    return guarded


def refold_key(key, attempt: int):
    """Re-fold a jax PRNG key for a post-rollback retry: attempt 1, 2, …
    draw distinct streams, so the retry does not replay the exact
    stochastic trajectory that diverged."""
    return jax.random.fold_in(key, jnp.uint32(0x5EED0000 + int(attempt)))


def refold_seed(seed: int, attempt: int) -> int:
    """Integer-seed (counter-based kernels, `ops.stochastic`) analog of
    `refold_key` — deterministic avalanche of (seed, attempt)."""
    return _mix32(int(seed) ^ _mix32(0x5EED0000 + int(attempt)))


class DivergenceError(RuntimeError):
    """Escalation exhausted; ``record`` is the banked diagnostic."""

    def __init__(self, msg: str, record: dict):
        super().__init__(msg)
        self.record = record


class Sentinel:
    """Host-side escalation policy around the device-carried counters.

    Typical loop::

        sent = Sentinel(ckptr, check_every=10)
        guarded = jax.jit(sent.guard(amp.make_train_step(loss_fn)))
        carry = (state, sentinel_init())
        while step < total:
            carry, metrics = guarded(carry, batch_at(step))
            action = sent.poll(carry[1])          # syncs every Nth call
            if action == "rollback":
                state, manifest, s0 = sent.rollback(template=carry[0])
                step = manifest.step              # rewind data position
                carry = (state, s0)               # + refold_key(...)
                continue
            step += 1

    ``poll`` raises `DivergenceError` on the abort rung; every rollback
    and abort banks a JSON diagnostic record under ``diagnostics_dir``
    (default ``<checkpoint dir>/diagnostics``).
    """

    def __init__(self, checkpointer=None, *, check_every: int = 10,
                 rollback_after: int = 2, abort_after: int = 4,
                 max_rollbacks: int = 2,
                 gnorm_threshold: Optional[float] = None,
                 diagnostics_dir: Optional[str] = None):
        if check_every < 1:
            raise ValueError("check_every must be >= 1")
        if not 1 <= rollback_after <= abort_after:
            raise ValueError("need 1 <= rollback_after <= abort_after")
        self.checkpointer = checkpointer
        self.check_every = int(check_every)
        self.rollback_after = int(rollback_after)
        self.abort_after = int(abort_after)
        self.max_rollbacks = int(max_rollbacks)
        self.gnorm_threshold = gnorm_threshold
        self.diagnostics_dir = diagnostics_dir
        self.records: list[dict] = []   # banked this process, in order
        self.rollbacks_done = 0
        self._polls = 0

    def guard(self, train_step: Callable,
              axis_names: Tuple[str, ...] = ()) -> Callable:
        return guard_train_step(train_step,
                                gnorm_threshold=self.gnorm_threshold,
                                axis_names=axis_names)

    def init_state(self) -> SentinelState:
        return sentinel_init()

    # -- host control plane (cold code: the int() casts below are the
    # amortized every-Nth-step sync, never inside a traced program) -----

    def _diagnostic(self, s: SentinelState, action: str) -> dict:
        return {"action": action,
                "time": time.time(),
                "steps_seen": int(s.steps_seen),
                "consecutive_bad": int(s.consecutive_bad),
                "total_bad": int(s.total_bad),
                "last_bad_step": int(s.last_bad_step),
                "last_loss": float(s.last_loss),
                "last_grad_norm": float(s.last_grad_norm),
                "rollbacks_done": self.rollbacks_done}

    def _bank_dir(self) -> Optional[str]:
        """Resolved lazily, not at __init__: the checkpointer may be
        attached after construction (fingerprint chicken-and-egg in
        training loops — see examples/gpt2_amp.py)."""
        if self.diagnostics_dir is not None:
            return self.diagnostics_dir
        if self.checkpointer is not None:
            return os.path.join(self.checkpointer.directory,
                                "diagnostics")
        return None

    def _bank(self, record: dict) -> dict:
        self.records.append(record)
        ddir = self._bank_dir()
        if ddir:
            os.makedirs(ddir, exist_ok=True)
            name = (f"divergence_{len(self.records):04d}_"
                    f"{record['action']}.json")
            atomic_write_json(os.path.join(ddir, name), record)
            record["path"] = os.path.join(ddir, name)
        # mirror into the telemetry spine (APEX1_OBS_DIR): divergence
        # diagnostics join the same run stream as the loop's metrics,
        # so a skipped/rolled-back step is visible NEXT TO the loss
        # curve it interrupted (docs/observability.md)
        from apex1_tpu.obs import spine
        spine.emit("event", "sentinel.diagnostic", **record)
        return record

    def poll(self, s: SentinelState, *, force: bool = False
             ) -> Optional[str]:
        """Check the carried counters every ``check_every``-th call (one
        device sync). Returns None (healthy / not checked), ``"skip"``
        (bad steps were skipped device-side, below the rollback rung),
        or ``"rollback"``; raises `DivergenceError` on the abort rung."""
        self._polls += 1
        if not force and self._polls % self.check_every:
            return None
        consecutive = int(s.consecutive_bad)
        if consecutive == 0:
            return None
        can_rollback = (self.checkpointer is not None
                        and self.rollbacks_done < self.max_rollbacks
                        and self.checkpointer.latest_valid() is not None)
        if consecutive >= self.abort_after or (
                consecutive >= self.rollback_after and not can_rollback):
            record = self._bank(self._diagnostic(s, "abort"))
            raise DivergenceError(
                f"diverged: {consecutive} consecutive unhealthy steps "
                f"(total {int(s.total_bad)}), escalation exhausted — "
                f"diagnostic banked at {record.get('path', '<memory>')}",
                record)
        if consecutive >= self.rollback_after:
            self._bank(self._diagnostic(s, "rollback"))
            return "rollback"
        self._bank(self._diagnostic(s, "skip"))
        return "skip"

    def rollback(self, template: Any):
        """Restore the last-good checkpoint. Returns ``(state, manifest,
        fresh_sentinel_state)``; the caller rewinds its data position to
        ``manifest.step`` and re-folds its PRNG with `refold_key(key,
        sentinel.rollbacks_done)`."""
        if self.checkpointer is None:
            raise DivergenceError("rollback requested without a "
                                  "checkpointer", {})
        state, manifest = self.checkpointer.restore(template)
        self.rollbacks_done += 1
        return state, manifest, sentinel_init()
