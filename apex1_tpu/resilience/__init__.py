"""Resilient training runtime — SURVEY §5.2's missing elastic-recovery
story, built as four cooperating pieces (see `docs/robustness.md`):

- `ResilientCheckpointer` / `find_restorable` (`.checkpointer`): async
  double-buffered snapshots with per-leaf integrity manifests, atomic
  ``latest`` promotion, ring keep-policy + milestone pins, and a
  backward scan past corrupt checkpoints to the newest valid one.
- `Sentinel` / `guard_train_step` (`.sentinel`): a device-side
  finite/divergence guard for ALL dtypes with a skip → rollback → abort
  escalation ladder and banked diagnostics.
- `PreemptionHandler` / `EXIT_RESUMABLE` (`.preemption`): SIGTERM grace
  hook → final sync checkpoint → the exit code `tools/tpu_watch.sh`
  re-queues instead of recording a failure.
- `retry_call` / `backoff_delays` / `TransientError` (`.retry`): the one
  bounded-exponential-backoff-with-deterministic-jitter policy, shared
  with `runtime.RequestFeeder`.
- `reshard_state` / `reshard_checkpoint` / `LayoutMismatch`
  (`.reshard`) + `elastic_resume` / `ElasticDecision` / the elastic
  drill (`.elastic`): plan-carrying checkpoints remapped onto a fresh
  planner layout when the fleet shrinks/grows — manifest-verified end
  to end, every decision banked as obs-spine events (ISSUE 14,
  docs/robustness.md § Elastic resume).

Every recovery path is exercised deterministically on CPU by the chaos
harness (`apex1_tpu.testing.chaos`) — injected NaNs, truncated and
bit-flipped checkpoints, simulated SIGTERM, transient backend errors.
"""

from apex1_tpu.resilience.checkpointer import (ResilientCheckpointer,
                                               find_restorable,
                                               is_valid_checkpoint,
                                               step_dir_name)
from apex1_tpu.resilience.elastic import ElasticDecision, elastic_resume
from apex1_tpu.resilience.manifest import (IntegrityError, Manifest,
                                           read_manifest, verify_files,
                                           verify_tree, write_manifest)
from apex1_tpu.resilience.preemption import EXIT_RESUMABLE, PreemptionHandler
from apex1_tpu.resilience.reshard import (LayoutMismatch, read_plan,
                                          reshard_checkpoint,
                                          reshard_state)
from apex1_tpu.resilience.retry import (TransientError, backoff_delays,
                                        retry_call)
from apex1_tpu.resilience.sentinel import (DivergenceError, Sentinel,
                                           SentinelState, guard_train_step,
                                           health_flag, refold_key,
                                           refold_seed, sentinel_init)

__all__ = [
    "ResilientCheckpointer", "find_restorable", "is_valid_checkpoint",
    "step_dir_name",
    "IntegrityError", "Manifest", "read_manifest", "verify_files",
    "verify_tree", "write_manifest",
    "EXIT_RESUMABLE", "PreemptionHandler",
    "ElasticDecision", "LayoutMismatch", "elastic_resume", "read_plan",
    "reshard_checkpoint", "reshard_state",
    "TransientError", "backoff_delays", "retry_call",
    "DivergenceError", "Sentinel", "SentinelState", "guard_train_step",
    "health_flag", "refold_key", "refold_seed", "sentinel_init",
]
