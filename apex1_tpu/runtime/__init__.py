"""Host-side native runtime — ctypes bindings over ``_runtime.cpp``.

Reference: ``csrc/flatten_unflatten.cpp :: flatten/unflatten`` (the
``apex_C`` extension backing DDP bucket flattening) and
``examples/imagenet/main_amp.py :: data_prefetcher`` (side-stream input
normalization + prefetch). See `_runtime.cpp` for the TPU-native design
rationale. The library is compiled on first import with ``g++ -O3``;
every entry point has a NumPy fallback so the package works without a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import queue
import subprocess
import threading
import time as _time
from typing import Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_runtime.cpp")
_LIB_PATH = os.path.join(_DIR, "_runtime.so")
_N_THREADS = max(1, (os.cpu_count() or 4) // 2)


def _build_library() -> Optional[str]:
    if os.path.exists(_LIB_PATH) and (os.path.getmtime(_LIB_PATH)
                                      >= os.path.getmtime(_SRC)):
        return _LIB_PATH
    tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"  # per-pid: concurrent imports
    try:                                    # must not interleave writes
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
             "-pthread", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)          # atomic publish
        return _LIB_PATH
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> Optional[ctypes.CDLL]:
    path = _build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        # gate BEFORE touching any symbol: a stale .so from an older source
        # must fall back to NumPy, and ctypes raises AttributeError (not
        # OSError) for missing symbols
        lib.apex1_runtime_abi_version.restype = ctypes.c_int
        if lib.apex1_runtime_abi_version() != 4:
            return None
        i64, vp = ctypes.c_int64, ctypes.c_void_p
        lib.apex1_flatten.argtypes = [ctypes.POINTER(vp),
                                      ctypes.POINTER(i64), i64, vp,
                                      ctypes.c_int]
        lib.apex1_unflatten.argtypes = [vp, ctypes.POINTER(i64), i64,
                                        ctypes.POINTER(vp), ctypes.c_int]
        lib.apex1_normalize_u8_f32.argtypes = [
            vp, vp, i64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), i64, ctypes.c_int]
        lib.apex1_f32_to_bf16.argtypes = [vp, vp, i64, ctypes.c_int]
        lib.apex1_bf16_to_f32.argtypes = [vp, vp, i64, ctypes.c_int]
        lib.apex1_loader_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                          i64, i64, ctypes.c_uint64,
                                          ctypes.c_int]
        lib.apex1_loader_open.restype = vp
        lib.apex1_loader_num_sequences.argtypes = [vp]
        lib.apex1_loader_num_sequences.restype = i64
        lib.apex1_loader_next.argtypes = [vp, i64, vp, ctypes.c_int]
        lib.apex1_loader_next.restype = ctypes.c_int
        lib.apex1_loader_fetch.argtypes = [vp, i64, vp]
        lib.apex1_loader_fetch.restype = ctypes.c_int
        lib.apex1_loader_close.argtypes = [vp]
        lib.apex1_pack_fill.argtypes = [
            vp, vp, vp, vp, vp, vp, vp, i64, vp, vp, vp, i64, i64,
            ctypes.c_int32, ctypes.c_int]
        lib.apex1_pack_plan.argtypes = [
            vp, vp, i64, i64, ctypes.c_int, vp, vp, vp, vp, vp, vp]
        lib.apex1_pack_plan.restype = i64
        return lib
    except (OSError, AttributeError):
        return None


_LIB = _load()


def native_available() -> bool:
    return _LIB is not None


def _as_contig(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a)


def flatten(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Pack arrays into one contiguous byte buffer (``apex_C.flatten``).
    Returns a uint8 view; pair with `unflatten` + the original specs."""
    arrays = [_as_contig(np.asarray(a)) for a in arrays]
    sizes = [a.nbytes for a in arrays]
    out = np.empty(sum(sizes), np.uint8)
    if _LIB is not None and arrays:
        n = len(arrays)
        srcs = (ctypes.c_void_p * n)(
            *[a.ctypes.data for a in arrays])
        csizes = (ctypes.c_int64 * n)(*sizes)
        _LIB.apex1_flatten(srcs, csizes, n, out.ctypes.data, _N_THREADS)
    else:
        off = 0
        for a, s in zip(arrays, sizes):
            out[off:off + s] = a.view(np.uint8).reshape(-1)
            off += s
    return out


def unflatten(flat: np.ndarray,
              specs: Sequence[tuple[tuple[int, ...], np.dtype]]
              ) -> list[np.ndarray]:
    """Inverse of `flatten`: ``specs`` is [(shape, dtype), ...]
    (``apex_C.unflatten``)."""
    flat = _as_contig(np.asarray(flat)).view(np.uint8)
    outs = [np.empty(shape, dtype) for shape, dtype in specs]
    sizes = [o.nbytes for o in outs]
    if sum(sizes) != flat.nbytes:
        raise ValueError(f"flat buffer holds {flat.nbytes} bytes, specs "
                         f"need {sum(sizes)}")
    if _LIB is not None and outs:
        n = len(outs)
        dsts = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
        csizes = (ctypes.c_int64 * n)(*sizes)
        _LIB.apex1_unflatten(flat.ctypes.data, csizes, n, dsts, _N_THREADS)
    else:
        off = 0
        for o, s in zip(outs, sizes):
            o.view(np.uint8).reshape(-1)[:] = flat[off:off + s]
            off += s
    return outs


def normalize_images(batch_u8: np.ndarray, mean: Sequence[float],
                     std: Sequence[float]) -> np.ndarray:
    """uint8 NHWC -> fp32 ``(x/255 - mean) / std`` per channel — the
    reference prefetcher's side-stream normalize, on host threads."""
    batch_u8 = _as_contig(np.asarray(batch_u8, np.uint8))
    c = batch_u8.shape[-1]
    if len(mean) != c or len(std) != c:
        raise ValueError(f"mean/std length must equal channels ({c})")
    out = np.empty(batch_u8.shape, np.float32)
    if _LIB is not None:
        fmean = (ctypes.c_float * c)(*[float(m) for m in mean])
        fstd = (ctypes.c_float * c)(*[float(s) for s in std])
        _LIB.apex1_normalize_u8_f32(batch_u8.ctypes.data, out.ctypes.data,
                                    batch_u8.size, fmean, fstd, c,
                                    _N_THREADS)
    else:
        out[:] = (batch_u8.astype(np.float32) / 255.0
                  - np.asarray(mean, np.float32)) / np.asarray(
                      std, np.float32)
    return out


def f32_to_bf16_bits(x: np.ndarray) -> np.ndarray:
    """fp32 -> bf16 bit patterns (uint16), round-to-nearest-even — host
    staging for bf16 comm/checkpoint buffers."""
    x = _as_contig(np.asarray(x, np.float32))
    out = np.empty(x.shape, np.uint16)
    if _LIB is not None:
        _LIB.apex1_f32_to_bf16(x.ctypes.data, out.ctypes.data, x.size,
                               _N_THREADS)
    else:
        bits = x.view(np.uint32)
        rounding = 0x7FFF + ((bits >> 16) & 1)
        rounded = ((bits + rounding) >> 16).astype(np.uint16)
        # NaN: carry out of the mantissa would corrupt to ±0 — quiet it
        nan = (bits & 0x7FFFFFFF) > 0x7F800000
        out[:] = np.where(nan, ((bits >> 16) | 0x0040).astype(np.uint16),
                          rounded)
    return out


def bf16_bits_to_f32(bits: np.ndarray) -> np.ndarray:
    bits = _as_contig(np.asarray(bits, np.uint16))
    out = np.empty(bits.shape, np.float32)
    if _LIB is not None:
        _LIB.apex1_bf16_to_f32(bits.ctypes.data, out.ctypes.data,
                               bits.size, _N_THREADS)
    else:
        out.view(np.uint32)[:] = bits.astype(np.uint32) << 16
    return out


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 over uint64 — must match ``mix64`` in `_runtime.cpp`."""
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(30)))
             * np.uint64(0xBF58476D1CE4E5B9)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(27)))
             * np.uint64(0x94D049BB133111EB)).astype(np.uint64)
        return x ^ (x >> np.uint64(31))


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _epoch_perm(epoch: np.ndarray, i: np.ndarray, *, seed: int, n: int,
                pow2: int) -> np.ndarray:
    """Exact permutation of [0, n) per epoch (cycle-walked affine map over
    the pow2 ring) — the math of ``TokenLoader::perm``, vectorized."""
    seed = np.uint64(seed)
    a = (_mix64(seed ^ _mix64(epoch)) | np.uint64(1))
    c = _mix64(seed ^ _mix64(epoch ^ np.uint64(0xD1B54A32D192ED03)))
    m = np.uint64(pow2 - 1)
    x = i.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (a * x + c) & m
        todo = x >= np.uint64(n)
        while np.any(todo):
            x[todo] = (a[todo] * x[todo] + c[todo]) & m
            todo = x >= np.uint64(n)
    return x.astype(np.int64)


class TokenDataset:
    """Deterministic LM-pretraining batches from a flat binary token file.

    TPU-native design (vs. the reference's stateful torch DataLoader
    iterators): ``batch_at(step)`` is a pure function of (file, seed,
    step) — checkpoint/resume stores only the step counter, matching the
    framework's functional train-state story, and prefetch workers can
    fetch any step. Shuffling is an exact per-epoch permutation (affine
    map over the next power of two with cycle-walking — O(1) memory for
    arbitrarily large corpora). Backed by the memory-mapped native loader
    in `_runtime.cpp`; the NumPy fallback reproduces the identical
    permutation bit-for-bit.

    The file is raw little-endian tokens, uint16 (vocab < 65536) or
    int32/uint32. For next-token training use ``seq_len = S + 1`` and
    shift in the loss.
    """

    def __init__(self, path: str, *, seq_len: int, batch_size: int,
                 dtype=np.uint16, seed: int = 0, shuffle: bool = True):
        self.path = str(path)
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self.dtype = np.dtype(dtype)
        if self.dtype.itemsize not in (2, 4):
            raise ValueError("token dtype must be 2 or 4 bytes")
        # wrap to uint64 so native (C cast) and NumPy fallback agree for
        # negative / oversized seeds
        self.seed = int(seed) & ((1 << 64) - 1)
        self.shuffle = bool(shuffle)
        self._closed = False
        self._handle = None
        self._finalizer = None
        if _LIB is not None:
            self._handle = _LIB.apex1_loader_open(
                self.path.encode(), self.dtype.itemsize, self.seq_len,
                self.batch_size, ctypes.c_uint64(self.seed),
                int(self.shuffle))
            if self._handle:
                import weakref
                self._finalizer = weakref.finalize(
                    self, _LIB.apex1_loader_close, self._handle)
        if self._handle:
            self.num_sequences = int(
                _LIB.apex1_loader_num_sequences(self._handle))
            self._tokens = None
        else:
            self._tokens = np.memmap(self.path, dtype=self.dtype, mode="r")
            self.num_sequences = len(self._tokens) // self.seq_len
        if self.num_sequences < 1:
            raise ValueError(
                f"{path}: fewer than one {seq_len}-token sequence")
        self._pow2 = _next_pow2(self.num_sequences)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def steps_per_epoch(self) -> int:
        return self.num_sequences // self.batch_size

    def _perm(self, epoch: np.ndarray, i: np.ndarray) -> np.ndarray:
        """Vectorized epoch permutation — mirrors TokenLoader::perm."""
        if not self.shuffle:
            return i.astype(np.int64)
        return _epoch_perm(epoch, i, seed=self.seed, n=self.num_sequences,
                           pow2=self._pow2)

    def fetch(self, seq_index: int, out=None) -> np.ndarray:
        """One raw sequence by index (no permutation) — the building
        block `ShardedTokenDataset` routes its global shuffle through.
        ``out``: optional int32 (seq_len,) buffer to fill in place (the
        sharded batch loop passes batch rows, avoiding per-row allocs)."""
        if self._closed:
            raise RuntimeError("TokenDataset is closed")
        if not 0 <= seq_index < self.num_sequences:
            raise IndexError(seq_index)
        if out is None:
            out = np.empty((self.seq_len,), np.int32)
        if self._handle:
            rc = _LIB.apex1_loader_fetch(self._handle, seq_index,
                                         out.ctypes.data)
            if rc != 0:
                raise RuntimeError(f"loader_fetch failed ({seq_index})")
            return out
        lo = seq_index * self.seq_len
        out[:] = self._tokens[lo:lo + self.seq_len]
        return out

    def batch_at(self, step: int) -> np.ndarray:
        """(batch_size, seq_len) int32 tokens of global step ``step``."""
        if self._closed:
            raise RuntimeError("TokenDataset is closed")
        if step < 0:
            raise ValueError("step must be >= 0")
        out = np.empty((self.batch_size, self.seq_len), np.int32)
        if self._handle:
            rc = _LIB.apex1_loader_next(self._handle, step,
                                        out.ctypes.data, _N_THREADS)
            if rc != 0:
                raise RuntimeError(f"loader_next failed (step={step})")
            return out
        g = np.uint64(step) * np.uint64(self.batch_size) + np.arange(
            self.batch_size, dtype=np.uint64)
        epoch = g // np.uint64(self.num_sequences)
        s = self._perm(epoch, g % np.uint64(self.num_sequences))
        for r in range(self.batch_size):
            lo = int(s[r]) * self.seq_len
            out[r] = self._tokens[lo:lo + self.seq_len]
        return out

    def iter_from(self, step: int = 0) -> Iterator[np.ndarray]:
        """Endless step-indexed batch stream (wrap in `PrefetchLoader` to
        overlap host work with device compute)."""
        while True:
            yield self.batch_at(step)
            step += 1

    def close(self):
        self._closed = True
        if self._finalizer is not None:
            self._finalizer()  # idempotent: detaches + closes the handle
            self._finalizer = None
        self._handle = None
        self._tokens = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ShardedTokenDataset:
    """`TokenDataset` over a sharded corpus (many flat token files) —
    real pretraining datasets ship as shards. Same contract: pure
    ``batch_at(step)``, exact global shuffle (one permutation over the
    CONCATENATED sequence pool, so epoch boundaries and resume semantics
    are corpus-global, not per-shard), NumPy fallback bit-identical.
    Shards are mmapped native loaders; rows route to their shard via the
    cumulative sequence counts. Shard order is the CALLER's order (pass
    a sorted list for a canonical corpus — no silent re-sorting)."""

    def __init__(self, paths: Sequence[str], *, seq_len: int,
                 batch_size: int, dtype=np.uint16, seed: int = 0,
                 shuffle: bool = True):
        if not paths:
            raise ValueError("need at least one shard path")
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self.seed = int(seed) & ((1 << 64) - 1)
        self.shuffle = bool(shuffle)
        self._shards = [TokenDataset(str(p), seq_len=seq_len,
                                     batch_size=1, dtype=dtype, seed=0,
                                     shuffle=False) for p in paths]
        counts = [s.num_sequences for s in self._shards]
        self._starts = np.concatenate([[0], np.cumsum(counts)])
        self.num_sequences = int(self._starts[-1])
        self._pow2 = _next_pow2(self.num_sequences)

    @property
    def native(self) -> bool:
        return all(s.native for s in self._shards)

    def steps_per_epoch(self) -> int:
        return self.num_sequences // self.batch_size

    def batch_at(self, step: int) -> np.ndarray:
        if step < 0:
            raise ValueError("step must be >= 0")
        g = np.uint64(step) * np.uint64(self.batch_size) + np.arange(
            self.batch_size, dtype=np.uint64)
        epoch = g // np.uint64(self.num_sequences)
        i = g % np.uint64(self.num_sequences)
        s = (_epoch_perm(epoch, i, seed=self.seed, n=self.num_sequences,
                         pow2=self._pow2)
             if self.shuffle else i.astype(np.int64))
        out = np.empty((self.batch_size, self.seq_len), np.int32)
        shard_of = np.searchsorted(self._starts, s, side="right") - 1
        for r in range(self.batch_size):
            sh = int(shard_of[r])
            self._shards[sh].fetch(int(s[r] - self._starts[sh]),
                                   out=out[r])
        return out

    def iter_from(self, step: int = 0) -> Iterator[np.ndarray]:
        while True:
            yield self.batch_at(step)
            step += 1

    def close(self):
        for s in self._shards:
            s.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def pack_documents(docs: Sequence[np.ndarray], seq_len: int,
                   *, pad_id: int = 0,
                   restart_chunk_positions: bool = False):
    """Greedy first-fit packing of variable-length documents into fixed
    (rows, seq_len) batches — the data-side half of varlen attention
    (≙ the reference fmha's cu_seqlens packed QKV batches; the model side
    is ``segment_ids`` on the flash/ring attention kernels).

    Returns ``(tokens, segment_ids, positions)``, each (rows, seq_len)
    int32. ``segment_ids`` are unique per document within a row, ``-1`` on
    padding (never matches a real segment in the kernels' equality mask);
    ``positions`` restart at 0 per document (feed per-row RoPE tables).
    Documents longer than ``seq_len`` are split into ``seq_len`` chunks
    (each chunk its own segment); their positions continue within the doc
    (RoPE models — no table bound) unless ``restart_chunk_positions`` is
    set, which restarts every chunk at 0 (REQUIRED for learned-position
    models like GPT-2, whose position table would otherwise be indexed
    out of bounds and silently clamped).
    """
    if seq_len <= 0:
        # must precede the native branch: apex1_pack_plan's chunk loop
        # cannot advance at seq_len <= 0 (unbounded writes, not an error)
        raise ValueError(f"seq_len must be positive, got {seq_len}")
    docs = [np.ascontiguousarray(np.asarray(d).ravel(), np.int32)
            for d in docs]
    doc_lens = np.asarray([len(d) for d in docs], np.int64)
    doc_starts = np.zeros(len(docs) + 1, np.int64)
    np.cumsum(doc_lens, out=doc_starts[1:])
    flat = (np.concatenate(docs) if docs else np.zeros(0, np.int32))
    n_chunks = int(np.sum(-(-doc_lens // seq_len)))

    if _LIB is not None:
        # native plan (first-fit placement) + threaded fill
        starts = np.empty(n_chunks, np.int64)
        lens64 = np.empty(n_chunks, np.int64)
        rows64 = np.empty(n_chunks, np.int64)
        cols64 = np.empty(n_chunks, np.int64)
        segs32 = np.empty(n_chunks, np.int32)
        pos032 = np.empty(n_chunks, np.int32)
        n = _LIB.apex1_pack_plan(
            doc_lens.ctypes.data, doc_starts.ctypes.data, len(docs),
            seq_len, int(restart_chunk_positions), starts.ctypes.data,
            lens64.ctypes.data, rows64.ctypes.data, cols64.ctypes.data,
            segs32.ctypes.data, pos032.ctypes.data)
        tokens = np.empty((n, seq_len), np.int32)
        segs = np.empty((n, seq_len), np.int32)
        pos = np.empty((n, seq_len), np.int32)
        _LIB.apex1_pack_fill(
            flat.ctypes.data, starts.ctypes.data, lens64.ctypes.data,
            rows64.ctypes.data, cols64.ctypes.data, segs32.ctypes.data,
            pos032.ctypes.data, n_chunks, tokens.ctypes.data,
            segs.ctypes.data, pos.ctypes.data, n, seq_len, pad_id,
            _N_THREADS)
        return tokens, segs, pos

    # ---- NumPy fallback: identical first-fit policy in Python ----
    space: list[int] = []
    fill: list[int] = []       # next free column per row
    nseg: list[int] = []       # segments placed per row
    open_rows: list[int] = []  # bounded first-fit window: corpus-scale
    MAX_OPEN = 256             # packing stays O(chunks · MAX_OPEN)
    plan: list[tuple[int, int, int, int, int, int]] = []
    for di, doc in enumerate(docs):
        for lo in range(0, len(doc), seq_len):
            ln = min(seq_len, len(doc) - lo)
            for r in open_rows:
                if space[r] >= ln:
                    break
            else:
                r = len(space)
                space.append(seq_len)
                fill.append(0)
                nseg.append(0)
                if ln < seq_len:   # full rows never enter the window
                    open_rows.append(r)
                    if len(open_rows) > MAX_OPEN:
                        open_rows.pop(0)  # evict by age, stays bounded
            plan.append((int(doc_starts[di]) + lo, ln, r, fill[r],
                         nseg[r], 0 if restart_chunk_positions else lo))
            space[r] -= ln
            fill[r] += ln
            nseg[r] += 1
            if space[r] == 0 and r in open_rows:
                open_rows.remove(r)
    n = len(space)
    tokens = np.full((n, seq_len), pad_id, np.int32)
    segs = np.full((n, seq_len), -1, np.int32)
    pos = np.zeros((n, seq_len), np.int32)
    for start, ln, r, c, sid, pos0 in plan:
        tokens[r, c:c + ln] = flat[start:start + ln]
        segs[r, c:c + ln] = sid
        pos[r, c:c + ln] = np.arange(pos0, pos0 + ln)
    return tokens, segs, pos


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write a flat token file `TokenDataset` can read (little-endian)."""
    arr = np.asarray(tokens)
    if arr.dtype.itemsize not in (2, 4):
        raise ValueError("token dtype must be 2 or 4 bytes")
    arr.astype(arr.dtype.newbyteorder("<")).tofile(path)


class PrefetchLoader:
    """Background-thread prefetcher — ``examples/imagenet ::
    data_prefetcher`` equivalent. Pulls batches from ``source`` on a worker
    thread, runs ``transform`` (e.g. `normalize_images` or `flatten`) off
    the critical path, and optionally ``device_put``s ahead so
    host→device transfer overlaps the current step (the reference's CUDA
    side-stream overlap, via JAX async dispatch)."""

    _DONE = object()

    def __init__(self, source: Iterable, *,
                 transform: Optional[Callable] = None,
                 device_put: bool = True, prefetch: int = 2):
        self.source = source
        self.transform = transform
        self.device_put = device_put
        self.prefetch = max(1, prefetch)

    def __iter__(self) -> Iterator:
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        err: list[BaseException] = []
        stop = threading.Event()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def work():
            try:
                import jax
                for batch in self.source:
                    if stop.is_set():
                        return
                    if self.transform is not None:
                        batch = self.transform(batch)
                    if self.device_put:
                        batch = jax.tree.map(jax.device_put, batch)
                    if not put(batch):
                        return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                put(self._DONE)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._DONE:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # consumer stopped early (break/exception): unblock the worker
            # and wait until it is actually DEAD — callers (e.g. the
            # TokenDataset example) may tear down resources the worker
            # reads (an mmap) right after this returns, so a timed-out
            # join must not be swallowed
            stop.set()
            deadline = _time.monotonic() + 60.0
            while t.is_alive() and _time.monotonic() < deadline:
                while not q.empty():
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
                t.join(timeout=0.1)
            if t.is_alive():
                # a source blocked in next() can never observe `stop`;
                # warn loudly instead of hanging teardown forever — the
                # caller must keep resources the worker reads alive
                import warnings
                warnings.warn(
                    "PrefetchLoader worker did not stop within 60s (source "
                    "blocked?); resources it reads must outlive it",
                    RuntimeWarning, stacklevel=2)


class RequestFeeder:
    """Background request-ingest thread for `apex1_tpu.serving`: pulls
    raw prompts from ``source`` (an iterable of anything — text lines,
    token lists), tokenizes them OFF the engine's critical path, and
    pushes them through ``submit`` (the engine/scheduler entry point),
    absorbing `Backpressure` with the scheduler docstring's promised
    429/retry contract: BOUNDED EXPONENTIAL BACKOFF with deterministic
    jitter (``resilience.retry.backoff_delays`` — base ``retry_wait_s``,
    doubling, capped at ``retry_cap_s``, jittered so a burst of rejected
    feeders doesn't re-slam the queue in lockstep) and a
    drop-after-deadline rule: once an item has spent ``deadline_s``
    total in retries it is shed (``dropped``), because an overloaded
    engine must shed load, not stretch tail latency unboundedly.

    A structured rejection's ``retry_after_s`` is the server's backoff
    hint and is honored as a FLOOR on the next sleep: the exponential
    schedule may wait longer, never shorter — a thousand feeders
    retrying "soon" against a server that said "50 ms" is exactly the
    re-slam the hint exists to prevent. The floored delay still counts
    against ``deadline_s``.

    ``tokenize(item) -> (tokens, kwargs)`` where kwargs go straight to
    ``submit(tokens, **kwargs)`` (``max_new_tokens`` etc.). Rejections
    that outlive ``retries``/``deadline_s`` land in ``dropped`` with the
    reason. ``counters`` tracks the aggregate: ``submitted``,
    ``retries`` (backoff sleeps taken), ``dropped_backpressure``,
    ``dropped_error`` — the feed-side metrics record.

    The worker only SUBMITS; stepping the engine stays with the caller
    (the engine is not thread-safe by design — one loop owns the
    device). Typical shape::

        feeder = RequestFeeder(prompts, tokenize, engine.submit)
        feeder.start()
        while not feeder.idle or engine.n_active or engine.scheduler.depth:
            engine.step()
        feeder.join()
    """

    def __init__(self, source: Iterable, tokenize: Callable,
                 submit: Callable, *, retries: int = 100,
                 retry_wait_s: float = 0.005,
                 retry_cap_s: float = 0.25,
                 deadline_s: Optional[float] = None,
                 jitter: float = 0.5, seed: int = 0):
        self.source = source
        self.tokenize = tokenize
        self.submit = submit
        self.retries = int(retries)
        self.retry_wait_s = float(retry_wait_s)
        self.retry_cap_s = float(retry_cap_s)
        self.deadline_s = deadline_s
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.submitted: list = []
        self.dropped: list = []          # (item, reason)
        self.errors: list = []
        self.counters = {"submitted": 0, "retries": 0,
                         "dropped_backpressure": 0, "dropped_error": 0}
        self._thread: Optional[threading.Thread] = None
        self._done = threading.Event()

    @property
    def idle(self) -> bool:
        """True once the source is drained and every item dispatched."""
        return self._done.is_set()

    def start(self) -> "RequestFeeder":
        from apex1_tpu.resilience.retry import backoff_delays
        from apex1_tpu.serving.scheduler import (Backpressure,
                                                 new_request_id)

        def work():
            try:
                for n_item, item in enumerate(self.source):
                    # a PER-ITEM failure (tokenizer bug, contract
                    # ValueError from submit) drops THAT item and keeps
                    # feeding — one malformed request must not silently
                    # starve the rest of the stream (review finding)
                    try:
                        tokens, kw = self.tokenize(item)
                    except Exception as e:
                        self.dropped.append((item, f"tokenize: {e!r}"))
                        self.counters["dropped_error"] += 1
                        self.errors.append(e)
                        continue
                    # one id across every retry attempt: transient
                    # backpressure rejections then update ONE metrics
                    # record instead of minting a phantom rejected
                    # record per attempt (review finding)
                    kw.setdefault("req_id", new_request_id())
                    delays = backoff_delays(
                        self.retries, base_s=self.retry_wait_s,
                        cap_s=self.retry_cap_s, jitter=self.jitter,
                        seed=self.seed ^ n_item)
                    t0 = _time.monotonic()
                    while True:
                        try:
                            self.submitted.append(
                                self.submit(tokens, **kw))
                            self.counters["submitted"] += 1
                            break
                        except Backpressure as e:
                            d = next(delays, None)
                            if d is not None:
                                # server hint = the floor, not the value:
                                # back off MORE than asked, never less
                                floor = getattr(e, "retry_after_s", None)
                                if floor:
                                    d = max(d, float(floor))
                            waited = _time.monotonic() - t0
                            if d is None:
                                reason = f"{e.reason} (retries exhausted)"
                            elif (self.deadline_s is not None
                                  and waited + d > self.deadline_s):
                                reason = (f"{e.reason} (deadline "
                                          f"{self.deadline_s}s after "
                                          f"{waited:.3f}s)")
                            else:
                                self.counters["retries"] += 1
                                _time.sleep(d)
                                continue
                            self.dropped.append((item, reason))
                            self.counters["dropped_backpressure"] += 1
                            break
                        except Exception as e:
                            self.dropped.append((item, repr(e)))
                            self.counters["dropped_error"] += 1
                            self.errors.append(e)
                            break
            except BaseException as e:   # source iteration died —
                self.errors.append(e)    # surfaced via join()
            finally:
                self._done.set()

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
        if self.errors:
            raise self.errors[0]
