// apex1_tpu host runtime — native byte-moving for the data path.
//
// Reference capabilities covered (TPU-native redesign, not a port):
// - csrc/flatten_unflatten.cpp :: flatten/unflatten ("apex_C"): the
//   reference flattens gradient buckets for NCCL; on TPU gradient
//   bucketing is XLA's job, but HOST-side flattening is still the right
//   tool for the input pipeline — pack a batch of samples into ONE
//   contiguous staging buffer so each step issues a single host->device
//   transfer (the tunnel/PCIe hop amortizes much better than per-array
//   puts). Multi-threaded memcpy saturates host memory bandwidth.
// - examples/imagenet/main_amp.py :: data_prefetcher: the reference
//   normalizes uint8 NHWC images to fp32 on a CUDA side stream; here the
//   normalize (u8 -> f32, per-channel mean/std) runs in native threads on
//   the host, overlapped with device compute by the Python PrefetchLoader.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in image).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) across up to `threads` hardware threads.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (n <= 0) return;
  int tn = std::min<int64_t>(threads, n);
  if (tn <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(tn);
  for (int t = 0; t < tn; ++t) {
    pool.emplace_back([=] {
      for (int64_t i = t; i < n; i += tn) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Pack n_src source buffers (sizes in bytes) back-to-back into dst.
// Offsets are the exclusive prefix sum of sizes; dst must hold sum(sizes).
void apex1_flatten(const void** srcs, const int64_t* sizes, int64_t n_src,
                   void* dst, int threads) {
  std::vector<int64_t> offs(n_src);
  int64_t acc = 0;
  for (int64_t i = 0; i < n_src; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n_src, threads, [&](int64_t i) {
    std::memcpy(static_cast<char*>(dst) + offs[i], srcs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// Inverse: split src into n_dst buffers of the given sizes.
void apex1_unflatten(const void* src, const int64_t* sizes, int64_t n_dst,
                     void** dsts, int threads) {
  std::vector<int64_t> offs(n_dst);
  int64_t acc = 0;
  for (int64_t i = 0; i < n_dst; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n_dst, threads, [&](int64_t i) {
    std::memcpy(dsts[i], static_cast<const char*>(src) + offs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// uint8 NHWC image batch -> float32, (x/255 - mean[c]) / std[c].
// n = total elements; c = channel count (innermost dim).
void apex1_normalize_u8_f32(const uint8_t* src, float* dst, int64_t n,
                            const float* mean, const float* stddev,
                            int64_t c, int threads) {
  // precompute per-channel scale/bias: y = x * (1/(255*std)) - mean/std
  std::vector<float> scale(c), bias(c);
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stddev[ch]);
    bias[ch] = -mean[ch] / stddev[ch];
  }
  const int64_t kChunk = 1 << 16;
  int64_t n_chunks = (n + kChunk - 1) / kChunk;
  parallel_for(n_chunks, threads, [&](int64_t chunk) {
    int64_t lo = chunk * kChunk, hi = std::min(n, lo + kChunk);
    for (int64_t i = lo; i < hi; ++i) {
      int64_t ch = i % c;
      dst[i] = static_cast<float>(src[i]) * scale[ch] + bias[ch];
    }
  });
}

// bf16 (as uint16 bit patterns) <-> f32 host conversion for staging
// checkpoint/comm buffers without a device round-trip.
void apex1_f32_to_bf16(const float* src, uint16_t* dst, int64_t n,
                       int threads) {
  const int64_t kChunk = 1 << 16;
  int64_t n_chunks = (n + kChunk - 1) / kChunk;
  parallel_for(n_chunks, threads, [&](int64_t chunk) {
    int64_t lo = chunk * kChunk, hi = std::min(n, lo + kChunk);
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits;
      std::memcpy(&bits, &src[i], 4);
      if ((bits & 0x7FFFFFFFu) > 0x7F800000u) {
        // NaN: rounding could carry out of the mantissa (e.g. 0x7FFFFFFF
        // -> -0.0); keep a quiet NaN with the top payload bits instead
        dst[i] = static_cast<uint16_t>((bits >> 16) | 0x0040u);
        continue;
      }
      // round-to-nearest-even on the dropped 16 bits
      uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
      dst[i] = static_cast<uint16_t>((bits + rounding) >> 16);
    }
  });
}

void apex1_bf16_to_f32(const uint16_t* src, float* dst, int64_t n,
                       int threads) {
  const int64_t kChunk = 1 << 16;
  int64_t n_chunks = (n + kChunk - 1) / kChunk;
  parallel_for(n_chunks, threads, [&](int64_t chunk) {
    int64_t lo = chunk * kChunk, hi = std::min(n, lo + kChunk);
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
      std::memcpy(&dst[i], &bits, 4);
    }
  });
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Token-dataset loader: memory-mapped LM pretraining data.
//
// Reference capability: the examples' input pipelines (imagenet
// data_prefetcher lineage) generalized to the LM-pretrain configs this
// framework benches. TPU-native design choice: batches are addressed by
// STEP INDEX, not by iterator state — `next(step)` is a pure function of
// (file, seed, step), so checkpoint/resume needs only the step counter
// (matching the framework's functional checkpoint story) and any worker
// can prefetch any step. Shuffling is an exact per-epoch permutation via
// an LCG over the next power of two with cycle-walking (no index table,
// O(1) memory for arbitrarily large corpora).
// ---------------------------------------------------------------------------

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// splitmix64 — per-(seed, epoch) parameter derivation.
uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

struct TokenLoader {
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  int64_t n_tokens = 0;
  int dtype_size = 0;   // 2 (uint16) or 4 (int32/uint32)
  int64_t seq_len = 0;
  int64_t batch = 0;
  uint64_t seed = 0;
  int shuffle = 0;
  int64_t n_seqs = 0;   // sequences per epoch
  uint64_t pow2 = 1;    // next power of two >= n_seqs

  // exact permutation of [0, n_seqs) for one epoch: affine step over the
  // pow2 ring, walking past out-of-range points. a must be odd (unit mod
  // 2^k); a fixed small number of extra walks amortizes to O(1).
  int64_t perm(uint64_t epoch, uint64_t i) const {
    if (!shuffle) return static_cast<int64_t>(i);
    uint64_t a = mix64(seed ^ mix64(epoch)) | 1ull;
    uint64_t c = mix64(seed ^ mix64(epoch ^ 0xD1B54A32D192ED03ull));
    uint64_t m = pow2 - 1;
    uint64_t x = i;
    do {
      x = (a * x + c) & m;
    } while (x >= static_cast<uint64_t>(n_seqs));
    return static_cast<int64_t>(x);
  }
};

}  // namespace

extern "C" {

void* apex1_loader_open(const char* path, int dtype_size, int64_t seq_len,
                        int64_t batch, uint64_t seed, int shuffle) {
  if ((dtype_size != 2 && dtype_size != 4) || seq_len <= 0 || batch <= 0)
    return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < dtype_size * seq_len) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  ::close(fd);  // mapping keeps the file alive
  if (map == MAP_FAILED) return nullptr;
  auto* L = new TokenLoader();
  L->map = static_cast<const uint8_t*>(map);
  L->map_len = st.st_size;
  L->n_tokens = st.st_size / dtype_size;
  L->dtype_size = dtype_size;
  L->seq_len = seq_len;
  L->batch = batch;
  L->seed = seed;
  L->shuffle = shuffle;
  L->n_seqs = L->n_tokens / seq_len;
  while (static_cast<int64_t>(L->pow2) < L->n_seqs) L->pow2 <<= 1;
  return L;
}

int64_t apex1_loader_num_sequences(void* h) {
  return h ? static_cast<TokenLoader*>(h)->n_seqs : -1;
}

// Fill out (batch, seq_len) int32 with the tokens of global step `step`.
// Row r reads epoch-permuted sequence ((step*batch + r) % n_seqs) of epoch
// ((step*batch + r) / n_seqs). Returns 0 on success.
int apex1_loader_next(void* h, int64_t step, int32_t* out, int threads) {
  if (!h || step < 0) return 1;
  auto* L = static_cast<TokenLoader*>(h);
  parallel_for(L->batch, threads, [&](int64_t r) {
    uint64_t g = static_cast<uint64_t>(step) * L->batch + r;
    uint64_t epoch = g / L->n_seqs;
    int64_t s = L->perm(epoch, g % L->n_seqs);
    const uint8_t* src = L->map + s * L->seq_len * L->dtype_size;
    int32_t* dst = out + r * L->seq_len;
    if (L->dtype_size == 2) {
      auto* p = reinterpret_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < L->seq_len; ++i) dst[i] = p[i];
    } else {
      std::memcpy(dst, src, L->seq_len * 4);
    }
  });
  return 0;
}

// Fetch ONE sequence by raw index (no permutation) — the building block
// for multi-shard datasets whose global shuffle lives above the shards.
int apex1_loader_fetch(void* h, int64_t seq_index, int32_t* out) {
  if (!h) return 1;
  auto* L = static_cast<TokenLoader*>(h);
  if (seq_index < 0 || seq_index >= L->n_seqs) return 2;
  const uint8_t* src = L->map + seq_index * L->seq_len * L->dtype_size;
  if (L->dtype_size == 2) {
    auto* p = reinterpret_cast<const uint16_t*>(src);
    for (int64_t i = 0; i < L->seq_len; ++i) out[i] = p[i];
  } else {
    std::memcpy(out, src, L->seq_len * 4);
  }
  return 0;
}

void apex1_loader_close(void* h) {
  if (!h) return;
  auto* L = static_cast<TokenLoader*>(h);
  munmap(const_cast<uint8_t*>(L->map), L->map_len);
  delete L;
}

// Packed-batch PLAN (the policy half of runtime.pack_documents): greedy
// first-fit of doc chunks over a bounded window of open rows — must
// match the Python fallback's semantics exactly (same MAX_OPEN window,
// same age eviction). Outputs one record per chunk into caller-allocated
// arrays sized n_chunks = sum(ceil(len/seq_len)); returns the row count.
int64_t apex1_pack_plan(const int64_t* doc_lens, const int64_t* doc_starts,
                        int64_t n_docs, int64_t seq_len,
                        int restart_positions, int64_t* starts,
                        int64_t* lens, int64_t* row, int64_t* col,
                        int32_t* seg, int32_t* pos0) {
  constexpr int64_t kMaxOpen = 256;
  std::vector<int64_t> space, fill;
  std::vector<int32_t> nseg;
  std::vector<int64_t> open;  // age-ordered open-row window
  int64_t ci = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    for (int64_t lo = 0; lo < doc_lens[d]; lo += seq_len) {
      int64_t ln = std::min(seq_len, doc_lens[d] - lo);
      int64_t r = -1;
      for (size_t k = 0; k < open.size(); ++k) {
        if (space[open[k]] >= ln) { r = open[k]; break; }
      }
      if (r < 0) {
        r = static_cast<int64_t>(space.size());
        space.push_back(seq_len);
        fill.push_back(0);
        nseg.push_back(0);
        if (ln < seq_len) {  // full rows never enter the window
          open.push_back(r);
          if (static_cast<int64_t>(open.size()) > kMaxOpen)
            open.erase(open.begin());  // evict by age, stays bounded
        }
      }
      starts[ci] = doc_starts[d] + lo;
      lens[ci] = ln;
      row[ci] = r;
      col[ci] = fill[r];
      seg[ci] = nseg[r];
      pos0[ci] = restart_positions ? 0 : static_cast<int32_t>(lo);
      space[r] -= ln;
      fill[r] += ln;
      nseg[r] += 1;
      if (space[r] == 0) {
        for (size_t k = 0; k < open.size(); ++k) {
          if (open[k] == r) { open.erase(open.begin() + k); break; }
        }
      }
      ++ci;
    }
  }
  return static_cast<int64_t>(space.size());
}

// Packed-batch fill (the byte-moving half of runtime.pack_documents —
// placement comes from apex1_pack_plan or the Python fallback):
// chunk i is flat_tokens[starts[i] : starts[i]+lens[i]], destined for
// (row[i], col[i]) with segment id seg[i] and first position pos0[i].
// tokens/segments/positions are (n_rows, seq_len) int32; this fills the
// pad/-1/0 background by row, then scatters all chunks — both passes
// threaded.
void apex1_pack_fill(const int32_t* flat_tokens, const int64_t* starts,
                     const int64_t* lens, const int64_t* row,
                     const int64_t* col, const int32_t* seg,
                     const int32_t* pos0, int64_t n_chunks,
                     int32_t* tokens, int32_t* segments,
                     int32_t* positions, int64_t n_rows,
                     int64_t seq_len, int32_t pad_id, int threads) {
  parallel_for(n_rows, threads, [&](int64_t r) {
    int32_t* t = tokens + r * seq_len;
    int32_t* s = segments + r * seq_len;
    int32_t* p = positions + r * seq_len;
    for (int64_t i = 0; i < seq_len; ++i) t[i] = pad_id;
    for (int64_t i = 0; i < seq_len; ++i) s[i] = -1;
    std::memset(p, 0, seq_len * 4);
  });
  parallel_for(n_chunks, threads, [&](int64_t i) {
    int64_t off = row[i] * seq_len + col[i];
    std::memcpy(tokens + off, flat_tokens + starts[i], lens[i] * 4);
    int32_t* s = segments + off;
    int32_t* p = positions + off;
    for (int64_t j = 0; j < lens[i]; ++j) s[j] = seg[i];
    for (int64_t j = 0; j < lens[i]; ++j) p[j] = pos0[i] + j;
  });
}

int apex1_runtime_abi_version() { return 4; }

}  // extern "C"
