// apex1_tpu host runtime — native byte-moving for the data path.
//
// Reference capabilities covered (TPU-native redesign, not a port):
// - csrc/flatten_unflatten.cpp :: flatten/unflatten ("apex_C"): the
//   reference flattens gradient buckets for NCCL; on TPU gradient
//   bucketing is XLA's job, but HOST-side flattening is still the right
//   tool for the input pipeline — pack a batch of samples into ONE
//   contiguous staging buffer so each step issues a single host->device
//   transfer (the tunnel/PCIe hop amortizes much better than per-array
//   puts). Multi-threaded memcpy saturates host memory bandwidth.
// - examples/imagenet/main_amp.py :: data_prefetcher: the reference
//   normalizes uint8 NHWC images to fp32 on a CUDA side stream; here the
//   normalize (u8 -> f32, per-channel mean/std) runs in native threads on
//   the host, overlapped with device compute by the Python PrefetchLoader.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in image).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) across up to `threads` hardware threads.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (n <= 0) return;
  int tn = std::min<int64_t>(threads, n);
  if (tn <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(tn);
  for (int t = 0; t < tn; ++t) {
    pool.emplace_back([=] {
      for (int64_t i = t; i < n; i += tn) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Pack n_src source buffers (sizes in bytes) back-to-back into dst.
// Offsets are the exclusive prefix sum of sizes; dst must hold sum(sizes).
void apex1_flatten(const void** srcs, const int64_t* sizes, int64_t n_src,
                   void* dst, int threads) {
  std::vector<int64_t> offs(n_src);
  int64_t acc = 0;
  for (int64_t i = 0; i < n_src; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n_src, threads, [&](int64_t i) {
    std::memcpy(static_cast<char*>(dst) + offs[i], srcs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// Inverse: split src into n_dst buffers of the given sizes.
void apex1_unflatten(const void* src, const int64_t* sizes, int64_t n_dst,
                     void** dsts, int threads) {
  std::vector<int64_t> offs(n_dst);
  int64_t acc = 0;
  for (int64_t i = 0; i < n_dst; ++i) { offs[i] = acc; acc += sizes[i]; }
  parallel_for(n_dst, threads, [&](int64_t i) {
    std::memcpy(dsts[i], static_cast<const char*>(src) + offs[i],
                static_cast<size_t>(sizes[i]));
  });
}

// uint8 NHWC image batch -> float32, (x/255 - mean[c]) / std[c].
// n = total elements; c = channel count (innermost dim).
void apex1_normalize_u8_f32(const uint8_t* src, float* dst, int64_t n,
                            const float* mean, const float* stddev,
                            int64_t c, int threads) {
  // precompute per-channel scale/bias: y = x * (1/(255*std)) - mean/std
  std::vector<float> scale(c), bias(c);
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * stddev[ch]);
    bias[ch] = -mean[ch] / stddev[ch];
  }
  const int64_t kChunk = 1 << 16;
  int64_t n_chunks = (n + kChunk - 1) / kChunk;
  parallel_for(n_chunks, threads, [&](int64_t chunk) {
    int64_t lo = chunk * kChunk, hi = std::min(n, lo + kChunk);
    for (int64_t i = lo; i < hi; ++i) {
      int64_t ch = i % c;
      dst[i] = static_cast<float>(src[i]) * scale[ch] + bias[ch];
    }
  });
}

// bf16 (as uint16 bit patterns) <-> f32 host conversion for staging
// checkpoint/comm buffers without a device round-trip.
void apex1_f32_to_bf16(const float* src, uint16_t* dst, int64_t n,
                       int threads) {
  const int64_t kChunk = 1 << 16;
  int64_t n_chunks = (n + kChunk - 1) / kChunk;
  parallel_for(n_chunks, threads, [&](int64_t chunk) {
    int64_t lo = chunk * kChunk, hi = std::min(n, lo + kChunk);
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits;
      std::memcpy(&bits, &src[i], 4);
      if ((bits & 0x7FFFFFFFu) > 0x7F800000u) {
        // NaN: rounding could carry out of the mantissa (e.g. 0x7FFFFFFF
        // -> -0.0); keep a quiet NaN with the top payload bits instead
        dst[i] = static_cast<uint16_t>((bits >> 16) | 0x0040u);
        continue;
      }
      // round-to-nearest-even on the dropped 16 bits
      uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
      dst[i] = static_cast<uint16_t>((bits + rounding) >> 16);
    }
  });
}

void apex1_bf16_to_f32(const uint16_t* src, float* dst, int64_t n,
                       int threads) {
  const int64_t kChunk = 1 << 16;
  int64_t n_chunks = (n + kChunk - 1) / kChunk;
  parallel_for(n_chunks, threads, [&](int64_t chunk) {
    int64_t lo = chunk * kChunk, hi = std::min(n, lo + kChunk);
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t bits = static_cast<uint32_t>(src[i]) << 16;
      std::memcpy(&dst[i], &bits, 4);
    }
  });
}

int apex1_runtime_abi_version() { return 1; }

}  // extern "C"
