"""Per-op and per-model TPU profiling harness.

Usage (on a machine with a live TPU):
    python tools/profile_ops.py [ops|gpt2|llama|all]

Prints ms per fwd / fwd+bwd for each Pallas kernel vs its XLA composite,
and model-level step breakdowns. Sync discipline: the axon tunnel backend
defines buffers before the program finishes, so every measurement fetches
one fused scalar reduction over all outputs (see bench.py).
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _reduce_all(tree):
    return sum(jnp.sum(x.astype(jnp.float32)) for x in jax.tree.leaves(tree))


def sync(tree):
    float(_reduce_all(tree))


def bench(name, fn, *args, n=20):
    sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn(*args)
    sync(r)
    ms = (time.perf_counter() - t0) / n * 1e3
    print(f"{name:55s} {ms:8.2f} ms", flush=True)
    return ms


def profile_ops():
    from apex1_tpu.ops import (layer_norm, set_impl,
                               scaled_upper_triang_masked_softmax,
                               softmax_cross_entropy_loss)
    from apex1_tpu.ops.attention import flash_attention

    rng = np.random.default_rng(0)
    B, S, H, D, hid, V = 8, 1024, 12, 64, 768, 50304

    x3 = jnp.asarray(rng.normal(size=(B, S, hid)), jnp.bfloat16)
    gamma = jnp.ones((hid,), jnp.float32)
    beta = jnp.zeros((hid,), jnp.float32)
    for impl in ("auto", "xla"):
        set_impl(impl)
        f = jax.jit(jax.grad(lambda x: jnp.sum(
            layer_norm(x, gamma, beta).astype(jnp.float32))))
        bench(f"layernorm f+b (B{B} S{S} H{hid}) [{impl}]", f, x3)
    set_impl("auto")

    scores = jnp.asarray(rng.normal(size=(B, H, S, S)), jnp.float32)
    for impl in ("auto", "xla"):
        set_impl(impl)
        f = jax.jit(jax.grad(lambda s: jnp.sum(
            scaled_upper_triang_masked_softmax(s, scale=0.125))))
        bench(f"causal softmax f+b (B{B} H{H} S{S}) [{impl}]", f, scores)
    set_impl("auto")

    logits = jnp.asarray(rng.normal(size=(B * S, V)), jnp.float32)
    lbl = jnp.asarray(rng.integers(0, 50257, (B * S,)), jnp.int32)
    for impl in ("auto", "xla"):
        set_impl(impl)
        f = jax.jit(jax.grad(lambda l: jnp.sum(softmax_cross_entropy_loss(
            l, lbl, num_classes=50257))))
        bench(f"xentropy f+b ({B*S}x{V}) [{impl}]", f, logits)
    set_impl("auto")

    q = jnp.asarray(rng.normal(size=(B, H, S, 128)), jnp.bfloat16)
    f = jax.jit(jax.grad(lambda q: jnp.sum(
        flash_attention(q, q, q, causal=True).astype(jnp.float32))))
    bench(f"flash attn f+b (B{B} H{H} S{S} D128)", f, q)

    # fused LM-head+CE vs materialized logits+CE at GPT-2 head scale
    from apex1_tpu.ops import linear_cross_entropy
    h2 = jnp.asarray(rng.normal(size=(B * S, hid)) * 0.3, jnp.bfloat16)
    w2 = jnp.asarray(rng.normal(size=(V, hid)) * 0.3, jnp.bfloat16)
    f = jax.jit(jax.grad(lambda h, w: jnp.sum(linear_cross_entropy(
        h, w, lbl, num_classes=50257)), argnums=(0, 1)))
    bench(f"fused linear+CE f+b ({B*S}x{hid}x{V})", f, h2, w2)

    def unfused(h, w):
        logits = jnp.einsum("th,vh->tv", h, w,
                            preferred_element_type=jnp.float32)
        return jnp.sum(softmax_cross_entropy_loss(logits, lbl,
                                                  num_classes=50257))
    f = jax.jit(jax.grad(unfused, argnums=(0, 1)))
    bench(f"matmul+xentropy f+b ({B*S}x{hid}x{V})", f, h2, w2)


def profile_gpt2():
    from apex1_tpu.amp import Amp
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
    from apex1_tpu.optim.fused_adam import fused_adam

    for use_flash in (True, False):
        cfg = GPT2Config(policy=get_policy("O2"), use_flash=use_flash)
        model = GPT2(cfg)
        tokens = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, 1024)), jnp.int32)
        params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]
        amp = Amp(tx=fused_adam(1e-4), opt_level="O2")
        state = amp.init(params)
        step = jax.jit(amp.make_train_step(gpt2_loss_fn(model)))
        ms = bench(f"gpt2-125M O2 step (flash={use_flash})", step, state,
                   tokens, n=10)
        toks = 8 * 1024 / (ms / 1e3)
        print(f"    -> {toks:,.0f} tokens/sec/chip")
        del state, params


def profile_llama():
    from apex1_tpu.amp import Amp
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import Llama, LlamaConfig, llama_loss_fn
    from apex1_tpu.optim.fused_adam import fused_adam

    # single-chip-sized llama (8B needs the pod); long-seq to exercise
    # flash + remat
    cfg = LlamaConfig(vocab_size=32128, max_seq_len=4096, num_layers=8,
                      num_heads=16, num_kv_heads=8, hidden_size=1024,
                      ffn_size=2816, remat=True,
                      policy=get_policy("O2"))
    model = Llama(cfg)
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 4096)), jnp.int32)
    params = jax.jit(model.init)(jax.random.key(0), tokens)["params"]
    amp = Amp(tx=fused_adam(1e-4), opt_level="O2")
    state = amp.init(params)
    step = jax.jit(amp.make_train_step(llama_loss_fn(model)))
    ms = bench("llama-0.2B long-ctx O2 remat step (S=4096)", step, state,
               tokens, n=5)
    print(f"    -> {1 * 4096 / (ms / 1e3):,.0f} tokens/sec/chip")


if __name__ == "__main__":
    what = sys.argv[1] if len(sys.argv) > 1 else "all"
    print("backend:", jax.default_backend(), flush=True)
    if what in ("ops", "all"):
        profile_ops()
    if what in ("gpt2", "all"):
        profile_gpt2()
    if what in ("llama", "all"):
        profile_llama()
