"""Tunnel-free evidence for the `lax.cond` branch-elision question
(VERDICT r3 Missing #5): compile the bubble-skip shape of conditional for
the REAL TPU target through the AOT topology client and inspect the
optimized HLO — does the `conditional` survive to the executable (TPU
executes only the taken branch), or does the compiler flatten it into
`select` (both branches execute and the "skip" saves nothing)?

This is the static half of the answer; `tools/cond_elision_probe.py`
(queued on hardware revival) is the timing half. The two shapes checked
mirror the production sites:

- pipeline bubble-skip: cond around a transformer-stage-sized body
  (`schedules.pipeline_apply` / `one_f_one_b`);
- ring causal-skip: cond around one flash-attention block step
  (`parallel/ring_attention`).

Run: python tools/cond_elision_aot.py [--topology v5e:2x2]
Writes a PRESERVED/FLATTENED verdict per shape plus op-count detail.
"""

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x2")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import SingleDeviceSharding

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    s1 = SingleDeviceSharding(topo.devices[0])

    def verdict(name, fn, *shapes, dtypes=jnp.bfloat16):
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes] * len(shapes)
        arrs = [jax.ShapeDtypeStruct(s, d, sharding=s1)
                for s, d in zip(shapes, dtypes)]
        txt = jax.jit(fn).lower(*arrs).compile().as_text()
        n_cond = len(re.findall(r"conditional", txt))
        n_fusion = len(re.findall(r"\bfusion\b", txt))
        n_select = len(re.findall(r"\bselect\(", txt))
        kept = n_cond > 0
        print(f"{'PRESERVED' if kept else 'FLATTENED'} {name}: "
              f"conditional x{n_cond}, fusion x{n_fusion}, "
              f"select x{n_select}", flush=True)
        return kept

    D = 512

    # 1. pipeline bubble-skip shape: cond around a stage-sized body
    def stage(w, x):
        h = jnp.tanh(x @ w)
        return x + h @ w.T

    def bubble(pred_in, w, x):
        pred = jnp.sum(pred_in) > 0  # traced predicate, like `valid`
        def run(ops):
            return stage(*ops)
        return jax.lax.cond(pred, run, lambda ops: ops[1], (w, x))

    k1 = verdict("pipeline bubble-skip (stage-sized branches)", bubble,
                 (1,), (D, D), (8, D),
                 dtypes=[jnp.float32, jnp.bfloat16, jnp.bfloat16])

    # 2. ring causal-skip shape: cond around one attention block step
    def attn_block(q, k, v):
        s = jnp.einsum("sd,td->st", q, k,
                       preferred_element_type=jnp.float32)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return p @ v

    def ring_tick(pred_in, q, k, v):
        pred = jnp.sum(pred_in) > 0
        return jax.lax.cond(pred,
                            lambda ops: attn_block(*ops),
                            lambda ops: jnp.zeros_like(ops[0]),
                            (q, k, v))

    k2 = verdict("ring causal-skip (one flash block step)", ring_tick,
                 (1,), (512, 64), (512, 64), (512, 64),
                 dtypes=[jnp.float32] + [jnp.bfloat16] * 3)

    # 3. adversarial tiny-branch case: is flattening even in play?
    def tiny(pred_in, x):
        pred = jnp.sum(pred_in) > 0
        return jax.lax.cond(pred, lambda x: x * 2.0, lambda x: x + 1.0, x)

    verdict("tiny elementwise branches (flatten candidate)", tiny,
            (1,), (8, 128), dtypes=[jnp.float32, jnp.float32])

    print(f"summary: production shapes "
          f"{'PRESERVED' if (k1 and k2) else 'AT RISK'} on "
          f"{args.topology}", flush=True)
    return 0 if (k1 and k2) else 1


if __name__ == "__main__":
    sys.exit(main())
