#!/bin/bash
# Watch for the TPU tunnel to come back; the moment it does, run the
# measurement queue (kernel A/B sweeps + every bench config) and leave
# the logs in /tmp/tpu_results/. Safe to re-run; one instance at a time.
RES=/tmp/tpu_results
mkdir -p "$RES"
exec 9>"$RES/.lock"
flock -n 9 || { echo "tpu_watch already running"; exit 0; }
cd /root/repo

probe() {
  # a blocked init holds /tmp/libtpu_lockfile, which starves the AOT
  # compile-only client — honor the pause flag and keep probes short
  [ -e "$RES/pause" ] && return 1
  timeout 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
print(float(jnp.sum((x @ x).astype(jnp.float32))))" >/dev/null 2>&1
}

echo "watch start $(date -u +%H:%M:%S)" >> "$RES/status.log"
until probe; do
  echo "down $(date -u +%H:%M:%S)" >> "$RES/status.log"
  sleep 120
done
echo "TPU BACK $(date -u +%H:%M:%S)" >> "$RES/status.log"

run() { # name timeout cmd...
  local name=$1 to=$2; shift 2
  stdbuf -oL -eL timeout "$to" "$@" > "$RES/$name.log" 2>&1
  echo "$name rc=$? $(date -u +%H:%M:%S)" >> "$RES/status.log"
}

# Headline numbers first (most valuable if the tunnel dies again),
# then per-kernel A/B sweeps for the perf playbook.
run bench_gpt2      1800 python bench.py --config gpt2
run bench_bert_lg   1800 python bench.py --config bert_large
run bench_llama16k  2400 python bench.py --config llama_longctx
run bench_bert      1500 python bench.py --config bert
run bench_resnet    1500 python bench.py --config resnet
run kern_attn       2400 python tools/bench_kernels.py attn
run kern_xent       2400 python tools/bench_kernels.py xent
run kern_norm       1200 python tools/bench_kernels.py norm
echo "queue done $(date -u +%H:%M:%S)" >> "$RES/status.log"
