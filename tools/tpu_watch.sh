#!/bin/bash
# Watch for the TPU tunnel to come back; the moment it does, run the
# measurement queue (kernel A/B sweeps + every bench config) and leave
# the logs in /tmp/tpu_results/. Safe to re-run; one instance at a time.
RES=/tmp/tpu_results
mkdir -p "$RES"
exec 9>"$RES/.lock"
flock -n 9 || { echo "tpu_watch already running"; exit 0; }
cd /root/repo

probe() {
  # a blocked init holds /tmp/libtpu_lockfile, which starves the AOT
  # compile-only client — honor the pause flag and keep probes short.
  # -k 15: a probe stuck in uninterruptible axon init shrugs off the
  # SIGTERM `timeout` sends, and `timeout` then waits forever — the
  # watcher looked alive but never polled again (observed 06:03→06:12
  # gap). SIGKILL after the grace period actually ends it.
  [ -e "$RES/pause" ] && return 1
  # 9>&- : children must NOT inherit the flock fd — an orphaned probe
  # (or its sleep) would hold the single-instance lock after the
  # watcher dies and block every restart
  timeout -k 15 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
print(float(jnp.sum((x @ x).astype(jnp.float32))))" >/dev/null 2>&1 9>&-
}

echo "watch start $(date -u +%H:%M:%S)" >> "$RES/status.log"

# Results ALSO land in the repo so they survive the session for the
# next round's context (committed by the next session, not by this
# script).
REPO_RES=/root/repo/perf_results
mkdir -p "$REPO_RES"

run() { # name timeout cmd...
  local name=$1 to=$2; shift 2
  # the whole pipeline runs with fd 9 closed (see probe) — tee must not
  # inherit the lock either, or a surviving benchmark child blocks
  # watcher restarts for its full timeout
  local rc
  { stdbuf -oL -eL timeout -k 30 "$to" "$@" 2>&1 | tee "$RES/$name.log" \
    > "$REPO_RES/$name.log"; rc=${PIPESTATUS[0]}; } 9>&-
  echo "$name rc=$rc $(date -u +%H:%M:%S)" >> "$RES/status.log"
}

# The flagship AOT re-check is TUNNEL-FREE (compile-only topology
# client) — run it before the revival wait so its memory table is
# fresh even while the tunnel is dead (5 x ~5-min 8B compiles).
run aot_flagship    3600 python tools/aot_check.py --flagship

until probe; do
  echo "down $(date -u +%H:%M:%S)" >> "$RES/status.log"
  sleep 120 9>&-
done
echo "TPU BACK $(date -u +%H:%M:%S)" >> "$RES/status.log"

# Queue order per VERDICT r2 item 1: (a) on-device kernel NUMERICS parity
# (2-min sweep — Mosaic numerics, not just lowering), (b) headline bench +
# MFU, (c) remaining configs, (d) per-op profile + kernel A/B sweeps
# (includes the fused_dense roofline and flat-vs-per-tensor optimizer A/B,
# the open "measure-first" debts).
run hw_numerics     1200 python tools/hw_numerics.py
run bench_gpt2      1800 python bench.py --config gpt2
run bench_llama_blk 2400 python bench.py --config llama_block
run bench_bert_lg   1800 python bench.py --config bert_large
run bench_llama16k  2400 python bench.py --config llama_longctx
run bench_bert      1500 python bench.py --config bert
run bench_resnet    1500 python bench.py --config resnet
run bench_t5        1800 python bench.py --config t5
run bench_gpt2_b24  1500 python bench.py --config gpt2 --batch 24
run profile_gpt2    1500 python tools/profile_step.py --config gpt2 --top 40
run cond_elision    900  python tools/cond_elision_probe.py
run kern_all        4800 python tools/bench_kernels.py all
run kern_all_llama  4800 python tools/bench_kernels.py all --llama
echo "queue done $(date -u +%H:%M:%S)" >> "$RES/status.log"
