#!/bin/bash
# Watch for the TPU tunnel to come back; the moment it does, run the
# measurement queue and leave the logs in /tmp/tpu_results/ (mirrored to
# perf_results/). Safe to re-run; one instance at a time.
#
# QUEUE ORDER (VERDICT r3 item 1): the round-3 window lived only minutes,
# so the FIRST entry must produce the headline timing number. Numerics are
# banked (12/12 on real silicon, perf_results/hw_numerics_r3.log) — only
# the post-window flash-bias check (#13) runs early (one ~60s compile);
# the full numerics re-sweep runs LAST.
#
#   1. bench_gpt2        headline tokens/sec/chip + MFU      (~5 min)
#   2. hw_numerics bias  the single unbanked kernel check    (~2 min)
#   3. llama_block / bert_large                              (~10 min)
#   4. tune_kernels --kernel attention: the in-process flash
#      block sweep — the 0.36x-roofline localizer for
#      llama_longctx (VERDICT r5) — runs BEFORE its re-bench
#      so the re-bench rides any folded-in winner             (~10 min)
#   4b. ring_overlap_ab: serialized vs double-buffered ring at
#      the 16k llama_longctx shape (needs >= 2 devices; emits a
#      skip record on a single-chip window), also BEFORE the
#      llama_longctx re-bench                                 (~10 min)
#   4c. fused_comm_ab: fused vs decomposed vs serialized comm
#      kernels (SP boundary MLP + fused-merge ring attention +
#      the RDMA reduce-scatter's first execution/parity datum),
#      also BEFORE the llama_longctx re-bench                 (~10 min)
#   5. llama_longctx re-bench; bert_dropout (PR5 fused in-kernel
#      dropout — the headline BERT-pretrain config) AHEAD of the
#      plain bert re-bench; remaining configs                (~25 min)
#   6. per-op profile + cond-elision probe + the NEW
#      bench_cond_elision production-site A/B                (~15 min)
#   7. kernel A/B sweeps + remaining tune_kernels sweeps     (~2x40 min)
#   7b. gpt2 O1-fp16 dynamic-loss-scaling bench (VERDICT
#      Weak #8) BEHIND the sweeps                            (~10 min)
#   8. full hw_numerics re-sweep                             (~20 min)
#
# calibrate_refresh entries run AFTER each bench group (and last):
# python -m apex1_tpu.obs.calibrate re-fits the predicted-vs-measured
# correction factors from whatever the window banked so far, and
# trace_reports turns every stamped profile_artifact into a per-op
# trace_report.json (docs/observability.md — the measurement flywheel).
#
# Every phase tees its log to perf_results/ AS IT RUNS (stdbuf line
# buffered), so a tunnel that dies mid-phase still leaves the lines that
# printed — no phase buffers results to the end.
#
# REHEARSAL (VERDICT r3 item 1 "rehearse the whole queue end-to-end on
# CPU"): `tools/tpu_watch.sh --rehearse` runs every queue entry with
# JAX_PLATFORMS=cpu and tiny shapes (bench.py configs auto-shrink on cpu;
# bench_kernels takes --tiny; hw_numerics takes --allow-cpu). This
# validates the exact command lines + script plumbing so a script bug
# cannot eat a real hardware window. Output: perf_results/rehearsal_r4.log
RES=/tmp/tpu_results
MODE=real
[ "${1:-}" = "--rehearse" ] && { MODE=rehearse; RES=/tmp/tpu_rehearse; }
mkdir -p "$RES"
exec 9>"$RES/.lock"
flock -n 9 || { echo "tpu_watch already running"; exit 0; }
cd /root/repo

probe() {
  # a blocked init holds /tmp/libtpu_lockfile, which starves the AOT
  # compile-only client — honor the pause flag and keep probes short.
  # -k 15: a probe stuck in uninterruptible axon init shrugs off the
  # SIGTERM `timeout` sends, and `timeout` then waits forever — the
  # watcher looked alive but never polled again (observed 06:03→06:12
  # gap). SIGKILL after the grace period actually ends it.
  [ -e "$RES/pause" ] && return 1
  # 9>&- : children must NOT inherit the flock fd — an orphaned probe
  # (or its sleep) would hold the single-instance lock after the
  # watcher dies and block every restart
  timeout -k 15 150 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
print(float(jnp.sum((x @ x).astype(jnp.float32))))" >/dev/null 2>&1 9>&-
}

echo "watch start mode=$MODE $(date -u +%H:%M:%S)" >> "$RES/status.log"

# Results ALSO land in the repo so they survive the session for the
# next round's context (committed by the next session, not by this
# script).
REPO_RES=/root/repo/perf_results
mkdir -p "$REPO_RES"

if [ "$MODE" = rehearse ]; then
  export JAX_PLATFORMS=cpu
  REHLOG="$REPO_RES/rehearsal_r4.log"
  : > "$REHLOG"
fi

run() { # name timeout cmd...
  local name=$1 to=$2; shift 2
  # the whole pipeline runs with fd 9 closed (see probe) — tee must not
  # inherit the lock either, or a surviving benchmark child blocks
  # watcher restarts for its full timeout
  local rc
  if [ "$MODE" = rehearse ]; then
    # rehearsal: shorter cap (tiny shapes), one combined log, loud rc
    { stdbuf -oL -eL timeout -k 30 600 "$@" 2>&1 \
      | tee -a "$REHLOG" > "$RES/$name.log"; rc=${PIPESTATUS[0]}; } 9>&-
    echo "REHEARSE $name rc=$rc" | tee -a "$REHLOG" >> "$RES/status.log"
    [ "$rc" -ne 0 ] && REH_FAIL=1
    return 0
  fi
  # Up to 3 attempts per entry. Two recoverable outcomes re-run the
  # entry IN PLACE (re-queue at head) instead of losing the round:
  #   rc=75  EXIT_RESUMABLE (apex1_tpu/resilience/preemption.py): the
  #          run was preempted mid-window but banked a checkpoint; the
  #          relaunch resumes via --resume auto / find_restorable.
  #   [unreachable] in the log: the tunnel died BETWEEN entries (bench
  #          emitted its fallback record) — wait for the probe to see
  #          the TPU again, then retry with backoff, rather than
  #          recording a zero for a config the window could still bank.
  # Each attempt streams (live-tailable) into its own attempt log, then
  # lands appended in the cumulative logs; the recoverable-outcome
  # checks read ONLY the last attempt — a stale [unreachable] line from
  # attempt 1 must not keep re-running an entry that already recovered.
  local attempt att="$RES/$name.attempt.log"
  for attempt in 1 2 3; do
    { stdbuf -oL -eL timeout -k 30 "$to" "$@" 2>&1 \
      | tee "$att" > /dev/null; rc=${PIPESTATUS[0]}; } 9>&-
    if [ "$attempt" -eq 1 ]; then
      cp "$att" "$RES/$name.log"; cp "$att" "$REPO_RES/$name.log"
    else
      cat "$att" >> "$RES/$name.log"; cat "$att" >> "$REPO_RES/$name.log"
    fi
    echo "$name rc=$rc attempt=$attempt $(date -u +%H:%M:%S)" \
      >> "$RES/status.log"
    # no recovery work after the final attempt: sleeping or waiting on
    # the probe with no retry left only burns the window
    [ "$attempt" -ge 3 ] && break
    if [ "$rc" -eq 75 ]; then
      echo "$name resumable (rc=75): retrying at head" >> "$RES/status.log"
      sleep $((30 * attempt)) 9>&-
      continue
    fi
    if grep -q '\[unreachable\]' "$att" 2>/dev/null; then
      echo "$name backend unreachable: waiting for probe" >> "$RES/status.log"
      until probe; do
        echo "down $(date -u +%H:%M:%S)" >> "$RES/status.log"
        sleep 120 9>&-
      done
      continue
    fi
    break
  done
  rm -f "$att"
}

REH_FAIL=0

if [ "$MODE" = real ]; then
  # The flagship AOT re-check is TUNNEL-FREE (compile-only topology
  # client) — run it before the revival wait so its memory table is
  # fresh even while the tunnel is dead (5 x ~5-min 8B compiles).
  run aot_flagship    3600 python tools/aot_check.py --flagship

  until probe; do
    echo "down $(date -u +%H:%M:%S)" >> "$RES/status.log"
    sleep 120 9>&-
  done
  echo "TPU BACK $(date -u +%H:%M:%S)" >> "$RES/status.log"
fi

# --- the measurement queue (identical command lines in both modes, ---
# --- modulo the cpu/tiny flags appended in rehearsal)              ---
if [ "$MODE" = rehearse ]; then
  CPUQ=(--allow-cpu)
  TINY=(--tiny)
else
  CPUQ=()
  TINY=()
fi

run bench_gpt2      1200 python bench.py --config gpt2 --timeout 1000
run hw_num_new       600 python tools/hw_numerics.py --only bias,int8 \
                         --timeout 480 "${CPUQ[@]}"
run bench_llama_blk 1800 python bench.py --config llama_block --timeout 1500
run bench_bert_lg   1500 python bench.py --config bert_large --timeout 1200
# calibrate_refresh AFTER each bench group (ROADMAP-5 flywheel): re-fit
# the predicted-vs-measured correction factors the moment new silicon
# records bank, so later entries' calibrated_ratio prices THIS window's
# history, not last round's
run calibrate_refresh1 300 python -m apex1_tpu.obs.calibrate
# the flash block sweep (in-process, winners persisted to
# perf_results/tuning/) runs AHEAD of the llama_longctx re-bench: the
# 16k config measured 0.36x its roofline and the sweep is the localizer
run tune_attention  1800 python tools/tune_kernels.py --kernel attention
# serialized-vs-overlapped ring A/B at the 16k shape, ahead of the
# llama_longctx re-bench (the overlap layer is the claimed fix for its
# 0.36x roofline ratio — measure the claim before the headline number)
run ring_overlap_ab 1800 python tools/bench_ring_ab.py
# fused-vs-decomposed comm-kernel A/B (PR 9 ops.fused_collective): SP
# boundary MLP + fused-merge ring attention + the RDMA kernel's first
# execution/parity datum — AHEAD of the llama_longctx re-bench so the
# 16k number rides whichever form wins (needs >= 2 devices; emits a
# skip record on a single-chip window).
# GATE: the RDMA kernel's numerics are UNVERIFIED until this entry
# runs, and its semaphore/DMA protocol is proved only by graftlint's
# APX2xx model checker (docs/lint.md) — a red APX2xx run means the
# kernel would be first-executed with a known protocol defect, so the
# A/B must NOT dispatch. apx2_gate runs immediately before; its rc
# gates the --rdma entry (a lint failure burns ~10s, not the window).
run apx2_gate        120 python tools/lint.py --kernels
if [ -f "$RES/apx2_gate.log" ] && grep -q " 0 findings" "$RES/apx2_gate.log"; then
  run fused_comm_ab   1800 python tools/bench_fused_comm.py --rdma
else
  echo "SKIP fused_comm_ab: APX2xx kernel lint not green (see apx2_gate.log)" \
    | tee -a "$RES/status.log"
fi
run bench_llama16k  1800 python bench.py --config llama_longctx --timeout 1500
# planner A/B (ROADMAP item 1, apex1_tpu.planner): the auto-parallel
# planner's pick vs the hand-tuned layout — pricing leg against the
# JUST-refit calibration, measured leg on the live mesh (skip record
# on a single-chip window), plus the planner-driven llama_3d bench
# record. Runs AFTER the llama_longctx re-bench: the planner consumes
# this window's calibration, it must not delay the headline numbers.
run planner_ab      1800 python tools/bench_planner_ab.py
run bench_llama3d   1800 python bench.py --config llama_3d --timeout 1500
# dropout=0.1 bert variant FIRST (PR5: attention-probability dropout now
# rides the flash kernel + fused dropout-add-LN epilogues — this is the
# headline BERT-pretrain configuration, measured before the plain
# re-bench so the fused-dropout cost/win is priced on the same window)
run bench_bert_drop 1500 python bench.py --config bert_dropout --timeout 1200
run bench_bert      1200 python bench.py --config bert --timeout 1000
run bench_resnet    1200 python bench.py --config resnet --timeout 1000
run bench_t5        1500 python bench.py --config t5 --timeout 1200
run bench_gpt2_b24  1200 python bench.py --config gpt2 --batch 24 --timeout 1000
run bench_decode    1200 python bench.py --config decode --timeout 1000
run bench_dec_int8  1200 python bench.py --config decode_int8 --timeout 1000
# re-fit after the re-bench group (bert/resnet/t5/gpt2_b24/decode rows)
run calibrate_refresh2 300 python -m apex1_tpu.obs.calibrate
run profile_gpt2    1200 python tools/profile_step.py --config gpt2 --top 40
# per-op breakdowns for every profile_artifact the benches above
# stamped — the trace -> attribution leg of the flywheel, banked next
# to each artifact as trace_report.json
run trace_reports    900 python tools/trace_report.py --all
run cond_elision     900 python tools/cond_elision_probe.py
# A/B wall-clock of the PRODUCTION cond skips (pipeline bubble-skip +
# ring causal-skip) — executable-verified since r4, first timing
run bench_cond_ab   1200 python tools/bench_cond_elision.py
run kern_all        4800 python tools/bench_kernels.py all "${TINY[@]}"
run kern_all_llama  4800 python tools/bench_kernels.py all --llama "${TINY[@]}"
run tune_all        4800 python tools/tune_kernels.py --kernel all
# gpt2 O1-fp16 dynamic loss scaling BEHIND the sweeps (VERDICT Weak #8:
# fp16 is half the reference's reason to exist, zero hardware evidence;
# record carries skipped_steps + final loss_scale)
run bench_gpt2_fp16 1200 python bench.py --config gpt2_fp16 --timeout 1000
# re-fit after the sweep/kernel group: tune_all just banked measured
# per-kernel timings WITH their analytic predicted.ms — the first
# silicon-backed kernel factors
run calibrate_refresh3 300 python -m apex1_tpu.obs.calibrate
run hw_numerics     1500 python tools/hw_numerics.py --timeout 1400 "${CPUQ[@]}"
# PR 7 multi-replica serving sweep BEHIND the existing entries: replica
# scaling + goodput under a seed-keyed replica kill; record banked
# atomically per sweep point so a dying tunnel keeps completed points
run bench_serving_rep 1800 python tools/bench_serving.py --loads 8 \
                         --replicas 1 2 --chaos \
                         --out perf_results/bench_serving_replicas.json
# ISSUE 15 goodput multipliers ON SILICON: the shared-system-prompt
# trace under baseline / radix / radix+spec at equal offered load.
# The CPU proxy (perf_results/bench_spec_serving_cpu.log) banked
# hit/accept rates and the radix win, but speculation's wall-clock is
# TPU-shaped (weight-streaming-bound decode) — this entry is the first
# honest measurement of the spec axis, plus the int8 capacity row on
# real HBM geometry.
run bench_spec_serving 1800 python tools/bench_serving.py --loads 8 \
                         --prefix-len 24 --num-draft 4 \
                         --out perf_results/bench_spec_serving.json
# ISSUE 16 disaggregation A/B: unified vs prefill/decode pools at
# equal offered load + equal replicas on the adversarial long-prompt
# trace (virtual clock — routing/control evidence; the CPU proxy
# banked the same drill, this is the device-count-scaled rerun), with
# per-phase TTFT/TPOT parsed back off the obs spine and cross-fleet
# token parity asserted over every common completion.
run bench_disagg    1800 python tools/bench_serving.py --loads 4 \
                         --prefix-len 0 --disagg \
                         --out perf_results/bench_disagg.json
# ISSUE 18 paged decode ON SILICON: sweep the page-size / block_v
# tables first (the committed tables carry CPU tiny-mode picks; the
# hardware winners feed Engine._resolve_page_size for the bench that
# follows), then the dense-vs-paged A/B at peak load — the first
# honest timing of the fused kernel path (in-kernel int8 dequant +
# sampling epilogue: the CPU proxy prices composite ops only,
# docs/paged_decode.md), with per-phase attribution parsed back off
# the obs spine and per-rep token parity vs the dense engine.
run tune_paged      1800 python tools/tune_kernels.py --kernel paged_decode
run tune_fsample     900 python tools/tune_kernels.py --kernel fused_sample
run bench_paged_decode 1800 python tools/bench_serving.py --loads 8 \
                         --prefix-len 24 --num-draft 4 \
                         --out perf_results/bench_paged_decode.json
# ISSUE 19 chunked losses + fused GLU + LoRA epilogue ON SILICON:
# sweep the three new kernel tables first (the committed tables carry
# CPU tiny-mode picks; hardware winners feed the chunk_v / block_t /
# block_f / block_v auto-pickers), then the single- vs N-tenant LoRA
# serving A/B at peak load — the first honest timing of the fused
# adapter epilogue (cross-tenant page gather in the logits matmul),
# with per-rep token parity vs per-tenant solo runs on both rows so
# the A/B prices wall-clock, never correctness.
run tune_chunked    1800 python tools/tune_kernels.py --kernel chunked_loss
run tune_swiglu     1800 python tools/tune_kernels.py --kernel fused_swiglu
run tune_lora        900 python tools/tune_kernels.py --kernel lora_epilogue
run bench_lora_serving 1800 python tools/bench_serving.py --loads 8 \
                         --prefix-len 0 --lora-tenants 4 \
                         --out perf_results/bench_lora_serving.json
# elastic shrink-resume A/B (ISSUE 14) BEHIND the banked-bench
# backlog: the n -> n/2 mid-run shrink through the planner re-plan +
# manifest-verified reshard vs the from-checkpoint control, on the
# LIVE device set (skip record on a single-chip window; with
# JAX_PLATFORMS=cpu — the rehearsal — it runs the virtual 8->4 form).
# The CPU drill proves the remap/determinism contract; this entry is
# what proves it on silicon timings and a real multi-chip mesh.
run elastic_ab      1200 python -m apex1_tpu.resilience.elastic --drill --real
# final re-fit: the window's complete corpus (all bench groups + the
# tuning sweeps) becomes the calibration the NEXT session commits
run calibrate_refresh4 300 python -m apex1_tpu.obs.calibrate
echo "queue done $(date -u +%H:%M:%S)" >> "$RES/status.log"

if [ "$MODE" = rehearse ]; then
  if [ "$REH_FAIL" -ne 0 ]; then
    echo "REHEARSAL: FAILURES (see above)" | tee -a "$REHLOG"
    exit 1
  fi
  echo "REHEARSAL: ALL QUEUE ENTRIES OK" | tee -a "$REHLOG"
fi
