"""A/B: the auto-parallel planner's pick vs the hand-tuned layout —
the wall-clock form of ROADMAP item 1's acceptance contract (the
pricing form is pinned in tier-1 by tests/test_planner.py).

Two legs, banked to one log (tee this under tpu_watch as
``planner_ab``; the queue entry writes perf_results/bench_planner_ab.log):

1. PRICING (runs anywhere, no devices needed): for each banked bench
   shape (gpt2, llama_longctx, the llama-8B projection) price the
   hand-tuned layout and the planner's pick through the calibrated
   cost engine against the committed calibration.json, and emit the
   ratio — planner within ~10% of (i.e. at or below 1.10x) the hand
   config is the pass line.

2. MEASURED (needs >= 2 devices): build the SAME model under (a) the
   hand-tuned example layout and (b) the planner's pick for the live
   device count, time both `models.llama_3d` train steps, and emit
   both rates + the measured ratio. On a single-chip window this leg
   emits a skip record (rc 0 — the queue must keep moving); on CPU it
   rehearses on the 8-device virtual mesh with a tiny model,
   validating the command line end-to-end.

Usage: python tools/bench_planner_ab.py [--iters K] [--skip-measured]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(record):
    print(json.dumps(record), flush=True)


def _backend_is_cpu(timeout_s=120.0):
    """Subprocess backend probe (same contract as bench_ring_ab: the
    main process must not init a backend before the virtual-mesh
    decision)."""
    import subprocess
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "print('BACKEND=' + jax.default_backend())")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        return "BACKEND=cpu" in out.stdout
    except Exception:
        return False


#: the hand-tuned comparators the pricing leg scores against — the
#: exact layouts the repo's bench/aot history picked by hand:
#: gpt2/llama_longctx are the single-chip bench configs;
#: llama8b is aot_check --flagship's dp2 x pp2 x tp4 on 16 chips.
def _hand_cases():
    from apex1_tpu import planner

    S = planner.BANKED_SHAPES
    return [
        ("gpt2", S["gpt2"], 1, "v5e",
         planner.Layout(num_microbatches=16)),
        ("llama_longctx", S["llama_longctx"], 1, "v5e",
         planner.Layout(num_microbatches=1)),
        ("llama8b", S["llama8b"], 16, "v5p",
         planner.Layout(dp=2, pp=2, tp=4, num_microbatches=4)),
    ]


def pricing_leg():
    from apex1_tpu import planner

    worst = 0.0
    for name, shape, n, gen, hand in _hand_cases():
        hand_price = planner.price_layout(shape, hand, generation=gen)
        plan = planner.make_plan(shape, n, generation=gen)
        pick = plan["predicted"]
        ratio = (pick["calibrated_step_ms"]
                 / hand_price["calibrated_step_ms"])
        worst = max(worst, ratio)
        _emit({
            "metric": f"planner_ab pricing {name} [{gen} x{n}]",
            "hand_mesh": hand.mesh_str(),
            "hand_calibrated_ms": round(
                hand_price["calibrated_step_ms"], 3),
            "planner_mesh": plan["mesh"],
            "planner_calibrated_ms": round(
                pick["calibrated_step_ms"], 3),
            "planner_over_hand": round(ratio, 4),
            "calibration": pick["calibration"]["source"],
            "pass": ratio <= 1.10,
        })
    return worst


def measured_leg(iters):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu import planner
    from apex1_tpu.core.policy import get_policy
    from apex1_tpu.models.llama import LlamaConfig
    from apex1_tpu.models.llama_3d import (Llama3DConfig,
                                           make_train_step)

    backend = jax.default_backend()
    devices = jax.devices()
    n = len(devices)
    if n < 2:
        _emit({"metric": f"planner_ab measured [{backend}]",
               "value": 0.0,
               "error": f"devices available: {n} — skipped (multichip "
                        f"window required for a layout A/B)"})
        return
    on_accel = backend not in ("cpu",)
    if on_accel:
        mcfg = LlamaConfig(vocab_size=32000, max_seq_len=2048,
                           num_layers=8, num_heads=32, num_kv_heads=4,
                           hidden_size=2048, ffn_size=5632, remat=True,
                           policy=get_policy("O2"))
    else:
        import dataclasses
        mcfg = dataclasses.replace(
            LlamaConfig.tiny(policy=get_policy("O2")),
            max_seq_len=128, remat=True)
    global_batch = 4 * n
    shape = planner.ModelShape.from_llama(mcfg, name="llama_3d",
                                          global_batch=global_batch)
    gen = None
    if on_accel:
        from apex1_tpu.core.capability import get_capability
        gen = get_capability().generation

    # the hand comparator: the flagship recipe's shape — dp=2 fixed,
    # tp as deep as the kv heads allow, pp the remainder (the same
    # rule tools/aot_check.py --flagship applies by hand). An odd or
    # otherwise unfactorable device count has no hand layout of this
    # family — skip record, not a traceback (the queue must keep
    # moving).
    cands = [t for t in (1, 2, 4, 8)
             if n % (2 * t) == 0 and n // (2 * t) >= 1
             and shape.num_kv_heads % t == 0
             and shape.seq_len % t == 0]
    if not cands:
        _emit({"metric": f"planner_ab measured [{backend}]",
               "value": 0.0,
               "error": f"no dp=2-family hand comparator for n={n} "
                        f"devices — skipped"})
        return
    tp = max(cands)
    dp = 2
    pp = n // (dp * tp)
    hand_cfg = Llama3DConfig(model=mcfg, dp=dp, pp=pp, tp=tp,
                             num_microbatches=global_batch // dp,
                             microbatch_size=1)
    plan = planner.make_plan(shape, n, generation=gen,
                             allow_zero=False)
    plan_cfg = planner.llama3d_config_from_plan(plan, mcfg)

    def timed(tag, cfg):
        step, state, _ = make_train_step(cfg)
        rng = np.random.default_rng(0)
        dshape = (cfg.num_microbatches, mcfg.max_seq_len,
                  cfg.microbatch_size * cfg.dp * cfg.ep)
        tokens = jnp.asarray(
            rng.integers(0, mcfg.vocab_size, dshape), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        state, loss = step(state, tokens, labels)   # compile + warm
        jax.block_until_ready((state, loss))
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = step(state, tokens, labels)
        jax.block_until_ready((state, loss))
        dt = (time.perf_counter() - t0) / iters
        del state
        return dt

    t_hand = timed("hand", hand_cfg)
    t_plan = timed("plan", plan_cfg)
    tok = shape.tokens_per_step
    _emit({
        "metric": f"planner_ab measured [{backend}]",
        "value": round(tok / t_plan / n, 1),
        "unit": "tokens/sec/chip",
        "hand_mesh": f"dp={dp} pp={pp} tp={tp}",
        "hand_step_ms": round(t_hand * 1e3, 2),
        "hand_rate": round(tok / t_hand / n, 1),
        "planner_mesh": plan["mesh"],
        "planner_step_ms": round(t_plan * 1e3, 2),
        "planner_over_hand_time": round(t_plan / t_hand, 4),
        "predicted_calibrated_ms": round(
            plan["predicted"]["calibrated_step_ms"], 3),
        "iters": iters,
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--skip-measured", action="store_true",
                    help="pricing leg only (no backend init)")
    args = ap.parse_args()

    print("== planner_ab pricing (calibrated cost engine, banked "
          "shapes) ==", flush=True)
    worst = pricing_leg()
    print(f"pricing leg worst planner/hand ratio: {worst:.3f} "
          f"({'PASS' if worst <= 1.10 else 'FAIL'} at the 1.10 line)",
          flush=True)
    if args.skip_measured:
        return 0 if worst <= 1.10 else 1

    print("== planner_ab measured (live mesh) ==", flush=True)
    plat = os.environ.get("JAX_PLATFORMS", "").strip()
    on_cpu = plat == "cpu" if plat else _backend_is_cpu()
    if on_cpu:
        from apex1_tpu.testing import force_virtual_cpu_devices
        force_virtual_cpu_devices(8)
    else:
        from apex1_tpu.testing import honor_jax_platforms_env
        honor_jax_platforms_env()
    from apex1_tpu.testing import enable_persistent_compilation_cache
    enable_persistent_compilation_cache()
    measured_leg(args.iters or (2 if on_cpu else 6))
    return 0 if worst <= 1.10 else 1


if __name__ == "__main__":
    raise SystemExit(main())
