"""In-process block-size sweep driver for the Pallas kernels.

Measures N block-size candidates per kernel **in one process** — block
sizes are static kernel arguments (`apex1_tpu.tuning` threading), so the
jit cache keys on them and each candidate compiles exactly one
executable. This replaces the old ``APEX1_ATTN_BLOCK_*`` env-var sweeps,
which were read at trace time and forced a fresh process (a cold compile
of everything) per candidate — the reason the kernel A/B sweeps never
fit an 18-minute tunnel window.

Per kernel the driver:

1. filters candidates through the `apex1_tpu.tuning.registry` VMEM
   model (dropped candidates are LOGGED, never silently skipped);
2. times each survivor fwd(+bwd) on the live backend with the loop in
   one dispatch (tunnel dispatch latency hidden; interpret mode on CPU
   — plumbing-valid, timing-meaningless, marked ``timing:
   "interpret"`` in the table so real TPUs never serve it);
3. records the winner in the shape-keyed tuning table, persists it
   under ``perf_results/tuning/`` (override: ``APEX1_TUNING_DIR``),
   clears the jit cache (earlier traces baked the OLD table values),
   and verifies a fresh lookup returns the winner.

Output is tee'd to ``perf_results/tune_<kernel>_<backend>.log`` so a
tunnel death mid-sweep still banks every line that printed.

``--validate`` runs the strict table check instead (every in-repo table
parses; every entry passes the VMEM-budget model for its recorded
capability) — the ``== tuning tables ==`` step of tools/check_all.sh.

Usage:
    python tools/tune_kernels.py --kernel attention [--backend cpu]
    python tools/tune_kernels.py --kernel all --iters 20
    python tools/tune_kernels.py --validate
"""

import argparse
import dataclasses
import functools
import os
import sys
from typing import Callable, Sequence

_TOOLS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TOOLS)
sys.path.insert(0, _REPO)
sys.path.insert(0, _TOOLS)   # for bench_kernels (shared timeit)


@dataclasses.dataclass
class Case:
    """One kernel sweep: candidates (dicts of block params) + a factory
    returning (timed_fn, args) for a candidate. ``flops``/``nbytes``
    are the ANALYTIC cost of one timed invocation at the sweep shape
    (formulas mirror tools/predict_perf.py::_kernel_cases) — banked
    beside the winner as ``predicted.ms`` so `apex1_tpu.obs.calibrate`
    can pair every measured sweep against its own roofline. None =
    unpriced (the entry then never feeds calibration)."""
    kernel: str                   # registry name (keys the table)
    dims: dict                    # padded dims for the table key
    dtype: str                    # canonical dtype for the table key
    candidates: Sequence[dict]
    make: Callable                # blocks -> (fn, args)
    grad: bool                    # fwd+bwd (training path) vs fwd-only
    flops: float = None           # analytic flops per timed invocation
    nbytes: float = None          # analytic min HBM bytes per invocation


def _flash_cost(B, Hq, Hkv, S, D, causal=True, grad=False):
    """Analytic (flops, min HBM bytes) for one flash invocation —
    predict_perf's formula, incl. the 4.5x fwd+bwd factor for the
    SHIPPED two-pass backward (7 bwd matmuls, not the fused-5)."""
    f = 4 * B * Hq * S * S * D * (0.5 if causal else 1.0)
    if grad:
        f *= 4.5
    qb = B * Hq * S * D * 2
    kvb = 2 * B * Hkv * S * D * 2
    byt = qb + kvb + qb            # q, k, v in; o out
    if grad:
        byt += 2 * qb + kvb + qb   # dq out, dk/dv out, do in
    return float(f), float(byt)


def _elemwise_cost(n_elem, passes, itemsize, fpe):
    """Bandwidth-bound row kernels: bytes = per-pass element traffic."""
    return float(fpe * n_elem), float(passes * n_elem * itemsize)


def _grad_of_sum(f, argnums):
    import jax
    import jax.numpy as jnp

    def g(*args):
        return jax.grad(lambda *a: jnp.sum(
            jax.tree.leaves(f(*a))[0].astype(jnp.float32)),
            argnums=argnums)(*args)
    return g


# --------------------------------------------------------------------------
# sweep cases — shapes auto-shrink on CPU (interpret mode validates the
# plumbing; tpu shapes mirror tools/bench_kernels.py so winners line up
# with the banked A/B numbers)
# --------------------------------------------------------------------------

def _attention_case(B, Hq, Hkv, S, D, cands):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.attention import flash_attention
    from apex1_tpu.tuning import padded_lanes, seq_bucket

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, Hq, S, D)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), jnp.bfloat16)

    def make(blocks):
        f = functools.partial(flash_attention, causal=True,
                              block_q=blocks["block_q"],
                              block_k=blocks["block_k"])
        return _grad_of_sum(f, (0, 1, 2)), (q, k, v)

    fl, by = _flash_cost(B, Hq, Hkv, S, D, causal=True, grad=True)
    return Case("flash_attention",
                {"Dp": padded_lanes(D), "Sb": seq_bucket(S)}, "bfloat16",
                [dict(block_q=bq, block_k=bk) for bq, bk in cands
                 if bq <= S and bk <= S],
                make, grad=True, flops=fl, nbytes=by)


def case_attention(tiny):
    if tiny:
        return _attention_case(1, 2, 2, 256, 64,
                               [(128, 128), (256, 256)])
    cands = [(256, 256), (256, 512), (512, 512), (512, 1024),
             (1024, 1024)]
    # one sweep per SEQ BUCKET the benches actually run: winners are
    # seq-keyed, so the gpt2-shape sweep cannot govern the 16k GQA
    # config (llama_longctx — the 0.36x-roofline localizer target)
    return [_attention_case(8, 12, 12, 1024, 64, cands),
            _attention_case(1, 32, 4, 16384, 64, cands)]


def case_linear_xent(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.linear_xent import linear_cross_entropy
    from apex1_tpu.tuning import padded_lanes

    T, H, V = (256, 128, 512) if tiny else (8184, 768, 50432)
    cands = ([(64, 128), (128, 128)] if tiny else
             [(256, 512), (512, 512), (256, 768), (512, 1024),
              (1024, 1024)])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.02, jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.02, jnp.bfloat16)
    t = jnp.asarray(rng.integers(0, V - 100, (T,)), jnp.int32)

    def make(blocks):
        def f(x, w):
            return linear_cross_entropy(x, w, t, num_classes=V - 100,
                                        block_t=blocks["block_t"],
                                        block_v=blocks["block_v"])
        return _grad_of_sum(f, (0, 1)), (x, w)

    return Case("linear_xent", {"Hp": padded_lanes(H)}, "bfloat16",
                [dict(block_t=bt, block_v=bv) for bt, bv in cands],
                make, grad=True,
                flops=float(6 * T * H * V),              # fwd + dX + dW
                nbytes=float(2 * (3 * V * H + 2 * T * H + V * H)))


def _row_case(kernel, tiny, build, tiny_cands=(32, 64),
              cands=(64, 128, 256, 336, 512)):
    from apex1_tpu.tuning import padded_lanes

    fn_factory, lanes, dtype, fl, by = build(tiny)
    brs = tiny_cands if tiny else cands
    return Case(kernel, {"lanes": padded_lanes(lanes)}, dtype,
                [dict(block_rows=br) for br in brs], fn_factory,
                grad=True, flops=fl, nbytes=by)


def case_softmax(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops import scaled_upper_triang_masked_softmax

    def build(tiny):
        B, H, S = (1, 2, 128) if tiny else (8, 12, 1024)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(B, H, S, S)), jnp.float32)

        def make(blocks):
            def f(x):
                return scaled_upper_triang_masked_softmax(
                    x, scale=0.125, block_rows=blocks["block_rows"])
            return _grad_of_sum(f, 0), (x,)

        return make, S, "float32", *_elemwise_cost(
            B * H * S * S // 2, 4, 4, 8)   # causal half, f+b

    return _row_case("fused_softmax", tiny, build)


def case_layer_norm(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops import layer_norm

    def build(tiny):
        R, H = (256, 128) if tiny else (8192, 768)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(R, H)), jnp.bfloat16)
        g = jnp.ones((H,), jnp.float32)
        b = jnp.zeros((H,), jnp.float32)

        def make(blocks):
            def f(x):
                return layer_norm(x, g, b,
                                  block_rows=blocks["block_rows"])
            return _grad_of_sum(f, 0), (x,)

        return make, H, "bfloat16", *_elemwise_cost(R * H, 4, 2, 8)

    return _row_case("layer_norm", tiny, build)


def case_rope(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops import apply_rotary_pos_emb, rope_tables

    def build(tiny):
        # head_dim 256: the rope kernel's lane gate needs half % 128 == 0
        B, S, H, D = (1, 64, 2, 256) if tiny else (1, 4096, 16, 256)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16)
        cos, sin = rope_tables(jnp.arange(S), D)

        def make(blocks):
            def f(x):
                return apply_rotary_pos_emb(
                    x, cos, sin, block_rows=blocks["block_rows"])
            return _grad_of_sum(f, 0), (x,)

        return make, D // 2, "bfloat16", *_elemwise_cost(
            B * S * H * D, 4, 2, 6)

    return _row_case("rope", tiny, build)


def case_xentropy(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops import softmax_cross_entropy_loss

    def build(tiny):
        T, V = (256, 512) if tiny else (8184, 50432)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(T, V)), jnp.float32)
        t = jnp.asarray(rng.integers(0, V - 100, (T,)), jnp.int32)

        def make(blocks):
            def f(x):
                return softmax_cross_entropy_loss(
                    x, t, num_classes=V - 100,
                    block_rows=blocks["block_rows"])
            return _grad_of_sum(f, 0), (x,)

        return make, V, "float32", *_elemwise_cost(
            T * V, 3, 4, 8)   # recompute-bwd: x, x, dx

    return _row_case("xentropy", tiny, build,
                     tiny_cands=(32, 64), cands=(8, 16, 32))


def case_bias_dropout_add(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops import fused_bias_dropout_add

    def build(tiny):
        R, H = (256, 128) if tiny else (8192, 1024)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(R, H)), jnp.bfloat16)
        r = jnp.asarray(rng.normal(size=(R, H)), jnp.bfloat16)
        b = jnp.asarray(rng.normal(size=(H,)), jnp.float32)

        def make(blocks):
            def f(x, r):
                return fused_bias_dropout_add(
                    x, r, bias=b, p=0.1, seed=1234,
                    block_rows=blocks["block_rows"])
            return _grad_of_sum(f, (0, 1)), (x, r)

        # fwd: x, r in + out; bwd: dout in + dx, dr out — 6 passes of
        # (R, H) bf16; ~10 flops/elem covers the hash + mask + muladd
        return make, H, "bfloat16", *_elemwise_cost(R * H, 6, 2, 10)

    return _row_case("bias_dropout_add", tiny, build)


def case_fused_matmul(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.fused_collective import _chunk_matmul
    from apex1_tpu.tuning import padded_lanes

    # the SP-boundary chunk shape (per-ring-step rows x hidden-shard):
    # one ring step's dot is what the ppermute/RDMA forms launch
    M, K, N = (64, 128, 128) if tiny else (1024, 1024, 4096)
    cands = ([(32, 128), (64, 128)] if tiny else
             [(128, 512), (256, 512), (256, 1024), (512, 512),
              (512, 1024)])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(M, K)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(K, N)) * 0.02, jnp.bfloat16)

    def make(blocks):
        def f(x, w):
            return _chunk_matmul(x, w, blocks["block_m"],
                                 blocks["block_n"])
        return f, (x, w)   # fwd-only: the ring VJP reuses the same
                           # kernel through the dual's forward

    return Case("fused_collective_matmul", {"Kp": padded_lanes(K)},
                "bfloat16",
                [dict(block_m=bm, block_n=bn) for bm, bn in cands
                 if bm <= M], make, grad=False,
                flops=float(2 * M * K * N),
                nbytes=float(M * K * 2 + K * N * 2 + M * N * 4))


def case_fused_ag_flash(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.fused_collective import _agf_call
    from apex1_tpu.tuning import padded_lanes, seq_bucket

    # one ring step of the 16k GQA target: attend a visiting K/V shard
    # and fold the carried (out, lse) in the kernel epilogue (cp=4
    # shard of the llama_longctx shape on hardware)
    B, Hq, Hkv, S, D = (1, 2, 2, 256, 64) if tiny else (1, 32, 4, 4096,
                                                        64)
    cands = ([(128, 128), (256, 256)] if tiny else
             [(256, 256), (256, 512), (512, 512), (512, 1024),
              (1024, 1024)])
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, Hq, S, D)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)), jnp.bfloat16)
    out0 = jnp.zeros((B, Hq, S, D), jnp.float32)
    lse0 = jnp.full((B, Hq, S), -1e30, jnp.float32)

    def make(blocks):
        def f(q, k, v):
            # q_off=S, k_off=0: the query shard sits AFTER the visiting
            # K/V shard, so the causal gate keeps every block live and
            # the sweep times a full attend+merge (q_off=0/k_off=S
            # would mask every grid point and time an attend-free
            # kernel — the banked winner would be noise)
            return _agf_call(q, k, v, None, None, S, 0, out0, lse0,
                             1.0 / float(np.sqrt(D)), True, False,
                             blocks["block_q"], blocks["block_k"])
        return f, (q, k, v)

    # full (uncausal-equivalent) attend of one visiting shard + the
    # fp32 (out, lse) carry read+written in the epilogue
    qb = B * Hq * S * D * 2
    kvb = 2 * B * Hkv * S * D * 2
    carry = 2 * (B * Hq * S * D * 4 + B * Hq * S * 4)
    return Case("fused_ag_flash",
                {"Dp": padded_lanes(D), "Sb": seq_bucket(S)}, "bfloat16",
                [dict(block_q=bq, block_k=bk) for bq, bk in cands
                 if bq <= S and bk <= S], make, grad=False,
                flops=float(4 * B * Hq * S * S * D),
                nbytes=float(qb + kvb + carry))


def case_int8(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops import int8_matmul, quantize_int8

    T, N, K = (8, 256, 256) if tiny else (8, 2048, 2048)
    cands = ([(128, 128), (256, 128)] if tiny else
             [(256, 512), (512, 512), (256, 1024), (512, 256)])
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(N, K)) * 0.02, jnp.float32)
    x = jnp.asarray(rng.normal(size=(T, K)), jnp.bfloat16)
    wq, s = quantize_int8(w)

    def make(blocks):
        def f(x):
            return int8_matmul(x, wq, s, blocks["block_n"],
                               blocks["block_k"])
        return f, (x,)   # decode path: fwd-only is the product shape

    return Case("int8_matmul", {"N": N, "K": K}, "int8",
                [dict(block_n=bn, block_k=bk) for bn, bk in cands],
                make, grad=False,
                flops=float(2 * T * N * K),
                nbytes=float(N * K + N * 4 + T * K * 2 + T * N * 2))


def case_paged_decode(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.paged_decode import paged_attend
    from apex1_tpu.tuning import padded_lanes

    # the serving engine's decode row class (GQA group 4, one query per
    # slot). page_p is a POOL LAYOUT parameter, not a kernel static
    # arg: each candidate re-pages the SAME dense lanes at its page
    # size, so the sweep times the real layout the engine would
    # allocate — the winner feeds Engine._resolve_page_size through
    # the table. Both cache tiers sweep (int8's fused dequant changes
    # the page-streaming balance, so its winner may differ from bf16).
    N, Hq, Hkv, D, L = ((4, 8, 2, 64, 128) if tiny
                        else (8, 32, 8, 128, 2048))
    cands = [8, 16] if tiny else [8, 16, 32, 64, 128]
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(N, Hq, 1, D)), jnp.bfloat16)
    lanes_k = rng.normal(size=(N, Hkv, L, D))
    lanes_v = rng.normal(size=(N, Hkv, L, D))
    lengths = jnp.asarray(rng.integers(L // 2, L, size=N), jnp.int32)

    def tier(dtype_name, cast):
        def make(blocks):
            P = blocks["page_p"]
            T = L // P
            bt = np.arange(1, 1 + N * T, dtype=np.int32).reshape(N, T)
            kp = np.zeros((1 + N * T, Hkv, P, D), np.float32)
            vp = np.zeros_like(kp)
            for r in range(N):
                for t in range(T):
                    kp[bt[r, t]] = lanes_k[r, :, t * P:(t + 1) * P]
                    vp[bt[r, t]] = lanes_v[r, :, t * P:(t + 1) * P]
            kpj, vpj, btj = cast(kp), cast(vp), jnp.asarray(bt)

            def f(q):
                return paged_attend(q, kpj, vpj, btj, lengths)
            return f, (q,)

        es = 1 if dtype_name == "int8" else 2
        return Case("paged_decode", {"Dp": padded_lanes(D), "Rq": 8},
                    dtype_name, [dict(page_p=p) for p in cands],
                    make, grad=False,
                    flops=float(4 * N * Hq * L * D),
                    nbytes=float(2 * N * Hkv * L * D * es
                                 + 2 * N * Hq * D * 2))

    return [tier("bfloat16", lambda a: jnp.asarray(a, jnp.bfloat16)),
            tier("int8", lambda a: jnp.asarray(np.clip(
                a * 30.0, -127, 127).astype(np.int8)))]


def case_fused_sample(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.paged_decode import fused_sample
    from apex1_tpu.tuning import padded_lanes

    # the sampling epilogue at the engine's step shape: R slot rows over
    # a GPT-2-class padded vocab. block_v tiles the vocab axis; every
    # split is bitwise-identical (exact f32 (max, first-index) fold),
    # so this sweep is purely a VMEM-residency/grid-overhead trade.
    R, V = (8, 1024) if tiny else (8, 50432)
    cands = ([512, 1024] if tiny
             else [3200, 6400, 12672, 25216, 50432])
    rng = np.random.default_rng(0)
    lg = jnp.asarray(rng.standard_normal((R, V)), jnp.float32)
    seeds = jnp.asarray(rng.integers(0, 2**31 - 1, size=R), jnp.int32)
    pos = jnp.asarray(rng.integers(0, 64, size=R), jnp.int32)

    def make(blocks):
        def f(lg):
            return fused_sample(lg, seeds, pos, temperature=0.7,
                                vocab_size=V - 175,
                                block_v=blocks["block_v"])
        return f, (lg,)

    return Case("fused_sample", {"Vp": padded_lanes(V)}, "float32",
                [dict(block_v=bv) for bv in cands], make, grad=False,
                flops=float(30 * R * V),
                nbytes=float(R * V * 4 + R * 4))


def case_chunked_loss(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.chunked_loss import chunked_logprob
    from apex1_tpu.tuning import padded_lanes

    # preference-loss building block at the gpt2 head shape: chunk_v
    # trades recompute passes (fwd + bwd stream each chunk twice)
    # against per-chunk VMEM residency. Every split is numerically
    # identical (online-softmax merge), so the sweep is pure timing.
    T, H, V = (128, 128, 512) if tiny else (8184, 768, 50432)
    cands = [256, 512] if tiny else [2048, 4096, 8192, 16384, 25216]
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.02, jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.02, jnp.bfloat16)
    t = jnp.asarray(rng.integers(0, V - 100, (T,)), jnp.int32)

    def make(blocks):
        def f(x, w):
            return chunked_logprob(x, w, t, num_classes=V - 100,
                                   chunk_v=blocks["chunk_v"])
        return _grad_of_sum(f, (0, 1)), (x, w)

    return Case("chunked_loss", {"Hp": padded_lanes(H)}, "bfloat16",
                [dict(chunk_v=cv) for cv in cands], make, grad=True,
                flops=float(8 * T * H * V),       # fwd stats + recomputed
                #                                   bwd chunk + dX + dW
                nbytes=float(2 * (3 * V * H + 2 * T * H + V * H)))


def case_fused_swiglu(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.fused_dense import fused_glu
    from apex1_tpu.tuning import padded_lanes

    # the llama fused_mlp tile (gate+up in one pass over x): block_t x
    # block_f tiles the (tokens, ffn) output; both matmuls re-read the
    # x block, so the trade is x-block reuse vs activation residency.
    T, H, F = (64, 128, 256) if tiny else (8192, 4096, 14336)
    cands = ([(8, 128), (16, 128)] if tiny
             else [(128, 512), (256, 512), (128, 1024), (256, 1024),
                   (512, 1024)])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(T, H)) * 0.02, jnp.bfloat16)
    wg = jnp.asarray(rng.normal(size=(H, F)) * 0.02, jnp.bfloat16)
    wu = jnp.asarray(rng.normal(size=(H, F)) * 0.02, jnp.bfloat16)

    def make(blocks):
        def f(x, wg, wu):
            return fused_glu(x, wg, wu, block_t=blocks["block_t"],
                             block_f=blocks["block_f"])
        return _grad_of_sum(f, (0, 1, 2)), (x, wg, wu)

    return Case("fused_swiglu", {"Hp": padded_lanes(H)}, "bfloat16",
                [dict(block_t=bt, block_f=bf) for bt, bf in cands],
                make, grad=True,
                flops=float(3 * 2 * 2 * T * H * F),  # fwd + recompute +
                #                                      bwd, two GEMMs
                nbytes=float(2 * (2 * H * F * 2 + 2 * T * H + T * F)))


def case_lora_epilogue(tiny):
    import jax.numpy as jnp
    import numpy as np

    from apex1_tpu.ops.lora_epilogue import lora_delta
    from apex1_tpu.tuning import padded_lanes

    # the multi-tenant serving epilogue at the engine's decode step
    # shape: N slot rows, rank pages gathered via the scalar-prefetched
    # block table. block_v tiles the vocab axis of the B pages; every
    # split is bitwise-identical (fp32 accumulate), pure residency.
    N, H, V, R = (4, 128, 512, 2) if tiny else (8, 4096, 50432, 8)
    n_pg = 1 + 4 * R
    cands = [128, 256] if tiny else [2048, 6400, 12672, 25216]
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(N, H)) * 0.02, jnp.bfloat16)
    ap = jnp.asarray(rng.normal(size=(n_pg, H)) * 0.02, jnp.float32)
    bp = jnp.asarray(rng.normal(size=(n_pg, V)) * 0.02, jnp.float32)
    bt = jnp.asarray(
        rng.integers(1, n_pg, size=(N, R)), jnp.int32)

    def make(blocks):
        def f(h):
            return lora_delta(h, ap, bp, bt,
                              block_v=blocks["block_v"])
        return f, (h,)

    return Case("lora_epilogue",
                {"Hp": padded_lanes(H), "Vp": padded_lanes(V)},
                "bfloat16", [dict(block_v=bv) for bv in cands],
                make, grad=False,
                flops=float(2 * N * R * (H + V)),
                nbytes=float(N * R * (H + V) * 4 + N * V * 4))


CASES = {
    "attention": case_attention,
    "paged_decode": case_paged_decode,
    "fused_sample": case_fused_sample,
    "chunked_loss": case_chunked_loss,
    "fused_swiglu": case_fused_swiglu,
    "lora_epilogue": case_lora_epilogue,
    "linear_xent": case_linear_xent,
    "softmax": case_softmax,
    "layer_norm": case_layer_norm,
    "rope": case_rope,
    "xentropy": case_xentropy,
    "bias_dropout_add": case_bias_dropout_add,
    "fused_matmul": case_fused_matmul,
    "fused_ag_flash": case_fused_ag_flash,
    "int8": case_int8,
}


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

class _Tee:
    """print() to stdout AND the banked log, line-buffered."""

    def __init__(self, path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self.f = open(path, "a", buffering=1)

    def __call__(self, *parts):
        line = " ".join(str(p) for p in parts)
        print(line, flush=True)
        self.f.write(line + "\n")


def sweep_one(name, iters, say, write=True):
    """Sweep one kernel (possibly several shape cases); returns
    (winners, problems) — one winner blocks-dict per swept case."""
    from apex1_tpu.ops._common import on_tpu

    tiny = not on_tpu()
    cases = CASES[name](tiny)
    if isinstance(cases, Case):
        cases = [cases]
    winners, problems = [], []
    for case in cases:
        w, p = _sweep_case(case, iters, say, write)
        if w is not None:
            winners.append(w)
        problems += p
    return winners, problems


def _sweep_case(case, iters, say, write):
    import jax
    import numpy as np

    from apex1_tpu import tuning
    from apex1_tpu.core.capability import vmem_budget
    from apex1_tpu.obs import calibrate, spine
    from apex1_tpu.ops._common import force_impl, on_tpu
    from apex1_tpu.tuning.registry import SPECS

    tiny = not on_tpu()
    spec = SPECS[case.kernel]
    budget = vmem_budget()
    es = np.dtype(case.dtype).itemsize
    say(f"== {case.kernel} dims={case.dims} dtype={case.dtype} "
        f"backend={jax.default_backend()} "
        f"{'(interpret-mode plumbing run)' if tiny else ''} ==")

    runnable = []
    # the per-candidate DEVICE-TIME BREAKDOWN banked with the winner
    # (ROADMAP item 5's flywheel: every sweep's measurements persist
    # next to the tuning tables instead of being discarded after the
    # winner is picked — the (shape -> timing) corpus the analytic
    # model's correction factors will be fitted from)
    breakdown = []
    for blocks in case.candidates:
        ok, est = spec.check(blocks, case.dims, es, budget)
        if ok:
            runnable.append(blocks)
        else:
            say(f"  drop {blocks}: VMEM model {est / 2**20:.1f} MiB "
                f"> budget {budget / 2**20:.0f} MiB")
            breakdown.append({"blocks": dict(blocks), "status": "vmem",
                              "vmem_est_bytes": int(est)})
    if len(runnable) < 2:
        say(f"  SKIP {case.kernel}: <2 runnable candidates")
        return None, [f"{case.kernel}: <2 runnable candidates"]

    # shared single-dispatch timing methodology (the eps-tap fori loop):
    # lazy import so jax initializes only after --backend takes effect
    from bench_kernels import timeit

    # analytic roofline for ONE timed invocation at the sweep shape —
    # banked as `predicted.ms` beside the winner so obs.calibrate can
    # pair every sweep measurement against its own prediction (the
    # (shape -> timing) corpus ROADMAP-5 fits correction factors from).
    # Keyed to the same generation the table entry lands under.
    gen = tuning.canonical_generation(None)
    pred_ms = None
    if case.flops is not None and case.nbytes is not None:
        pred_ms = round(calibrate.roofline_ms(case.flops, case.nbytes,
                                              gen), 6)
        say(f"  predicted {pred_ms:.4f} ms roofline ({gen}; interpret "
            f"timings will sit far above it — plumbing, not silicon)"
            if tiny else
            f"  predicted {pred_ms:.4f} ms roofline ({gen})")

    results = []
    for blocks in runnable:
        fn, args = case.make(blocks)
        try:
            with force_impl("pallas"):
                dt = timeit(fn, *args, iters=iters)
            say(f"  {blocks}  {dt * 1e3:9.3f} ms "
                f"{'fwd+bwd' if case.grad else 'fwd'}")
            results.append((dt, blocks))
            breakdown.append({"blocks": dict(blocks), "status": "timed",
                              "time_ms": round(dt * 1e3, 4)})
            spine.emit("event", "tune.candidate", kernel=case.kernel,
                       blocks=dict(blocks), status="timed",
                       time_ms=round(dt * 1e3, 4))
        except Exception as e:
            say(f"  {blocks}: {type(e).__name__}: {str(e)[:140]}")
            breakdown.append({"blocks": dict(blocks), "status": "error",
                              "error": f"{type(e).__name__}: "
                                       f"{str(e)[:140]}"})
            spine.emit("event", "tune.candidate", kernel=case.kernel,
                       blocks=dict(blocks), status="error")
    if not results:
        return None, [f"{case.kernel}: every candidate failed"]

    dt, blocks = min(results, key=lambda r: r[0])
    say(f"  WINNER {blocks}  {dt * 1e3:.3f} ms")
    spine.emit("event", "tune.winner", kernel=case.kernel,
               blocks=dict(blocks), time_ms=round(dt * 1e3, 4),
               predicted_ms=pred_ms)
    if not write:
        return blocks, []
    extra = {"sweep": {"iters": iters,
                       "grad": bool(case.grad),
                       "candidates": breakdown}}
    if pred_ms is not None:
        extra["predicted"] = {"ms": pred_ms, "flops": case.flops,
                              "bytes": case.nbytes, "generation": gen}
    key, _entry = tuning.record(
        case.kernel, case.dims, case.dtype, blocks, time_ms=dt * 1e3,
        extra=extra)
    path = tuning.save(case.kernel)
    # earlier traces in THIS process baked the pre-sweep table values
    # into their executables — drop them before anyone re-traces
    jax.clear_caches()
    tuning.clear_cache()
    got = tuning.lookup(case.kernel, case.dims, case.dtype)
    if got != blocks:
        return blocks, [f"{case.kernel}: post-save lookup returned "
                        f"{got}, expected {blocks}"]
    say(f"  banked {key} -> {path} (lookup verified)")
    return blocks, []


def validate(say):
    from apex1_tpu import tuning
    d = tuning.default_tuning_dir()
    problems = tuning.validate_tables(d)
    n = len([f for f in (os.listdir(d) if os.path.isdir(d) else ())
             if f.endswith(".json")])
    say(f"tuning tables: {n} file(s) under {d}")
    for p in problems:
        say(f"  INVALID {p}")
    say("tuning tables OK" if not problems
        else f"{len(problems)} invalid entries/files")
    return problems


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kernel", default="attention",
                    choices=sorted(CASES) + ["all"])
    ap.add_argument("--backend", default=None,
                    help="force a JAX platform (e.g. cpu) before init")
    ap.add_argument("--iters", type=int, default=None,
                    help="timing loop length (default 20, 2 on cpu)")
    ap.add_argument("--no-write", action="store_true",
                    help="measure only; don't touch the tables")
    ap.add_argument("--validate", action="store_true",
                    help="strict table check (check_all.sh gate); no sweep")
    args = ap.parse_args()

    if args.validate:
        # table validation is file parsing + arithmetic — skip backend
        # init and cache setup (this runs on every check_all invocation)
        problems = validate(print)
        sys.exit(1 if problems else 0)

    if args.backend:
        os.environ["JAX_PLATFORMS"] = args.backend
    from apex1_tpu.testing import (enable_persistent_compilation_cache,
                                   honor_jax_platforms_env)
    honor_jax_platforms_env()
    enable_persistent_compilation_cache()

    import jax
    backend = jax.default_backend()
    names = sorted(CASES) if args.kernel == "all" else [args.kernel]
    iters = args.iters or (2 if backend == "cpu" else 20)
    say = _Tee(os.path.join(_REPO, "perf_results",
                            f"tune_{args.kernel}_{backend}.log"))
    say(f"tune_kernels backend={backend} kernels={names} iters={iters}")
    problems = []
    for name in names:
        _, probs = sweep_one(name, iters, say, write=not args.no_write)
        problems += probs
    say("SWEEP DONE" + (f" ({len(problems)} problems)" if problems
                        else " — all winners banked"))
    sys.exit(1 if problems else 0)


if __name__ == "__main__":
    main()
