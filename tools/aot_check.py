"""AOT compile-check the Pallas kernels AND the full bench train steps
for a real TPU target WITHOUT hardware: libtpu's compile-only PJRT
topology client lowers through Mosaic exactly as a real chip would, so
kernel lowering errors, VMEM exhaustion, and whole-step HBM overflow
surface here instead of in the driver's benchmark run.

Usage: python tools/aot_check.py [--topology v5e:2x2] [--kernels]
                                 [--steps]            (default: both)

- Kernel checks shard the batch over a dp mesh (Mosaic kernels are not
  auto-partitionable), sized so PER-DEVICE shapes equal the single-chip
  bench shapes.
- Step checks compile the ACTUAL `bench.py` train steps single-device
  with donated state and report the HBM breakdown — these are the
  numbers the bench.py batch/layer comments cite.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _gen_from_topology(topology: str) -> str:
    return topology.split(":")[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x2")
    ap.add_argument("--kernels", action="store_true")
    ap.add_argument("--steps", action="store_true")
    args = ap.parse_args()
    if not (args.kernels or args.steps):
        args.kernels = args.steps = True

    # Before ANY apex1_tpu import: make dispatch pick the REAL (non-
    # interpret) Pallas path, and block planning match the target chip.
    os.environ["PALLAS_AXON_TPU_GEN"] = _gen_from_topology(args.topology)
    import apex1_tpu.ops._common as _common
    _common.on_tpu = lambda: True          # use_pallas() -> True
    _common.interpret_mode = lambda: False  # real Mosaic lowering
    # kernel modules bound interpret_mode by value at import in some
    # refactors — fail loudly if the patch ever stops taking effect
    assert not _common.interpret_mode()

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, SingleDeviceSharding
    from jax.sharding import PartitionSpec as P

    from apex1_tpu.ops import force_impl

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    n = len(topo.devices)
    mesh = Mesh(np.array(topo.devices).reshape(n), ("dp",))
    ok = True

    def report(name, lower_fn):
        nonlocal ok
        try:
            mem = lower_fn().compile().memory_analysis()
            tmp = mem.temp_size_in_bytes / 2**30
            arg = mem.argument_size_in_bytes / 2**30
            print(f"  OK   {name:48s} temp {tmp:6.2f} GiB  "
                  f"args {arg:6.2f} GiB", flush=True)
        except Exception as e:
            ok = False
            print(f"  FAIL {name}: {type(e).__name__}: {str(e)[:300]}",
                  flush=True)

    def check(name, fn, shapes, *, dtypes=jnp.bfloat16, in_specs=None,
              grad=False):
        """Kernel check: shapes are PER-DEVICE; sharded dims scale by n."""
        if not isinstance(dtypes, (tuple, list)):
            dtypes = [dtypes] * len(shapes)
        in_specs = in_specs or (P("dp"),) * len(shapes)
        # global shape = per-device shape scaled along the sharded dim
        def gshape(shp, spec):
            if spec == P():
                return shp
            return (shp[0] * n,) + tuple(shp[1:])
        arrs = [jax.ShapeDtypeStruct(
                    gshape(shp, spec), dt,
                    sharding=NamedSharding(mesh, spec))
                for shp, dt, spec in zip(shapes, dtypes, in_specs)]

        def run():
            def local(*xs):
                with force_impl("pallas"):
                    out = fn(*xs)
                return out

            if grad:
                base = local

                def local(*xs):  # noqa: F811
                    fi = tuple(i for i, x in enumerate(xs)
                               if jnp.issubdtype(x.dtype, jnp.floating))
                    return jax.grad(
                        lambda *a: jnp.sum(base(*a).astype(jnp.float32)),
                        argnums=fi)(*xs)

            out_specs = jax.tree_util.tree_map(
                lambda _: P("dp"), jax.eval_shape(local, *arrs))
            smapped = jax.shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                                    out_specs=out_specs, check_vma=False)
            return jax.jit(smapped).lower(*arrs)

        report(name, run)

    if args.kernels:
        print(f"== Pallas kernels (per-device = bench shapes), "
              f"{args.topology} ==", flush=True)
        from apex1_tpu.ops import (layer_norm, rms_norm,
                                   scaled_upper_triang_masked_softmax,
                                   softmax_cross_entropy_loss)
        from apex1_tpu.ops.attention import flash_attention
        from apex1_tpu.ops.linear_xent import linear_cross_entropy
        from apex1_tpu.ops.rope import apply_rotary_pos_emb, rope_tables

        fa = lambda q, k, v: flash_attention(q, k, v, causal=True)
        for nm, shp in (("flash gpt2 B16 (16,12,1024,64)",
                         (16, 12, 1024, 64)),
                        ("flash longctx (1,32,16384,64)",
                         (1, 32, 16384, 64))):
            check(f"{nm} fwd", fa, [shp] * 3)
            check(f"{nm} fwd+bwd", fa, [shp] * 3, grad=True)

        T, Hid, V = 16 * 1023, 768, 50432
        check(f"linear_xent gpt2 ({T},{Hid},{V}) fwd+bwd",
              lambda x, w: linear_cross_entropy(
                  x, w, jnp.zeros((x.shape[0],), jnp.int32),
                  num_classes=V - 200),
              [(T, Hid), (V, Hid)], in_specs=(P("dp"), P()), grad=True)

        g = jnp.ones((768,), jnp.float32)
        check("layer_norm (16384,768) fwd+bwd",
              lambda x: layer_norm(x, g, jnp.zeros_like(g)),
              [(16384, 768)], grad=True)
        check("rms_norm (16384,2048) fwd+bwd",
              lambda x: rms_norm(x, jnp.ones((2048,), jnp.float32)),
              [(16384, 2048)], grad=True)
        check("causal softmax (16,12,1024,1024) fwd+bwd",
              lambda x: scaled_upper_triang_masked_softmax(x, scale=0.125),
              [(16, 12, 1024, 1024)], dtypes=jnp.float32, grad=True)
        check("xentropy (16368,50432) fwd+bwd",
              lambda x: softmax_cross_entropy_loss(
                  x, jnp.zeros((x.shape[0],), jnp.int32),
                  num_classes=50257),
              [(16368, 50432)], dtypes=jnp.float32, grad=True)
        cos, sin = rope_tables(jnp.arange(16384), 64)
        check("rope llama (1,16384,32,64) fwd+bwd",
              lambda x: apply_rotary_pos_emb(x, cos, sin),
              [(1, 16384, 32, 64)], grad=True)

    if args.steps:
        print(f"== full bench train steps (single device), "
              f"{args.topology} ==", flush=True)
        import bench as bench_mod
        from apex1_tpu.amp import Amp
        from apex1_tpu.optim.fused_adam import fused_adam

        s1 = SingleDeviceSharding(topo.devices[0])

        def step_check(tag, model, loss_fn, tok_shape):
            def run():
                tokens = jax.ShapeDtypeStruct(tok_shape, jnp.int32,
                                              sharding=s1)
                pshapes = jax.eval_shape(
                    model.init, jax.random.key(0),
                    jnp.zeros(tok_shape, jnp.int32))["params"]
                amp = Amp(tx=fused_adam(1e-4, weight_decay=0.01),
                          opt_level="O2")
                st = jax.eval_shape(amp.init, pshapes)
                st = jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                   sharding=s1), st)
                step = amp.make_train_step(loss_fn)
                return jax.jit(step, donate_argnums=0).lower(st, tokens)

            report(tag, run)

        from apex1_tpu.core.policy import get_policy
        from apex1_tpu.models.gpt2 import GPT2, GPT2Config, gpt2_loss_fn
        from apex1_tpu.models.llama import (Llama, LlamaConfig,
                                            llama_loss_fn)
        m = GPT2(GPT2Config(policy=get_policy("O2")))
        step_check("gpt2 bench step (B=16, S=1024)", m, gpt2_loss_fn(m),
                   (16, 1024))
        cfg = LlamaConfig(vocab_size=32000, max_seq_len=16384,
                          num_layers=16, num_heads=32, num_kv_heads=4,
                          hidden_size=2048, ffn_size=5632, remat=True,
                          policy=get_policy("O2"))
        mm = Llama(cfg)
        step_check("llama_longctx bench step (B=1, S=16k, L=16)", mm,
                   llama_loss_fn(mm), (1, 16384))

    print("ALL OK" if ok else "FAILURES PRESENT", flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
